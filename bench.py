"""Benchmark: POA window consensus throughput (windows/sec/chip).

Prints exactly one JSON line on stdout. Primary value = direct-timed
compute-only windows/s of one warm production chunk (all refinement
rounds in one dispatch, chained reps, single trailing sync); the
chunk-pipelined end-to-end rate rides along as extra keys. The split
exists because this environment reaches its TPU through a tunnel whose
h2d bandwidth swings 1.4-7 MB/s hour to hour (round-5 measurements —
PROFILE.md): the pipelined end-to-end rate measured 97-213 w/s across
four same-code runs in one afternoon, all of the spread being tunnel
weather, while the compute rate held within 3%. Production-attached
TPUs feed from local host RAM and pay none of that; both numbers are
reported so the tunnel tax stays visible.

Workload matches BASELINE.md's north-star metric: w=500-class windows at
30x coverage (the reference's hot loop, src/polisher.cpp:451-513 ->
src/window.cpp:61-137), run through the full PoaEngine device pipeline —
batched NW forward + column-walk traceback + device merge, all
refinement rounds on chip.

Baseline: BASELINE.json targets >=20x a 64-thread CPU SPOA path on a
v5e-8 (8 chips). The denominator is MEASURED, not estimated: the repo's
own native host path (C++ adaptive-band NW + numpy merge — the fastest
CPU racon-equivalent runnable in this image; the reference binary cannot
build here, its vendored spoa/edlib trees are absent) does 15.45
windows/s single-threaded on this exact workload
(scripts/measure_cpu_anchor.py, 2026-07-30), idealized to 64 threads as
64 x 15.45 = 988.8 — generous to the CPU, whose merge phase does not
actually parallelize. vs_baseline = value / 988.8; the north star (20x
on 8 chips) means vs_baseline >= 2.5 per chip. The reference's own spoa
path is ~6x slower than our native anchor (~2.5 w/s single-thread
estimated), so value / 160 rides along as vs_ref_spoa_64t_est.
"""

import json
import os
from racon_tpu.utils import envspec
import sys
import time

import numpy as np

# Measured single-thread native-path anchor (scripts/measure_cpu_anchor.py
# on this image, 2026-07-30: 15.45 w/s at n=64), idealized x64 threads.
CPU_1T_MEASURED = 15.45
CPU_64T_WINDOWS_PER_SEC = 64 * CPU_1T_MEASURED          # = 988.8
CPU_64T_REF_SPOA_EST = 160.0   # reference racon (spoa) estimate, kept
                               # for cross-round comparability


def build_windows(n_windows: int, coverage: int, wlen: int, seed: int = 0):
    """Vectorized synthetic polishing workload: per window a hidden truth
    sequence, a 10%-error backbone, and `coverage` 10%-error layers."""
    from racon_tpu.models.window import Window, WindowType
    from racon_tpu.ops.encode import decode_bases

    rng = np.random.default_rng(seed)
    windows = []
    for _ in range(n_windows):
        true = rng.integers(0, 4, wlen).astype(np.uint8)

        def noisy(rate=0.10):
            r = rng.random(wlen)
            dele = r < rate / 3
            sub = (r >= rate / 3) & (r < 2 * rate / 3)
            ins = (r >= 2 * rate / 3) & (r < rate)
            counts = np.where(dele, 0, np.where(ins, 2, 1))
            base = np.where(sub, rng.integers(0, 4, wlen).astype(np.uint8),
                            true)
            starts = np.cumsum(counts) - counts
            out = np.zeros(int(counts.sum()), np.uint8)
            keep = ~dele
            out[starts[keep]] = base[keep]
            out[starts[ins] + 1] = rng.integers(0, 4, int(ins.sum()))
            return decode_bases(out)

        backbone = noisy()
        qual = bytes(rng.integers(33 + 8, 33 + 25, len(backbone),
                                  dtype=np.uint8))
        w = Window(0, 0, WindowType.TGS, backbone, qual)
        for _ in range(coverage):
            lay = noisy()
            lq = bytes(rng.integers(33 + 8, 33 + 25, len(lay),
                                    dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        windows.append(w)
    return windows


def _ingest_bench() -> dict:
    """Ingest micro-bench (metric_version 11): a multi-member gzipped
    genome-like FASTA (1 MB contig lines — inflate-dominated, the
    ROADMAP item 2 shape) parsed twice: RACON_TPU_INGEST=0 (serial
    gzip.open reader) vs =1 (parallel member inflate, io/inflate.py),
    records asserted identical. Publishes decompressed MB/s for both
    and the speedup; the gated run's registry ingest_* accounting
    (bytes, inflate/parse/wait seconds, fraction-of-wall) rides along
    via ingest_extras. NOTE the speedup scales with physical cores —
    member inflate parallelizes across a worker pool (zlib releases
    the GIL), so a 1-core container reads ~1x here by construction."""
    import gzip
    import tempfile
    from racon_tpu.io.parsers import CHUNK_SIZE, create_sequence_parser
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.pipeline.streaming import serial_chunks

    rng = np.random.default_rng(12)
    line = rng.choice(np.frombuffer(b"ACGT", np.uint8),
                      size=1 << 20).tobytes()
    n_members = int(envspec.read("RACON_TPU_BENCH_INGEST_MB"))
    gate0 = envspec.read("RACON_TPU_INGEST")
    out: dict = {}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ingest_bench.fasta.gz")
        with open(path, "wb") as fh:
            for i in range(n_members):        # one member per contig
                fh.write(gzip.compress(b">c%d\n%s\n" % (i, line),
                                       compresslevel=1))
        raw_mb = n_members * (len(line) + 8) / 1e6
        try:
            os.environ["RACON_TPU_INGEST"] = "0"
            t0 = time.perf_counter()
            serial_recs = create_sequence_parser(path).parse_all()
            dt_serial = time.perf_counter() - t0
            os.environ["RACON_TPU_INGEST"] = "1"
            parser = create_sequence_parser(path)
            par_recs = []
            t0 = time.perf_counter()
            for chunk, _more in serial_chunks(parser, CHUNK_SIZE):
                par_recs.extend(chunk)
            dt_par = time.perf_counter() - t0
        finally:
            if gate0:
                os.environ["RACON_TPU_INGEST"] = gate0
            else:
                os.environ.pop("RACON_TPU_INGEST", None)
    assert [(s.name, bytes(s.data)) for s in par_recs] == \
        [(s.name, bytes(s.data)) for s in serial_recs], \
        "parallel ingest diverged from serial reader"
    obs_metrics.set_ingest_fraction(dt_par)
    out["ingest_mb_per_sec"] = round(raw_mb / dt_par, 2)
    out["ingest_serial_mb_per_sec"] = round(raw_mb / dt_serial, 2)
    out["ingest_speedup_vs_serial"] = round(dt_serial / dt_par, 2)
    out["ingest_seconds"] = round(dt_par, 4)
    out["ingest_bench_mb"] = round(raw_mb, 1)
    # The parallel-inflate speedup scales with physical cores (see the
    # docstring NOTE); publish the host's core count next to it so a
    # ~1x on a 1-core CI box reads as by-construction, not regression.
    out["ingest_host_cores"] = os.cpu_count() or 1
    return out


def _serve_bench(backend: str, coverage: int, wlen: int) -> dict:
    """Serve-plane micro-bench (metric_version 13): three concurrent
    jobs from two tenants push their windows through one
    CrossRequestBatcher over a warm engine (racon_tpu/server/batch.py),
    consensi asserted identical to a solo serial pass of the same
    windows — the per-window determinism invariant the daemon's
    byte-identity rests on, exercised at bench geometry with the full
    telemetry plane armed (histograms recording, flight ring live: the
    identity assert doubles as the telemetry-on/off byte-identity
    gate, since the solo reference pass above ran before any serve
    telemetry was recorded). Publishes serve_jobs_per_min /
    serve_batch_occupancy and the rest of the serve_* registry extras
    (batches, windows, tenant wait, queue peak), plus (metric_version
    15) p50/p95/p99 for serve_job_latency_s and dispatch_round_s and
    the flight-recorder dump overhead, gated < 1% of the drill's
    wall."""
    import threading
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.ops.poa import PoaEngine
    from racon_tpu.server.batch import CrossRequestBatcher

    n_per_job = 16
    jobs = [("j1", "acme"), ("j2", "acme"), ("j3", "umbrella")]
    total = n_per_job * len(jobs)
    ref = build_windows(total, coverage, wlen, seed=23)
    PoaEngine(backend=backend).consensus_windows(ref)
    shared = build_windows(total, coverage, wlen, seed=23)
    # Capacity fits all three jobs' windows in one dispatch; the 1 s
    # staging window absorbs thread-start skew so the batch actually
    # merges across jobs (occupancy ~1.0 when it does).
    batcher = CrossRequestBatcher(PoaEngine(backend=backend),
                                  capacity=total, wait_s=1.0)
    batcher.start()
    results: dict = {}

    def _job(idx: int, job_id: str, tenant: str) -> None:
        lo = idx * n_per_job
        tj0 = time.perf_counter()
        results[job_id] = batcher.consensus(
            job_id, tenant, shared[lo:lo + n_per_job])
        obs_metrics.record_hist("serve_job_latency_s",
                                time.perf_counter() - tj0)

    threads = [threading.Thread(target=_job, args=(i, j, t),
                                name=f"serve-bench-{j}")
               for i, (j, t) in enumerate(jobs)]
    t0 = time.perf_counter()
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    finally:
        dt = time.perf_counter() - t0
        batcher.close()
    assert sum(results.values()) == total
    assert [w.consensus for w in shared] == [w.consensus for w in ref], \
        "batched serve consensus diverged from solo serial"
    obs_metrics.set_serve_rate(len(jobs) / (dt / 60.0))
    out = dict(obs_metrics.serve_extras())
    out["serve_bench_jobs"] = len(jobs)
    out["serve_bench_seconds"] = round(dt, 4)
    for family in ("serve_job_latency_s", "dispatch_round_s"):
        out.update({k: round(v, 6) for k, v in
                    obs_metrics.hist_percentiles(family).items()})
    # Flight-recorder cost: one full ring dump (the most expensive
    # thing the recorder ever does, and it only happens at teardown)
    # must stay under 1% of the drill's wall — the always-armed ring
    # may not tax the serve plane it exists to debug.
    import tempfile
    from racon_tpu.obs import flightrec
    with tempfile.TemporaryDirectory() as flight_dir:
        tf0 = time.perf_counter()
        assert flightrec.dump(flight_dir, reason="bench"), \
            "flight dump failed"
        flight_dt = time.perf_counter() - tf0
    assert flight_dt < 0.01 * dt, \
        f"flight dump cost {flight_dt:.4f}s >= 1% of serve wall {dt:.4f}s"
    out["flight_dump_seconds"] = round(flight_dt, 6)
    out["flight_overhead_fraction"] = round(flight_dt / dt, 6)
    return out


def _cache_bench(backend: str, coverage: int, wlen: int) -> dict:
    """Result-cache micro-bench (metric_version 14): one job's windows
    run twice through a WindowMemo-armed CrossRequestBatcher
    (racon_tpu/cache/ + server/batch.py). The cold pass dispatches and
    memoizes; the warm resubmit must be served entirely from the memo —
    the engine sees zero windows — with consensus byte-identical to a
    plain solo pass. Publishes cache_resubmit_speedup and cold/warm
    jobs-per-minute next to the cache_* registry extras
    (hits/misses/stores/bytes, cache_hit_ratio)."""
    from racon_tpu.cache import WindowMemo
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.ops.poa import PoaEngine
    from racon_tpu.server.batch import CrossRequestBatcher

    n = 32
    ref = build_windows(n, coverage, wlen, seed=29)
    PoaEngine(backend=backend).consensus_windows(ref)
    memo = WindowMemo(("cache-bench",))

    def _pass() -> tuple:
        windows = build_windows(n, coverage, wlen, seed=29)
        batcher = CrossRequestBatcher(PoaEngine(backend=backend),
                                      capacity=n, wait_s=0.05,
                                      memo=memo).start()
        t0 = time.perf_counter()
        try:
            assert batcher.consensus("jc", "acme", windows) == n
        finally:
            dt = time.perf_counter() - t0
            batcher.close()
        return windows, dt

    before = obs_metrics.registry().snapshot()
    cold_windows, dt_cold = _pass()
    warm_windows, dt_warm = _pass()
    after = obs_metrics.registry().snapshot()
    assert [w.consensus for w in cold_windows] == \
        [w.consensus for w in ref], "cold cached consensus diverged"
    assert [w.consensus for w in warm_windows] == \
        [w.consensus for w in ref], "memo-served consensus diverged"
    assert after.get("cache_hits_total", 0) - \
        before.get("cache_hits_total", 0) == n, \
        "warm resubmit was not served from the window memo"
    out = dict(obs_metrics.result_cache_extras())
    out["cache_resubmit_speedup"] = round(dt_cold / max(dt_warm, 1e-9), 2)
    out["cache_cold_jobs_per_min"] = round(60.0 / max(dt_cold, 1e-9), 2)
    out["cache_warm_jobs_per_min"] = round(60.0 / max(dt_warm, 1e-9), 2)
    return out


# One fleet-bench pass: a fresh interpreter (exactly what an autoscaled
# gateway worker is) runs the same 3-job workload sequentially against
# whatever RACON_TPU_JAX_CACHE points at, reporting wall, per-job
# digests, and the compile-cache counters. min_compile_time drops to 0
# so every executable persists — the pool must capture each shape, not
# only the slow ones.
_FLEET_BENCH_BOOT = """\
import hashlib, json, time
from racon_tpu.utils.jaxcache import enable_compile_cache, cache_extras
enable_compile_cache()
import jax
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
from bench import build_windows
from racon_tpu.ops.poa import PoaEngine
digests = []
t0 = time.perf_counter()
for seed in (31, 32, 33):
    eng = PoaEngine(backend="jax")
    ws = build_windows({n}, {coverage}, {wlen}, seed=seed)
    assert eng.consensus_windows(ws) == {n}
    digests.append(hashlib.sha256(
        b"".join(w.consensus for w in ws)).hexdigest())
print(json.dumps({{"dt": time.perf_counter() - t0,
                   "digests": digests, **cache_extras()}}))
"""


def _fleet_serve_bench(coverage: int, wlen: int) -> dict:
    """Fleet-serve micro-bench (metric_version 16): the same 3-job
    workload twice through fresh interpreters — pass one against an
    EMPTY compile-cache dir (a lone daemon paying the cold compile,
    the single-daemon baseline), pass two against the now-warm shared
    jaxcache pool (a freshly spawned gateway fleet worker). On this
    1-core host the fleet's throughput win is exactly the warm-pool
    compile skip, so the drill asserts the mechanism directly: the
    warm worker starts with entries in the pool, adds none, and its
    digests match the cold pass byte-for-byte. Publishes
    gate_fleet_jobs_per_min (warm fleet worker) vs serve_jobs_per_min
    (re-based to the cold single-daemon wall on this same workload)
    and gate_compile_skip_s, asserting the fleet rate strictly above
    the single-daemon rate. Geometry is offset from the main bench's
    so the cold pass genuinely compiles fresh shapes."""
    import subprocess
    import tempfile
    from racon_tpu.obs import metrics as obs_metrics

    repo = os.path.dirname(os.path.abspath(__file__))
    n_jobs, n_per_job = 3, 8
    boot = _FLEET_BENCH_BOOT.format(n=n_per_job, coverage=coverage,
                                    wlen=wlen + 37)

    with tempfile.TemporaryDirectory() as pool:
        env = dict(os.environ)
        env["RACON_TPU_JAX_CACHE"] = pool
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

        def _pass() -> dict:
            p = subprocess.run([sys.executable, "-c", boot], cwd=repo,
                               env=env, capture_output=True, text=True,
                               timeout=600)
            assert p.returncode == 0, \
                f"fleet bench pass failed:\n{p.stderr[-2000:]}"
            return json.loads(p.stdout.strip().splitlines()[-1])

        solo = _pass()   # cold pool: the lone daemon pays every compile
        fleet = _pass()  # fresh worker process on the warm shared pool

    assert fleet["digests"] == solo["digests"], \
        "warm-pool worker diverged from the cold single-daemon pass"
    assert solo["jax_cache_entries_added"] > 0, \
        "cold pass compiled nothing — the warm pass proves nothing"
    assert fleet["jax_cache_entries_start"] > 0 and \
        fleet["jax_cache_entries_added"] == 0, \
        "freshly spawned worker missed the shared warm pool " \
        f"({fleet})"
    solo_jpm = n_jobs / (solo["dt"] / 60.0)
    fleet_jpm = n_jobs / (fleet["dt"] / 60.0)
    assert fleet_jpm > solo_jpm, \
        f"fleet {fleet_jpm:.2f} jobs/min not above single-daemon " \
        f"{solo_jpm:.2f} on the same workload"
    obs_metrics.set_gate_rate(
        fleet_jpm, compile_skip_s=solo["dt"] - fleet["dt"])
    out = dict(obs_metrics.gate_extras())
    out["gate_bench_jobs"] = n_jobs
    out["gate_solo_seconds"] = round(solo["dt"], 4)
    out["gate_fleet_seconds"] = round(fleet["dt"], 4)
    out["gate_pool_entries"] = fleet["jax_cache_entries_start"]
    # Same-workload single-daemon baseline: overrides _serve_bench's
    # in-process figure so the gate_fleet_jobs_per_min comparison reads
    # apples-to-apples from one record (metric_version 16 re-base).
    out["serve_jobs_per_min"] = round(solo_jpm, 2)
    return out


def _ava_child(n_reads: int, out_path: str) -> int:
    """Child half of _ava_bench (``python bench.py --ava-child N OUT``):
    synthesize an ava read set (the same skewed family generator the CI
    smoke uses), run one serial kF correction through the real CLI with
    a checkpoint store (v2 segmented manifests — the fragment_correction
    default), and report wall, peak RSS and manifest accounting as JSON.
    Runs in its own interpreter so ru_maxrss is THIS workload's peak,
    not the parent bench's."""
    import resource
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from scripts.ava_scale_smoke import _write_inputs
    from racon_tpu import cli

    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d, n_reads)
        reads = os.path.join(d, "reads.fasta")
        ckpt = os.path.join(d, "ckpt")
        corrected = os.path.join(d, "corrected.fasta")
        # The CLI emits on stdout; route fd 1 to a file for the drill.
        sink = os.open(corrected, os.O_WRONLY | os.O_CREAT | os.O_TRUNC)
        sys.stdout.flush()
        old_stdout = os.dup(1)
        os.dup2(sink, 1)
        os.close(sink)
        try:
            t0 = time.perf_counter()
            rc = cli.main(["--backend", "jax", "-f",
                           "--checkpoint-dir", ckpt,
                           reads, os.path.join(d, "ava.paf"), reads])
            dt = time.perf_counter() - t0
            sys.stdout.flush()
        finally:
            os.dup2(old_stdout, 1)
            os.close(old_stdout)
        assert rc == 0, f"ava child CLI exited {rc}"
        emitted = open(corrected, "rb").read().count(b">")
        assert emitted == n_reads, \
            f"ava child corrected {emitted}/{n_reads} reads"
        manifest = open(os.path.join(ckpt, "manifest.jsonl"),
                        "rb").read()
        recs = [json.loads(ln) for ln in manifest.splitlines()]
        assert recs and recs[0].get("manifest") == 2, \
            f"kF checkpoint store is not v2: {recs[:1]}"
        segs = [r for r in recs[1:] if r.get("ev") == "seg"]
        assert len(segs) == len(recs) - 1, \
            "per-target records in a v2 manifest"
        rss_mb = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1024.0
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump({"dt": dt, "n_reads": n_reads,
                       "manifest_bytes": len(manifest),
                       "seg_records": len(segs),
                       "peak_rss_mb": round(rss_mb, 2)}, fh)
    return 0


def _ava_bench() -> dict:
    """Assembly-scale ava micro-bench (metric_version 17): one serial
    kF correction of a skewed read set through the real CLI in a fresh
    interpreter (so peak RSS is the workload's own), checkpointed
    through a v2 segmented manifest store. Publishes ava_reads_per_sec
    (corrected reads per wall second), ava_peak_rss_mb, and
    ava_manifest_bytes_per_target — the o(1)-records acceptance number
    v1's one-record-per-target format cannot reach — and asserts the
    segment amortization outright (records * 8 <= targets)."""
    import subprocess
    import tempfile
    from racon_tpu.obs import metrics as obs_metrics

    repo = os.path.dirname(os.path.abspath(__file__))
    n_reads = 600
    with tempfile.TemporaryDirectory() as d:
        res_path = os.path.join(d, "ava.json")
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"),
             "--ava-child", str(n_reads), res_path],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=600)
        assert p.returncode == 0, \
            f"ava bench child failed:\n{p.stderr[-2000:]}"
        with open(res_path, "r", encoding="utf-8") as fh:
            r = json.load(fh)

    assert r["seg_records"] * 8 <= n_reads, \
        f"{r['seg_records']} manifest records for {n_reads} targets — " \
        "segment amortization failed"
    reads_per_sec = n_reads / r["dt"]
    obs_metrics.set_ava_bench(reads_per_sec, r["peak_rss_mb"],
                              r["manifest_bytes"] / n_reads)
    out = dict(obs_metrics.ava_extras())
    out["ava_bench_reads"] = n_reads
    out["ava_bench_seconds"] = round(r["dt"], 4)
    out["ava_bench_seg_records"] = r["seg_records"]
    return out


def main():
    from racon_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()
    n_windows = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    coverage = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    wlen = 500

    import jax
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.obs.trace import configure as configure_trace
    from racon_tpu.ops.poa import PoaEngine, _accelerator_present

    tracer = configure_trace()        # honors RACON_TPU_TRACE, else no-op
    backend = "jax" if _accelerator_present() else "native"
    dev = jax.devices()[0].platform if backend == "jax" else "cpu-native"

    # Warmup with the same workload shape so every bucketed executable
    # the measured run needs is already compiled (run-level caps +
    # balanced chunking make the shapes deterministic).
    eng = PoaEngine(backend=backend)
    eng.consensus_windows(build_windows(n_windows, coverage, wlen, seed=99))

    # End-to-end: pipelined (chunk i+1's h2d overlaps chunk i's compute).
    # metric_version 6: MEDIAN of RACON_TPU_BENCH_E2E_REPS (default 3)
    # reps — the tunnel's minute-scale bandwidth swings made single-shot
    # e2e rates mostly weather (97-213 w/s across four same-code runs,
    # PROFILE.md round 5). Each rep rebuilds its windows OUTSIDE the
    # timer and runs the identical workload (same seed); per-rep rates
    # ride along in e2e_rep_windows_per_sec so the spread stays visible.
    # The registry resets before every rep, so the transfer extras (h2d/
    # d2h bytes, seconds, effective bandwidth) describe exactly the LAST
    # measured run.
    e2e_reps = max(1, int(envspec.read("RACON_TPU_BENCH_E2E_REPS")))
    e2e_rates = []
    for rep in range(e2e_reps):
        windows = build_windows(n_windows, coverage, wlen)
        eng = PoaEngine(backend=backend)
        obs_metrics.reset()
        enable_compile_cache()        # re-record cache entry baseline
        t0 = time.perf_counter()
        with tracer.span("run", "bench_e2e", n_windows=n_windows,
                         rep=rep):
            n_polished = eng.consensus_windows(windows)
        dt = time.perf_counter() - t0
        assert n_polished == n_windows
        e2e_rates.append(n_windows / dt)
    e2e_transfers = obs_metrics.transfer_extras()
    e2e_transfers = {f"e2e_{k}": v for k, v in e2e_transfers.items()}
    e2e_transfers["e2e_rep_windows_per_sec"] = \
        [round(r, 2) for r in e2e_rates]

    # Sanity: consensus must actually polish (each window was built from a
    # 10%-error backbone; consensus should be near the truth, i.e. differ
    # from the backbone).
    n_changed = sum(1 for w in windows if w.consensus != bytes(w.backbone))
    assert n_changed > n_windows * 0.9, "consensus did not polish"

    e2e = float(np.median(e2e_rates))

    # Streamed end-to-end: the same workload through the streaming
    # executor (racon_tpu/pipeline/ — build/pack/h2d/compute stage
    # threads, depth-2 double buffering). Output must be bit-identical
    # to the serial run; the rate and the pipe_* gauges (stage busy /
    # stall, queue peaks, overlap efficiency) ride along as extras.
    pwindows = build_windows(n_windows, coverage, wlen)
    peng = PoaEngine(backend=backend)
    obs_metrics.reset()
    t0 = time.perf_counter()
    with tracer.span("run", "bench_e2e_pipelined", n_windows=n_windows):
        from racon_tpu.pipeline.streaming import stream_consensus
        covered = 0
        for s, e in stream_consensus(peng, pwindows, depth=2):
            covered += e - s
    dt_pipe = time.perf_counter() - t0
    assert covered == n_windows
    assert [w.consensus for w in pwindows] == \
        [w.consensus for w in windows], \
        "pipelined consensus diverged from serial"
    e2e_pipe = n_windows / dt_pipe
    pipe_extras = obs_metrics.pipeline_extras()

    # Decoupled walk (ISSUE 14): a sub-workload streamed at a small
    # chunk size so several device chunks are actually in flight, once
    # with the walk stage decoupled (RACON_TPU_WALK_ASYNC=1 — chunk N's
    # final-round walk dispatched as its own executable, overlapping
    # chunk N+1's forward rounds) and once fused, consensi asserted
    # byte-identical. Pinned to the jax backend (the decoupled path
    # only exists there; on a native-anchored box this is the same
    # jax-cpu backend the test suite gates on) and to RACON_TPU_SCHED=0
    # for both runs — the scheduler keeps fused dispatches (its
    # per-round flag pulls consume every walk), so the comparison only
    # exists on the fixed-round path.
    walk_bench_extras = {}
    _walk_saved = {k: os.environ.get(k)
                   for k in ("RACON_TPU_SCHED", "RACON_TPU_WALK_ASYNC")}
    try:
        os.environ["RACON_TPU_SCHED"] = "0"
        n_walk = min(n_windows, 128)
        walk_chunk = max(8, n_walk // 4)
        wwindows = build_windows(n_walk, coverage, wlen, seed=7)
        os.environ["RACON_TPU_WALK_ASYNC"] = "1"
        obs_metrics.reset()
        t0 = time.perf_counter()
        with tracer.span("run", "bench_walk_async", n_windows=n_walk):
            covered = sum(e - s for s, e in stream_consensus(
                PoaEngine(backend="jax"), wwindows,
                chunk=walk_chunk, depth=2))
        dt_wasync = time.perf_counter() - t0
        assert covered == n_walk
        walk_ref = [w.consensus for w in wwindows]
        walk_bench_extras = obs_metrics.walk_extras()
        walk_bench_extras["walk_async_windows_per_sec"] = round(
            n_walk / dt_wasync, 2)
        os.environ["RACON_TPU_WALK_ASYNC"] = "0"
        fwindows = build_windows(n_walk, coverage, wlen, seed=7)
        obs_metrics.reset()
        t0 = time.perf_counter()
        covered = sum(e - s for s, e in stream_consensus(
            PoaEngine(backend="jax"), fwindows,
            chunk=walk_chunk, depth=2))
        dt_wfused = time.perf_counter() - t0
        assert covered == n_walk
        assert [w.consensus for w in fwindows] == walk_ref, \
            "decoupled-walk stream diverged from fused stream"
        walk_bench_extras["walk_fused_windows_per_sec"] = round(
            n_walk / dt_wfused, 2)
    finally:
        for k, v in _walk_saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # Compute-only: time one warm production chunk with chained reps.
    # When the convergence scheduler is on (the default), the production
    # chunk program IS the scheduler's dispatch chain (racon_tpu/sched/)
    # — its per-chunk flag pulls are on the critical path, so each rep
    # syncs; the fixed engine's single all-rounds dispatch rides along
    # in extras for round-over-round continuity (and is the primary
    # value under RACON_TPU_SCHED=0). The earlier stats-serialized phase
    # split paid a ~75 ms tunnel round-trip per phase edge and let
    # in-flight transfers bleed between phases — through this tunnel its
    # numbers were noise.
    compute = e2e
    sched_extras = {}
    probe_extras = {}
    if backend == "jax":
        # Tunnel h2d bandwidth probe: one warm 8 MiB device_put timed to
        # completion — the tunnel-weather denominator published next to
        # the e2e rates it explains (production-attached TPUs should
        # read hundreds of MB/s here; this env's tunnel reads 1.4-7).
        probe = np.zeros(8 * 1024 * 1024, np.uint8)
        jax.block_until_ready(jax.device_put(probe))        # warm path
        t1 = time.perf_counter()
        jax.block_until_ready(jax.device_put(probe))
        probe_extras["h2d_probe_mb_per_s"] = round(
            8.0 / max(time.perf_counter() - t1, 1e-9), 2)
    if backend == "jax":
        from racon_tpu.ops.device_poa import (ChunkPlan, run_caps,
                                              _use_pallas,
                                              device_chunk_packed)
        from racon_tpu.sched import (ConvergenceScheduler, SchedTelemetry,
                                     sched_enabled)
        n_sub = min(n_windows, 128)
        sub = build_windows(n_sub, coverage, wlen, seed=3)
        lqm = max(max(len(d) for d in w.layer_data) for w in sub)
        lam = max(len(w.backbone) for w in sub)
        lq_cap, la_cap = run_caps(lqm, lam)
        plan = ChunkPlan(sub, lq_cap=lq_cap, la_cap=la_cap)
        job_h, win_h = plan.packed_bufs()
        job_buf, win_buf = jax.device_put((job_h, win_h))
        sc = tuple(eng._round_scales(eng.refine_rounds + 1))
        # Same adaptive gate as dispatch_chunk, so the fixed-engine rate
        # times the production chunk program (adaptive round exit on by
        # default; RACON_TPU_ADAPTIVE=0 restores the unrolled chain).
        kw = dict(match=5, mismatch=-4, gap=-8,
                  ins_scale=sc,
                  Lq=plan.Lq,
                  n_win=plan.n_win, LA=plan.LA,
                  pallas=_use_pallas(plan.B, plan.Lq, plan.LA),
                  band_w=plan.band_w, rounds=eng.refine_rounds + 1,
                  adaptive=(envspec.read("RACON_TPU_ADAPTIVE")
                            not in ("0", "false")
                            and eng.refine_rounds + 1 >= 3
                            and len(set(sc[:-1])) <= 1))
        out = device_chunk_packed(job_buf, win_buf, **kw)
        np.asarray(out[:1])                       # compile + sync
        reps = 3
        t1 = time.perf_counter()
        for _ in range(reps):
            out = device_chunk_packed(job_buf, win_buf, **kw)
        np.asarray(out[:1])
        fixed_rate = n_sub / ((time.perf_counter() - t1) / reps)
        compute = fixed_rate
        if sched_enabled():
            sched = ConvergenceScheduler(
                match=5, mismatch=-4, gap=-8,
                scales=eng._round_scales(eng.refine_rounds + 1))
            sched.run_chunk(plan, bufs=(job_buf, win_buf))  # compile/warm
            sched.telemetry = SchedTelemetry(sched.rounds)  # timed-only
            t1 = time.perf_counter()
            for _ in range(reps):
                sched.run_chunk(plan, bufs=(job_buf, win_buf))
            compute = n_sub / ((time.perf_counter() - t1) / reps)
            # Registry-routed: publish the canonical sched_* keys and
            # serialize them from there (same source the polisher's
            # stderr summary formats from).
            obs_metrics.publish_sched(sched.telemetry)
            sched_extras = obs_metrics.sched_extras()
            sched_extras["fixed_engine_windows_per_sec"] = \
                round(fixed_rate, 2)
    # Chunk pipelining overlaps h2d/compute/d2h, so pipelined end-to-end
    # reflects the tunnel-fed rate while compute-only is the chip rate;
    # both are reported.
    from racon_tpu.utils.jaxcache import cache_extras
    # Adaptive-round telemetry (collect_chunk increments these whenever a
    # chunk's d2h lands): executed vs scheduled refinement rounds and how
    # many chunks exited the device round loop early.
    adaptive_extras = {
        k: v for k, v in obs_metrics.registry().snapshot().items()
        if k.startswith("adaptive_")}
    # RACON_TPU_BENCH_DP=<path>: fold in the dp-scaling artifact from
    # scripts/dp_scaling_bench.py (dp_workers, dp_windows_per_sec_<N>,
    # dp_scaling_efficiency). Loud-failure contract: pointing at a
    # missing/invalid artifact, or one with no dp_* keys, aborts the
    # bench rather than silently publishing a record without the curve
    # the caller asked for.
    dp_extras = {}
    dp_path = envspec.read("RACON_TPU_BENCH_DP")
    if dp_path:
        with open(dp_path, "r", encoding="utf-8") as fh:
            dp_extras = json.load(fh)
        assert isinstance(dp_extras, dict) and any(
            k.startswith("dp_") for k in dp_extras), \
            f"RACON_TPU_BENCH_DP artifact {dp_path!r} has no dp_* " \
            "keys — re-run scripts/dp_scaling_bench.py --out"
        dp_extras = {k: v for k, v in dp_extras.items()
                     if k.startswith("dp_")}
    ingest_bench_extras = _ingest_bench()
    serve_bench_extras = _serve_bench(backend, coverage, wlen)
    cache_bench_extras = _cache_bench(backend, coverage, wlen)
    # Fleet-serve drill runs its passes in subprocesses on the jax
    # backend regardless of the parent's anchor — the warm-pool
    # comparison is about the persistent compile cache, which exists
    # on every jax platform. Merged AFTER the serve extras: its
    # serve_jobs_per_min re-base (same-workload single-daemon
    # baseline) must win.
    fleet_serve_extras = _fleet_serve_bench(coverage, wlen)
    # Ava drill runs serially in its own interpreter (peak RSS must be
    # the kF workload's own, not this process's accumulated footprint).
    ava_bench_extras = _ava_bench()
    extras = {**sched_extras, **e2e_transfers, **pipe_extras,
              **walk_bench_extras, **probe_extras, **adaptive_extras,
              **cache_extras(), **obs_metrics.resilience_extras(),
              **obs_metrics.ovl_extras(), **obs_metrics.dist_extras(),
              **obs_metrics.redo_extras(), **obs_metrics.ingest_extras(),
              **ingest_bench_extras, **serve_bench_extras,
              **cache_bench_extras, **fleet_serve_extras,
              **ava_bench_extras, **dp_extras}
    out = {
        # metric_version 17: same primary value as versions 2-16 (the
        # compute bench is untouched — ava workload planning shapes
        # which windows batch together and how results are
        # checkpointed, it never changes what the engine computes per
        # window). New in 17: the assembly-scale ava extras
        # (_ava_bench; one serial kF fragment correction of a
        # length-skewed read set through the real CLI in a fresh
        # interpreter, checkpointed through a v2 segmented manifest
        # store) — ava_reads_per_sec (corrected reads per wall
        # second), ava_peak_rss_mb (the child's own ru_maxrss),
        # ava_manifest_bytes_per_target (v2 segment amortization; v1's
        # per-target records hold this ~100 at any scale), plus
        # ava_bench_reads / ava_bench_seconds / ava_bench_seg_records
        # describing the drill, and the ava_* plan gauges
        # (ava_targets / ava_buckets / ava_quantum /
        # ava_compile_budget / ava_pad_frac) when a fleet run planned
        # shapes in-process — see docs/AVA.md.
        # metric_version 16: same primary value as versions 2-15 (the
        # compute bench is untouched — the gateway routes jobs around
        # the engine, it never changes what the engine computes). New
        # in 16: the fleet-serve extras (_fleet_serve_bench; the same
        # 3-job workload through a cold fresh interpreter and then a
        # warm-pool fresh interpreter, digests asserted identical) —
        # gate_fleet_jobs_per_min (fresh gateway worker on the shared
        # jaxcache warm pool), gate_compile_skip_s (cold wall − warm
        # wall: the compile seconds the pool saves every spawned
        # worker), gate_solo_seconds / gate_fleet_seconds /
        # gate_pool_entries describing the drill. SEMANTIC RE-BASE:
        # serve_jobs_per_min now reports the cold single-daemon wall
        # of this same workload (it previously came from the
        # in-process batcher drill), so gate_fleet_jobs_per_min >
        # serve_jobs_per_min is an apples-to-apples acceptance gate —
        # see docs/GATEWAY.md.
        # metric_version 15: same primary value as versions 2-14 (the
        # compute bench is untouched — telemetry observes the serve
        # plane, it never changes what the engine computes; the serve
        # drill's identity assert now doubles as the telemetry-on/off
        # byte-identity gate, since the solo reference pass runs before
        # any serve telemetry is recorded). New in 15: latency
        # percentiles from the serve drill's log-spaced histograms
        # (serve_job_latency_s_p50/p95/p99 — per-job wall through the
        # batcher, dispatch_round_s_p50/p95/p99 — per-dispatch device
        # wall, via obs/metrics.py hist_percentiles), plus the
        # flight-recorder cost gate — one full ring dump timed and
        # asserted < 1% of the serve drill's wall, published as
        # flight_dump_seconds / flight_overhead_fraction (see
        # docs/OBSERVABILITY.md "Crash flight recorder").
        # metric_version 14: same primary value as versions 2-13 (the
        # compute bench is untouched — the result cache sits in front
        # of the engine, it never changes what the engine computes).
        # New in 14: the result-cache extras from the resubmission
        # drill (_cache_bench; the same job's windows twice through a
        # WindowMemo-armed batcher, warm pass asserted fully
        # memo-served and byte-identical to a solo pass) —
        # cache_resubmit_speedup (cold wall / warm wall),
        # cache_cold_jobs_per_min / cache_warm_jobs_per_min, and the
        # cache_* registry accounting (cache_hits_total /
        # cache_misses_total / cache_stores_total / cache_bytes /
        # cache_hit_ratio) via result_cache_extras — see docs/CACHE.md.
        # metric_version 13: same primary value as versions 2-12 (the
        # compute bench is untouched — the serve plane wraps the same
        # engine, it does not change it). New in 13: the serve_* extras
        # from the cross-request batcher micro-bench (_serve_bench;
        # three concurrent jobs from two tenants through one
        # racon_tpu/server/batch.py batcher over a warm engine,
        # consensi asserted identical to a solo serial pass) —
        # serve_jobs_per_min (wall throughput of the 3-job drill),
        # serve_batch_occupancy (windows per dispatch / capacity, ~1.0
        # when the jobs' windows actually co-ride), serve_batches,
        # serve_batch_windows, serve_tenant_wait_s,
        # serve_queue_depth_peak, plus serve_bench_jobs /
        # serve_bench_seconds describing the drill itself.
        # metric_version 12: same primary value as versions 2-11 (the
        # compute bench still times the fused production chunk). New in
        # 12: the decoupled-walk stream comparison — the workload runs
        # through the pipeline executor twice at a small chunk size
        # (SCHED=0, byte-identity asserted), publishing
        # walk_async_windows_per_sec / walk_fused_windows_per_sec plus
        # the walk_* registry extras (walk_async_enabled,
        # walk_hidden_fraction — the fraction of walk seconds hidden
        # behind the next chunk's forward dispatch — walk_queue_peak,
        # walk_seconds, walk_overlap_s, walk_dispatches,
        # walk_fused_chunks). Also new: ingest_host_cores rides along
        # with the ingest micro-bench so the core-scaling caveat on
        # ingest_speedup_vs_serial (≈1x on a 1-core box by construction)
        # is readable from the record itself.
        # metric_version 11: same primary value as versions 2-10 (the
        # consensus bench itself reads no files). New in 11: the ingest
        # data-plane extras (ISSUE 12) — ingest_mb_per_sec /
        # ingest_serial_mb_per_sec / ingest_speedup_vs_serial from the
        # multi-member-gzip micro-bench (_ingest_bench; parallel member
        # inflate on the io/inflate.py worker pool vs the serial
        # gzip.open reader, records asserted identical; speedup scales
        # with physical cores), plus the registry's ingest_* accounting
        # (bytes in/out, inflate/parse/wait seconds, blocks, records,
        # ingest_fraction_of_wall) via ingest_extras. A perf number
        # produced with the ingest gate off shows ingest_enabled=0.
        # metric_version 10: same primary value as versions 2-9 (the
        # bench's own compute path is untouched this round). New in 10:
        # the measured dp-scaling curve rides along when
        # RACON_TPU_BENCH_DP points at a scripts/dp_scaling_bench.py
        # artifact — dp_workers (the counts run), dp_windows_per_sec_<N>
        # (fleet throughput at N ledger workers, merge byte-identity
        # gated against serial at every N), and dp_scaling_efficiency
        # (rate_N / (N * rate_1)). Absent when no artifact is supplied;
        # a supplied-but-invalid artifact fails the bench loudly. This
        # closes ROADMAP item 2's "measured dp-scaling curve as a
        # first-class bench metric".
        # metric_version 9: same primary value as versions 2-8 (the
        # chunk program changed again this round — quad-column packed
        # walk over the new u16 nxt2 plane, bit-identity-gated — so
        # compute-rate deltas vs version 8 are real perf). New in 9:
        # walk_chain_len (the serialized dependent-gather count of the
        # timed chunk's column walk, 161 at bench geometry under the
        # default RACON_TPU_WALK_K=4, 321 at k=2) and the
        # redo_device_windows / redo_host_windows counters from the
        # wide-band on-device redo (ops/redo.py) — host_windows stays 0
        # at bench geometry, so a perf number produced while windows
        # escaped to the host mid-polish is visibly flagged.
        # metric_version 8: same primary value as versions 2-7 (the
        # bench itself is single-process). New in 8: the dist_*
        # distributed-ledger extras (claims / shards_stolen /
        # lease_renewals / contigs_resumed / steal_latency_s ... from
        # racon_tpu/distributed/) ride along — absent on a bench that
        # never joined a work ledger, populated when the harness runs a
        # sharded polish in-process, so a perf number produced while
        # recovering stolen shards is visibly flagged.
        # metric_version 7: same primary value as versions 2-6 (the
        # consensus bench runs no overlap alignment, so the compute
        # rate is untouched). New in 7: the ovl_* extras ride along —
        # ovl_device_jobs / ovl_native_jobs / ovl_tiles_exec /
        # ovl_device_fraction from the tiled ultralong overlap path
        # (ops/ovl_align.py round 7) and align_phase_seconds, the
        # polisher's wall-clock alignment phase — all absent on a bench
        # that never aligned overlaps, populated when the genome bench
        # path runs a polish in-process.
        # metric_version 6: same primary value as versions 2-5
        # (compute-only windows/s of a warm production chunk). New in 6:
        # the e2e rate is the MEDIAN of RACON_TPU_BENCH_E2E_REPS reps
        # (per-rep rates in e2e_rep_windows_per_sec), an 8 MiB h2d
        # bandwidth probe rides along as h2d_probe_mb_per_s, and the
        # adaptive round-exit counters (adaptive_rounds_executed /
        # _scheduled / _early_exits) report how many refinement rounds
        # the chunks actually ran vs had scheduled (RACON_TPU_ADAPTIVE,
        # default on). The chunk program itself changed this round
        # (dual-column packed walk + i32-packed band slices + adaptive
        # exit, all bit-identity-gated), so compute-rate deltas vs
        # version 5 are real perf, not metric drift.
        # metric_version 5: same primary value as versions 2/3/4. New
        # in 5: res_* resilience extras (retry/fault/degradation/
        # checkpoint counters from racon_tpu/resilience/) ride along —
        # all zero/absent on a healthy bench, non-empty when
        # RACON_TPU_FAULTS or retry activity occurred, so a perf number
        # produced under degradation is visibly flagged.
        # metric_version 4: same primary value as versions 2/3
        # (compute-only windows/s of a warm production chunk — the
        # convergence scheduler's dispatch chain when RACON_TPU_SCHED is
        # on, the default, else the fixed fused dispatch). New in 4: the
        # same workload also runs through the streaming executor
        # (racon_tpu/pipeline/), asserted bit-identical to the serial
        # run, reported as e2e_pipelined_windows_per_sec with the pipe_*
        # stage/queue gauges and pipe_overlap_efficiency as extras.
        # Version 3 added registry-sourced e2e_h2d_*/e2e_d2h_* transfer
        # accounting; version 1 (rounds <= 5) timed the fixed fused
        # dispatch only — that series continues under
        # fixed_engine_windows_per_sec. Bump this whenever the primary
        # value's definition changes, so round-over-round comparisons
        # can't silently mix metrics.
        "metric_version": 17,
        "metric": f"POA windows/sec/chip, compute-only (direct-timed warm "
                  f"production chunk, convergence-scheduled refinement "
                  f"rounds — racon_tpu/sched/, telemetry in sched_* "
                  f"extras; w={wlen}, {coverage}x cov, "
                  f"backend={backend}:{dev}; vs_baseline = value / "
                  "MEASURED 64-thread-idealized native CPU anchor "
                  f"{CPU_64T_WINDOWS_PER_SEC:.1f} w/s; chunk-pipelined "
                  "end-to-end rate through this env's 1.4-7 MB/s tunnel "
                  "in e2e_* extras, streaming-pipeline rate in "
                  "e2e_pipelined_* / pipe_* extras)",
        "value": round(compute, 2),
        "unit": "windows/s",
        "vs_baseline": round(compute / CPU_64T_WINDOWS_PER_SEC, 3),
        # Cross-round continuity: BENCH_r01-r04 recorded "value" as the
        # e2e rate and (r04) the compute rate under compute_only_*; both
        # series stay readable under their old names.
        "compute_only_windows_per_sec": round(compute, 2),
        "compute_only_vs_baseline": round(compute /
                                          CPU_64T_WINDOWS_PER_SEC, 3),
        "e2e_windows_per_sec": round(e2e, 2),
        "e2e_vs_baseline": round(e2e / CPU_64T_WINDOWS_PER_SEC, 3),
        "e2e_pipelined_windows_per_sec": round(e2e_pipe, 2),
        "e2e_pipelined_vs_baseline": round(
            e2e_pipe / CPU_64T_WINDOWS_PER_SEC, 3),
        "cpu_anchor_1t_measured": CPU_1T_MEASURED,
        "vs_ref_spoa_64t_est": round(compute / CPU_64T_REF_SPOA_EST, 3),
        "n_windows": n_windows,
        **extras,
    }
    print(json.dumps(out))
    # RACON_TPU_BENCH_OUT=<path>: also persist the record durably. The
    # atomic write means a bench killed mid-emission leaves the previous
    # artifact intact rather than a torn JSON file.
    out_path = envspec.read("RACON_TPU_BENCH_OUT")
    if out_path:
        from racon_tpu.utils.atomicio import atomic_write_text
        atomic_write_text(out_path, json.dumps(out) + "\n")
    tracer.finish(metrics={**obs_metrics.registry().snapshot(),
                           "bench_value": out["value"]})


if __name__ == "__main__":
    if sys.argv[1:2] == ["--ava-child"]:
        sys.exit(_ava_child(int(sys.argv[2]), sys.argv[3]))
    main()
