"""Benchmark: POA window consensus throughput (windows/sec/chip).

Prints exactly one JSON line on stdout:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Workload matches BASELINE.md's north-star metric: w=500-class windows at
30x coverage (the reference's hot loop, src/polisher.cpp:451-513 ->
src/window.cpp:61-137), run through the full PoaEngine pipeline — batched
NW on device (or native host fallback), refinement rounds, and host column
merge — i.e. the real end-to-end consensus cost per window, not just the
kernel.

Baseline: BASELINE.json targets >=20x a 64-thread CPU SPOA path. The
reference publishes no absolute numbers, so the CPU anchor is estimated
from the reference's own workload: single-thread racon polishes the
bundled 96-window lambda dataset in tens of seconds (~2.5 windows/s);
64 ideal threads ~= 160 windows/s. vs_baseline = value / 160, so
vs_baseline >= 1.0 means at least estimated-64-thread-CPU parity and
>= 20 hits the north-star target.
"""

import json
import sys
import time

import numpy as np

CPU_64T_WINDOWS_PER_SEC = 160.0  # estimated 64-thread CPU SPOA anchor


def build_windows(n_windows: int, coverage: int, wlen: int, seed: int = 0):
    from racon_tpu.models.window import Window, WindowType
    from racon_tpu.ops.encode import decode_bases

    rng = np.random.default_rng(seed)
    windows = []
    for _ in range(n_windows):
        true = rng.integers(0, 4, wlen).astype(np.uint8)

        def noisy(rate=0.10):
            keep = rng.random(wlen)
            out = []
            for b, r in zip(true, keep):
                if r < rate / 3:
                    continue
                if r < 2 * rate / 3:
                    out.append(int(rng.integers(0, 4)))
                    continue
                out.append(int(b))
                if r < rate:
                    out.append(int(rng.integers(0, 4)))
            return decode_bases(np.asarray(out, np.uint8))

        backbone = noisy()
        qual = bytes(rng.integers(33 + 8, 33 + 25, len(backbone),
                                  dtype=np.uint8))
        w = Window(0, 0, WindowType.TGS, backbone, qual)
        for _ in range(coverage):
            lay = noisy()
            lq = bytes(rng.integers(33 + 8, 33 + 25, len(lay),
                                    dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        windows.append(w)
    return windows


def main():
    n_windows = int(sys.argv[1]) if len(sys.argv) > 1 else 96
    coverage = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    wlen = 500

    import jax
    from racon_tpu.ops.poa import PoaEngine, _accelerator_present

    backend = "jax" if _accelerator_present() else "native"
    dev = jax.devices()[0].platform if backend == "jax" else "cpu-native"

    # Warmup with the same workload shape so every bucketed kernel the
    # measured run needs is already compiled.
    eng = PoaEngine(backend=backend)
    eng.consensus_windows(build_windows(n_windows, coverage, wlen, seed=99))

    windows = build_windows(n_windows, coverage, wlen)
    t0 = time.perf_counter()
    eng = PoaEngine(backend=backend)
    n_polished = eng.consensus_windows(windows)
    dt = time.perf_counter() - t0
    assert n_polished == n_windows

    # Sanity: consensus must actually polish (each window was built from a
    # 10%-error backbone; consensus should be near the truth, i.e. differ
    # from the backbone).
    n_changed = sum(1 for w in windows if w.consensus != bytes(w.backbone))
    assert n_changed > n_windows * 0.9, "consensus did not polish"

    value = n_windows / dt
    print(json.dumps({
        "metric": f"POA windows/sec/chip (w={wlen}, {coverage}x cov, "
                  f"full engine incl. refinement, backend={backend}:{dev})",
        "value": round(value, 2),
        "unit": "windows/s",
        "vs_baseline": round(value / CPU_64T_WINDOWS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
