"""Ablation: tband build strategies and g3/g2 gather dtype slimming."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, reps=4):
    np.asarray(jax.tree.leaves(fn(*args))[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / reps


def main():
    B, Lq, W, LA = 3072, 640, 384, 768
    n_win = 96
    rng = np.random.default_rng(0)
    flat = jnp.asarray(rng.integers(0, 4, (n_win + 1) * LA).astype(np.uint8))
    win = jnp.asarray(np.repeat(np.arange(n_win + 1), 32)[:B].astype(np.int32))
    t_off = jnp.zeros(B, jnp.int32)
    klo = jnp.full(B, -192, jnp.int32)
    lt = jnp.full(B, 500, jnp.int32)

    @jax.jit
    def tband_take():
        y = jnp.arange(W + Lq, dtype=jnp.int32)[None, :]
        rel = klo[:, None] + y
        okb = (rel >= 0) & (rel < lt[:, None])
        gidxb = (win[:, None] * LA + jnp.clip(t_off[:, None] + rel, 0,
                                              LA - 1))
        return jnp.sum(jnp.where(okb, jnp.take(flat, gidxb), 7)
                       .astype(jnp.uint8)[:, 0], dtype=jnp.int32)

    print(f"tband take       : {timeit(tband_take) * 1e3:7.1f} ms",
          flush=True)

    # Slice-mode: pad the anchor table so per-lane slices never clip,
    # then one vmapped dynamic_slice (lowers to a slice-gather).
    PADW = W + Lq

    @jax.jit
    def tband_slice():
        tab = jnp.concatenate(
            [jnp.full((PADW,), 7, flat.dtype), flat,
             jnp.full((PADW,), 7, flat.dtype)])
        start = win * LA + t_off + klo + PADW
        y = jnp.arange(PADW, dtype=jnp.int32)[None, :]
        rel = klo[:, None] + y
        okb = (rel >= 0) & (rel < lt[:, None])
        sl = jax.vmap(lambda s: jax.lax.dynamic_slice(tab, (s,), (PADW,)))(
            start)
        # clip semantics beyond the anchor row differ from take; mask ok
        out = jnp.where(okb, sl, 7)
        return jnp.sum(out[:, 0], dtype=jnp.int32)

    print(f"tband dyn-slice  : {timeit(tband_slice) * 1e3:7.1f} ms",
          flush=True)

    # g3-style gathers at qstart indices
    qstart = jnp.asarray(rng.integers(0, Lq - 8, (B, LA + 1)).astype(np.int32))
    qx = jnp.asarray(rng.integers(0, 4, (B, Lq)).astype(np.uint8))
    qw8 = jnp.asarray(rng.integers(1, 40, (B, Lq)).astype(np.uint8))
    K = 8

    @jax.jit
    def g3_f32(qstart):
        qw = qw8.astype(jnp.float32)
        qwcum = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.float32), jnp.cumsum(qw, axis=1)], axis=1)
        qx_pad = jnp.concatenate(
            [qx.astype(jnp.int32),
             jnp.repeat(qx[:, -1:].astype(jnp.int32), K - 1, axis=1)], axis=1)
        qw_pad = jnp.concatenate(
            [qw, jnp.repeat(qw[:, -1:], K - 1, axis=1)], axis=1)
        chans = ([qx_pad[:, k:k + Lq].astype(jnp.float32)
                  for k in range(K)] +
                 [qw_pad[:, k:k + Lq] for k in range(K)] +
                 [qwcum[:, :Lq]])
        stack = jnp.stack(chans, axis=-1)
        G = jnp.take_along_axis(stack, qstart[:, :, None], axis=1)
        return jnp.sum(G[:, 0])

    print(f"g3 f32 17ch      : {timeit(g3_f32, qstart) * 1e3:7.1f} ms",
          flush=True)

    @jax.jit
    def g3_u8(qstart):
        # 16 uint8 channels in one gather + qwcum int32 in another
        qx_pad = jnp.concatenate(
            [qx, jnp.repeat(qx[:, -1:], K - 1, axis=1)], axis=1)
        qw_pad = jnp.concatenate(
            [qw8, jnp.repeat(qw8[:, -1:], K - 1, axis=1)], axis=1)
        chans = ([qx_pad[:, k:k + Lq] for k in range(K)] +
                 [qw_pad[:, k:k + Lq] for k in range(K)])
        stack = jnp.stack(chans, axis=-1)                 # [B, Lq, 16] u8
        G = jnp.take_along_axis(stack, qstart[:, :, None], axis=1)
        qwcum = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.int32),
             jnp.cumsum(qw8.astype(jnp.int32), axis=1)], axis=1)
        Gc = jnp.take_along_axis(qwcum, qstart, axis=1)
        return jnp.sum(G[:, 0].astype(jnp.int32)) + jnp.sum(Gc[:, 0])

    print(f"g3 u8 16ch+cum   : {timeit(g3_u8, qstart) * 1e3:7.1f} ms",
          flush=True)

    @jax.jit
    def g3_u8_interleave(qstart):
        # single uint8 stack including 4 bytes of qwcum bitcast
        qw = qw8.astype(jnp.int32)
        qwcum = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.int32), jnp.cumsum(qw, axis=1)],
            axis=1)[:, :Lq]
        cum8 = jax.lax.bitcast_convert_type(qwcum, jnp.uint8)  # [B, Lq, 4]
        qx_pad = jnp.concatenate(
            [qx, jnp.repeat(qx[:, -1:], K - 1, axis=1)], axis=1)
        qw_pad = jnp.concatenate(
            [qw8, jnp.repeat(qw8[:, -1:], K - 1, axis=1)], axis=1)
        chans = ([qx_pad[:, k:k + Lq] for k in range(K)] +
                 [qw_pad[:, k:k + Lq] for k in range(K)])
        stack = jnp.concatenate(
            [jnp.stack(chans, axis=-1), cum8], axis=-1)   # [B, Lq, 20] u8
        G = jnp.take_along_axis(stack, qstart[:, :, None], axis=1)
        return jnp.sum(G[:, 0].astype(jnp.int32))

    print(f"g3 u8 20ch 1gthr : {timeit(g3_u8_interleave, qstart) * 1e3:7.1f}"
          f" ms", flush=True)

    # g2-style: 2 channels at qi
    qi = jnp.asarray(rng.integers(0, Lq, (B, LA + 1)).astype(np.int32))

    @jax.jit
    def g2_f32(qi):
        stack = jnp.stack([qx.astype(jnp.float32),
                           qw8.astype(jnp.float32)], axis=-1)
        G = jnp.take_along_axis(stack, qi[:, :, None], axis=1)
        return jnp.sum(G[:, 0])

    print(f"g2 f32 2ch       : {timeit(g2_f32, qi) * 1e3:7.1f} ms",
          flush=True)

    @jax.jit
    def g2_u8(qi):
        stack = jnp.stack([qx, qw8], axis=-1)
        G = jnp.take_along_axis(stack, qi[:, :, None], axis=1)
        return jnp.sum(G[:, 0].astype(jnp.int32))

    print(f"g2 u8 2ch        : {timeit(g2_u8, qi) * 1e3:7.1f} ms",
          flush=True)

    # rekey gathers (int16, 2ch) as in extract_votes_cols
    S = LA + 1
    ch16 = jnp.asarray(rng.integers(0, 600, (B, S, 2)).astype(np.int16))
    tg = jnp.asarray(rng.integers(0, S, (B, LA + 1)).astype(np.int32))

    @jax.jit
    def rekey(tg):
        G = jnp.take_along_axis(ch16, tg[:, :, None], axis=1)
        return jnp.sum(G[:, 0].astype(jnp.int32))

    print(f"rekey i16 2ch    : {timeit(rekey, tg) * 1e3:7.1f} ms",
          flush=True)


if __name__ == "__main__":
    main()
