"""CI smoke: tiny polish through the streaming pipeline, trace-gated.

Runs the real CLI path twice on a synthetic contig — serial
(RACON_TPU_PIPELINE=0) and streamed (--pipeline-depth 2) — asserts the
polished FASTA is byte-identical (the pipeline's core contract), then
validates the streamed run's trace against the documented schema
(pipeline/stage/queue span kinds and their required attrs —
scripts/obs_report.py --validate logic) and checks the pipe_* gauges
landed in the metrics footer.
"""

import contextlib
import io
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from racon_tpu import cli                            # noqa: E402
from scripts import obs_report                       # noqa: E402
from scripts.obs_smoke import _write_inputs          # noqa: E402


def _run_cli(d, *extra, trace=None):
    if trace is not None:
        os.environ["RACON_TPU_TRACE"] = trace
    else:
        os.environ.pop("RACON_TPU_TRACE", None)

    class _Capture(io.StringIO):
        pass

    stdout = _Capture()
    stdout.buffer = io.BytesIO()
    with contextlib.redirect_stdout(stdout):
        rc = cli.main(["--backend", "jax", *extra,
                       os.path.join(d, "reads.fasta"),
                       os.path.join(d, "ovl.paf"),
                       os.path.join(d, "draft.fasta")])
    assert rc == 0, f"cli exited {rc}"
    return stdout.buffer.getvalue()


def main():
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)

        os.environ["RACON_TPU_PIPELINE"] = "0"
        serial = _run_cli(d)
        assert serial.startswith(b">c1 LN:i:"), "no polished FASTA"

        os.environ.pop("RACON_TPU_PIPELINE", None)
        trace = os.path.join(d, "trace.jsonl")
        from racon_tpu.obs import metrics as obs_metrics
        obs_metrics.reset()
        streamed = _run_cli(d, "--pipeline-depth", "2", trace=trace)
        os.environ.pop("RACON_TPU_TRACE", None)

        assert streamed == serial, \
            "pipelined FASTA differs from serial output"

        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        kinds = {s["kind"] for s in tr["spans"].values()}
        for want in ("run", "pipeline", "stage", "queue", "chunk"):
            assert want in kinds, f"no {want!r} span in trace ({kinds})"
        m = tr["metrics"]
        assert m is not None, "no metrics footer"
        assert m.get("pipe_runs", 0) >= 1, "no pipeline accounting"
        assert "pipe_stage_compute_busy_s" in m, "no stage gauges"
        assert "pipe_overlap_efficiency" in m, "no overlap efficiency"
        print(f"[pipeline-smoke] trace ok: {len(tr['spans'])} spans, "
              f"kinds={sorted(kinds)}, overlap_eff="
              f"{m['pipe_overlap_efficiency']}", flush=True)
    print("[pipeline-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
