"""Measured dp-scaling curve: 1/2/4/8-worker ledger fleets over the
bench genome (closes ROADMAP item 2's open half).

For each requested worker count N this script runs a complete
``--ledger-dir`` fleet of N real CLI subprocesses over the same
synthetic genome, gates the merged FASTA byte-identical against a
single serial run, and measures fleet throughput as total polished
windows (from the fleet metric model, racon_tpu/obs/fleet.py) over the
fleet's wall clock. The curve is emitted as one JSON object::

    {"dp_workers": [1, 2, 4], "dp_windows_per_sec_1": ...,
     "dp_windows_per_sec_2": ..., "dp_windows_per_sec_4": ...,
     "dp_scaling_efficiency": rate_N / (N * rate_1), ...}

Publish it through bench.py (metric_version 10) by pointing
``RACON_TPU_BENCH_DP`` at the artifact::

    python scripts/dp_scaling_bench.py --out /tmp/dp.json
    RACON_TPU_BENCH_DP=/tmp/dp.json python bench.py

Worker counts: ``--workers 1,2,4,8`` (default ``auto`` = 1,2,4 plus 8
when the host has >= 8 CPUs). An explicitly requested count the host
cannot run (more workers than CPUs) is a **hard error** — silently
benching fewer workers would publish a scaling curve that was never
measured. On this CPU image the curve measures the *fleet machinery's*
scaling (sharding, leases, per-process JAX compute); on a TPU pod each
worker binds its own chip and the same curve reads as chip scaling.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from racon_tpu.utils import envspec

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = ("import sys; from racon_tpu import cli; "
        "sys.exit(cli.main(sys.argv[1:]))")
N_CONTIGS = 8
N_READS = 6
DEFAULT_COUNTS = (1, 2, 4, 8)


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d, contig_len: int):
    rng = np.random.default_rng(41)
    drafts, reads, paf = [], [], []
    for c in range(N_CONTIGS):
        truth = BASES[rng.integers(0, 4, contig_len + 40 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(N_READS):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cmd(d, *extra):
    return [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
            os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
            os.path.join(d, "draft.fasta")]


def _env():
    e = dict(os.environ)
    for k in ("RACON_TPU_FAULTS", "RACON_TPU_TRACE", "RACON_TPU_OBS_DIR",
              "RACON_TPU_OBS_FLUSH_S"):
        e.pop(k, None)
    # One shard per contig: every worker count up to 8 has enough
    # shards to keep all workers busy, and the partition is identical
    # across counts, so per-N differences are scheduling, not layout.
    e["RACON_TPU_DIST_SHARDS"] = str(N_CONTIGS)
    return e


def _run_fleet(d, n_workers: int, timeout_s: float):
    """One complete N-worker fleet; returns (merged_bytes, wall_s,
    fleet_model)."""
    from racon_tpu.obs import fleet as obs_fleet
    ledger = os.path.join(d, f"ledger_{n_workers}")
    env = _env()
    t0 = time.perf_counter()
    procs = [subprocess.Popen(
        _cmd(d, "--ledger-dir", ledger, "--workers", str(n_workers),
             "--worker-id", f"w{i}"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        for i in range(n_workers)]
    outs = []
    for p in procs:
        o, err = p.communicate(timeout=timeout_s)
        if p.returncode != 0:
            for q in procs:
                q.kill()
            raise RuntimeError(
                f"[dp-scaling] worker exited {p.returncode} in the "
                f"{n_workers}-worker fleet:\n{err.decode()}")
        outs.append(o)
    wall = time.perf_counter() - t0
    emitters = [o for o in outs if o]
    if len(emitters) != 1:
        raise RuntimeError(
            f"[dp-scaling] expected exactly one merge emitter, got "
            f"{len(emitters)} in the {n_workers}-worker fleet")
    model = obs_fleet.aggregate(ledger)
    return emitters[0], wall, model


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        out_path = argv[i + 1]
        del argv[i:i + 2]
    counts_arg = "auto"
    if "--workers" in argv:
        i = argv.index("--workers")
        counts_arg = argv[i + 1]
        del argv[i:i + 2]
    contig_len = int(argv[argv.index("--contig-len") + 1]) \
        if "--contig-len" in argv else 300
    timeout_s = float(envspec.read("RACON_TPU_DP_TIMEOUT"))

    ncpu = os.cpu_count() or 1
    if counts_arg == "auto":
        counts = [n for n in DEFAULT_COUNTS if n <= max(4, ncpu)]
        dropped = [n for n in DEFAULT_COUNTS if n not in counts]
        if dropped:
            print(f"[dp-scaling] host has {ncpu} CPUs: skipping "
                  f"{dropped} worker count(s) (request explicitly "
                  "with --workers to force)", file=sys.stderr)
    else:
        counts = sorted({int(p) for p in counts_arg.split(",")})
        bad = [n for n in counts if n < 1]
        if bad:
            print(f"[dp-scaling] error: invalid worker count(s) {bad}",
                  file=sys.stderr)
            return 2
        # The loud-failure contract: an explicitly requested count the
        # host cannot actually run is an error, NOT a silent downgrade
        # to fewer workers.
        over = [n for n in counts if n > ncpu]
        if over:
            print(f"[dp-scaling] error: requested worker count(s) "
                  f"{over} exceed the host's {ncpu} CPUs — each fleet "
                  "worker is a full polisher process; benching fewer "
                  "would mislabel the curve. Drop the count or use "
                  "a larger host.", file=sys.stderr)
            return 1
    if max(counts) > N_CONTIGS:
        print(f"[dp-scaling] error: worker count {max(counts)} "
              f"exceeds the workload's {N_CONTIGS} shards — workers "
              "beyond the shard count would sit idle and dilute the "
              "curve", file=sys.stderr)
        return 1

    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d, contig_len)

        # Serial baseline: correctness gate + the window count every
        # fleet must reproduce.
        proc = subprocess.run(_cmd(d), capture_output=True, env=_env())
        if proc.returncode != 0:
            print(proc.stderr.decode(), file=sys.stderr)
            return 1
        base = proc.stdout
        assert base.count(b">") == N_CONTIGS

        rates = {}
        windows_total = None
        for n in counts:
            merged, wall, model = _run_fleet(d, n, timeout_s)
            if merged != base:
                print(f"[dp-scaling] error: {n}-worker merged output "
                      "differs from serial run", file=sys.stderr)
                return 1
            windows = model["fleet"].get("poa_windows_total", 0)
            if not windows:
                print(f"[dp-scaling] error: fleet model for n={n} "
                      "reports zero polished windows", file=sys.stderr)
                return 1
            if windows_total is None:
                windows_total = windows
            rates[n] = windows / wall
            print(f"[dp-scaling] n={n}: {windows} windows in "
                  f"{wall:.2f}s = {rates[n]:.2f} windows/s "
                  f"(merge byte-identical to serial)", file=sys.stderr)

    n_max = max(counts)
    out = {"dp_workers": counts,
           "dp_total_windows": windows_total,
           "dp_scaling_efficiency": round(
               rates[n_max] / (n_max * rates[1]), 3) if 1 in rates
           else None}
    for n, r in rates.items():
        out[f"dp_windows_per_sec_{n}"] = round(r, 2)
    text = json.dumps(out, sort_keys=True)
    print(text)
    if out_path:
        from racon_tpu.utils.atomicio import atomic_write_text
        atomic_write_text(out_path, text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
