"""CI smoke: decoupled asynchronous column walk (ISSUE 14).

A multi-chunk traced stream on synthetic windows — small chunk size so
several device chunks are in flight, RACON_TPU_SCHED=0 so the stream
takes the fixed-round path where the walk stage actually decouples
(the scheduler keeps fused dispatches; see sched/scheduler.py). Gates:

1. the decoupled run reports ``walk_dispatches >= 1`` and
   ``walk_hidden_fraction > 0`` — chunk N's walk measurably overlapped
   chunk N+1's forward dispatch;
2. its trace validates against the span schema and contains the
   ``walk`` span kind with the documented attrs;
3. a rerun under RACON_TPU_WALK_ASYNC=0 (fused dispatches) produces
   byte-identical consensi;
4. one stall drill: a wedged walk stage (hang at pipe/walk) trips the
   stall detector and the stream recovers to full, byte-identical
   coverage on the host path.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)

_ENVS = ("RACON_TPU_SCHED", "RACON_TPU_PIPELINE", "RACON_TPU_WALK_ASYNC",
         "RACON_TPU_STALL_S", "RACON_TPU_TRACE")


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.04:
            continue
        out.append(int(BASES[rng.integers(0, 4)]) if r < 0.08 else int(b))
        if r > 0.96:
            out.append(int(BASES[rng.integers(0, 4)]))
    return bytes(out)


def _build_windows(n, seed=0, coverage=5, wlen=80):
    from racon_tpu.models.window import Window, WindowType
    rng = np.random.default_rng(seed)
    ws = []
    for i in range(n):
        truth = BASES[rng.integers(0, 4, wlen)]
        backbone = _mutate(rng, truth)
        qual = bytes(rng.integers(43, 63, len(backbone), dtype=np.uint8))
        w = Window(i, i % 7, WindowType.TGS, backbone, qual)
        for _ in range(coverage):
            lay = _mutate(rng, truth)
            lq = bytes(rng.integers(43, 63, len(lay), dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        ws.append(w)
    return ws


def _stream(seed, trace=None):
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.obs import trace as trace_mod
    from racon_tpu.ops.poa import PoaEngine
    from racon_tpu.pipeline.streaming import stream_consensus

    obs_metrics.reset()
    tracer = trace_mod.configure(trace)
    ws = _build_windows(32, seed=seed)
    ranges = list(stream_consensus(PoaEngine(backend="jax"), ws,
                                   chunk=8, depth=2))
    flat = [i for s, e in ranges for i in range(s, e)]
    assert flat == list(range(len(ws))), "incomplete stream coverage"
    snap = obs_metrics.registry().snapshot()
    if trace is not None:
        tracer.finish(metrics=snap)
        trace_mod.configure("")  # detach so later runs don't append
    return [w.consensus for w in ws], snap


def main():
    saved = {k: os.environ.get(k) for k in _ENVS}
    os.environ["RACON_TPU_SCHED"] = "0"
    os.environ["RACON_TPU_PIPELINE"] = "1"
    os.environ.pop("RACON_TPU_TRACE", None)
    try:
        import tempfile
        from scripts import obs_report
        from racon_tpu.resilience import faults

        with tempfile.TemporaryDirectory() as d:
            trace = os.path.join(d, "walk_trace.jsonl")
            os.environ["RACON_TPU_WALK_ASYNC"] = "1"
            decoupled, snap = _stream(21, trace=trace)

            assert snap.get("walk_async_enabled") == 1, snap
            assert snap.get("walk_dispatches", 0) >= 1, \
                f"no decoupled walk dispatches: {snap}"
            hidden = snap.get("walk_hidden_fraction", 0.0)
            assert hidden > 0, \
                f"no walk latency hidden (walk_hidden_fraction={hidden})"

            tr = obs_report.load_trace(trace)
            errs = obs_report.validate(tr)
            assert not errs, \
                "trace schema violations:\n" + "\n".join(errs)
            kinds = {s["kind"] for s in tr["spans"].values()}
            assert "walk" in kinds, f"no walk span in trace ({kinds})"
            walks = [s for s in tr["spans"].values()
                     if s["kind"] == "walk"]
            assert all("lanes" in s and "windows" in s for s in walks)
            print(f"[walk-smoke] decoupled ok: "
                  f"{snap['walk_dispatches']} walk dispatches, "
                  f"hidden_fraction={hidden}, "
                  f"queue_peak={snap.get('walk_queue_peak')}", flush=True)

            os.environ["RACON_TPU_WALK_ASYNC"] = "0"
            fused, fsnap = _stream(21)
            assert fsnap.get("walk_dispatches", 0) == 0
            assert fused == decoupled, \
                "decoupled consensi differ from fused path"
            print("[walk-smoke] byte-identity vs WALK_ASYNC=0 ok",
                  flush=True)

            # Stall drill: wedge the walk stage; the detector must trip
            # and the host re-polish must restore full coverage with
            # unchanged bytes.
            os.environ["RACON_TPU_WALK_ASYNC"] = "1"
            os.environ["RACON_TPU_STALL_S"] = "0.5"
            faults.configure("pipe/walk:0!hang=3")
            try:
                stalled, ssnap = _stream(21)
            finally:
                faults.configure(None)
            assert stalled == fused, "stall recovery changed bytes"
            assert ssnap.get("pipe_stall_events", 0) >= 1, ssnap
            print("[walk-smoke] stall drill ok: "
                  f"{ssnap['pipe_stall_events']} stall event(s)",
                  flush=True)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    print("[walk-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
