"""Stage-by-stage wall profile of the device consensus engine on the
current backend (real TPU under axon; CPU with jax_platforms=cpu).

Times, at bench shapes, each piece of device_round in isolation by
jitting progressively larger prefixes of the round and blocking on a
scalar consume of the result. Prints one line per stage.

Usage: python scripts/profile_engine.py [n_windows] [coverage]
       RACON_TPU_TRACE=/tmp/racon_trace python scripts/profile_engine.py
           ... additionally captures a jax.profiler trace of one full
           engine run (view with tensorboard/xprof) — the in-repo
           re-measurement harness for the tracing subsystem.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from racon_tpu.utils import envspec

import numpy as np


def t(fn, *args, reps=2, **kw):
    out = np.asarray(fn(*args, **kw))   # compile + force d2h
    t0 = time.perf_counter()
    for _ in range(reps):
        out = np.asarray(fn(*args, **kw))
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp
    import functools
    from bench import build_windows
    from racon_tpu.ops.device_poa import ChunkPlan, run_caps, _use_pallas
    from racon_tpu.ops import device_merge as dm
    from racon_tpu.ops import flat as flatmod

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 96
    cov = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    windows = build_windows(n, cov, 500, seed=0)
    lq = max(max(len(d) for d in w.layer_data) for w in windows)
    la = max(len(w.backbone) for w in windows)
    lq_cap, la_cap = run_caps(lq, la)
    plan = ChunkPlan(windows, lq_cap=lq_cap, la_cap=la_cap)
    print(f"backend={jax.default_backend()} B={plan.B} Lq={plan.Lq} "
          f"LA={plan.LA} W={plan.band_w} n_win={plan.n_win} "
          f"steps={plan.steps}", flush=True)
    M, X, G, INS = 5, -4, -8, 0.3

    t0 = time.perf_counter()
    dev = jax.device_put((plan.bb, plan.bbw, plan.alen, plan.begin,
                          plan.end, plan.q, plan.qw8, plan.lq,
                          plan.w_read, plan.win))
    jax.block_until_ready(dev)
    bb, bbw, alen, begin, end, q, qw8, lqv, w_read, win = dev
    print(f"h2d: {time.perf_counter() - t0:.3f}s", flush=True)

    pallas = _use_pallas(plan.B, plan.Lq, plan.LA)
    LA, Lq, n_win = plan.LA, plan.Lq, plan.n_win

    @functools.partial(jax.jit, static_argnames=("upto",))
    def stage(bb, bbw, alen, begin, end, q, qw8, lqv, w_read, win, *,
              upto):
        L = jnp.take(alen, win)
        b_c = jnp.clip(begin, 0, L - 1)
        e_c = jnp.clip(end, b_c, L - 1)
        offs = L // 100
        full = (b_c < offs) & (e_c > L - offs)
        t_off = jnp.where(full, 0, b_c).astype(jnp.int32)
        lt = jnp.where(full, L, e_c - b_c + 1).astype(jnp.int32)
        flat = bb.reshape(-1)
        from racon_tpu.ops.colwalk import col_walk
        band_w = plan.band_w
        if band_w:
            from racon_tpu.ops.pallas.band_kernel import (
                fw_dirs_band, fw_dirs_band_xla, band_geometry)
            klo, wl = band_geometry(lqv, lt, band_w)
            y = jnp.arange(band_w + Lq, dtype=jnp.int32)[None, :]
            rel = klo[:, None] + y
            okb = (rel >= 0) & (rel < lt[:, None])
            gidxb = (win[:, None] * LA +
                     jnp.clip(t_off[:, None] + rel, 0, LA - 1))
            tband = jnp.where(okb, jnp.take(flat, gidxb),
                              7).astype(jnp.uint8)
            if upto == "tband":
                return jnp.sum(tband[:, 0], dtype=jnp.int32)
            fwd = fw_dirs_band if pallas else fw_dirs_band_xla
            dirs, nxt, hlast = fwd(tband, q.T, klo, lqv, match=M,
                                   mismatch=X, gap=G, W=band_w)
            if upto == "fw":
                return (jnp.sum(dirs[0, 0].astype(jnp.int32)) +
                        jnp.sum(hlast))
            cols = col_walk(dirs, lqv, lt, klo, t_off, LA=LA,
                            layout="band_t" if pallas else "band",
                            nxt=nxt)
        else:
            x = jnp.arange(LA, dtype=jnp.int32)[None, :]
            ok = x < lt[:, None]
            gidx = (win[:, None] * LA +
                    jnp.clip(t_off[:, None] + x, 0, LA - 1))
            tbuf = jnp.where(ok, jnp.take(flat, gidx), 7).astype(jnp.uint8)
            if pallas:
                from racon_tpu.ops.pallas.flat_kernel import fw_dirs_pallas
                dirs = fw_dirs_pallas(tbuf, q.T, match=M, mismatch=X,
                                      gap=G)
            else:
                dirs = flatmod.fw_dirs_xla(tbuf, q.T, match=M, mismatch=X,
                                           gap=G)
            if upto == "fw":
                return jnp.sum(dirs[0, 0].astype(jnp.int32))
            cols = col_walk(dirs, lqv, lt, None, t_off, LA=LA,
                            layout="flat")
        if upto == "tb":
            return sum(jnp.sum(cols[k][:, 0], dtype=jnp.int32)
                       for k in ("ins_len", "qstart", "op_c", "qi_c"))
        votes = dm.extract_votes_cols(cols, q, qw8, w_read, lt, t_off, LA)
        if upto == "votes":
            return sum(jnp.sum(v) for v in votes.values())
        acc = dm.aggregate_votes(votes, win, n_win + 1)
        if upto == "agg":
            return sum(jnp.sum(v) for v in acc.values())
        acc = {k: v[:-1] for k, v in acc.items()}
        acc = dm.add_backbone(acc, bb[:-1], bbw[:-1], alen[:-1])
        asm = dm.assemble(acc, alen[:-1], INS)
        codes, cov_, total = dm.compact(asm, LA)
        map_b, map_e = dm.coord_maps(asm, alen[:-1], LA)
        return (jnp.sum(codes, dtype=jnp.int32) + jnp.sum(total) +
                jnp.sum(map_b) + jnp.sum(map_e) + jnp.sum(cov_))

    args = (bb, bbw, alen, begin, end, q, qw8, lqv, w_read, win)
    prev = 0.0
    for upto in ("tband", "fw", "tb", "votes", "agg", "all"):
        dt = t(stage, *args, upto=upto)
        print(f"{upto:6s}: {dt:.3f}s (+{dt - prev:.3f}s)", flush=True)
        prev = dt

    trace_dir = envspec.read("RACON_TPU_TRACE")
    if trace_dir:
        from racon_tpu.ops.poa import PoaEngine
        eng = PoaEngine(backend="jax")
        eng.consensus_windows(build_windows(n, cov, 500, seed=1))  # warm
        with jax.profiler.trace(trace_dir):
            eng.consensus_windows(windows)
        print(f"jax.profiler trace written to {trace_dir}", flush=True)


if __name__ == "__main__":
    main()
