"""Ablation: where extract_votes_cols spends its time on the real TPU.

profile_engine.py (round-5) shows the votes stage dominating a round at
larger B (+188 ms at B=6144 vs +59 ms for the column walk). This script
times jitted prefixes of extract_votes_cols at bench-like shapes with
synthetic walk outputs, so each sub-piece's marginal cost is visible.

Usage: python scripts/ablate_votes.py [B]
"""

import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def t(fn, *args, reps=3, **kw):
    out = np.asarray(fn(*args, **kw))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = np.asarray(fn(*args, **kw))
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops.device_merge import NBASE, K_INS, _onehot
    from racon_tpu.ops.cigar import DIAG
    from racon_tpu.ops.flat import U_SAT as _U_SAT

    B = int(sys.argv[1]) if len(sys.argv) > 1 else 6144
    Lq, LA = 640, 768
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.integers(0, 4, (B, Lq)).astype(np.uint8))
    qw8 = jnp.asarray(rng.integers(1, 60, (B, Lq)).astype(np.uint8))
    w_read = jnp.asarray(rng.random(B).astype(np.float32) * 30)
    lt = jnp.asarray(rng.integers(450, 530, B).astype(np.int32))
    t_off = jnp.zeros(B, jnp.int32)
    cols = {
        "ins_len": jnp.asarray(
            (rng.random((B, LA + 2)) < 0.03).astype(np.int16)),
        "qstart": jnp.asarray(
            np.clip(np.tile(np.arange(LA + 2), (B, 1)) - 10, 0, Lq - 1)
            .astype(np.int16)),
        "op_c": jnp.asarray(rng.choice([0, 1, 2], (B, LA + 2),
                                       p=[0.9, 0.05, 0.05])
                            .astype(np.int16)),
        "qi_c": jnp.asarray(
            np.clip(np.tile(np.arange(LA + 2), (B, 1)) - 9, 0, Lq - 1)
            .astype(np.int16)),
        "sat": jnp.zeros(B, bool),
    }

    @functools.partial(jax.jit, static_argnames=("upto",))
    def stage(cols, q, qw8, w_read, lt, t_off, *, upto):
        ltc = lt[:, None]
        pa = jnp.arange(LA + 1, dtype=jnp.int32)[None, :]
        c = pa - t_off[:, None]
        in_cols = (c >= 0) & (c < ltc)
        in_gaps = (c >= 0) & (c <= ltc)
        ins_len = jnp.where(in_gaps, cols["ins_len"][:, :LA + 1]
                            .astype(jnp.int32), 0)
        op_at = cols["op_c"][:, 1:].astype(jnp.int32)
        qi = cols["qi_c"][:, 1:].astype(jnp.int32)
        is_match = in_cols & (op_at == DIAG)

        QO = K_INS + 1
        WO = _U_SAT + 1
        qpad = jnp.concatenate(
            [q, jnp.repeat(q[:, -1:], WO, axis=1)], axis=1)
        wpad = jnp.concatenate(
            [qw8, jnp.repeat(qw8[:, -1:], WO, axis=1)], axis=1)
        stack = jnp.stack([qpad[:, o:o + Lq] for o in range(QO)] +
                          [wpad[:, o:o + Lq] for o in range(WO)],
                          axis=-1)
        qs_full = cols["qstart"].astype(jnp.int32)
        qsc_full = jnp.clip(qs_full, 0, Lq - 1)
        s0_full = jnp.maximum(qsc_full - 1, 0)
        Gfull = jnp.take_along_axis(stack, s0_full[:, :, None], axis=1)
        if upto == "gather":
            return jnp.sum(Gfull.astype(jnp.int32))
        G = Gfull[:, :LA + 1]
        qwin = G[..., :QO].astype(jnp.int32)
        wwin = jnp.maximum(G[..., QO:].astype(jnp.float32) - 1.0, 0.0)
        o1 = (qsc_full - s0_full)[:, :LA + 1] == 1

        def sel_q(o):
            return jnp.where(o1, qwin[..., o + 1], qwin[..., o])

        def sel_w(o):
            return jnp.where(o1, wwin[..., o + 1], wwin[..., o])

        Gc = Gfull[:, 1:]
        qi1 = (jnp.clip(qi, 0, Lq - 1) - s0_full[:, 1:]) == 1
        colbase = jnp.where(qi1, Gc[..., 1], Gc[..., 0]).astype(jnp.int32)
        colw = jnp.maximum(
            jnp.where(qi1, Gc[..., QO + 1], Gc[..., QO])
            .astype(jnp.float32) - 1.0, 0.0)
        wq = jnp.where(is_match, colw, w_read[:, None])

        cols_m = in_cols[:, :LA]
        base_idx = jnp.where(is_match[:, :LA], colbase[:, :LA], NBASE)
        col_w = jnp.where(cols_m, jnp.where(is_match[:, :LA], colw[:, :LA],
                                            w_read[:, None]), 0.0)
        col_oh = _onehot(base_idx, NBASE + 1)
        col_w_ch = col_oh * col_w[..., None]
        col_c_ch = col_oh[..., :NBASE] * (is_match[:, :LA] &
                                          cols_m)[..., None]
        if upto == "col":
            return jnp.sum(col_w_ch) + jnp.sum(col_c_ch)

        crossed = (c >= 1) & (c <= ltc - 1) & (ins_len == 0)
        wq_prev = jnp.concatenate([w_read[:, None], wq[:, :LA]], axis=1)
        cross_w = jnp.where(crossed, 0.5 * (wq_prev + wq), 0.0)
        has1 = in_gaps & (ins_len == 1)
        multi = in_gaps & (ins_len >= 2)
        b1 = sel_q(0)
        w1 = sel_w(0)
        ins1_oh = _onehot(jnp.where(has1, b1, NBASE),
                          NBASE + 1)[..., :NBASE]
        ins1_w_ch = ins1_oh * jnp.where(has1, w1, 0.0)[..., None]
        ins1_c_ch = ins1_oh * has1[..., None]
        ins1_stop = jnp.where(has1, w1, 0.0)
        if upto == "ins1":
            return (jnp.sum(col_w_ch) + jnp.sum(col_c_ch) +
                    jnp.sum(cross_w) + jnp.sum(ins1_w_ch) +
                    jnp.sum(ins1_c_ch) + jnp.sum(ins1_stop))

        pk_w, pk_c = [], []
        for k in range(K_INS):
            inrun = multi & (ins_len > k)
            oh = _onehot(jnp.where(inrun, sel_q(k), NBASE),
                         NBASE + 1)[..., :NBASE]
            pk_w.append(oh * jnp.where(inrun, sel_w(k), 0.0)[..., None])
            pk_c.append(oh * inrun[..., None])
        pile_w_ch = jnp.stack(pk_w, axis=2)
        pile_c_ch = jnp.stack(pk_c, axis=2)
        if upto == "pile":
            return (jnp.sum(col_w_ch) + jnp.sum(col_c_ch) +
                    jnp.sum(cross_w) + jnp.sum(ins1_w_ch) +
                    jnp.sum(ins1_c_ch) + jnp.sum(pile_w_ch) +
                    jnp.sum(pile_c_ch))

        run_sum = sum(jnp.where(ins_len > k, sel_w(k), 0.0)
                      for k in range(_U_SAT))
        wmean = jnp.where(multi, run_sum / jnp.maximum(ins_len, 1), 0.0)
        lw_oh = (jnp.clip(ins_len, 0, K_INS)[..., None] ==
                 jnp.arange(2, K_INS + 1)[None, None, :])
        lenw_ch = lw_oh * (wmean * multi)[..., None]
        return (jnp.sum(col_w_ch) + jnp.sum(col_c_ch) +
                jnp.sum(cross_w) + jnp.sum(ins1_w_ch) +
                jnp.sum(ins1_c_ch) + jnp.sum(ins1_stop) +
                jnp.sum(pile_w_ch) + jnp.sum(pile_c_ch) +
                jnp.sum(lenw_ch))

    print(f"backend={jax.default_backend()} B={B} Lq={Lq} LA={LA}")
    prev = 0.0
    for upto in ("gather", "col", "ins1", "pile", "runsum"):
        dt = t(stage, cols, q, qw8, w_read, lt, t_off, upto=upto)
        print(f"{upto:7s}: {dt:.3f}s (+{dt - prev:.3f}s)", flush=True)
        prev = dt


if __name__ == "__main__":
    main()
