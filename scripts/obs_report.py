"""Render a racon_tpu JSONL trace into a per-stage breakdown table.

The manual workflow this automates: PROFILE.md's delta tables were
hand-assembled from RACON_TPU_TIMING stderr lines and stopwatch
arithmetic every perf round. A trace (RACON_TPU_TRACE=<path> or
``--trace``) now carries the same decomposition; this script renders it.

Usage:
    python scripts/obs_report.py TRACE.jsonl            # breakdown table
    python scripts/obs_report.py TRACE.jsonl --validate # schema check
    python scripts/obs_report.py TRACE.jsonl --fleet LEDGER_DIR
                                         # + per-shard lease timeline,
                                         #   steals, per-worker rates

``--validate`` exits non-zero unless the trace is well-formed: a begin
header, JSON-parseable lines, required span keys, non-negative timings,
parents that exist, children contained in their parent's interval, and
well-typed fleet context attrs (``worker_id``/``shard``/``run_fp`` —
one run fingerprint per trace) (the contract documented in
docs/OBSERVABILITY.md; ci.sh gates it).

``--fleet`` aggregates the worker metric shards + events.jsonl under a
ledger directory (racon_tpu/obs/fleet.py) into a ``fleet:`` section;
shards stamped by different run fingerprints are a hard error, never a
silent merge.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional

REQUIRED_SPAN_KEYS = ("id", "parent", "kind", "name", "t0", "dur_s")

#: Per-kind attribute contract (docs/OBSERVABILITY.md): spans of these
#: kinds must carry the listed attrs or downstream aggregation (the
#: transfer table, the pipeline section) silently under-counts.
KIND_REQUIRED_ATTRS = {
    "transfer": ("bytes", "dir"),
    "stage": ("items", "busy_s", "stall_s"),
    "queue": ("peak", "capacity", "items"),
    "retry": ("attempt", "error"),
    "fault": ("index", "action"),
    "checkpoint": ("tid", "bytes"),
    # One query-axis tile of the tiled ultralong overlap forward,
    # emitted under the ovl_tiled_chunk dispatch span (ops/ovl_align.py).
    "tile": ("index", "rows", "W"),
    # One distributed-ledger event (claim/steal/renew/commit/merge,
    # racon_tpu/distributed/): which shard, and which worker did it.
    "dist": ("shard", "worker"),
    # One watchdog deadline breach (resilience/watchdog.py): how long
    # the site was allowed and how long it actually waited.
    "watchdog": ("deadline_s", "waited_s"),
    # One pipeline stall-detector firing (pipeline/stages.py): the
    # silence window that tripped it and how many stages were frozen.
    "stall": ("window_s", "stages"),
    # One ingest-plane event (io/inflate.py inflate/<plan>,
    # obs/metrics.py parse/<reader>): which plan ran and how many
    # decompressed/raw bytes it moved.
    "ingest": ("mode", "bytes"),
    # One decoupled final-round walk dispatch (pipeline/streaming.py
    # walk stage over ops/colwalk.py::dispatch_walk): geometry of the
    # chunk whose traceback it finishes.
    "walk": ("lanes", "windows"),
    # One serve-plane event (racon_tpu/server/, obs/metrics.py): a job
    # lifecycle transition (submitted/resumed/completed/...) or a
    # cross-request batch dispatch; job/tenant are comma-joined lists
    # on batch points so one dispatch names every rider. trace_id /
    # parent_id tie the point into its job's cross-process timeline
    # ("-" / 0 when the caller has no context).
    "serve": ("job", "tenant", "trace_id", "parent_id"),
    # One result-cache event (racon_tpu/cache/ via obs/metrics.py
    # record_cache): which tier (job CAS / window memo) and which
    # outcome (hit/miss/store/evict/verify_fail) — per-window probes
    # arrive batched, one point per chunk.
    "cache": ("tier", "outcome"),
    # One fleet-serve gateway event (racon_tpu/gateway/ via
    # obs/metrics.py record_gate): a routing decision
    # (route_fleet/route_local), a standby adoption, or a finished
    # fleet execution — same trace-context attrs as serve points, so
    # the per-job timeline shows gateway → supervisor → workers.
    "gate": ("job", "tenant", "trace_id", "parent_id"),
}

# Span kinds that carry no required attributes — structural intervals
# whose payload is just name + duration. Together with
# KIND_REQUIRED_ATTRS this is the closed set of legal span kinds: the
# span-schema lint rule (racon_tpu/analysis, SPAN001–SPAN003) checks
# every Tracer emission against the union, both directions.
ATTR_FREE_KINDS = ("chunk", "dispatch", "phase", "pipeline", "round",
                   "run")

# Span intervals are rounded to 1e-6 on write and a parent's clock stops
# fractionally after its children's; allow that much slack in nesting.
EPS = 5e-3


class TraceError(ValueError):
    pass


def load_trace(path: str) -> Dict[str, object]:
    """Parse a trace file into {begin, spans (by id), metrics}."""
    begin = None
    metrics = None
    spans: Dict[int, dict] = {}
    with open(path, "r", encoding="utf-8") as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as exc:
                raise TraceError(f"line {ln}: not valid JSON ({exc})")
            ev = obj.get("ev")
            if ev == "begin":
                begin = obj
            elif ev == "span":
                spans[obj.get("id")] = obj
            elif ev == "metrics":
                metrics = obj
            elif ev is None:
                raise TraceError(f"line {ln}: missing 'ev' key")
    return {"begin": begin, "spans": spans, "metrics": metrics}


def validate(tr: Dict[str, object]) -> List[str]:
    """Return a list of schema violations (empty = valid)."""
    errs: List[str] = []
    if tr["begin"] is None:
        errs.append("no begin header")
    elif tr["begin"].get("schema") != 1:
        errs.append(f"unknown schema {tr['begin'].get('schema')!r}")
    spans: Dict[int, dict] = tr["spans"]
    for sid, s in spans.items():
        for k in REQUIRED_SPAN_KEYS:
            if k not in s:
                errs.append(f"span {sid}: missing key {k!r}")
        if not isinstance(s.get("id"), int):
            errs.append(f"span {sid}: non-integer id")
        for k in ("t0", "dur_s"):
            v = s.get(k)
            if not isinstance(v, (int, float)) or v < 0:
                errs.append(f"span {sid}: {k} must be a non-negative "
                            f"number, got {v!r}")
        for k in KIND_REQUIRED_ATTRS.get(s.get("kind"), ()):
            if k not in s:
                errs.append(f"span {sid}: kind {s.get('kind')!r} missing "
                            f"attr {k!r}")
        # Fleet context attrs (set_context, racon_tpu/obs/trace.py):
        # optional, but when present they must be usable by the fleet
        # aggregation — a mistyped worker_id/shard silently breaks the
        # per-worker grouping downstream.
        if "worker_id" in s and not isinstance(s["worker_id"], str):
            errs.append(f"span {sid}: worker_id must be a string, got "
                        f"{s['worker_id']!r}")
        if "shard" in s and (not isinstance(s["shard"], int) or
                             isinstance(s["shard"], bool)):
            errs.append(f"span {sid}: shard must be an integer, got "
                        f"{s['shard']!r}")
        if "run_fp" in s and not isinstance(s["run_fp"], str):
            errs.append(f"span {sid}: run_fp must be a string, got "
                        f"{s['run_fp']!r}")
        if "trace_id" in s and not isinstance(s["trace_id"], str):
            errs.append(f"span {sid}: trace_id must be a string, got "
                        f"{s['trace_id']!r}")
        if "parent_id" in s and (not isinstance(s["parent_id"], int) or
                                 isinstance(s["parent_id"], bool)):
            errs.append(f"span {sid}: parent_id must be an integer, "
                        f"got {s['parent_id']!r}")
        parent = s.get("parent")
        if parent is not None:
            p = spans.get(parent)
            if p is None:
                errs.append(f"span {sid}: parent {parent} not in trace")
            else:
                if s["t0"] < p["t0"] - EPS:
                    errs.append(f"span {sid}: starts before parent "
                                f"{parent}")
                if s["t0"] + s["dur_s"] > \
                        p["t0"] + p["dur_s"] + EPS:
                    errs.append(f"span {sid}: ends after parent {parent}")
    fps = sorted({s["run_fp"] for s in spans.values()
                  if isinstance(s.get("run_fp"), str)})
    if len(fps) > 1:
        errs.append("mixed run_fp across spans: " +
                    ", ".join(fp[:12] for fp in fps) +
                    " — one trace must belong to one run")
    return errs


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GB"


def _agg(rows: List[dict]):
    total = sum(s["dur_s"] for s in rows)
    return len(rows), total


def render(tr: Dict[str, object], out=None,
           fleet_dir: Optional[str] = None) -> None:
    """Print the per-stage breakdown (the PROFILE.md table, automated)."""
    if out is None:
        # Resolved at call time, not def time: test harnesses (capsys)
        # swap sys.stdout per test, and this module may have been
        # imported under a different one.
        out = sys.stdout
    spans: Dict[int, dict] = tr["spans"]
    if not spans:
        print("(empty trace: no spans)", file=out)
        return
    runs = [s for s in spans.values() if s["kind"] == "run"]
    wall = max((s["t0"] + s["dur_s"] for s in spans.values()))
    run_dur = runs[0]["dur_s"] if runs else wall
    base = runs[0]["name"] if runs else "(no run span)"
    print(f"run: {base}  wall={run_dur:.3f}s  spans={len(spans)}",
          file=out)

    # Per-kind > per-name aggregation, phases in time order.
    by_kind: Dict[str, List[dict]] = {}
    for s in spans.values():
        by_kind.setdefault(s["kind"], []).append(s)

    for kind in ("phase", "pipeline", "stage", "chunk", "round",
                 "dispatch", "tile"):
        rows = by_kind.get(kind)
        if not rows:
            continue
        print(f"\n{kind:>8}  {'count':>5}  {'total_s':>9}  {'%run':>6}"
              f"  name", file=out)
        by_name: Dict[str, List[dict]] = {}
        for s in sorted(rows, key=lambda s: s["t0"]):
            by_name.setdefault(s["name"], []).append(s)
        for name, group in by_name.items():
            n, tot = _agg(group)
            pct = 100.0 * tot / run_dur if run_dur else 0.0
            print(f"{'':>8}  {n:>5}  {tot:>9.3f}  {pct:>5.1f}%  {name}",
                  file=out)

    transfers = by_kind.get("transfer", [])
    if transfers:
        print(f"\ntransfer  {'count':>5}  {'total_s':>9}  {'bytes':>10}"
              f"  {'MB/s':>8}  dir", file=out)
        for d in ("h2d", "d2h"):
            rows = [s for s in transfers if s.get("dir") == d]
            if not rows:
                continue
            n, tot = _agg(rows)
            nb = sum(s.get("bytes", 0) for s in rows)
            bw = nb / tot / 1e6 if tot > 0 else 0.0
            print(f"{'':>8}  {n:>5}  {tot:>9.3f}  {_fmt_bytes(nb):>10}"
                  f"  {bw:>8.3f}  {d}", file=out)

    # Coverage: how much of the run the traced stages account for. The
    # phase spans partition the run's wall clock (chunk/round/dispatch
    # spans nest inside them and would double-count); without phases,
    # fall back to direct children of the run span.
    if runs:
        rows = by_kind.get("phase") or [
            s for s in spans.values() if s.get("parent") == runs[0]["id"]]
        cov = sum(s["dur_s"] for s in rows)
        pct = 100.0 * cov / run_dur if run_dur else 0.0
        print(f"\ncoverage: traced stages sum {cov:.3f}s = {pct:.1f}% "
              f"of run wall", file=out)

    m = tr["metrics"]
    _render_ingest(m, by_kind, out)
    _render_pipeline(m, out)
    _render_resilience(m, by_kind, out)
    _render_dist(m, by_kind, out)
    _render_server(m, by_kind, out, trace_end_unix=_trace_end_unix(tr))
    _render_hist(m, out)
    _render_cache(m, by_kind, out)
    _render_ava(m, out)
    if fleet_dir:
        _render_fleet(fleet_dir, out)
    _render_redo(m, out)
    if m:
        keys = [k for k in sorted(m) if k != "ev"]
        print("\nmetrics:", file=out)
        for k in keys:
            print(f"  {k} = {m[k]}", file=out)


def _render_ingest(m, by_kind, out) -> None:
    """The "ingest:" section: data-plane totals (bytes through the
    inflate pool, parse/wait split, fraction of wall) plus one line per
    ``ingest`` span (which inflate plan / reader each file used). Runs
    that never booked ingest accounting print nothing."""
    m = m or {}
    if not (int(m.get("ingest_records", 0) or 0)
            or int(m.get("ingest_blocks", 0) or 0)):
        return
    bin_ = int(m.get("ingest_bytes_in", 0) or 0)
    bout = int(m.get("ingest_bytes_out", 0) or 0)
    raw = int(m.get("ingest_raw_bytes", 0) or 0)
    print(f"\ningest: records={int(m.get('ingest_records', 0) or 0)}  "
          f"raw={raw / 1e6:.1f}MB  "
          f"inflate={bin_ / 1e6:.1f}→{bout / 1e6:.1f}MB "
          f"({int(m.get('ingest_blocks', 0) or 0)} block(s))", file=out)
    print(f"  inflate={float(m.get('ingest_inflate_s', 0) or 0):.3f}s  "
          f"parse={float(m.get('ingest_parse_s', 0) or 0):.3f}s  "
          f"wait={float(m.get('ingest_wait_s', 0) or 0):.3f}s"
          + (f"  fraction_of_wall="
             f"{float(m['ingest_fraction_of_wall']):.4f}"
             if "ingest_fraction_of_wall" in m else ""), file=out)
    for s in by_kind.get("ingest", []):
        print(f"  {s['name']:<16} {s.get('bytes', 0) / 1e6:>8.1f}MB  "
              f"{s['dur_s']:.3f}s", file=out)


_STAGE_SUFFIXES = ("_busy_s", "_stall_in_s", "_stall_out_s", "_items")
_QUEUE_SUFFIXES = ("_peak", "_put_wait_s", "_get_wait_s")


def _pipe_names(m: dict, prefix: str, suffixes) -> List[str]:
    names = set()
    for k in m:
        if not k.startswith(prefix):
            continue
        for suf in suffixes:
            if k.endswith(suf):
                names.add(k[len(prefix):-len(suf)])
    return sorted(names)


def _render_pipeline(m, out) -> None:
    """The "Pipeline" section: per-stage busy/stall, per-queue gauges,
    and overlap efficiency (device-busy / pipeline wall), all from the
    ``pipe_*`` metrics the streaming executor records."""
    if not m or not int(m.get("pipe_runs", 0) or 0):
        return
    wall = float(m.get("pipe_wall_s", 0.0))
    print(f"\npipeline: runs={int(m['pipe_runs'])}  wall={wall:.3f}s",
          file=out)
    stages = _pipe_names(m, "pipe_stage_", _STAGE_SUFFIXES)
    if stages:
        print(f"{'stage':>8}  {'items':>5}  {'busy_s':>9}  "
              f"{'stall_in':>9}  {'stall_out':>9}", file=out)
        for name in stages:
            g = lambda suf: m.get(f"pipe_stage_{name}{suf}", 0)  # noqa: E731
            print(f"{name:>8}  {int(g('_items')):>5}  "
                  f"{float(g('_busy_s')):>9.3f}  "
                  f"{float(g('_stall_in_s')):>9.3f}  "
                  f"{float(g('_stall_out_s')):>9.3f}", file=out)
    queues = _pipe_names(m, "pipe_queue_", _QUEUE_SUFFIXES)
    if queues:
        print(f"{'queue':>8}  {'peak':>5}  {'put_wait':>9}  "
              f"{'get_wait':>9}", file=out)
        for name in queues:
            g = lambda suf: m.get(f"pipe_queue_{name}{suf}", 0)  # noqa: E731
            print(f"{name:>8}  {int(g('_peak')):>5}  "
                  f"{float(g('_put_wait_s')):>9.3f}  "
                  f"{float(g('_get_wait_s')):>9.3f}", file=out)
    eff = m.get("pipe_overlap_efficiency")
    if eff is None and wall > 0:
        eff = float(m.get("pipe_stage_compute_busy_s", 0.0)) / wall
    if eff is not None:
        print(f"overlap efficiency: {float(eff):.3f} "
              "(compute busy / pipeline wall)", file=out)
    stalls = int(m.get("pipe_stall_events", 0) or 0)
    if stalls:
        print(f"stalls: {stalls} detector firing(s) — frozen stages "
              "were dumped to stderr and re-polished on the host",
              file=out)


def _render_resilience(m, by_kind, out) -> None:
    """The "Resilience" section: retry/fault/degradation/checkpoint
    counters plus the per-site retry spans, all from the ``res_*``
    metrics and ``retry``/``fault``/``checkpoint`` spans the resilience
    package records. Quiet runs (no res_* activity) print nothing."""
    m = m or {}
    res = {k: v for k, v in m.items() if k.startswith("res_")}
    spans = (by_kind.get("retry", []) + by_kind.get("fault", []) +
             by_kind.get("checkpoint", []))
    if not res and not spans:
        return
    print(f"\nresilience: retries={int(m.get('res_retry_total', 0))}  "
          f"exhausted={int(m.get('res_retry_exhausted', 0))}  "
          f"faults={int(m.get('res_fault_injected_total', 0))}  "
          f"degraded_windows={int(m.get('res_degraded_windows', 0))}",
          file=out)
    sites = sorted(k[len("res_retry_site_"):] for k in res
                   if k.startswith("res_retry_site_"))
    if sites:
        print(f"{'site':>24}  {'retries':>7}  {'faults':>6}", file=out)
        for site in sites:
            print(f"{site:>24}  "
                  f"{int(res.get(f'res_retry_site_{site}', 0)):>7}  "
                  f"{int(res.get(f'res_fault_site_{site}', 0)):>6}",
                  file=out)
    breaches = int(m.get("res_watchdog_breach_total", 0))
    if breaches:
        wsites = sorted(k[len("res_watchdog_site_"):] for k in res
                        if k.startswith("res_watchdog_site_"))
        per = "  ".join(
            f"{s}={int(res[f'res_watchdog_site_{s}'])}" for s in wsites)
        print(f"watchdog: breaches={breaches}  "
              f"terminal={int(m.get('res_watchdog_terminal_total', 0))}"
              f"  stalls={int(m.get('pipe_stall_events', 0))}",
              file=out)
        if per:
            print(f"  breach sites: {per}", file=out)
    backoff = float(m.get("res_retry_backoff_s", 0.0))
    if backoff:
        print(f"backoff slept: {backoff:.3f}s", file=out)
    commits = int(m.get("res_ckpt_commits", 0))
    skips = int(m.get("res_ckpt_skips", 0))
    if commits or skips or int(m.get("res_ckpt_resumes", 0)):
        print(f"checkpoint: commits={commits}  resumed_skips={skips}  "
              f"bytes={_fmt_bytes(float(m.get('res_ckpt_bytes', 0)))}",
              file=out)


def _render_dist(m, by_kind, out) -> None:
    """The "Distributed" section: fleet shape, claim/steal/lease
    counters, and per-worker event counts, from the ``dist_*`` metrics
    and ``dist`` spans the work ledger records (docs/DISTRIBUTED.md).
    Single-process runs (no dist_* activity) print nothing."""
    m = m or {}
    dist = {k: v for k, v in m.items() if k.startswith("dist_")}
    spans = by_kind.get("dist", [])
    if not dist and not spans:
        return
    print(f"\ndistributed: workers={int(m.get('dist_workers', 0))}  "
          f"shards={int(m.get('dist_shards', 0))}  "
          f"targets={int(m.get('dist_n_targets', 0))}", file=out)
    print(f"  claims={int(m.get('dist_claims', 0))}  "
          f"stolen={int(m.get('dist_shards_stolen', 0))}  "
          f"lease_renewals={int(m.get('dist_lease_renewals', 0))}  "
          f"leases_lost={int(m.get('dist_leases_lost', 0))}", file=out)
    print(f"  contigs: polished={int(m.get('dist_contigs_polished', 0))}"
          f"  resumed={int(m.get('dist_contigs_resumed', 0))}  "
          f"repolished={int(m.get('dist_contigs_repolished', 0))}",
          file=out)
    rels = int(m.get("dist_releases", 0))
    evics = int(m.get("dist_self_evictions", 0))
    if rels or evics:
        print(f"  releases={rels}  self_evictions={evics}  "
              "(fail-slow: lease given back before the term expired)",
              file=out)
    lat = float(m.get("dist_steal_latency_s", 0.0))
    rec = float(m.get("dist_recovery_wall_s", 0.0))
    if lat or rec:
        print(f"  steal latency {lat:.3f}s  recovery wall {rec:.3f}s",
              file=out)
    if spans:
        by_worker: Dict[str, int] = {}
        for s in spans:
            by_worker[str(s.get("worker"))] = \
                by_worker.get(str(s.get("worker")), 0) + 1
        workers = ", ".join(f"{w}: {n}" for w, n in
                            sorted(by_worker.items()))
        print(f"  events by worker: {workers}", file=out)


def _trace_end_unix(tr) -> Optional[float]:
    """Wall-clock instant of the last span end: the begin header's
    unix_time plus the latest relative span end. None when the trace
    has no absolute anchor (old traces, empty traces)."""
    begin = tr.get("begin") or {}
    t0 = begin.get("unix_time")
    spans = tr.get("spans") or {}
    if not isinstance(t0, (int, float)) or not spans:
        return None
    return float(t0) + max(
        (s["t0"] + s["dur_s"] for s in spans.values()
         if isinstance(s.get("t0"), (int, float)) and
         isinstance(s.get("dur_s"), (int, float))), default=0.0)


def _render_server(m, by_kind, out, trace_end_unix=None) -> None:
    """The "server:" section: daemon job lifecycle totals, the
    cross-request batcher's packing efficiency, and per-tenant event
    counts, from the ``serve_*`` metrics and ``serve`` points the
    server plane records (docs/SERVER.md). Runs that never served
    (no serve_* activity) print nothing."""
    m = m or {}
    serve = {k: v for k, v in m.items() if k.startswith("serve_")}
    spans = by_kind.get("serve", [])
    if not serve and not spans:
        return
    print(f"\nserver: submitted={int(m.get('serve_jobs_submitted', 0))}"
          f"  completed={int(m.get('serve_jobs_completed', 0))}  "
          f"failed={int(m.get('serve_jobs_failed', 0))}  "
          f"cancelled={int(m.get('serve_jobs_cancelled', 0))}  "
          f"resumed={int(m.get('serve_jobs_resumed', 0))}", file=out)
    batches = int(m.get("serve_batches", 0) or 0)
    if batches:
        print(f"  batches={batches}  "
              f"windows={int(m.get('serve_batch_windows', 0))}  "
              f"occupancy={float(m.get('serve_batch_occupancy', 0)):.4f}"
              f"  queue_peak={int(m.get('serve_queue_depth_peak', 0))}  "
              f"tenant_wait={float(m.get('serve_tenant_wait_s', 0)):.3f}"
              f"s", file=out)
    rate = m.get("serve_jobs_per_min")
    if rate is not None:
        # Rate/occupancy gauges are only as fresh as their last stamp:
        # a snapshot much older than the trace's end (> the fleet
        # staleness budget, 5x the flush cadence) is flagged so nobody
        # reads a dead daemon's last throughput as current.
        stale = ""
        stamp = m.get("serve_rate_wall_s")
        if isinstance(stamp, (int, float)) and trace_end_unix:
            import os
            sys.path.insert(0, os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            from racon_tpu.obs.export import SUPERVISOR_STALE_FACTOR
            from racon_tpu.obs.fleet import DEFAULT_FLUSH_S
            age = float(trace_end_unix) - float(stamp)
            if age > SUPERVISOR_STALE_FACTOR * DEFAULT_FLUSH_S:
                stale = (f"  [STALE: gauges last updated {age:.1f}s "
                         f"before trace end]")
        print(f"  throughput: {float(rate):.4f} job(s)/min{stale}",
              file=out)
    if spans:
        # Batch points carry comma-joined tenant lists; split them so a
        # tenant's count includes every dispatch it rode in.
        by_tenant: Dict[str, int] = {}
        for s in spans:
            for tenant in str(s.get("tenant", "?")).split(","):
                by_tenant[tenant] = by_tenant.get(tenant, 0) + 1
        tenants = ", ".join(f"{t}: {n}" for t, n in
                            sorted(by_tenant.items()))
        print(f"  events by tenant: {tenants}", file=out)


def _render_hist(m, out) -> None:
    """The "latency:" section: p50/p95/p99 for every histogram family
    in the metrics snapshot, interpolated from the fixed log-spaced
    buckets declared in obs/metrics.HIST_BUCKETS. Snapshots with no
    recorded histograms print nothing."""
    m = m or {}
    hists = {k: v for k, v in sorted(m.items())
             if isinstance(v, dict) and "buckets" in v
             and int(v.get("count", 0) or 0)}
    if not hists:
        return
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from racon_tpu.obs.metrics import HIST_BUCKETS, hist_quantile
    print(f"\nlatency:  {'count':>6}  {'p50':>9}  {'p95':>9}  "
          f"{'p99':>9}  family", file=out)
    for name, h in hists.items():
        bounds = HIST_BUCKETS.get(name)
        if bounds is None:
            continue
        p50, p95, p99 = (hist_quantile(h, q, bounds)
                         for q in (0.50, 0.95, 0.99))
        print(f"{'':>8}  {int(h['count']):>6}  {p50:>9.4f}  "
              f"{p95:>9.4f}  {p99:>9.4f}  {name}", file=out)


def _render_job(root: str, trace_id: str, out=None) -> int:
    """The ``--job TRACE_ID`` mode: stitch one job's causal timeline
    out of every per-process trace under ``<root>/obs`` (the fleet
    merge step, obs/fleet.assemble_job_timeline), then render any
    flight-recorder dumps beside it and the aggregated latency
    histograms. Returns an exit code; refusals (no such trace, mixed
    runs) surface as errors, never empty reports."""
    import os
    if out is None:
        out = sys.stdout
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from racon_tpu.obs import flightrec
    from racon_tpu.obs.fleet import (FleetObsError, aggregate,
                                     assemble_job_timeline, obs_dir_for)
    try:
        tl = assemble_job_timeline(root, trace_id)
    except (FleetObsError, OSError) as exc:
        print(f"[obs_report] error: {exc}", file=sys.stderr)
        return 1
    print(f"job {tl['trace_id']}: {tl['n_spans']} span(s) across "
          f"{tl['n_processes']} process(es)", file=out)
    for src in sorted(tl["sources"]):
        print(f"  {src}: {tl['sources'][src]} span(s)", file=out)
    t_base = tl["spans"][0]["t_abs"] if tl["spans"] else 0.0
    print(f"\n{'t_rel_s':>9}  {'dur_s':>8}  {'source':<22}  span",
          file=out)
    for s in tl["spans"]:
        name = f"{s['kind']}/{s['name']}"
        extra = ""
        if s.get("kind") == "serve":
            extra = f"  job={s.get('job')} tenant={s.get('tenant')}"
        elif s.get("kind") == "gate":
            extra = f"  job={s.get('job')} tenant={s.get('tenant')}"
            if s.get("decision"):
                extra += f" decision={s.get('decision')}"
            if s.get("reason"):
                extra += f" reason={s.get('reason')}"
        elif "worker_id" in s:
            extra = f"  worker={s['worker_id']}"
        print(f"{s['t_abs'] - t_base:>9.3f}  {s['dur_s']:>8.3f}  "
              f"{s['src']:<22}  {name}{extra}", file=out)
    flights = flightrec.list_flights(obs_dir_for(root))
    for path in flights:
        try:
            fl = flightrec.load_flight(path)
        except ValueError as exc:
            print(f"\nflight {os.path.basename(path)}: unreadable "
                  f"({exc})", file=out)
            continue
        h = fl["header"]
        tear = "" if fl["clean"] else "  [TORN: clean prefix shown]"
        print(f"\nflight {os.path.basename(path)}: pid={h['pid']}  "
              f"reason={h['reason']}  {len(fl['events'])} event(s)"
              f"{tear}", file=out)
        for e in fl["events"][-8:]:
            print(f"  {json.dumps(e, sort_keys=True)}", file=out)
    try:
        _render_hist(aggregate(root).get("fleet", {}), out)
    except (FleetObsError, OSError):
        pass  # no metric shards next to the traces — timeline stands
    return 0


def _render_cache(m, by_kind, out) -> None:
    """The "cache:" section: result-store totals (hits/misses/stores/
    evictions/verify failures), the derived hit ratio and stored
    bytes, and per-tier event counts, from the ``cache_*`` metrics and
    ``cache`` points the content-addressed result cache records
    (docs/CACHE.md). Runs that never probed the cache print nothing."""
    m = m or {}
    cache = {k: v for k, v in m.items() if k.startswith("cache_")}
    spans = by_kind.get("cache", [])
    if not cache and not spans:
        return
    print(f"\ncache: hits={int(m.get('cache_hits_total', 0))}  "
          f"misses={int(m.get('cache_misses_total', 0))}  "
          f"stores={int(m.get('cache_stores_total', 0))}  "
          f"evictions={int(m.get('cache_evictions_total', 0))}  "
          f"verify_fail={int(m.get('cache_verify_fail_total', 0))}",
          file=out)
    ratio = m.get("cache_hit_ratio")
    if ratio is not None:
        print(f"  hit_ratio={float(ratio):.4f}  "
              f"bytes={int(m.get('cache_bytes', 0))}", file=out)
    if spans:
        by_tier: Dict[str, int] = {}
        for s in spans:
            key = f"{s.get('tier', '?')}/{s.get('outcome', '?')}"
            by_tier[key] = by_tier.get(key, 0) + int(s.get("n", 1))
        tiers = ", ".join(f"{t}: {n}" for t, n in
                          sorted(by_tier.items()))
        print(f"  events by tier: {tiers}", file=out)


def _render_ava(m, out) -> None:
    """The "ava:" section: the shape-bucket plan (targets, buckets vs
    the compile budget, quantum, padding overhead) and — when the ava
    bench ran — throughput, peak RSS, and the v2 manifest's bytes per
    committed target (docs/AVA.md). kC runs record no ``ava_*`` keys
    and print nothing."""
    m = m or {}
    if not any(k.startswith("ava_") for k in m):
        return
    print(f"\nava: targets={int(m.get('ava_targets', 0))}  "
          f"buckets={int(m.get('ava_buckets', 0))}"
          f"/{int(m.get('ava_compile_budget', 0))}  "
          f"quantum={int(m.get('ava_quantum', 0))}  "
          f"pad_frac={float(m.get('ava_pad_frac', 0) or 0):.4f}",
          file=out)
    if m.get("ava_reads_per_sec") is not None:
        print(f"  reads/s={float(m.get('ava_reads_per_sec', 0)):.1f}  "
              f"peak_rss={float(m.get('ava_peak_rss_mb', 0)):.1f}MB  "
              f"manifest_bytes/target="
              f"{float(m.get('ava_manifest_bytes_per_target', 0)):.2f}",
              file=out)


def _render_fleet(fleet_dir: str, out) -> None:
    """The "Fleet" section (``--fleet LEDGER_DIR``): the cross-worker
    view from the worker metric shards + events.jsonl — per-worker
    rates (stragglers flagged), merged counters, the autoscaler's
    supervisor heartbeat when one attached, and the per-shard lease
    timeline (claim/renew/steal/release/complete plus
    ``split->child`` markers, renew runs compressed; split-child
    lanes lead with their ancestry chain). Mixed-run
    shard directories raise FleetObsError in the aggregator; main()
    turns that into a clear exit-1 error."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from racon_tpu.obs.fleet import aggregate
    model = aggregate(fleet_dir)
    elastic = ""
    if model.get("splits") or model.get("spawns") or \
            model.get("retires"):
        elastic = (f"  splits={model.get('splits', 0)}  "
                   f"spawns={model.get('spawns', 0)}  "
                   f"retires={model.get('retires', 0)}")
    print(f"\nfleet: workers={model['n_workers']}  "
          f"steals={model['steals']}{elastic}  "
          f"run_fp={model['run_fp'][:12]}", file=out)
    sup = model.get("supervisor")
    if sup:
        done = "done" if sup.get("done") else "running"
        print(f"  supervisor: target={sup.get('target_workers', '?')}  "
              f"live={sup.get('live_workers', '?')}  "
              f"spawned={sup.get('spawned_total', '?')}  "
              f"retired={sup.get('workers_retired', 0)}  "
              f"evicted={sup.get('workers_evicted', 0)}  "
              f"[{done}]", file=out)
    print(f"  {'worker':>16}  {'windows/s':>9}  {'wall_s':>8}  "
          f"{'final':>5}  {'snapshots':>9}", file=out)
    for wid in sorted(model["workers"]):
        w = model["workers"][wid]
        seq = w.get("seq")
        flag = "  STRAGGLER" if w.get("straggler") else ""
        print(f"  {wid:>16}  {w['windows_per_sec']:>9.1f}  "
              f"{w['wall_s']:>8.2f}  "
              f"{'yes' if w['final'] else 'no':>5}  "
              f"{(seq + 1 if isinstance(seq, int) else '?'):>9}"
              f"{flag}",
              file=out)
        phases = w.get("phase_seconds", {})
        if phases:
            top = sorted(phases.items(), key=lambda kv: -kv[1])[:3]
            line = "  ".join(f"{name}={secs:.2f}s"
                             for name, secs in top)
            print(f"  {'':>16}  phases: {line}", file=out)
    stragglers = model.get("stragglers") or []
    if stragglers:
        print("  stragglers: " + ", ".join(stragglers) +
              "  (windows/s below the fleet-median fraction, "
              "obs/fleet.py)", file=out)
    timeline = model.get("timeline", {})
    lineage = model.get("lineage") or {}
    if timeline:
        print("  lease timeline:", file=out)
        t_base = min((e["t"] for lane in timeline.values()
                      for e in lane if isinstance(e.get("t"),
                                                  (int, float))),
                     default=0.0)
        for name in sorted(timeline):
            parts = []
            for e in timeline[name]:
                t = e.get("t")
                at = (f"@{t - t_base:.1f}s"
                      if isinstance(t, (int, float)) else "")
                if e["ev"] == "renew":
                    parts.append(f"renew x{e['n']} [{e['worker']}]")
                elif e["ev"] == "steal":
                    parts.append(
                        f"steal [{e['worker']}<-{e.get('victim')}] "
                        f"{at}")
                elif e["ev"] == "split":
                    parts.append(f"split->{e.get('child')} "
                                 f"[{e['worker']}] {at}")
                else:
                    parts.append(f"{e['ev']} [{e['worker']}] {at}")
            # A split child's lane leads with its full ancestry so the
            # reader can trace every donated range back to its seed
            # shard without cross-referencing lanes.
            chain, seen = [], set()
            parent = lineage.get(name)
            while parent is not None and parent not in seen:
                seen.add(parent)
                chain.append(parent)
                parent = lineage.get(parent)
            anc = (" (< " + " < ".join(chain) + ")") if chain else ""
            print(f"    {name}{anc}: " + " -> ".join(parts), file=out)


def _render_redo(m, out) -> None:
    """The "Redo" section: where flagged windows were resolved (the
    on-device wide-band pass vs the host fallback) and the walk's
    dependent-gather chain length, from the ``redo_*`` counters and the
    ``walk_chain_len`` gauge (docs/KERNELS.md "Wide-band device redo").
    Runs with no flagged windows print only the chain gauge."""
    m = m or {}
    passes = int(m.get("redo_passes", 0) or 0)
    chain = m.get("walk_chain_len")
    if not passes and chain is None:
        return
    if passes:
        dev = int(m.get("redo_device_windows", 0))
        host = int(m.get("redo_host_windows", 0))
        tail = "" if host else "  (host untouched mid-polish)"
        print(f"\nredo: passes={passes}  device_windows={dev}  "
              f"host_windows={host}{tail}", file=out)
    if chain is not None:
        lead = "" if passes else "\n"
        print(f"{lead}walk chain: {int(chain)} dependent gather(s) "
              "per column scan", file=out)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    do_validate = "--validate" in argv
    argv = [a for a in argv if a != "--validate"]
    fleet_dir = None
    if "--fleet" in argv:
        i = argv.index("--fleet")
        try:
            fleet_dir = argv[i + 1]
        except IndexError:
            print("[obs_report] error: --fleet needs a ledger/obs "
                  "directory", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    job_trace = None
    if "--job" in argv:
        i = argv.index("--job")
        try:
            job_trace = argv[i + 1]
        except IndexError:
            print("[obs_report] error: --job needs a trace id",
                  file=sys.stderr)
            return 2
        del argv[i:i + 2]
    paths = [a for a in argv if not a.startswith("--")]
    if len(paths) != 1 or len(argv) != len(paths):
        print("usage: obs_report.py TRACE.jsonl [--validate] "
              "[--fleet LEDGER_DIR] | obs_report.py ROOT_DIR "
              "--job TRACE_ID", file=sys.stderr)
        return 2
    if job_trace is not None:
        # --job mode: the positional is a run/ledger root holding an
        # obs/ directory of per-process traces, not a single trace.
        return _render_job(paths[0], job_trace)
    try:
        tr = load_trace(paths[0])
    except (OSError, TraceError) as exc:
        print(f"[obs_report] error: {exc}", file=sys.stderr)
        return 1
    if do_validate:
        errs = validate(tr)
        if errs:
            for e in errs:
                print(f"[obs_report] invalid: {e}", file=sys.stderr)
            return 1
        print(f"[obs_report] valid: {len(tr['spans'])} spans, "
              f"schema {tr['begin'].get('schema')}")
        return 0
    try:
        render(tr, fleet_dir=fleet_dir)
    except Exception as exc:
        # FleetObsError (mixed run_fp shards, empty obs dir) and
        # unreadable ledgers surface as a clear error, never a silent
        # partial report.
        from racon_tpu.obs.fleet import FleetObsError
        if not isinstance(exc, (FleetObsError, OSError)):
            raise
        print(f"[obs_report] error: {exc}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
