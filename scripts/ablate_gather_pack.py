"""Micro-bench: does packing gathered u8 channels into i32 words cut
TPU gather cost proportionally to element count?

Variants at B=6144, Lq=656, P=770:
  a) [B, Lq, 26] u8 axis-1 gather (current extract_votes_cols shape)
  b) [B, Lq, 7] i32 packed words, same index
  c) [B, Lq, 3] i32 (the K_INS=4 / U_SAT=7 target shape)
  d) 3 separate [B, Lq] i32 2D gathers
  e) [B, Lq] i32 single 2D gather (baseline per-call cost)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def t(fn, *args, reps=10):
    """Chained dispatch, single trailing sync (PROFILE.md timing rule)."""
    np.asarray(fn(*args))                      # compile + settle
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    B, Lq, P = 6144, 656, 770
    rng = np.random.default_rng(0)
    s8 = jnp.asarray(rng.integers(0, 256, (B, Lq, 26)).astype(np.uint8))
    s32_7 = jnp.asarray(rng.integers(0, 2**20, (B, Lq, 7)).astype(np.int32))
    s32_3 = jnp.asarray(rng.integers(0, 2**20, (B, Lq, 3)).astype(np.int32))
    s32_1 = jnp.asarray(rng.integers(0, 2**20, (B, Lq)).astype(np.int32))
    idx = jnp.asarray(
        np.clip(np.tile(np.arange(P), (B, 1)) - 10, 0, Lq - 1)
        .astype(np.int32))

    @jax.jit
    def g_a(s, idx):
        return jnp.sum(jnp.take_along_axis(
            s, idx[:, :, None], axis=1).astype(jnp.int32))

    @jax.jit
    def g_b(s, idx):
        return jnp.sum(jnp.take_along_axis(s, idx[:, :, None], axis=1))

    @jax.jit
    def g_d(s, idx):
        return sum(jnp.sum(jnp.take_along_axis(s[..., k], idx, axis=1))
                   for k in range(3))

    @jax.jit
    def g_e(s, idx):
        return jnp.sum(jnp.take_along_axis(s, idx, axis=1))

    @jax.jit
    def g_noop(s, idx):
        return jnp.sum(idx)

    print(f"backend={jax.default_backend()}")
    print(f"noop    : {t(g_noop, s32_1, idx):.3f}s")
    print(f"a u8x26 : {t(g_a, s8, idx):.3f}s")
    print(f"b i32x7 : {t(g_b, s32_7, idx):.3f}s")
    print(f"c i32x3 : {t(g_b, s32_3, idx):.3f}s")
    print(f"d 3x2D  : {t(g_d, s32_3, idx):.3f}s")
    print(f"e 1x2D  : {t(g_e, s32_1, idx):.3f}s")


if __name__ == "__main__":
    main()
