"""Ablation: traceback dependency-chain length vs walk depth k.

The column walk's cost on TPU is its serialized per-column HBM gather
chain (PROFILE.md round 5's top remaining compute cost). The k-step
walk consumes the band kernels' packed predecessor planes to undo k
anchor positions per dependent gather, dividing the chain:

  k=1 : LA + 2 columns -> 1 dependent gather per column (reference)
  k=2 : nxt plane       -> 1 dependent gather per 2 columns
  k=4 : nxt + nxt2 u16  -> 1 dependent gather per 4 columns

Runs the band forward (XLA twin, any backend) once per (Lq, k), then
times col_walk at each depth and checks bit-identity of the
unflagged-lane channels against the k=1 reference — the ratio isolates
lever 1 of round 6 (and round 8's k=4 extension) from kernel cost.

A second section ablates the decoupled walk dispatch (ISSUE 14): the
same synthetic stream run twice through the pipeline executor, once
with RACON_TPU_WALK_ASYNC=1 (chunk N's final-round walk dispatched as
its own executable, overlapping chunk N+1's forward rounds) and once
fused, printing wall seconds, walk seconds, the measured
walk_hidden_fraction, and bit-identity of the consensi.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

KS = (1, 2, 4)

BASES = np.frombuffer(b"ACGT", np.uint8)

_STREAM_ENVS = ("RACON_TPU_SCHED", "RACON_TPU_PIPELINE",
                "RACON_TPU_WALK_ASYNC")


def t(fn, *args, reps=10):
    """Chained dispatch, single trailing sync (PROFILE.md timing rule)."""
    out = fn(*args)
    np.asarray(out["sat"])                     # compile + settle
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    np.asarray(out["sat"])
    return (time.perf_counter() - t0) / reps


def _inputs(rng, B, Lq, W):
    """Vectorized random band jobs (no per-cell python loops)."""
    import jax.numpy as jnp
    from racon_tpu.ops.pallas.band_kernel import band_geometry

    lq = rng.integers(Lq // 2, Lq + 1, B).astype(np.int32)
    lt = (lq + rng.integers(-Lq // 16, Lq // 16 + 1, B)).clip(8)
    lt = lt.astype(np.int32)
    qT = rng.integers(0, 4, (Lq, B)).astype(np.uint8)
    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    ts = rng.integers(0, 4, (B, int(lt.max()))).astype(np.uint8)
    j = klo_h[:, None] + np.arange(W + Lq)[None, :]
    tband = np.where((j >= 0) & (j < lt[:, None]),
                     np.take_along_axis(ts, j.clip(0, ts.shape[1] - 1),
                                        axis=1),
                     np.uint8(7)).astype(np.uint8)
    return tband, qT, klo, lq, lt


def main():
    import functools

    import jax
    import jax.numpy as jnp

    from racon_tpu.ops.colwalk import chain_len, col_walk
    from racon_tpu.ops.pallas.band_kernel import fw_dirs_band_xla

    B, W = 1024, 128
    rng = np.random.default_rng(0)
    print(f"backend={jax.default_backend()}  B={B} W={W}")
    hdr = f"{'Lq':>6}"
    for k in KS:
        hdr += f" {'chain_k%d' % k:>9} {'k%d_ms' % k:>8}"
    hdr += f" {'k4/k1':>7} {'bitid':>6}"
    print(hdr)
    for Lq in (128, 256, 512, 1024):
        tband, qT, klo, lq, lt = _inputs(rng, B, Lq, W)
        fwd = (jnp.asarray(tband), jnp.asarray(qT), klo, jnp.asarray(lq))
        kw = dict(match=5, mismatch=-4, gap=-8, W=W)
        dirs, nxt, _ = fw_dirs_band_xla(*fwd, **kw)
        _, _, nxt2, _ = fw_dirs_band_xla(*fwd, nxt_k=4, **kw)
        LA = tband.shape[1] + 16
        t_off = jnp.zeros(B, jnp.int32)
        args = (dirs, jnp.asarray(lq), jnp.asarray(lt), klo, t_off)
        planes = {1: dict(), 2: dict(nxt=nxt),
                  4: dict(nxt=nxt, nxt2=nxt2)}
        times, outs = {}, {}
        for k in KS:
            fn = jax.jit(functools.partial(col_walk, LA=LA,
                                           layout="band", **planes[k]))
            times[k] = t(fn, *args)
            outs[k] = fn(*args)
        ref = outs[1]
        ok = ~np.asarray(ref["sat"])
        bitid = all(
            np.array_equal(np.asarray(ref["sat"]),
                           np.asarray(outs[k]["sat"])) and
            all(np.array_equal(np.asarray(ref[c])[ok],
                               np.asarray(outs[k][c])[ok])
                for c in ("ins_len", "qstart", "op_c", "qi_c"))
            for k in KS[1:])
        row = f"{Lq:>6}"
        for k in KS:
            row += f" {chain_len(LA, k):>9} {times[k] * 1e3:>8.2f}"
        row += (f" {times[1] / times[4]:>6.2f}x"
                f" {'PASS' if bitid else 'FAIL':>6}")
        print(row)
        if not bitid:
            sys.exit(1)
    decoupled_mode()


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.04:
            continue
        out.append(int(BASES[rng.integers(0, 4)]) if r < 0.08 else int(b))
        if r > 0.96:
            out.append(int(BASES[rng.integers(0, 4)]))
    return bytes(out)


def _build_windows(n, seed=0, coverage=5, wlen=80):
    from racon_tpu.models.window import Window, WindowType
    rng = np.random.default_rng(seed)
    ws = []
    for i in range(n):
        truth = BASES[rng.integers(0, 4, wlen)]
        backbone = _mutate(rng, truth)
        qual = bytes(rng.integers(43, 63, len(backbone), dtype=np.uint8))
        w = Window(i, i % 7, WindowType.TGS, backbone, qual)
        for _ in range(coverage):
            lay = _mutate(rng, truth)
            lq = bytes(rng.integers(43, 63, len(lay), dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        ws.append(w)
    return ws


def _stream_once(seed):
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.ops.poa import PoaEngine
    from racon_tpu.pipeline.streaming import stream_consensus

    obs_metrics.reset()
    ws = _build_windows(32, seed=seed)
    t0 = time.perf_counter()
    list(stream_consensus(PoaEngine(backend="jax"), ws, chunk=8, depth=2))
    wall = time.perf_counter() - t0
    snap = obs_metrics.registry().snapshot()
    return [w.consensus for w in ws], snap, wall


def decoupled_mode():
    """Decoupled-vs-fused walk dispatch through the pipeline executor."""
    saved = {k: os.environ.get(k) for k in _STREAM_ENVS}
    os.environ["RACON_TPU_SCHED"] = "0"
    os.environ["RACON_TPU_PIPELINE"] = "1"
    try:
        print("\ndecoupled walk dispatch (streamed, 4 chunks, depth=2)")
        print(f"{'mode':>10} {'wall_s':>8} {'walk_s':>8} "
              f"{'hidden':>7} {'dispatches':>10}")
        os.environ["RACON_TPU_WALK_ASYNC"] = "1"
        dec, dsnap, dwall = _stream_once(33)
        print(f"{'decoupled':>10} {dwall:>8.3f} "
              f"{dsnap.get('walk_seconds', 0.0):>8.3f} "
              f"{dsnap.get('walk_hidden_fraction', 0.0):>7.3f} "
              f"{dsnap.get('walk_dispatches', 0):>10}")
        os.environ["RACON_TPU_WALK_ASYNC"] = "0"
        fus, fsnap, fwall = _stream_once(33)
        print(f"{'fused':>10} {fwall:>8.3f} {'-':>8} {'-':>7} "
              f"{fsnap.get('walk_dispatches', 0):>10}")
        bitid = dec == fus
        print(f"{'bitid':>10} {'PASS' if bitid else 'FAIL':>8}")
        if not bitid or dsnap.get("walk_dispatches", 0) < 1:
            sys.exit(1)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    main()
