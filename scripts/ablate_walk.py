"""Ablation: traceback dependency-chain length, single vs dual walk.

The column walk's cost on TPU is its serialized per-column HBM gather
chain (PROFILE.md round 5's top remaining compute cost). The dual-
column walk consumes the band kernels' nxt plane to undo TWO anchor
positions per dependent gather, halving the chain:

  single : LA + 2 columns -> 1 dependent gather per column
  dual   : LA + 2 columns -> 1 dependent gather per 2 columns

Runs the band forward (XLA twin, any backend) once per Lq, then times
col_walk with and without the nxt plane and checks bit-identity of the
unflagged-lane channels — the ratio isolates lever 1 of round 6 from
kernel cost.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def t(fn, *args, reps=10):
    """Chained dispatch, single trailing sync (PROFILE.md timing rule)."""
    out = fn(*args)
    np.asarray(out["sat"])                     # compile + settle
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    np.asarray(out["sat"])
    return (time.perf_counter() - t0) / reps


def _inputs(rng, B, Lq, W):
    """Vectorized random band jobs (no per-cell python loops)."""
    import jax.numpy as jnp
    from racon_tpu.ops.pallas.band_kernel import band_geometry

    lq = rng.integers(Lq // 2, Lq + 1, B).astype(np.int32)
    lt = (lq + rng.integers(-Lq // 16, Lq // 16 + 1, B)).clip(8)
    lt = lt.astype(np.int32)
    qT = rng.integers(0, 4, (Lq, B)).astype(np.uint8)
    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    ts = rng.integers(0, 4, (B, int(lt.max()))).astype(np.uint8)
    j = klo_h[:, None] + np.arange(W + Lq)[None, :]
    tband = np.where((j >= 0) & (j < lt[:, None]),
                     np.take_along_axis(ts, j.clip(0, ts.shape[1] - 1),
                                        axis=1),
                     np.uint8(7)).astype(np.uint8)
    return tband, qT, klo, lq, lt


def main():
    import functools

    import jax
    import jax.numpy as jnp

    from racon_tpu.ops.colwalk import col_walk
    from racon_tpu.ops.pallas.band_kernel import fw_dirs_band_xla

    B, W = 1024, 128
    rng = np.random.default_rng(0)
    print(f"backend={jax.default_backend()}  B={B} W={W}")
    print(f"{'Lq':>6} {'chain_s':>8} {'chain_d':>8} "
          f"{'single_ms':>10} {'dual_ms':>8} {'speedup':>8} {'bitid':>6}")
    for Lq in (128, 256, 512, 1024):
        tband, qT, klo, lq, lt = _inputs(rng, B, Lq, W)
        dirs, nxt, _ = fw_dirs_band_xla(
            jnp.asarray(tband), jnp.asarray(qT), klo, jnp.asarray(lq),
            match=5, mismatch=-4, gap=-8, W=W)
        LA = tband.shape[1] + 16
        t_off = jnp.zeros(B, jnp.int32)
        args = (dirs, jnp.asarray(lq), jnp.asarray(lt), klo, t_off)
        single = jax.jit(functools.partial(col_walk, LA=LA, layout="band"))
        dual = jax.jit(functools.partial(col_walk, LA=LA, layout="band",
                                         nxt=nxt))
        ts_ = t(single, *args)
        td_ = t(dual, *args)
        s, d = single(*args), dual(*args)
        ok = ~np.asarray(s["sat"])
        bitid = (np.array_equal(np.asarray(s["sat"]),
                                np.asarray(d["sat"])) and
                 all(np.array_equal(np.asarray(s[k])[ok],
                                    np.asarray(d[k])[ok])
                     for k in ("ins_len", "qstart", "op_c", "qi_c")))
        print(f"{Lq:>6} {LA + 2:>8} {(LA + 2 + 1) // 2:>8} "
              f"{ts_ * 1e3:>10.2f} {td_ * 1e3:>8.2f} "
              f"{ts_ / td_:>7.2f}x {'PASS' if bitid else 'FAIL':>6}")
        if not bitid:
            sys.exit(1)


if __name__ == "__main__":
    main()
