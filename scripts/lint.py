#!/usr/bin/env python
"""Contract linter driver.

Runs every rule in racon_tpu.analysis.rules.ALL_RULES over the repo
(racon_tpu/, scripts/, bench.py), subtracts the baseline, and prints a
byte-stable report plus a ``lint_findings_total=...`` summary line.

    python scripts/lint.py              # report, exit 0 always
    python scripts/lint.py --ci         # exit 1 on non-baselined findings
    python scripts/lint.py --json       # machine-readable report
    python scripts/lint.py --baseline p # alternate baseline file

The baseline (.lint-baseline.json, a JSON list of
``rule:path:message`` fingerprints) grandfathers known findings; the
repo ships an empty one — new findings fail CI immediately.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from racon_tpu.analysis import (ALL_RULES, Context, load_baseline,  # noqa: E402
                                render_json, render_text, run_rules,
                                split_findings, summary_line)


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ci", action="store_true",
                    help="exit 1 when non-baselined findings exist")
    ap.add_argument("--json", action="store_true",
                    help="emit the JSON report instead of text")
    ap.add_argument("--baseline",
                    default=os.path.join(repo, ".lint-baseline.json"),
                    help="baseline file (JSON list of fingerprints)")
    ap.add_argument("--root", default=repo,
                    help="repo root to lint (default: this repo)")
    args = ap.parse_args(argv)

    ctx = Context(args.root)
    findings = run_rules(ALL_RULES, ctx)
    active, suppressed = split_findings(
        findings, load_baseline(args.baseline))

    if args.json:
        sys.stdout.write(render_json(active, suppressed))
    else:
        sys.stdout.write(render_text(active, suppressed))
    print(summary_line(active, suppressed, len(ALL_RULES),
                       len(ctx.files)))

    if args.ci and active:
        print(f"[racon_tpu::lint] FAIL: {len(active)} non-baselined "
              f"finding(s); fix them or (exceptionally) add their "
              f"fingerprints to {os.path.basename(args.baseline)}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
