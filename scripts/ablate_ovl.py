"""Ablation: where a device overlap-alignment chunk spends its time.

Builds one 128-lane chunk of ~8 kb synthetic overlap jobs (the genome
bench geometry) and times jitted prefixes: tband build, banded forward,
column walk, breaking-point reduction.
"""

import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def t(fn, *args, reps=3, **kw):
    out = np.asarray(fn(*args, **kw))
    t0 = time.perf_counter()
    o = None
    for _ in range(reps):
        o = fn(*args, **kw)
    np.asarray(o)
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops.ovl_align import (band_width_for_read, _round_up,
                                         _pick_tiles)
    from racon_tpu.ops.colwalk import col_walk
    from racon_tpu.ops.pallas.band_kernel import (
        fw_dirs_band, fw_dirs_band_xla, band_geometry)
    from racon_tpu.ops.cigar import DIAG

    B = 128
    rng = np.random.default_rng(0)
    L = 8000
    Lq = _round_up(L + 400, 2048)
    LA = Lq
    W = _round_up(band_width_for_read(L, L), 512)
    w_len = 500
    NW = LA // w_len + 2
    pallas = jax.default_backend() in ("tpu", "axon")
    tb, ch = _pick_tiles(W, Lq)
    print(f"backend={jax.default_backend()} B={B} Lq={Lq} W={W} NW={NW} "
          f"tiles={tb},{ch}")

    q = rng.integers(0, 4, (B, Lq)).astype(np.uint8)
    tt = rng.integers(0, 4, (B, LA)).astype(np.uint8)
    lq = np.full(B, L, np.int32)
    lt = np.full(B, L + 37, np.int32)
    t_begin = rng.integers(0, 10000, B).astype(np.int32)

    @functools.partial(jax.jit, static_argnames=("upto",))
    def stage(q, tt, lq, lt, t_begin, *, upto):
        klo, wl = band_geometry(lq, lt, W)
        PW = W + Lq
        tpad = jnp.concatenate(
            [jnp.zeros((B, PW), jnp.uint8), tt,
             jnp.zeros((B, PW), jnp.uint8)], axis=1)
        y = jnp.arange(PW, dtype=jnp.int32)[None, :]
        rel = klo[:, None] + y
        okb = (rel >= 0) & (rel < lt[:, None])
        sl = jax.vmap(
            lambda row, s: jax.lax.dynamic_slice(row, (s,), (PW,)))(
            tpad, klo + PW)
        tband = jnp.where(okb, sl, 7).astype(jnp.uint8)
        if upto == "tband":
            return jnp.sum(tband[:, ::64].astype(jnp.int32))
        if pallas:
            dirs, nxt, hlast = fw_dirs_band(
                tband, q.T, klo, lq, match=0, mismatch=-1, gap=-1,
                W=W, tb=tb, ch=ch)
        else:
            dirs, nxt, hlast = fw_dirs_band_xla(
                tband, q.T, klo, lq, match=0, mismatch=-1, gap=-1, W=W)
        if upto == "fw":
            return jnp.sum(dirs[0, 0].astype(jnp.int32)) + jnp.sum(hlast)
        cols = col_walk(dirs, lq, lt, klo, jnp.zeros(B, jnp.int32),
                        LA=LA, layout="band_t" if pallas else "band",
                        nxt=nxt)
        if upto == "walk":
            return sum(jnp.sum(cols[k].astype(jnp.int32))
                       for k in ("ins_len", "op_c", "qi_c"))
        op = cols["op_c"][:, 1:LA + 1].astype(jnp.int32)
        qi = cols["qi_c"][:, 1:LA + 1].astype(jnp.int32)
        c = jnp.arange(LA, dtype=jnp.int32)[None, :]
        is_m = (c < lt[:, None]) & (op == DIAG)
        widx = (t_begin[:, None] + c) // w_len - \
            (t_begin // w_len)[:, None]
        HUGE = 2 ** 30
        outs = []
        for k in range(NW):
            mask = is_m & (widx == k)
            outs.append(jnp.min(jnp.where(mask, c, HUGE), axis=1))
            outs.append(jnp.max(jnp.where(mask, c, -1), axis=1))
        fc = jnp.stack(outs[::2], axis=1)
        return jnp.sum(fc) + jnp.sum(qi[:, ::64])

    args = (q, tt, lq, lt, t_begin)
    prev = 0.0
    for upto in ("tband", "fw", "walk", "bp"):
        dt = t(stage, *args, upto=upto)
        print(f"{upto:6s}: {dt:.3f}s (+{dt - prev:.3f}s)", flush=True)
        prev = dt


if __name__ == "__main__":
    main()
