"""CI smoke: flagged windows resolve through the on-device wide-band
redo pass — zero host consensus redos, byte-identical output.

The workload engineers the anchor-growth flag class: each draft contig
drops every second base across most of its truth sequence, so the
consensus must GROW ~260 bases past the backbone — more than the
round-0 chunk's ``la_grow = 64`` anchor slack plus its 128-grid
padding, which raises the sticky device overflow flag. (The deletions are scattered single bases, so no
insertion run approaches ``U_SAT`` — this is exactly the
redo-recoverable class, not the saturation class.) Before round 8
those windows re-polished on the HOST (serial native POA mid-polish);
now ``ops/redo.py`` re-runs them on device at 4x growth slack / 2x
band and the host path never fires:

1. ``RACON_TPU_REDO=0`` (the pre-round-8 behavior): run completes,
   trace metrics show ``redo_host_windows >= 1`` — proof the workload
   really triggers the legacy host-redo class.
2. Default run: stdout byte-identical to (1), ``redo_device_windows
   >= 1``, ``redo_host_windows == 0``, ``walk_chain_len`` gauge
   published, trace schema valid, and obs_report renders its "redo:"
   section from the footer.

Subprocesses (not in-process cli.main) so each run's env gates arm
independently and the metrics registry starts clean.
"""

import io
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"


def _noisy(rng, truth, err=0.02):
    out = []
    for b in truth:
        r = rng.random()
        if r < err / 2:
            continue
        out.append(int(rng.integers(0, 4)) if r < err else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d, n_contigs=2):
    rng = np.random.default_rng(17)
    drafts, reads, paf = [], [], []
    for c in range(n_contigs):
        truth = BASES[rng.integers(0, 4, 900 + 32 * c)]
        # Draft drops every 2nd base of truth[40:460]: ~210 scattered
        # single-base deletions, all landing in the draft's FIRST
        # 500-base window -> that window's consensus grows past the
        # anchor slack (la_grow=64 plus <=127 of 128-grid padding),
        # with no multi-base insertion run anywhere near U_SAT, while
        # the whole-read length imbalance (~23%) stays inside the
        # overlap error filter.
        keep = np.ones(len(truth), bool)
        keep[40:460:2] = False
        draft = bytes(BASES[np.searchsorted(BASES, truth[keep])])
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(8):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _run(d, env=None):
    e = dict(os.environ)
    for k in ("RACON_TPU_REDO", "RACON_TPU_TRACE"):
        e.pop(k, None)
    e.update(env or {})
    proc = subprocess.run(
        [sys.executable, "-c", BOOT, "--backend", "jax",
         os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
         os.path.join(d, "draft.fasta")],
        capture_output=True, env=e)
    return proc.returncode, proc.stdout, proc.stderr.decode()


def _metrics_footer(trace_path):
    with open(trace_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("ev") == "metrics":
                return rec
    raise AssertionError(f"no metrics footer in {trace_path}")


def main():
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)

        # --- pre-round-8 behavior: the flagged class lands on the host.
        trace0 = os.path.join(d, "host.jsonl")
        rc, base, err = _run(d, env={"RACON_TPU_REDO": "0",
                                     "RACON_TPU_TRACE": trace0})
        assert rc == 0, err
        assert base.count(b">") == 2, "expected 2 polished contigs"
        m0 = _metrics_footer(trace0)
        host0 = int(m0.get("redo_host_windows", 0))
        assert host0 >= 1, (
            f"workload no longer triggers the host-redo class: {m0}")

        # --- round-8 default: same windows resolve on device.
        trace1 = os.path.join(d, "device.jsonl")
        rc, out, err = _run(d, env={"RACON_TPU_TRACE": trace1})
        assert rc == 0, err
        assert out == base, \
            "wide-band device redo output differs from the host path"
        m1 = _metrics_footer(trace1)
        assert int(m1.get("redo_device_windows", 0)) >= 1, m1
        assert int(m1.get("redo_host_windows", 0)) == 0, m1
        assert int(m1.get("walk_chain_len", 0)) >= 1, m1

        from scripts import obs_report
        tr = obs_report.load_trace(trace1)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        buf = io.StringIO()
        obs_report.render(tr, out=buf)
        rendered = buf.getvalue()
        assert "redo:" in rendered and "walk chain:" in rendered, rendered

        print(f"[redo-smoke] {host0} host-redo window(s) under "
              f"RACON_TPU_REDO=0 -> "
              f"{int(m1['redo_device_windows'])} device / "
              f"{int(m1['redo_host_windows'])} host with the wide-band "
              f"pass; walk chain {int(m1['walk_chain_len'])}; output "
              "byte-identical", flush=True)

    print("[redo-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
