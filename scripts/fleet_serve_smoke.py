"""CI smoke: the fleet-serve gateway end to end through real processes
(racon_tpu/gateway/, docs/GATEWAY.md).

One daemon becomes a sharded service gateway: an armed routing policy
ships big jobs to an autoscaled ledger fleet (worker subprocesses over
a nonce-fenced WorkLedger) and keeps small ones on the in-process
batcher, with every streamed byte asserted identical to a solo serial
CLI run.

Phases:

A. **Routed fleet under fire** — 3 concurrent jobs from 2 tenants:
   two route to the fleet (4 targets >= RACON_TPU_GATE_FLEET_MIN_-
   TARGETS=2), one stays local (1 target). The autoscaler fault plan
   hard-kills each fleet's first worker mid-job (``dist/contig:1!kill``
   → ``os._exit(137)``); the supervisor replaces it and the replacement
   steals the orphaned shard. All three streams byte-diff clean, the
   gate_* counters tell the routes apart, and /metrics validates.
B. **Resubmit = CAS hit** — the same spec resubmitted is served from
   the daemon's result CAS without a second fleet dispatch.
C. **Warm pool** — a fresh fleet job's freshly spawned worker attaches
   to the shared jaxcache pool populated in phase A: its metric shard
   records the pool's entry count at start, and the pool gains zero
   entries (every compile was a hit).
D. **Gateway kill drill** — a fresh primary is hard-killed mid-commit
   (``serve/commit:1!kill``) while holding the gateway lease; a
   ``--standby`` replica (skewed clock, same discipline as the shard
   ledger drills) adopts the state dir, re-queues the in-flight job,
   replays the committed prefix from its store, short-circuits on the
   already-merged ledger output, and streams byte-identical.

Plus: one trace id spans gateway → supervisor → workers —
``obs_report.py <state> --job <trace_id>`` stitches gate spans and
worker spans into one timeline.
"""

import io
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = ("import sys; from racon_tpu import cli; "
        "sys.exit(cli.main(sys.argv[1:]))")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRUB_ENVS = ("RACON_TPU_FAULTS", "RACON_TPU_TRACE",
              "RACON_TPU_TRACE_CTX", "RACON_TPU_OBS_DIR",
              "RACON_TPU_GATE_FLEET", "RACON_TPU_GATE_FLEET_MIN_TARGETS",
              "RACON_TPU_GATE_WORKERS", "RACON_TPU_GATE_LEASE_S",
              "RACON_TPU_AUTOSCALE_FAULT_PLAN", "RACON_TPU_CACHE_DIR",
              "RACON_TPU_JAX_CACHE")


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d, n_contigs, seed):
    rng = np.random.default_rng(seed)
    drafts, reads, paf = [], [], []
    for c in range(n_contigs):
        truth = BASES[rng.integers(0, 4, 300 + 40 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _env(**overrides):
    e = dict(os.environ)
    for k in SCRUB_ENVS:
        e.pop(k, None)
    e.update(overrides)
    return e


def _solo_cli(d):
    proc = subprocess.run(
        [sys.executable, "-c", BOOT, "--backend", "jax",
         os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
         os.path.join(d, "draft.fasta")],
        capture_output=True, env=_env(), cwd=ROOT)
    assert proc.returncode == 0, proc.stderr.decode()
    return proc.stdout


# ------------------------------------------------------------ daemon ops


def _start_daemon(state, env=None, standby=False):
    e = _env(**(env or {}))
    os.makedirs(state, exist_ok=True)
    port_file = os.path.join(state, "port")
    if os.path.exists(port_file):
        os.remove(port_file)
    argv = [sys.executable, "-m", "racon_tpu.server", "--state-dir",
            state, "--port", "0"]
    if standby:
        argv.append("--standby")
    proc = subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, env=e, cwd=ROOT)
    deadline = time.monotonic() + 180
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise AssertionError("daemon died on startup:\n" +
                                 proc.stderr.read().decode())
        if time.monotonic() > deadline:
            proc.kill()
            raise AssertionError("daemon never published its port")
        time.sleep(0.05)
    with open(port_file) as fh:
        port = int(fh.read().strip())
    return proc, port


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.read()


def _submit(port, tenant, d):
    body = json.dumps({
        "tenant": tenant,
        "sequences": os.path.join(d, "reads.fasta"),
        "overlaps": os.path.join(d, "ovl.paf"),
        "targets": os.path.join(d, "draft.fasta"),
        "options": {"backend": "jax"}}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/jobs", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())["id"]


def _wait_done(port, job_id, timeout_s=600):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/v1/jobs/{job_id}"))
        if status["state"] in ("done", "failed", "cancelled"):
            assert status["state"] == "done", status
            return status
        time.sleep(0.2)
    raise AssertionError(f"job {job_id} did not finish in {timeout_s}s")


def _metric(text, key):
    m = re.search(rf"^racon_tpu_{key}(?:_total)? (\S+)$", text,
                  re.MULTILINE)
    return float(m.group(1)) if m else None


def _pool_entries(pool):
    try:
        return sum(1 for e in os.scandir(pool) if e.is_file())
    except OSError:
        return 0


def _fleet_run_dirs(state):
    root = os.path.join(state, "fleet")
    return [os.path.join(root, n) for n in sorted(os.listdir(root))
            if n not in ("jaxcache", "cas") and
            os.path.isdir(os.path.join(root, n, "ledger"))]


def main():
    from racon_tpu.obs.export import validate_openmetrics
    from racon_tpu.obs import fleet as obs_fleet

    with tempfile.TemporaryDirectory() as d:
        dirs = {
            "a": (os.path.join(d, "inA"), 4, 11),   # fleet, acme
            "b": (os.path.join(d, "inB"), 4, 22),   # fleet, umbrella
            "c": (os.path.join(d, "inC"), 1, 33),   # local (1 target)
            "e": (os.path.join(d, "inE"), 4, 44),   # fleet, warm drill
            "f": (os.path.join(d, "inF"), 2, 55),   # fleet, kill drill
        }
        refs = {}
        for key, (di, n, seed) in dirs.items():
            _write_inputs(di, n, seed)
            refs[key] = _solo_cli(di)
            assert refs[key].count(b">") == n, key

        # --- phase A: routed fleet under fire -------------------------
        s1 = os.path.join(d, "s1")
        os.makedirs(os.path.join(s1, "obs"), exist_ok=True)
        pool = os.path.join(s1, "fleet", "jaxcache")
        gate_env = {
            "RACON_TPU_GATE_FLEET": "1",
            "RACON_TPU_GATE_FLEET_MIN_TARGETS": "2",
            "RACON_TPU_GATE_WORKERS": "2",
            "RACON_TPU_AUTOSCALE_INTERVAL_S": "0.2",
            "RACON_TPU_TRACE": os.path.join(s1, "obs", "daemon.jsonl"),
        }
        # Each fleet's first spawned worker is hard-killed at its 2nd
        # contig; the supervisor must replace it and the replacement
        # must steal the orphaned shard.
        plan = os.path.join(d, "fault_plan.json")
        with open(plan, "w") as fh:
            json.dump(["dist/contig:1!kill"], fh)
        proc, port = _start_daemon(s1, env=dict(
            gate_env, RACON_TPU_AUTOSCALE_FAULT_PLAN=plan))
        j1 = _submit(port, "acme", dirs["a"][0])
        j2 = _submit(port, "umbrella", dirs["b"][0])
        j3 = _submit(port, "acme", dirs["c"][0])
        st1 = _wait_done(port, j1)
        _wait_done(port, j2)
        _wait_done(port, j3)
        for jid, key in ((j1, "a"), (j2, "b"), (j3, "c")):
            assert _get(port, f"/v1/jobs/{jid}/stream") == refs[key], \
                f"job {jid} ({key}) differs from solo serial CLI"
        text = _get(port, "/metrics").decode()
        errs = validate_openmetrics(text)
        assert not errs, "invalid /metrics:\n" + "\n".join(errs)
        assert _metric(text, "gate_routed_fleet") == 2, text
        assert _metric(text, "gate_routed_local") == 1, text
        assert _metric(text, "gate_fleet_runs") == 2, text
        assert _metric(text, "gate_fleet_target") is not None, \
            "service-signal autoscaling published no gate_fleet_target"
        evicted = 0
        for run in _fleet_run_dirs(s1):
            hb = os.path.join(run, "ledger", "obs", "autoscaler.json")
            with open(hb) as fh:
                evicted += json.loads(fh.readline())["evicted_total"]
        assert evicted >= 2, \
            f"expected both fleets' first workers hard-killed, " \
            f"saw {evicted} eviction(s)"
        assert _pool_entries(pool) > 0, \
            "fleet workers populated no shared compile-cache pool"
        print(f"[fleet-serve-smoke] A: 2 fleet + 1 local jobs "
              f"byte-identical across 2 tenants; {evicted} worker "
              f"kill(s) absorbed; pool holds "
              f"{_pool_entries(pool)} entr(ies)", flush=True)

        # --- phase B: resubmit = CAS hit, no second dispatch ----------
        j4 = _submit(port, "acme", dirs["a"][0])
        _wait_done(port, j4)
        assert _get(port, f"/v1/jobs/{j4}/stream") == refs["a"]
        text = _get(port, "/metrics").decode()
        assert _metric(text, "gate_routed_fleet") == 2, \
            "resubmitted job dispatched a second fleet run instead " \
            "of hitting the daemon CAS"
        print("[fleet-serve-smoke] B: resubmit served from the result "
              "CAS, fleet not re-dispatched", flush=True)

        # --- phase C: freshly spawned worker hits the warm pool -------
        entries = _pool_entries(pool)
        before = set(_fleet_run_dirs(s1))
        j5 = _submit(port, "umbrella", dirs["e"][0])
        _wait_done(port, j5)
        assert _get(port, f"/v1/jobs/{j5}/stream") == refs["e"]
        assert _pool_entries(pool) == entries, \
            f"warm-pool miss: {_pool_entries(pool) - entries} fresh " \
            "compile(s) escaped the shared jaxcache"
        run5 = sorted(set(_fleet_run_dirs(s1)) - before)
        assert len(run5) == 1, \
            f"expected exactly one new fleet run dir, got {run5}"
        shards = obs_fleet.load_worker_shards(
            os.path.join(run5[0], "ledger", "obs"))
        starts = [sh["records"][-1]["metrics"].get(
            "jax_cache_entries_start", 0) for sh in shards]
        assert any(s == entries for s in starts), \
            f"no spawned worker recorded the warm pool at start " \
            f"(pool {entries}, workers saw {starts})"
        print(f"[fleet-serve-smoke] C: fresh worker started against "
              f"{entries} pooled executable(s), 0 added", flush=True)

        # --- one trace id: gateway -> supervisor -> workers -----------
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=180)
        assert rc == 0, proc.stderr.read().decode()
        trace_id = st1["trace"].split(":")[0]
        from scripts import obs_report
        buf = io.StringIO()
        assert obs_report._render_job(s1, trace_id, out=buf) == 0
        tl_text = buf.getvalue()
        m = re.search(r"across (\d+) process", tl_text)
        assert m and int(m.group(1)) >= 2, tl_text
        assert "gate/route_fleet" in tl_text, tl_text
        assert "gate/fleet_run" in tl_text, tl_text
        assert "decision=fleet" in tl_text, tl_text
        assert re.search(r"worker_as\d", tl_text), \
            "no autoscaled worker spans joined the job timeline:\n" + \
            tl_text
        print(f"[fleet-serve-smoke] timeline: job {trace_id} spans "
              f"{m.group(1)} processes incl. gate spans", flush=True)

        # --- phase D: gateway kill drill with standby adoption --------
        s2 = os.path.join(d, "s2")
        d_env = {
            "RACON_TPU_GATE_FLEET": "1",
            "RACON_TPU_GATE_FLEET_MIN_TARGETS": "2",
            "RACON_TPU_GATE_WORKERS": "1",
            "RACON_TPU_AUTOSCALE_INTERVAL_S": "0.2",
        }
        primary, port = _start_daemon(s2, env=dict(
            d_env, RACON_TPU_FAULTS="serve/commit:1!kill"))
        j6 = _submit(port, "acme", dirs["f"][0])
        rc = primary.wait(timeout=600)
        assert rc == 137, \
            f"expected the primary hard-killed mid-commit (137), " \
            f"got {rc}: {primary.stderr.read().decode()}"
        # The fleet finished merging before the kill; the job's store
        # holds exactly the first committed contig.
        man = os.path.join(s2, "jobs", j6, "ckpt", "manifest.jsonl")
        committed = sum(1 for line in open(man)
                        if json.loads(line).get("ev") == "contig")
        assert committed == 1, \
            f"expected 1 committed contig at the kill, {committed}"
        # Standby with a skewed clock (the ledger drills' instant-steal
        # idiom): adopts the dead primary's lease, re-queues the job.
        standby, port = _start_daemon(
            s2, env=dict(d_env, RACON_TPU_FAULTS="skew=99999"),
            standby=True)
        _wait_done(port, j6)
        assert _get(port, f"/v1/jobs/{j6}/stream") == refs["f"], \
            "adopted job differs from solo serial CLI"
        text = _get(port, "/metrics").decode()
        assert _metric(text, "gate_adoptions") == 1, text
        standby.send_signal(signal.SIGTERM)
        rc = standby.wait(timeout=180)
        assert rc == 0, standby.stderr.read().decode()
        print(f"[fleet-serve-smoke] D: primary killed mid-commit "
              f"({committed} contig durable), standby adopted the "
              f"lease, replayed the prefix, finished byte-identical",
              flush=True)

    print("[fleet-serve-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
