"""Ablation microbench of the banded forward kernel (real TPU).

Times kernel variants that each remove one cost component, all at bench
shapes (B=3072, Lq=640, W=384), using in-program deltas (chained reps of
the jitted call with a single scalar d2h at the end to sync — per
PROFILE.md, single-call timings through the axon tunnel are meaningless).

Variants:
  base       — the production kernel (band_kernel._kernel)
  noladder   — shift-max ladder removed (h = max(diag, up) only; WRONG
               results, cost ablation only)
  ladder3    — ladder truncated to 3 passes (max chain 8; WRONG)
  nodirs     — dirs computed but not stored (only hlast out; WRONG)
  notw       — target window slice hoisted (same row every time; WRONG)
  i16        — int16 scores end to end
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.ops.cigar import DIAG, UP, LEFT

_NEG = -(2 ** 30)
_NEG16 = -(2 ** 13)
TB = 128
CH = 32


def make_kernel(*, match, mismatch, gap, W, ladder_passes, store_dirs,
                dyn_tw, dtype):
    NEG = _NEG16 if dtype == jnp.int16 else _NEG

    def _kernel(tbandT_ref, qT_ref, klo_ref, lq_ref, dirs_ref, hlast_ref,
                prev_ref):
        c = pl.program_id(1)
        xr = jax.lax.broadcasted_iota(jnp.int32, (W, TB), 0)
        klo = klo_ref[0]
        lqv = lq_ref[0]

        @pl.when(c == 0)
        def _():
            j0 = klo[None, :] + xr
            init = jnp.where(j0 >= 0, j0 * gap, NEG).astype(dtype)
            prev_ref[:] = init
            hlast_ref[:] = init

        def row(r, _):
            i = c * CH + r + 1
            qrow = qT_ref[r]
            if dyn_tw:
                tw = tbandT_ref[pl.dslice(i - 1, W), :]
            else:
                tw = tbandT_ref[pl.dslice(0, W), :]
            jcol = i + klo[None, :] + xr
            sub = jnp.where(tw == qrow[None, :], match, mismatch)
            sub = jnp.where(jcol >= 1, sub, NEG).astype(dtype)
            P = prev_ref[:]
            diag = P + sub
            up = jnp.concatenate(
                [P[1:, :], jnp.full((1, TB), NEG, dtype)], axis=0) + \
                dtype(gap)
            tmp = jnp.maximum(diag, up)
            tmp = jnp.where(jcol == 0, (i * gap), tmp).astype(dtype)
            jg = (jcol * gap).astype(dtype)
            f = tmp - jg
            s = 1
            passes = 0
            while s < W and passes < ladder_passes:
                f = jnp.maximum(
                    f, jnp.concatenate(
                        [jnp.full((s, TB), NEG // 2, dtype), f[:-s, :]],
                        axis=0))
                s *= 2
                passes += 1
            h = f + jg
            h = jnp.where(jcol >= 0, h, NEG).astype(dtype)
            h = jnp.maximum(h, NEG)
            d = jnp.where(h == diag, DIAG,
                          jnp.where(h == up, UP, LEFT)).astype(jnp.uint8)
            if store_dirs:
                dirs_ref[r] = d
            prev_ref[:] = h
            hlast_ref[:] = jnp.where((lqv == i)[None, :], h, hlast_ref[:])
            return 0

        jax.lax.fori_loop(0, CH, row, 0)

    return _kernel


def build_fw(*, B, Lq, W, match, mismatch, gap, ladder_passes=99,
             store_dirs=True, dyn_tw=True, dtype=jnp.int32):
    kernel = make_kernel(match=match, mismatch=mismatch, gap=gap, W=W,
                         ladder_passes=ladder_passes, store_dirs=store_dirs,
                         dyn_tw=dyn_tw, dtype=dtype)

    @jax.jit
    def fw(tband, qT, klo, lq):
        dirs, hlast = pl.pallas_call(
            kernel,
            grid=(B // TB, Lq // CH),
            in_specs=[
                pl.BlockSpec((W + Lq, TB), lambda b, c: (0, b),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((CH, TB), lambda b, c: (c, b),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, TB), lambda b, c: (0, b),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, TB), lambda b, c: (0, b),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((CH, W, TB), lambda b, c: (c, 0, b),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((W, TB), lambda b, c: (0, b),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((Lq, W, B), jnp.uint8),
                jax.ShapeDtypeStruct((W, B), dtype),
            ],
            scratch_shapes=[pltpu.VMEM((W, TB), dtype)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary")),
        )(tband.astype(dtype).T, qT.astype(dtype), klo[None, :],
          lq[None, :])
        # consume: tiny reduction so only a scalar syncs
        return jnp.sum(hlast.astype(jnp.int32)) + jnp.sum(
            dirs[::97, ::31, ::53].astype(jnp.int32))

    return fw


def timeit(fn, args, reps=4):
    out = fn(*args)
    np.asarray(out)          # compile + sync
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / reps


def main():
    B, Lq, W = 3072, 640, 384
    M, X, G = 5, -4, -8
    rng = np.random.default_rng(0)
    tband = rng.integers(0, 4, (B, W + Lq)).astype(np.uint8)
    qT = rng.integers(0, 4, (Lq, B)).astype(np.uint8)
    klo = np.full(B, -192, np.int32)
    lq = np.full(B, 500, np.int32)
    args = (jnp.asarray(tband), jnp.asarray(qT), jnp.asarray(klo),
            jnp.asarray(lq))

    variants = [
        ("base", dict()),
        ("nodirs", dict(store_dirs=False)),
        ("noladder", dict(ladder_passes=0)),
        ("ladder3", dict(ladder_passes=3)),
        ("notw", dict(dyn_tw=False)),
        ("i16", dict(dtype=jnp.int16)),
        ("i16+ladder3", dict(dtype=jnp.int16, ladder_passes=3)),
        ("i16+nodirs", dict(dtype=jnp.int16, store_dirs=False)),
    ]
    for name, kw in variants:
        fw = build_fw(B=B, Lq=Lq, W=W, match=M, mismatch=X, gap=G, **kw)
        dt = timeit(fw, args)
        cells = B * Lq * W
        print(f"{name:14s}: {dt * 1e3:7.1f} ms   "
              f"{cells / dt / 1e9:6.1f} Gcell/s", flush=True)


if __name__ == "__main__":
    main()
