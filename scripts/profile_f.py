"""Micro-bench: variants of the monotone counting step
F[b,v] = #{s : X[b,s] < v} that dominates extract_votes.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

B, S, P = 2048, 1408, 770


def t(fn, *args, reps=3):
    out = np.asarray(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = np.asarray(fn(*args))
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    Xh = np.sort(rng.integers(-1, P, (B, S)), axis=1).astype(np.int32)
    X = jnp.asarray(Xh)
    vg = jnp.asarray(np.tile(np.arange(P, dtype=np.int32), (B, 1)))

    @jax.jit
    def f_mid(X, vg):                      # current form (sum over axis 1)
        return jnp.sum(X[:, :, None] < vg[:, None, :], axis=1,
                       dtype=jnp.int32)

    @jax.jit
    def f_last(X, vg):                     # reduce over the lane axis
        return jnp.sum(X[:, None, :] < vg[:, :, None], axis=2,
                       dtype=jnp.int32)

    @jax.jit
    def f_mm(X, vg):                       # MXU: ones @ compare (bf16)
        cmp = (X[:, :, None] < vg[:, None, :]).astype(jnp.bfloat16)
        ones = jnp.ones((B, S), jnp.bfloat16)
        return jnp.einsum("bs,bsp->bp", ones, cmp).astype(jnp.int32)

    @jax.jit
    def f_two(X, vg):                      # two-level monotone blocks
        K = 128
        nb = S // K
        Xb = X.reshape(B, nb, K)
        last = Xb[:, :, -1]                           # block max
        coarse = jnp.sum(last[:, :, None] < vg[:, None, :], axis=1,
                         dtype=jnp.int32)             # full blocks
        kstar = jnp.clip(coarse, 0, nb - 1)
        blk = jnp.take_along_axis(Xb, kstar[:, :, None], axis=1)  # [B,P,K]
        fine = jnp.sum(blk < vg[:, :, None], axis=2, dtype=jnp.int32)
        # Blocks before kstar are entirely < v; kstar's partial count adds
        # fine (when coarse == nb, kstar = nb-1 and fine = K, so F = S).
        return kstar * K + fine

    # correctness vs numpy
    ref = (Xh[:, :, None] < np.arange(P)[None, None, :]).sum(1)
    outs = {}
    for name, fn in (("mid", f_mid), ("last", f_last), ("mm", f_mm),
                     ("two", f_two)):
        o = np.asarray(fn(X, vg))
        outs[name] = o
        ok = np.array_equal(o, ref)
        dt = t(fn, X, vg)
        print(f"{name:5s}: {dt*1e3:7.1f} ms  correct={ok}", flush=True)


if __name__ == "__main__":
    main()
