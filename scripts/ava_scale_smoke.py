"""CI smoke: assembly-scale all-vs-all fragment correction through the
ava planner subsystem (racon_tpu/ava/, docs/AVA.md), end to end
through real processes.

The drill: an ava read set (``--reads``, default 10,000; skewed — a
long-read head, a short-read tail, so count- and byte-balanced
partitions genuinely differ) corrected with ``-f`` (every read is a
target) three ways —

1. serial CLI: the golden bytes;
2. fleet worker A on a shared work ledger, hard-killed mid-run
   (``dist/contig:<k>!kill`` — the one injected eviction);
3. fleet worker B (clock skew outruns A's stale lease): steals A's
   shard, resumes the committed prefix, finishes every shard, merges.

Gates:
- the merged fleet output is **byte-identical** to the serial run;
- the ledger published **length-weighted** shard bounds (different
  from the count partition on this skewed set, same cover invariants);
- every shard's checkpoint manifest is **v2 segmented**: run-length
  ``seg`` records only, amortized far below one record per target —
  the o(1)-metadata acceptance bar;
- the worker logged its shape-bucket plan (compile keys within the
  ``RACON_TPU_AVA_COMPILE_BUDGET``) and the survivor's trace footer
  accounts the steal, the resumed prefix, and the v2 seals.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"


FAMILY = 4


def _write_inputs(d, n_reads):
    """``n_reads`` reads with a skewed length mix (5% long head, 95%
    short tail — IN THAT ORDER, so byte-balanced bounds must cut the
    head finer than the count partition would). Reads come in families
    of ``FAMILY`` noisy copies of a shared truth (so the all-vs-all
    overlaps are genuine alignments, not filtered out as spurious),
    with ring overlaps within each family, both PAF directions."""
    assert n_reads >= 2, "need at least one overlap pair"
    rng = np.random.default_rng(23)
    n_long = max(FAMILY, n_reads // 20)
    sizes = [FAMILY] * (n_reads // FAMILY)
    rem = n_reads % FAMILY
    if rem == 1 and sizes:
        sizes[-1] += 1       # no singleton families (no self-overlap)
    elif rem:
        sizes.append(rem)
    reads, paf = [], []
    i = 0
    for fam in sizes:
        ln = int(rng.integers(400, 700)) if i < n_long \
            else int(rng.integers(40, 90))
        truth = BASES[rng.integers(0, 4, ln)]
        names = []
        for _ in range(fam):
            out = []
            for b in truth:
                r = rng.random()
                if r < 0.03:
                    continue
                out.append(int(BASES[rng.integers(0, 4)]) if r < 0.06
                           else int(b))
            data = bytes(out)
            name = f"r{i + len(names)}"
            names.append((name, len(data)))
            reads.append(b">" + name.encode() + b"\n" + data + b"\n")
        for j in range(len(names)):
            qn, ql = names[j]
            tn, tl = names[(j + 1) % len(names)]
            if qn == tn:
                continue
            m, al = min(ql, tl), max(ql, tl)
            paf.append(f"{qn}\t{ql}\t0\t{ql}\t+\t{tn}\t{tl}\t0\t{tl}"
                       f"\t{m}\t{al}\t60")
            paf.append(f"{tn}\t{tl}\t0\t{tl}\t+\t{qn}\t{ql}\t0\t{ql}"
                       f"\t{m}\t{al}\t60")
        i += fam
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ava.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cmd(d, *extra):
    # Native backend: this smoke drills the ava planning/ledger/manifest
    # machinery, which is backend-agnostic — and at 10k reads the
    # per-window jax dispatch on a CPU-only CI box would turn a
    # 2-minute drill into an hour. Byte-identity is native vs native.
    return [sys.executable, "-c", BOOT, "--backend", "native", "-f",
            *extra,
            os.path.join(d, "reads.fasta"), os.path.join(d, "ava.paf"),
            os.path.join(d, "reads.fasta")]


def _env(**overrides):
    e = dict(os.environ)
    for k in ("RACON_TPU_FAULTS", "RACON_TPU_TRACE"):
        e.pop(k, None)
    e.update(overrides)
    return e


def _metrics_footer(trace_path):
    with open(trace_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("ev") == "metrics":
                return rec
    raise AssertionError(f"no metrics footer in {trace_path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reads", type=int, default=10_000,
                    help="ava read-set size (every read is a target)")
    args = ap.parse_args()
    n_reads = args.reads

    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d, n_reads)

        # Serial golden: the bytes the fleet must reproduce.
        proc = subprocess.run(_cmd(d), capture_output=True, env=_env())
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        base = proc.stdout
        assert base.count(b">") == n_reads, \
            f"serial kF emitted {base.count(b'>')}/{n_reads} reads"
        print(f"[ava-smoke] serial golden: {n_reads} reads, "
              f"{len(base)} bytes", flush=True)

        ledger = os.path.join(d, "ledger")

        # Worker A: hard-killed mid-run after committing a real prefix.
        # Fleet runs pin RACON_TPU_AVA_SEG=32 so the victim has *sealed*
        # segments behind it when it dies (v2 recovery drops only the
        # unsealed tail; at the default 256 a small-prefix kill would
        # legitimately resume nothing).
        seg = 32
        kill_at = max(seg + seg // 2, n_reads // 50)
        a = subprocess.Popen(
            _cmd(d, "--ledger-dir", ledger, "--workers", "2",
                 "--worker-id", "A"),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=_env(RACON_TPU_FAULTS=f"dist/contig:{kill_at}!kill",
                     RACON_TPU_AVA_SEG=str(seg)))
        a_out, a_err = a.communicate(timeout=900)
        assert a.returncode == 137, \
            f"A: expected kill 137, got {a.returncode}: " \
            f"{a_err.decode()[-2000:]}"
        assert a_out == b"", "evicted worker must not emit output"
        print(f"[ava-smoke] worker A evicted after ~{kill_at} commits "
              "(137)", flush=True)

        # Worker B: outruns A's stale lease, steals, finishes, merges.
        trace = os.path.join(d, "b.jsonl")
        b = subprocess.Popen(
            _cmd(d, "--ledger-dir", ledger, "--workers", "2",
                 "--worker-id", "B"),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=_env(RACON_TPU_FAULTS="skew=99999",
                     RACON_TPU_AVA_SEG=str(seg),
                     RACON_TPU_TRACE=trace))
        b_out, b_err = b.communicate(timeout=900)
        assert b.returncode == 0, b_err.decode()[-2000:]

        # Gate 1: byte identity.
        assert b_out == base, \
            "fleet-merged ava output differs from serial CLI"
        assert open(os.path.join(ledger, "out.fasta"),
                    "rb").read() == base
        print("[ava-smoke] fleet output byte-identical to serial",
              flush=True)

        # Gate 2: the published bounds are length-weighted — they cut
        # the long-read head finer than the count partition.
        meta = json.load(open(os.path.join(ledger, "meta.json")))
        bounds = meta["bounds"]
        n_shards = len(bounds) - 1
        count_bounds = [round(n_reads * k / n_shards)
                        for k in range(n_shards + 1)]
        assert bounds[0] == 0 and bounds[-1] == n_reads
        assert all(bounds[i] < bounds[i + 1] for i in range(n_shards))
        assert bounds != count_bounds, \
            f"expected weighted bounds on skewed input, got the " \
            f"count partition {bounds}"
        assert bounds[1] < count_bounds[1], \
            f"weighted bounds should cut the heavy head early: " \
            f"{bounds} vs count {count_bounds}"
        print(f"[ava-smoke] weighted bounds {bounds} "
              f"(count partition would be {count_bounds})", flush=True)

        # Gate 3: v2 segmented manifests — run-length records only,
        # amortized far below one record per target.
        seg_records = 0
        covered = 0
        for name in sorted(os.listdir(ledger)):
            man = os.path.join(ledger, name, "manifest.jsonl")
            if not name.startswith("shard_") or not os.path.isfile(man):
                continue
            for line in open(man, "rb").read().splitlines():
                rec = json.loads(line)
                if rec.get("ev") == "begin":
                    assert rec.get("manifest") == 2, \
                        f"{name}: expected a v2 manifest header: {rec}"
                elif rec.get("ev") == "seg":
                    seg_records += 1
                    covered += int(rec["end"]) - int(rec["start"])
                else:
                    raise AssertionError(
                        f"{name}: per-target record in a v2 manifest: "
                        f"{rec}")
        assert covered >= n_reads, \
            f"segments cover {covered}/{n_reads} targets"
        assert seg_records * 8 <= n_reads, \
            f"{seg_records} manifest records for {n_reads} targets — " \
            "segment amortization failed"
        print(f"[ava-smoke] {seg_records} segment record(s) cover "
              f"{covered} targets (v2 manifests, "
              f"{covered // max(1, seg_records)} targets/record)",
              flush=True)

        # Gate 4: the shape-bucket plan was published under budget, and
        # the survivor's footer accounts the steal + resume + seals.
        b_err_text = b_err.decode()
        assert "[racon_tpu::ava] worker:" in b_err_text, \
            "worker never logged its shape-bucket plan"
        m = _metrics_footer(trace)
        assert m.get("ava_targets", 0) == n_reads, m.get("ava_targets")
        budget = int(m.get("ava_compile_budget", 0))
        assert 0 < m.get("ava_buckets", 0) <= budget, \
            f"bucket plan over budget: {m.get('ava_buckets')} > {budget}"
        assert m.get("dist_shards_stolen", 0) >= 1, \
            "survivor never stole the evicted worker's shard"
        assert m.get("dist_contigs_resumed", 0) >= 1, \
            "victim's committed prefix was not resumed"
        assert m.get("res_ckpt_seals", 0) >= 1, \
            "no v2 segment seals recorded"
        print(f"[ava-smoke] plan: {int(m['ava_buckets'])} bucket(s) "
              f"within budget {budget}; survivor stole "
              f"{int(m['dist_shards_stolen'])} shard(s), resumed "
              f"{int(m['dist_contigs_resumed'])} committed target(s), "
              f"{int(m['res_ckpt_seals'])} seal(s)", flush=True)

    print("[ava-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
