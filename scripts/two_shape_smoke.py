"""CI smoke: two differently-shaped device-engine runs in one process.

Round 3 shipped a crash in exactly this pattern (a module-level
jax.Array constant lowered as a hoisted executable parameter that the
execution path then under-supplied — INVALID_ARGUMENT / "Execution
supplied N buffers but compiled program expected N+1"). Runs on
whatever backend is available: the failure reproduced on the CPU
backend too, so CI without a TPU still guards it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import build_windows                      # noqa: E402
from racon_tpu.ops.poa import PoaEngine              # noqa: E402


def main():
    # Geometry chosen so run-level padding caps differ between the runs
    # (different Lq/LA buckets -> genuinely distinct executables).
    for n, cov, wlen, seed in ((6, 6, 120, 3), (5, 8, 150, 7),
                               (4, 10, 260, 11)):
        ws = build_windows(n, cov, wlen, seed=seed)
        eng = PoaEngine(backend="jax")
        assert eng.consensus_windows(ws) == n
        assert all(w.consensus for w in ws)
        print(f"[smoke] ok: {n} windows, wlen={wlen}", flush=True)
    print("[smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
