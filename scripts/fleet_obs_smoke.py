"""CI smoke: the fleet observability plane, end to end through real
processes (racon_tpu/obs/fleet.py, obs/export.py, docs/OBSERVABILITY.md).

The drill: 6 contigs in 3 shards, a 2-worker fleet with one real
eviction —

  worker A  ``dist/contig:1!term``  SIGTERM'd mid-shard after one
                                    contig; the teardown contract must
                                    leave a *final* metric snapshot;
  worker B  ``skew=99999``          the survivor: steals A's shard,
                                    finishes every shard, merges.

Both workers run with ``RACON_TPU_OBS_FLUSH_S=0`` (snapshot per
contig) and ``RACON_TPU_PIPELINE=2`` (streamed execution, so pipe_*
gauges exist to survive the merge).

Gates:
- merged FASTA byte-identical to a serial run (the fleet is still a
  correct polisher while being observed);
- both workers left metric shards; A's last snapshot is ``final`` (the
  SIGTERM flush);
- the merged fleet model's sum-kind counters equal the per-worker sums
  (checked for every sum key, not a cherry-picked few), and ``dist_*``
  / ``pipe_*`` / phase-seconds series survive the merge;
- the OpenMetrics render passes the structural validator, contains
  ``dist_*``, ``pipe_*``, and phase-seconds families, and is
  byte-stable across renders;
- the survivor's trace spans carry ``worker_id``/``run_fp`` context
  and scripts/obs_report.py renders a ``fleet:`` section for the
  ledger.
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = ("import sys; from racon_tpu import cli; "
        "sys.exit(cli.main(sys.argv[1:]))")
N_CONTIGS = 6
N_SHARDS = 3


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d):
    rng = np.random.default_rng(23)
    drafts, reads, paf = [], [], []
    for c in range(N_CONTIGS):
        truth = BASES[rng.integers(0, 4, 300 + 30 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cmd(d, *extra):
    return [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
            os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
            os.path.join(d, "draft.fasta")]


def _env(**overrides):
    e = dict(os.environ)
    for k in ("RACON_TPU_FAULTS", "RACON_TPU_TRACE", "RACON_TPU_OBS_DIR",
              "RACON_TPU_PIPELINE", "RACON_TPU_OBS_FLUSH_S"):
        e.pop(k, None)
    e["RACON_TPU_DIST_SHARDS"] = str(N_SHARDS)
    e.update(overrides)
    return e


def _worker(d, ledger, wid, *, faults=None, trace=None):
    env = {"RACON_TPU_OBS_FLUSH_S": "0", "RACON_TPU_PIPELINE": "2"}
    if faults:
        env["RACON_TPU_FAULTS"] = faults
    if trace:
        env["RACON_TPU_TRACE"] = trace
    return subprocess.Popen(
        _cmd(d, "--ledger-dir", ledger, "--workers", "2",
             "--worker-id", wid),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=_env(**env))


def main():
    from racon_tpu.obs import export as obs_export
    from racon_tpu.obs import fleet as obs_fleet
    from racon_tpu.obs.metrics import MERGE_SUM, merge_kind

    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)

        # Serial baseline: the bytes the observed fleet must still emit.
        proc = subprocess.run(_cmd(d), capture_output=True, env=_env())
        assert proc.returncode == 0, proc.stderr.decode()
        base = proc.stdout
        assert base.count(b">") == N_CONTIGS

        ledger = os.path.join(d, "ledger")

        # Worker A: SIGTERM'd after committing one contig. 143 = the
        # CLI's orderly teardown ran — which is exactly what the final
        # metric flush rides on.
        a = _worker(d, ledger, "A", faults="dist/contig:1!term")
        a_out, a_err = a.communicate(timeout=300)
        assert a.returncode == 143, \
            f"A: expected SIGTERM exit 143, got {a.returncode}: " \
            f"{a_err.decode()}"
        assert a_out == b""
        print("[fleet-obs-smoke] worker A evicted via SIGTERM (143)",
              flush=True)

        # Worker B: outruns every stale lease, finishes, merges.
        trace = os.path.join(d, "b.jsonl")
        b = _worker(d, ledger, "B", faults="skew=99999", trace=trace)
        b_out, b_err = b.communicate(timeout=300)
        assert b.returncode == 0, b_err.decode()
        assert b_out == base, \
            "merged FASTA differs from single-process serial run"
        print("[fleet-obs-smoke] worker B stole, finished, merged "
              "(byte-identical to serial)", flush=True)

        # ---- worker metric shards.
        obs_dir = os.path.join(ledger, obs_fleet.OBS_SUBDIR)
        shards = obs_fleet.load_worker_shards(obs_dir)
        assert len(shards) == 2, \
            f"expected 2 worker shards in {obs_dir}: {shards}"

        model = obs_fleet.aggregate(ledger)
        assert model["n_workers"] == 2, model["workers"].keys()
        assert model["workers"]["A"]["final"], \
            "evicted worker A left no final (SIGTERM-flushed) snapshot"
        assert model["workers"]["B"]["final"]
        assert model["workers"]["B"]["windows_per_sec"] > 0

        # Sum-kind counters must equal the per-worker sums — every key,
        # not a cherry-picked few.
        workers = model["workers"]
        for key, merged in model["fleet"].items():
            if merge_kind(key) != MERGE_SUM or \
                    not isinstance(merged, (int, float)):
                continue
            expect = sum(w["metrics"].get(key, 0) for w in
                         workers.values())
            assert abs(merged - expect) < 1e-6, \
                f"fleet[{key}] = {merged} != per-worker sum {expect}"
        for prefix in ("dist_", "pipe_", "phase_seconds_",
                       "poa_windows"):
            assert any(k.startswith(prefix) for k in model["fleet"]), \
                f"no {prefix}* metric survived the merge: " \
                f"{sorted(model['fleet'])}"
        # The eviction shows in the lease timeline.
        assert model["steals"] >= 1, model["timeline"]
        print(f"[fleet-obs-smoke] fleet model: {model['n_workers']} "
              f"workers, {model['steals']} steal(s), "
              f"{len(model['fleet'])} merged metrics (sums verified)",
              flush=True)

        # ---- OpenMetrics render: valid, complete, byte-stable.
        text = obs_export.render_fleet(model)
        errors = obs_export.validate_openmetrics(text)
        assert not errors, "invalid OpenMetrics:\n" + "\n".join(errors)
        for needle in ("racon_tpu_dist_", "racon_tpu_pipe_",
                       "racon_tpu_phase_seconds",
                       "racon_tpu_worker_windows_per_sec"):
            assert needle in text, f"missing {needle} series:\n{text}"
        assert text == obs_export.render_fleet(
            obs_fleet.aggregate(ledger)), \
            "OpenMetrics render is not byte-stable"
        rc = __import__("scripts.obs_export", fromlist=["main"]).main(
            [ledger, "--validate", "--out", os.path.join(d, "m.prom")])
        assert rc == 0, "scripts/obs_export.py --validate failed"
        print("[fleet-obs-smoke] OpenMetrics render valid and "
              "byte-stable", flush=True)

        # ---- span context: B's spans carry worker identity.
        from scripts import obs_report
        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        tagged = [s for s in tr["spans"].values()
                  if s.get("worker_id") == "B"]
        assert tagged, "no span carries worker_id context"
        assert all("run_fp" in s for s in tagged)
        assert any(isinstance(s.get("shard"), int) for s in tagged), \
            "no span carries the claimed-shard context"
        import io
        buf = io.StringIO()
        obs_report.render(tr, out=buf, fleet_dir=ledger)
        assert "fleet:" in buf.getvalue(), buf.getvalue()
        print("[fleet-obs-smoke] spans tagged with worker context; "
              "report renders fleet section", flush=True)

    print("[fleet-obs-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
