"""Ablation: device_chunk_packed windows/s at B=4096 vs B=8192.

The column walk is a serialized chain whose per-iteration cost is
dispatch overhead + one [B] gather; doubling B amortizes it over twice
the lanes if the gather is latency-bound. Usage:
python scripts/ablate_chunk_b.py [n_windows_per_chunk ...]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    from bench import build_windows
    from racon_tpu.ops.device_poa import (ChunkPlan, run_caps, _use_pallas,
                                          device_chunk_packed)

    sizes = [int(a) for a in sys.argv[1:]] or [128, 256]
    print(f"backend={jax.default_backend()}")
    for n in sizes:
        sub = build_windows(n, 30, 500, seed=3)
        lqm = max(max(len(d) for d in w.layer_data) for w in sub)
        lam = max(len(w.backbone) for w in sub)
        lq_cap, la_cap = run_caps(lqm, lam)
        plan = ChunkPlan(sub, lq_cap=lq_cap, la_cap=la_cap)
        job_h, win_h = plan.packed_bufs()
        job_buf, win_buf = jax.device_put((job_h, win_h))
        kw = dict(match=5, mismatch=-4, gap=-8, ins_scale=0.3,
                  Lq=plan.Lq, n_win=plan.n_win, LA=plan.LA,
                  pallas=_use_pallas(plan.B, plan.Lq, plan.LA),
                  band_w=plan.band_w, rounds=4)
        out = device_chunk_packed(job_buf, win_buf, **kw)
        np.asarray(out[:1])
        reps = 3
        t1 = time.perf_counter()
        for _ in range(reps):
            out = device_chunk_packed(job_buf, win_buf, **kw)
        np.asarray(out[:1])
        dt = (time.perf_counter() - t1) / reps
        print(f"n_win={n:4d} B={plan.B} Lq={plan.Lq} LA={plan.LA} "
              f"W={plan.band_w}: {dt*1000:.0f} ms/chunk = "
              f"{n/dt:.1f} windows/s", flush=True)


if __name__ == "__main__":
    main()
