"""CI smoke: the ingest data plane, differential + trace-gated.

Three gates in one script (ISSUE 12):

1. **Raw inflate byte-diff** — a multi-member gzip and a BGZF file
   round-trip through io/inflate.py's parallel plans byte-identical to
   ``gzip.decompress``, and mid-member truncation raises the
   offset-bearing ParseError (member ordinal + compressed offset).
2. **CLI differential** — the full polish runs on gzipped AND plain
   inputs with ``RACON_TPU_INGEST=0`` (serial readers) and ``=1``
   (parallel inflate + mmap index-first readers + prefetch overlap);
   all four polished FASTAs must be byte-identical.
3. **Obs contract** — the gated gzipped run's trace validates against
   the documented schema and contains ``ingest`` spans; the metrics
   footer carries the ingest_* accounting.
"""

import contextlib
import gzip
import io
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from racon_tpu import cli                            # noqa: E402
from scripts import obs_report                       # noqa: E402
from scripts.obs_smoke import _write_inputs          # noqa: E402


def _run_cli(d, reads, ovl, draft, trace=None):
    if trace is not None:
        os.environ["RACON_TPU_TRACE"] = trace
    else:
        os.environ.pop("RACON_TPU_TRACE", None)

    class _Capture(io.StringIO):
        pass

    stdout = _Capture()
    stdout.buffer = io.BytesIO()
    with contextlib.redirect_stdout(stdout):
        rc = cli.main(["--backend", "jax", reads, ovl, draft])
    assert rc == 0, f"cli exited {rc}"
    return stdout.buffer.getvalue()


def _check_inflate(d):
    """Gate 1: parallel inflate plans vs gzip.decompress + truncation."""
    from racon_tpu.io.inflate import open_gzip_source
    from racon_tpu.io.parsers import ParseError

    payload = b"".join(b">m%d\n%s\n" % (i, b"ACGT" * 600)
                       for i in range(64))
    multi = os.path.join(d, "multi.fasta.gz")
    with open(multi, "wb") as fh:
        for i in range(0, len(payload), len(payload) // 8):
            fh.write(gzip.compress(payload[i:i + len(payload) // 8]))
    with open_gzip_source(multi) as src:
        got = b"".join(src.blocks())
    assert src.mode == "members", f"expected members plan, got {src.mode}"
    assert got == payload, "parallel member inflate diverged"

    blob = open(multi, "rb").read()
    trunc = os.path.join(d, "trunc.fasta.gz")
    open(trunc, "wb").write(blob[:-32])
    try:
        with open_gzip_source(trunc) as src:
            b"".join(src.blocks())
        raise AssertionError("truncated gzip did not raise")
    except ParseError as exc:
        msg = str(exc)
        assert "member" in msg and "compressed offset" in msg, msg
    print("[ingest-smoke] inflate plans ok (members byte-identical, "
          "truncation offset-bearing)", flush=True)


def main():
    with tempfile.TemporaryDirectory() as d:
        _check_inflate(d)

        _write_inputs(d)
        plain = [os.path.join(d, n)
                 for n in ("reads.fasta", "ovl.paf", "draft.fasta")]
        gz = [p + ".gz" for p in plain]
        for src, dst in zip(plain, gz):
            with open(src, "rb") as fi, open(dst, "wb") as fo:
                # Two members so the gated run takes the parallel plan.
                data = fi.read()
                fo.write(gzip.compress(data[:len(data) // 2]))
                fo.write(gzip.compress(data[len(data) // 2:]))

        outs = {}
        from racon_tpu.obs import metrics as obs_metrics
        trace = os.path.join(d, "trace.jsonl")
        for gate in ("0", "1"):
            os.environ["RACON_TPU_INGEST"] = gate
            outs[("plain", gate)] = _run_cli(d, *plain)
            obs_metrics.reset()
            outs[("gz", gate)] = _run_cli(
                d, *gz, trace=trace if gate == "1" else None)
        os.environ.pop("RACON_TPU_INGEST", None)
        os.environ.pop("RACON_TPU_TRACE", None)

        vals = set(outs.values())
        assert len(vals) == 1 and outs[("plain", "0")].startswith(b">"), \
            f"ingest outputs diverged across {sorted(outs)}"
        print("[ingest-smoke] 4-way byte-identity ok "
              "(plain/gz x gate off/on)", flush=True)

        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        kinds = {s["kind"] for s in tr["spans"].values()}
        assert "ingest" in kinds, f"no ingest span in trace ({kinds})"
        modes = {s.get("mode") for s in tr["spans"].values()
                 if s["kind"] == "ingest"}
        m = tr["metrics"]
        assert m is not None and m.get("ingest_records", 0) > 0, \
            "no ingest accounting in metrics footer"
        assert m.get("ingest_bytes_out", 0) > 0, "no inflate accounting"
        print(f"[ingest-smoke] trace ok: ingest modes={sorted(modes)}, "
              f"records={m['ingest_records']}, "
              f"inflate_bytes={m['ingest_bytes_out']}", flush=True)
    print("[ingest-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
