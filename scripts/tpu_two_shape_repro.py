"""Minimal repro harness for the two-executable TPU INVALID_ARGUMENT crash.

Round-3 bench failure (BENCH_r03 rc=1): running the device consensus
engine at two different padded shapes in one process crashes the second
run on the real TPU; same shape twice is fine, and small shapes are fine
(bench 8x8 passes). This script bisects the failure surface:

  python scripts/tpu_two_shape_repro.py engine   # full engine, 2 shapes
  python scripts/tpu_two_shape_repro.py pallas   # fw_dirs_pallas only
  python scripts/tpu_two_shape_repro.py xla      # fw_dirs_xla only
  python scripts/tpu_two_shape_repro.py trace    # fw + traceback, 2 shapes

Shapes mirror the default bench (96 windows x 30 cov): B=2944, LA=768,
Lq = 544 then 512.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

B, LA = 2944, 768
LQS = (544, 512)


def _consume(x):
    return float(np.asarray(x.reshape(-1)[:8]).sum())


def run_fw(kind: str, with_trace: bool) -> None:
    import jax.numpy as jnp
    from racon_tpu.ops import flat as flatmod

    rng = np.random.default_rng(0)
    for Lq in LQS:
        tbuf = jnp.asarray(rng.integers(0, 4, (B, LA)).astype(np.uint8))
        qT = jnp.asarray(rng.integers(0, 4, (Lq, B)).astype(np.uint8))
        if kind == "pallas":
            from racon_tpu.ops.pallas.flat_kernel import fw_dirs_pallas
            dirs = fw_dirs_pallas(tbuf, qT, match=5, mismatch=-4, gap=-8)
        else:
            dirs = flatmod.fw_dirs_xla(tbuf, qT, match=5, mismatch=-4,
                                       gap=-8)
        if with_trace:
            lq = jnp.full(B, Lq - 7, jnp.int32)
            lt = jnp.full(B, LA - 9, jnp.int32)
            rev = flatmod.fw_traceback(dirs, lq, lt, Lq + LA)
            print(f"Lq={Lq}: trace ok, sum={_consume(rev)}", flush=True)
        else:
            print(f"Lq={Lq}: fw ok, sum={_consume(dirs)}", flush=True)


def run_engine() -> None:
    from bench import build_windows
    from racon_tpu.ops.poa import PoaEngine

    for seed in (99, 0):
        eng = PoaEngine(backend="jax")
        n = eng.consensus_windows(build_windows(96, 30, 500, seed=seed))
        print(f"seed={seed}: engine ok, {n} windows", flush=True)


def run_round() -> None:
    """Two full run_chunk executions at forced different Lq caps."""
    from bench import build_windows
    from racon_tpu.ops.device_poa import ChunkPlan, run_chunk

    windows = build_windows(96, 30, 500, seed=0)
    for w in windows:
        w.consensus = None
    for lq_cap in LQS:
        plan = ChunkPlan(windows, lq_cap=lq_cap, la_cap=LA)
        codes, covs = run_chunk(plan, match=5, mismatch=-4, gap=-8,
                                ins_scale=0.3, rounds=4)
        print(f"Lq={lq_cap}: round ok, len0={len(codes[0] or b'')}",
              flush=True)


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "engine"
    if mode == "engine":
        run_engine()
    elif mode == "round":
        run_round()
    elif mode == "pallas":
        run_fw("pallas", False)
    elif mode == "xla":
        run_fw("xla", False)
    elif mode == "trace":
        run_fw("pallas", True)
    elif mode == "trace-xla":
        run_fw("xla", True)
    else:
        raise SystemExit(f"unknown mode {mode}")
    print("PASS", flush=True)
