"""CI smoke: the content-addressed result cache, end to end.

0. **Serial CLI**: ``--cache-dir`` resubmission re-emits from the job
   CAS (stderr announces zero consensus dispatches) byte-identical to
   the cold run.
1. **Daemon resubmit**: an identical job resubmitted to a real daemon
   is served from the CAS — ``serve_batch_windows`` does not move, the
   stream is byte-identical to the serial baseline — and a *restarted*
   daemon keeps hitting through its recovered index. The daemon's
   trace satisfies the ``cache`` span contract and obs_report renders
   a ``cache:`` section from it.
2. **Poisoning drill**: ``cache/load:0!torn`` tears the first probe;
   verify-on-hit demotes it to a miss (``cache_verify_fail_total``),
   the job recomputes, and the bytes never change.
3. **Disabled fallback**: ``RACON_TPU_CACHE=0`` over the same
   populated state recomputes byte-identically and records no
   ``cache_*`` metrics at all.

Subprocess daemons (not in-process PolishServer) so each phase's
env-gated knobs arm independently and restart recovery is real.
"""

import io
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d, n_contigs=3, seed=23):
    rng = np.random.default_rng(seed)
    drafts, reads, paf = [], [], []
    for c in range(n_contigs):
        truth = BASES[rng.integers(0, 4, 300 + 40 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cli(d, extra=()):
    e = dict(os.environ)
    e.pop("RACON_TPU_FAULTS", None)
    e.pop("RACON_TPU_TRACE", None)
    proc = subprocess.run(
        [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
         os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
         os.path.join(d, "draft.fasta")],
        capture_output=True, env=e, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr.decode()
    return proc.stdout, proc.stderr.decode()


# ------------------------------------------------------------ daemon ops


def _start_daemon(state, env=None):
    e = dict(os.environ)
    e.pop("RACON_TPU_FAULTS", None)
    e.pop("RACON_TPU_TRACE", None)
    e.update(env or {})
    os.makedirs(state, exist_ok=True)
    port_file = os.path.join(state, "port")
    if os.path.exists(port_file):
        os.remove(port_file)
    proc = subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.server", "--state-dir", state,
         "--port", "0"],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, env=e,
        cwd=ROOT)
    deadline = time.monotonic() + 180
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise AssertionError("daemon died on startup:\n" +
                                 proc.stderr.read().decode())
        if time.monotonic() > deadline:
            proc.kill()
            raise AssertionError("daemon never published its port")
        time.sleep(0.05)
    with open(port_file) as fh:
        port = int(fh.read().strip())
    return proc, port


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.read()


def _submit(port, d):
    body = json.dumps({
        "tenant": "acme",
        "sequences": os.path.join(d, "reads.fasta"),
        "overlaps": os.path.join(d, "ovl.paf"),
        "targets": os.path.join(d, "draft.fasta"),
        "options": {"backend": "jax"}}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/jobs", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())["id"]


def _wait_done(port, job_id, timeout_s=300):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/v1/jobs/{job_id}"))
        if status["state"] in ("done", "failed", "cancelled"):
            assert status["state"] == "done", status
            return
        time.sleep(0.2)
    raise AssertionError(f"job {job_id} did not finish in {timeout_s}s")


def _metric(port, name, default=0.0):
    text = _get(port, "/metrics").decode()
    m = re.search(rf"^{re.escape(name)} (\S+)$", text, re.MULTILINE)
    return float(m.group(1)) if m else default


def _run_job(port, d, base):
    jid = _submit(port, d)
    _wait_done(port, jid)
    stream = _get(port, f"/v1/jobs/{jid}/stream")
    assert stream == base, f"job {jid} stream differs from serial CLI"
    return jid


def _drain(proc):
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=120)
    assert rc == 0, ("daemon drain not clean (rc {}):\n".format(rc) +
                     proc.stderr.read().decode())


def main():
    with tempfile.TemporaryDirectory() as d:
        inp = os.path.join(d, "in")
        _write_inputs(inp)
        base, _ = _cli(inp)
        assert base.count(b">") == 3

        # --- phase 0: serial CLI --cache-dir resubmission.
        cdir = os.path.join(d, "cli-cache")
        cold, err_cold = _cli(inp, extra=("--cache-dir", cdir))
        assert cold == base
        assert "cache: re-emitted" not in err_cold
        warm, err_warm = _cli(inp, extra=("--cache-dir", cdir))
        assert warm == base, "CLI cache hit changed bytes"
        assert "cache: re-emitted" in err_warm and \
            "zero consensus dispatches" in err_warm, err_warm
        print("[cache-smoke] CLI --cache-dir resubmit byte-identical, "
              "re-emitted from CAS", flush=True)

        # --- phase 1: daemon resubmit = zero consensus dispatches.
        state = os.path.join(d, "s1")
        trace = os.path.join(d, "cache.jsonl")
        proc, port = _start_daemon(state, env={
            "RACON_TPU_SERVE_BATCH": "16", "RACON_TPU_TRACE": trace})
        _run_job(port, inp, base)
        windows_cold = _metric(port, "racon_tpu_serve_batch_windows_total")
        assert windows_cold > 0
        _run_job(port, inp, base)
        windows_warm = _metric(port, "racon_tpu_serve_batch_windows_total")
        assert windows_warm == windows_cold, (
            f"resubmit dispatched windows: {windows_warm} != {windows_cold}")
        assert _metric(port, "racon_tpu_cache_hits_total") >= 1
        assert _metric(port, "racon_tpu_cache_hit_ratio") > 0
        _drain(proc)

        # Restarted daemon hits through the recovered index.
        proc, port = _start_daemon(state, env={
            "RACON_TPU_SERVE_BATCH": "16"})
        _run_job(port, inp, base)
        assert _metric(port, "racon_tpu_serve_batch_windows_total") == 0, \
            "restarted daemon recomputed despite a recovered CAS index"
        assert _metric(port, "racon_tpu_cache_hits_total") >= 1
        _drain(proc)

        from scripts import obs_report
        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        kinds = {s["kind"] for s in tr["spans"].values()}
        assert "cache" in kinds, kinds
        buf = io.StringIO()
        obs_report.render(tr, out=buf)
        assert "cache:" in buf.getvalue(), buf.getvalue()
        print(f"[cache-smoke] daemon resubmit byte-identical with zero "
              f"dispatches ({windows_cold:.0f} cold windows, 0 warm; "
              f"index survives restart; trace valid, cache section "
              f"renders)", flush=True)

        # --- phase 2: torn-entry poisoning drill over the warm CAS.
        proc, port = _start_daemon(state, env={
            "RACON_TPU_SERVE_BATCH": "16",
            "RACON_TPU_FAULTS": "cache/load:0!torn"})
        _run_job(port, inp, base)
        assert _metric(port, "racon_tpu_cache_verify_fail_total") >= 1, \
            "torn probe did not register a verify failure"
        assert _metric(port, "racon_tpu_serve_batch_windows_total") > 0, \
            "torn probe was served instead of recomputed"
        _drain(proc)
        print("[cache-smoke] torn entry quarantined: verify-fail "
              "counted, recompute byte-identical", flush=True)

        # --- phase 3: RACON_TPU_CACHE=0 falls back byte-identically.
        proc, port = _start_daemon(state, env={
            "RACON_TPU_SERVE_BATCH": "16", "RACON_TPU_CACHE": "0"})
        _run_job(port, inp, base)
        assert _metric(port, "racon_tpu_serve_batch_windows_total") > 0
        text = _get(port, "/metrics").decode()
        assert "racon_tpu_cache_" not in text, \
            "cache metrics recorded with RACON_TPU_CACHE=0"
        _drain(proc)
        print("[cache-smoke] RACON_TPU_CACHE=0 recomputes "
              "byte-identically, no cache accounting", flush=True)

    print("[cache-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
