"""CI smoke: tiny polish with tracing on, then validate the trace.

Runs the real CLI path (create_polisher -> polish -> FASTA out) on a
synthetic contig with --trace enabled, then checks the emitted JSONL
against the documented schema (scripts/obs_report.py --validate logic:
required keys, span nesting containment, non-negative timings) and
renders the breakdown table once so a formatting regression fails CI
rather than the next perf investigation.
"""

import contextlib
import io
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

from racon_tpu import cli                            # noqa: E402
from scripts import obs_report                       # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d):
    rng = np.random.default_rng(11)
    truth = BASES[rng.integers(0, 4, 400)]
    draft = _noisy(rng, truth)
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b">c1\n" + draft + b"\n")
    reads, paf = [], []
    for i in range(8):
        r = _noisy(rng, truth)
        reads.append(b">r%d\n%s\n" % (i, r))
        paf.append(f"r{i}\t{len(r)}\t0\t{len(r)}\t+\tc1\t{len(draft)}\t0"
                   f"\t{len(draft)}\t{min(len(r), len(draft))}"
                   f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")
    return d


def main():
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)
        trace = os.path.join(d, "trace.jsonl")
        # Exercise the env-var path (--trace covers the same configure()
        # call; tests/test_obs.py exercises the explicit-path form).
        os.environ["RACON_TPU_TRACE"] = trace

        # cli.main writes FASTA to sys.stdout.buffer; run it captured so
        # the smoke's own output stays readable.
        class _Capture(io.StringIO):
            buffer = io.BytesIO()

        stdout = _Capture()
        buf = stdout.buffer
        with contextlib.redirect_stdout(stdout):
            rc = cli.main(["--backend", "jax",
                           os.path.join(d, "reads.fasta"),
                           os.path.join(d, "ovl.paf"),
                           os.path.join(d, "draft.fasta")])
        assert rc == 0, f"cli exited {rc}"
        assert buf.getvalue().startswith(b">c1 LN:i:"), "no polished FASTA"

        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        spans = tr["spans"]
        kinds = {s["kind"] for s in spans.values()}
        for want in ("run", "phase", "chunk"):
            assert want in kinds, f"no {want!r} span in trace ({kinds})"
        assert tr["metrics"] is not None, "no metrics footer"
        assert tr["metrics"].get("h2d_bytes", 0) > 0, "no h2d accounting"
        print(f"[obs-smoke] trace ok: {len(spans)} spans, kinds={sorted(kinds)}",
              flush=True)
        obs_report.render(tr)
    print("[obs-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
