"""Measure the CPU anchor for bench.py's vs_baseline denominator.

Runs the repo's own native host consensus path (C++ adaptive-band NW via
ctypes + numpy column merge — the fastest CPU racon-equivalent available
in this image; the reference binary cannot be built here because its
vendored spoa/edlib trees are absent from the snapshot) single-threaded
on the exact bench workload, then reports an idealized 64-thread
extrapolation (perfect linear scaling — generous to the CPU, since the
reference's own window fan-out is embarrassingly parallel but its merge
is not).

Usage: python scripts/measure_cpu_anchor.py [n_windows]
Prints one JSON line: {"cpu_1t_windows_per_sec": ..., "cpu_64t_idealized":
..., "n_windows": ..., "host": ...}
"""

import json
import os
import platform
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    from bench import build_windows
    from racon_tpu.ops.poa import PoaEngine

    eng = PoaEngine(backend="native", threads=1)
    eng.consensus_windows(build_windows(8, 30, 500, seed=7))  # warm

    ws = build_windows(n, 30, 500, seed=1)
    eng = PoaEngine(backend="native", threads=1)
    t0 = time.perf_counter()
    eng.consensus_windows(ws)
    dt = time.perf_counter() - t0
    r1 = n / dt
    print(json.dumps({
        "cpu_1t_windows_per_sec": round(r1, 2),
        "cpu_64t_idealized": round(64 * r1, 1),
        "n_windows": n,
        "seconds": round(dt, 2),
        "host": platform.processor() or platform.machine(),
        "n_cores_here": os.cpu_count(),
    }))


if __name__ == "__main__":
    main()
