"""CI smoke: end-to-end job telemetry through real processes
(obs/trace.py, obs/metrics.py histograms, obs/flightrec.py,
docs/OBSERVABILITY.md "Cross-process trace propagation").

The drill: one job's trace context crosses three processes —

  daemon    mints the context at submit (trace id = spec fingerprint
            prefix, parent = the submit span), journals it, serves the
            job, exports latency histograms on /metrics, dumps its
            flight ring on drain;
  worker A  inherits the context via ``RACON_TPU_TRACE_CTX``, is
            SIGTERM'd mid-shard (``dist/contig:1!term``) — the
            teardown must leave a flight-recorder dump beside its
            final metric snapshot;
  worker B  same context, ``skew=99999``: steals A's shard, finishes,
            merges byte-identically to a telemetry-off serial run.

Gates:
- the daemon job's status carries a well-formed trace context and its
  /metrics export passes the OpenMetrics validator WITH histogram
  samples (``serve_job_latency_s_bucket``/``_count``);
- telemetry changes no bytes: daemon stream == fleet merge == serial
  CLI run with tracing/obs/handoff all unset;
- ``obs_report.py <ledger> --job <trace_id>`` stitches one timeline
  from >= 3 per-process trace files;
- the killed worker's flight dump loads and renders in that report
  (reason ``signal-15``);
- the fleet OpenMetrics render validates with the folded histogram
  series.
"""

import io
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = ("import sys; from racon_tpu import cli; "
        "sys.exit(cli.main(sys.argv[1:]))")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_CONTIGS = 6
N_SHARDS = 3

TELEMETRY_ENVS = ("RACON_TPU_FAULTS", "RACON_TPU_TRACE",
                  "RACON_TPU_TRACE_CTX", "RACON_TPU_OBS_DIR",
                  "RACON_TPU_OBS_FLUSH_S", "RACON_TPU_FLIGHT_EVENTS")


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d):
    rng = np.random.default_rng(31)
    drafts, reads, paf = [], [], []
    for c in range(N_CONTIGS):
        truth = BASES[rng.integers(0, 4, 300 + 30 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cmd(d, *extra):
    return [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
            os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
            os.path.join(d, "draft.fasta")]


def _env(**overrides):
    e = dict(os.environ)
    for k in TELEMETRY_ENVS:
        e.pop(k, None)
    e.update(overrides)
    return e


# ------------------------------------------------------------ daemon ops


def _start_daemon(state, env=None):
    e = _env(**(env or {}))
    os.makedirs(state, exist_ok=True)
    port_file = os.path.join(state, "port")
    if os.path.exists(port_file):
        os.remove(port_file)
    proc = subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.server", "--state-dir", state,
         "--port", "0"],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, env=e,
        cwd=ROOT)
    deadline = time.monotonic() + 180
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise AssertionError("daemon died on startup:\n" +
                                 proc.stderr.read().decode())
        if time.monotonic() > deadline:
            proc.kill()
            raise AssertionError("daemon never published its port")
        time.sleep(0.05)
    with open(port_file) as fh:
        port = int(fh.read().strip())
    return proc, port


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.read()


def _submit(port, tenant, d):
    body = json.dumps({
        "tenant": tenant,
        "sequences": os.path.join(d, "reads.fasta"),
        "overlaps": os.path.join(d, "ovl.paf"),
        "targets": os.path.join(d, "draft.fasta"),
        "options": {"backend": "jax"}}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/jobs", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())["id"]


def _wait_done(port, job_id, timeout_s=300):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/v1/jobs/{job_id}"))
        if status["state"] in ("done", "failed", "cancelled"):
            assert status["state"] == "done", status
            return status
        time.sleep(0.2)
    raise AssertionError(f"job {job_id} did not finish in {timeout_s}s")


def main():
    from racon_tpu.obs import export as obs_export
    from racon_tpu.obs import fleet as obs_fleet
    from racon_tpu.obs import flightrec
    from racon_tpu.obs.trace import TRACE_ID_LEN, parse_trace_ctx

    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)
        ledger = os.path.join(d, "ledger")
        obs_dir = os.path.join(ledger, obs_fleet.OBS_SUBDIR)
        os.makedirs(obs_dir)

        # Telemetry-off baseline: the bytes every telemetry-on path
        # below must still emit.
        proc = subprocess.run(_cmd(d), capture_output=True, env=_env())
        assert proc.returncode == 0, proc.stderr.decode()
        base = proc.stdout
        assert base.count(b">") == N_CONTIGS

        # --- leg 1: the daemon mints the context and exports
        # histograms.
        proc, port = _start_daemon(os.path.join(d, "state"), env={
            "RACON_TPU_TRACE": os.path.join(obs_dir, "daemon.jsonl"),
            "RACON_TPU_OBS_DIR": obs_dir})
        jid = _submit(port, "acme", d)
        status = _wait_done(port, jid)
        ctx = parse_trace_ctx(status.get("trace", ""))
        assert ctx is not None, f"job status has no trace ctx: {status}"
        assert len(ctx.trace_id) == TRACE_ID_LEN
        assert ctx.parent_id > 0, \
            "submit span id must parent the job's downstream spans"
        assert _get(port, f"/v1/jobs/{jid}/stream") == base, \
            "daemon stream differs from telemetry-off serial CLI"
        metrics_text = _get(port, "/metrics").decode()
        errs = obs_export.validate_openmetrics(metrics_text)
        assert not errs, "invalid /metrics:\n" + "\n".join(errs)
        for needle in ("racon_tpu_serve_job_latency_s_bucket{le=",
                       "racon_tpu_serve_job_latency_s_count 1",
                       "racon_tpu_serve_queue_wait_s_count 1"):
            assert needle in metrics_text, \
                f"missing histogram sample {needle!r}:\n{metrics_text}"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        assert rc == 0, proc.stderr.read().decode()
        daemon_flight = flightrec.flight_path(obs_dir, proc.pid)
        assert os.path.exists(daemon_flight), \
            "daemon drain left no flight dump"
        assert flightrec.load_flight(daemon_flight)["header"][
            "reason"] == "daemon-drain"
        print(f"[job-trace-smoke] daemon: ctx {ctx.encode()} minted, "
              f"stream byte-identical, histograms on /metrics, flight "
              f"dump on drain", flush=True)

        # --- leg 2: the handoff. Two ledger workers inherit the
        # daemon job's context through RACON_TPU_TRACE_CTX (the same
        # edge the autoscaler hands its spawns); A dies mid-shard.
        def _worker(wid, *, faults):
            return subprocess.Popen(
                _cmd(d, "--ledger-dir", ledger, "--workers", "2",
                     "--worker-id", wid),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=_env(**{
                    "RACON_TPU_DIST_SHARDS": str(N_SHARDS),
                    "RACON_TPU_OBS_FLUSH_S": "0",
                    "RACON_TPU_TRACE_CTX": ctx.encode(),
                    "RACON_TPU_TRACE": os.path.join(
                        obs_dir, f"worker_{wid}.trace.jsonl"),
                    "RACON_TPU_FAULTS": faults}))

        a = _worker("A", faults="dist/contig:1!term")
        a_out, a_err = a.communicate(timeout=300)
        assert a.returncode == 143, \
            f"A: expected SIGTERM exit 143, got {a.returncode}: " \
            f"{a_err.decode()}"
        a_flight = flightrec.flight_path(obs_dir, a.pid)
        assert os.path.exists(a_flight), \
            f"killed worker left no flight dump in {obs_dir}"
        rec = flightrec.load_flight(a_flight)
        assert rec["header"]["reason"] == "signal-15", rec["header"]
        assert rec["events"], "flight ring empty at the kill"
        print(f"[job-trace-smoke] worker A SIGTERM'd mid-shard; flight "
              f"dump holds {len(rec['events'])} event(s)", flush=True)

        b = _worker("B", faults="skew=99999")
        b_out, b_err = b.communicate(timeout=300)
        assert b.returncode == 0, b_err.decode()
        assert b_out == base, \
            "fleet merge differs from telemetry-off serial run"

        # --- leg 3: one causal timeline across all three processes.
        tl = obs_fleet.assemble_job_timeline(ledger, ctx.trace_id)
        assert tl["n_processes"] >= 3, tl["sources"]
        assert "daemon.jsonl" in tl["sources"], tl["sources"]
        assert any(s.startswith("worker_A") for s in tl["sources"])
        assert any(s.startswith("worker_B") for s in tl["sources"])
        from scripts import obs_report
        buf = io.StringIO()
        assert obs_report._render_job(ledger, ctx.trace_id,
                                      out=buf) == 0
        text = buf.getvalue()
        m = re.search(r"across (\d+) process", text)
        assert m and int(m.group(1)) >= 3, text
        assert "reason=signal-15" in text, \
            "killed worker's flight dump not rendered:\n" + text
        print(f"[job-trace-smoke] timeline: {tl['n_spans']} span(s) "
              f"across {tl['n_processes']} processes "
              f"({', '.join(sorted(tl['sources']))})", flush=True)

        # --- leg 4: fleet fold still validates with histograms in it.
        model = obs_fleet.aggregate(ledger)
        fleet_text = obs_export.render_fleet(model)
        errs = obs_export.validate_openmetrics(fleet_text)
        assert not errs, "invalid fleet render:\n" + "\n".join(errs)
        hist_families = [k for k, v in model["fleet"].items()
                        if isinstance(v, dict) and "buckets" in v]
        assert hist_families, \
            "no histogram family survived the fleet merge"
        print(f"[job-trace-smoke] fleet OpenMetrics valid; folded "
              f"histograms: {', '.join(sorted(hist_families))}",
              flush=True)

    print("[job-trace-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
