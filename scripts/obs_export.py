"""One-shot OpenMetrics emit for a fleet run (docs/OBSERVABILITY.md).

Aggregates the worker metric shards (plus ``events.jsonl``) under a
ledger / obs directory into the fleet model (racon_tpu/obs/fleet.py)
and renders it as OpenMetrics text (racon_tpu/obs/export.py)::

    python scripts/obs_export.py <ledger-or-obs-dir>            # stdout
    python scripts/obs_export.py <dir> --out metrics.prom       # file
    python scripts/obs_export.py <dir> --validate               # gate
    python scripts/obs_export.py <dir> --json                   # model

``--validate`` re-parses the rendered text with the structural
OpenMetrics checker and exits 1 on any problem — the CI smoke's gate.
``--json`` dumps the aggregated fleet model instead (the same dict the
``fleet:`` section of scripts/obs_report.py formats). For a *live*
scrape of a running worker use ``RACON_TPU_METRICS_PORT`` instead —
this script is the offline path.
"""

import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from racon_tpu.obs.export import (render_fleet,            # noqa: E402
                                  validate_openmetrics)
from racon_tpu.obs.fleet import FleetObsError, aggregate   # noqa: E402


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        try:
            out_path = argv[i + 1]
        except IndexError:
            print("obs_export: --out needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    want_validate = "--validate" in argv
    want_json = "--json" in argv
    argv = [a for a in argv if a not in ("--validate", "--json")]
    paths = [a for a in argv if not a.startswith("--")]
    if len(paths) != 1 or len(argv) != len(paths):
        print("usage: obs_export.py <ledger-or-obs-dir> "
              "[--out FILE] [--validate] [--json]", file=sys.stderr)
        return 2

    try:
        model = aggregate(paths[0])
    except FleetObsError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    if want_json:
        text = json.dumps(model, sort_keys=True, indent=2) + "\n"
    else:
        text = render_fleet(model)
        if want_validate:
            errors = validate_openmetrics(text)
            if errors:
                for e in errors:
                    print(f"obs_export: INVALID: {e}", file=sys.stderr)
                return 1
    if out_path:
        from racon_tpu.utils.atomicio import atomic_write_text
        atomic_write_text(out_path, text)
        print(f"obs_export: wrote {len(text)} bytes to {out_path}",
              file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
