"""CI smoke: fail-slow hardening, end to end through real processes
(racon_tpu/resilience/watchdog.py, docs/RESILIENCE.md "Fail-slow").

The drill, against a serial baseline:

1. **Choke-point hangs** — one serial run per device choke point
   (h2d/chunk, dispatch/chunk, d2h/chunk) with an injected ``hang``
   (sleeps past 2x the ambient deadline) and a ~3 s deadline base: the
   watchdog must convert each silent wedge into DispatchTimeout inside
   the retry ladder, the run must finish byte-identical, and the trace
   footer must count the breach.
2. **Pipeline stage hang** — streaming pipeline with a wedged ``pack``
   stage body and a 2 s stall window: the stall detector fires, dumps
   stage/queue state to stderr, and the driver re-polishes the tail on
   the host — byte-identical output, ``pipe_stall_events`` counted.
3. **Fleet self-eviction** — a 2-worker ledger fleet where worker A
   hangs at dispatch under ``RACON_TPU_WATCHDOG_TERMINAL=1``: A must
   exit EXIT_SELF_EVICT (75) well before the 60 s hang expires, leave
   an explicit ``release`` event in events.jsonl (thieves do not wait
   out the lease term), and worker B must claim, polish, and merge
   byte-identically to serial.
4. **Merge drill** — a worker SIGTERMed mid-merge-write
   (``dist/merge_write:1!term``) must leave NO out.fasta (the atomic
   writer unlinks its tmp); a successor steals the merge lease and
   re-merges byte-identically.

Zero hung processes: every subprocess is reaped with a bounded
communicate() — a wait-out anywhere fails the smoke by timeout.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"
N_CONTIGS = 4
N_SHARDS = 2
EXIT_SELF_EVICT = 75

#: Env this smoke (or an operator shell) might set — scrubbed per run.
_SCRUB = (
    "RACON_TPU_FAULTS", "RACON_TPU_TRACE", "RACON_TPU_PIPELINE",
    "RACON_TPU_STALL_S", "RACON_TPU_WATCHDOG_TERMINAL",
    "RACON_TPU_DEADLINE_H2D", "RACON_TPU_DEADLINE_D2H",
    "RACON_TPU_DEADLINE_DISPATCH", "RACON_TPU_DEADLINE_MBPS",
    "RACON_TPU_DEADLINE_CELLS_PER_S", "RACON_TPU_DEADLINE_SCALE",
    "RACON_TPU_FAULT_HANG_S", "RACON_TPU_FAULT_STALL_S",
    "RACON_TPU_SCHED",
)

#: The convergence scheduler replaces the fused all-rounds dispatch
#: with its own sched/flags + h2d/repack sites, so the dispatch/chunk
#: choke point only exists on the fixed-round path.
_SITE_ENV = {"dispatch/chunk": {"RACON_TPU_SCHED": "0"}}


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d):
    rng = np.random.default_rng(11)
    drafts, reads, paf = [], [], []
    for c in range(N_CONTIGS):
        truth = BASES[rng.integers(0, 4, 300 + 30 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cmd(d, *extra):
    return [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
            os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
            os.path.join(d, "draft.fasta")]


def _env(**overrides):
    e = dict(os.environ)
    for k in _SCRUB:
        e.pop(k, None)
    e["RACON_TPU_DIST_SHARDS"] = str(N_SHARDS)
    e.update(overrides)
    return e


def _metrics_footer(trace_path):
    with open(trace_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("ev") == "metrics":
                return rec
    raise AssertionError(f"no metrics footer in {trace_path}")


def _check_trace(trace, want_kind, want_render):
    import io

    from scripts import obs_report
    tr = obs_report.load_trace(trace)
    errs = obs_report.validate(tr)
    assert not errs, "trace schema violations:\n" + "\n".join(errs)
    assert want_kind in {s["kind"] for s in tr["spans"].values()}, \
        f"no {want_kind!r} span in {trace}"
    buf = io.StringIO()
    obs_report.render(tr, out=buf)
    assert want_render in buf.getvalue(), buf.getvalue()


def main():
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)

        # Serial baseline: the bytes every hardened run must match.
        proc = subprocess.run(_cmd(d), capture_output=True, env=_env())
        assert proc.returncode == 0, proc.stderr.decode()
        base = proc.stdout
        assert base.count(b">") == N_CONTIGS

        # ---- 1. a hang at each device choke point, watchdogged.
        for site in ("h2d/chunk", "dispatch/chunk", "d2h/chunk"):
            trace = os.path.join(d, site.replace("/", "_") + ".jsonl")
            t0 = time.monotonic()
            proc = subprocess.run(
                _cmd(d), capture_output=True, timeout=300,
                env=_env(**{
                    # Bare !hang sleeps 2x whatever deadline is armed.
                    "RACON_TPU_FAULTS": f"{site}:0!hang",
                    "RACON_TPU_DEADLINE_H2D": "3",
                    "RACON_TPU_DEADLINE_D2H": "3",
                    "RACON_TPU_DEADLINE_DISPATCH": "3",
                    "RACON_TPU_TRACE": trace,
                    **_SITE_ENV.get(site, {}),
                }))
            wall = time.monotonic() - t0
            assert proc.returncode == 0, \
                f"{site}: rc {proc.returncode}: {proc.stderr.decode()}"
            assert proc.stdout == base, \
                f"{site}: output diverged after watchdog recovery"
            m = _metrics_footer(trace)
            assert m.get("res_watchdog_breach_total", 0) >= 1, m
            _check_trace(trace, "watchdog", "watchdog: breaches=")
            print(f"[failslow-smoke] {site}: hang detected in "
                  f"{wall:.1f}s wall, retried, byte-identical "
                  f"({int(m['res_watchdog_breach_total'])} breach)",
                  flush=True)

        # ---- 2. a wedged pipeline stage body, stall-detected.
        trace = os.path.join(d, "stall.jsonl")
        proc = subprocess.run(
            _cmd(d), capture_output=True, timeout=300,
            env=_env(**{
                "RACON_TPU_PIPELINE": "1",
                "RACON_TPU_STALL_S": "2",
                "RACON_TPU_FAULTS": "pipe/pack:0!hang=8",
                "RACON_TPU_TRACE": trace,
            }))
        assert proc.returncode == 0, proc.stderr.decode()
        assert proc.stdout == base, "stall recovery diverged"
        assert b"stall detected" in proc.stderr, proc.stderr.decode()
        m = _metrics_footer(trace)
        assert m.get("pipe_stall_events", 0) >= 1, m
        _check_trace(trace, "stall", "stalls: 1 detector firing")
        print("[failslow-smoke] pipeline: pack stage wedged, stall "
              "detector fired at 2s window, host re-polish "
              "byte-identical", flush=True)

        # ---- 3. 2-worker fleet; A hangs terminally and self-evicts.
        ledger = os.path.join(d, "ledger")
        t0 = time.monotonic()
        a = subprocess.Popen(
            _cmd(d, "--ledger-dir", ledger, "--workers", "2",
                 "--worker-id", "A"),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=_env(**{
                "RACON_TPU_FAULTS": "dispatch/chunk:0!hang=60",
                "RACON_TPU_DEADLINE_DISPATCH": "3",
                "RACON_TPU_WATCHDOG_TERMINAL": "1",
                **_SITE_ENV["dispatch/chunk"],
            }))
        a_out, a_err = a.communicate(timeout=300)
        a_wall = time.monotonic() - t0
        assert a.returncode == EXIT_SELF_EVICT, \
            f"A: expected {EXIT_SELF_EVICT}, got {a.returncode}: " \
            + a_err.decode()
        assert a_out == b"", "self-evicted worker must not emit output"
        assert b"self-evicting" in a_err, a_err.decode()
        assert a_wall < 60, \
            f"A took {a_wall:.0f}s — waited out the injected hang"
        events = open(os.path.join(ledger, "events.jsonl"),
                      "rb").read().decode()
        assert '"release"' in events, \
            "no explicit lease release in events.jsonl:\n" + events

        b_trace = os.path.join(d, "b.jsonl")
        b = subprocess.Popen(
            _cmd(d, "--ledger-dir", ledger, "--workers", "2",
                 "--worker-id", "B"),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=_env(RACON_TPU_TRACE=b_trace))
        b_out, b_err = b.communicate(timeout=300)
        assert b.returncode == 0, b_err.decode()
        assert b_out == base, \
            "fleet merge differs from single-process serial run"
        assert open(os.path.join(ledger, "out.fasta"),
                    "rb").read() == base
        m = _metrics_footer(b_trace)
        assert m.get("dist_merges", 0) == 1, m
        print(f"[failslow-smoke] fleet: A self-evicted (exit 75, "
              f"{a_wall:.1f}s wall, lease released), B polished and "
              "merged byte-identical to serial", flush=True)

        # ---- 4. SIGTERM mid-merge-write: no partial out.fasta, the
        # successor re-merges byte-identically.
        ledger2 = os.path.join(d, "ledger2")
        w1 = subprocess.run(
            _cmd(d, "--ledger-dir", ledger2, "--workers", "1",
                 "--worker-id", "W1"),
            capture_output=True, timeout=300,
            env=_env(RACON_TPU_FAULTS="dist/merge_write:1!term"))
        assert w1.returncode == 143, \
            f"W1: expected 143, got {w1.returncode}: " \
            + w1.stderr.decode()
        assert not os.path.exists(os.path.join(ledger2, "out.fasta")), \
            "merge victim left a partial out.fasta"
        w2 = subprocess.run(
            _cmd(d, "--ledger-dir", ledger2, "--workers", "1",
                 "--worker-id", "W2"),
            capture_output=True, timeout=300,
            env=_env(RACON_TPU_FAULTS="skew=9999"))
        assert w2.returncode == 0, w2.stderr.decode()
        assert w2.stdout == base, "re-merge diverged"
        assert open(os.path.join(ledger2, "out.fasta"),
                    "rb").read() == base
        print("[failslow-smoke] merge drill: SIGTERM mid-write left no "
              "partial output; successor re-merged byte-identical",
              flush=True)

    print("[failslow-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
