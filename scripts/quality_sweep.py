"""Quality experiment harness: lambda golden EDs vs engine knobs.

Runs the four reference acceptance configs (PAF/SAM x FASTQ/FASTA)
through the full polisher on the current backend and prints the edit
distance vs NC_001416 for each, for every knob combination given.

Usage:
  python scripts/quality_sweep.py                  # current defaults
  python scripts/quality_sweep.py 0.3:1.0 0.25:0.6 # ins_scale:final
Each arg is base[:final] — one setting for both weight regimes, so the
sweep tests derivation hypotheses, not per-regime fitting.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD = {
    ("sample_reads.fastq.gz", "sample_overlaps.paf.gz"): 1312,
    ("sample_reads.fasta.gz", "sample_overlaps.paf.gz"): 1566,
    ("sample_reads.fastq.gz", "sample_overlaps.sam.gz"): 1317,
    ("sample_reads.fasta.gz", "sample_overlaps.sam.gz"): 1770,
}


def edit_distance(a, b):
    from racon_tpu.native.aligner import NativeAligner
    from racon_tpu.ops.encode import encode_bases
    ops = NativeAligner().align(a, b)
    qa, ta = encode_bases(a), encode_bases(b)
    qi = ti = ed = 0
    for d in ops:
        if d == 0:
            ed += int(qa[qi] != ta[ti])
            qi += 1
            ti += 1
        else:
            ed += 1
            qi += d == 1
            ti += d == 2
    return ed


def main():
    from racon_tpu.models.polisher import create_polisher, PolisherType
    from racon_tpu.ops.encode import reverse_complement
    from racon_tpu.io.parsers import FastaParser

    D = "/root/reference/test/data/"
    ref = FastaParser(D + "sample_reference.fasta.gz").parse_all()[0].data

    combos = []
    for a in sys.argv[1:]:
        parts = a.split(":")
        combos.append((float(parts[0]),
                       float(parts[1]) if len(parts) > 1 else None))
    if not combos:
        combos = [(None, None)]

    for base, final in combos:
        print(f"--- ins_scale={base} final={final}", flush=True)
        for (reads, ovl), gold in GOLD.items():
            p = create_polisher(D + reads, D + ovl,
                                D + "sample_layout.fasta.gz",
                                PolisherType.kC, 500, 10, 0.3, 5, -4, -8,
                                backend="jax")
            if base is not None:
                p.engine.ins_scale = base
                p.engine.ins_scale_final = final
            p.initialize()
            out = p.polish(True)
            ed = edit_distance(reverse_complement(out[0].data), ref)
            tag = "FASTQ" if "fastq" in reads else "FASTA"
            o = "PAF" if "paf" in ovl else "SAM"
            print(f"  {o}+{tag}: ED {ed} (golden {gold}, "
                  f"{'BEAT' if ed <= gold else f'+{ed - gold}'})",
                  flush=True)


if __name__ == "__main__":
    main()
