"""CI smoke: preemption-tolerant sharded execution, end to end through
real processes (racon_tpu/distributed/, docs/DISTRIBUTED.md).

The drill: 6 contigs in 3 shards, a 2-worker fleet, **three injected
evictions** across two waves —

wave 1 (concurrent):
  worker A  ``dist/contig:1!kill``   hard-killed mid-shard, after
                                     committing exactly one contig;
  worker B  ``ckpt/manifest:0!term`` SIGTERM in the mid-commit window
                                     (shard bytes durable, manifest
                                     record not) — exits 143 leaving
                                     orphaned shard bytes;
wave 2 (sequential):
  worker A2 ``skew=9999;dist/shard:0!kill``
                                     steals a dead worker's shard and
                                     is immediately killed — eviction
                                     during recovery itself;
  worker B2 ``skew=99999``           the survivor: steals everything
                                     (its skew outruns A2's inflated
                                     lease deadlines), resumes every
                                     committed prefix, finishes, and
                                     merges.

Gates:
- B2's merged stdout is **byte-identical** to a single-process serial
  run (the headline guarantee);
- zero committed contigs re-polished: every target id appears exactly
  once across the shard manifests;
- only the merge winner emitted stdout;
- dist_* accounting in B2's trace footer (shards stolen, contigs
  resumed) and a schema-valid trace whose report renders the
  distributed section.

Subprocesses (not in-process cli.main) so kills are real hard exits,
each worker's env-gated injector and lease clock arm independently,
and the ledger really is crossing process boundaries.
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"
N_CONTIGS = 6
N_SHARDS = 3


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d):
    rng = np.random.default_rng(11)
    drafts, reads, paf = [], [], []
    for c in range(N_CONTIGS):
        truth = BASES[rng.integers(0, 4, 300 + 30 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cmd(d, *extra):
    return [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
            os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
            os.path.join(d, "draft.fasta")]


def _env(**overrides):
    e = dict(os.environ)
    for k in ("RACON_TPU_FAULTS", "RACON_TPU_TRACE"):
        e.pop(k, None)
    e["RACON_TPU_DIST_SHARDS"] = str(N_SHARDS)
    e.update(overrides)
    return e


def _worker(d, ledger, wid, *, faults=None, trace=None):
    env = {}
    if faults:
        env["RACON_TPU_FAULTS"] = faults
    if trace:
        env["RACON_TPU_TRACE"] = trace
    return subprocess.Popen(
        _cmd(d, "--ledger-dir", ledger, "--workers", "2",
             "--worker-id", wid),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=_env(**env))


def _metrics_footer(trace_path):
    with open(trace_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("ev") == "metrics":
                return rec
    raise AssertionError(f"no metrics footer in {trace_path}")


def main():
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)

        # Serial baseline: the bytes every distributed run must match.
        proc = subprocess.run(_cmd(d), capture_output=True, env=_env())
        assert proc.returncode == 0, proc.stderr.decode()
        base = proc.stdout
        assert base.count(b">") == N_CONTIGS

        ledger = os.path.join(d, "ledger")

        # ---- wave 1: two workers, two evictions, concurrent.
        a = _worker(d, ledger, "A", faults="dist/contig:1!kill")
        b = _worker(d, ledger, "B", faults="ckpt/manifest:0!term")
        a_out, a_err = a.communicate(timeout=300)
        b_out, b_err = b.communicate(timeout=300)
        assert a.returncode == 137, \
            f"A: expected hard kill 137, got {a.returncode}: {a_err.decode()}"
        assert b.returncode == 143, \
            f"B: expected SIGTERM exit 143, got {b.returncode}: {b_err.decode()}"
        assert a_out == b"" and b_out == b"", \
            "evicted workers must not have emitted output"
        print("[preemption-smoke] wave 1: A killed mid-shard (137), "
              "B terminated mid-commit (143)", flush=True)

        # ---- wave 2: recovery. A2 steals a shard and dies instantly
        # (third eviction); B2 then outruns every stale lease and
        # finishes the run alone.
        a2 = _worker(d, ledger, "A2",
                     faults="skew=9999;dist/shard:0!kill")
        a2_out, a2_err = a2.communicate(timeout=300)
        assert a2.returncode == 137, \
            f"A2: expected 137, got {a2.returncode}: {a2_err.decode()}"
        assert a2_out == b""

        trace = os.path.join(d, "b2.jsonl")
        b2 = _worker(d, ledger, "B2", faults="skew=99999", trace=trace)
        b2_out, b2_err = b2.communicate(timeout=300)
        assert b2.returncode == 0, b2_err.decode()

        # The headline gate: byte-identical to the serial path.
        assert b2_out == base, \
            "merged FASTA differs from single-process serial run"
        assert open(os.path.join(ledger, "out.fasta"),
                    "rb").read() == base
        print("[preemption-smoke] wave 2: survivor stole remaining "
              "shards, merged FASTA byte-identical to serial",
              flush=True)

        # Zero committed contigs re-polished: each target id committed
        # exactly once across the shard manifests.
        tids = []
        for k in range(N_SHARDS):
            man = os.path.join(ledger, f"shard_{k}", "manifest.jsonl")
            for line in open(man, "rb").read().splitlines():
                rec = json.loads(line)
                if rec.get("ev") == "contig":
                    tids.append(rec["tid"])
        assert sorted(tids) == list(range(N_CONTIGS)), \
            f"committed contig re-polished or missing: {sorted(tids)}"

        # dist_* accounting in the survivor's trace footer.
        m = _metrics_footer(trace)
        assert m.get("dist_shards_stolen", 0) >= 2, m
        assert m.get("dist_contigs_resumed", 0) >= 1, m
        assert m.get("dist_merges", 0) == 1, m

        # Trace schema (dist spans carry shard+worker) and report.
        import io
        from scripts import obs_report
        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        assert "dist" in {s["kind"] for s in tr["spans"].values()}
        buf = io.StringIO()
        obs_report.render(tr, out=buf)
        assert "distributed:" in buf.getvalue(), buf.getvalue()
        print(f"[preemption-smoke] survivor stole "
              f"{int(m['dist_shards_stolen'])} shard(s), resumed "
              f"{int(m['dist_contigs_resumed'])} committed contig(s), "
              "repolished none (trace valid, report renders "
              "distributed section)", flush=True)

    print("[preemption-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
