"""CI smoke: the resilience subsystem's two headline guarantees, end to
end through real processes.

1. **Fault absorption**: with ``RACON_TPU_FAULTS`` injecting three
   transfer faults (``h2d/chunk:0,1,2``) the run completes with
   byte-identical FASTA and ``res_retry_total >= 3`` in the trace's
   metrics footer; with a permanent fault (``p=1.0``) every device
   chunk degrades to the host path — output still byte-identical.
2. **Kill-and-resume**: a run killed mid-commit (``ckpt/commit:1!kill``
   → ``os._exit(137)``, no cleanup) leaves a usable checkpoint;
   ``--resume`` re-emits the committed contig from the shard, computes
   the rest, and the resumed stdout is byte-identical to an
   uninterrupted run's.

Subprocesses (not in-process cli.main) so the kill is a real hard exit
and each run's env-gated injector arms independently.
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d, n_contigs=3):
    rng = np.random.default_rng(11)
    drafts, reads, paf = [], [], []
    for c in range(n_contigs):
        truth = BASES[rng.integers(0, 4, 300 + 40 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _run(d, *extra, env=None):
    e = dict(os.environ)
    e.pop("RACON_TPU_FAULTS", None)
    e.pop("RACON_TPU_TRACE", None)
    e.update(env or {})
    proc = subprocess.run(
        [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
         os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
         os.path.join(d, "draft.fasta")],
        capture_output=True, env=e)
    return proc.returncode, proc.stdout, proc.stderr.decode()


def _metrics_footer(trace_path):
    with open(trace_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("ev") == "metrics":
                return rec
    raise AssertionError(f"no metrics footer in {trace_path}")


def main():
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)

        rc, base, err = _run(d)
        assert rc == 0, err
        assert base.count(b">") == 3, "expected 3 polished contigs"

        # --- transient faults: 3 injected h2d failures, fully absorbed.
        trace = os.path.join(d, "faults.jsonl")
        rc, out, err = _run(d, env={
            "RACON_TPU_FAULTS": "h2d/chunk:0,1,2",
            "RACON_TPU_RETRY": "base=0.001",
            "RACON_TPU_TRACE": trace})
        assert rc == 0, err
        assert out == base, "faulted run's FASTA differs"
        m = _metrics_footer(trace)
        assert m.get("res_retry_total", 0) >= 3, m
        assert m.get("res_fault_injected_total", 0) >= 3, m
        # The retry/fault spans must satisfy the documented per-kind
        # attr contract, and the report must render its resilience
        # section from them.
        import io
        from scripts import obs_report
        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        kinds = {s["kind"] for s in tr["spans"].values()}
        assert "retry" in kinds and "fault" in kinds, kinds
        buf = io.StringIO()
        obs_report.render(tr, out=buf)
        assert "resilience:" in buf.getvalue(), buf.getvalue()
        print(f"[resilience-smoke] absorbed "
              f"{int(m['res_fault_injected_total'])} faults with "
              f"{int(m['res_retry_total'])} retries (trace valid, "
              "report renders resilience section)", flush=True)

        # --- permanent fault: every chunk degrades to the host path.
        trace = os.path.join(d, "degrade.jsonl")
        rc, out, err = _run(d, env={
            "RACON_TPU_FAULTS": "h2d/chunk:p=1.0",
            "RACON_TPU_RETRY": "attempts=2,base=0.001",
            "RACON_TPU_TRACE": trace})
        assert rc == 0, err
        assert out == base, "degraded run's FASTA differs"
        m = _metrics_footer(trace)
        assert m.get("res_degraded_windows", 0) >= 1, m
        print(f"[resilience-smoke] degraded "
              f"{int(m['res_degraded_windows'])} windows to host path, "
              "output identical", flush=True)

        # --- kill mid-commit, then resume: byte-identical stdout.
        ck = os.path.join(d, "ckpt")
        rc, _, err = _run(d, "--checkpoint-dir", ck, env={
            "RACON_TPU_FAULTS": "ckpt/commit:1!kill"})
        assert rc == 137, f"expected hard kill (137), got {rc}: {err}"
        man = os.path.join(ck, "manifest.jsonl")
        committed = sum(1 for line in open(man)
                        if json.loads(line).get("ev") == "contig")
        assert committed == 1, f"expected 1 committed contig, {committed}"

        rc, out, err = _run(d, "--checkpoint-dir", ck, "--resume")
        assert rc == 0, err
        assert out == base, "kill-and-resume stdout differs from " \
            "uninterrupted run"
        assert "resuming: 1 contig(s)" in err, err
        print("[resilience-smoke] kill-and-resume byte-identical "
              f"({committed} contig from shard, 2 recomputed)", flush=True)

    print("[resilience-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
