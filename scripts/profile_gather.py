"""Micro-bench: TPU gather cost shapes the extract_votes redesign.

Times (a) the monotone compare-reduce (F tensor), (b) one
take_along_axis gather [B,P] <- [B,S], (c) a stacked gather
[B,P,C] <- [B,S,C], (d) C separate gathers — to learn whether gather
cost is per-call or per-element on this stack.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

B, S, P, C = 2048, 1408, 770, 8


def t(fn, *args, reps=3):
    out = np.asarray(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = np.asarray(fn(*args))
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    X = jnp.asarray(np.sort(rng.integers(-1, P, (B, S)), axis=1)
                    .astype(np.int32))
    vg = jnp.asarray(np.tile(np.arange(P, dtype=np.int32), (B, 1)))
    a = jnp.asarray(rng.random((B, S)).astype(np.float32))
    aC = jnp.asarray(rng.random((B, S, C)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, S, (B, P)).astype(np.int32))
    idx_mono = jnp.asarray(np.sort(rng.integers(0, S, (B, P)), axis=1)
                           .astype(np.int32))

    @jax.jit
    def f_compare(X, vg):
        return jnp.sum(X[:, :, None] < vg[:, None, :], axis=1,
                       dtype=jnp.int32)

    @jax.jit
    def g_one(a, idx):
        return jnp.sum(jnp.take_along_axis(a, idx, axis=1))

    @jax.jit
    def g_stack(aC, idx):
        out = jnp.take_along_axis(aC, idx[:, :, None], axis=1)
        return jnp.sum(out)

    @jax.jit
    def g_sep(aC, idx):
        s = 0.0
        for c in range(C):
            s += jnp.sum(jnp.take_along_axis(aC[:, :, c], idx, axis=1))
        return s

    @jax.jit
    def g_onehot_mm(a, vg, X):
        oh = (X[:, :, None] == vg[:, None, :]).astype(jnp.bfloat16)
        return jnp.sum(jnp.einsum("bs,bsp->bp", a.astype(jnp.bfloat16),
                                  oh, precision=jax.lax.Precision.DEFAULT))

    print(f"backend={jax.default_backend()} B={B} S={S} P={P} C={C}",
          flush=True)
    print(f"compare-reduce F [B,S,P]: {t(f_compare, X, vg)*1e3:.1f} ms",
          flush=True)
    print(f"gather x1   [B,P]<-[B,S]: {t(g_one, a, idx)*1e3:.1f} ms",
          flush=True)
    print(f"gather x1 monotone idx  : {t(g_one, a, idx_mono)*1e3:.1f} ms",
          flush=True)
    print(f"gather stacked [B,P,{C}] : {t(g_stack, aC, idx)*1e3:.1f} ms",
          flush=True)
    print(f"gather separate x{C}     : {t(g_sep, aC, idx)*1e3:.1f} ms",
          flush=True)
    print(f"onehot-matmul alternative: {t(g_onehot_mm, a, vg, X)*1e3:.1f} ms",
          flush=True)


if __name__ == "__main__":
    main()
