"""Held-out validation for ins_scale settings chosen on the 4 primary
lambda configs: w=1000 golden, (1,-1,-1)-scoring golden, and the
fragment-correction totals. Guards against fitting the acceptance set.

Usage: python scripts/quality_holdout.py 0.2:0.6 0.15:0.6
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from quality_sweep import edit_distance  # noqa: E402


def main():
    from racon_tpu.models.polisher import create_polisher, PolisherType
    from racon_tpu.ops.encode import reverse_complement
    from racon_tpu.io.parsers import FastaParser

    D = "/root/reference/test/data/"
    ref = FastaParser(D + "sample_reference.fasta.gz").parse_all()[0].data

    def mk(reads, ovl, type_=PolisherType.kC, window=500,
           scores=(5, -4, -8), base=None, final=None, target=None):
        p = create_polisher(D + reads, D + ovl,
                            D + (target or "sample_layout.fasta.gz"),
                            type_, window, 10, 0.3, *scores,
                            backend="jax")
        if base is not None:
            p.engine.ins_scale = base
            p.engine.ins_scale_final = final
        p.initialize()
        return p.polish(type_ == PolisherType.kC)

    for a in sys.argv[1:]:
        parts = a.split(":")
        base = float(parts[0])
        final = float(parts[1]) if len(parts) > 1 else None
        print(f"--- ins_scale={base} final={final}", flush=True)

        out = mk("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                 window=1000, base=base, final=final)
        ed = edit_distance(reverse_complement(out[0].data), ref)
        print(f"  w=1000: ED {ed} (golden 1289)", flush=True)

        out = mk("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                 scores=(1, -1, -1), base=base, final=final)
        ed = edit_distance(reverse_complement(out[0].data), ref)
        print(f"  scores(1,-1,-1): ED {ed} (golden 1321)", flush=True)

        out = mk("sample_reads.fastq.gz", "sample_ava_overlaps.paf.gz",
                 scores=(1, -1, -1), base=base, final=final,
                 target="sample_reads.fastq.gz")
        total = sum(len(s.data) for s in out)
        print(f"  kC-ava: {len(out)} seqs / {total} bp "
              f"(golden 39 / 389,394; ratio {total / 389394:.4f})",
              flush=True)

        out = mk("sample_reads.fastq.gz", "sample_ava_overlaps.paf.gz",
                 type_=PolisherType.kF, scores=(1, -1, -1), base=base,
                 final=final, target="sample_reads.fastq.gz")
        out = [s for s in out]
        total = sum(len(s.data) for s in out)
        print(f"  kF-ava: {len(out)} seqs / {total} bp "
              f"(golden 236 / 1,658,216; ratio {total / 1658216:.4f})",
              flush=True)


if __name__ == "__main__":
    main()
