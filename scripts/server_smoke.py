"""CI smoke: polishing-as-a-service, end to end through a real daemon.

1. **Byte-identity**: every job served by the daemon — solo or packed
   into cross-request batches with other tenants' jobs — streams FASTA
   byte-identical to a solo serial CLI run of the same inputs.
2. **Cross-request occupancy**: three concurrent jobs from two tenants
   share dispatches, so mean batch occupancy strictly exceeds the
   one-job-at-a-time occupancy of the same workload.
3. **Clean drain**: SIGTERM lets in-flight jobs finish and exits 0.
4. **Kill-and-restart**: a daemon hard-killed mid-job
   (``serve/commit:1!kill`` → ``os._exit(137)``) restarts, re-queues
   the journaled job, re-emits the committed prefix from its store, and
   finishes byte-identical.

Subprocesses (not in-process PolishServer) so the kill is a real hard
exit and each daemon's env-gated knobs arm independently.
"""

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d, n_contigs=3, seed=11):
    rng = np.random.default_rng(seed)
    drafts, reads, paf = [], [], []
    for c in range(n_contigs):
        truth = BASES[rng.integers(0, 4, 300 + 40 * c)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _solo_cli(d):
    e = dict(os.environ)
    e.pop("RACON_TPU_FAULTS", None)
    e.pop("RACON_TPU_TRACE", None)
    proc = subprocess.run(
        [sys.executable, "-c", BOOT, "--backend", "jax",
         os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
         os.path.join(d, "draft.fasta")],
        capture_output=True, env=e, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr.decode()
    return proc.stdout


# ------------------------------------------------------------ daemon ops


def _start_daemon(state, env=None):
    e = dict(os.environ)
    e.pop("RACON_TPU_FAULTS", None)
    e.pop("RACON_TPU_TRACE", None)
    e.update(env or {})
    os.makedirs(state, exist_ok=True)
    port_file = os.path.join(state, "port")
    if os.path.exists(port_file):
        os.remove(port_file)
    proc = subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.server", "--state-dir", state,
         "--port", "0"],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, env=e,
        cwd=ROOT)
    deadline = time.monotonic() + 180
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise AssertionError("daemon died on startup:\n" +
                                 proc.stderr.read().decode())
        if time.monotonic() > deadline:
            proc.kill()
            raise AssertionError("daemon never published its port")
        time.sleep(0.05)
    with open(port_file) as fh:
        port = int(fh.read().strip())
    return proc, port


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.read()


def _submit(port, tenant, d):
    body = json.dumps({
        "tenant": tenant,
        "sequences": os.path.join(d, "reads.fasta"),
        "overlaps": os.path.join(d, "ovl.paf"),
        "targets": os.path.join(d, "draft.fasta"),
        "options": {"backend": "jax"}}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/jobs", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())["id"]


def _wait_done(port, job_id, timeout_s=300):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/v1/jobs/{job_id}"))
        if status["state"] in ("done", "failed", "cancelled"):
            assert status["state"] == "done", status
            return
        time.sleep(0.2)
    raise AssertionError(f"job {job_id} did not finish in {timeout_s}s")


def _occupancy(port):
    text = _get(port, "/metrics").decode()
    m = re.search(r"^racon_tpu_serve_batch_occupancy (\S+)$", text,
                  re.MULTILINE)
    assert m, "serve_batch_occupancy not exported:\n" + text
    return float(m.group(1))


def _drain(proc):
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=120)
    assert rc == 0, ("daemon drain not clean (rc {}):\n".format(rc) +
                     proc.stderr.read().decode())


def main():
    with tempfile.TemporaryDirectory() as d:
        dirs = [os.path.join(d, f"in{i}") for i in range(3)]
        for i, di in enumerate(dirs):
            _write_inputs(di, seed=11 + 11 * i)
        bases = [_solo_cli(di) for di in dirs]
        assert all(b.count(b">") == 3 for b in bases)
        tenants = ["acme", "acme", "umbrella"]

        # --- phase 1: one job at a time (the occupancy baseline).
        proc, port = _start_daemon(os.path.join(d, "s1"),
                                   env={"RACON_TPU_SERVE_BATCH": "16"})
        jids = []
        for tenant, di in zip(tenants, dirs):
            jid = _submit(port, tenant, di)
            _wait_done(port, jid)
            jids.append(jid)
        occ_solo = _occupancy(port)
        for jid, base in zip(jids, bases):
            assert _get(port, f"/v1/jobs/{jid}/stream") == base, \
                f"solo-phase job {jid} differs from serial CLI"
        _drain(proc)
        print(f"[server-smoke] sequential: 3 jobs byte-identical, "
              f"occupancy {occ_solo:.4f}, SIGTERM drain clean",
              flush=True)

        # --- phase 2: 3 concurrent jobs, 2 tenants, shared dispatches.
        trace = os.path.join(d, "serve.jsonl")
        proc, port = _start_daemon(os.path.join(d, "s2"), env={
            "RACON_TPU_SERVE_BATCH": "16",
            # Generous staging window so the jobs' chunks actually
            # co-ride despite initialize() skew between them.
            "RACON_TPU_SERVE_BATCH_WAIT_S": "15",
            "RACON_TPU_TRACE": trace})
        jids = [_submit(port, tenant, di)
                for tenant, di in zip(tenants, dirs)]
        for jid in jids:
            _wait_done(port, jid)
        occ_conc = _occupancy(port)
        health = json.loads(_get(port, "/healthz"))
        assert health["status"] == "ok", health
        assert len(health["serve"]["jobs"]) == 3, health
        for jid, base in zip(jids, bases):
            assert _get(port, f"/v1/jobs/{jid}/stream") == base, \
                f"concurrent job {jid} differs from serial CLI"
        _drain(proc)
        assert occ_conc > occ_solo, (
            f"cross-request batching did not raise occupancy: "
            f"concurrent {occ_conc:.4f} <= solo {occ_solo:.4f}")
        # The daemon's trace must satisfy the serve span contract and
        # the report must render its server section from it.
        import io
        from scripts import obs_report
        tr = obs_report.load_trace(trace)
        errs = obs_report.validate(tr)
        assert not errs, "trace schema violations:\n" + "\n".join(errs)
        kinds = {s["kind"] for s in tr["spans"].values()}
        assert "serve" in kinds, kinds
        buf = io.StringIO()
        obs_report.render(tr, out=buf)
        assert "server:" in buf.getvalue(), buf.getvalue()
        print(f"[server-smoke] concurrent: 3 jobs / 2 tenants "
              f"byte-identical, occupancy {occ_conc:.4f} > "
              f"{occ_solo:.4f} (trace valid, report renders server "
              f"section)", flush=True)

        # --- phase 3: hard kill mid-job, restart, resume to identity.
        state = os.path.join(d, "s3")
        proc, port = _start_daemon(state, env={
            "RACON_TPU_FAULTS": "serve/commit:1!kill"})
        jid = _submit(port, "acme", dirs[0])
        rc = proc.wait(timeout=300)
        assert rc == 137, f"expected hard kill (137), got {rc}"
        man = os.path.join(state, "jobs", jid, "ckpt", "manifest.jsonl")
        committed = sum(1 for line in open(man)
                        if json.loads(line).get("ev") == "contig")
        assert committed == 1, f"expected 1 committed contig, {committed}"

        proc, port = _start_daemon(state)
        _wait_done(port, jid)
        assert _get(port, f"/v1/jobs/{jid}/stream") == bases[0], \
            "kill-and-restart stream differs from serial CLI"
        metrics_text = _get(port, "/metrics").decode()
        assert "racon_tpu_serve_jobs_resumed_total 1" in metrics_text
        _drain(proc)
        print("[server-smoke] kill-and-restart byte-identical "
              f"({committed} contig from shard, 2 recomputed)",
              flush=True)

    print("[server-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
