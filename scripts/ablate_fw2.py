"""Second-level ablation: where do the non-kernel forward costs live?

Times, with chained reps + scalar consume:
  tband   — the pre-shifted target gather build (take over flat anchors)
  kernel  — fw_dirs_band alone (production kernel)
  k+tb    — kernel + banded traceback
  sumdirs — kernel + jnp.sum(dirs) (profile_engine's consume, to correct
            its stage attribution)
  votes sub-stages — cumsums / count / gathers 1-4 / channels, each as a
            prefix of extract_votes.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from racon_tpu.ops.pallas.band_kernel import (fw_dirs_band,
                                              fw_traceback_band)
from racon_tpu.ops.flat import PAD_OP
from racon_tpu.ops.cigar import UP, LEFT


def timeit(fn, *args, reps=4):
    out = fn(*args)
    jax.tree.map(np.asarray, out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.tree.map(np.asarray, out)
    return (time.perf_counter() - t0) / reps


def main():
    B, Lq, W, LA = 3072, 640, 384, 768
    steps = Lq + LA
    M, X, G = 5, -4, -8
    n_win = 96
    rng = np.random.default_rng(0)
    flat = jnp.asarray(rng.integers(0, 4, (n_win + 1) * LA).astype(np.uint8))
    win = jnp.asarray(np.repeat(np.arange(n_win + 1), 32)[:B].astype(np.int32))
    t_off = jnp.zeros(B, jnp.int32)
    klo = jnp.full(B, -192, jnp.int32)
    lq = jnp.full(B, 500, jnp.int32)
    lt = jnp.full(B, 500, jnp.int32)
    qT = jnp.asarray(rng.integers(0, 4, (Lq, B)).astype(np.uint8))

    @jax.jit
    def build_tband():
        y = jnp.arange(W + Lq, dtype=jnp.int32)[None, :]
        rel = klo[:, None] + y
        okb = (rel >= 0) & (rel < lt[:, None])
        gidxb = (win[:, None] * LA + jnp.clip(t_off[:, None] + rel, 0,
                                              LA - 1))
        return jnp.where(okb, jnp.take(flat, gidxb), 7).astype(jnp.uint8)

    tband = build_tband()
    np.asarray(tband)

    print(f"tband build : {timeit(lambda: jnp.sum(build_tband(), dtype=jnp.int32)) * 1e3:7.1f} ms", flush=True)

    @jax.jit
    def kern(tband):
        dirs, nxt, hlast = fw_dirs_band(tband, qT, klo, lq, match=M,
                                        mismatch=X, gap=G, W=W)
        return jnp.sum(hlast) + jnp.sum(dirs[0, 0].astype(jnp.int32))

    print(f"kernel      : {timeit(kern, tband) * 1e3:7.1f} ms", flush=True)

    @jax.jit
    def kern_tb(tband):
        dirs, nxt, hlast = fw_dirs_band(tband, qT, klo, lq, match=M,
                                        mismatch=X, gap=G, W=W)
        rev = fw_traceback_band(dirs, lq, lt, klo, steps, transposed=True)
        return jnp.sum(rev, dtype=jnp.int32) + jnp.sum(hlast)

    print(f"kernel+tb   : {timeit(kern_tb, tband) * 1e3:7.1f} ms", flush=True)

    @jax.jit
    def kern_tb_flip(tband):
        dirs, nxt, hlast = fw_dirs_band(tband, qT, klo, lq, match=M,
                                        mismatch=X, gap=G, W=W)
        rev = fw_traceback_band(dirs, lq, lt, klo, steps, transposed=True)
        ops = jnp.flip(rev, axis=1)
        return jnp.sum(ops[:, 0], dtype=jnp.int32) + jnp.sum(hlast)

    print(f"k+tb+flip   : {timeit(kern_tb_flip, tband) * 1e3:7.1f} ms",
          flush=True)

    @jax.jit
    def kern_sum(tband):
        dirs, nxt, hlast = fw_dirs_band(tband, qT, klo, lq, match=M,
                                        mismatch=X, gap=G, W=W)
        return jnp.sum(dirs, dtype=jnp.int32) + jnp.sum(hlast)

    print(f"kernel+sumd : {timeit(kern_sum, tband) * 1e3:7.1f} ms",
          flush=True)

    # ---- extract_votes sub-stages ----------------------------------------
    rev = np.asarray(jax.jit(lambda tb: fw_traceback_band(
        fw_dirs_band(tb, qT, klo, lq, match=M, mismatch=X, gap=G, W=W)[0],
        lq, lt, klo, steps, transposed=True))(tband))
    ops = jnp.asarray(np.flip(rev, axis=1))
    q = jnp.asarray(np.asarray(qT).T.copy())
    qw = jnp.asarray(rng.integers(8, 25, (B, Lq)).astype(np.float32))
    w_read = jnp.asarray(np.full(B, 15.0, np.float32))

    from racon_tpu.ops.pallas.count_kernel import monotone_count_pallas

    S = ops.shape[1]

    def votes_prefix(upto):
        @jax.jit
        def f(ops, q, qw):
            valid = ops != PAD_OP
            tcons = valid & (ops != UP)
            qcons = valid & (ops != LEFT)
            ct = jnp.cumsum(tcons, axis=1, dtype=jnp.int32)
            cq = jnp.cumsum(qcons, axis=1, dtype=jnp.int32)
            ct_excl = ct - tcons
            cq_excl = cq - qcons
            X_ = jnp.where(valid, ct_excl, -1)
            if upto == "cumsum":
                return (jnp.sum(X_[:, 0]) + jnp.sum(cq_excl[:, 0]))
            Xs = X_ + t_off[:, None]
            F = monotone_count_pallas(Xs, LA + 2)
            if upto == "count":
                return jnp.sum(F[:, 0]) + jnp.sum(cq_excl[:, 0])
            ops32 = ops.astype(jnp.int32)
            stack_s = jnp.stack(
                [jnp.concatenate([cq_excl, cq_excl[:, -1:]], axis=1),
                 jnp.concatenate([cq_excl[:, :1], cq_excl], axis=1),
                 jnp.concatenate([ops32[:, :1], ops32], axis=1)],
                axis=-1)
            G1 = jnp.take_along_axis(
                stack_s, jnp.clip(F, 0, S)[:, :, None], axis=1)
            if upto == "g1":
                return jnp.sum(G1[:, 0], dtype=jnp.float32).astype(jnp.int32)
            qstart = G1[:, :-1, 0]
            qi = G1[:, 1:, 1]
            stack_qi = jnp.stack([q.astype(jnp.float32), qw], axis=-1)
            Gqi = jnp.take_along_axis(
                stack_qi, jnp.clip(qi, 0, Lq - 1)[:, :, None], axis=1)
            if upto == "g2":
                return jnp.sum(Gqi[:, 0]).astype(jnp.int32)
            from racon_tpu.ops.device_merge import K_INS
            qwcum = jnp.concatenate(
                [jnp.zeros((B, 1), jnp.float32), jnp.cumsum(qw, axis=1)],
                axis=1)
            qx = q.astype(jnp.int32)
            qx_pad = jnp.concatenate(
                [qx, jnp.repeat(qx[:, -1:], K_INS - 1, axis=1)], axis=1)
            qw_pad = jnp.concatenate(
                [qw, jnp.repeat(qw[:, -1:], K_INS - 1, axis=1)], axis=1)
            chans = ([qx_pad[:, k:k + Lq].astype(jnp.float32)
                      for k in range(K_INS)] +
                     [qw_pad[:, k:k + Lq] for k in range(K_INS)] +
                     [qwcum[:, :Lq]])
            stack_qs = jnp.stack(chans, axis=-1)
            Gqs = jnp.take_along_axis(
                stack_qs, jnp.clip(qstart, 0, Lq - 1)[:, :, None], axis=1)
            return jnp.sum(Gqs[:, 0]).astype(jnp.int32)
        return f

    for upto in ("cumsum", "count", "g1", "g2", "g3"):
        dt = timeit(votes_prefix(upto), ops, q, qw)
        print(f"votes/{upto:7s}: {dt * 1e3:7.1f} ms", flush=True)

    # full extract_votes for reference
    from racon_tpu.ops import device_merge as dm

    @jax.jit
    def votes_full(ops, q, qw):
        v = dm.extract_votes(ops, q, qw, w_read, lt, t_off, LA, pallas=True)
        return sum(jnp.sum(x[:, 0]) for x in v.values()).astype(jnp.int32)

    print(f"votes/full   : {timeit(votes_full, ops, q, qw) * 1e3:7.1f} ms",
          flush=True)

    @jax.jit
    def votes_agg(ops, q, qw):
        v = dm.extract_votes(ops, q, qw, w_read, lt, t_off, LA, pallas=True)
        acc = dm.aggregate_votes(v, win, n_win + 1)
        return sum(jnp.sum(x[:1]) for x in acc.values()).astype(jnp.int32)

    print(f"votes+agg    : {timeit(votes_agg, ops, q, qw) * 1e3:7.1f} ms",
          flush=True)


if __name__ == "__main__":
    main()
