"""Genome-scale end-to-end acceptance/perf run (BASELINE.json config 2).

Synthesizes a truth genome, a ~2%-error draft contig, 30x ~8 kb reads at
~10% error (half reverse-strand) with qualities, and a PAF overlap file
with draft-coordinate mappings; then runs the FULL CLI pipeline (parse
-> initialize -> polish) as a subprocess and reports wall time per
phase, windows/s, peak RSS, and sampled identity of the polished contig
vs the truth.

Usage:
  python scripts/genome_bench.py [genome_mb] [coverage] [--backend auto]
Prints one JSON line. Work dir: /tmp/racon_tpu_genome (reused).
"""

import json
import os
import resource
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BASES = np.frombuffer(b"ACGT", np.uint8)


def mutate(rng, seq, rate):
    """Vectorized mutation (sub/ins/del each rate/3); returns (mutated,
    map) where map[i] = position of truth base i in the output (deleted
    bases map to the previous surviving position)."""
    n = len(seq)
    r = rng.random(n)
    dele = r < rate / 3
    sub = (r >= rate / 3) & (r < 2 * rate / 3)
    ins = (r >= 2 * rate / 3) & (r < rate)
    counts = np.where(dele, 0, np.where(ins, 2, 1))
    starts = np.cumsum(counts) - counts
    out = np.zeros(int(counts.sum()), np.uint8)
    keep = ~dele
    base = np.where(sub, BASES[rng.integers(0, 4, n)], seq)
    out[starts[keep]] = base[keep]
    out[starts[ins] + 1] = BASES[rng.integers(0, 4, int(ins.sum()))]
    posmap = np.maximum.accumulate(np.where(keep, starts, -1))
    posmap = np.maximum(posmap, 0).astype(np.int64)
    return out, posmap


RC = np.zeros(256, np.uint8)
RC[np.frombuffer(b"ACGT", np.uint8)] = np.frombuffer(b"TGCA", np.uint8)


def main():
    # The CLI subprocess enables the persistent compile cache itself;
    # repeated genome runs then skip the 1-2 min/shape remote compiles.
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    genome_mb = float(args[0]) if args else 5.0
    coverage = int(args[1]) if len(args) > 1 else 30
    n = int(genome_mb * 1e6)
    read_len = 8000
    rng = np.random.default_rng(7)

    d = "/tmp/racon_tpu_genome"
    os.makedirs(d, exist_ok=True)
    t0 = time.perf_counter()

    truth = BASES[rng.integers(0, 4, n)]
    draft, posmap = mutate(rng, truth, 0.02)
    with open(f"{d}/draft.fasta", "w") as f:
        f.write(">contig1\n")
        f.write(draft.tobytes().decode())
        f.write("\n")

    n_reads = n * coverage // read_len
    paf = []
    with open(f"{d}/reads.fastq", "wb") as f:
        for i in range(n_reads):
            p = int(rng.integers(0, n - read_len))
            seg, _ = mutate(rng, truth[p:p + read_len], 0.10)
            strand = rng.random() < 0.5
            if strand:
                seg = RC[seg][::-1]
            q = rng.integers(33 + 8, 33 + 40, len(seg),
                             dtype=np.uint8).tobytes()
            name = f"r{i}"
            f.write(b"@" + name.encode() + b"\n" + seg.tobytes() + b"\n+\n"
                    + q + b"\n")
            ts, te = int(posmap[p]), int(posmap[p + read_len - 1]) + 1
            paf.append(f"{name}\t{len(seg)}\t0\t{len(seg)}\t"
                       f"{'-' if strand else '+'}\tcontig1\t{len(draft)}\t"
                       f"{ts}\t{te}\t{read_len}\t{read_len}\t60")
    with open(f"{d}/overlaps.paf", "w") as f:
        f.write("\n".join(paf) + "\n")
    t_gen = time.perf_counter() - t0

    backend = "auto"
    for a in sys.argv[1:]:
        if a.startswith("--backend="):
            backend = a.split("=", 1)[1]
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu.cli", "--backend", backend,
         f"{d}/reads.fastq", f"{d}/overlaps.paf", f"{d}/draft.fasta"],
        capture_output=True, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    t_polish = time.perf_counter() - t0
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.decode()[-3000:])
        sys.exit(1)
    out = proc.stdout.decode()
    polished = out.split("\n", 1)[1].replace("\n", "").encode()
    phases = [ln for ln in proc.stderr.decode().splitlines() if "[racon" in ln]
    peak_rss_mb = resource.getrusage(resource.RUSAGE_CHILDREN).ru_maxrss / 1024

    # Sampled identity: align 20 x 10 kb chunks of the polished contig
    # against the corresponding truth slices (+-2 kb slop).
    from racon_tpu.native.aligner import NativeAligner
    from racon_tpu.ops.encode import encode_bases
    al = NativeAligner(0, -1, -1)

    def sampled_identity_vs_truth(contig: bytes, n_samples: int = 20):
        scale = len(contig) / n
        eds, tot = 0, 0
        for s in np.linspace(0, len(contig) - 10000,
                             n_samples).astype(int):
            pc = contig[s:s + 10000]
            ts = max(0, int(s / scale) - 2000)
            tc = truth[ts:ts + 14000].tobytes()
            ops = np.asarray(al.align(pc, tc))
            qa, ta = encode_bases(pc), encode_bases(tc)
            qi = ti = ed = 0
            for dd in ops:
                if dd == 0:
                    ed += int(qa[qi] != ta[ti]); qi += 1; ti += 1
                elif dd == 1:
                    ed += 1; qi += 1
                else:
                    ed += 1; ti += 1
            # The truth slice deliberately overhangs the chunk by 2 kb
            # per side; a global alignment must delete the overhang, and
            # tie-breaking scatters those deletions, so subtract the
            # unavoidable length difference instead of trimming flanks.
            eds += max(ed - (len(tc) - len(pc)), 0)
            tot += len(pc)
        return 1 - eds / max(tot, 1)

    identity = sampled_identity_vs_truth(polished)
    draft_identity = sampled_identity_vs_truth(draft.tobytes(), 8)

    n_windows = -(-len(draft) // 500)
    print(json.dumps({
        "genome_mb": genome_mb, "coverage": coverage,
        "n_reads": n_reads, "n_windows": n_windows,
        "gen_seconds": round(t_gen, 1),
        "polish_seconds": round(t_polish, 1),
        "windows_per_sec_e2e": round(n_windows / t_polish, 2),
        "peak_rss_mb": round(peak_rss_mb, 1),
        "sampled_identity": round(identity, 6),
        "draft_identity_vs_truth": round(draft_identity, 6),
        "polished_len": len(polished),
        "phases": phases[-8:],
    }))


if __name__ == "__main__":
    main()
