"""Chaos bench: an elastic fleet under seeded evictions and stragglers
must finish within a bounded factor of the no-fault makespan, with the
merged FASTA byte-identical to a serial run.

This is the certification drill for the autoscaling supervisor
(racon_tpu/distributed/autoscaler.py) on top of the work ledger's
lease-steal + split machinery:

- the supervisor runs as a real subprocess (``--autoscale``) and
  spawns its own worker subprocesses against one ``--ledger-dir``;
- a seeded fault plan (``RACON_TPU_AUTOSCALE_FAULT_PLAN``) assigns
  injected faults to spawn ordinals: a hard kill at shard claim
  (``dist/shard:0!kill``), a SIGTERM mid-commit (``!term`` — the
  worker's signal path releases its lease, so reclaim is instant),
  a mid-shard kill, and a straggler (``dist/shard:0!stall=S``) —
  every run replays the same chaos;
- gates: supervisor exit 0; its stdout AND the ledger's out.fasta
  byte-identical to the serial baseline; the heartbeat shows the
  fleet was held at target (initial spawns + one replacement per
  eviction, every eviction classified); makespan <= ``--factor`` x
  the NO-FAULT FLEET baseline + ``--slack``.

The baseline for the factor is a fleet run of the same shape with no
fault plan — that isolates what the chaos actually costs (lease-expiry
waits and respawns) from what the fleet costs anyway (per-claim
polisher builds, merge barrier). The slack term absorbs per-respawn
constant costs (each replacement pays the interpreter + jax import
again — seconds that at smoke scale would swamp a multiplicative
bound) plus one lease-expiry wait for the mid-shard kill; the factor
certifies the algorithmic claim that evictions cost bounded rework,
not lost shards.

``--monster`` runs the dynamic shard-split drill instead: one shard
ending in a contig ~12x the others, held by a *degraded* worker (an
injected 2s stall at every contig commit — the slow-disk straggler),
versus the same fleet with ``RACON_TPU_SPLIT=0``. The holder stalls
at the claim fault site long enough for the healthy second worker to
join starved, so the claim-time trigger fires deterministically: the
degraded holder keeps only the in-flight first contig and donates the
entire un-committed tail — monster included — as a child shard the
healthy worker claims and finishes at full speed. Without the split,
every tail contig pays the degraded holder's per-commit stall. Gates:
>= 1 split event published, byte-identical output both ways, and the
split run measurably faster than the no-split run (the margin is
~tail_size x the per-commit degradation, deterministic even on a
single-core host).

``--smoke`` shrinks the chaos run (3 workers, 2 evictions + 1
straggler) for CI; the default is the full 4-worker / 3-eviction
certification.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                                   # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
BOOT = "import sys; from racon_tpu import cli; sys.exit(cli.main(sys.argv[1:]))"

#: Shard lease for every fleet run here. Must outlast a polisher build
#: under full fleet load (the lease renews per contig commit, and the
#: first renewal comes only after initialize + the first consensus) or
#: fresh claims get spuriously stolen into a re-init ping-pong.
LEASE_S = 30.0


def _noisy(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
            np.searchsorted(BASES, b)))
    return bytes(BASES[np.array(out)])


def _write_inputs(d, lengths, seed=11):
    rng = np.random.default_rng(seed)
    drafts, reads, paf = [], [], []
    for c, n in enumerate(lengths):
        truth = BASES[rng.integers(0, 4, n)]
        draft = _noisy(rng, truth)
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(6):
            r = _noisy(rng, truth)
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _cmd(d, *extra):
    return [sys.executable, "-c", BOOT, "--backend", "jax", *extra,
            os.path.join(d, "reads.fasta"), os.path.join(d, "ovl.paf"),
            os.path.join(d, "draft.fasta")]


def _env(**overrides):
    e = dict(os.environ)
    for k in ("RACON_TPU_FAULTS", "RACON_TPU_TRACE",
              "RACON_TPU_OBS_DIR", "RACON_TPU_OBS_FLUSH_S",
              "RACON_TPU_DIST_AVOID", "RACON_TPU_DIST_SHARDS",
              "RACON_TPU_SPLIT", "RACON_TPU_SPLIT_AFTER_S",
              "RACON_TPU_METRICS_PORT"):
        e.pop(k, None)
    for k in list(e):
        if k.startswith("RACON_TPU_AUTOSCALE_"):
            e.pop(k)
    e.update(overrides)
    return e


def _serial(d):
    t0 = time.monotonic()
    proc = subprocess.run(_cmd(d), capture_output=True, env=_env())
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stderr.decode()
    return proc.stdout, wall


def _split_events(ledger):
    path = os.path.join(ledger, "events.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    for line in open(path, "rb").read().splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail
        if rec.get("ev") == "split":
            out.append(rec)
    return out


def _heartbeat(ledger):
    path = os.path.join(ledger, "obs", "autoscaler.json")
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


# ---------------------------------------------------------------- chaos
def _fleet(d, ledger, n_workers, shards, timeout, plan=None):
    """One supervised fleet run; returns (stdout, wall_seconds)."""
    env = {
        "RACON_TPU_DIST_SHARDS": str(shards),
        "RACON_TPU_OBS_FLUSH_S": "0",
        "RACON_TPU_AUTOSCALE_MIN": str(n_workers),
        "RACON_TPU_AUTOSCALE_MAX": str(n_workers),
        "RACON_TPU_AUTOSCALE_INTERVAL_S": "0.2",
        "RACON_TPU_AUTOSCALE_DEADLINE_S": str(timeout),
    }
    if plan is not None:
        plan_path = ledger + ".fault_plan.json"
        with open(plan_path, "w", encoding="utf-8") as fh:
            json.dump(plan, fh)
        env["RACON_TPU_AUTOSCALE_FAULT_PLAN"] = plan_path
    t0 = time.monotonic()
    proc = subprocess.run(
        _cmd(d, "--ledger-dir", ledger, "--workers", str(n_workers),
             "--lease-s", str(LEASE_S), "--autoscale"),
        capture_output=True, env=_env(**env), timeout=timeout + 60)
    wall = time.monotonic() - t0
    assert proc.returncode == 0, \
        f"supervisor exit {proc.returncode}:\n{proc.stderr.decode()}"
    return proc.stdout, wall


def run_chaos(args):
    if args.smoke:
        n_workers, lengths, shards = 3, [300 + 30 * c for c in range(6)], 3
        # Spawn-ordinal fault plan: 2 evictions + 1 straggler.
        plan = ["dist/shard:0!kill",       # as0: killed at shard claim
                "ckpt/manifest:0!term",    # as1: SIGTERM mid-commit
                "dist/shard:0!stall=2"]    # as2: straggles, survives
        n_evict = 2
    else:
        n_workers, lengths, shards = 4, [300 + 30 * c for c in range(8)], 4
        plan = ["dist/shard:0!kill",       # as0: killed at shard claim
                "ckpt/manifest:0!term",    # as1: SIGTERM mid-commit
                "dist/contig:1!kill",      # as2: killed mid-shard
                "dist/shard:0!stall=3"]    # as3: the straggler
        n_evict = 3
    # Replacements (ordinals beyond the plan) run clean.

    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d, lengths)
        base, t_serial = _serial(d)
        assert base.count(b">") == len(lengths)
        print(f"[chaos-bench] serial baseline: {t_serial:.1f}s, "
              f"{len(base)} bytes", flush=True)

        # No-fault fleet baseline: same supervisor, same shape, no
        # fault plan — the denominator of the makespan guarantee.
        out0, t_fleet = _fleet(d, os.path.join(d, "ledger0"),
                               n_workers, shards, args.timeout)
        assert out0 == base, \
            "no-fault fleet stdout differs from the serial run"
        print(f"[chaos-bench] no-fault fleet of {n_workers}: "
              f"{t_fleet:.1f}s", flush=True)

        ledger = os.path.join(d, "ledger")
        out1, t_chaos = _fleet(d, ledger, n_workers, shards,
                               args.timeout, plan=plan)

        # Byte identity: supervisor stdout AND the published merge.
        assert out1 == base, \
            "chaos fleet stdout differs from the serial run"
        assert open(os.path.join(ledger, "out.fasta"),
                    "rb").read() == base
        print(f"[chaos-bench] chaos fleet under {n_evict} eviction(s) "
              f"+ 1 straggler: {t_chaos:.1f}s, merged FASTA "
              "byte-identical to serial", flush=True)

        # The autoscaler held the fleet at target: initial spawns plus
        # one replacement per injected eviction, all recorded in the
        # final heartbeat, and every eviction classified.
        hb = _heartbeat(ledger)
        assert hb["done"] is True, hb
        assert hb["spawned_total"] >= n_workers + n_evict, hb
        assert hb["scale_up_total"] >= n_workers, hb
        evicted = hb["evicted_total"] + hb["self_evicted_total"]
        assert evicted >= n_evict, hb
        assert hb["workers_done"] >= 1, hb

        # The makespan guarantee: bounded factor of the no-fault fleet
        # run, plus additive slack for respawn startup + one mid-shard
        # lease expiry.
        bound = args.factor * t_fleet + args.slack
        assert t_chaos <= bound, \
            (f"chaos makespan {t_chaos:.1f}s exceeds bound "
             f"{bound:.1f}s ({args.factor} x {t_fleet:.1f}s no-fault "
             f"fleet + {args.slack:.0f}s slack)")
        print(f"[chaos-bench] makespan {t_chaos:.1f}s <= bound "
              f"{bound:.1f}s; heartbeat: {hb['spawned_total']} "
              f"spawn(s), {evicted} evicted, "
              f"{hb['workers_done']} done", flush=True)
    print("[chaos-bench] PASS", flush=True)


# -------------------------------------------------------------- monster
#: The degraded holder's lease. Its renewal gap spans the claim
#: stall + polisher build + all consensus compute + the first commit
#: stall (renewal is per-commit), and this drill certifies the split
#: path, not lease stealing — so keep the lease far above that gap.
MONSTER_LEASE_S = 120.0

#: The holder's per-commit degradation (a slow-disk straggler: every
#: contig commit stalls this long). The no-split run pays it for the
#: whole tail; the split run pays it once, on the kept first contig.
MONSTER_DEGRADE_S = 2.0


def _monster_fleet(d, ledger, *, split_on, timeout):
    """Two plain workers against one single-shard ledger whose last
    contig is the monster. Worker A — the *degraded* worker, stalling
    MONSTER_DEGRADE_S at every contig commit — claims the (only)
    shard and stalls at the claim fault site; worker B joins during
    the stall, so A's claim-time split trigger (armed immediately:
    SPLIT_AFTER_S=0) sees a starved live worker and donates the
    entire un-committed tail — monster included — keeping only the
    in-flight first contig. Healthy B claims the child and polishes
    the tail commit-stall-free. With RACON_TPU_SPLIT=0 the degraded
    A keeps everything and pays the per-commit stall for the whole
    tail while B just idles, so the makespan gap is ~tail_size x
    MONSTER_DEGRADE_S — independent of compute overlap, hence
    deterministic even on a single-core CI host."""
    env_common = {
        "RACON_TPU_DIST_SHARDS": "1",
        "RACON_TPU_OBS_FLUSH_S": "0",
        "RACON_TPU_SPLIT": "1" if split_on else "0",
        "RACON_TPU_SPLIT_AFTER_S": "0",
    }
    faults = (f"dist/shard:0!stall=8;"
              f"dist/contig:p=1.0!stall={MONSTER_DEGRADE_S:g}")
    t0 = time.monotonic()
    a = subprocess.Popen(
        _cmd(d, "--ledger-dir", ledger, "--workers", "2",
             "--worker-id", "A", "--lease-s", str(MONSTER_LEASE_S)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=_env(**env_common, RACON_TPU_FAULTS=faults))
    # A must be the claimer: wait for its lease before starting B. The
    # 8s stall then covers B's interpreter + jax import comfortably,
    # so B has joined (live metric shard, zero leases) by the time A
    # evaluates the split trigger.
    deadline = time.monotonic() + 120
    while not os.path.exists(os.path.join(ledger, "shard_0.lease")):
        assert time.monotonic() < deadline, "worker A never claimed"
        assert a.poll() is None, a.communicate()[1].decode()
        time.sleep(0.05)
    b = subprocess.Popen(
        _cmd(d, "--ledger-dir", ledger, "--workers", "2",
             "--worker-id", "B", "--lease-s", str(MONSTER_LEASE_S)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=_env(**env_common,
                 RACON_TPU_FAULTS="dist/shard:0!stall=12"))
    a_out, a_err = a.communicate(timeout=timeout)
    b_out, b_err = b.communicate(timeout=timeout)
    wall = time.monotonic() - t0
    assert a.returncode == 0, a_err.decode()
    assert b.returncode == 0, b_err.decode()
    outs = [o for o in (a_out, b_out) if o]
    assert len(outs) == 1, "exactly one worker must emit the merge"
    return outs[0], wall


def run_monster(args):
    # A tail of smalls capped by one monster contig (~12x the window
    # count of a small): the split run hands the whole tail to the
    # healthy worker B; the no-split run commits it all through the
    # degraded holder, paying MONSTER_DEGRADE_S per contig. The tail
    # width sets the expected margin (~22 x 2s) well clear of
    # compile-cache and load noise.
    lengths = [600] * 22 + [12000]
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d, lengths, seed=23)
        base, t_serial = _serial(d)
        assert base.count(b">") == len(lengths)
        print(f"[chaos-bench] monster drill serial baseline: "
              f"{t_serial:.1f}s", flush=True)

        led_split = os.path.join(d, "ledger_split")
        out_split, t_split = _monster_fleet(
            d, led_split, split_on=True, timeout=args.timeout)
        splits = _split_events(led_split)
        assert splits, "split run published no split event"
        assert out_split == base, \
            "split-run merged FASTA differs from serial"
        child = splits[0]["child"]
        assert os.path.exists(os.path.join(led_split,
                                           f"{child}.range"))

        led_flat = os.path.join(d, "ledger_nosplit")
        out_flat, t_flat = _monster_fleet(
            d, led_flat, split_on=False, timeout=args.timeout)
        assert not _split_events(led_flat), \
            "RACON_TPU_SPLIT=0 must suppress splitting"
        assert out_flat == base, \
            "no-split merged FASTA differs from serial"

        print(f"[chaos-bench] monster drill: split {t_split:.1f}s "
              f"({len(splits)} split event(s), child {child}) vs "
              f"no-split {t_flat:.1f}s", flush=True)
        assert t_split < t_flat, \
            (f"dynamic split did not shorten the makespan: "
             f"{t_split:.1f}s vs {t_flat:.1f}s")
    print("[chaos-bench] PASS", flush=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="small CI variant: 3 workers, 2 evictions + "
                         "1 straggler")
    ap.add_argument("--monster", action="store_true",
                    help="dynamic shard-split drill instead of the "
                         "eviction chaos run")
    ap.add_argument("--factor", type=float, default=1.5,
                    help="multiplicative makespan bound vs the "
                         "no-fault fleet baseline (default 1.5)")
    ap.add_argument("--slack", type=float, default=25.0,
                    help="additive makespan slack in seconds, "
                         "absorbing per-respawn startup costs and one "
                         "mid-shard lease expiry (default 25)")
    ap.add_argument("--timeout", type=float, default=420.0,
                    help="hard deadline per fleet run (default 420s)")
    args = ap.parse_args()
    if args.monster:
        run_monster(args)
    else:
        run_chaos(args)


if __name__ == "__main__":
    main()
