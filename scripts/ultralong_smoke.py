"""CI smoke: a 32 kb ultralong read set polishes ENTIRELY on device.

The acceptance gate for the tiled overlap path (round 7): before
tiling, any read past ~9 kb silently routed to the native aligner, so
ultralong inputs polished with ovl_device_fraction ~= 0. This smoke
builds a synthetic ~33 kb draft with full-coverage 32 kb reads at
ONT-HQ error (~2.5%), polishes it on the jax backend, and gates:

  * zero native fallbacks (registry ovl_native_jobs == 0, every
    overlap device-handled, ovl_device_fraction == 1.0),
  * the tiled path actually executed (ovl_tiles_exec covers the
    expected 16-tile-per-read stitch at the 16-lane W=2048 tier),
  * the alignment layers AND the polished consensus are byte-identical
    to the native-path run of the same inputs.

Runs on the CPU backend in CI (same XLA twin tier-1 certifies); on TPU
the same script exercises the Pallas tile kernel.
"""

import gzip
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import tempfile                                      # noqa: E402

import numpy as np                                   # noqa: E402

from racon_tpu.models.polisher import (create_polisher,  # noqa: E402
                                       PolisherType)
from racon_tpu.obs import metrics as obs_metrics     # noqa: E402

BASES = np.frombuffer(b"ACGT", np.uint8)
READ_LEN = 32_000
DRAFT_LEN = 33_000
N_READS = 3
RATE = 0.025


def _mutate(rng, seq, rate):
    r = rng.random(len(seq))
    dele = r < rate / 3
    sub = (r >= rate / 3) & (r < 2 * rate / 3)
    ins = (r >= 2 * rate / 3) & (r < rate)
    counts = np.where(dele, 0, np.where(ins, 2, 1))
    starts = np.cumsum(counts) - counts
    out = np.zeros(int(counts.sum()), np.uint8)
    keep = ~dele
    base = np.where(sub, BASES[rng.integers(0, 4, len(seq))], seq)
    out[starts[keep]] = base[keep]
    out[starts[ins] + 1] = BASES[rng.integers(0, 4, int(ins.sum()))]
    return out


def _write_inputs(d):
    rng = np.random.default_rng(32)
    draft = BASES[rng.integers(0, 4, DRAFT_LEN)]
    reads, paf = [], []
    for i in range(N_READS):
        t0 = int(rng.integers(0, DRAFT_LEN - READ_LEN))
        out = _mutate(rng, draft[t0:t0 + READ_LEN], RATE)
        reads.append((f"r{i}", out.tobytes()))
        paf.append(f"r{i}\t{len(out)}\t0\t{len(out)}\t+\tdraft\t"
                   f"{DRAFT_LEN}\t{t0}\t{t0 + READ_LEN}\t{READ_LEN}\t"
                   f"{READ_LEN}\t255")
    with gzip.open(os.path.join(d, "reads.fasta.gz"), "wb") as fh:
        for name, data in reads:
            fh.write(b">" + name.encode() + b"\n" + data + b"\n")
    with gzip.open(os.path.join(d, "draft.fasta.gz"), "wb") as fh:
        fh.write(b">draft\n" + draft.tobytes() + b"\n")
    with gzip.open(os.path.join(d, "overlaps.paf.gz"), "wb") as fh:
        fh.write(("\n".join(paf) + "\n").encode())


def _layers(p):
    return [[(bytes(w.layer_data[i]), int(w.layer_begin[i]),
              int(w.layer_end[i])) for i in range(w.n_layers)]
            for w in p.windows]


def main():
    with tempfile.TemporaryDirectory() as d:
        _write_inputs(d)
        args = (os.path.join(d, "reads.fasta.gz"),
                os.path.join(d, "overlaps.paf.gz"),
                os.path.join(d, "draft.fasta.gz"),
                PolisherType.kC, 500, 10.0, 0.3, 5, -4, -8)

        pn = create_polisher(*args, backend="native")
        pn.initialize()
        layers_n = _layers(pn)
        recs_n = [(r.name, bytes(r.data)) for r in pn.polish()]

        obs_metrics.reset()
        pj = create_polisher(*args, backend="jax")
        pj.initialize()
        layers_j = _layers(pj)

        reg = obs_metrics.registry()
        dev = int(reg.get("ovl_device_jobs"))
        nat = int(reg.get("ovl_native_jobs"))
        tiles = int(reg.get("ovl_tiles_exec"))
        frac = reg.get("ovl_device_fraction")
        print(f"[ultralong-smoke] device_jobs={dev} native_jobs={nat} "
              f"tiles={tiles} device_fraction={frac}", flush=True)
        assert nat == 0, f"{nat} ultralong overlaps fell back to native"
        assert dev == N_READS, f"expected {N_READS} device jobs, got {dev}"
        # 32 kb lands in the 16-lane W=2048 T=2048 tier: ceil(32k/2k)
        # = 16+ tiles for the one chunk.
        assert tiles >= 16, f"tiled path barely ran: {tiles} tiles"
        assert frac == 1.0, f"device fraction {frac} != 1.0"

        assert layers_j == layers_n, "alignment layers differ from native"
        recs_j = [(r.name, bytes(r.data)) for r in pj.polish()]
        assert recs_j == recs_n, "polished consensus differs from native"
        n_bp = sum(len(w) for w in layers_j)
        print(f"[ultralong-smoke] {len(recs_j)} contig(s), "
              f"{n_bp} window layers byte-identical to native", flush=True)
    print("[ultralong-smoke] PASS", flush=True)


if __name__ == "__main__":
    main()
