#!/usr/bin/env bash
# CI entry point (reference parity: .travis.yml:32-37 runs racon_test on
# every build). Runs the full CPU suite, the multi-chip dryrun, and the
# two-shape device-engine smoke — the regression class that shipped in
# round 3 (two differently-shaped consensus runs in one process crashed
# with INVALID_ARGUMENT; reproducible on the CPU backend, see
# scripts/tpu_two_shape_repro.py).
set -euo pipefail
cd "$(dirname "$0")"

echo "[ci] pytest (CPU, 8 virtual devices)"
python -m pytest tests/ -q

echo "[ci] multi-chip dryrun (8 virtual devices)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "[ci] two-shape device-engine smoke"
python scripts/two_shape_smoke.py

echo "[ci] OK"
