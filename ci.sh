#!/usr/bin/env bash
# CI entry point (reference parity: .travis.yml:32-37 runs racon_test on
# every build). Default tier runs the full CPU suite, the flagship
# device-engine golden (ED vs the reference acceptance value — a gate,
# not a docstring), the multi-chip dryrun, and the two-shape
# device-engine smoke — the regression class that shipped in round 3
# (two differently-shaped consensus runs in one process crashed with
# INVALID_ARGUMENT; reproducible on the CPU backend, see
# scripts/tpu_two_shape_repro.py).
#
#   ci.sh          default tier
#   ci.sh --full   additionally runs every opt-in 'ava' golden
#                  (fragment-correction acceptance set)
set -euo pipefail
cd "$(dirname "$0")"

FULL=0
[[ "${1:-}" == "--full" ]] && FULL=1

echo "[ci] contract linter (docs/ANALYSIS.md; fails on non-baselined findings)"
python scripts/lint.py --ci

if [[ "$FULL" == 1 ]]; then
  echo "[ci] pytest (CPU, 8 virtual devices, FULL incl. ava goldens)"
  python -m pytest tests/ -q -m ''
else
  echo "[ci] pytest (CPU, 8 virtual devices)"
  python -m pytest tests/ -q
  echo "[ci] device-engine golden (SAM+FASTQ acceptance, gates ED <= 1317)"
  python -m pytest tests/test_polisher.py -q -m '' \
    -k test_consensus_device_engine_golden_sam_fastq
  echo "[ci] scheduler differential golden (sched vs fixed, SAM+FASTQ)"
  python -m pytest tests/test_polisher.py -q -m '' \
    -k "test_sched_differential_golden and sam_fastq"
  echo "[ci] pipeline differential golden (streamed vs serial, SAM+FASTQ)"
  python -m pytest tests/test_pipeline.py -q -m '' \
    -k "test_pipeline_differential_golden and sam_fastq"
fi

echo "[ci] multi-chip dryrun (8 virtual devices)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "[ci] interpreter-mode kernel + dual-column walk smoke"
python -m pytest tests/test_kernels_interpret.py tests/test_colwalk.py \
  -q -m ''

echo "[ci] two-shape device-engine smoke"
python scripts/two_shape_smoke.py

echo "[ci] ultralong smoke (32 kb tiled device path, zero native fallbacks)"
python scripts/ultralong_smoke.py

echo "[ci] observability smoke (traced tiny polish + JSONL schema gate)"
python scripts/obs_smoke.py

echo "[ci] pipeline smoke (streamed == serial FASTA + pipe span/gauge gate)"
python scripts/pipeline_smoke.py

echo "[ci] walk overlap smoke (decoupled walk hidden>0, byte-diff vs fused, stall drill)"
python scripts/walk_overlap_smoke.py

echo "[ci] resilience smoke (injected faults + kill-and-resume byte-diff)"
python scripts/resilience_smoke.py

echo "[ci] preemption smoke (2-worker fleet, 3 evictions, steal + merge byte-diff)"
python scripts/preemption_smoke.py

echo "[ci] redo smoke (flagged windows resolve on device, zero host redos, byte-diff)"
python scripts/redo_smoke.py

echo "[ci] fleet obs smoke (2-worker fleet, 1 eviction, aggregate + OpenMetrics gate)"
python scripts/fleet_obs_smoke.py

echo "[ci] failslow smoke (choke-point hangs, stage stall, self-eviction + merge byte-diff)"
python scripts/failslow_smoke.py

echo "[ci] chaos bench smoke (autoscaled fleet, evictions + straggler, makespan bound + byte-diff)"
python scripts/chaos_bench.py --smoke

echo "[ci] ingest smoke (parallel inflate plans, gz+plain 4-way byte-diff, ingest spans validate)"
python scripts/ingest_smoke.py

echo "[ci] server smoke (daemon, 3 jobs/2 tenants, cross-request occupancy > solo, kill+restart byte-diff)"
python scripts/server_smoke.py

echo "[ci] cache smoke (CAS resubmit = zero dispatches, torn-entry drill, CACHE=0 fallback, byte-diff)"
python scripts/cache_smoke.py

echo "[ci] job trace smoke (daemon + 2-worker fleet, ctx handoff, mid-shard kill, 3-process timeline + flight dump)"
python scripts/job_trace_smoke.py

echo "[ci] fleet serve smoke (gateway routing, worker kill, warm pool, standby adoption, byte-diff)"
python scripts/fleet_serve_smoke.py

echo "[ci] ava scale smoke (10k-read kF fleet, 1 eviction, weighted bounds, v2 manifests, byte-diff)"
python scripts/ava_scale_smoke.py

echo "[ci] OK"
