"""Decoupled asynchronous column walk tests (ISSUE 14).

The fused forward+walk chunk dispatch splits into device_chunk_fwd
(ops/device_poa.py) + walk_chunk_packed (ops/colwalk.py); the streaming
executor's walk stage overlaps chunk N's walk with chunk N+1's forward
dispatch. These tests pin the contract: byte-identity of the split
against the fused program at the ops level and through the stream (the
4-gate SCHED x ADAPTIVE x PIPELINE x WALK_ASYNC matrix), the
``dispatch/walk`` fault/retry envelope (FLT002), stall detection on the
walk stage with host re-polish, and the automatic fused fallbacks.
"""

import numpy as np
import pytest

from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.pipeline.streaming import stream_consensus
from racon_tpu.resilience import faults, retry, watchdog

BASES = np.frombuffer(b"ACGT", np.uint8)

_ENVS = ("RACON_TPU_WALK_ASYNC", "RACON_TPU_WALK_QUEUE",
         "RACON_TPU_SCHED", "RACON_TPU_ADAPTIVE", "RACON_TPU_PIPELINE",
         "RACON_TPU_STALL_S", "RACON_TPU_WALK_K")


@pytest.fixture(autouse=True)
def walk_sandbox(monkeypatch):
    monkeypatch.delenv(retry.ENV_RETRY, raising=False)
    monkeypatch.delenv(faults.ENV_FAULTS, raising=False)
    for name in _ENVS:
        monkeypatch.delenv(name, raising=False)
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()
    watchdog.reset()
    yield
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()
    watchdog.reset()


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.04:
            continue
        out.append(int(BASES[rng.integers(0, 4)]) if r < 0.08 else int(b))
        if r > 0.96:
            out.append(int(BASES[rng.integers(0, 4)]))
    return bytes(out)


def _build_windows(n, seed=0, coverage=5, wlen=80):
    """tests/test_pipeline.py's synthetic window set: trivial windows
    sprinkled in so the stream exercises the inline backbone path and
    device chunks alike."""
    from racon_tpu.models.window import Window, WindowType
    rng = np.random.default_rng(seed)
    ws = []
    for i in range(n):
        truth = BASES[rng.integers(0, 4, wlen)]
        backbone = _mutate(rng, truth)
        qual = bytes(rng.integers(43, 63, len(backbone), dtype=np.uint8))
        w = Window(i, i % 7, WindowType.TGS, backbone, qual)
        cov = 0 if i % 9 == 8 else coverage
        for _ in range(cov):
            lay = _mutate(rng, truth)
            lq = bytes(rng.integers(43, 63, len(lay), dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        ws.append(w)
    return ws


def _chunk_fixture(seed=1):
    """One packed ChunkPlan plus the engine's dispatch parameters."""
    from racon_tpu.ops.poa import PoaEngine
    eng = PoaEngine(backend="jax")
    ws = [w for w in _build_windows(12, seed=seed) if w.n_layers >= 2]
    dev, _host, lq_max, la_max = eng._partition_device(ws)
    sp = eng._plan_device_slice(dev, lq_max, la_max)
    assert sp.groups
    plan = eng._make_chunk_plan(sp, sp.groups[0])
    rounds = eng.refine_rounds + 1
    return eng, plan, eng._round_scales(rounds), rounds


# -------------------------------------------------- ops-level parity


def test_walk_unit_parity_fused_vs_decoupled():
    """dispatch_chunk_fwd + dispatch_walk must produce the exact packed
    output bytes of the fused dispatch_chunk — the split composes the
    same traced bodies, so the d2h buffer is the equality witness."""
    from racon_tpu.ops.colwalk import dispatch_walk
    from racon_tpu.ops.device_poa import dispatch_chunk, dispatch_chunk_fwd

    eng, plan, scales, rounds = _chunk_fixture()
    fused = dispatch_chunk(plan, match=eng.match, mismatch=eng.mismatch,
                           gap=eng.gap, ins_scale=scales, rounds=rounds)
    fwd_out, meta = dispatch_chunk_fwd(
        plan, match=eng.match, mismatch=eng.mismatch, gap=eng.gap,
        ins_scale=scales, rounds=rounds)
    split = dispatch_walk(plan, fwd_out, meta)
    assert bytes(np.asarray(split)) == bytes(np.asarray(fused))


def test_walk_unit_parity_adaptive(monkeypatch):
    """Same witness with the adaptive while_loop in the shared round
    prefix — the fwd program embeds the identical early-exit chain."""
    from racon_tpu.ops.colwalk import dispatch_walk
    from racon_tpu.ops.device_poa import dispatch_chunk, dispatch_chunk_fwd

    monkeypatch.setenv("RACON_TPU_ADAPTIVE", "1")
    eng, plan, scales, rounds = _chunk_fixture(seed=5)
    fused = dispatch_chunk(plan, match=eng.match, mismatch=eng.mismatch,
                           gap=eng.gap, ins_scale=scales, rounds=rounds)
    fwd_out, meta = dispatch_chunk_fwd(
        plan, match=eng.match, mismatch=eng.mismatch, gap=eng.gap,
        ins_scale=scales, rounds=rounds)
    split = dispatch_walk(plan, fwd_out, meta)
    assert bytes(np.asarray(split)) == bytes(np.asarray(fused))


def test_dispatch_walk_fault_absorbed_by_retry():
    """An injected fault at the ``dispatch/walk`` site is transient:
    one retry re-dispatches and the output bytes are unchanged."""
    from racon_tpu.ops.colwalk import dispatch_walk
    from racon_tpu.ops.device_poa import dispatch_chunk, dispatch_chunk_fwd

    eng, plan, scales, rounds = _chunk_fixture()
    fused = dispatch_chunk(plan, match=eng.match, mismatch=eng.mismatch,
                           gap=eng.gap, ins_scale=scales, rounds=rounds)
    fwd_out, meta = dispatch_chunk_fwd(
        plan, match=eng.match, mismatch=eng.mismatch, gap=eng.gap,
        ins_scale=scales, rounds=rounds)
    faults.configure("dispatch/walk:0")
    split = dispatch_walk(plan, fwd_out, meta)
    assert bytes(np.asarray(split)) == bytes(np.asarray(fused))
    snap = obs_metrics.registry().snapshot()
    assert snap["res_fault_site_dispatch_walk"] == 1
    assert snap["res_retry_site_dispatch_walk"] == 1
    assert snap.get("res_retry_exhausted", 0) == 0


# ------------------------------------------------- stream differential


def _stream(windows, chunk=8, depth=2):
    from racon_tpu.ops.poa import PoaEngine
    ranges = list(stream_consensus(PoaEngine(backend="jax"), windows,
                                   chunk=chunk, depth=depth))
    flat = [i for s, e in ranges for i in range(s, e)]
    assert flat == list(range(len(windows)))
    return [w.consensus for w in windows]


def test_stream_walk_async_bit_identical_and_counted(monkeypatch):
    """On the decoupled path (fixed rounds, multi-chunk stream) the
    polished consensi match the serial engine bit for bit, and the
    walk_* telemetry proves the decoupled stage actually ran."""
    from racon_tpu.ops.poa import PoaEngine

    monkeypatch.setenv("RACON_TPU_SCHED", "0")
    serial = _build_windows(24, seed=3)
    PoaEngine(backend="jax").consensus_windows(serial)
    ref = [w.consensus for w in serial]

    monkeypatch.setenv("RACON_TPU_WALK_ASYNC", "1")
    obs_metrics.reset()
    assert _stream(_build_windows(24, seed=3)) == ref
    snap = obs_metrics.registry().snapshot()
    assert snap["walk_async_enabled"] == 1
    assert snap["walk_dispatches"] >= 1
    assert snap["walk_seconds"] > 0
    assert snap["walk_fused_chunks"] >= 1      # the last chunk
    assert "walk_queue_peak" in snap
    assert obs_metrics.walk_extras()  # non-empty after a recorded run

    monkeypatch.setenv("RACON_TPU_WALK_ASYNC", "0")
    obs_metrics.reset()
    assert _stream(_build_windows(24, seed=3)) == ref
    snap = obs_metrics.registry().snapshot()
    assert snap["walk_async_enabled"] == 0
    assert snap["walk_dispatches"] == 0


@pytest.mark.parametrize("sched", ["0", "1"])
@pytest.mark.parametrize("adaptive", ["0", "1"])
def test_stream_matrix_bit_identical(monkeypatch, sched, adaptive):
    """SCHED x ADAPTIVE x WALK_ASYNC: every combination streams to the
    serial engine's bytes. Under SCHED=1 the executor must fall back to
    fused dispatches (per-round flag pulls consume every walk)."""
    from racon_tpu.ops.poa import PoaEngine

    monkeypatch.setenv("RACON_TPU_SCHED", sched)
    monkeypatch.setenv("RACON_TPU_ADAPTIVE", adaptive)
    serial = _build_windows(16, seed=9)
    PoaEngine(backend="jax").consensus_windows(serial)
    ref = [w.consensus for w in serial]
    for walk in ("1", "0"):
        monkeypatch.setenv("RACON_TPU_WALK_ASYNC", walk)
        obs_metrics.reset()
        assert _stream(_build_windows(16, seed=9)) == ref, \
            f"SCHED={sched} ADAPTIVE={adaptive} WALK_ASYNC={walk}"
        snap = obs_metrics.registry().snapshot()
        if sched == "1" or walk == "0":
            assert snap.get("walk_dispatches", 0) == 0


# --------------------------------------------------- fused fallbacks


def test_single_chunk_stream_falls_back_fused(monkeypatch):
    """A one-chunk stream has nothing to overlap with: the last-chunk
    rule keeps it fused and the gauges say so."""
    monkeypatch.setenv("RACON_TPU_SCHED", "0")
    monkeypatch.setenv("RACON_TPU_WALK_ASYNC", "1")
    ws = _build_windows(8, seed=13)
    _stream(ws, chunk=32)
    snap = obs_metrics.registry().snapshot()
    assert snap["walk_dispatches"] == 0
    assert snap["walk_fused_chunks"] >= 1
    assert snap["walk_async_enabled"] == 1


def test_walk_queue_zero_disables_decoupling(monkeypatch):
    """RACON_TPU_WALK_QUEUE=0 is the queue-knob spelling of off."""
    monkeypatch.setenv("RACON_TPU_SCHED", "0")
    monkeypatch.setenv("RACON_TPU_WALK_ASYNC", "1")
    monkeypatch.setenv("RACON_TPU_WALK_QUEUE", "0")
    _stream(_build_windows(24, seed=3))
    snap = obs_metrics.registry().snapshot()
    assert snap["walk_dispatches"] == 0
    assert snap["walk_async_enabled"] == 0


# ------------------------------------------------------- stall drill


@pytest.mark.slow
def test_walk_stage_stall_detected_and_recovered(monkeypatch):
    """A wedged walk stage (hang at pipe/walk) trips the stall detector
    within the window; the abort cascade surfaces PipelineStalled and
    the streaming driver re-polishes the un-retired tail on the host —
    full coverage, bit-identical output."""
    from racon_tpu.ops.poa import PoaEngine

    monkeypatch.setenv("RACON_TPU_SCHED", "0")
    monkeypatch.setenv("RACON_TPU_WALK_ASYNC", "1")
    monkeypatch.setenv("RACON_TPU_STALL_S", "0.5")
    serial = _build_windows(24, seed=11)
    PoaEngine(backend="jax").consensus_windows(serial)
    ref = [w.consensus for w in serial]

    faults.configure("pipe/walk:0!hang=3")
    obs_metrics.reset()
    assert _stream(_build_windows(24, seed=11)) == ref
    snap = obs_metrics.registry().snapshot()
    assert snap["pipe_stall_events"] >= 1
    assert watchdog.health_snapshot()["pipeline_stalls"] >= 1
