"""Seeded determinism violation (lint fixture — never imported).

DET001: wallclock/PRNG inside an identity (fingerprint) path.
"""

import random
import time


def shard_fingerprint(path):
    return f"{path}:{time.time()}:{random.random()}"      # DET001 x2
