"""Seeded env-contract violations (lint fixtures — never imported).

ENV001: a raw environ read of a RACON_TPU_ name outside envspec.
ENV002: envspec.read of a gate nobody declared.
"""

import os

from racon_tpu.utils import envspec

MODE = os.environ.get("RACON_TPU_FIXTURE_MODE", "")       # ENV001


def ghost():
    return envspec.read("RACON_TPU_FIXTURE_GHOST")        # ENV002
