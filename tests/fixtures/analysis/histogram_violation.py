"""Seeded histogram violation (lint fixture — never imported).

HIS001: a record_hist family with no HIST_BUCKETS bounds.
"""

from racon_tpu.obs.metrics import record_hist


def observe():
    record_hist("zz_ghost_latency_s", 0.1)                # HIS001
