"""Seeded cache-surface violations (lint fixture — never imported).

One file, one violation per contract the cache subsystem extends:

FLT001: a cache/* fault-site literal faults.SITES does not declare.
MET001: a recorded cache_* key matching no METRIC_SPECS row.
SPAN002: the ``cache`` span kind emitted without tier/outcome.
ATM001: a bare write-mode open (racon_tpu/cache/ is ATM001-scoped).
"""

from racon_tpu.obs.metrics import registry
from racon_tpu.resilience.faults import maybe_fault


def poison():
    maybe_fault("cache/bogus")                            # FLT001
    registry().inc("cache_bogus_total")                   # MET001


def emit(tracer):
    with tracer.span("cache", "probe", note=1):           # SPAN002
        pass


def save(path, data):
    with open(path, "w") as fh:                           # ATM001
        fh.write(data)
