"""Seeded atomic-write violation (lint fixture — never imported).

ATM001: a bare write-mode open in durable-output code, no pragma.
"""


def save(path, data):
    with open(path, "w") as fh:                           # ATM001
        fh.write(data)
