"""Seeded span-schema violations (lint fixture — never imported).

SPAN001: an emitted kind the obs_report.py validators don't know.
SPAN002: a known kind emitted without its required attrs.
"""


def run(tracer):
    with tracer.span("ghost_kind", "x"):                  # SPAN001
        pass
    with tracer.span("transfer", "h2d", note=1):          # SPAN002
        pass
