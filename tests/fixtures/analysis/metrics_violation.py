"""Seeded metrics-contract violation (lint fixture — never imported).

MET001: a recorded key matching no METRIC_SPECS row.
"""

from racon_tpu.obs.metrics import registry


def bump():
    registry().inc("zz_ghost_total")                      # MET001
    registry().set(f"zz_ghost_{int(1)}_gauge", 1.0)       # MET001 (dyn)
