"""Seeded choke-point violation (lint fixture — never imported).

CHK001: jax.device_put outside any retry/watchdog-guarded closure.
"""

import jax


def ship(host_buf):
    return jax.device_put(host_buf)                       # CHK001
