"""Seeded lock-discipline violation (lint fixture — never imported).

LCK001: a guarded-by attribute mutated outside its lock.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0  # guarded-by: _lock
        self.tags = []  # guarded-by: _lock

    def bump_unlocked(self):
        self.n += 1                                       # LCK001
        self.tags.append("x")                             # LCK001

    def bump_locked(self):
        with self._lock:
            self.n += 1                                   # clean
            self.tags.append("y")
