"""Seeded fault-site violation (lint fixture — never imported).

FLT001: a hook literal that faults.SITES does not declare.
"""

from racon_tpu.resilience.faults import maybe_fault


def hook():
    maybe_fault("ghost/site")                             # FLT001
