"""Column-walk traceback (ops/colwalk.py): bit-identity of its vote
channels against the legacy op-string pipeline, and the saturation redo
route for pathological insertion runs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from racon_tpu.ops import device_merge as dm
from racon_tpu.ops.colwalk import col_walk
from racon_tpu.ops.flat import fw_dirs_xla, fw_traceback, U_SAT
from racon_tpu.ops.pallas.band_kernel import (band_geometry,
                                              fw_dirs_band_xla,
                                              fw_traceback_band)

M, X, G = 5, -4, -8


def _random_jobs(rng, B, err=0.15):
    qs, ts = [], []
    for _ in range(B):
        t = rng.integers(0, 4, int(rng.integers(30, 120))).astype(np.uint8)
        r = rng.random(len(t))
        q = []
        for k, b in enumerate(t):
            if r[k] < err / 3:
                continue
            q.append(rng.integers(0, 4) if r[k] < 2 * err / 3 else b)
            if r[k] > 1 - err / 3:
                q.append(rng.integers(0, 4))
        qs.append(np.asarray(q or [0], np.uint8))
        ts.append(t)
    return qs, ts


def _pad(qs, ts):
    B = len(qs)
    Lq = max(len(q) for q in qs)
    Lt = max(len(t) for t in ts)
    tbuf = np.full((B, Lt), 7, np.uint8)
    qT = np.zeros((Lq, B), np.uint8)
    lq = np.zeros(B, np.int32)
    lt = np.zeros(B, np.int32)
    for b, (q, t) in enumerate(zip(qs, ts)):
        tbuf[b, :len(t)] = t
        qT[:len(q), b] = q
        lq[b], lt[b] = len(q), len(t)
    return tbuf, qT, lq, lt


def _votes_equal(va, vb):
    for k in va:
        assert np.array_equal(np.asarray(va[k]), np.asarray(vb[k])), k


def test_colwalk_matches_legacy_flat():
    """extract_votes_cols(col_walk(...)) == extract_votes(legacy ops) —
    bitwise, full-width layout (every returned channel is masked, so
    equality is exact, not approximate)."""
    rng = np.random.default_rng(11)
    qs, ts = _random_jobs(rng, 17)
    tbuf, qT, lq, lt = _pad(qs, ts)
    B, Lt = tbuf.shape
    Lq = qT.shape[0]
    LA = Lt
    t_off = np.zeros(B, np.int32)
    w_read = rng.uniform(1, 20, B).astype(np.float32)
    qw = rng.integers(0, 40, (B, Lq)).astype(np.float32)

    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    rev = fw_traceback(dirs, jnp.asarray(lq), jnp.asarray(lt), Lq + Lt)
    ops = jnp.flip(rev, axis=1)
    old = dm.extract_votes(ops, jnp.asarray(np.ascontiguousarray(qT.T)), jnp.asarray(qw),
                           jnp.asarray(w_read), jnp.asarray(lt),
                           jnp.asarray(t_off), LA)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.asarray(t_off), LA=LA, layout="flat")
    assert not np.asarray(cols["sat"]).any()
    qw8 = (qw + 1).astype(np.uint8)
    new = dm.extract_votes_cols(cols, jnp.asarray(np.ascontiguousarray(qT.T)),
                                jnp.asarray(qw8), jnp.asarray(w_read),
                                jnp.asarray(lt), jnp.asarray(t_off), LA)
    _votes_equal(old, new)


def test_colwalk_matches_legacy_band():
    """Same bit-identity through the banded layout with per-lane band
    origins and nonzero slice offsets."""
    rng = np.random.default_rng(12)
    qs, ts = _random_jobs(rng, 9)
    tbuf, qT, lq, lt = _pad(qs, ts)
    B = tbuf.shape[0]
    Lq = qT.shape[0]
    W = 128
    LA = tbuf.shape[1] + 16
    t_off = rng.integers(0, 9, B).astype(np.int32)
    w_read = rng.uniform(1, 20, B).astype(np.float32)
    qw = rng.integers(0, 40, (B, Lq)).astype(np.float32)

    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    tband = np.full((B, W + Lq), 7, np.uint8)
    for b in range(B):
        for y in range(W + Lq):
            j = klo_h[b] + y
            if 0 <= j < lt[b]:
                tband[b, y] = ts[b][j]
    dirs, nxt, _ = fw_dirs_band_xla(jnp.asarray(tband), jnp.asarray(qT),
                                    klo, jnp.asarray(lq), match=M,
                                    mismatch=X, gap=G, W=W)
    rev = fw_traceback_band(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                            Lq + W)
    ops = jnp.flip(rev, axis=1)
    q = np.zeros((B, Lq), np.uint8)
    for b, qq in enumerate(qs):
        q[b, :len(qq)] = qq
    old = dm.extract_votes(ops, jnp.asarray(q), jnp.asarray(qw),
                           jnp.asarray(w_read), jnp.asarray(lt),
                           jnp.asarray(t_off), LA)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                    jnp.asarray(t_off), LA=LA, layout="band")
    assert not np.asarray(cols["sat"]).any()
    qw8 = (qw + 1).astype(np.uint8)
    new = dm.extract_votes_cols(cols, jnp.asarray(q), jnp.asarray(qw8),
                                jnp.asarray(w_read), jnp.asarray(lt),
                                jnp.asarray(t_off), LA)
    _votes_equal(old, new)


def _band_case(rng, B, err):
    """Random banded jobs -> (dirs, nxt, lq, lt, klo, LA)."""
    qs, ts = _random_jobs(rng, B, err=err)
    tbuf, qT, lq, lt = _pad(qs, ts)
    W = 128
    LA = tbuf.shape[1] + 16
    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    tband = np.full((tbuf.shape[0], W + qT.shape[0]), 7, np.uint8)
    for b in range(tbuf.shape[0]):
        for y in range(tband.shape[1]):
            j = klo_h[b] + y
            if 0 <= j < lt[b]:
                tband[b, y] = ts[b][j]
    dirs, nxt, _ = fw_dirs_band_xla(jnp.asarray(tband), jnp.asarray(qT),
                                    klo, jnp.asarray(lq), match=M,
                                    mismatch=X, gap=G, W=W)
    return dirs, nxt, lq, lt, klo, LA


@pytest.mark.parametrize("seed,err", [(21, 0.1), (22, 0.2), (23, 0.35)])
def test_dual_walk_matches_single_walk(seed, err):
    """Property: the dual-column walk (nxt plane, two positions per
    dependent gather) is bit-identical to the single-column reference
    walk on randomized alignments — every channel, every lane the
    saturation certificate admits; the sat flags themselves must agree
    ALWAYS (flagged windows re-polish on the host in both modes, so flag
    equality is the whole bit-identity contract for them)."""
    rng = np.random.default_rng(seed)
    dirs, nxt, lq, lt, klo, LA = _band_case(rng, 15, err)
    B = lq.shape[0]
    t_off = rng.integers(0, 9, B).astype(np.int32)
    single = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                      jnp.asarray(t_off), LA=LA, layout="band")
    dual = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                    jnp.asarray(t_off), LA=LA, layout="band", nxt=nxt)
    sat = np.asarray(single["sat"])
    assert np.array_equal(sat, np.asarray(dual["sat"]))
    ok = ~sat
    for k in ("ins_len", "qstart", "op_c", "qi_c"):
        assert np.array_equal(np.asarray(single[k])[ok],
                              np.asarray(dual[k])[ok]), k


def test_packed_byte_encode_decode():
    """Property: the walk's decode shifts invert the kernels' packing
    for EVERY valid field combination.

    dirs byte: d | consumer << 2 | up_run << 4 (d, consumer in 0..2,
    up_run in 0..U_SAT). nxt byte: up_run' << 2 | consumer'. Kernel
    scratch packs 12 bits (nxt << 6 | up_run << 2 | consumer) — the
    up_run unpack there MUST mask & 0xF or the nxt bits alias into it
    (the exact bug class this test pins)."""
    for d in range(3):
        for c in range(3):
            for u in range(U_SAT + 1):
                pv = d + (c << 2) + (u << 4)
                assert pv < 256
                assert (pv & 3) == d
                assert ((pv >> 2) & 3) == c
                assert (pv >> 4) == u
                nv = (u << 2) + c
                assert nv < 64          # fits the scratch's 6 nxt bits
                assert (nv >> 2) == u and (nv & 3) == c
                for n in range(64):
                    sc = (n << 6) + (u << 2) + c
                    assert (sc & 3) == c
                    assert ((sc >> 2) & 0xF) == u
                    assert (sc >> 6) == n


def test_packed_byte_slice_matches_dynamic_slice():
    """Property: device_poa._packed_byte_slice (i32-packed batched
    dynamic_slice, 4 cells/word) equals the plain per-byte slice for
    every start phase, including start = size - L (the 2-word slack
    boundary)."""
    from racon_tpu.ops.device_poa import _packed_byte_slice
    rng = np.random.default_rng(31)
    for _ in range(10):
        L = int(rng.integers(4, 400))
        n = int(rng.integers(L + 1, L + 3000))
        tab = rng.integers(0, 256, n).astype(np.uint8)
        start = rng.integers(0, n - L + 1, 16).astype(np.int32)
        start[:4] = [0, 1, 2, 3]
        start[4] = n - L
        out = np.asarray(_packed_byte_slice(jnp.asarray(tab),
                                            jnp.asarray(start), L))
        ref = np.stack([tab[s:s + L] for s in start])
        assert np.array_equal(out, ref), (n, L)


def test_colwalk_leading_insertion_saturation():
    """A leading insertion run (gap 0, the j==0 closed-form step) longer
    than U_SAT must also raise the sat flag: extract_votes_cols' window
    channels only span U_SAT weights, so without the flag the run's
    length-weight votes would silently truncate."""
    t = np.tile(np.arange(4, dtype=np.uint8), 15)           # 60 bp target
    run = np.full(U_SAT + 5, 2, np.uint8)
    q = np.concatenate([run, t])                            # leading ins
    tbuf = t[None, :].repeat(2, 0)
    qT = np.zeros((len(q), 2), np.uint8)
    qT[:, 0] = q
    qT[: len(t), 1] = t
    lq = np.array([len(q), len(t)], np.int32)
    lt = np.array([len(t), len(t)], np.int32)
    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.zeros(2, jnp.int32), LA=len(t), layout="flat")
    sat = np.asarray(cols["sat"])
    assert sat[0] and not sat[1]


def test_colwalk_saturation_flags():
    """A forced insertion run longer than U_SAT sets the sticky sat flag
    (the engine then re-polishes that window on the host path)."""
    t = np.tile(np.arange(4, dtype=np.uint8), 20)          # 80 bp target
    run = np.full(U_SAT + 5, 2, np.uint8)                  # 20-base ins
    q = np.concatenate([t[:40], run, t[40:]])
    tbuf = t[None, :].repeat(2, 0)
    qT = np.zeros((len(q), 2), np.uint8)
    qT[:, 0] = q
    qT[: len(t), 1] = t
    lq = np.array([len(q), len(t)], np.int32)
    lt = np.array([len(t), len(t)], np.int32)
    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.zeros(2, jnp.int32), LA=len(t), layout="flat")
    sat = np.asarray(cols["sat"])
    assert sat[0] and not sat[1]
