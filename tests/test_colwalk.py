"""Column-walk traceback (ops/colwalk.py): bit-identity of its vote
channels against the legacy op-string pipeline, and the saturation redo
route for pathological insertion runs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from racon_tpu.ops import device_merge as dm
from racon_tpu.ops.colwalk import col_walk
from racon_tpu.ops.flat import fw_dirs_xla, fw_traceback, U_SAT
from racon_tpu.ops.pallas.band_kernel import (band_geometry,
                                              fw_dirs_band_xla,
                                              fw_traceback_band)

M, X, G = 5, -4, -8


def _random_jobs(rng, B, err=0.15):
    qs, ts = [], []
    for _ in range(B):
        t = rng.integers(0, 4, int(rng.integers(30, 120))).astype(np.uint8)
        r = rng.random(len(t))
        q = []
        for k, b in enumerate(t):
            if r[k] < err / 3:
                continue
            q.append(rng.integers(0, 4) if r[k] < 2 * err / 3 else b)
            if r[k] > 1 - err / 3:
                q.append(rng.integers(0, 4))
        qs.append(np.asarray(q or [0], np.uint8))
        ts.append(t)
    return qs, ts


def _pad(qs, ts):
    B = len(qs)
    Lq = max(len(q) for q in qs)
    Lt = max(len(t) for t in ts)
    tbuf = np.full((B, Lt), 7, np.uint8)
    qT = np.zeros((Lq, B), np.uint8)
    lq = np.zeros(B, np.int32)
    lt = np.zeros(B, np.int32)
    for b, (q, t) in enumerate(zip(qs, ts)):
        tbuf[b, :len(t)] = t
        qT[:len(q), b] = q
        lq[b], lt[b] = len(q), len(t)
    return tbuf, qT, lq, lt


def _votes_equal(va, vb):
    for k in va:
        assert np.array_equal(np.asarray(va[k]), np.asarray(vb[k])), k


def test_colwalk_matches_legacy_flat():
    """extract_votes_cols(col_walk(...)) == extract_votes(legacy ops) —
    bitwise, full-width layout (every returned channel is masked, so
    equality is exact, not approximate)."""
    rng = np.random.default_rng(11)
    qs, ts = _random_jobs(rng, 17)
    tbuf, qT, lq, lt = _pad(qs, ts)
    B, Lt = tbuf.shape
    Lq = qT.shape[0]
    LA = Lt
    t_off = np.zeros(B, np.int32)
    w_read = rng.uniform(1, 20, B).astype(np.float32)
    qw = rng.integers(0, 40, (B, Lq)).astype(np.float32)

    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    rev = fw_traceback(dirs, jnp.asarray(lq), jnp.asarray(lt), Lq + Lt)
    ops = jnp.flip(rev, axis=1)
    old = dm.extract_votes(ops, jnp.asarray(np.ascontiguousarray(qT.T)), jnp.asarray(qw),
                           jnp.asarray(w_read), jnp.asarray(lt),
                           jnp.asarray(t_off), LA)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.asarray(t_off), LA=LA, layout="flat")
    assert not np.asarray(cols["sat"]).any()
    qw8 = (qw + 1).astype(np.uint8)
    new = dm.extract_votes_cols(cols, jnp.asarray(np.ascontiguousarray(qT.T)),
                                jnp.asarray(qw8), jnp.asarray(w_read),
                                jnp.asarray(lt), jnp.asarray(t_off), LA)
    _votes_equal(old, new)


def test_colwalk_matches_legacy_band():
    """Same bit-identity through the banded layout with per-lane band
    origins and nonzero slice offsets."""
    rng = np.random.default_rng(12)
    qs, ts = _random_jobs(rng, 9)
    tbuf, qT, lq, lt = _pad(qs, ts)
    B = tbuf.shape[0]
    Lq = qT.shape[0]
    W = 128
    LA = tbuf.shape[1] + 16
    t_off = rng.integers(0, 9, B).astype(np.int32)
    w_read = rng.uniform(1, 20, B).astype(np.float32)
    qw = rng.integers(0, 40, (B, Lq)).astype(np.float32)

    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    tband = np.full((B, W + Lq), 7, np.uint8)
    for b in range(B):
        for y in range(W + Lq):
            j = klo_h[b] + y
            if 0 <= j < lt[b]:
                tband[b, y] = ts[b][j]
    dirs, _ = fw_dirs_band_xla(jnp.asarray(tband), jnp.asarray(qT), klo,
                               jnp.asarray(lq), match=M, mismatch=X,
                               gap=G, W=W)
    rev = fw_traceback_band(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                            Lq + W)
    ops = jnp.flip(rev, axis=1)
    q = np.zeros((B, Lq), np.uint8)
    for b, qq in enumerate(qs):
        q[b, :len(qq)] = qq
    old = dm.extract_votes(ops, jnp.asarray(q), jnp.asarray(qw),
                           jnp.asarray(w_read), jnp.asarray(lt),
                           jnp.asarray(t_off), LA)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                    jnp.asarray(t_off), LA=LA, layout="band")
    assert not np.asarray(cols["sat"]).any()
    qw8 = (qw + 1).astype(np.uint8)
    new = dm.extract_votes_cols(cols, jnp.asarray(q), jnp.asarray(qw8),
                                jnp.asarray(w_read), jnp.asarray(lt),
                                jnp.asarray(t_off), LA)
    _votes_equal(old, new)


def test_colwalk_leading_insertion_saturation():
    """A leading insertion run (gap 0, the j==0 closed-form step) longer
    than U_SAT must also raise the sat flag: extract_votes_cols' window
    channels only span U_SAT weights, so without the flag the run's
    length-weight votes would silently truncate."""
    t = np.tile(np.arange(4, dtype=np.uint8), 15)           # 60 bp target
    run = np.full(U_SAT + 5, 2, np.uint8)
    q = np.concatenate([run, t])                            # leading ins
    tbuf = t[None, :].repeat(2, 0)
    qT = np.zeros((len(q), 2), np.uint8)
    qT[:, 0] = q
    qT[: len(t), 1] = t
    lq = np.array([len(q), len(t)], np.int32)
    lt = np.array([len(t), len(t)], np.int32)
    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.zeros(2, jnp.int32), LA=len(t), layout="flat")
    sat = np.asarray(cols["sat"])
    assert sat[0] and not sat[1]


def test_colwalk_saturation_flags():
    """A forced insertion run longer than U_SAT sets the sticky sat flag
    (the engine then re-polishes that window on the host path)."""
    t = np.tile(np.arange(4, dtype=np.uint8), 20)          # 80 bp target
    run = np.full(U_SAT + 5, 2, np.uint8)                  # 20-base ins
    q = np.concatenate([t[:40], run, t[40:]])
    tbuf = t[None, :].repeat(2, 0)
    qT = np.zeros((len(q), 2), np.uint8)
    qT[:, 0] = q
    qT[: len(t), 1] = t
    lq = np.array([len(q), len(t)], np.int32)
    lt = np.array([len(t), len(t)], np.int32)
    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.zeros(2, jnp.int32), LA=len(t), layout="flat")
    sat = np.asarray(cols["sat"])
    assert sat[0] and not sat[1]
