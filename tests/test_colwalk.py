"""Column-walk traceback (ops/colwalk.py): bit-identity of its vote
channels against the legacy op-string pipeline, and the saturation redo
route for pathological insertion runs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from racon_tpu.ops import device_merge as dm
from racon_tpu.ops.colwalk import col_walk
from racon_tpu.ops.flat import fw_dirs_xla, fw_traceback, U_SAT
from racon_tpu.ops.pallas.band_kernel import (band_geometry,
                                              fw_dirs_band_xla,
                                              fw_traceback_band)

M, X, G = 5, -4, -8


def _random_jobs(rng, B, err=0.15):
    qs, ts = [], []
    for _ in range(B):
        t = rng.integers(0, 4, int(rng.integers(30, 120))).astype(np.uint8)
        r = rng.random(len(t))
        q = []
        for k, b in enumerate(t):
            if r[k] < err / 3:
                continue
            q.append(rng.integers(0, 4) if r[k] < 2 * err / 3 else b)
            if r[k] > 1 - err / 3:
                q.append(rng.integers(0, 4))
        qs.append(np.asarray(q or [0], np.uint8))
        ts.append(t)
    return qs, ts


def _pad(qs, ts):
    B = len(qs)
    Lq = max(len(q) for q in qs)
    Lt = max(len(t) for t in ts)
    tbuf = np.full((B, Lt), 7, np.uint8)
    qT = np.zeros((Lq, B), np.uint8)
    lq = np.zeros(B, np.int32)
    lt = np.zeros(B, np.int32)
    for b, (q, t) in enumerate(zip(qs, ts)):
        tbuf[b, :len(t)] = t
        qT[:len(q), b] = q
        lq[b], lt[b] = len(q), len(t)
    return tbuf, qT, lq, lt


def _votes_equal(va, vb):
    for k in va:
        assert np.array_equal(np.asarray(va[k]), np.asarray(vb[k])), k


def test_colwalk_matches_legacy_flat():
    """extract_votes_cols(col_walk(...)) == extract_votes(legacy ops) —
    bitwise, full-width layout (every returned channel is masked, so
    equality is exact, not approximate)."""
    rng = np.random.default_rng(11)
    qs, ts = _random_jobs(rng, 17)
    tbuf, qT, lq, lt = _pad(qs, ts)
    B, Lt = tbuf.shape
    Lq = qT.shape[0]
    LA = Lt
    t_off = np.zeros(B, np.int32)
    w_read = rng.uniform(1, 20, B).astype(np.float32)
    qw = rng.integers(0, 40, (B, Lq)).astype(np.float32)

    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    rev = fw_traceback(dirs, jnp.asarray(lq), jnp.asarray(lt), Lq + Lt)
    ops = jnp.flip(rev, axis=1)
    old = dm.extract_votes(ops, jnp.asarray(np.ascontiguousarray(qT.T)), jnp.asarray(qw),
                           jnp.asarray(w_read), jnp.asarray(lt),
                           jnp.asarray(t_off), LA)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.asarray(t_off), LA=LA, layout="flat")
    assert not np.asarray(cols["sat"]).any()
    qw8 = (qw + 1).astype(np.uint8)
    new = dm.extract_votes_cols(cols, jnp.asarray(np.ascontiguousarray(qT.T)),
                                jnp.asarray(qw8), jnp.asarray(w_read),
                                jnp.asarray(lt), jnp.asarray(t_off), LA)
    _votes_equal(old, new)


def test_colwalk_matches_legacy_band():
    """Same bit-identity through the banded layout with per-lane band
    origins and nonzero slice offsets."""
    rng = np.random.default_rng(12)
    qs, ts = _random_jobs(rng, 9)
    tbuf, qT, lq, lt = _pad(qs, ts)
    B = tbuf.shape[0]
    Lq = qT.shape[0]
    W = 128
    LA = tbuf.shape[1] + 16
    t_off = rng.integers(0, 9, B).astype(np.int32)
    w_read = rng.uniform(1, 20, B).astype(np.float32)
    qw = rng.integers(0, 40, (B, Lq)).astype(np.float32)

    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    tband = np.full((B, W + Lq), 7, np.uint8)
    for b in range(B):
        for y in range(W + Lq):
            j = klo_h[b] + y
            if 0 <= j < lt[b]:
                tband[b, y] = ts[b][j]
    dirs, nxt, _ = fw_dirs_band_xla(jnp.asarray(tband), jnp.asarray(qT),
                                    klo, jnp.asarray(lq), match=M,
                                    mismatch=X, gap=G, W=W)
    rev = fw_traceback_band(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                            Lq + W)
    ops = jnp.flip(rev, axis=1)
    q = np.zeros((B, Lq), np.uint8)
    for b, qq in enumerate(qs):
        q[b, :len(qq)] = qq
    old = dm.extract_votes(ops, jnp.asarray(q), jnp.asarray(qw),
                           jnp.asarray(w_read), jnp.asarray(lt),
                           jnp.asarray(t_off), LA)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                    jnp.asarray(t_off), LA=LA, layout="band")
    assert not np.asarray(cols["sat"]).any()
    qw8 = (qw + 1).astype(np.uint8)
    new = dm.extract_votes_cols(cols, jnp.asarray(q), jnp.asarray(qw8),
                                jnp.asarray(w_read), jnp.asarray(lt),
                                jnp.asarray(t_off), LA)
    _votes_equal(old, new)


def _band_case(rng, B, err, nxt_k=2):
    """Random banded jobs -> (dirs, nxt, nxt2, lq, lt, klo, LA)."""
    qs, ts = _random_jobs(rng, B, err=err)
    tbuf, qT, lq, lt = _pad(qs, ts)
    W = 128
    LA = tbuf.shape[1] + 16
    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    tband = np.full((tbuf.shape[0], W + qT.shape[0]), 7, np.uint8)
    for b in range(tbuf.shape[0]):
        for y in range(tband.shape[1]):
            j = klo_h[b] + y
            if 0 <= j < lt[b]:
                tband[b, y] = ts[b][j]
    if nxt_k >= 4:
        dirs, nxt, nxt2, _ = fw_dirs_band_xla(
            jnp.asarray(tband), jnp.asarray(qT), klo, jnp.asarray(lq),
            match=M, mismatch=X, gap=G, W=W, nxt_k=4)
    else:
        dirs, nxt, _ = fw_dirs_band_xla(
            jnp.asarray(tband), jnp.asarray(qT), klo, jnp.asarray(lq),
            match=M, mismatch=X, gap=G, W=W)
        nxt2 = None
    return dirs, nxt, nxt2, lq, lt, klo, LA


@pytest.mark.parametrize("seed,err", [(21, 0.1), (22, 0.2), (23, 0.35)])
def test_dual_walk_matches_single_walk(seed, err):
    """Property: the dual-column walk (nxt plane, two positions per
    dependent gather) is bit-identical to the single-column reference
    walk on randomized alignments — every channel, every lane the
    saturation certificate admits; the sat flags themselves must agree
    ALWAYS (flagged windows re-polish on the host in both modes, so flag
    equality is the whole bit-identity contract for them)."""
    rng = np.random.default_rng(seed)
    dirs, nxt, _, lq, lt, klo, LA = _band_case(rng, 15, err)
    B = lq.shape[0]
    t_off = rng.integers(0, 9, B).astype(np.int32)
    single = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                      jnp.asarray(t_off), LA=LA, layout="band")
    dual = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                    jnp.asarray(t_off), LA=LA, layout="band", nxt=nxt)
    sat = np.asarray(single["sat"])
    assert np.array_equal(sat, np.asarray(dual["sat"]))
    ok = ~sat
    for k in ("ins_len", "qstart", "op_c", "qi_c"):
        assert np.array_equal(np.asarray(single[k])[ok],
                              np.asarray(dual[k])[ok]), k


@pytest.mark.parametrize("seed,err", [(41, 0.1), (42, 0.2), (43, 0.35)])
def test_quad_walk_matches_single_walk(seed, err):
    """Property (round 8): the quad-column walk (nxt + nxt2 u16 planes,
    FOUR positions per dependent gather) is bit-identical to the
    single-step reference walk AND the dual walk on randomized
    alignments, and the k=4 forward's dirs/nxt emissions are bitwise
    the k=2 forward's — the second plane rides along without perturbing
    anything PR 5 shipped."""
    rng = np.random.default_rng(seed)
    dirs4, nxt4, nxt2, lq, lt, klo, LA = _band_case(rng, 15, err,
                                                    nxt_k=4)
    rng = np.random.default_rng(seed)          # same jobs, k=2 forward
    dirs2, nxt2_, _, lq2, lt2, klo2, LA2 = _band_case(rng, 15, err)
    assert np.array_equal(np.asarray(dirs4), np.asarray(dirs2))
    assert np.array_equal(np.asarray(nxt4), np.asarray(nxt2_))
    B = lq.shape[0]
    t_off = rng.integers(0, 9, B).astype(np.int32)
    args = (dirs4, jnp.asarray(lq), jnp.asarray(lt), klo,
            jnp.asarray(t_off))
    single = col_walk(*args, LA=LA, layout="band")
    dual = col_walk(*args, LA=LA, layout="band", nxt=nxt4)
    quad = col_walk(*args, LA=LA, layout="band", nxt=nxt4, nxt2=nxt2)
    sat = np.asarray(single["sat"])
    assert np.array_equal(sat, np.asarray(quad["sat"]))
    assert np.array_equal(sat, np.asarray(dual["sat"]))
    ok = ~sat
    for k in ("ins_len", "qstart", "op_c", "qi_c"):
        assert np.array_equal(np.asarray(single[k])[ok],
                              np.asarray(quad[k])[ok]), k
        assert np.array_equal(np.asarray(dual[k])[ok],
                              np.asarray(quad[k])[ok]), k


def test_quad_walk_requires_nxt():
    """nxt2 without nxt is a caller bug, not a silent fallback."""
    rng = np.random.default_rng(44)
    dirs, nxt, nxt2, lq, lt, klo, LA = _band_case(rng, 3, 0.1, nxt_k=4)
    with pytest.raises(ValueError):
        col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                 jnp.zeros(lq.shape[0], jnp.int32), LA=LA,
                 layout="band", nxt2=nxt2)


def test_packed_byte_encode_decode():
    """Property: the walk's decode shifts invert the kernels' packing
    for EVERY valid field combination.

    dirs byte: d | consumer << 2 | up_run << 4 (d, consumer in 0..2,
    up_run in 0..U_SAT). nxt byte: up_run' << 2 | consumer'. Kernel
    scratch packs 12 bits (nxt << 6 | up_run << 2 | consumer) — the
    up_run unpack there MUST mask & 0xF or the nxt bits alias into it
    (the exact bug class this test pins)."""
    for d in range(3):
        for c in range(3):
            for u in range(U_SAT + 1):
                pv = d + (c << 2) + (u << 4)
                assert pv < 256
                assert (pv & 3) == d
                assert ((pv >> 2) & 3) == c
                assert (pv >> 4) == u
                nv = (u << 2) + c
                assert nv < 64          # fits the scratch's 6 nxt bits
                assert (nv >> 2) == u and (nv & 3) == c
                for n in range(64):
                    sc = (n << 6) + (u << 2) + c
                    assert (sc & 3) == c
                    assert ((sc >> 2) & 0xF) == u
                    assert (sc >> 6) == n


def test_deep_plane_encode_decode():
    """Property (round 8): the quad walk's decode shifts invert the
    kernels' 24-bit scratch packing and the u16 nxt2 assembly for EVERY
    valid hop-field combination.

    Each hop field is 6 bits of ``(up_run << 2) | consumer`` (up_run in
    0..U_SAT, consumer in 0..2). Scratch packs
    ``(N3 << 18) | (N2 << 12) | (N1 << 6) | (U << 2) | C`` (24 bits,
    int32-safe); emissions split it as nxt u8 = N1, nxt2 u16 =
    ``(N3 << 8) | N2``. The walk reads hop 2 as ``((n2v >> 2) & 0xF,
    n2v & 3)`` and hop 3 as ``((n2v >> 10) & 0xF, (n2v >> 8) & 3)`` —
    the & 0xF masks are load-bearing (without them hop 3's bits alias
    into hop 2's up_run: the exact bug class this test pins)."""
    fields = [(u << 2) | c for u in range(U_SAT + 1) for c in range(3)]
    for f in fields:
        assert f < 64                      # fits one 6-bit hop slot
    for n1 in fields:
        for n2 in fields[::5]:
            for n3 in fields[::7]:
                u, c = U_SAT, 2
                sc = (n3 << 18) + (n2 << 12) + (n1 << 6) + (u << 2) + c
                assert sc < (1 << 24)      # int32 frontier word is safe
                assert (sc & 3) == c
                assert ((sc >> 2) & 0xF) == u
                assert ((sc >> 6) & 0x3F) == n1
                assert ((sc >> 12) & 0x3F) == n2
                assert ((sc >> 18) & 0x3F) == n3
                nv = (sc >> 6) & 0x3F      # nxt u8 emission
                n2v = ((sc >> 18) << 8) + ((sc >> 12) & 0x3F)
                assert n2v < (1 << 16)     # fits the u16 nxt2 plane
                # Walk-side hop decode (colwalk.quad_substep).
                assert (nv >> 2) == (n1 >> 2) and (nv & 3) == (n1 & 3)
                assert ((n2v >> 2) & 0xF) == (n2 >> 2)
                assert (n2v & 3) == (n2 & 3)
                assert ((n2v >> 10) & 0xF) == (n3 >> 2)
                assert ((n2v >> 8) & 3) == (n3 & 3)


def test_chain_len_pins():
    """chain_len is the acceptance-criterion quantity: at the bench
    anchor padding LA=640 the quad walk's dependent-gather chain is 161
    (<= the issue's ceiling), half the dual walk's 321 and a quarter of
    the single walk's 642."""
    from racon_tpu.ops.colwalk import chain_len
    assert chain_len(640, 1) == 642
    assert chain_len(640, 2) == 321
    assert chain_len(640, 4) == 161
    assert chain_len(0, 4) == 1
    with pytest.raises(ValueError):
        chain_len(640, 3)


def test_uc_boundary_pins():
    """Every hop field of the boundary fill decodes as (up_run 0,
    consumer LEFT) at both plane depths, and the k=2 value is the
    PR 5 constant (frozen: old checkpointed dirs remain walkable)."""
    from racon_tpu.ops.pallas.band_kernel import (LEFT, UC_BOUNDARY,
                                                  uc_boundary)
    assert uc_boundary(2) == UC_BOUNDARY == (LEFT << 6) | LEFT
    b4 = uc_boundary(4)
    assert b4 == (LEFT << 18) | (LEFT << 12) | (LEFT << 6) | LEFT
    assert (b4 & 3) == LEFT and ((b4 >> 2) & 0xF) == 0
    for shift in (6, 12, 18):
        f = (b4 >> shift) & 0x3F
        assert (f & 3) == LEFT and (f >> 2) == 0


def test_packed_byte_slice_matches_dynamic_slice():
    """Property: device_poa._packed_byte_slice (i32-packed batched
    dynamic_slice, 4 cells/word) equals the plain per-byte slice for
    every start phase, including start = size - L (the 2-word slack
    boundary)."""
    from racon_tpu.ops.device_poa import _packed_byte_slice
    rng = np.random.default_rng(31)
    for _ in range(10):
        L = int(rng.integers(4, 400))
        n = int(rng.integers(L + 1, L + 3000))
        tab = rng.integers(0, 256, n).astype(np.uint8)
        start = rng.integers(0, n - L + 1, 16).astype(np.int32)
        start[:4] = [0, 1, 2, 3]
        start[4] = n - L
        out = np.asarray(_packed_byte_slice(jnp.asarray(tab),
                                            jnp.asarray(start), L))
        ref = np.stack([tab[s:s + L] for s in start])
        assert np.array_equal(out, ref), (n, L)


def test_colwalk_leading_insertion_saturation():
    """A leading insertion run (gap 0, the j==0 closed-form step) longer
    than U_SAT must also raise the sat flag: extract_votes_cols' window
    channels only span U_SAT weights, so without the flag the run's
    length-weight votes would silently truncate."""
    t = np.tile(np.arange(4, dtype=np.uint8), 15)           # 60 bp target
    run = np.full(U_SAT + 5, 2, np.uint8)
    q = np.concatenate([run, t])                            # leading ins
    tbuf = t[None, :].repeat(2, 0)
    qT = np.zeros((len(q), 2), np.uint8)
    qT[:, 0] = q
    qT[: len(t), 1] = t
    lq = np.array([len(q), len(t)], np.int32)
    lt = np.array([len(t), len(t)], np.int32)
    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.zeros(2, jnp.int32), LA=len(t), layout="flat")
    sat = np.asarray(cols["sat"])
    assert sat[0] and not sat[1]


def test_colwalk_saturation_flags():
    """A forced insertion run longer than U_SAT sets the sticky sat flag
    (the engine then re-polishes that window on the host path)."""
    t = np.tile(np.arange(4, dtype=np.uint8), 20)          # 80 bp target
    run = np.full(U_SAT + 5, 2, np.uint8)                  # 20-base ins
    q = np.concatenate([t[:40], run, t[40:]])
    tbuf = t[None, :].repeat(2, 0)
    qT = np.zeros((len(q), 2), np.uint8)
    qT[:, 0] = q
    qT[: len(t), 1] = t
    lq = np.array([len(q), len(t)], np.int32)
    lt = np.array([len(t), len(t)], np.int32)
    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    cols = col_walk(dirs, jnp.asarray(lq), jnp.asarray(lt), None,
                    jnp.zeros(2, jnp.int32), LA=len(t), layout="flat")
    sat = np.asarray(cols["sat"])
    assert sat[0] and not sat[1]
