"""Test configuration.

Force the CPU backend with a virtual 8-device mesh so sharding/pjit tests
run without TPU hardware, as the build brief prescribes. Must run before
jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The axon site hook (PYTHONPATH=/root/.axon_site) overrides JAX_PLATFORMS
# back to the TPU tunnel; jax.config wins over both, so force it here
# before any test imports jax.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

REFERENCE_DATA = "/root/reference/test/data"


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end golden tests")


def reference_data_path(name: str) -> str:
    return os.path.join(REFERENCE_DATA, name)


@pytest.fixture(scope="session")
def ref_data():
    if not os.path.isdir(REFERENCE_DATA):
        pytest.skip("reference dataset not available")
    return reference_data_path
