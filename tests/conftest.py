"""Test configuration.

Force the CPU backend with a virtual 8-device mesh so sharding/pjit tests
run without TPU hardware, as the build brief prescribes. Must run before
jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402

REFERENCE_DATA = "/root/reference/test/data"


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end golden tests")


def reference_data_path(name: str) -> str:
    return os.path.join(REFERENCE_DATA, name)


@pytest.fixture(scope="session")
def ref_data():
    if not os.path.isdir(REFERENCE_DATA):
        pytest.skip("reference dataset not available")
    return reference_data_path
