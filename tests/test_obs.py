"""Observability tests: tracer JSONL, metrics registry, report/validate
(racon_tpu/obs/, scripts/obs_report.py)."""

import json
import sys

import pytest

from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.obs import trace as obs_trace


@pytest.fixture
def tracer_sandbox():
    """Isolate the process tracer global; restore disabled state after."""
    prev = obs_trace._tracer
    yield
    cur = obs_trace._tracer
    if isinstance(cur, obs_trace.Tracer):
        cur.finish()
    obs_trace._tracer = prev


def _read_trace(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# ------------------------------------------------------------------ tracer

def test_tracer_writes_nested_spans(tmp_path, tracer_sandbox):
    p = tmp_path / "t.jsonl"
    tr = obs_trace.configure(str(p))
    with tr.span("run", "outer", tag=1):
        with tr.span("chunk", "inner", lanes=8):
            pass
        tr.point("transfer", "h2d/x", dur_s=0.01, bytes=100, dir="h2d")
    tr.finish(metrics={"a": 1})

    recs = _read_trace(p)
    assert recs[0]["ev"] == "begin" and recs[0]["schema"] == 1
    spans = {r["name"]: r for r in recs if r["ev"] == "span"}
    outer, inner = spans["outer"], spans["inner"]
    assert outer["parent"] is None and outer["tag"] == 1
    assert inner["parent"] == outer["id"] and inner["lanes"] == 8
    # Close-time emission: the child's line precedes the parent's.
    names = [r["name"] for r in recs if r["ev"] == "span"]
    assert names.index("inner") < names.index("outer")
    xfer = spans["h2d/x"]
    assert xfer["parent"] == outer["id"]
    assert xfer["bytes"] == 100 and xfer["dir"] == "h2d"
    assert recs[-1] == {"ev": "metrics", "a": 1}


def test_tracer_emit_retro_span(tmp_path, tracer_sandbox):
    import time
    p = tmp_path / "t.jsonl"
    tr = obs_trace.configure(str(p))
    t0 = time.perf_counter()
    tr.emit("phase", "late", t0, 0.5)
    tr.finish()
    (span,) = [r for r in _read_trace(p) if r["ev"] == "span"]
    assert span["kind"] == "phase" and span["dur_s"] == 0.5
    assert span["t0"] >= 0


def test_configure_env_and_idempotence(tmp_path, monkeypatch,
                                       tracer_sandbox):
    p = tmp_path / "env.jsonl"
    monkeypatch.setenv(obs_trace.ENV_TRACE, str(p))
    obs_trace._tracer = None
    tr = obs_trace.get_tracer()
    assert isinstance(tr, obs_trace.Tracer) and tr.path == str(p)
    assert obs_trace.configure(str(p)) is tr      # same path: same tracer


def test_null_tracer_noop(monkeypatch):
    monkeypatch.delenv(obs_trace.ENV_TRACE, raising=False)
    tr = obs_trace.NullTracer()
    with tr.span("run", "x") as sp:
        sp.add(n=1).end()
    tr.emit("phase", "x", 0.0, 1.0)
    tr.point("transfer", "x")
    tr.finish(metrics={"a": 1})
    assert tr.enabled is False


# ---------------------------------------------------------------- registry

def test_registry_counters():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("n")
    reg.inc("n", 2)
    reg.set("s", [1, 2])
    reg.set("_internal", "hidden")
    assert reg.get("n") == 3
    assert reg.snapshot() == {"n": 3, "s": [1, 2]}
    reg.reset()
    assert reg.snapshot() == {}


def test_transfer_extras_derivation():
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.record_h2d(2_000_000, 0.5, reg=reg)
    obs_metrics.record_h2d(2_000_000, 0.5, reg=reg)
    obs_metrics.record_d2h(1_000_000, 0.25, reg=reg)
    obs_metrics.record_flag_pull(8, 0.1, reg=reg)
    reg.inc("device_dispatches", 4)
    ex = obs_metrics.transfer_extras(reg)
    assert ex["h2d_bytes"] == 4_000_000 and ex["h2d_transfers"] == 2
    assert ex["h2d_mb_per_s"] == pytest.approx(4.0)
    assert ex["d2h_mb_per_s"] == pytest.approx(4.0)
    # Flag pulls sync on compute: never folded into the h2d/d2h numbers.
    assert ex["sched_flag_pulls"] == 1
    assert ex["sched_flag_pull_s"] == pytest.approx(0.1)
    assert ex["device_dispatches"] == 4


def test_transfer_extras_empty():
    assert obs_metrics.transfer_extras(obs_metrics.MetricsRegistry()) == {}


def test_redo_extras_derivation():
    reg = obs_metrics.MetricsRegistry()
    assert obs_metrics.redo_extras(reg) == {}
    reg.set("walk_chain_len", 161)
    # The chain gauge reports even on runs where no window ever flags.
    assert obs_metrics.redo_extras(reg) == {"walk_chain_len": 161}
    obs_metrics.record_redo(3, 0, reg=reg)
    obs_metrics.record_redo(1, 1, reg=reg)
    ex = obs_metrics.redo_extras(reg)
    assert ex["redo_passes"] == 2
    assert ex["redo_device_windows"] == 4
    assert ex["redo_host_windows"] == 1
    assert ex["walk_chain_len"] == 161


def _telem():
    from racon_tpu.sched.telemetry import SchedTelemetry
    t = SchedTelemetry(5)
    t.record_chunk(10)
    for _ in range(6):
        t.record_freeze(2, 1)
    for _ in range(4):
        t.record_freeze(4, 1)
    for r in range(5):
        t.record_round(r, 10 if r < 2 else 4)
    t.record_repack(0.0123)
    return t


def test_publish_sched_canonical_keys():
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.publish_sched(_telem(), reg)
    ex = obs_metrics.sched_extras(reg)
    assert set(ex) == set(obs_metrics.SCHED_KEYS)
    assert ex["sched_windows"] == 10
    assert ex["sched_rounds_hist"] == {"2": 6, "4": 4}
    assert ex["sched_repack_overhead_s"] == pytest.approx(0.0123)


def test_sched_summary_line_format_stable():
    """The stderr line must keep the pre-registry format."""
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.publish_sched(_telem(), reg)
    line = obs_metrics.sched_summary_line(reg)
    assert line.startswith("windows=10 chunks=1 frozen[r2:6 r4:4] ")
    assert "rounds_saved=" in line and line.endswith("repack=0.012s")
    # And SchedTelemetry.summary() routes through the same formatter.
    assert _telem().summary() == line


# -------------------------------------------------------------- obs_report

def _report():
    sys.path.insert(0, "/root/repo")
    from scripts import obs_report
    return obs_report


def test_obs_report_validate_and_render(tmp_path, tracer_sandbox, capsys):
    obs_report = _report()
    p = tmp_path / "t.jsonl"
    tr = obs_trace.configure(str(p))
    with tr.span("run", "r"):
        with tr.span("phase", "load"):
            pass
        # point() backdates by dur_s; keep it shorter than the span so
        # the containment check sees a realistic in-parent transfer.
        tr.point("transfer", "h2d/x", dur_s=0.001, bytes=1000, dir="h2d")
    tr.finish(metrics={"h2d_bytes": 1000})
    trace = obs_report.load_trace(str(p))
    assert obs_report.validate(trace) == []
    obs_report.render(trace)
    out = capsys.readouterr().out
    assert "run: r" in out and "load" in out
    assert "h2d" in out and "metrics:" in out
    assert obs_report.main([str(p), "--validate"]) == 0


def test_obs_report_flags_violations(tmp_path):
    obs_report = _report()
    p = tmp_path / "bad.jsonl"
    p.write_text(
        json.dumps({"ev": "begin", "schema": 1, "unix_time": 0}) + "\n" +
        # Negative duration + dangling parent.
        json.dumps({"ev": "span", "id": 0, "parent": 7, "kind": "run",
                    "name": "r", "t0": 0.0, "dur_s": -1.0}) + "\n")
    errs = obs_report.validate(obs_report.load_trace(str(p)))
    assert any("parent 7" in e for e in errs)
    assert any("dur_s" in e for e in errs)
    assert obs_report.main([str(p), "--validate"]) == 1


def test_obs_report_rejects_garbage(tmp_path):
    obs_report = _report()
    p = tmp_path / "junk.jsonl"
    p.write_text("not json\n")
    assert obs_report.main([str(p), "--validate"]) == 1
