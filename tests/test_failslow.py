"""Fail-slow hardening tests: deadline derivation, the guard watchdog,
hang/stall fault actions, pipeline stall detection/recovery, ledger
lease release, straggler flagging, and the /healthz liveness view
(racon_tpu/resilience/watchdog.py, docs/RESILIENCE.md "Fail-slow")."""

import contextlib
import io
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.ops import budget
from racon_tpu.resilience import faults, retry, watchdog

BASES = np.frombuffer(b"ACGT", np.uint8)

#: Every env knob this subsystem reads — scrubbed around each test so
#: an operator shell (or a neighbouring test) can't leak configuration.
_ENVS = (
    "RACON_TPU_DEADLINE_H2D", "RACON_TPU_DEADLINE_D2H",
    "RACON_TPU_DEADLINE_DISPATCH", "RACON_TPU_DEADLINE_MBPS",
    "RACON_TPU_DEADLINE_CELLS_PER_S", "RACON_TPU_DEADLINE_SCALE",
    watchdog.ENV_TERMINAL, "RACON_TPU_STALL_S",
    faults.ENV_HANG_S, faults.ENV_STALL_S,
    "RACON_TPU_STRAGGLER_FRAC", "RACON_TPU_PIPELINE",
)


@pytest.fixture(autouse=True)
def failslow_sandbox(monkeypatch):
    monkeypatch.delenv(retry.ENV_RETRY, raising=False)
    monkeypatch.delenv(faults.ENV_FAULTS, raising=False)
    for name in _ENVS:
        monkeypatch.delenv(name, raising=False)
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()
    watchdog.reset()
    yield
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()
    watchdog.reset()


# ------------------------------------------------------ deadline budgets


def test_deadline_derivation_defaults():
    # base + bytes / (MB/s * 1e6), all at the documented defaults.
    assert budget.transfer_deadline_s(0, "h2d") == 60.0
    assert budget.transfer_deadline_s(10 * 10**6, "h2d") == 100.0
    assert budget.transfer_deadline_s(0, "d2h") == 300.0
    assert budget.dispatch_deadline_s(0) == 300.0
    assert budget.dispatch_deadline_s(4 * 10**6) == 302.0
    # Negative sizes clamp instead of shrinking the budget.
    assert budget.transfer_deadline_s(-5, "h2d") == 60.0


def test_deadline_env_overrides(monkeypatch):
    monkeypatch.setenv("RACON_TPU_DEADLINE_H2D", "10")
    monkeypatch.setenv("RACON_TPU_DEADLINE_MBPS", "1.0")
    monkeypatch.setenv("RACON_TPU_DEADLINE_SCALE", "2.0")
    assert budget.transfer_deadline_s(5 * 10**6, "h2d") == 2.0 * 15.0
    # base <= 0 disables the whole site class, scale notwithstanding.
    monkeypatch.setenv("RACON_TPU_DEADLINE_H2D", "0")
    assert budget.transfer_deadline_s(5 * 10**6, "h2d") == 0.0
    monkeypatch.setenv("RACON_TPU_DEADLINE_DISPATCH", "-1")
    assert budget.dispatch_deadline_s(10**9) == 0.0


def test_deadline_env_invalid(monkeypatch):
    monkeypatch.setenv("RACON_TPU_DEADLINE_H2D", "abc")
    with pytest.raises(ValueError, match="RACON_TPU_DEADLINE_H2D"):
        budget.transfer_deadline_s(0, "h2d")
    monkeypatch.delenv("RACON_TPU_DEADLINE_H2D")
    monkeypatch.setenv("RACON_TPU_DEADLINE_MBPS", "0")
    with pytest.raises(ValueError, match="RACON_TPU_DEADLINE_MBPS"):
        budget.transfer_deadline_s(1, "h2d")
    monkeypatch.delenv("RACON_TPU_DEADLINE_MBPS")
    with pytest.raises(ValueError, match="direction"):
        budget.transfer_deadline_s(0, "sideways")


def test_site_deadline_prefix_classes(monkeypatch):
    monkeypatch.setenv("RACON_TPU_DEADLINE_H2D", "7")
    monkeypatch.setenv("RACON_TPU_DEADLINE_D2H", "8")
    monkeypatch.setenv("RACON_TPU_DEADLINE_DISPATCH", "9")
    assert watchdog.site_deadline("h2d/chunk") == 7.0
    assert watchdog.site_deadline("d2h/align") == 8.0
    assert watchdog.site_deadline("dispatch/chunk") == 9.0
    assert watchdog.site_deadline("sched/flags") == 9.0
    assert watchdog.site_deadline("ckpt/manifest") == 0.0


# -------------------------------------------------------------- guard


def test_guard_passes_result_and_exceptions():
    assert watchdog.guard("t/s", 5.0, lambda a, b=0: a + b, 2, b=3) == 5
    with pytest.raises(KeyError):
        watchdog.guard("t/s", 5.0,
                       lambda: (_ for _ in ()).throw(KeyError("x")))
    assert "res_watchdog_breach_total" not in \
        obs_metrics.registry().snapshot()


def test_guard_disabled_runs_inline():
    # deadline <= 0: the body runs on the caller thread (no pool hop).
    assert watchdog.guard("t/s", 0.0, threading.get_ident) == \
        threading.get_ident()


def test_guard_breach_raises_and_counts():
    t0 = time.monotonic()
    with pytest.raises(watchdog.DispatchTimeout) as ei:
        watchdog.guard("d2h/slow", 0.15, time.sleep, 1.0)
    assert time.monotonic() - t0 < 1.0   # did NOT wait out the sleep
    assert ei.value.site == "d2h/slow"
    assert ei.value.deadline_s == 0.15
    snap = obs_metrics.registry().snapshot()
    assert snap["res_watchdog_breach_total"] == 1
    assert snap["res_watchdog_site_d2h_slow"] == 1
    h = watchdog.health_snapshot()
    assert h["status"] == "ok"           # non-terminal breach: still ok
    assert h["watchdog_breaches"] == 1
    assert h["last_breach"]["site"] == "d2h/slow"


def test_guard_ambient_deadline_visible_to_body():
    seen = watchdog.guard("t/s", 5.0, watchdog.ambient_deadline)
    assert seen == 5.0
    assert watchdog.ambient_deadline() == 0.0   # caller thread: unarmed


def test_terminal_breach_escalates(monkeypatch):
    monkeypatch.setenv(watchdog.ENV_TERMINAL, "1")
    with pytest.raises(watchdog.WatchdogTerminal) as ei:
        watchdog.guard("dispatch/chunk", 0.1, time.sleep, 0.8)
    assert watchdog.is_terminal(ei.value)
    wrapped = RuntimeError("stage boom")
    wrapped.__cause__ = ei.value
    assert watchdog.is_terminal(wrapped)
    assert not watchdog.is_terminal(RuntimeError("plain"))
    snap = obs_metrics.registry().snapshot()
    assert snap["res_watchdog_terminal_total"] == 1
    assert watchdog.health_snapshot()["status"] == "terminal"


def test_is_terminal_through_stage_error():
    from racon_tpu.pipeline.stages import StageError
    term = watchdog.WatchdogTerminal("dispatch/chunk", 1, 1)
    try:
        try:
            raise term
        except watchdog.WatchdogTerminal as exc:
            raise StageError("compute", exc) from exc
    except StageError as err:
        assert watchdog.is_terminal(err)
    assert not watchdog.is_terminal(StageError("compute",
                                               ValueError("x")))


def test_health_snapshot_stall_state():
    assert watchdog.health_snapshot()["status"] == "ok"
    watchdog.note_stall(4)
    h = watchdog.health_snapshot()
    assert h["status"] == "stalled" and h["pipeline_stalls"] == 1


# ------------------------------------------------- hang/stall injection


def test_fault_spec_hang_stall_grammar():
    faults.FaultInjector("a:0!hang=0.5;b:1!stall=2")      # parses
    faults.FaultInjector("a:0!hang")                      # default dur
    for bad in ("s:0!stall=x", "s:0!raise=3", "s:0!hang=-1",
                "s:0!kill=2"):
        with pytest.raises(faults.FaultSpecError):
            faults.FaultInjector(bad)


def test_stall_action_delays_then_proceeds():
    faults.configure("x/y:0!stall=0.3")
    t0 = time.monotonic()
    faults.maybe_fault("x/y")            # index 0: sleeps, no raise
    assert time.monotonic() - t0 >= 0.25
    t0 = time.monotonic()
    faults.maybe_fault("x/y")            # index 1: clean
    assert time.monotonic() - t0 < 0.1


def test_retry_detects_hang_and_recovers():
    """The acceptance loop on one site: an injected hang outlives the
    deadline, the guard converts it to DispatchTimeout (transient), and
    the retry's second attempt succeeds — bounded wall, same result."""
    faults.configure("h2d/chunk:0!hang=0.6")
    pol = retry.RetryPolicy(attempts=3, base=0.0, jitter=0.0)
    t0 = time.monotonic()
    out = retry.call("h2d/chunk", lambda: "ok", policy=pol,
                     deadline_s=0.15)
    assert out == "ok"
    assert time.monotonic() - t0 < 2.0
    snap = obs_metrics.registry().snapshot()
    assert snap["res_watchdog_breach_total"] == 1
    assert snap["res_retry_total"] == 1
    assert snap["res_fault_injected_total"] >= 1


# --------------------------------------------------------- chaos drill


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.04:
            continue
        out.append(int(BASES[rng.integers(0, 4)]) if r < 0.08 else int(b))
    return bytes(out)


def _build_windows(n, seed=0, coverage=5, wlen=80):
    from racon_tpu.models.window import Window, WindowType
    rng = np.random.default_rng(seed)
    ws = []
    for i in range(n):
        truth = BASES[rng.integers(0, 4, wlen)]
        backbone = _mutate(rng, truth)
        qual = bytes(rng.integers(43, 63, len(backbone), dtype=np.uint8))
        w = Window(i, i % 3, WindowType.TGS, backbone, qual)
        for _ in range(coverage):
            lay = _mutate(rng, truth)
            lq = bytes(rng.integers(43, 63, len(lay), dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        ws.append(w)
    return ws


_CHAOS_SITES = ("h2d/chunk", "dispatch/chunk", "d2h/chunk")
_CHAOS_ACTIONS = ("", "!hang=0.4", "!stall=0.2")   # "" = raise


@pytest.mark.slow
def test_chaos_mixed_faults_byte_identical(monkeypatch):
    """Seeded chaos: random mixes of raise/hang/stall across the device
    choke points must always converge to byte-identical output within a
    bounded wall — never a hang (the thread join is the outer
    watchdog)."""
    import random

    from racon_tpu.ops.poa import PoaEngine

    clean = _build_windows(10, seed=7)
    PoaEngine(backend="jax", log=io.StringIO()).consensus_windows(clean)
    want = [w.consensus for w in clean]

    monkeypatch.setenv("RACON_TPU_DEADLINE_H2D", "0.3")
    monkeypatch.setenv("RACON_TPU_DEADLINE_D2H", "0.3")
    monkeypatch.setenv("RACON_TPU_DEADLINE_DISPATCH", "0.5")
    retry.configure(retry.RetryPolicy(attempts=3, base=0.0, jitter=0.0))
    for seed in range(4):
        rng = random.Random(seed)
        spec = ";".join(
            f"{site}:{rng.randrange(2)}{rng.choice(_CHAOS_ACTIONS)}"
            for site in rng.sample(_CHAOS_SITES,
                                   rng.randint(1, len(_CHAOS_SITES))))
        faults.configure(spec)
        ws = _build_windows(10, seed=7)
        result = {}

        def run(ws=ws, result=result):
            try:
                PoaEngine(backend="jax",
                          log=io.StringIO()).consensus_windows(ws)
                result["ok"] = True
            except Exception as exc:  # typed failure is acceptable...
                result["exc"] = exc

        th = threading.Thread(target=run, daemon=True)
        th.start()
        th.join(45.0)
        # ...a hang is not.
        assert not th.is_alive(), f"seed {seed} hung (spec {spec!r})"
        assert result.get("ok"), \
            f"seed {seed}: {result.get('exc')!r} (spec {spec!r})"
        assert [w.consensus for w in ws] == want, \
            f"seed {seed}: output diverged (spec {spec!r})"
        faults.configure(None)
        watchdog.reset()


# ------------------------------------------------ pipeline stall drill


def _write_inputs(d, n_contigs=2, n_reads=6, clen=300):
    rng = np.random.default_rng(11)
    drafts, reads, paf = [], [], []
    for ci in range(n_contigs):
        truth = BASES[rng.integers(0, 4, clen)]
        draft = _mutate(rng, truth)
        drafts.append(b">c%d\n%s\n" % (ci, draft))
        for i in range(n_reads):
            r = _mutate(rng, truth)
            name = f"c{ci}r{i}"
            reads.append(b">" + name.encode() + b"\n" + r + b"\n")
            paf.append(f"{name}\t{len(r)}\t0\t{len(r)}\t+\tc{ci}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    (d / "draft.fasta").write_bytes(b"".join(drafts))
    (d / "reads.fasta").write_bytes(b"".join(reads))
    (d / "ovl.paf").write_text("\n".join(paf) + "\n")


def _run_cli(d, *extra):
    from racon_tpu import cli

    class _Capture(io.StringIO):
        pass

    stdout = _Capture()
    stdout.buffer = io.BytesIO()
    err = io.StringIO()
    with contextlib.redirect_stdout(stdout), \
            contextlib.redirect_stderr(err):
        rc = cli.main(["--backend", "jax", *extra,
                       str(d / "reads.fasta"), str(d / "ovl.paf"),
                       str(d / "draft.fasta")])
    return rc, stdout.buffer.getvalue(), err.getvalue()


@pytest.mark.slow
def test_pipeline_stall_detected_and_recovered(tmp_path, monkeypatch):
    """A wedged stage body (hang at pipe/pack) trips the stall detector
    within the window; the abort cascade surfaces PipelineStalled, the
    streaming driver re-polishes the un-retired tail on the host, and
    the output stays byte-identical."""
    _write_inputs(tmp_path)
    rc, base, _ = _run_cli(tmp_path)
    assert rc == 0 and base.count(b">") == 2

    monkeypatch.setenv("RACON_TPU_PIPELINE", "1")
    monkeypatch.setenv("RACON_TPU_STALL_S", "0.5")
    faults.configure("pipe/pack:0!hang=3")
    t0 = time.monotonic()
    rc, out, err = _run_cli(tmp_path)
    assert rc == 0, err
    assert out == base
    assert "stall detected" in err
    snap = obs_metrics.registry().snapshot()
    assert snap["pipe_stall_events"] >= 1
    assert watchdog.health_snapshot()["pipeline_stalls"] >= 1
    # The run must beat a full hang wait-out by a wide margin is not
    # guaranteed (shutdown joins the waking stage), but it must finish.
    assert time.monotonic() - t0 < 30.0


def test_stall_window_env(monkeypatch):
    from racon_tpu.pipeline.stages import stall_window_s
    assert stall_window_s() == 300.0
    monkeypatch.setenv("RACON_TPU_STALL_S", "2.5")
    assert stall_window_s() == 2.5
    monkeypatch.setenv("RACON_TPU_STALL_S", "nope")
    with pytest.raises(ValueError, match="RACON_TPU_STALL_S"):
        stall_window_s()


# --------------------------------------------- ledger release / merge


def test_ledger_release_enables_instant_reclaim(tmp_path):
    from racon_tpu.distributed.ledger import WorkLedger
    d = str(tmp_path / "led")
    led = WorkLedger.open(d, "fp1", n_targets=2, workers=1,
                          lease_s=60.0, n_shards=1)
    a = led.claim_shard("wA")
    assert a is not None and a.name == "shard_0"
    assert led.claim_shard("wB") is None        # live-leased elsewhere
    led.release(a)
    b = led.claim_shard("wB")                   # no lease term wait
    assert b is not None and b.worker == "wB"
    led.release(a)                              # stale nonce: no-op
    led.verify(b)                               # wB's lease untouched
    evs = [e["ev"] for e in led.events()]
    assert "release" in evs
    snap = obs_metrics.registry().snapshot()
    assert snap["dist_releases"] == 1


def test_merge_write_fault_leaves_no_partial_output(tmp_path):
    """The dist/merge!term class of drill at unit scale: a fault mid-
    merge-write must leave NO out.fasta (tmp unlinked), and the redo
    produces the full byte-identical file."""
    from racon_tpu.distributed.ledger import WorkLedger
    from racon_tpu.resilience import checkpoint as ckpt
    d = str(tmp_path / "led")
    led = WorkLedger.open(d, "fp1", n_targets=2, workers=1,
                          lease_s=60.0, n_shards=1)
    claim = led.claim_shard("w0")
    store = ckpt.CheckpointStore.create(led.shard_ckpt_dir(0),
                                        led.shard_fp(0))
    store.commit(0, b"c0", b"AAAA")
    store.commit(1, b"c1", b"CCCC")
    store.close()
    led.complete(claim)
    assert led.claim_merge("w0") is not None

    faults.configure("dist/merge_write:1")      # die on the 2nd blob
    with pytest.raises(faults.InjectedFault):
        led.merge()
    assert not os.path.exists(led.out_path)
    leftovers = [n for n in os.listdir(d) if ".tmp." in n]
    assert leftovers == []

    faults.configure(None)
    total, emitted = led.merge()
    assert emitted == 2
    blob = open(led.out_path, "rb").read()
    assert blob == b">c0\nAAAA\n>c1\nCCCC\n" and total == len(blob)


def test_atomic_writer_clean_and_aborted(tmp_path):
    from racon_tpu.utils.atomicio import atomic_writer
    p = str(tmp_path / "out.bin")
    with atomic_writer(p) as fh:
        fh.write(b"hello")
    assert open(p, "rb").read() == b"hello"
    with pytest.raises(RuntimeError, match="boom"):
        with atomic_writer(p) as fh:
            fh.write(b"garbage")
            raise RuntimeError("boom")
    assert open(p, "rb").read() == b"hello"     # prior bytes intact
    assert [n for n in os.listdir(str(tmp_path)) if ".tmp." in n] == []


# ---------------------------------------------- stragglers + /healthz


def _shard(d, wid, windows, wall, run_fp="fpX"):
    rec = {"schema": 1, "seq": 0, "worker_id": wid, "run_fp": run_fp,
           "unix_time": 0.0, "wall_s": wall, "final": True,
           "metrics": {"poa_windows_total": windows}}
    with open(os.path.join(d, f"worker_{wid}.metrics.jsonl"),
              "w", encoding="utf-8") as fh:
        fh.write(json.dumps(rec) + "\n")


def test_straggler_flagging(tmp_path, monkeypatch):
    from racon_tpu.obs.fleet import FleetObsError, aggregate
    d = str(tmp_path)
    _shard(d, "fast1", 1000, 10.0)   # 100 w/s
    _shard(d, "fast2", 900, 10.0)    # 90 w/s  -> median 90, cutoff 45
    _shard(d, "slow", 100, 10.0)     # 10 w/s  -> flagged
    _shard(d, "merge", 0, 10.0)      # rate 0: merge-only, never flagged
    model = aggregate(d)
    assert model["stragglers"] == ["slow"]
    assert model["workers"]["slow"]["straggler"] is True
    assert model["workers"]["merge"]["straggler"] is False
    assert model["workers"]["fast1"]["straggler"] is False
    monkeypatch.setenv("RACON_TPU_STRAGGLER_FRAC", "0.05")
    assert aggregate(d)["stragglers"] == []
    monkeypatch.setenv("RACON_TPU_STRAGGLER_FRAC", "2.0")
    with pytest.raises(FleetObsError):
        aggregate(d)


def test_straggler_needs_two_positive_rates(tmp_path):
    from racon_tpu.obs.fleet import aggregate
    d = str(tmp_path)
    _shard(d, "only", 100, 10.0)
    _shard(d, "merge", 0, 10.0)
    model = aggregate(d)                 # 1 positive rate: no flags
    assert model["stragglers"] == []


def test_healthz_endpoint():
    from racon_tpu.obs.export import serve_metrics
    state = {"status": "ok", "watchdog_breaches": 0}
    srv = serve_metrics(0, lambda: "# EOF\n",
                        health=lambda: dict(state))
    port = srv.server_address[1]
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
            assert r.status == 200
            assert json.loads(r.read())["status"] == "ok"
        state["status"] = "terminal"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "terminal"
        # Any other path still serves the metrics render.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            assert r.status == 200 and r.read() == b"# EOF\n"
    finally:
        srv.shutdown()
