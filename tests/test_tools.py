"""Tests for the rampler equivalent and the chunking wrapper."""

import os
import subprocess
import sys

import pytest

from racon_tpu.io.parsers import FastaParser, FastqParser
from racon_tpu.tools import rampler


def test_split_preserves_records(ref_data, tmp_path):
    src = ref_data("sample_reads.fastq.gz")
    originals = FastqParser(src).parse_all()
    paths = rampler.split(src, 300_000, str(tmp_path))
    assert len(paths) > 1
    back = []
    for p in paths:
        assert os.path.basename(p).startswith("sample_reads_")
        assert p.endswith(".fastq")
        back.extend(FastqParser(p).parse_all())
    assert len(back) == len(originals)
    assert all(a.name == b.name and a.data == b.data
               for a, b in zip(back, originals))
    # Chunks respect the base budget (single oversized reads excepted).
    for p in paths[:-1]:
        total = sum(len(s.data) for s in FastqParser(p).parse_all())
        assert total <= 300_000 + 50_000


def test_subsample_hits_target_coverage(ref_data, tmp_path):
    src = ref_data("sample_reads.fasta.gz")
    out = rampler.subsample(src, 47_564, 10, str(tmp_path))
    assert out.endswith("sample_reads_10x.fasta")
    kept = FastaParser(out).parse_all()
    total = sum(len(s.data) for s in kept)
    # ~10x of 47.5 kbp = ~476 kbp, binomial spread allowed.
    assert 0.6 * 475_640 < total < 1.4 * 475_640


def test_rampler_cli(ref_data, tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "racon_tpu.tools.rampler", "-o",
         str(tmp_path), "split", ref_data("sample_reads.fasta.gz"),
         "1000000"],
        capture_output=True, cwd="/root/repo")
    assert r.returncode == 0, r.stderr
    assert any(f.startswith("sample_reads_") for f in os.listdir(tmp_path))


@pytest.mark.slow
def test_wrapper_split_chunks_and_resumes(ref_data, tmp_path):
    """Targets split into per-contig chunks, each polished and
    checkpointed; --resume reuses checkpoints byte-identically.

    A 3-contig dataset is synthesized by tripling the lambda layout (and
    its SAM overlaps under the per-copy contig names) — record-level
    splitting needs multiple records, like the reference rampler's.
    """
    import gzip

    layout = FastaParser(ref_data("sample_layout.fasta.gz")).parse_all()[0]
    targets_path = str(tmp_path / "targets.fasta")
    with open(targets_path, "wb") as f:
        for i in range(3):
            f.write(b">utg%d\n" % i + layout.data + b"\n")
    sam_path = str(tmp_path / "overlaps.sam")
    with gzip.open(ref_data("sample_overlaps.sam.gz"), "rb") as src, \
            open(sam_path, "wb") as out:
        lines = src.read().split(b"\n")
        for i in range(3):
            for line in lines:
                if not line or line.startswith(b"@"):
                    continue
                t = line.split(b"\t")
                t[2] = b"utg%d" % i
                out.write(b"\t".join(t) + b"\n")

    work = str(tmp_path / "work")
    args = ["--split", "50000", "--work-directory", work, "--resume",
            "--backend", "native",
            ref_data("sample_reads.fastq.gz"), sam_path, targets_path]
    r1 = subprocess.run(
        [sys.executable, "-m", "racon_tpu.tools.wrapper", *args],
        capture_output=True, cwd="/root/repo")
    assert r1.returncode == 0, r1.stderr[-800:]
    chunks = sorted(f for f in os.listdir(work) if f.startswith("chunk_"))
    assert len(chunks) == 3
    assert r1.stdout.count(b">") == 3  # one polished contig per chunk
    # Resume: must reuse checkpoints and produce identical bytes.
    r2 = subprocess.run(
        [sys.executable, "-m", "racon_tpu.tools.wrapper", *args],
        capture_output=True, cwd="/root/repo")
    assert r2.returncode == 0
    assert r2.stdout == r1.stdout
    # Sharded execution covers a disjoint slice.
    r3 = subprocess.run(
        [sys.executable, "-m", "racon_tpu.tools.wrapper", *args,
         "--num-shards", "3", "--shard-id", "1"],
        capture_output=True, cwd="/root/repo")
    assert r3.returncode == 0
    assert r3.stdout.count(b">") == 1
    assert r3.stdout in r1.stdout
