"""Tests for the batched POA consensus engine (spoa replacement)."""

import numpy as np
import pytest

from racon_tpu.models.window import Window, WindowType
from racon_tpu.ops.encode import decode_bases
from racon_tpu.ops.poa import PoaEngine


def _noisy(rng, seq, rate):
    out = []
    for b in seq:
        r = rng.random()
        if r < rate / 3:
            continue  # deletion
        elif r < 2 * rate / 3:
            out.append(int(rng.integers(0, 4)))  # substitution
        elif r < rate:
            out.append(int(b))
            out.append(int(rng.integers(0, 4)))  # insertion
        else:
            out.append(int(b))
    return decode_bases(np.array(out, np.uint8))


def _make_window(rng, true, n_layers, rate=0.1, wtype=WindowType.TGS):
    backbone = _noisy(rng, true, rate)
    w = Window(0, 0, wtype, backbone, None)
    for _ in range(n_layers):
        lay = _noisy(rng, true, rate)
        w.add_layer(lay, None, 0, len(backbone) - 1)
    return w


@pytest.mark.parametrize("backend", ["native", "jax"])
def test_consensus_recovers_truth(backend):
    rng = np.random.default_rng(11)
    true = rng.integers(0, 4, 300).astype(np.uint8)
    true_b = decode_bases(true)
    w = _make_window(rng, true, 16, rate=0.1)
    eng = PoaEngine(backend=backend)
    assert eng.consensus_windows([w]) == 1
    assert w.polished
    from racon_tpu.ops.align import nw_oracle
    sc, _ = nw_oracle(w.consensus, true_b, 0, -1, -1)
    # 10% error backbone + 16 noisy layers must polish to (near) truth.
    assert -sc <= 3


def test_backends_agree():
    rng = np.random.default_rng(12)
    true = rng.integers(0, 4, 200).astype(np.uint8)
    w1 = _make_window(rng, true, 8, rate=0.08)
    w2 = Window(0, 0, WindowType.TGS, w1.backbone, None)
    for i in range(w1.n_layers):
        w2.add_layer(w1.layer_data[i], None, w1.layer_begin[i],
                     w1.layer_end[i])
    PoaEngine(backend="native").consensus_windows([w1])
    PoaEngine(backend="jax").consensus_windows([w2])
    assert w1.consensus == w2.consensus


def test_too_few_layers_keeps_backbone():
    w = Window(0, 0, WindowType.TGS, b"ACGTACGT", None)
    w.add_layer(b"ACGTACGT", None, 0, 7)
    eng = PoaEngine(backend="native")
    assert eng.consensus_windows([w]) == 0
    assert w.consensus == b"ACGTACGT"
    assert not w.polished


def test_quality_weights_break_ties():
    # Two high-quality layers voting one base beat two low-quality layers
    # voting another at the disputed position.
    backbone = b"AAAAACAAAA"
    w = Window(0, 0, WindowType.NGS, backbone, None)
    hi = bytes([33 + 40] * 10)
    lo = bytes([33 + 2] * 10)
    w.add_layer(b"AAAAAGAAAA", hi, 0, 9)
    w.add_layer(b"AAAAAGAAAA", hi, 0, 9)
    w.add_layer(b"AAAAATAAAA", lo, 0, 9)
    w.add_layer(b"AAAAATAAAA", lo, 0, 9)
    eng = PoaEngine(backend="native", refine_rounds=0)
    eng.consensus_windows([w])
    assert w.consensus == b"AAAAAGAAAA"


def test_ngs_windows_not_trimmed():
    # NGS windows skip the coverage trim (src/window.cpp:113-134).
    rng = np.random.default_rng(13)
    true = rng.integers(0, 4, 150).astype(np.uint8)
    backbone = decode_bases(true)
    w = Window(0, 0, WindowType.NGS, backbone, None)
    # Layers covering only the middle third.
    seg = backbone[50:100]
    for _ in range(6):
        w.add_layer(seg, None, 50, 99)
    PoaEngine(backend="native").consensus_windows([w])
    # Uncovered flanks survive in NGS mode.
    assert len(w.consensus) >= 140


def test_tgs_trim_drops_uncovered_flanks():
    rng = np.random.default_rng(14)
    true = rng.integers(0, 4, 150).astype(np.uint8)
    backbone = decode_bases(true)
    w = Window(0, 0, WindowType.TGS, backbone, None)
    seg = backbone[50:100]
    for _ in range(6):
        w.add_layer(seg, None, 50, 99)
    PoaEngine(backend="native").consensus_windows([w])
    # Coverage >= n_layers//2 only inside [50, 100).
    assert len(w.consensus) <= 60
