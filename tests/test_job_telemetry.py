"""End-to-end job telemetry tests: cross-process trace propagation
(obs/trace.py), latency histograms (obs/metrics.py + obs/export.py),
and the crash flight recorder (obs/flightrec.py) — the contracts in
docs/OBSERVABILITY.md "Cross-process trace propagation" and
"Post-mortem debugging"."""

import io
import json
import os
import sys

import pytest

from racon_tpu.obs import export as obs_export
from racon_tpu.obs import fleet as obs_fleet
from racon_tpu.obs import flightrec
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.obs import trace as obs_trace
from racon_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def telemetry_sandbox(monkeypatch):
    """Keep the process-global tracer, registry, injector, and flight
    ring out of other tests (and their env out of these)."""
    for env in (faults.ENV_FAULTS, obs_fleet.ENV_OBS_DIR,
                obs_trace.ENV_TRACE, obs_trace.ENV_TRACE_CTX,
                flightrec.ENV_FLIGHT_EVENTS):
        monkeypatch.delenv(env, raising=False)
    def _drop_tracer():
        if isinstance(obs_trace._tracer, obs_trace.Tracer):
            obs_trace._tracer.finish()
        obs_trace._tracer = None

    faults.configure(None)
    obs_metrics.reset()
    flightrec.reset()
    _drop_tracer()
    obs_fleet._WRITER = None
    yield
    faults.configure(None)
    obs_metrics.reset()
    flightrec.reset()
    _drop_tracer()
    obs_fleet._WRITER = None


class _Died(BaseException):
    """Stand-in for os._exit in in-process crash drills."""


@pytest.fixture
def soft_crash(monkeypatch):
    monkeypatch.setattr(faults, "hard_exit",
                        lambda code: (_ for _ in ()).throw(_Died(code)))
    return _Died


# ------------------------------------------------------- trace context


def test_trace_context_roundtrip():
    ctx = obs_trace.mint_trace_context("a" * 64, parent_id=7)
    assert ctx.trace_id == "a" * obs_trace.TRACE_ID_LEN
    assert ctx.parent_id == 7
    assert obs_trace.parse_trace_ctx(ctx.encode()) == ctx
    # The submit point is the root: parent defaults to 0.
    assert obs_trace.mint_trace_context("beef").parent_id == 0


@pytest.mark.parametrize("bad", [
    None, "", "   ", "nocolonhere", ":7", "abc:", "abc:xyz",
    "abc:1.5", 12, b"abc:3"])
def test_parse_trace_ctx_malformed_is_absent(bad):
    assert obs_trace.parse_trace_ctx(bad) is None


def test_adopt_trace_context_tags_spans(tmp_path, monkeypatch):
    monkeypatch.setenv(obs_trace.ENV_TRACE_CTX, "deadbeefcafef00d:42")
    tr = obs_trace.Tracer(str(tmp_path / "t.jsonl"))
    ctx = obs_trace.adopt_trace_context(tracer=tr)
    assert ctx == obs_trace.TraceContext("deadbeefcafef00d", 42)
    with tr.span("phase", "p"):
        pass
    tr.finish()
    spans = [json.loads(ln) for ln in open(tmp_path / "t.jsonl")
             if json.loads(ln).get("ev") == "span"]
    assert spans[0]["trace_id"] == "deadbeefcafef00d"
    assert spans[0]["parent_id"] == 42


def test_adopt_malformed_env_degrades_to_fresh_root(tmp_path,
                                                    monkeypatch):
    """A garbled handoff must NOT crash the worker — it keeps a fresh
    root trace (adoption-edge satellite)."""
    tr = obs_trace.Tracer(str(tmp_path / "t.jsonl"))
    for bad in ("%%%", "abc:notanint", ":", ""):
        monkeypatch.setenv(obs_trace.ENV_TRACE_CTX, bad)
        assert obs_trace.adopt_trace_context(tracer=tr) is None
    monkeypatch.delenv(obs_trace.ENV_TRACE_CTX)
    assert obs_trace.adopt_trace_context(tracer=tr) is None
    with tr.span("phase", "p"):
        pass
    tr.finish()
    spans = [json.loads(ln) for ln in open(tmp_path / "t.jsonl")
             if json.loads(ln).get("ev") == "span"]
    assert "trace_id" not in spans[0]


def test_env_trace_ctx_validates(monkeypatch):
    monkeypatch.setenv(obs_trace.ENV_TRACE_CTX, "abcd:3")
    assert obs_trace.env_trace_ctx() == "abcd:3"
    monkeypatch.setenv(obs_trace.ENV_TRACE_CTX, "garbage")
    assert obs_trace.env_trace_ctx() == ""


def test_serve_span_carries_context(tmp_path, monkeypatch):
    monkeypatch.setenv(obs_trace.ENV_TRACE, str(tmp_path / "t.jsonl"))
    reg = obs_metrics.MetricsRegistry()
    sid = obs_metrics.record_serve_job("submitted", "j1", "t1",
                                       trace_id="cafe1234cafe1234",
                                       reg=reg)
    assert sid > 0
    obs_trace.get_tracer().finish()
    spans = [json.loads(ln) for ln in open(tmp_path / "t.jsonl")
             if json.loads(ln).get("ev") == "span"]
    assert spans[0]["id"] == sid
    assert spans[0]["trace_id"] == "cafe1234cafe1234"
    assert spans[0]["parent_id"] == 0


def test_report_validates_trace_attr_types(tmp_path):
    sys.path.insert(0, REPO)
    from scripts import obs_report
    path = tmp_path / "t.jsonl"
    lines = [
        {"ev": "begin", "schema": 1, "unix_time": 0.0},
        {"ev": "span", "id": 1, "parent": None, "kind": "serve",
         "name": "submitted", "t0": 0.0, "dur_s": 0.0, "job": "j",
         "tenant": "t", "trace_id": 99, "parent_id": "zero"},
    ]
    with open(path, "w") as fh:
        for ln in lines:
            fh.write(json.dumps(ln) + "\n")
    errs = obs_report.validate(obs_report.load_trace(str(path)))
    assert any("trace_id must be a string" in e for e in errs)
    assert any("parent_id must be an integer" in e for e in errs)


# ---------------------------------------------------------- histograms


def test_record_hist_bins_sum_count():
    reg = obs_metrics.MetricsRegistry()
    bounds = obs_metrics.HIST_BUCKETS["serve_queue_wait_s"]
    obs_metrics.record_hist("serve_queue_wait_s", 0.02, reg=reg)
    obs_metrics.record_hist("serve_queue_wait_s", 0.02, reg=reg)
    obs_metrics.record_hist("serve_queue_wait_s", 999.0, reg=reg)
    h = reg.snapshot()["serve_queue_wait_s"]
    assert len(h["buckets"]) == len(bounds) + 1   # + overflow
    assert sum(h["buckets"]) == h["count"] == 3
    assert h["buckets"][-1] == 1                  # the overflow obs
    assert h["sum"] == pytest.approx(999.04)
    # An unknown family is a programming error, not a silent drop.
    with pytest.raises(KeyError):
        obs_metrics.record_hist("zz_not_a_family", 1.0, reg=reg)


def test_hist_quantiles_and_percentiles():
    reg = obs_metrics.MetricsRegistry()
    for v in (0.06, 0.06, 0.3, 0.3, 8.0):
        obs_metrics.record_hist("serve_job_latency_s", v, reg=reg)
    pcts = obs_metrics.hist_percentiles("serve_job_latency_s", reg=reg)
    assert set(pcts) == {"serve_job_latency_s_p50",
                         "serve_job_latency_s_p95",
                         "serve_job_latency_s_p99"}
    assert 0.05 <= pcts["serve_job_latency_s_p50"] <= 0.5
    assert 5.0 <= pcts["serve_job_latency_s_p95"] <= 10.0
    # Empty family: no keys, and the quantile helper answers 0.
    assert obs_metrics.hist_percentiles("serve_queue_wait_s",
                                        reg=reg) == {}
    assert obs_metrics.hist_quantile({"buckets": [], "count": 0},
                                     0.5, (1.0,)) == 0.0


def test_hist_merge_folds_per_bucket():
    ra, rb = obs_metrics.MetricsRegistry(), obs_metrics.MetricsRegistry()
    obs_metrics.record_hist("dispatch_round_s", 0.02, reg=ra)
    obs_metrics.record_hist("dispatch_round_s", 0.3, reg=rb)
    obs_metrics.record_hist("dispatch_round_s", 0.3, reg=rb)
    ha = ra.snapshot()["dispatch_round_s"]
    hb = rb.snapshot()["dispatch_round_s"]
    assert obs_metrics.merge_kind("dispatch_round_s") == \
        obs_metrics.MERGE_HIST
    merged = obs_metrics.merge_values("dispatch_round_s",
                                      [ha, None, hb])
    assert merged["count"] == 3
    assert merged["sum"] == pytest.approx(0.62)
    assert sum(merged["buckets"]) == 3
    assert [a + b for a, b in zip(ha["buckets"], hb["buckets"])] == \
        merged["buckets"]


def test_openmetrics_histogram_render():
    reg = obs_metrics.MetricsRegistry()
    for v in (0.02, 0.3, 0.3, 999.0):
        obs_metrics.record_hist("serve_queue_wait_s", v, reg=reg)
    reg.inc("dist_claims")
    text = obs_export.render_registry(reg.snapshot())
    assert obs_export.validate_openmetrics(text) == []
    assert text == obs_export.render_registry(reg.snapshot())
    assert "# TYPE racon_tpu_serve_queue_wait_s histogram" in text
    # Cumulative le series, closed by +Inf == _count.
    assert 'racon_tpu_serve_queue_wait_s_bucket{le="0.025"} 1' in text
    assert 'racon_tpu_serve_queue_wait_s_bucket{le="0.5"} 3' in text
    assert 'racon_tpu_serve_queue_wait_s_bucket{le="+Inf"} 4' in text
    assert "racon_tpu_serve_queue_wait_s_count 4" in text
    assert "racon_tpu_serve_queue_wait_s_sum 999.62" in text


def test_fleet_render_folds_histograms(tmp_path):
    for wid, values in (("A", (0.02, 0.3)), ("B", (0.3,))):
        reg = obs_metrics.MetricsRegistry()
        for v in values:
            obs_metrics.record_hist("serve_queue_wait_s", v, reg=reg)
        w = obs_fleet.WorkerMetricsWriter(str(tmp_path), wid, "fp1",
                                          reg=reg, interval_s=0.0)
        w.flush(final=True)
    model = obs_fleet.aggregate(str(tmp_path))
    assert model["fleet"]["serve_queue_wait_s"]["count"] == 3
    text = obs_export.render_fleet(model)
    assert obs_export.validate_openmetrics(text) == []
    assert 'racon_tpu_serve_queue_wait_s_bucket{le="+Inf"} 3' in text


# ------------------------------------------------------ flight recorder


def test_flight_ring_is_bounded():
    rec = flightrec.FlightRecorder(4)
    for i in range(10):
        rec.note({"i": i})
    assert [e["i"] for e in rec.events()] == [6, 7, 8, 9]
    off = flightrec.FlightRecorder(0)
    off.note({"i": 1})
    assert off.events() == []


def test_flight_capacity_from_env(monkeypatch):
    monkeypatch.setenv(flightrec.ENV_FLIGHT_EVENTS, "3")
    flightrec.reset()
    assert flightrec.recorder().capacity == 3
    monkeypatch.setenv(flightrec.ENV_FLIGHT_EVENTS, "nope")
    flightrec.reset()
    assert flightrec.recorder().capacity == flightrec.DEFAULT_EVENTS


def test_flight_dump_load_roundtrip(tmp_path):
    flightrec.note_span({"ev": "span", "id": 1, "kind": "phase",
                         "name": "p", "t0": 0.0, "dur_s": 0.1})
    flightrec.note_metric("dist_claims", 2)
    flightrec.note_breach("h2d", 5.0, 7.5, terminal=True)
    path = flightrec.dump(str(tmp_path), reason="unit-test")
    assert os.path.basename(path) == f"flight_{os.getpid()}.json"
    rec = flightrec.load_flight(path)
    assert rec["clean"]
    assert rec["header"]["reason"] == "unit-test"
    assert rec["header"]["events"] == 3
    assert [e["ev"] for e in rec["events"]] == ["span", "metric",
                                                "breach"]
    assert rec["metrics"] is not None
    # Dumps are discoverable and the write accounted for itself.
    assert flightrec.list_flights(str(tmp_path)) == [path]
    snap = obs_metrics.registry().snapshot()
    assert snap["flight_dumps_total"] == 1
    assert snap["flight_dump_write_s"] > 0
    # No resolvable directory: best-effort no-op, never a raise.
    assert flightrec.dump(None, reason="x") == ""


def test_load_flight_rejects_non_dumps(tmp_path):
    p = tmp_path / "flight_1.json"
    p.write_text('{"ev": "span"}\n')
    with pytest.raises(ValueError, match="not a flight dump"):
        flightrec.load_flight(str(p))


def test_torn_flight_dump_loads_as_prefix(tmp_path, soft_crash):
    """The obs/flight drill: a dump torn mid-write (SIGKILL racing the
    flush) must still load as a valid prefix — header plus every
    complete ring line before the tear."""
    for i in range(6):
        flightrec.note_metric("dist_claims", i)
    # Pad the trailing metrics-snapshot line past the tear length so
    # the truncation lands mid-record, not on a line boundary.
    obs_metrics.registry().inc("dist_claims", 123456789)
    obs_metrics.registry().inc("poa_windows_total", 987654321)
    faults.configure("obs/flight:0!torn")
    with pytest.raises(soft_crash):
        flightrec.dump(str(tmp_path), reason="kill")
    faults.configure(None)
    rec = flightrec.load_flight(flightrec.flight_path(str(tmp_path)))
    assert not rec["clean"]                      # the tear is visible
    assert rec["header"]["reason"] == "kill"
    # 6 direct notes + 2 fed through the global-registry incs above.
    assert rec["header"]["events"] == 8
    assert len(rec["events"]) <= 8               # prefix, never junk
    assert all(e["ev"] == "metric" for e in rec["events"])
    # A later clean dump overwrites the torn file atomically.
    path = flightrec.dump(str(tmp_path), reason="retry")
    assert flightrec.load_flight(path)["clean"]


def test_flush_final_dumps_flight_beside_shards(tmp_path):
    obs_fleet.install_writer(str(tmp_path), "W", "fp1",
                             reg=obs_metrics.MetricsRegistry(),
                             interval_s=0.0)
    flightrec.note_metric("dist_claims", 1)
    obs_fleet.flush_final(reason="watchdog-terminal")
    flights = flightrec.list_flights(str(tmp_path))
    assert len(flights) == 1
    rec = flightrec.load_flight(flights[0])
    assert rec["header"]["reason"] == "watchdog-terminal"
    assert obs_fleet.load_worker_shards(str(tmp_path))[0]["records"][-1][
        "final"]


# ------------------------------------------------------ job timelines


def _trace_file(path, begin_unix, spans):
    lines = [{"ev": "begin", "schema": 1, "unix_time": begin_unix}]
    lines.extend(spans)
    with open(path, "w") as fh:
        for ln in lines:
            fh.write(json.dumps(ln) + "\n")


def _span(sid, kind, name, t0, trace_id=None, **attrs):
    s = {"ev": "span", "id": sid, "parent": None, "kind": kind,
         "name": name, "t0": t0, "dur_s": 0.1, **attrs}
    if trace_id is not None:
        s["trace_id"] = trace_id
    return s


TID = "deadbeefcafef00d"


def _three_process_obs(root):
    obs = os.path.join(root, obs_fleet.OBS_SUBDIR)
    os.makedirs(obs, exist_ok=True)
    _trace_file(os.path.join(obs, "daemon.jsonl"), 100.0, [
        _span(1, "serve", "submitted", 0.5, TID, job="j1", tenant="t",
              parent_id=0, run_fp="fp1"),
        _span(2, "phase", "unrelated", 0.6, run_fp="fp1"),
    ])
    # A batch span serving two jobs: comma-joined trace ids match both.
    _trace_file(os.path.join(obs, "worker_A.trace.jsonl"), 101.0, [
        _span(1, "dispatch", "batch", 0.2,
              f"{TID},1111222233334444", run_fp="fp1",
              worker_id="A"),
    ])
    # A hard-killed worker never promoted its .part sidecar — its
    # spans are exactly the interesting ones.
    _trace_file(os.path.join(obs, "worker_B.trace.jsonl.part"), 102.0, [
        _span(1, "phase", "polish", 0.1, TID, run_fp="fp1",
              worker_id="B"),
    ])
    return obs


def test_assemble_job_timeline_stitches_processes(tmp_path):
    _three_process_obs(str(tmp_path))
    tl = obs_fleet.assemble_job_timeline(str(tmp_path), TID)
    assert tl["trace_id"] == TID
    assert tl["n_processes"] == 3
    assert tl["n_spans"] == 3
    assert tl["sources"] == {"daemon.jsonl": 1,
                             "worker_A.trace.jsonl": 1,
                             "worker_B.trace.jsonl.part": 1}
    # Sorted on the common wall clock, not per-file order.
    assert [s["t_abs"] for s in tl["spans"]] == [100.5, 101.2, 102.1]
    assert [s["src"] for s in tl["spans"]] == [
        "daemon.jsonl", "worker_A.trace.jsonl",
        "worker_B.trace.jsonl.part"]


def test_assemble_refuses_unknown_and_mixed(tmp_path):
    obs = _three_process_obs(str(tmp_path))
    with pytest.raises(obs_fleet.FleetObsError, match="no span"):
        obs_fleet.assemble_job_timeline(str(tmp_path), "f" * 16)
    # A stale trace from a previous run sharing the directory: refuse
    # rather than fabricate a timeline that never happened.
    _trace_file(os.path.join(obs, "stale.jsonl"), 90.0, [
        _span(1, "phase", "old", 0.1, TID, run_fp="fp0"),
    ])
    with pytest.raises(obs_fleet.FleetObsError, match="mixed runs"):
        obs_fleet.assemble_job_timeline(str(tmp_path), TID)


def test_obs_report_job_mode_renders_timeline(tmp_path):
    sys.path.insert(0, REPO)
    from scripts import obs_report
    _three_process_obs(str(tmp_path))
    # A flight dump beside the traces renders in the same report.
    flightrec.note_metric("dist_claims", 1)
    flightrec.dump(os.path.join(str(tmp_path), obs_fleet.OBS_SUBDIR),
                   reason="drill")
    out = io.StringIO()
    assert obs_report._render_job(str(tmp_path), TID, out=out) == 0
    text = out.getvalue()
    assert f"job {TID}: 3 span(s) across 3 process(es)" in text
    assert "worker_B.trace.jsonl.part" in text
    assert "serve/submitted" in text
    assert "reason=drill" in text
    # Unknown trace ids are loud errors, never empty reports.
    assert obs_report._render_job(str(tmp_path), "f" * 16,
                                  out=io.StringIO()) == 1


def test_obs_report_flags_stale_throughput(tmp_path):
    sys.path.insert(0, REPO)
    from scripts import obs_report
    m = {"serve_jobs_submitted": 1, "serve_jobs_per_min": 2.0,
         "serve_rate_wall_s": 100.0}
    budget = (obs_export.SUPERVISOR_STALE_FACTOR *
              obs_fleet.DEFAULT_FLUSH_S)
    out = io.StringIO()
    obs_report._render_server(m, {}, out,
                              trace_end_unix=100.0 + budget + 1.0)
    assert "[STALE: gauges last updated" in out.getvalue()
    out = io.StringIO()
    obs_report._render_server(m, {}, out,
                              trace_end_unix=100.0 + budget - 1.0)
    assert "STALE" not in out.getvalue()
    # No stamp (pre-telemetry snapshots): no flag, no crash.
    out = io.StringIO()
    obs_report._render_server({"serve_jobs_submitted": 1,
                               "serve_jobs_per_min": 2.0}, {}, out,
                              trace_end_unix=1e9)
    assert "STALE" not in out.getvalue()
