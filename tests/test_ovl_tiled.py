"""Tiled banded overlap alignment (ops/ovl_align.py round 7).

The tiled path runs the band forward kernel over query-axis tiles of T
rows, carrying the DP frontier (score row + packed N/U/C metadata +
last-row capture) between tiles and re-centering the band anchor at
tile boundaries. Its exactness contract: with the dead-zone anchor
fixed (no drift), every tile computes the SAME cells as the untiled
kernel, so outputs are bit-identical; with drift, the stitched walk and
the staircase escape certificate must still yield the native-identical
breaking points or hand the lane back uncertified.

These tests pin, bottom-up:
  * the frontier carry at the kernel level (chained tiled twin ==
    untiled twin on dirs/nxt/hlast),
  * chunk-level bit-identity vs the untiled chunk (single tile and
    multi-tile, no drift),
  * anchor re-centering through a controlled diagonal excursion,
  * polisher-level device-vs-native layer equality on reads past the
    ~9 kb untiled ceiling, with the registry confirming zero native
    fallbacks,
  * the independent over-budget / uncertified fallback accounting,
  * the RACON_TPU_OVL_TILED env gate.
"""

import io

import numpy as np
import pytest

import jax.numpy as jnp

from racon_tpu.models.polisher import create_polisher, PolisherType
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.ops import budget, ovl_align
from racon_tpu.ops.ovl_align import (_chunk_breaking_points,
                                     _tiled_chunk_breaking_points)
from racon_tpu.ops.pallas.band_kernel import (UC_BOUNDARY, band_geometry,
                                              fw_dirs_band_xla,
                                              fw_dirs_band_xla_tile)

from test_ovl_align import _layer_snapshot, _write_dataset

_BASES = np.frombuffer(b"ACGT", np.uint8)


def _mutate_codes(rng, tgt, err):
    """Mutate a 0..3 code array at ``err`` total error (del/sub/ins in
    equal thirds), returning the query codes."""
    out = []
    for base in tgt:
        r = rng.random()
        if r < err / 3:
            continue
        elif r < 2 * err / 3:
            out.append(int(rng.integers(0, 4)))
        else:
            out.append(int(base))
        if rng.random() < err / 3:
            out.append(int(rng.integers(0, 4)))
    return np.array(out, np.uint8)


def _mk_chunk(rng, read_len, err, B, Lq, LA):
    """B lanes of mutated (query, target) code pairs padded to the
    chunk geometry, mirroring the dispatcher's packing."""
    q = np.zeros((B, Lq), np.uint8)
    t = np.zeros((B, LA), np.uint8)
    lq = np.ones(B, np.int32)
    lt = np.ones(B, np.int32)
    t_begin = np.zeros(B, np.int32)
    for b in range(B):
        tgt = rng.integers(0, 4, read_len).astype(np.uint8)
        qq = _mutate_codes(rng, tgt, err)
        q[b, :len(qq)] = qq
        t[b, :len(tgt)] = tgt
        lq[b] = len(qq)
        lt[b] = len(tgt)
        t_begin[b] = int(rng.integers(0, 700))
    return q, t, lq, lt, t_begin


def _run_both(q, t, lq, lt, t_begin, *, W, T, Lq, LA,
              scoring=(0, -1, -1)):
    m, x, g = scoring
    kw = dict(match=m, mismatch=x, gap=g, W=W, w_len=500,
              NW=LA // 500 + 2, Lq=Lq, LA=LA)
    out_u = [np.asarray(a) for a in _chunk_breaking_points(
        q, t, lq, lt, t_begin, pallas=False, **kw)]
    out_t = [np.asarray(a) for a in _tiled_chunk_breaking_points(
        q, t, lq, lt, t_begin, T=T, tb=q.shape[0], ch=4, pallas=False,
        **kw)]
    return out_u, out_t


def test_single_tile_chunk_bit_identity():
    """One tile covering the whole read: the tiled chunk must reproduce
    the untiled chunk bit-for-bit on every output field, and certify
    every lane (fail == 0) at 10% error."""
    rng = np.random.default_rng(11)
    q, t, lq, lt, t_begin = _mk_chunk(rng, 1800, 0.10, B=8,
                                      Lq=2048, LA=2048)
    out_u, out_t = _run_both(q, t, lq, lt, t_begin, W=512, T=2048,
                             Lq=2048, LA=2048)
    for i, (a, b) in enumerate(zip(out_u, out_t)):
        assert np.array_equal(a, b), f"field {i} differs"
    assert not out_u[5].any()


def test_multi_tile_no_drift_chunk_bit_identity():
    """Two tiles, anchor never re-centers (drift stays in the dead
    zone): the frontier carry must make the stitched result identical
    to the untiled chunk."""
    rng = np.random.default_rng(12)
    q, t, lq, lt, t_begin = _mk_chunk(rng, 3900, 0.08, B=8,
                                      Lq=4096, LA=4096)
    out_u, out_t = _run_both(q, t, lq, lt, t_begin, W=512, T=2048,
                             Lq=4096, LA=4096)
    for i, (a, b) in enumerate(zip(out_u, out_t)):
        assert np.array_equal(a, b), f"field {i} differs"
    assert not out_u[5].any()
    # The klos observability field reports one row per tile; with the
    # anchor in the dead zone it never moves.
    klos = out_t[6]
    assert klos.shape[0] == 2
    assert np.array_equal(klos[0], klos[1])


def test_frontier_carry_matches_untiled_twin():
    """Kernel-level: chaining the tiled XLA twin across tiles with the
    carried (prev, uc, hlast) frontier reproduces the untiled twin's
    dirs/nxt/hlast exactly (same klo, so no re-centering involved)."""
    rng = np.random.default_rng(0)
    B, Lq, W, T = 8, 64, 128, 32
    lq = rng.integers(40, Lq + 1, B).astype(np.int32)
    lt = (lq + rng.integers(-5, 6, B)).clip(5).astype(np.int32)
    qT = rng.integers(0, 4, (Lq, B)).astype(np.uint8)
    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    ts = rng.integers(0, 4, (B, int(lt.max()))).astype(np.uint8)

    def band_window(row0, height):
        win = np.full((B, height), 7, np.uint8)
        for b in range(B):
            for y in range(height):
                j = klo_h[b] + row0 + y
                if 0 <= j < lt[b]:
                    win[b, y] = ts[b, j]
        return win

    M, X, G = 0, -1, -1
    du, nu, hu = fw_dirs_band_xla(jnp.asarray(band_window(0, W + Lq)),
                                  jnp.asarray(qT), klo, jnp.asarray(lq),
                                  match=M, mismatch=X, gap=G, W=W)

    NEG = -(2 ** 30)
    j0 = klo_h[:, None] + np.arange(W)[None, :]
    prev = jnp.asarray(np.where(j0 >= 0, j0 * G, NEG).astype(np.int32))
    uc = jnp.asarray(np.full((B, W), UC_BOUNDARY, np.int32))
    hl = prev
    ds, ns = [], []
    for tile in range(Lq // T):
        i0 = jnp.full((B,), tile * T, jnp.int32)
        d, n, hl, prev, uc = fw_dirs_band_xla_tile(
            jnp.asarray(band_window(tile * T, W + T)),
            jnp.asarray(qT[tile * T:(tile + 1) * T]),
            klo, jnp.asarray(lq), i0, prev, uc, hl,
            match=M, mismatch=X, gap=G, W=W)
        ds.append(np.asarray(d))
        ns.append(np.asarray(n))
    assert np.array_equal(np.concatenate(ds, axis=0), np.asarray(du))
    assert np.array_equal(np.concatenate(ns, axis=0), np.asarray(nu))
    assert np.array_equal(np.asarray(hl), np.asarray(hu))


def test_anchor_recentering_tracks_excursion():
    """A controlled diagonal excursion (300 spread deletions followed by
    300 spread insertions, net delta = 0) pushes the frontier argmax out
    of the dead zone, so the anchor must re-center mid-read — and the
    stitched walk through the re-centered tiles must still match the
    untiled chunk (whose straight W=1024 band also holds the path)."""
    rng = np.random.default_rng(4)
    n = 2000
    qq = rng.integers(0, 4, n).astype(np.uint8)
    # Rows 500..1400 drift to diagonal -300 (delete every 3rd base),
    # rows 1400..2000 drift back to 0 (insert after every 2nd base).
    mid = np.array([b for i, b in enumerate(qq[500:1400]) if i % 3 != 0],
                   np.uint8)
    tail = []
    for i, b in enumerate(qq[1400:2000]):
        tail.append(int(b))
        if i % 2 == 1:
            tail.append(int(rng.integers(0, 4)))
    tt = np.concatenate([qq[:500], mid, np.array(tail, np.uint8)])
    assert len(tt) == n  # net delta 0, excursion -300

    B, W, T, Lq, LA = 8, 1024, 256, 2048, 2048
    q = np.zeros((B, Lq), np.uint8)
    t = np.zeros((B, LA), np.uint8)
    q[0, :n] = qq
    t[0, :n] = tt
    # Lanes 1..7: drift-free copies; their anchor must never move.
    for b in range(1, B):
        q[b, :n] = qq
        t[b, :n] = qq
    lq = np.full(B, n, np.int32)
    lt = np.full(B, n, np.int32)
    t_begin = np.zeros(B, np.int32)

    out_u, out_t = _run_both(q, t, lq, lt, t_begin, W=W, T=T,
                             Lq=Lq, LA=LA)
    # Certified: the -300 excursion stays under the re-centered band's
    # clearance, and ED (<= 600) is under the staircase bound.
    assert not out_t[5].any()
    # The excursion lane re-centered at least once; drift-free lanes
    # never did.
    klos = out_t[6]
    assert len(np.unique(klos[:, 0])) > 1
    for b in range(1, B):
        assert len(np.unique(klos[:, b])) == 1
    # Same breaking points as the untiled band.
    for i, (a, b) in enumerate(zip(out_u, out_t)):
        assert np.array_equal(a, b), f"field {i} differs"


@pytest.mark.parametrize("read_len,rate", [(12_000, 0.03)])
def test_ultralong_device_matches_native(tmp_path, read_len, rate):
    """Reads past the untiled ~9 kb ceiling route through the tiled
    device path and must produce byte-identical layers to the native
    aligner — with ZERO native fallbacks, confirmed via the registry."""
    d = _write_dataset(tmp_path, n_reads=4, read_len=read_len, seed=11,
                       rate=rate)
    args = (f"{d}/reads.fasta.gz", f"{d}/overlaps.paf.gz",
            f"{d}/draft.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
            5, -4, -8)
    pn = create_polisher(*args, backend="native")
    pn.initialize()
    obs_metrics.reset()
    pj = create_polisher(*args, backend="jax")
    pj.initialize()
    assert _layer_snapshot(pj) == _layer_snapshot(pn)
    reg = obs_metrics.registry()
    assert reg.get("ovl_native_jobs") == 0
    assert reg.get("ovl_device_jobs") == 4
    assert reg.get("ovl_tiles_exec") >= 2
    assert reg.get("ovl_device_fraction") == 1.0
    assert float(reg.get("align_phase_seconds")) > 0


@pytest.mark.parametrize("read_len,rate", [(24_000, 0.025),
                                           (48_000, 0.025)])
def test_ultralong_deep_matches_native(tmp_path, read_len, rate):
    """Tier-boundary coverage at ONT-class lengths: 24 kb and 48 kb
    reads both land in the 16-lane W=2048 tier and must stay device-
    handled and native-identical."""
    d = _write_dataset(tmp_path, n_reads=2, read_len=read_len, seed=3,
                       rate=rate, draft_len=read_len + 12_000)
    args = (f"{d}/reads.fasta.gz", f"{d}/overlaps.paf.gz",
            f"{d}/draft.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
            5, -4, -8)
    pn = create_polisher(*args, backend="native")
    pn.initialize()
    obs_metrics.reset()
    pj = create_polisher(*args, backend="jax")
    pj.initialize()
    assert _layer_snapshot(pj) == _layer_snapshot(pn)
    reg = obs_metrics.registry()
    assert reg.get("ovl_native_jobs") == 0
    assert reg.get("ovl_device_jobs") == 2


class _FakeOverlap:
    """Minimal overlap stub for driving device_breaking_points
    directly (classification + accounting, no PAF plumbing)."""

    strand = False

    def __init__(self, q, t):
        self._q, self._t = q, t
        self.q_begin, self.q_end, self.q_length = 0, len(q), len(q)
        self.t_begin = 0
        self.breaking_points = None

    def alignment_operands(self, sequences):
        return self._q, self._t


def _random_seq(rng, n):
    return _BASES[rng.integers(0, 4, n)].tobytes()


def test_fallback_accounting_counts_causes_independently():
    """One over-budget job (130 kb: no tile tier fits) plus one
    uncertified job (1.2 kb of unrelated sequence: escape bound fails)
    in the same batch must be reported as '1 over the device length
    budget, 1 uncertified' — the round-6 subtraction lumped both into
    one bucket."""
    rng = np.random.default_rng(2)
    big = _random_seq(rng, 130_000)
    pending = [
        _FakeOverlap(_random_seq(rng, 1200), _random_seq(rng, 1200)),
        _FakeOverlap(big, big),
    ]
    obs_metrics.reset()
    buf = io.StringIO()
    fb = ovl_align.device_breaking_points(
        pending, None, 500, match=5, mismatch=-4, gap=-8, log=buf)
    assert set(id(o) for o in fb) == set(id(o) for o in pending)
    assert "1 over the device length budget, 1 uncertified" in buf.getvalue()
    reg = obs_metrics.registry()
    assert reg.get("ovl_device_jobs") == 0
    assert reg.get("ovl_native_jobs") == 2
    assert reg.get("ovl_device_fraction") == 0.0


def test_tiled_env_gate_off_routes_native(monkeypatch):
    """RACON_TPU_OVL_TILED=0 disables the tiled path: an ultralong job
    that WOULD plan (tile_plan admits it) must fall back as over-budget
    without dispatching any device work."""
    assert budget.tile_plan(10_000, 10_000) is not None
    monkeypatch.setenv("RACON_TPU_OVL_TILED", "0")
    rng = np.random.default_rng(8)
    o = _FakeOverlap(_random_seq(rng, 10_000), _random_seq(rng, 10_000))
    obs_metrics.reset()
    buf = io.StringIO()
    fb = ovl_align.device_breaking_points(
        [o], None, 500, match=5, mismatch=-4, gap=-8, log=buf)
    assert fb == [o]
    assert "exceed the device length budget" in buf.getvalue()
    reg = obs_metrics.registry()
    assert reg.get("ovl_native_jobs") == 1
    assert reg.get("ovl_device_jobs") == 0
    assert reg.get("ovl_tiles_exec") == 0
