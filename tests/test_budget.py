"""Shared element/VMEM budget (ops/budget.py): boundary geometries.

Round 5's bug class: the consensus engine capped dirs planes at a
hand-written 1.6e9 while the overlap aligner re-derived 1.9e9, and the
0.7% gap silently routed every 8 kb genome overlap (128 x 8192 x 1536
= 1.61e9 elements) to the native fallback. These tests pin (a) the two
consumers import the SAME derived cap, (b) the cap's boundary admits
the genome geometry and rejects what the constraints forbid, (c) the
VMEM tile picker still admits the genome geometry now that the
dual-column nxt plane doubled the row-tile term.
"""

import numpy as np

from racon_tpu.ops import budget
from racon_tpu.ops import device_poa
from racon_tpu.ops import ovl_align

# The geometry the round-5 literal wrongly rejected: 128 lanes of 8 kb
# reads at the W=1536 long-read band.
GENOME_ELEMS = 128 * 8192 * 1536            # 1,610,612,736


def test_consumers_share_one_cap():
    assert device_poa.MAX_DIR_ELEMS == budget.max_dir_elems(1)
    assert ovl_align.MAX_DIR_ELEMS == budget.max_dir_elems(1)


def test_u8_cap_admits_genome_geometry():
    cap = budget.max_dir_elems(1)
    assert cap == 1_932_735_283
    assert GENOME_ELEMS <= cap
    # ~2.2e9 violates both the int32 flat index and the 2 GB buffer.
    assert 128 * 8192 * 2176 > cap


def test_cap_never_exceeds_hard_constraints():
    for cb in (1, 2, 4):
        cap = budget.max_dir_elems(cb)
        assert cap < budget.INT32_INDEX_ELEMS
        assert cap * cb < budget.BUFFER_BYTES


def test_u16_cells_would_reject_genome_geometry():
    # Why the dual-column metadata ships as a second u8 plane and not a
    # widened u16 cell word: the 2 GB buffer ceiling halves the cap.
    assert budget.max_dir_elems(2) == 966_367_641
    assert GENOME_ELEMS > budget.max_dir_elems(2)


def test_pick_tiles_admits_genome_geometry_at_ch4():
    # The nxt plane doubled vmem_est's row-tile dirs term; without the
    # ch=4 tier the 8 kb genome tile (W=1536, Lq=8192) that fit at ch=8
    # would be evicted from VMEM admission.
    W, Lq = 1536, 8192
    assert budget.vmem_est(W, Lq, 8) > budget.VMEM_BUDGET
    assert budget.vmem_est(W, Lq, 4) <= budget.VMEM_BUDGET
    tb, ch = ovl_align._pick_tiles(W, Lq)
    assert (tb, ch) == (ovl_align.TB, 4)
    assert ovl_align.TB * Lq * W <= ovl_align.MAX_DIR_ELEMS


def test_vmem_model_monotone_in_ch():
    for W, Lq in ((128, 256), (768, 4096), (1536, 8192)):
        ests = [budget.vmem_est(W, Lq, ch) for ch in (4, 8, 32)]
        assert ests == sorted(ests)
        assert all(e > 0 for e in ests)


def test_cell_bytes_validation():
    try:
        budget.max_dir_elems(0)
    except ValueError:
        pass
    else:
        raise AssertionError("cell_bytes=0 must be rejected")
