"""Shared element/VMEM budget (ops/budget.py): boundary geometries.

Round 5's bug class: the consensus engine capped dirs planes at a
hand-written 1.6e9 while the overlap aligner re-derived 1.9e9, and the
0.7% gap silently routed every 8 kb genome overlap (128 x 8192 x 1536
= 1.61e9 elements) to the native fallback. These tests pin (a) the two
consumers import the SAME derived cap, (b) the cap's boundary admits
the genome geometry and rejects what the constraints forbid, (c) the
VMEM tile picker still admits the genome geometry now that the
dual-column nxt plane doubled the row-tile term.
"""

import numpy as np

from racon_tpu.ops import budget
from racon_tpu.ops import device_poa
from racon_tpu.ops import ovl_align

# The geometry the round-5 literal wrongly rejected: 128 lanes of 8 kb
# reads at the W=1536 long-read band.
GENOME_ELEMS = 128 * 8192 * 1536            # 1,610,612,736


def test_consumers_share_one_cap():
    assert device_poa.MAX_DIR_ELEMS == budget.max_dir_elems(1)
    assert ovl_align.MAX_DIR_ELEMS == budget.max_dir_elems(1)


def test_u8_cap_admits_genome_geometry():
    cap = budget.max_dir_elems(1)
    assert cap == 1_932_735_283
    assert GENOME_ELEMS <= cap
    # ~2.2e9 violates both the int32 flat index and the 2 GB buffer.
    assert 128 * 8192 * 2176 > cap


def test_cap_never_exceeds_hard_constraints():
    for cb in (1, 2, 4):
        cap = budget.max_dir_elems(cb)
        assert cap < budget.INT32_INDEX_ELEMS
        assert cap * cb < budget.BUFFER_BYTES


def test_u16_cells_would_reject_genome_geometry():
    # Why the dual-column metadata ships as a second u8 plane and not a
    # widened u16 cell word: the 2 GB buffer ceiling halves the cap.
    assert budget.max_dir_elems(2) == 966_367_641
    assert GENOME_ELEMS > budget.max_dir_elems(2)


def test_pick_tiles_admits_genome_geometry_at_ch4():
    # The nxt plane doubled vmem_est's row-tile dirs term; without the
    # ch=4 tier the 8 kb genome tile (W=1536, Lq=8192) that fit at ch=8
    # would be evicted from VMEM admission.
    W, Lq = 1536, 8192
    assert budget.vmem_est(W, Lq, 8) > budget.VMEM_BUDGET
    assert budget.vmem_est(W, Lq, 4) <= budget.VMEM_BUDGET
    tb, ch = ovl_align._pick_tiles(W, Lq)
    assert (tb, ch) == (ovl_align.TB, 4)
    assert ovl_align.TB * Lq * W <= ovl_align.MAX_DIR_ELEMS


def test_vmem_model_monotone_in_ch():
    for W, Lq in ((128, 256), (768, 4096), (1536, 8192)):
        ests = [budget.vmem_est(W, Lq, ch) for ch in (4, 8, 32)]
        assert ests == sorted(ests)
        assert all(e > 0 for e in ests)


def test_cell_bytes_validation():
    try:
        budget.max_dir_elems(0)
    except ValueError:
        pass
    else:
        raise AssertionError("cell_bytes=0 must be rejected")


# ---------------------------------------------------------------------------
# Tiled overlap admission (round 7): every tier and every plan the picker
# can emit must stay under the int32 flat-index cap, the 2 GB buffer
# ceiling, and the per-tile VMEM budget.
# ---------------------------------------------------------------------------


def test_tile_tiers_respect_all_budgets():
    cap = budget.max_dir_elems(1)
    for lanes, W, T, ch in budget.TILE_TIERS:
        # Per-tile kernel blocks fit VMEM.
        assert budget.vmem_est(W, T, ch) <= budget.VMEM_BUDGET
        # The tier admits at least one tile's worth of rows under the
        # element cap (otherwise it could never fire).
        assert lanes * T * W <= cap
        # Tile height divides into kernel row-chunks and the grid.
        assert T % ch == 0
        # Lane counts stay powers of two so the adaptive lane halving in
        # the dispatcher always lands on a valid kernel batch.
        assert lanes & (lanes - 1) == 0


def test_tile_plan_results_never_exceed_budgets():
    cap = budget.max_dir_elems(1)
    for lq in (9_000, 12_000, 19_000, 32_768, 48_000, 57_000,
               100_000, 114_000):
        plan = budget.tile_plan(lq, lq + 500)
        assert plan is not None, lq
        # Stitched dirs/nxt planes stay addressable by a flat int32
        # index and under the 2 GB single-buffer ceiling.
        assert plan.lanes * plan.Lq * plan.W <= cap
        assert budget.vmem_est(plan.W, plan.T, plan.ch) <= budget.VMEM_BUDGET
        # Padded length covers the read and divides exactly into tiles.
        assert plan.Lq >= lq
        assert plan.Lq % plan.T == 0
        assert plan.n_tiles == plan.Lq // plan.T


def test_tile_plan_tier_boundaries():
    # ~9 kb (just past the untiled ceiling) still fits the 64-lane tier;
    # 32 kb overflows its element cap (64 * 32768 * 1536 = 3.2e9) and
    # drops to the 16-lane tier; ~100 kb needs the 8-lane T=4096 tier.
    assert budget.tile_plan(9_000, 9_100).lanes == 64
    assert budget.tile_plan(32_768, 33_000).lanes == 16
    assert budget.tile_plan(100_000, 101_000).lanes == 8


def test_tile_plan_rejects_untrackable_jobs():
    # Past the last tier's element cap: no plan, caller goes native.
    assert budget.tile_plan(130_000, 130_500) is None
    # Length imbalance beyond W // 2 leaves no clearance for the band to
    # hold both DP corners, even with re-centering.
    assert budget.tile_plan(20_000, 24_000) is None
    # Degenerate operands clamp to one tile instead of dividing by zero
    # (the dispatcher screens empty jobs before planning anyway).
    assert budget.tile_plan(0, 0).Lq == budget.TILE_TIERS[0][2]
