"""Shared element/VMEM budget (ops/budget.py): boundary geometries.

Round 5's bug class: the consensus engine capped dirs planes at a
hand-written 1.6e9 while the overlap aligner re-derived 1.9e9, and the
0.7% gap silently routed every 8 kb genome overlap (128 x 8192 x 1536
= 1.61e9 elements) to the native fallback. These tests pin (a) the two
consumers import the SAME derived cap, (b) the cap's boundary admits
the genome geometry and rejects what the constraints forbid, (c) the
VMEM tile picker still admits the genome geometry now that the
dual-column nxt plane doubled the row-tile term.
"""

import numpy as np

from racon_tpu.ops import budget
from racon_tpu.ops import device_poa
from racon_tpu.ops import ovl_align

# The geometry the round-5 literal wrongly rejected: 128 lanes of 8 kb
# reads at the W=1536 long-read band.
GENOME_ELEMS = 128 * 8192 * 1536            # 1,610,612,736


def test_consumers_share_one_cap():
    assert device_poa.MAX_DIR_ELEMS == budget.max_dir_elems(1)
    assert ovl_align.MAX_DIR_ELEMS == budget.max_dir_elems(1)


def test_u8_cap_admits_genome_geometry():
    cap = budget.max_dir_elems(1)
    assert cap == 1_932_735_283
    assert GENOME_ELEMS <= cap
    # ~2.2e9 violates both the int32 flat index and the 2 GB buffer.
    assert 128 * 8192 * 2176 > cap


def test_cap_never_exceeds_hard_constraints():
    for cb in (1, 2, 4):
        cap = budget.max_dir_elems(cb)
        assert cap < budget.INT32_INDEX_ELEMS
        assert cap * cb < budget.BUFFER_BYTES


def test_u16_cells_would_reject_genome_geometry():
    # Why the dual-column metadata ships as a second u8 plane and not a
    # widened u16 cell word: the 2 GB buffer ceiling halves the cap.
    assert budget.max_dir_elems(2) == 966_367_641
    assert GENOME_ELEMS > budget.max_dir_elems(2)


def test_pick_tiles_admits_genome_geometry_at_ch4():
    # The nxt plane doubled vmem_est's row-tile dirs term; without the
    # ch=4 tier the 8 kb genome tile (W=1536, Lq=8192) that fit at ch=8
    # would be evicted from VMEM admission.
    W, Lq = 1536, 8192
    assert budget.vmem_est(W, Lq, 8) > budget.VMEM_BUDGET
    assert budget.vmem_est(W, Lq, 4) <= budget.VMEM_BUDGET
    tb, ch = ovl_align._pick_tiles(W, Lq)
    assert (tb, ch) == (ovl_align.TB, 4)
    assert ovl_align.TB * Lq * W <= ovl_align.MAX_DIR_ELEMS


def test_vmem_model_monotone_in_ch():
    for W, Lq in ((128, 256), (768, 4096), (1536, 8192)):
        ests = [budget.vmem_est(W, Lq, ch) for ch in (4, 8, 32)]
        assert ests == sorted(ests)
        assert all(e > 0 for e in ests)


def test_cell_bytes_validation():
    try:
        budget.max_dir_elems(0)
    except ValueError:
        pass
    else:
        raise AssertionError("cell_bytes=0 must be rejected")


# ---------------------------------------------------------------------------
# Tiled overlap admission (round 7): every tier and every plan the picker
# can emit must stay under the int32 flat-index cap, the 2 GB buffer
# ceiling, and the per-tile VMEM budget.
# ---------------------------------------------------------------------------


def test_tile_tiers_respect_all_budgets():
    cap = budget.max_dir_elems(1)
    for lanes, W, T, ch in budget.TILE_TIERS:
        # Per-tile kernel blocks fit VMEM.
        assert budget.vmem_est(W, T, ch) <= budget.VMEM_BUDGET
        # The tier admits at least one tile's worth of rows under the
        # element cap (otherwise it could never fire).
        assert lanes * T * W <= cap
        # Tile height divides into kernel row-chunks and the grid.
        assert T % ch == 0
        # Lane counts stay powers of two so the adaptive lane halving in
        # the dispatcher always lands on a valid kernel batch.
        assert lanes & (lanes - 1) == 0


def test_tile_plan_results_never_exceed_budgets():
    cap = budget.max_dir_elems(1)
    for lq in (9_000, 12_000, 19_000, 32_768, 48_000, 57_000,
               100_000, 114_000):
        plan = budget.tile_plan(lq, lq + 500)
        assert plan is not None, lq
        # Stitched dirs/nxt planes stay addressable by a flat int32
        # index and under the 2 GB single-buffer ceiling.
        assert plan.lanes * plan.Lq * plan.W <= cap
        assert budget.vmem_est(plan.W, plan.T, plan.ch) <= budget.VMEM_BUDGET
        # Padded length covers the read and divides exactly into tiles.
        assert plan.Lq >= lq
        assert plan.Lq % plan.T == 0
        assert plan.n_tiles == plan.Lq // plan.T


def test_tile_plan_tier_boundaries():
    # ~9 kb (just past the untiled ceiling) still fits the 64-lane tier;
    # 32 kb overflows its element cap (64 * 32768 * 1536 = 3.2e9) and
    # drops to the 16-lane tier; ~100 kb needs the 8-lane T=4096 tier.
    assert budget.tile_plan(9_000, 9_100).lanes == 64
    assert budget.tile_plan(32_768, 33_000).lanes == 16
    assert budget.tile_plan(100_000, 101_000).lanes == 8


def test_tile_plan_rejects_untrackable_jobs():
    # Past the last tier's element cap: no plan, caller goes native.
    assert budget.tile_plan(130_000, 130_500) is None
    # Length imbalance beyond W // 2 leaves no clearance for the band to
    # hold both DP corners, even with re-centering.
    assert budget.tile_plan(20_000, 24_000) is None
    # Degenerate operands clamp to one tile instead of dividing by zero
    # (the dispatcher screens empty jobs before planning anyway).
    assert budget.tile_plan(0, 0).Lq == budget.TILE_TIERS[0][2]


# ---------------------------------------------------------------------------
# Walk-depth admission (round 8): the k=4 nxt2 plane costs one u16 plane
# of elements and doubles vmem_est's metadata planes term. Every tier's
# admission decision is pinned here — a drifting estimate would either
# OOM VMEM on TPU or silently degrade the bench chain back to 321.
# ---------------------------------------------------------------------------


def test_vmem_est_nxt_k_term():
    # The deep plane doubles the per-row metadata planes (u8 nxt +
    # u16 nxt2 = 3 bytes padded to two u32-backed planes vs one).
    for W, T, ch in ((128, 640, 4), (1536, 2048, 4)):
        base = budget.vmem_est(W, T, ch)
        assert budget.vmem_est(W, T, ch, 2) == base
        assert budget.vmem_est(W, T, ch, 4) == base + 128 * W * 4 * ch


def test_walk_k_env_validation(monkeypatch):
    monkeypatch.delenv(budget.WALK_K_ENV, raising=False)
    assert budget.walk_k_env() == 4                # round-8 default
    for v in ("1", "2", "4"):
        monkeypatch.setenv(budget.WALK_K_ENV, v)
        assert budget.walk_k_env() == int(v)
    monkeypatch.setenv(budget.WALK_K_ENV, "3")
    try:
        budget.walk_k_env()
    except ValueError:
        pass
    else:
        raise AssertionError("walk depth 3 must be rejected")


def test_walk_k_for_element_boundary():
    # The u16 plane makes the forward's largest buffer 2 bytes/cell, so
    # k=4 admission is gated by max_dir_elems(2) exactly.
    cap2 = budget.max_dir_elems(2)
    assert budget.walk_k_for(cap2) == 4
    assert budget.walk_k_for(cap2 + 1) == 2
    # The 8 kb genome overlap geometry (1.61e9 elements) exceeds it:
    # the untiled dispatcher degrades those buckets to the dual walk.
    assert budget.walk_k_for(GENOME_ELEMS) == 2
    # Bench consensus geometry admits the quad walk -> chain 161.
    assert budget.walk_k_for(2048 * 640 * 128) == 4
    # An explicit env override caps, never raises, the derived depth.
    assert budget.walk_k_for(2048 * 640 * 128, env_k=2) == 2
    assert budget.walk_k_for(2048 * 640 * 128, env_k=1) == 1
    assert budget.walk_k_for(GENOME_ELEMS, env_k=4) == 2


def test_tile_plan_walk_depth_per_tier():
    # TilePlan carries its walk depth, and the bucket key includes it so
    # lanes with different depths never share one kernel dispatch.
    p = budget.tile_plan(8_192, 8_292)
    assert p.nxt_k == 4                   # 64-lane tier, Lq=8192: both
    assert p.key() == (64, 1536, 2048, 4, 4)  # gates pass (pins below)
    assert 64 * p.Lq * p.W <= budget.max_dir_elems(2)
    assert budget.vmem_est(p.W, p.T, p.ch, 4) <= budget.VMEM_BUDGET
    # One tile row higher (Lq pads to 10240): element cap degrades to 2.
    assert budget.tile_plan(9_000, 9_100).nxt_k == 2
    assert 64 * 10_240 * 1536 > budget.max_dir_elems(2)
    # The W=2048 tiers never admit k=4 — their deep-plane VMEM blocks
    # overflow the 12 MiB budget at any row chunk.
    assert budget.vmem_est(2048, 2048, 4, 4) > budget.VMEM_BUDGET
    assert budget.vmem_est(2048, 4096, 4, 4) > budget.VMEM_BUDGET
    assert budget.tile_plan(32_768, 33_000).nxt_k == 2
    assert budget.tile_plan(100_000, 101_000).nxt_k == 2
    # Every emitted plan's depth is self-consistent with both gates.
    for lq in (9_000, 12_000, 32_768, 100_000, 114_000):
        plan = budget.tile_plan(lq, lq + 500)
        if plan.nxt_k >= 4:
            assert plan.lanes * plan.Lq * plan.W <= budget.max_dir_elems(2)
            assert budget.vmem_est(plan.W, plan.T, plan.ch, 4) \
                <= budget.VMEM_BUDGET


# ---------------------------------------- decoupled-walk queue budget


def test_walk_plane_bytes_per_depth():
    # u8 dirs always; +u8 nxt at k>=2; +u16 nxt2 at k>=4 — 1/2/4 bytes
    # per cell by walk depth.
    assert budget.walk_plane_bytes(1024, 512, 256, 1) == 1024 * 512 * 256
    assert budget.walk_plane_bytes(1024, 512, 256, 2) \
        == 2 * 1024 * 512 * 256
    assert budget.walk_plane_bytes(1024, 512, 256, 4) \
        == 4 * 1024 * 512 * 256 == 536_870_912
    # Bench consensus geometry at the narrowed final band (W=192, k=4):
    # one queued chunk parks ~1.0 GB of planes.
    assert budget.walk_plane_bytes(2048, 640, 192, 4) == 1_006_632_960


def test_walk_queue_budget_pins():
    # Same 9/10-margin discipline as the single-buffer caps: the queue
    # gets one 2 GB buffer's worth of HBM, shared across queued chunks.
    assert budget.WALK_QUEUE_BYTES == 1_932_735_283
    bench_pb = budget.walk_plane_bytes(2048, 640, 192, 4)
    # Bench geometry admits exactly ONE queued chunk — the classic
    # depth-2 pipeline still overlaps (one walking + one queued is
    # checked as want+1 by the streaming admission).
    assert budget.walk_queue_depth(bench_pb, 4) == 1
    assert budget.walk_queue_depth(bench_pb, 1) == 1
    # Small geometries keep the requested depth.
    small = budget.walk_plane_bytes(256, 128, 192, 4)
    assert budget.walk_queue_depth(small, 2) == 2
    # want <= 0 is off; an oversized plane clamps to 0, never admits.
    assert budget.walk_queue_depth(bench_pb, 0) == 0
    assert budget.walk_queue_depth(budget.WALK_QUEUE_BYTES + 1, 3) == 0
    assert budget.walk_queue_depth(0, 3) == 3


def test_walk_queue_env_validation(monkeypatch):
    monkeypatch.delenv(budget.WALK_QUEUE_ENV, raising=False)
    assert budget.walk_queue_env(2) == 2           # empty -> default
    monkeypatch.setenv(budget.WALK_QUEUE_ENV, "3")
    assert budget.walk_queue_env(2) == 3
    monkeypatch.setenv(budget.WALK_QUEUE_ENV, "0")
    assert budget.walk_queue_env(2) == 0           # explicit off
    for bad in ("-1", "two"):
        monkeypatch.setenv(budget.WALK_QUEUE_ENV, bad)
        try:
            budget.walk_queue_env(2)
        except ValueError:
            pass
        else:
            raise AssertionError(f"{bad!r} must be rejected")
