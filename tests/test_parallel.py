"""Multi-chip sharding tests on the 8-virtual-device CPU mesh.

conftest.py forces JAX_PLATFORMS=cpu with
--xla_force_host_platform_device_count=8 before jax is imported, so these
run without TPU hardware; the same code paths drive real chips.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from racon_tpu.ops.align import nw_align_batch, nw_scores
from racon_tpu.parallel.dispatch import (make_mesh, nw_align_batch_sharded,
                                         sp_nw_align, sp_nw_scores)


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(5)
    B, Lq, Lt = 6, 40, 64
    q = np.zeros((B, Lq), np.uint8)
    t = np.zeros((B, Lt), np.uint8)
    lq = rng.integers(5, Lq, B).astype(np.int32)
    lt = rng.integers(8, Lt, B).astype(np.int32)
    for b in range(B):
        q[b, :lq[b]] = rng.integers(0, 4, lq[b])
        t[b, :lt[b]] = rng.integers(0, 4, lt[b])
    return q, t, lq, lt


def test_eight_cpu_devices_present():
    assert len(jax.devices()) >= 8
    assert all(d.platform == "cpu" for d in jax.devices())


def test_dp_sharded_align_equals_single_device(batch):
    q, t, lq, lt = batch
    mesh = make_mesh(8, axes=("dp",))
    ops_s, n_s = nw_align_batch_sharded(mesh, q, t, lq, lt,
                                        match=5, mismatch=-4, gap=-8)
    ops_r, n_r = nw_align_batch(jnp.asarray(q), jnp.asarray(t),
                                jnp.asarray(lq), jnp.asarray(lt),
                                match=5, mismatch=-4, gap=-8)
    assert np.array_equal(np.asarray(n_r), n_s)
    assert np.array_equal(np.asarray(ops_r), ops_s)


def test_sp_sequence_parallel_scores_equal_single_device(batch):
    q, t, lq, lt = batch
    mesh = make_mesh(8, axes=("dp", "sp"))
    assert mesh.shape["sp"] > 1  # genuinely sharded target axis
    sc_sp = sp_nw_scores(mesh, q, t, lq, lt, match=5, mismatch=-4, gap=-8)
    sc_r = np.asarray(nw_scores(jnp.asarray(q), jnp.asarray(t),
                                jnp.asarray(lq), jnp.asarray(lt),
                                match=5, mismatch=-4, gap=-8))
    assert np.array_equal(sc_r, sc_sp)


def test_sp_sequence_parallel_align_matches_single_device(batch):
    """Full sp traceback (VERDICT r3 #8): the target-sharded forward +
    replicated psum walk must reproduce the single-device alignment
    bit-for-bit (same DP values, same DIAG>UP>LEFT tie rule)."""
    q, t, lq, lt = batch
    mesh = make_mesh(8, axes=("dp", "sp"))
    assert mesh.shape["sp"] > 1
    ops_s, n_s = sp_nw_align(mesh, q, t, lq, lt,
                             match=5, mismatch=-4, gap=-8)
    ops_r, n_r = nw_align_batch(jnp.asarray(q), jnp.asarray(t),
                                jnp.asarray(lq), jnp.asarray(lt),
                                match=5, mismatch=-4, gap=-8)
    assert np.array_equal(np.asarray(n_r), n_s)
    assert np.array_equal(np.asarray(ops_r), ops_s)


def test_engine_with_mesh_matches_engine_without():
    from racon_tpu.models.window import Window, WindowType
    from racon_tpu.ops.encode import decode_bases
    from racon_tpu.ops.poa import PoaEngine

    rng = np.random.default_rng(6)
    true = rng.integers(0, 4, 120).astype(np.uint8)
    backbone = decode_bases(true)

    def build():
        w = Window(0, 0, WindowType.TGS, backbone, None)
        for k in range(5):
            lay = bytearray(backbone)
            lay[10 + k] = ord("T") if lay[10 + k] != ord("T") else ord("A")
            w.add_layer(bytes(lay), None, 0, len(backbone) - 1)
        return w

    w_single = build()
    w_mesh = build()
    PoaEngine(backend="jax").consensus_windows([w_single])
    PoaEngine(backend="jax",
              mesh=make_mesh(8, axes=("dp",))).consensus_windows([w_mesh])
    assert w_single.consensus == w_mesh.consensus


def test_sharded_device_engine_noisy_windows():
    """device_round_sharded on the 8-device mesh must reproduce the
    single-device engine bit-for-bit on realistic noisy windows (psum'd
    vote accumulators, jobs of one window spread across shards)."""
    from bench import build_windows
    from racon_tpu.ops.poa import PoaEngine

    ws_ref = build_windows(10, 6, 130, seed=11)
    ws_dp = build_windows(10, 6, 130, seed=11)
    assert PoaEngine(backend="jax").consensus_windows(ws_ref) == 10
    mesh = make_mesh(8, axes=("dp",))
    assert PoaEngine(backend="jax",
                     mesh=mesh).consensus_windows(ws_dp) == 10
    # The psum reassociates f32 vote sums vs the unsharded matmul, so a
    # sub-ulp tie can legitimately flip a near-tied column; require
    # near-total agreement rather than strict bit equality.
    same = sum(a.consensus == b.consensus for a, b in zip(ws_ref, ws_dp))
    assert same >= 9, f"only {same}/10 windows identical"


def test_sp_routing_for_over_budget_windows():
    """A window whose alignment jobs exceed the single-chip dirs budget
    must route through the sequence-parallel NW when the mesh has an
    "sp" axis, and produce a consensus bit-equal to the pure host path
    (VERDICT r4 missing #4: sp was test-only plumbing before)."""
    from racon_tpu.models.window import Window, WindowType
    from racon_tpu.ops.encode import decode_bases
    from racon_tpu.ops.poa import PoaEngine

    rng = np.random.default_rng(9)
    true = rng.integers(0, 4, 160).astype(np.uint8)
    backbone = decode_bases(true)

    def build():
        w = Window(0, 0, WindowType.TGS, backbone, None)
        for k in range(4):
            lay = bytearray(backbone)
            lay[30 + 3 * k] = ord("ACGT"[(true[30 + 3 * k] + 1) % 4])
            w.add_layer(bytes(lay), None, 0, len(backbone) - 1)
        return w

    w_host = build()
    w_sp = build()
    # Host reference (no mesh, native aligner).
    PoaEngine(backend="native").consensus_windows([w_host])
    # sp-routed: shrink the budget so these 160x160 jobs overflow it.
    eng = PoaEngine(backend="native", mesh=make_mesh(8, axes=("dp", "sp")))
    eng.sp_cell_budget = 10_000
    jobs_seen = []
    orig = eng._align_sp

    def spy(jobs):
        jobs_seen.extend(jobs)
        return orig(jobs)

    eng._align_sp = spy
    eng.consensus_windows([w_sp])
    assert jobs_seen, "no job routed through the sp aligner"
    assert w_host.consensus == w_sp.consensus


def test_graft_entry_single_chip():
    import __graft_entry__ as graft
    fn, args = graft.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    assert np.asarray(out).shape == (64,)


# ---------------------------------------------- fault-site coverage

def test_h2d_align_fault_site_retried(batch):
    """The declared h2d/align site is live: a one-shot injected fault
    on the sharded upload is absorbed by one retry and the result stays
    bit-identical (lint rule FLT002 requires every declared site to be
    exercised)."""
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.resilience import faults, retry
    q, t, lq, lt = batch
    mesh = make_mesh(8, axes=("dp",))
    ops_r, n_r = nw_align_batch(jnp.asarray(q), jnp.asarray(t),
                                jnp.asarray(lq), jnp.asarray(lt),
                                match=5, mismatch=-4, gap=-8)
    retry.configure(retry.RetryPolicy(attempts=2, base=0.0, jitter=0.0))
    faults.configure("h2d/align:0")
    try:
        ops_s, n_s = nw_align_batch_sharded(mesh, q, t, lq, lt,
                                            match=5, mismatch=-4, gap=-8)
        snap = obs_metrics.registry().snapshot()
    finally:
        retry.configure(None)
        faults.configure(None)
        obs_metrics.reset()
    assert snap["res_fault_injected_total"] >= 1
    assert snap["res_retry_total"] >= 1
    assert np.array_equal(np.asarray(n_r), n_s)
    assert np.array_equal(np.asarray(ops_r), ops_s)


def test_d2h_sp_fault_site_retried(batch):
    """Same drill for the d2h/sp pull on the sequence-parallel path."""
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.resilience import faults, retry
    q, t, lq, lt = batch
    mesh = make_mesh(8, axes=("dp", "sp"))
    sc_r = np.asarray(nw_scores(jnp.asarray(q), jnp.asarray(t),
                                jnp.asarray(lq), jnp.asarray(lt),
                                match=5, mismatch=-4, gap=-8))
    retry.configure(retry.RetryPolicy(attempts=2, base=0.0, jitter=0.0))
    faults.configure("d2h/sp:0")
    try:
        sc_sp = sp_nw_scores(mesh, q, t, lq, lt,
                             match=5, mismatch=-4, gap=-8)
        snap = obs_metrics.registry().snapshot()
    finally:
        retry.configure(None)
        faults.configure(None)
        obs_metrics.reset()
    assert snap["res_fault_injected_total"] >= 1
    assert snap["res_retry_total"] >= 1
    assert np.array_equal(sc_r, sc_sp)
