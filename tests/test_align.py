"""Tests for the batched NW aligner: JAX device kernel, native C++ banded
aligner, and their agreement with a plain numpy oracle."""

import numpy as np
import pytest

from racon_tpu.ops.align import (DIAG, UP, LEFT, nw_align_batch, nw_scores,
                                 nw_oracle, ops_to_cigar)
from racon_tpu.native.aligner import NativeAligner


def _score_of_ops(q, t, ops, m, x, g):
    qi = ti = s = 0
    for d in ops:
        if d == DIAG:
            s += m if q[qi] == t[ti] else x
            qi += 1
            ti += 1
        elif d == UP:
            s += g
            qi += 1
        else:
            s += g
            ti += 1
    assert qi == len(q) and ti == len(t)
    return s


SCORINGS = [(5, -4, -8), (0, -1, -1), (1, -1, -1)]


@pytest.mark.parametrize("scoring", SCORINGS)
def test_jax_kernel_matches_oracle(scoring):
    import jax.numpy as jnp
    m, x, g = scoring
    rng = np.random.default_rng(0)
    B, Lq, Lt = 12, 48, 56
    q = np.zeros((B, Lq), np.uint8)
    t = np.zeros((B, Lt), np.uint8)
    lq = rng.integers(1, Lq + 1, B).astype(np.int32)
    lt = rng.integers(1, Lt + 1, B).astype(np.int32)
    for b in range(B):
        q[b, :lq[b]] = rng.integers(0, 5, lq[b])
        t[b, :lt[b]] = rng.integers(0, 5, lt[b])
    ops, n = nw_align_batch(jnp.asarray(q), jnp.asarray(t), jnp.asarray(lq),
                            jnp.asarray(lt), match=m, mismatch=x, gap=g)
    sc = nw_scores(jnp.asarray(q), jnp.asarray(t), jnp.asarray(lq),
                   jnp.asarray(lt), match=m, mismatch=x, gap=g)
    ops, n, sc = np.asarray(ops), np.asarray(n), np.asarray(sc)
    W = ops.shape[1]
    for b in range(B):
        o = ops[b, W - n[b]:]
        osc, oops = nw_oracle(q[b, :lq[b]], t[b, :lt[b]], m, x, g)
        s = _score_of_ops(q[b, :lq[b]], t[b, :lt[b]], o, m, x, g)
        assert s == osc == sc[b]
        # identical tie-breaking -> identical path
        assert np.array_equal(o, oops)


@pytest.mark.parametrize("scoring", SCORINGS)
def test_native_matches_oracle(scoring):
    m, x, g = scoring
    rng = np.random.default_rng(1)
    al = NativeAligner(m, x, g)
    for _ in range(60):
        lq = int(rng.integers(1, 200))
        lt = int(rng.integers(1, 200))
        q = rng.integers(0, 5, lq).astype(np.uint8)
        t = rng.integers(0, 5, lt).astype(np.uint8)
        ops = al.align_codes(q, t)
        osc, _ = nw_oracle(q, t, m, x, g)
        assert _score_of_ops(q, t, ops, m, x, g) == osc


def test_native_band_doubling_long_indel():
    # Large length imbalance forces the adaptive band to grow.
    rng = np.random.default_rng(2)
    t = rng.integers(0, 4, 4000).astype(np.uint8)
    q = np.concatenate([t[:1000], t[3000:]])  # 2000-base deletion
    al = NativeAligner()
    ops = al.align_codes(q, t)
    osc, _ = nw_oracle(q, t, 0, -1, -1)
    assert _score_of_ops(q, t, ops, 0, -1, -1) == osc == -2000


def test_native_band_stability_balanced_indel():
    """Adversarial case for band acceptance (VERDICT r3 #7 / ADVICE r2 #1):
    swapped blocks give equal lengths (diagonal offset 0) but the optimal
    path deviates |X| off-diagonal — a balanced long insertion+deletion.
    An in-band mismatch-heavy path exists that never touches the
    artificial band edge, so untouched-edge acceptance alone returned a
    sub-optimal CIGAR from the initial 128-wide band; the score must be
    stable across one band doubling before acceptance (edlib is exact,
    reference call site src/overlap.cpp:198-213)."""
    rng = np.random.default_rng(4)
    X = rng.integers(0, 4, 300).astype(np.uint8)
    Z = rng.integers(0, 4, 1200).astype(np.uint8)
    q = np.concatenate([X, Z])
    t = np.concatenate([Z, X])
    for m, x, g in SCORINGS:
        adaptive = NativeAligner(m, x, g)
        exact = NativeAligner(m, x, g, band=10_000)  # full matrix
        sa = _score_of_ops(q, t, adaptive.align_codes(q, t), m, x, g)
        se = _score_of_ops(q, t, exact.align_codes(q, t), m, x, g)
        assert sa == se, (m, x, g, sa, se)


def test_native_batch_threaded_matches_serial():
    rng = np.random.default_rng(5)
    pairs = []
    for _ in range(64):
        lq = int(rng.integers(1, 400))
        lt = int(rng.integers(1, 400))
        pairs.append((rng.integers(0, 5, lq).astype(np.uint8),
                      rng.integers(0, 5, lt).astype(np.uint8)))
    serial = NativeAligner(threads=1).align_batch(pairs)
    threaded = NativeAligner(threads=8).align_batch(pairs)
    for a, b in zip(serial, threaded):
        assert np.array_equal(a, b)


def test_native_full_band_matches_jax_path():
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    lq, lt = 90, 100
    q = rng.integers(0, 4, lq).astype(np.uint8)
    t = rng.integers(0, 4, lt).astype(np.uint8)
    al = NativeAligner(5, -4, -8, band=10_000)  # full matrix
    native_ops = al.align_codes(q, t)
    ops, n = nw_align_batch(jnp.asarray(q[None]), jnp.asarray(t[None]),
                            jnp.asarray([lq], np.int32),
                            jnp.asarray([lt], np.int32),
                            match=5, mismatch=-4, gap=-8)
    jax_ops = np.asarray(ops)[0, ops.shape[1] - int(n[0]):]
    assert np.array_equal(native_ops, jax_ops)


def test_batch_api_empty_and_edge():
    al = NativeAligner()
    assert al.align_batch([]) == []
    ops = al.align_codes(np.zeros(0, np.uint8), np.array([1, 2], np.uint8))
    assert list(ops) == [LEFT, LEFT]
    ops = al.align_codes(np.array([1, 2], np.uint8), np.zeros(0, np.uint8))
    assert list(ops) == [UP, UP]


def test_ops_to_cigar():
    assert ops_to_cigar(np.array([], np.uint8)) == b""
    assert ops_to_cigar(np.array([0, 0, 1, 2, 2, 0], np.uint8)) == \
        b"2M1I2D1M"
