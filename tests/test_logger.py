"""Logger formatting/behavior tests (reference logger parity plus the
non-TTY fallback — utils/logger.py)."""

import io
import re

from racon_tpu.utils.logger import Logger, NullLogger


class _Tty(io.StringIO):
    def isatty(self):
        return True


def test_phase_format():
    s = io.StringIO()
    log = Logger(stream=s)
    log.begin()
    log.phase("[x] loaded")
    out = s.getvalue()
    assert re.fullmatch(r"\[x\] loaded \d+\.\d{6} s\n", out), out


def test_total_format():
    s = io.StringIO()
    log = Logger(stream=s)
    log.total("[x] total =")
    assert re.fullmatch(r"\[x\] total = \d+\.\d{6} s\n", s.getvalue())


def test_tick_tty_redraws_with_cr():
    s = _Tty()
    log = Logger(stream=s)
    log.begin()
    log.tick("[x] working")
    log.tick("[x] working")
    out = s.getvalue()
    # Carriage-return redraw, no newline until the bar completes.
    assert out.count("\r") == 2
    assert "\n" not in out
    assert "[==                  ]" in out


def test_tick_tty_bar_completes_with_newline():
    s = _Tty()
    log = Logger(stream=s)
    log.begin()
    for _ in range(20):
        log.tick("[x] working")
    out = s.getvalue()
    assert out.endswith("s\n")
    assert "[====================]" in out


def test_tick_non_tty_plain_lines():
    """Non-TTY stderr (log files, CI pipes): one complete line per tick,
    no '\\r' anywhere — a redrawn bar in a log is one garbled mega-line."""
    s = io.StringIO()
    log = Logger(stream=s)
    log.begin()
    for _ in range(3):
        log.tick("[x] working")
    out = s.getvalue()
    assert "\r" not in out
    lines = out.splitlines()
    assert len(lines) == 3
    assert "[=                   ]" in lines[0]
    assert "[===                 ]" in lines[2]


def test_phase_closes_partial_tty_bar():
    """A phase print after a partial bar must start on a fresh line."""
    s = _Tty()
    log = Logger(stream=s)
    log.begin()
    log.tick("[x] working")
    log.phase("[x] done")
    out = s.getvalue()
    # The partial bar line is terminated before the phase line prints.
    assert "\n[x] done" in out


def test_line_closes_partial_tty_bar():
    s = _Tty()
    log = Logger(stream=s)
    log.begin()
    log.tick("[x] working")
    log.line("[x] diagnostic")
    assert "\n[x] diagnostic\n" in s.getvalue()


def test_bar_resets_per_phase():
    s = io.StringIO()
    log = Logger(stream=s)
    log.begin()
    for _ in range(5):
        log.tick("[x] a")
    log.phase("[x] a done")
    log.begin()
    log.tick("[x] b")
    # New phase's bar starts from one '=' again.
    assert "[=                   ]" in s.getvalue().splitlines()[-1]


def test_with_prefix_tags_messages_and_shares_state():
    s = io.StringIO()
    log = Logger(stream=s)
    view = log.with_prefix("[pack] ")
    view.line("starting")
    log.line("plain")
    nested = view.with_prefix("sub: ")
    nested.line("deep")
    lines = s.getvalue().splitlines()
    assert lines == ["[pack] starting", "plain", "[pack] sub: deep"]
    # The view shares the parent's stream (and therefore its lock).
    assert view.stream is log.stream


def test_with_prefix_is_thread_safe():
    """Concurrent stages ticking through prefixed views must never
    interleave mid-line — every emitted line is exactly one tick."""
    import threading
    s = io.StringIO()
    log = Logger(stream=s)

    def work(tag):
        v = log.with_prefix(f"[{tag}] ")
        for _ in range(50):
            v.tick("working")

    threads = [threading.Thread(target=work, args=(t,))
               for t in ("a", "b", "c")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = s.getvalue().splitlines()
    assert len(lines) == 150
    assert all(re.fullmatch(
        r"\[[abc]\] working \[[= ]{20}\] \d+\.\d{6} s", ln)
        for ln in lines), lines[:5]


def test_null_logger_with_prefix_is_self():
    log = NullLogger()
    assert log.with_prefix("[x] ") is log


def test_null_logger_is_silent_and_safe():
    log = NullLogger()
    log.begin()
    log.phase("msg")
    for _ in range(25):
        log.tick("msg")
    log.line("msg")
    log.total("msg")
    # Its stream is inert (never a real fd) and reports non-TTY.
    assert log.stream.isatty() is False
    assert log.stream.write("x") == 1
    log.stream.flush()
