"""Tests for the convergence-aware refinement scheduler (racon_tpu/sched/).

Covers the survivor-repacking planner (shape buckets, padding, lane-index
round-trip), the telemetry counters, the scale-schedule validation, and —
the load-bearing part — bit-identity of the scheduled engine against the
fixed-round engine (RACON_TPU_SCHED=0) on every control-flow path the
chunk driver has: the fused tail (low convergence), the repack loop (high
convergence), full early exit (every window converges), and the repack
loop under a dp mesh (repacked chunks must stay dp-shardable).
"""

import numpy as np
import pytest

from racon_tpu.models.window import Window, WindowType
from racon_tpu.ops.encode import decode_bases
from racon_tpu.sched import (ConvergenceScheduler, RepackPlan,
                             SchedTelemetry, sched_enabled)


# --------------------------------------------------------------- RepackPlan


def _toy_plan(n_shards=1):
    # 8 current rows (6 real + 2 padded), dummy row id 8, original trash
    # row 100. Survivors: rows 0, 2, 3, 6.
    surv = np.array([1, 0, 1, 1, 0, 0, 1, 0], bool)
    win = np.array([0, 0, 1, 2, 2, 3, 4, 5, 6, 8, 8, 8], np.int32)
    orig_ids = np.array([10, 11, 12, 13, 14, 15, 16, 17], np.int32)
    return surv, win, orig_ids, RepackPlan(surv, win, orig_ids, trash=100,
                                           n_shards=n_shards)


def test_repack_plan_shape_buckets():
    from racon_tpu.ops.device_poa import _bucket_b, _round_up
    for n_shards in (1, 4, 8):
        _, _, _, plan = _toy_plan(n_shards=n_shards)
        assert plan.n_surv == 4
        assert plan.n_win == 32                      # 32-grid window rows
        assert plan.B % (128 * n_shards) == 0        # dp-shardable lanes
        assert plan.B == _round_up(_bucket_b(max(plan.n_lanes, 1)),
                                   128 * n_shards)
        assert plan.B >= plan.n_lanes


def test_repack_plan_padding():
    surv, win, orig_ids, plan = _toy_plan()
    n_win_cur = surv.shape[0]
    # Real new rows map to the surviving old rows, in ascending order.
    assert plan.win_map[:plan.n_surv].tolist() == [0, 2, 3, 6]
    assert plan.win_real[:plan.n_surv].all()
    assert plan.orig_ids[:plan.n_surv].tolist() == [10, 12, 13, 16]
    # Padded rows and the new dummy row point at the OLD dummy row and
    # the output trash row, so their writes land harmlessly.
    assert (plan.win_map[plan.n_surv:] == n_win_cur).all()
    assert not plan.win_real[plan.n_surv:].any()
    assert (plan.orig_ids[plan.n_surv:] == 100).all()
    # Padded lanes gather lane 0 (the fill masks re-dummy them) and
    # belong to the new dummy window.
    assert (plan.lane_idx[plan.n_lanes:] == 0).all()
    assert (plan.new_win[plan.n_lanes:] == plan.n_win).all()


def test_repack_plan_lane_round_trip():
    surv, win, orig_ids, plan = _toy_plan()
    # Surviving lanes, original order preserved.
    assert plan.n_lanes == 6
    assert plan.lane_idx[:plan.n_lanes].tolist() == [0, 1, 3, 4, 5, 8]
    assert np.all(np.diff(plan.lane_idx[:plan.n_lanes]) > 0)
    # Round trip: a new lane's window must resolve to the same ORIGINAL
    # output row its old lane's window did.
    for i in range(plan.n_lanes):
        old_lane = plan.lane_idx[i]
        assert (orig_ids[win[old_lane]]
                == plan.orig_ids[plan.new_win[i]])


# ------------------------------------------------- scheduler host-side bits


def test_scheduler_rejects_varying_scales():
    with pytest.raises(ValueError, match="uniform"):
        ConvergenceScheduler(match=5, mismatch=-4, gap=-8,
                             scales=(0.1, 0.2, 0.6))
    with pytest.raises(ValueError, match="empty"):
        ConvergenceScheduler(match=5, mismatch=-4, gap=-8, scales=())
    s = ConvergenceScheduler(match=5, mismatch=-4, gap=-8,
                             scales=(0.2, 0.2, 0.2, 0.6))
    assert s.rounds == 4 and s.scale == 0.2 and s.scale_final == 0.6


def test_sched_enabled_env(monkeypatch):
    monkeypatch.delenv("RACON_TPU_SCHED", raising=False)
    assert sched_enabled()
    monkeypatch.setenv("RACON_TPU_SCHED", "0")
    assert not sched_enabled()
    monkeypatch.setenv("RACON_TPU_SCHED", "false")
    assert not sched_enabled()
    monkeypatch.setenv("RACON_TPU_SCHED", "1")
    assert sched_enabled()


def test_telemetry_counters():
    t = SchedTelemetry(4)
    t.record_chunk(10)
    for r in range(2):
        t.record_round(r, 10)
    t.record_freeze(2, 6)          # 6 windows froze after 2 rounds
    t.record_round(2, 4)
    t.record_round(3, 4)
    t.record_freeze(4, 4)          # the rest ran the full schedule
    t.record_repack(0.25)
    assert t.windows == 10 and t.chunks == 1
    assert sum(t.hist.values()) == t.windows
    assert t.survivor_frac() == [1.0, 1.0, 0.4, 0.4]
    assert t.rounds_saved_frac() == pytest.approx(1 - 28 / 40)
    ex = t.as_extras()
    assert ex["sched_rounds_hist"] == {"2": 6, "4": 4}
    assert ex["sched_repack_overhead_s"] == 0.25
    assert ex["sched_dispatches_saved"] == 0
    assert "windows=10" in t.summary()


# ------------------------------------------------- differential bit-identity


def _noisy(rng, seq, rate):
    out = []
    for b in seq:
        r = rng.random()
        if r < rate / 3:
            continue
        elif r < 2 * rate / 3:
            out.append(int(rng.integers(0, 4)))
        elif r < rate:
            out.append(int(b))
            out.append(int(rng.integers(0, 4)))
        else:
            out.append(int(b))
    return decode_bases(np.array(out, np.uint8))


def _noisy_batch(seed, n, wlen, layers, rate=0.1):
    rng = np.random.default_rng(seed)
    ws = []
    for _ in range(n):
        true = rng.integers(0, 4, wlen).astype(np.uint8)
        backbone = _noisy(rng, true, rate)
        w = Window(0, 0, WindowType.TGS, backbone, None)
        for _ in range(layers):
            w.add_layer(_noisy(rng, true, rate), None, 0,
                        len(backbone) - 1)
        ws.append(w)
    return ws


def _stable_batch(seed, n, wlen, layers=6):
    """Windows whose layers equal the backbone: the merge is a fixed
    point after round 1, so detection must freeze them at rounds_used=2."""
    rng = np.random.default_rng(seed)
    ws = []
    for _ in range(n):
        backbone = decode_bases(rng.integers(0, 4, wlen).astype(np.uint8))
        w = Window(0, 0, WindowType.TGS, backbone, None)
        for _ in range(layers):
            w.add_layer(backbone, None, 0, len(backbone) - 1)
        ws.append(w)
    return ws


def _mixed_batch():
    """>32 real windows so the survivor set can halve the 32-grid window
    bucket: 28 self-converging + 8 noisy forces the repack path."""
    return _stable_batch(31, 28, 160) + _noisy_batch(32, 8, 160, 8)


def _polish(factory, sched, monkeypatch, mesh=None):
    from racon_tpu.ops.poa import PoaEngine
    monkeypatch.setenv("RACON_TPU_SCHED", "1" if sched else "0")
    ws = factory()
    eng = PoaEngine(backend="jax", mesh=mesh)
    eng.consensus_windows(ws)
    return [w.consensus for w in ws], eng


def test_sched_bit_identical_fused_tail(monkeypatch):
    # 10% noise rarely reaches an exact fixed point, so the survivor set
    # stays in the original shape bucket and the driver fuses the tail.
    factory = lambda: _noisy_batch(21, 10, 200, 8)
    ref, _ = _polish(factory, False, monkeypatch)
    out, eng = _polish(factory, True, monkeypatch)
    assert out == ref
    t = eng.sched_telemetry
    assert t.windows == 10
    assert sum(t.hist.values()) == 10


def test_sched_bit_identical_repack(monkeypatch):
    ref, _ = _polish(_mixed_batch, False, monkeypatch)
    out, eng = _polish(_mixed_batch, True, monkeypatch)
    assert out == ref
    t = eng.sched_telemetry
    # Every self-converging window froze right after the detection round.
    assert t.hist.get(2, 0) >= 28
    assert t.rounds_saved_frac() > 0.3
    assert sum(t.hist.values()) == t.windows == 36


def test_sched_full_early_exit(monkeypatch):
    factory = lambda: _stable_batch(41, 8, 150)
    ref, _ = _polish(factory, False, monkeypatch)
    out, eng = _polish(factory, True, monkeypatch)
    assert out == ref
    t = eng.sched_telemetry
    assert t.hist == {2: 8}
    # Rounds 2 and 3 never dispatched.
    assert t.dispatches_saved == 2
    assert t.rounds_saved_frac() == pytest.approx(0.5)


def test_sched_repack_under_dp_mesh(monkeypatch):
    # Acceptance: repacked chunks must remain dp-shardable. Quality-less
    # layers keep the psum'd vote weights integral, so the sharded merge
    # is exact and the comparison can demand bit equality.
    from racon_tpu.parallel.dispatch import make_mesh
    mesh = make_mesh(8, axes=("dp",))
    ref, _ = _polish(_mixed_batch, False, monkeypatch, mesh=mesh)
    out, eng = _polish(_mixed_batch, True, monkeypatch, mesh=mesh)
    assert out == ref
    assert eng.sched_telemetry.hist.get(2, 0) >= 28
