"""Elastic-fleet supervisor tests: the autoscale policy, worker argv
derivation, fault-plan loading, the heartbeat record, and the /healthz
fleet view (racon_tpu/distributed/autoscaler.py, obs/export.py,
docs/DISTRIBUTED.md "Elastic fleets").

The control loop's end-to-end behaviour (spawn/retire/replace real
subprocesses, makespan bound, byte-identical merge) is the multi-
process drill scripts/chaos_bench.py --smoke, wired into ci.sh.
"""

import io
import json
import os
import time

import pytest

from racon_tpu.distributed import autoscaler as asc
from racon_tpu.distributed.ledger import LedgerError
from racon_tpu.obs import export as obs_export
from racon_tpu.obs import fleet as obs_fleet


@pytest.fixture(autouse=True)
def autoscale_sandbox(monkeypatch):
    for env in (asc.ENV_MIN, asc.ENV_MAX, asc.ENV_INTERVAL,
                asc.ENV_MAX_SPAWNS, asc.ENV_DEADLINE,
                asc.ENV_FAULT_PLAN):
        monkeypatch.delenv(env, raising=False)
    yield


# --------------------------------------------------------------- policy


def test_policy_defaults_and_env(monkeypatch):
    pol = asc.AutoscalePolicy.from_env(default_max=4)
    assert (pol.min_workers, pol.max_workers) == (1, 4)
    assert pol.interval_s == 0.5
    assert pol.max_spawns == 16           # max(8, 4 * MAX)
    assert pol.deadline_s == 0.0          # no deadline
    monkeypatch.setenv(asc.ENV_MIN, "2")
    monkeypatch.setenv(asc.ENV_MAX, "6")
    monkeypatch.setenv(asc.ENV_INTERVAL, "0.01")  # clamped to 0.05
    monkeypatch.setenv(asc.ENV_MAX_SPAWNS, "40")
    monkeypatch.setenv(asc.ENV_DEADLINE, "120")
    pol = asc.AutoscalePolicy.from_env(default_max=4)
    assert (pol.min_workers, pol.max_workers) == (2, 6)
    assert pol.interval_s == 0.05
    assert (pol.max_spawns, pol.deadline_s) == (40, 120.0)
    monkeypatch.setenv(asc.ENV_MAX, "oops")
    with pytest.raises(LedgerError, match="not a number"):
        asc.AutoscalePolicy.from_env(default_max=4)
    monkeypatch.setenv(asc.ENV_MAX, "1")
    monkeypatch.setenv(asc.ENV_MIN, "5")
    with pytest.raises(LedgerError, match="MIN 5 > MAX 1"):
        asc.AutoscalePolicy.from_env(default_max=4)


def test_decide_clamps_to_open_work():
    pol = asc.AutoscalePolicy(1, 4, 0.5, 16, 0.0)
    # Meta unpublished: spawn at MAX optimistically.
    assert asc.decide(None, pol) == 4
    assert asc.decide(0, pol) == 1        # MIN floor (merge pending)
    assert asc.decide(2, pol) == 2
    assert asc.decide(9, pol) == 4        # MAX ceiling


def test_worker_argv_strips_supervisor_flags():
    raw = ["--backend", "jax", "--autoscale", "--worker-id", "sup",
           "--ledger-dir", "L", "--worker-id=sup2", "reads.fa"]
    assert asc.worker_argv(raw) == ["--backend", "jax",
                                    "--ledger-dir", "L", "reads.fa"]


def test_fault_plan_loads_and_validates(tmp_path, monkeypatch):
    log = io.StringIO()
    assert asc._load_fault_plan(log) == []    # no plan: all clean
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(["dist/shard:0!kill", "", "skew=1"]))
    monkeypatch.setenv(asc.ENV_FAULT_PLAN, str(path))
    assert asc._load_fault_plan(log) == ["dist/shard:0!kill", "",
                                         "skew=1"]
    assert "2 faulted spawn(s) of 3" in log.getvalue()
    path.write_text(json.dumps({"not": "a list"}))
    with pytest.raises(LedgerError, match="JSON list"):
        asc._load_fault_plan(log)
    monkeypatch.setenv(asc.ENV_FAULT_PLAN, str(tmp_path / "missing"))
    with pytest.raises(LedgerError, match="unreadable fault plan"):
        asc._load_fault_plan(log)


# ------------------------------------------------------------ heartbeat


def _scaler(tmp_path):
    return asc.Autoscaler(str(tmp_path / "ledger"), ["--backend",
                          "jax"], policy=asc.AutoscalePolicy(
                              1, 2, 0.1, 8, 0.0),
                          out=io.BytesIO(), log=io.StringIO())


def test_heartbeat_record_round_trips(tmp_path):
    sc = _scaler(tmp_path)
    sc.counters["scale_up_total"] = 3
    sc.counters["evicted_total"] = 1
    sc.counters["self_evicted_total"] = 1
    sc._heartbeat(target=2, open_work=5, done=False)
    hb = obs_fleet.load_supervisor(sc.ledger_dir)
    assert hb is not None and hb["schema"] == 1
    assert hb["target_workers"] == 2 and hb["open_shards"] == 5
    assert hb["done"] is False and hb["seq"] == 0
    assert hb["workers_evicted"] == 2     # evicted + self-evicted
    # The supervisor's metric facts ride the heartbeat (it has no
    # metric shard of its own) under fleet merge-kind names.
    assert hb["metrics"] == {"dist_scale_up_total": 3,
                             "dist_scale_down_total": 0,
                             "fleet_target_workers": 2}
    sc._heartbeat(target=0, open_work=0, done=True)
    hb = obs_fleet.load_supervisor(sc.ledger_dir)
    assert hb["seq"] == 1 and hb["done"] is True


# --------------------------------------------------------- fleet health


def _write_heartbeat(ledger_dir, age_s=0.0, interval_s=0.5,
                     done=False):
    d = obs_fleet.obs_dir_for(ledger_dir)
    os.makedirs(d, exist_ok=True)
    rec = {"schema": 1, "unix_time": time.time() - age_s,
           "interval_s": interval_s, "target_workers": 2,
           "live_workers": 2, "done": done, "workers_live": 2,
           "workers_evicted": 1, "workers_retired": 0,
           "workers_done": 0}
    with open(os.path.join(d, obs_fleet.SUPERVISOR_NAME), "w") as fh:
        fh.write(json.dumps(rec))


def test_fleet_health_view_and_supervisor_staleness(tmp_path):
    ld = str(tmp_path / "ledger")
    os.makedirs(ld)
    # No supervisor ever ran: not penalized, ledger meta unpublished.
    snap = obs_export.fleet_health(ld)
    assert snap["status"] == "ok"
    assert snap["fleet"]["open_shards"] is None
    assert "autoscaler" not in snap["fleet"]
    # Fresh heartbeat: ok, and the decision facts are surfaced.
    _write_heartbeat(ld, age_s=0.0)
    snap = obs_export.fleet_health(ld)
    assert snap["status"] == "ok"
    assert snap["fleet"]["autoscaler"]["target_workers"] == 2
    assert snap["fleet"]["workers_evicted"] == 1
    # Stale heartbeat mid-run: supervisor-dead — the probes' 503.
    _write_heartbeat(ld, age_s=60.0, interval_s=0.5)
    snap = obs_export.fleet_health(ld)
    assert snap["status"] == "supervisor-dead"
    assert snap["fleet"]["autoscaler"]["age_s"] >= 59.0
    # A stale heartbeat that says done is a finished fleet, not a dead
    # one.
    _write_heartbeat(ld, age_s=60.0, done=True)
    assert obs_export.fleet_health(ld)["status"] == "ok"


def test_fleet_health_served_as_503(tmp_path):
    """End-to-end probe contract: the /healthz endpoint returns 503
    for a supervisor-dead fleet so a stock HTTP liveness probe can
    evict it."""
    import urllib.error
    import urllib.request

    ld = str(tmp_path / "ledger")
    os.makedirs(ld)
    _write_heartbeat(ld, age_s=60.0, interval_s=0.5)
    srv = obs_export.serve_metrics(
        0, lambda: "# EOF\n",
        health=lambda: obs_export.fleet_health(ld))
    try:
        url = "http://127.0.0.1:%d/healthz" % srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == \
            "supervisor-dead"
        _write_heartbeat(ld, age_s=0.0)
        with urllib.request.urlopen(url) as resp:
            assert resp.status == 200
    finally:
        srv.shutdown()
