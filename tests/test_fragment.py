"""Fragment-correction (kC/kF with all-vs-all overlaps) tests.

Reference goldens (test/racon_test.cpp:219-289): kC ava-PAF -> 39 seqs /
389,394 bp; kF FASTQ PAF or MHAP -> 236 seqs / 1,658,216 bp; kF FASTA ->
236 seqs / 1,663,982 bp. Sequence *counts* are engine-independent (they
fall out of window routing and the polished-ratio drop rule), so they are
asserted exactly; total lengths depend on the consensus engine and get a
1% band (measured: kF PAF 1,665,388 bp = 1.0043x golden).

The full ava configs run ~10 min each on one CPU core -> marked "ava"
(excluded by default via pyproject addopts). The subset smoke test keeps
the kF pipeline covered in the default suite.
"""

import gzip
import os

import pytest

from racon_tpu.models.polisher import PolisherType, create_polisher


def _polish(ref_data, reads, overlaps, type_, drop, scores=(1, -1, -1),
            refine_rounds=None):
    p = create_polisher(ref_data(reads), ref_data(overlaps),
                        ref_data(reads), type_, 500, 10.0, 0.3, *scores,
                        backend="native")
    if refine_rounds is not None:
        p.engine.refine_rounds = refine_rounds
    p.initialize()
    return p.polish(drop)


def test_fragment_correction_subset(ref_data, tmp_path):
    """Fast kF smoke: first 30 reads + their mutual ava overlaps."""
    # Pick 30 reads that actually overlap each other: walk the ava PAF
    # and collect names until 30 distinct reads are involved.
    keep = {}
    with gzip.open(ref_data("sample_ava_overlaps.paf.gz"), "rb") as f:
        for line in f:
            t = line.split(b"\t")
            for name in (t[0], t[5]):
                if len(keep) < 30:
                    keep.setdefault(name, True)
            if len(keep) >= 30:
                break
    from racon_tpu.io.parsers import FastqParser
    all_reads = FastqParser(ref_data("sample_reads.fastq.gz")).parse_all()
    recs = [(s.name.encode(), s) for s in all_reads
            if s.name.encode() in keep]
    assert len(recs) == 30
    reads_path = os.path.join(tmp_path, "sub.fastq")
    with open(reads_path, "wb") as f:
        for name, s in recs:
            qual = s.quality if s.quality is not None else b"I" * len(s.data)
            f.write(b"@" + name + b"\n" + s.data + b"\n+\n" + qual + b"\n")
    ovl_path = os.path.join(tmp_path, "sub.paf")
    n_ovl = 0
    with gzip.open(ref_data("sample_ava_overlaps.paf.gz"), "rb") as f, \
            open(ovl_path, "wb") as out:
        for line in f:
            t = line.split(b"\t")
            if t[0] in keep and t[5] in keep:
                out.write(line)
                n_ovl += 1
    assert n_ovl > 10

    p = create_polisher(reads_path, ovl_path, reads_path, PolisherType.kF,
                        500, 10.0, 0.3, 1, -1, -1, backend="native")
    p.engine.refine_rounds = 1  # plumbing smoke test, not a quality test
    p.initialize()
    out = p.polish(False)
    # kF + include-unpolished emits every target read; the kF tag string
    # appends 'r' to the name before the LN tag (src/polisher.cpp:487-491).
    assert len(out) == 30
    for name, _ in recs:
        assert any(s.name.startswith(name.decode() + "r ") for s in out)
    for seq in out:
        assert " LN:i:" in seq.name and " RC:i:" in seq.name
    total_in = sum(len(s.data) for _, s in recs)
    total_out = sum(len(s.data) for s in out)
    assert 0.9 * total_in < total_out < 1.1 * total_in


@pytest.mark.ava
def test_fragment_correction_kc_ava(ref_data):
    """Golden: 39 seqs / 389,394 bp (racon_test.cpp:219-235).

    Measured (2026-07-30, round-5 insertion-scale schedule 0.2/0.6):
    39 seqs / 388,171 bp = 0.9969x golden — the 2% inflation that
    earlier rounds tracked (397,305 bp at the old per-regime
    calibration) came from scattered insertion votes in these 1-4-layer
    windows clearing the single lenient gate; the strict final-round
    gate closed it. Band tightened to the reference-parity 1%; the
    count is asserted exactly."""
    out = _polish(ref_data, "sample_reads.fastq.gz",
                  "sample_ava_overlaps.paf.gz", PolisherType.kC, True)
    assert len(out) == 39
    total = sum(len(s.data) for s in out)
    assert abs(total - 389394) < 389394 * 0.01


@pytest.mark.ava
def test_fragment_correction_kf_paf(ref_data):
    """Golden: 236 seqs / 1,658,216 bp (racon_test.cpp:237-253)."""
    out = _polish(ref_data, "sample_reads.fastq.gz",
                  "sample_ava_overlaps.paf.gz", PolisherType.kF, False)
    assert len(out) == 236
    total = sum(len(s.data) for s in out)
    assert abs(total - 1658216) < 1658216 * 0.01


@pytest.mark.ava
def test_fragment_correction_kf_mhap_equivalent(ref_data):
    """MHAP input must route identically to PAF (racon_test.cpp:273-289)."""
    out_paf = _polish(ref_data, "sample_reads.fastq.gz",
                      "sample_ava_overlaps.paf.gz", PolisherType.kF, False)
    out_mhap = _polish(ref_data, "sample_reads.fastq.gz",
                       "sample_ava_overlaps.mhap.gz", PolisherType.kF, False)
    assert len(out_mhap) == len(out_paf) == 236
    assert [s.data for s in out_mhap] == [s.data for s in out_paf]


@pytest.mark.ava
def test_fragment_correction_kf_fasta(ref_data):
    """Golden: 236 seqs / 1,663,982 bp (racon_test.cpp:255-271)."""
    out = _polish(ref_data, "sample_reads.fasta.gz",
                  "sample_ava_overlaps.paf.gz", PolisherType.kF, False)
    assert len(out) == 236
    total = sum(len(s.data) for s in out)
    assert abs(total - 1663982) < 1663982 * 0.015
