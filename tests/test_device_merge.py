"""Device merge (ops/device_merge.py) vs the numpy reference merge.

The device merge reformulates _merge_round's scatters as gathers +
one-hot matmuls; on integer-valued weights its consensus, coverage, and
coordinate maps must be bit-identical to the numpy implementation
(which itself mirrors spoa's add_alignment/generate_consensus,
reference src/window.cpp:100-111).
"""

import numpy as np
import pytest

from racon_tpu.models.window import sorted_layer_order
from racon_tpu.ops.encode import encode_bases
from racon_tpu.ops.poa import PoaEngine
from racon_tpu.ops import device_merge as dm
from tests.test_flat import _build_windows


@pytest.mark.parametrize("with_quality", [True, False])
def test_device_merge_matches_numpy(with_quality):
    import jax.numpy as jnp
    windows = _build_windows(7, 5, 10, 220, with_quality)
    eng = PoaEngine(backend="native")
    active = [w for w in windows if w.n_layers >= 2]

    layers, anchors, spans = [], [], []
    for w in active:
        lst, sp = [], []
        for li in sorted_layer_order(w):
            data = bytes(w.layer_data[li])
            qual = w.layer_quality[li]
            codes = encode_bases(data)
            if qual is not None:
                wts = (np.frombuffer(bytes(qual), dtype=np.uint8)
                       .astype(np.float32) - 33.0)
            else:
                wts = np.ones(len(data), dtype=np.float32)
            lst.append((codes, wts))
            sp.append((int(w.layer_begin[li]), int(w.layer_end[li])))
        layers.append(lst)
        spans.append(sp)
        bb = encode_bases(bytes(w.backbone))
        if w.backbone_quality is not None:
            bw = (np.frombuffer(bytes(w.backbone_quality), dtype=np.uint8)
                  .astype(np.float32) - 33.0)
        else:
            bw = np.zeros(len(bb), dtype=np.float32)
        anchors.append((bb, bw))

    jobs = []
    for wi in range(len(active)):
        jobs.extend(eng._build_jobs(wi, anchors[wi][0], layers[wi],
                                    spans[wi]))
    eng._align(jobs)
    ref = eng._merge_round(anchors, jobs)

    B = len(jobs)
    S = max(len(j.ops) for j in jobs) + 8
    Lq = max(len(j.q) for j in jobs)
    LA = max(len(bb) for bb, _ in anchors) + 8
    ops = np.full((B, S), dm.PAD_OP, np.uint8)
    q = np.zeros((B, Lq), np.uint8)
    qw = np.zeros((B, Lq), np.float32)
    w_read = np.zeros(B, np.float32)
    lt = np.zeros(B, np.int32)
    t_off = np.zeros(B, np.int32)
    win = np.zeros(B, np.int32)
    for b, j in enumerate(jobs):
        ops[b, S - len(j.ops):] = j.ops
        q[b, :len(j.q)] = j.q
        qw[b, :len(j.q)] = j.w
        w_read[b] = j.w_read
        lt[b] = j.t_len
        t_off[b] = j.t_off
        win[b] = j.win
    Nw = len(anchors)
    bb_pad = np.zeros((Nw, LA), np.uint8)
    bbw_pad = np.zeros((Nw, LA), np.float32)
    alen = np.zeros(Nw, np.int32)
    for wi, (bb, bw) in enumerate(anchors):
        bb_pad[wi, :len(bb)] = bb
        bbw_pad[wi, :len(bb)] = bw
        alen[wi] = len(bb)

    votes = dm.extract_votes(jnp.asarray(ops), jnp.asarray(q),
                             jnp.asarray(qw), jnp.asarray(w_read),
                             jnp.asarray(lt), jnp.asarray(t_off), LA)
    acc = dm.aggregate_votes(votes, jnp.asarray(win), Nw)
    acc = dm.add_backbone(acc, jnp.asarray(bb_pad), jnp.asarray(bbw_pad),
                          jnp.asarray(alen))
    asm = dm.assemble(acc, jnp.asarray(alen), eng.ins_scale)
    codes, cov, total = dm.compact(asm, LA + 64)
    map_b, map_e = dm.coord_maps(asm, jnp.asarray(alen), LA)
    codes, cov, total = map(np.asarray, (codes, cov, total))
    map_b, map_e = np.asarray(map_b), np.asarray(map_e)

    for wi, (cons_ref, cov_ref, mb_ref, me_ref) in enumerate(ref):
        L = len(cons_ref)
        assert total[wi] == L
        assert np.array_equal(codes[wi, :L], cons_ref)
        assert np.array_equal(cov[wi, :L], cov_ref)
        assert np.array_equal(map_b[wi, :len(mb_ref)], mb_ref)
        assert np.array_equal(map_e[wi, :len(me_ref)], me_ref)
