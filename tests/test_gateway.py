"""Gateway subsystem tests: the routing policy matrix, the job→ledger
adapter (journal↔ledger state machine), nonce-fenced gateway fail-over,
the shared warm-pool layout, and byte-identity of fleet-executed vs
in-process jobs (racon_tpu/gateway/, docs/GATEWAY.md)."""

import contextlib
import io
import json
import os
import sys

import numpy as np
import pytest

from racon_tpu.distributed.autoscaler import AutoscalePolicy, decide
from racon_tpu.gateway import dispatch as gw_dispatch
from racon_tpu.gateway import ha as gw_ha
from racon_tpu.gateway import policy as gw_policy
from racon_tpu.gateway.dispatch import (FleetDispatchError, RouteDecision,
                                        decide_route, fleet_paths,
                                        run_fleet_job, worker_cli_argv)
from racon_tpu.gateway.ha import GatewayLease, GatewayLeaseLost
from racon_tpu.obs import fleet as obs_fleet
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.obs import trace as obs_trace
from racon_tpu.resilience import faults
from racon_tpu.server.engine import JobSpec
from racon_tpu.server.jobs import Job, open_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASES = np.frombuffer(b"ACGT", np.uint8)

GATE_ENVS = (gw_dispatch.ENV_GATE_FLEET, gw_dispatch.ENV_MIN_TARGETS,
             gw_dispatch.ENV_QUEUE_PRESSURE, gw_dispatch.ENV_GATE_WORKERS,
             gw_ha.ENV_LEASE_S, gw_ha.ENV_STANDBY_POLL_S)


@pytest.fixture(autouse=True)
def gateway_sandbox(monkeypatch):
    """Keep the process-global injector/registry/tracer — and this
    suite's env knobs — out of other tests."""
    from racon_tpu.distributed import autoscaler as asc
    for env in GATE_ENVS + (asc.ENV_MIN, asc.ENV_MAX, asc.ENV_INTERVAL,
                            asc.ENV_MAX_SPAWNS, asc.ENV_DEADLINE,
                            asc.ENV_FAULT_PLAN, faults.ENV_FAULTS,
                            obs_trace.ENV_TRACE, obs_trace.ENV_TRACE_CTX,
                            "RACON_TPU_CACHE_DIR", "RACON_TPU_JAX_CACHE"):
        monkeypatch.delenv(env, raising=False)
    faults.configure(None)
    obs_metrics.reset()
    yield
    faults.configure(None)
    obs_metrics.reset()


# ------------------------------------------------------ routing policy


def test_route_matrix_disabled_size_and_pressure(monkeypatch):
    """The policy matrix: unarmed → always local; armed → fleet on
    size or on queue pressure, local otherwise."""
    # Unarmed: even a huge job under a deep queue stays local.
    d = decide_route(None, 10_000, queue_depth=99)
    assert d == RouteDecision("local", "fleet-disabled", 10_000, 99)

    monkeypatch.setenv(gw_dispatch.ENV_GATE_FLEET, "1")
    monkeypatch.setenv(gw_dispatch.ENV_MIN_TARGETS, "4")
    monkeypatch.setenv(gw_dispatch.ENV_QUEUE_PRESSURE, "2")
    cases = [
        # (n_targets, queue_depth) -> route
        (4, 0, "fleet"),    # at the size threshold
        (400, 0, "fleet"),  # far past it
        (3, 0, "local"),    # small, idle daemon
        (3, 1, "local"),    # small, shallow queue
        (1, 2, "fleet"),    # queue-pressure override on a tiny job
    ]
    for n, depth, want in cases:
        got = decide_route(None, n, queue_depth=depth)
        assert got.route == want, (n, depth, got)
        assert (got.n_targets, got.queue_depth) == (n, depth)
    # Reasons name the clause that fired — they land in the gate span.
    assert "n_targets 4 >= 4" in decide_route(None, 4).reason
    assert "queue_depth 2 >= 2" in \
        decide_route(None, 1, queue_depth=2).reason


def test_count_targets_counts_fasta_records(tmp_path):
    """The size signal is the record count of the target file, not an
    artifact of the index scan's return shape (a single-contig job must
    be able to stay local)."""
    p = tmp_path / "t.fasta"
    p.write_text(">c0\nACGT\n")
    assert gw_dispatch.count_targets(str(p)) == 1
    p.write_text(">c0\nACGT\n>c1\nAC\n>c2\nGGTT\n")
    assert gw_dispatch.count_targets(str(p)) == 3


def test_route_fault_site_fires_before_decision(monkeypatch):
    """The declared ``gate/route`` site injects at the routing seam."""
    monkeypatch.setenv(gw_dispatch.ENV_GATE_FLEET, "1")
    faults.configure("gate/route:0")
    with pytest.raises(faults.InjectedFault):
        decide_route(None, 10_000)


def test_worker_cli_argv_carries_identity_flags(tmp_path):
    """The fleet worker argv replays the JobSpec's identity contract —
    every output-affecting flag, the shared ledger, nothing else."""
    spec = JobSpec("r.fa", "o.paf", "d.fa", window_length=250,
                   match=3, backend="jax", include_unpolished=True)
    argv = worker_cli_argv(spec, str(tmp_path / "ledger"), 3)
    assert argv[:3] == ["r.fa", "o.paf", "d.fa"]
    assert "--include-unpolished" in argv
    for flag, want in (("--window-length", "250"), ("--match", "3"),
                       ("--backend", "jax"), ("--workers", "3")):
        assert argv[argv.index(flag) + 1] == want
    assert argv[argv.index("--ledger-dir") + 1] == \
        str(tmp_path / "ledger")


# --------------------------------------------------- warm-pool layout


def test_fleet_paths_key_stability(tmp_path):
    """Run dirs are keyed by job fingerprint (resubmission and standby
    adoption attach to the same ledger); the jaxcache warm pool and the
    result CAS are shared across every job under one gateway."""
    state = str(tmp_path / "state")
    fp_a = "a" * 64
    fp_b = "b" * 64
    p1 = fleet_paths(state, fp_a)
    p2 = fleet_paths(state, fp_a)
    p3 = fleet_paths(state, fp_b)
    assert p1 == p2, "same fingerprint must map to the same run dir"
    assert p1.run_dir != p3.run_dir
    assert p1.run_dir == os.path.join(state, "fleet", fp_a[:16])
    assert p1.ledger_dir == os.path.join(p1.run_dir, "ledger")
    # Shared across jobs: one warm pool, one CAS, per gateway root.
    assert p1.pool_dir == p3.pool_dir
    assert p1.cas_dir == p3.cas_dir
    assert os.path.dirname(p1.pool_dir) == p1.root


# -------------------------------------------------- gateway fail-over


def test_lease_first_claim_blocks_live_standby(tmp_path):
    a = GatewayLease(str(tmp_path), "gw1", lease_s=30.0)
    assert a.try_acquire()
    assert a.epoch == 1 and not a.adopted
    a.verify()
    a.renew()
    b = GatewayLease(str(tmp_path), "gw2", lease_s=30.0)
    assert not b.try_acquire(), "live lease must not be stealable"
    assert not b.acquire(poll_s=0.01, deadline_s=0.05)


def test_lease_release_hands_off_without_adoption(tmp_path):
    """Clean drain: release leaves a marker (never unlinks), the next
    claim is instant, and it is NOT an adoption — the released
    gateway's jobs were drained, not orphaned."""
    a = GatewayLease(str(tmp_path), "gw1", lease_s=30.0)
    assert a.try_acquire()
    a.release()
    assert os.path.isfile(a.path), "release must never unlink"
    b = GatewayLease(str(tmp_path), "gw2", lease_s=30.0)
    assert b.acquire(poll_s=0.01, deadline_s=1.0)
    assert b.epoch == 2 and not b.adopted
    with pytest.raises(GatewayLeaseLost):
        a.verify()


def test_lease_steal_after_expiry_is_adoption_and_fences(tmp_path):
    """The kill-drill edge: a dead primary's expired lease is stolen
    (skewed clock, exactly the shard-ledger drill), the steal counts
    as an adoption, and the fenced primary can no longer renew."""
    a = GatewayLease(str(tmp_path), "gw1", lease_s=30.0)
    assert a.try_acquire()
    faults.configure("skew=1e9")
    b = GatewayLease(str(tmp_path), "gw2", lease_s=30.0)
    assert b.try_acquire()
    assert b.adopted and b.epoch == 2
    faults.configure(None)
    with pytest.raises(GatewayLeaseLost):
        a.renew()
    # The stale gateway also loses the adoption race outright: the
    # thief's lease is live now, so a late try_acquire gets nothing.
    assert not a.try_acquire()


def test_lease_adoption_race_loser_sees_foreign_nonce(tmp_path,
                                                      monkeypatch):
    """Two standbys steal the same expired lease: the loser's rewrite
    is overwritten before its re-read, so the nonce check fails and
    try_acquire reports False instead of a split-brain claim."""
    a = GatewayLease(str(tmp_path), "gw1", lease_s=0.0)
    assert a.try_acquire()  # deadline == now: instantly stealable
    real_write = gw_ha.atomic_write_bytes

    def racing_write(path, blob):
        real_write(path, blob)
        rec = json.loads(blob)
        rec["nonce"] = "feedfacefeedface"  # the winner lands after us
        real_write(path, (json.dumps(rec, sort_keys=True) +
                          "\n").encode())

    monkeypatch.setattr(gw_ha, "atomic_write_bytes", racing_write)
    loser = GatewayLease(str(tmp_path), "gw2", lease_s=30.0)
    assert not loser.try_acquire()
    assert loser.nonce == ""
    with pytest.raises(GatewayLeaseLost):
        loser.verify()


def test_lease_adopt_fault_site_breaks_adopting_standby(tmp_path):
    """The declared ``gate/adopt`` site fires on the adoption edge —
    the drill can kill a standby at the exact moment it wins."""
    a = GatewayLease(str(tmp_path), "gw1", lease_s=0.0)
    assert a.try_acquire()
    faults.configure("gate/adopt:0")
    b = GatewayLease(str(tmp_path), "gw2", lease_s=30.0)
    with pytest.raises(faults.InjectedFault):
        b.try_acquire()


# ------------------------------------------------- autoscaling policy


def test_service_target_boosts_on_queue_signals(monkeypatch):
    """service_target layers queue depth and wait-p95 boosts over the
    stock open-work clamp, publishes gate_fleet_target, and respects
    the policy's max."""
    monkeypatch.setenv(gw_dispatch.ENV_QUEUE_PRESSURE, "4")
    pol = AutoscalePolicy(1, 8, 0.5, 16, 0.0)
    reg = obs_metrics.registry()
    assert gw_policy.service_target(2, pol) == decide(2, pol) == 2
    reg.set("serve_queue_depth_peak", 4)
    assert gw_policy.service_target(2, pol) == 3
    for _ in range(20):
        obs_metrics.record_hist("serve_queue_wait_s", 1.0)
    assert gw_policy.service_target(2, pol) == 4
    assert reg.get("gate_fleet_target") == 4
    # The boost never pushes past the policy ceiling.
    assert gw_policy.service_target(8, pol) == 8
    # None open_work (unreadable ledger) still clamps to max.
    assert gw_policy.service_target(None, pol) == 8


def test_service_target_damped_by_fleet_drain_rate(tmp_path,
                                                   monkeypatch):
    """A fleet already draining faster than work arrives gets no
    pressure boost — the signals must not oscillate the fleet size."""
    monkeypatch.setenv(gw_dispatch.ENV_QUEUE_PRESSURE, "1")
    pol = AutoscalePolicy(1, 8, 0.5, 16, 0.0)
    reg = obs_metrics.registry()
    reg.set("serve_queue_depth_peak", 9)
    ld = str(tmp_path / "ledger")
    obs = os.path.join(ld, obs_fleet.OBS_SUBDIR)
    os.makedirs(obs)
    assert gw_policy.fleet_windows_per_sec(ld) == 0.0
    assert gw_policy.service_target(2, pol, ledger_dir=ld) == 3
    with open(os.path.join(obs, "worker_w1.metrics.jsonl"), "w") as fh:
        fh.write(json.dumps({
            "schema": obs_fleet.SNAPSHOT_SCHEMA, "worker_id": "w1",
            "run_fp": "f" * 16, "wall_s": 2.0,
            "metrics": {"poa_windows_total": 400}}) + "\n")
    assert gw_policy.fleet_windows_per_sec(ld) == 200.0
    assert gw_policy.service_target(2, pol, ledger_dir=ld) == 2


def test_record_gate_events_and_extras():
    reg = obs_metrics.registry()
    obs_metrics.record_gate("route_fleet", "j1", "acme",
                            decision="fleet")
    obs_metrics.record_gate("route_local", "j2", "acme")
    obs_metrics.record_gate("adopt", "j1", "acme", epoch=2)
    obs_metrics.record_gate("fleet_run", "j1", "acme", wall_s=1.5)
    with pytest.raises(ValueError):
        obs_metrics.record_gate("no-such-event", "j1", "acme")
    obs_metrics.set_gate_rate(12.5, compile_skip_s=30.0)
    snap = reg.snapshot()
    assert snap["gate_routed_fleet"] == 1
    assert snap["gate_routed_local"] == 1
    assert snap["gate_adoptions"] == 1
    assert snap["gate_fleet_runs"] == 1
    assert snap["gate_fleet_wall_s"] == 1.5
    assert snap["gate_fleet_jobs_per_min"] == 12.5
    assert snap["gate_compile_skip_s"] == 30.0
    extras = obs_metrics.gate_extras()
    assert extras["gate_routed_fleet"] == 1
    assert all(k.startswith("gate_") for k in extras)
    # Gauges merge last-wins across shards; counters sum.
    assert obs_metrics.merge_kind("gate_fleet_target") == "last"
    assert obs_metrics.merge_kind("gate_fleet_jobs_per_min") == "last"
    assert obs_metrics.merge_kind("gate_compile_skip_s") == "last"
    assert obs_metrics.merge_kind("gate_routed_fleet") == "sum"


# --------------------------------------------- the job→ledger adapter


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        if r < 0.06:
            out.append(BASES[rng.integers(0, 4)])
        else:
            out.append(b)
    return bytes(bytearray(out))


def _write_inputs(d, n_contigs=2, n_reads=6, clen=300, seed=11):
    rng = np.random.default_rng(seed)
    drafts, reads, paf = [], [], []
    for ci in range(n_contigs):
        truth = BASES[rng.integers(0, 4, clen)]
        draft = _mutate(rng, truth)
        drafts.append(b">c%d\n%s\n" % (ci, draft))
        for i in range(n_reads):
            r = _mutate(rng, truth)
            name = f"c{ci}r{i}"
            reads.append(b">" + name.encode() + b"\n" + r + b"\n")
            paf.append(f"{name}\t{len(r)}\t0\t{len(r)}\t+\tc{ci}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _spec_for(d):
    return JobSpec(os.path.join(d, "reads.fasta"),
                   os.path.join(d, "ovl.paf"),
                   os.path.join(d, "draft.fasta"), backend="jax")


def _run_cli_bytes(argv):
    from racon_tpu import cli
    stdout = io.StringIO()
    stdout.buffer = io.BytesIO()
    with contextlib.redirect_stdout(stdout), \
            contextlib.redirect_stderr(io.StringIO()):
        rc = cli.main(argv)
    assert rc == 0
    return stdout.buffer.getvalue()


def _solo_cli_bytes(d):
    return _run_cli_bytes(["--backend", "jax",
                           os.path.join(d, "reads.fasta"),
                           os.path.join(d, "ovl.paf"),
                           os.path.join(d, "draft.fasta")])


def _seed_fleet_ledger(state, spec):
    """Run one in-process ledger worker with the exact argv the
    gateway hands its autoscaled fleet, publishing out.fasta under the
    job's fleet run dir."""
    paths = fleet_paths(state, spec.fingerprint())
    os.makedirs(paths.ledger_dir, exist_ok=True)
    argv = worker_cli_argv(spec, paths.ledger_dir, 1)
    return _run_cli_bytes(argv + ["--worker-id", "seed"]), paths


def _wait_finished(job, timeout_s=120.0):
    assert job.finished.wait(timeout_s), \
        f"job {job.id} still {job.state} after {timeout_s}s"


def test_run_fleet_job_commits_ledger_output_byte_identical(tmp_path):
    """The adapter state machine: a fleet-produced out.fasta is
    re-committed contig-by-contig through the job's own checkpoint
    store, so the journal, /stream, and recovery see a fleet job
    exactly like a local one — and the bytes match the solo CLI."""
    d = str(tmp_path / "in")
    _write_inputs(d, n_contigs=3)
    base = _solo_cli_bytes(d)
    spec = _spec_for(d)
    state = str(tmp_path / "state")
    fleet_out, paths = _seed_fleet_ledger(state, spec)
    assert fleet_out == base, "ledger worker diverged from solo CLI"
    obs_metrics.reset()

    job = Job("j0001", "acme", spec, str(tmp_path / "jobs" / "j0001"))
    store = open_store(job)
    assert run_fleet_job(job, state, store) == 3
    store.close()
    assert job.result_bytes() == base
    snap = obs_metrics.registry().snapshot()
    assert snap["gate_fleet_runs"] == 1
    assert snap["gate_fleet_wall_s"] >= 0

    # Restart/adoption replay: a resumed store's committed prefix is
    # re-emitted byte-for-byte from the shard — zero recompute, and
    # the finished ledger short-circuits the supervisor entirely.
    job2 = Job("j0001", "acme", spec, str(tmp_path / "jobs" / "j0001"))
    store2 = open_store(job2)
    assert len(store2.committed) == 3
    assert run_fleet_job(job2, state, store2) == 3
    store2.close()
    assert job2.result_bytes() == base


def test_run_fleet_job_resumes_partial_prefix(tmp_path):
    """Adoption mid-job: tid 0 already committed in the journal's
    store, tids 1-2 still owed — the adapter re-emits the prefix from
    the store and commits only the remainder."""
    d = str(tmp_path / "in")
    _write_inputs(d, n_contigs=3)
    base = _solo_cli_bytes(d)
    spec = _spec_for(d)
    state = str(tmp_path / "state")
    _seed_fleet_ledger(state, spec)

    recs = gw_dispatch._split_fasta(base)
    assert len(recs) == 3
    job = Job("j0002", "acme", spec, str(tmp_path / "jobs" / "j0002"))
    store = open_store(job)
    nl = recs[0].index(b"\n")
    store.commit(0, bytes(recs[0][1:nl]), bytes(recs[0][nl + 1:-1]))
    assert run_fleet_job(job, state, store) == 3
    assert len(store.committed) == 3
    store.close()
    assert job.result_bytes() == base


def test_run_fleet_job_plumbs_shared_caches_and_fails_loud(tmp_path,
                                                           monkeypatch):
    """Worker env plumbing (the CAS satellite): every spawned worker
    inherits the shared jaxcache warm pool and the fleet result CAS
    under the gateway root — and a supervisor that produces no merged
    output is a loud FleetDispatchError, never a silent empty job."""
    d = str(tmp_path / "in")
    _write_inputs(d)
    spec = _spec_for(d)
    state = str(tmp_path / "state")
    paths = fleet_paths(state, spec.fingerprint())
    seen = {}

    class _FakeScaler:
        def __init__(self, ledger_dir, argv, **kw):
            seen.update(kw, ledger_dir=ledger_dir, argv=argv)

        def run(self):
            return 0  # "success", but never publishes out.fasta

    monkeypatch.setattr("racon_tpu.distributed.autoscaler.Autoscaler",
                        _FakeScaler)
    job = Job("j0003", "acme", spec, str(tmp_path / "jobs" / "j0003"))
    store = open_store(job)
    with pytest.raises(FleetDispatchError, match="without a merged"):
        run_fleet_job(job, state, store, trace_ctx="cafe" * 4 + ":7")
    store.close()
    env = seen["extra_env"]
    assert env["RACON_TPU_JAX_CACHE"] == paths.pool_dir
    assert env["RACON_TPU_CACHE_DIR"] == paths.cas_dir
    assert env["RACON_TPU_TRACE_CTX"] == "cafe" * 4 + ":7"
    assert seen["ledger_dir"] == paths.ledger_dir
    assert seen["trace_dir"] == os.path.join(paths.ledger_dir, "obs")
    assert os.path.isdir(paths.pool_dir) and os.path.isdir(paths.cas_dir)

    class _DeadScaler(_FakeScaler):
        def run(self):
            return 71

    monkeypatch.setattr("racon_tpu.distributed.autoscaler.Autoscaler",
                        _DeadScaler)
    store = open_store(job)
    with pytest.raises(FleetDispatchError, match="exited 71"):
        run_fleet_job(job, state, store)
    store.close()


# ------------------------------------------- daemon routing end-to-end


def test_daemon_routes_by_policy_byte_identical(tmp_path, monkeypatch):
    """The tentpole seam: an armed daemon ships a big-enough job to
    the fleet path (here a pre-published ledger — the same
    short-circuit a resubmitted fingerprint hits) and keeps small jobs
    on the in-process batcher; both streams are byte-identical to the
    solo CLI and the gate_* counters tell the routes apart."""
    from racon_tpu.server.daemon import PolishServer

    d1 = str(tmp_path / "in1")
    d2 = str(tmp_path / "in2")
    _write_inputs(d1, seed=11)
    _write_inputs(d2, seed=22)
    base1 = _solo_cli_bytes(d1)
    base2 = _solo_cli_bytes(d2)
    state = str(tmp_path / "state")
    _seed_fleet_ledger(state, _spec_for(d1))
    obs_metrics.reset()

    monkeypatch.setenv(gw_dispatch.ENV_GATE_FLEET, "1")
    monkeypatch.setenv(gw_dispatch.ENV_MIN_TARGETS, "1")
    server = PolishServer(state)
    j1 = server.submit("acme", _spec_for(d1))
    _wait_finished(j1)
    # Small-job route: raise the bar so the second job stays local.
    monkeypatch.setenv(gw_dispatch.ENV_MIN_TARGETS, "99")
    j2 = server.submit("umbrella", _spec_for(d2))
    _wait_finished(j2)
    for b in server._batchers.values():
        b.close()
    assert (j1.state, j2.state) == ("done", "done"), (j1.error, j2.error)
    assert j1.result_bytes() == base1
    assert j2.result_bytes() == base2
    snap = obs_metrics.registry().snapshot()
    assert snap["gate_routed_fleet"] == 1
    assert snap["gate_routed_local"] == 1
    assert snap["gate_fleet_runs"] == 1
    assert snap["serve_jobs_completed"] == 2


# --------------------------------------------------- gate observability


def test_gate_spans_validate_and_render(tmp_path):
    """obs_report --job stitches gateway spans into the same timeline
    as daemon and worker spans, and the validator holds gate spans to
    their declared attr contract."""
    sys.path.insert(0, REPO)
    from scripts import obs_report

    tid = "deadbeefcafef00d"
    obs = os.path.join(str(tmp_path), obs_fleet.OBS_SUBDIR)
    os.makedirs(obs)

    def span(sid, kind, name, t0, **attrs):
        return {"ev": "span", "id": sid, "parent": None, "kind": kind,
                "name": name, "t0": t0, "dur_s": 0.1, **attrs}

    def trace_file(path, begin, spans):
        with open(path, "w") as fh:
            fh.write(json.dumps({"ev": "begin", "schema": 1,
                                 "unix_time": begin}) + "\n")
            for s in spans:
                fh.write(json.dumps(s) + "\n")

    trace_file(os.path.join(obs, "daemon.jsonl"), 100.0, [
        span(1, "gate", "route_fleet", 0.1, trace_id=tid, job="j1",
             tenant="acme", parent_id=0, decision="fleet",
             reason="n_targets 4 >= 1"),
        span(2, "gate", "fleet_run", 0.9, trace_id=tid, job="j1",
             tenant="acme", parent_id=0, decision="fleet"),
    ])
    trace_file(os.path.join(obs, "worker_as0.jsonl"), 101.0, [
        span(1, "phase", "polish", 0.2, trace_id=tid, run_fp="fp1",
             worker_id="as0"),
    ])
    assert obs_report.validate(
        obs_report.load_trace(os.path.join(obs, "daemon.jsonl"))) == []
    out = io.StringIO()
    assert obs_report._render_job(str(tmp_path), tid, out=out) == 0
    text = out.getvalue()
    assert f"job {tid}: 3 span(s) across 2 process(es)" in text
    assert "gate/route_fleet" in text and "gate/fleet_run" in text
    assert "job=j1 tenant=acme" in text
    assert "decision=fleet" in text and "reason=n_targets" in text

    # A gate span missing its contract attrs is a validation error.
    bad = os.path.join(str(tmp_path), "bad.jsonl")
    trace_file(bad, 103.0, [span(1, "gate", "adopt", 0.1,
                                 trace_id=tid, job="j1")])
    errs = obs_report.validate(obs_report.load_trace(bad))
    assert errs and any("tenant" in e for e in errs)
