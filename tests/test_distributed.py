"""Distributed work-ledger tests: shard partition, lease claim/steal
fencing, per-shard checkpoint resume, ordered merge, and the CLI worker
surface (racon_tpu/distributed/, docs/DISTRIBUTED.md).

Eviction drills run in-process by monkeypatching the injector's
hard-exit seam; the real multi-process drill (kills, SIGTERM
mid-commit, byte-diff vs serial) is scripts/preemption_smoke.py.
"""

import contextlib
import io
import json
import os

import numpy as np
import pytest

from racon_tpu.distributed import (LeaseLost, LedgerError, WorkLedger)
from racon_tpu.distributed import ledger as dledger
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.resilience import checkpoint as ckpt
from racon_tpu.resilience import faults, retry

BASES = np.frombuffer(b"ACGT", np.uint8)


@pytest.fixture(autouse=True)
def dist_sandbox(monkeypatch):
    monkeypatch.delenv(retry.ENV_RETRY, raising=False)
    monkeypatch.delenv(faults.ENV_FAULTS, raising=False)
    monkeypatch.delenv(dledger.ENV_SHARDS, raising=False)
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()
    yield
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()


# ------------------------------------------------------------ partition


def test_fault_site_dist_claim_injects_then_claims(tmp_path):
    """The declared dist/claim injection site is live: a one-shot fault
    surfaces from the first claim attempt and the retried claim wins
    the shard normally (lint rule FLT002 requires every declared site
    to be exercised)."""
    d = str(tmp_path / "ledger")
    led = WorkLedger.open(d, "fp1", n_targets=4, workers=2)
    faults.configure("dist/claim:0")
    with pytest.raises(faults.InjectedFault):
        led.claim_shard("w0")
    claim = led.claim_shard("w0")
    assert claim is not None and claim.worker == "w0"
    snap = obs_metrics.registry().snapshot()
    assert snap["res_fault_injected_total"] == 1


def test_partition_bounds_balanced():
    assert dledger._partition(6, 3) == [0, 2, 4, 6]
    assert dledger._partition(7, 3) == [0, 3, 5, 7]
    assert dledger._partition(2, 2) == [0, 1, 2]
    # Shards never outnumber targets (clamped at open()).
    b = dledger._partition(3, 3)
    assert b == [0, 1, 2, 3]


def test_open_publishes_once_and_joins(tmp_path, monkeypatch):
    d = str(tmp_path / "ledger")
    a = WorkLedger.open(d, "fp1", n_targets=6, workers=2)
    assert a.n_shards == 4 and a.bounds[-1] == 6
    # A second worker with *different* flags adopts the published
    # partition — meta.json is the contract, not the CLI.
    b = WorkLedger.open(d, "fp1", n_targets=6, workers=7, lease_s=1.0)
    assert b.bounds == a.bounds and b.lease_s == a.lease_s

    with pytest.raises(LedgerError, match="fingerprint"):
        WorkLedger.open(d, "fp2", n_targets=6)
    with pytest.raises(LedgerError, match="target count"):
        WorkLedger.open(d, "fp1", n_targets=5)
    with pytest.raises(LedgerError, match="empty target set"):
        WorkLedger.open(str(tmp_path / "x"), "fp1", n_targets=0)

    monkeypatch.setenv(dledger.ENV_SHARDS, "3")
    c = WorkLedger.open(str(tmp_path / "env"), "fp1", n_targets=6)
    assert c.n_shards == 3


def test_claim_lifecycle_and_done(tmp_path):
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=4,
                          workers=1)  # 2 shards
    a = led.claim_shard("A")
    b = led.claim_shard("B")
    assert (a.shard, b.shard) == (0, 1) and not a.stolen
    # Everything live-leased: nothing left to claim.
    assert led.claim_shard("C") is None

    led.verify(a)
    old = a.deadline
    led.renew(a)
    assert a.deadline >= old

    led.complete(a, n_committed=2)
    assert led.is_done("shard_0") and not led.shards_done()
    assert led.claim_shard("C") is None      # done + leased
    led.complete(b)
    assert led.shards_done()
    ev = [e["ev"] for e in led.events()]
    assert ev.count("claim") == 2 and ev.count("complete") == 2


def test_steal_after_expiry_fences_victim(tmp_path):
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=2,
                          workers=1, n_shards=1)
    a = led.claim_shard("A")
    # Fresh lease: a second worker cannot touch it.
    assert led.claim_shard("B") is None
    # Shift only the thief's clock (the skew= fault clause): the lease
    # now looks expired and B steals it.
    faults.configure("skew=9999")
    b = led.claim_shard("B")
    assert b is not None and b.stolen and b.epoch == a.epoch + 1
    # The victim's nonce is gone: every fenced operation refuses.
    faults.configure(None)
    with pytest.raises(LeaseLost):
        led.renew(a)
    with pytest.raises(LeaseLost):
        led.complete(a)
    # The thief still owns it.
    led.renew(b)
    led.complete(b)
    snap = obs_metrics.registry().snapshot()
    assert snap["dist_shards_stolen"] == 1
    assert snap["dist_leases_expired"] == 1
    assert snap["dist_leases_lost"] == 2
    assert "dist_steal_latency_s" in snap


def test_torn_lease_is_stealable(tmp_path):
    """A worker that died mid-lease-publish leaves an unreadable lease;
    it must count as expired, not wedge the shard forever."""
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=2,
                          workers=1, n_shards=1)
    with open(led._lease_path("shard_0"), "wb") as fh:
        fh.write(b'{"worker": "A", "dead')
    c = led.claim_shard("B")
    assert c is not None and c.stolen


def test_merge_guards(tmp_path):
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=2,
                          workers=1, n_shards=1)
    with pytest.raises(LedgerError, match="still pending"):
        led.merge()
    # A done marker whose store doesn't cover the shard's range is
    # corruption, not something to paper over.
    claim = led.claim_shard("A")
    store = ckpt.CheckpointStore.create(led.shard_ckpt_dir(0),
                                        led.shard_fp(0))
    store.commit(0, b"c0", b"AAAA")
    store.close()
    led.complete(claim)
    with pytest.raises(LedgerError, match="no committed record"):
        led.merge()


def test_merge_orders_and_concatenates(tmp_path):
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=4,
                          workers=1)  # bounds [0,2,4]
    for k in range(2):
        claim = led.claim_shard(f"W{k}")
        store = ckpt.CheckpointStore.create(led.shard_ckpt_dir(k),
                                            led.shard_fp(k))
        lo, hi = led.shard_range(k)
        for tid in range(lo, hi):
            if tid == 1:
                store.commit_dropped(tid)   # dropped target: no bytes
            else:
                store.commit(tid, b"c%d" % tid, b"A" * (tid + 1))
        store.close()
        led.complete(claim)
    nbytes, emitted = led.merge()
    assert emitted == 3
    data = open(led.out_path, "rb").read()
    assert len(data) == nbytes
    assert data == b">c0\nA\n>c2\nAAA\n>c3\nAAAA\n"


# -------------------------------------------------- CLI worker surface


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.04:
            continue
        out.append(int(BASES[rng.integers(0, 4)]) if r < 0.08 else int(b))
    return bytes(out)


def _write_inputs(d, n_contigs=4, n_reads=6, clen=300):
    rng = np.random.default_rng(11)
    drafts, reads, paf = [], [], []
    for ci in range(n_contigs):
        truth = BASES[rng.integers(0, 4, clen)]
        draft = _mutate(rng, truth)
        drafts.append(b">c%d\n%s\n" % (ci, draft))
        for i in range(n_reads):
            r = _mutate(rng, truth)
            name = f"c{ci}r{i}"
            reads.append(b">" + name.encode() + b"\n" + r + b"\n")
            paf.append(f"{name}\t{len(r)}\t0\t{len(r)}\t+\tc{ci}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    (d / "draft.fasta").write_bytes(b"".join(drafts))
    (d / "reads.fasta").write_bytes(b"".join(reads))
    (d / "ovl.paf").write_text("\n".join(paf) + "\n")


def _run_cli(d, *extra):
    from racon_tpu import cli

    stdout = io.StringIO()
    stdout.buffer = io.BytesIO()
    err = io.StringIO()
    with contextlib.redirect_stdout(stdout), \
            contextlib.redirect_stderr(err):
        rc = cli.main(["--backend", "jax", *extra,
                       str(d / "reads.fasta"), str(d / "ovl.paf"),
                       str(d / "draft.fasta")])
    return rc, stdout.buffer.getvalue(), err.getvalue()


def test_cli_flag_conflicts(tmp_path):
    _write_inputs(tmp_path, n_contigs=1)
    rc, _, err = _run_cli(tmp_path, "--ledger-dir",
                          str(tmp_path / "l"), "--checkpoint-dir",
                          str(tmp_path / "ck"))
    assert rc == 1 and "manages per-shard checkpoints" in err
    rc, _, err = _run_cli(tmp_path, "--ledger-dir",
                          str(tmp_path / "l"), "--workers", "0")
    assert rc == 1 and "invalid --workers" in err
    rc, _, err = _run_cli(tmp_path, "--ledger-dir",
                          str(tmp_path / "l"), "--lease-s", "0")
    assert rc == 1 and "invalid --lease-s" in err


def test_ledger_cli_byte_identity(tmp_path):
    """One worker, whole fleet: the sharded run's merged stdout must be
    byte-identical to the serial path, with dist_* accounting."""
    _write_inputs(tmp_path)
    rc, base, _ = _run_cli(tmp_path)
    assert rc == 0 and base.count(b">") == 4

    ld = str(tmp_path / "ledger")
    obs_metrics.reset()
    rc, out, err = _run_cli(tmp_path, "--ledger-dir", ld,
                            "--worker-id", "solo")
    assert rc == 0, err
    assert out == base
    snap = obs_metrics.registry().snapshot()
    assert snap["dist_shards"] == 2 and snap["dist_n_targets"] == 4
    assert snap["dist_claims"] == 2
    assert snap["dist_shards_completed"] == 2
    assert snap["dist_contigs_polished"] == 4
    assert snap["dist_merges"] == 1
    assert "dist_shards_stolen" not in snap
    assert open(os.path.join(ld, dledger.OUT_NAME),
                "rb").read() == base
    # A late worker joining a finished ledger recomputes nothing and
    # emits nothing — only the merge winner owns stdout; it points at
    # the published out.fasta instead.
    obs_metrics.reset()
    rc, again, err = _run_cli(tmp_path, "--ledger-dir", ld,
                              "--worker-id", "late")
    assert rc == 0 and again == b""
    assert "already published" in err
    assert "dist_contigs_polished" not in \
        obs_metrics.registry().snapshot()


def test_eviction_steal_resume_byte_identity(tmp_path):
    """The tier-1 eviction drill: a worker crashes mid-shard (injected
    fault between contigs); a second worker with a skewed lease clock
    steals the shard, resumes the committed prefix, recomputes only the
    in-flight contig, and the merged output is byte-identical."""
    _write_inputs(tmp_path)
    rc, base, _ = _run_cli(tmp_path)
    assert rc == 0

    ld = str(tmp_path / "ledger")
    # 4 contigs, 2 shards ([0,2) and [2,4)). The fault fires at the 4th
    # dist/contig event: shard_0 completes (c0, c1), then c2 commits on
    # shard_1 and the worker dies before c3.
    faults.configure("dist/contig:3")
    with pytest.raises(faults.InjectedFault):
        _run_cli(tmp_path, "--ledger-dir", ld, "--worker-id", "victim")
    led = WorkLedger.open(ld, fingerprint=_ledger_fp(ld),
                          n_targets=4)
    assert led.is_done("shard_0") and not led.is_done("shard_1")

    # Survivor: skewed clock makes the victim's lease expired NOW.
    obs_metrics.reset()
    faults.configure("skew=1e9")
    rc, out, err = _run_cli(tmp_path, "--ledger-dir", ld,
                            "--worker-id", "thief")
    assert rc == 0, err
    assert out == base, "post-eviction merged FASTA differs from serial"
    snap = obs_metrics.registry().snapshot()
    assert snap["dist_shards_stolen"] == 1
    assert snap["dist_contigs_resumed"] == 1       # c2 from the victim
    assert snap["dist_contigs_polished"] == 1      # only c3 recomputed
    assert snap["dist_contigs_repolished"] == 1
    assert "recovery_wall_s" not in snap or \
        snap["dist_recovery_wall_s"] >= 0
    # Zero committed contigs re-polished: each tid appears exactly once
    # across the shard manifests.
    tids = []
    for k in range(led.n_shards):
        man = os.path.join(led.shard_ckpt_dir(k), ckpt.MANIFEST_NAME)
        for line in open(man, "rb").read().splitlines():
            rec = json.loads(line)
            if rec.get("ev") == "contig":
                tids.append(rec["tid"])
    assert sorted(tids) == [0, 1, 2, 3]


def _ledger_fp(ld):
    with open(os.path.join(ld, dledger.META_NAME)) as fh:
        return json.load(fh)["fingerprint"]

# --------------------------------------------------------------- split


def test_split_publishes_child_and_shrinks_parent(tmp_path):
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=6,
                          workers=1, n_shards=1)
    a = led.claim_shard("A")
    child = led.split(a, 2)
    assert child is not None
    assert (child.start, child.end) == (2, 6)
    assert child.parent == "shard_0" and child.root == 0
    assert a.info.end == 2
    # The effective ranges still tile [0, 6).
    infos = {i.name: (i.start, i.end) for i in led.all_shards()}
    assert infos["shard_0"] == (0, 2)
    assert infos[child.name] == (2, 6)
    assert sorted(led.pending_shards()) == sorted(
        ["shard_0", child.name])
    assert dledger.split_depth(child.name) == 1
    # Any idle worker claims the child immediately — fresh, not stolen.
    b = led.claim_shard("B")
    assert b is not None and b.name == child.name and not b.stolen
    ev = [e for e in led.events() if e.get("ev") == "split"]
    assert len(ev) == 1 and ev[0]["child"] == child.name
    assert obs_metrics.registry().snapshot()["dist_splits_total"] == 1


def test_split_guards(tmp_path):
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=4,
                          workers=1, n_shards=1)
    a = led.claim_shard("A")
    for cut in (0, 4, 9):
        with pytest.raises(LedgerError, match="outside the held"):
            led.split(a, cut)
    m = led.claim_merge("A")
    with pytest.raises(LedgerError, match="only shard claims"):
        led.split(m, 1)
    # A stolen lease cannot split: the thief owns the full range now.
    faults.configure("skew=9999")
    b = led.claim_shard("B")
    faults.configure(None)
    assert b is not None and b.stolen
    with pytest.raises(LeaseLost):
        led.split(a, 2)
    assert len(led.all_shards()) == 1  # nothing was published


def test_torn_split_is_invisible(tmp_path, monkeypatch):
    """The dist/split torn-write drill: a holder that dies mid-publish
    leaves a truncated .range at the final path; readers must see no
    child and the parent's full range — never a half-carved shard."""
    class _Died(BaseException):
        pass

    monkeypatch.setattr(
        dledger, "hard_exit",
        lambda code: (_ for _ in ()).throw(_Died(code)))
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=4,
                          workers=1, n_shards=1)
    a = led.claim_shard("A")
    faults.configure("dist/split:0!torn")
    with pytest.raises(_Died):
        led.split(a, 2)
    faults.configure(None)
    # The torn file is on disk but never becomes work.
    assert any(fn.endswith(dledger.RANGE_SUFFIX)
               for fn in os.listdir(str(tmp_path / "l")))
    assert [(i.name, i.start, i.end) for i in led.all_shards()] == \
        [("shard_0", 0, 4)]
    assert led.pending_shards() == ["shard_0"]


def test_release_is_fenced_and_hands_off_instantly(tmp_path):
    """Regression for the release/steal race: release is a marker
    rename, never an unlink, so a victim's late release cannot revoke
    a thief's freshly won lease — and a live holder's release makes
    the shard instantly claimable with a bumped epoch."""
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=4,
                          workers=1, n_shards=1)
    a = led.claim_shard("A")
    faults.configure("skew=9999")
    b = led.claim_shard("B")
    faults.configure(None)
    assert b is not None and b.stolen
    led.release(a)          # stale nonce: silent no-op, B keeps it
    led.renew(b)
    child = led.split(b, 2)  # the split protocol survives too
    assert child is not None
    led.complete(b, n_committed=2)
    # Cooperative handoff: release -> instant reclaim, epoch bumped,
    # not counted as a steal.
    c = led.claim_shard("C")
    assert c is not None and c.name == child.name
    led.release(c)
    d = led.claim_shard("D")
    assert d is not None and d.name == child.name
    assert d.epoch == c.epoch + 1 and not d.stolen
    ev = [e["ev"] for e in led.events()]
    assert ev.count("release") == 1 and ev.count("steal") == 1


def test_split_depth_cap_blocks_cascade(tmp_path, monkeypatch):
    """Two workers trading a shrinking tail must not fragment it into
    one-contig claims: every handoff costs the new holder a polisher
    build, so at the default cap a split child never re-splits
    (regression for the claim-time handoff cascade)."""
    from racon_tpu.distributed import worker as dworker

    monkeypatch.setenv(dworker.ENV_SPLIT_AFTER, "0")
    monkeypatch.setattr(dworker, "_live_workers", lambda d: 99)
    led = WorkLedger.open(str(tmp_path / "l"), "fp", n_targets=8,
                          workers=1, n_shards=1)
    log = io.StringIO()
    a = led.claim_shard("A")
    assert dworker._maybe_split(led, a, 1, 0.0, log)
    assert a.info.end == 2  # kept [0, 2), donated [2, 8)
    b = led.claim_shard("B")
    assert b is not None and dledger.split_depth(b.name) == 1
    # Same starvation signals, but the claim is a split child: refuse.
    assert not dworker._maybe_split(led, b, b.info.start, 0.0, log)
    # Raising the cap re-enables recursive splitting.
    monkeypatch.setenv(dledger.ENV_SPLIT_DEPTH, "2")
    assert dworker._maybe_split(led, b, b.info.start, 0.0, log)
