"""Full-width device NW (ops/flat.py) and the device engine end-to-end.

Strategy mirrors the reference's differential discipline: the device
kernels must be *bit-identical* to the numpy oracle / native C++ aligner
(reference edlib+spoa semantics) — not merely close. Runs on the CPU
backend (conftest forces it); the Pallas variants are asserted equal to
the XLA variants on real TPU runs (racon_tpu/ops/pallas/flat_kernel.py).
"""

import numpy as np
import pytest

from racon_tpu.models.window import Window, WindowType
from racon_tpu.ops.cigar import nw_oracle, DIAG, UP, LEFT
from racon_tpu.ops.encode import decode_bases
from racon_tpu.ops.flat import fw_dirs_xla, fw_traceback, PAD_OP
from racon_tpu.ops.poa import PoaEngine

M, X, G = 5, -4, -8


def _score(q, t, ops):
    i = j = s = 0
    for d in ops:
        if d == DIAG:
            s += M if q[i] == t[j] else X
            i += 1
            j += 1
        elif d == UP:
            s += G
            i += 1
        else:
            s += G
            j += 1
    assert i == len(q) and j == len(t)
    return s


def _mutate(rng, base, rate):
    out = []
    for b in base:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.integers(0, 4))
            continue
        out.append(b)
        if r < rate:
            out.append(rng.integers(0, 4))
    return np.asarray(out, np.uint8)


def test_fw_paths_match_oracle():
    """Batched full-width NW paths are bit-identical to the numpy oracle."""
    import jax.numpy as jnp
    rng = np.random.default_rng(5)
    qs, ts = [], []
    for trial in range(25):
        L = int(rng.integers(4, 300))
        t = rng.integers(0, 4, L).astype(np.uint8)
        q = _mutate(rng, t, 0.2) if trial % 3 else \
            rng.integers(0, 4, int(rng.integers(1, 200))).astype(np.uint8)
        if len(q) == 0:
            q = np.array([0], np.uint8)
        qs.append(q)
        ts.append(t)
    B = len(qs)
    Lq = max(len(q) for q in qs)
    Lt = max(len(t) for t in ts)
    tbuf = np.full((B, Lt), 7, np.uint8)
    qT = np.zeros((Lq, B), np.uint8)
    lq = np.zeros(B, np.int32)
    lt = np.zeros(B, np.int32)
    for b, (q, t) in enumerate(zip(qs, ts)):
        tbuf[b, :len(t)] = t
        qT[:len(q), b] = q
        lq[b], lt[b] = len(q), len(t)
    dirs = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT),
                       match=M, mismatch=X, gap=G)
    steps = Lq + Lt
    rev = np.asarray(fw_traceback(dirs, jnp.asarray(lq), jnp.asarray(lt),
                                  steps))
    for b in range(B):
        ops = rev[b][rev[b] != PAD_OP][::-1]
        ref_score, ref_ops = nw_oracle(qs[b], ts[b], M, X, G)
        assert _score(qs[b], ts[b], ops) == ref_score
        assert np.array_equal(ops, ref_ops), b


def _build_windows(seed, n, cov, wlen, with_quality):
    rng = np.random.default_rng(seed)
    ws = []
    for _ in range(n):
        true = rng.integers(0, 4, wlen).astype(np.uint8)

        def noisy():
            return decode_bases(_mutate(rng, true, 0.12))

        backbone = noisy()
        bq = bytes(rng.integers(38, 53, len(backbone), dtype=np.uint8)) \
            if with_quality else None
        w = Window(0, 0, WindowType.TGS, backbone, bq)
        for _ in range(cov):
            lay = noisy()
            lquals = bytes(rng.integers(38, 53, len(lay), dtype=np.uint8)) \
                if with_quality else None
            if rng.random() < 0.3 and len(backbone) > 60:
                b0 = int(rng.integers(0, len(backbone) // 3))
                e0 = int(rng.integers(2 * len(backbone) // 3,
                                      len(backbone) - 1))
                c0 = int(len(lay) * b0 / len(backbone))
                c1 = int(len(lay) * e0 / len(backbone))
                w.add_layer(lay[c0:c1], lquals[c0:c1] if lquals else None,
                            b0, e0)
            else:
                w.add_layer(lay, lquals, 0, len(backbone) - 1)
        ws.append(w)
    return ws


@pytest.mark.parametrize("with_quality", [True, False])
def test_device_engine_matches_native(with_quality):
    """The all-device engine's consensus is bit-identical to the host
    native path (same alignments, same merge) on mixed full/partial-span
    windows."""
    w_dev = _build_windows(11, 6, 12, 260, with_quality)
    w_nat = _build_windows(11, 6, 12, 260, with_quality)
    PoaEngine(backend="jax").consensus_windows(w_dev)
    PoaEngine(backend="native").consensus_windows(w_nat)
    for a, b in zip(w_dev, w_nat):
        assert a.consensus == b.consensus
        assert a.polished == b.polished
