"""Banded NW forward (ops/pallas/band_kernel.py): score exactness via
the escape bound, and engine-level equality against the full-width path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from racon_tpu.ops.align import nw_oracle
from racon_tpu.ops.cigar import DIAG, UP, LEFT
from racon_tpu.ops.pallas.band_kernel import (band_geometry,
                                              fw_dirs_band_xla,
                                              fw_traceback_band)


def _score_of_ops(q, t, ops, m, x, g):
    qi = ti = s = 0
    for d in ops:
        if d == DIAG:
            s += m if q[qi] == t[ti] else x
            qi += 1
            ti += 1
        elif d == UP:
            s += g
            qi += 1
        elif d == LEFT:
            s += g
            ti += 1
    assert qi == len(q) and ti == len(t)
    return s


@pytest.mark.parametrize("scoring", [(5, -4, -8), (0, -1, -1)])
def test_band_scores_and_paths_match_oracle(scoring):
    """Random jobs whose optimum fits the band: the banded terminal
    score must equal the full NW optimum (escape-bound certified) and
    the traceback must be a valid path achieving it."""
    m, x, g = scoring
    rng = np.random.default_rng(8)
    B, Lq, W = 8, 64, 128
    # Mildly noisy pairs: small |lt - lq|, deviation far below W//2.
    qs, ts = [], []
    for _ in range(B):
        t = rng.integers(0, 4, int(rng.integers(40, 60)))
        keep = rng.random(len(t)) > 0.08
        q = t[keep]
        sub = rng.random(len(q)) < 0.06
        q = np.where(sub, rng.integers(0, 4, len(q)), q)
        qs.append(q.astype(np.uint8))
        ts.append(t.astype(np.uint8))
    lq = np.array([len(q) for q in qs], np.int32)
    lt = np.array([len(t) for t in ts], np.int32)
    qpad = np.zeros((B, Lq), np.uint8)
    for b in range(B):
        qpad[b, :lq[b]] = qs[b]
    klo, wl = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    tband = np.full((B, W + Lq), 7, np.uint8)
    for b in range(B):
        for y in range(W + Lq):
            j = klo_h[b] + y
            if 0 <= j < lt[b]:
                tband[b, y] = ts[b][j]
    dirs, _, hlast = fw_dirs_band_xla(
        jnp.asarray(tband), jnp.asarray(qpad.T), klo,
        jnp.asarray(lq), match=m, mismatch=x, gap=g, W=W)
    rev = fw_traceback_band(dirs, jnp.asarray(lq), jnp.asarray(lt), klo,
                            Lq + W)
    ops = np.asarray(jnp.flip(rev, axis=1))
    hlast = np.asarray(hlast)
    for b in range(B):
        o = [d for d in ops[b] if d != 3]
        osc, _ = nw_oracle(qs[b], ts[b], m, x, g)
        xend = lt[b] - lq[b] - klo_h[b]
        assert hlast[b, xend] == osc
        assert _score_of_ops(qs[b], ts[b], o, m, x, g) == osc


def test_engine_band_matches_full_width():
    """End-to-end: banded and full-width device paths produce identical
    consensus on bench-like windows (band covers the optimum, identical
    tie-breaking)."""
    import os
    from bench import build_windows
    from racon_tpu.ops.poa import PoaEngine

    ws_band = build_windows(8, 6, 200, seed=13)
    ws_full = build_windows(8, 6, 200, seed=13)
    assert PoaEngine(backend="jax").consensus_windows(ws_band) == 8
    os.environ["RACON_TPU_NO_BAND"] = "1"
    try:
        assert PoaEngine(backend="jax").consensus_windows(ws_full) == 8
    finally:
        del os.environ["RACON_TPU_NO_BAND"]
    for a, b in zip(ws_band, ws_full):
        assert a.consensus == b.consensus
