"""End-to-end Polisher tests against the reference's acceptance suite.

The reference regression-tests consensus quality as an *exact* edit
distance on the bundled lambda-phage dataset
(reference: test/racon_test.cpp:87-289). Our engine is a re-design (not a
spoa port), so exact score equality is meaningless; the acceptance
criterion here is: **at most 1.25x the reference's golden edit distance**
(and the measured values are asserted as an upper bound so regressions
are caught). Current measured values (see docstrings) beat the reference
goldens on the quality-bearing configs.
"""

import numpy as np
import pytest

from racon_tpu.io.parsers import FastaParser, ParseError
from racon_tpu.models.overlap import PolisherError
from racon_tpu.models.polisher import (PolisherType, create_polisher)
from racon_tpu.native.aligner import NativeAligner
from racon_tpu.ops.encode import reverse_complement


def _edit_distance(a: bytes, b: bytes) -> int:
    al = NativeAligner()  # maximize (0,-1,-1) == minimum edit distance
    ops = al.align(a, b)
    from racon_tpu.ops.encode import encode_bases
    qa, ta = encode_bases(a), encode_bases(b)
    qi = ti = ed = 0
    for d in ops:
        if d == 0:
            ed += int(qa[qi] != ta[ti])
            qi += 1
            ti += 1
        else:
            ed += 1
            qi += d == 1
            ti += d == 2
    return ed


def _polish(ref_data, reads, overlaps, window=500, scores=(5, -4, -8),
            type_=PolisherType.kC, drop=True):
    p = create_polisher(
        ref_data(reads), ref_data(overlaps),
        ref_data("sample_layout.fasta.gz"), type_,
        window, 10.0, 0.3, *scores, backend="native")
    p.initialize()
    return p.polish(drop)


@pytest.fixture(scope="module")
def reference_genome(ref_data_module):
    return FastaParser(
        ref_data_module("sample_reference.fasta.gz")).parse_all()[0].data


@pytest.fixture(scope="module")
def ref_data_module():
    import os
    d = "/root/reference/test/data"
    if not os.path.isdir(d):
        pytest.skip("reference dataset not available")
    return lambda name: os.path.join(d, name)


# ----------------------------------------------------- validation behaviors


def test_invalid_polisher_type():
    with pytest.raises(PolisherError, match="invalid polisher type"):
        create_polisher("", "", "", "bogus")


def test_invalid_window_length():
    with pytest.raises(PolisherError, match="invalid window length"):
        create_polisher("", "", "", PolisherType.kC, 0)


def test_sequences_path_extension_error():
    with pytest.raises(ParseError, match=r"unsupported format extension.*"
                       r"\.fasta, \.fasta\.gz, \.fa, \.fa\.gz"):
        create_polisher("", "", "", PolisherType.kC, 500)


def test_overlaps_path_extension_error(ref_data_module):
    with pytest.raises(ParseError, match=r"unsupported format extension.*"
                       r"\.mhap, \.mhap\.gz, \.paf, \.paf\.gz"):
        create_polisher(ref_data_module("sample_reads.fastq.gz"), "", "",
                        PolisherType.kC, 500)


def test_target_path_extension_error(ref_data_module):
    with pytest.raises(ParseError, match=r"unsupported format extension"):
        create_polisher(ref_data_module("sample_reads.fastq.gz"),
                        ref_data_module("sample_overlaps.paf.gz"), "",
                        PolisherType.kC, 500)


# ------------------------------------------------------- golden consensus


def _check(out, reference_genome, golden, measured_bound):
    assert len(out) == 1
    ed = _edit_distance(reverse_complement(out[0].data), reference_genome)
    assert ed <= int(golden * 1.25), f"ED {ed} vs golden {golden}"
    assert ed <= measured_bound, \
        f"ED {ed} regressed past recorded bound {measured_bound}"
    return ed


def test_consensus_sam_with_qualities(ref_data_module, reference_genome):
    """Reference golden 1317 (racon_test.cpp:131-151); ours ~1252
    (round-5 ins_scale 0.2/0.6 schedule)."""
    out = _polish(ref_data_module, "sample_reads.fastq.gz",
                  "sample_overlaps.sam.gz")
    _check(out, reference_genome, 1317, 1310)
    assert out[0].name.startswith("utg000001l LN:i:")
    assert " RC:i:181 " in out[0].name
    assert out[0].name.endswith("XC:f:1.000000")


def test_consensus_paf_with_qualities(ref_data_module, reference_genome):
    """Reference golden 1312 (racon_test.cpp:87-107); ours ~1211."""
    out = _polish(ref_data_module, "sample_reads.fastq.gz",
                  "sample_overlaps.paf.gz")
    _check(out, reference_genome, 1312, 1270)


@pytest.mark.slow
def test_consensus_paf_without_qualities(ref_data_module, reference_genome):
    """Reference golden 1566 (racon_test.cpp:109-129); ours ~1578
    (round-5: the shared 0.2/0.6 insertion-scale schedule replaced the
    fitted unit-weight calibration and closed most of the gap)."""
    out = _polish(ref_data_module, "sample_reads.fasta.gz",
                  "sample_overlaps.paf.gz")
    _check(out, reference_genome, 1566, 1640)


@pytest.mark.slow
def test_consensus_sam_without_qualities(ref_data_module, reference_genome):
    """Reference golden 1770 (racon_test.cpp:153-173); ours ~1913."""
    out = _polish(ref_data_module, "sample_reads.fasta.gz",
                  "sample_overlaps.sam.gz")
    _check(out, reference_genome, 1770, 1990)


@pytest.mark.slow
def test_consensus_larger_window(ref_data_module, reference_genome):
    """Reference golden 1289 (racon_test.cpp:175-195); ours ~1235."""
    out = _polish(ref_data_module, "sample_reads.fastq.gz",
                  "sample_overlaps.paf.gz", window=1000)
    _check(out, reference_genome, 1289, 1300)


@pytest.mark.slow
def test_consensus_edit_distance_scoring(ref_data_module, reference_genome):
    """Reference golden 1321 (racon_test.cpp:197-217); ours ~1158."""
    out = _polish(ref_data_module, "sample_reads.fastq.gz",
                  "sample_overlaps.paf.gz", scores=(1, -1, -1))
    _check(out, reference_genome, 1321, 1230)


# The six reference acceptance configs (racon_test.cpp:87-217), used by
# the scheduler differential below: reads, overlaps, window, scores, and
# the reference golden ED.
_GOLDEN_CONFIGS = [
    ("sample_reads.fastq.gz", "sample_overlaps.sam.gz", 500,
     (5, -4, -8), 1317),
    ("sample_reads.fastq.gz", "sample_overlaps.paf.gz", 500,
     (5, -4, -8), 1312),
    ("sample_reads.fasta.gz", "sample_overlaps.paf.gz", 500,
     (5, -4, -8), 1566),
    ("sample_reads.fasta.gz", "sample_overlaps.sam.gz", 500,
     (5, -4, -8), 1770),
    ("sample_reads.fastq.gz", "sample_overlaps.paf.gz", 1000,
     (5, -4, -8), 1289),
    ("sample_reads.fastq.gz", "sample_overlaps.paf.gz", 500,
     (1, -1, -1), 1321),
]
_GOLDEN_IDS = ["sam_fastq", "paf_fastq", "paf_fasta", "sam_fasta",
               "window1000", "edit_scores"]


def _polish_device(ref_data_module, reads, overlaps, window=500,
                   scores=(5, -4, -8)):
    p = create_polisher(
        ref_data_module(reads), ref_data_module(overlaps),
        ref_data_module("sample_layout.fasta.gz"), PolisherType.kC,
        window, 10.0, 0.3, *scores, backend="jax")
    p.initialize()
    return p.polish(True)


@pytest.mark.ava
@pytest.mark.parametrize("reads,overlaps,window,scores,golden",
                         _GOLDEN_CONFIGS, ids=_GOLDEN_IDS)
def test_sched_differential_golden(ref_data_module, reference_genome,
                                   monkeypatch, reads, overlaps, window,
                                   scores, golden):
    """The convergence scheduler (racon_tpu/sched/) must be
    BIT-IDENTICAL to the fixed-round engine on every reference
    acceptance config — a frozen window's recorded consensus is the
    final-scale replay of its detection round, so any divergence is a
    scheduler bug, not noise. ci.sh runs the sam_fastq case in the
    default tier; --full runs all six."""
    monkeypatch.setenv("RACON_TPU_SCHED", "0")
    fixed = _polish_device(ref_data_module, reads, overlaps, window,
                           scores)
    monkeypatch.setenv("RACON_TPU_SCHED", "1")
    sched = _polish_device(ref_data_module, reads, overlaps, window,
                           scores)
    assert [s.data for s in sched] == [s.data for s in fixed]
    assert [s.name for s in sched] == [s.name for s in fixed]
    ed = _edit_distance(reverse_complement(sched[0].data),
                        reference_genome)
    assert ed <= int(golden * 1.25), f"ED {ed} vs golden {golden}"


@pytest.mark.ava
def test_consensus_device_engine_golden_sam_fastq(ref_data_module,
                                                  reference_genome):
    """The flagship device-resident engine through the full reference
    acceptance config (SAM+FASTQ, racon_test.cpp:131-151, golden 1317).

    Measured 2026-07-30: ED 1252 on the real TPU with the round-5
    insertion-scale schedule (earlier in the round: 1305) — beats the
    reference golden. Runs
    ~1.5 min on one CPU core since the column-walk rework; ci.sh runs it
    explicitly in the default tier (the 'ava' marker only keeps it out
    of bare `pytest tests/` invocations).
    """
    from racon_tpu.models.polisher import create_polisher
    p = create_polisher(
        ref_data_module("sample_reads.fastq.gz"),
        ref_data_module("sample_overlaps.sam.gz"),
        ref_data_module("sample_layout.fasta.gz"), PolisherType.kC,
        500, 10.0, 0.3, 5, -4, -8, backend="jax")
    p.initialize()
    out = p.polish(True)
    ed = _edit_distance(reverse_complement(out[0].data), reference_genome)
    assert ed <= 1317, f"device engine ED {ed} vs reference golden 1317"
