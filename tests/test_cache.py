"""Content-addressed result cache (racon_tpu/cache/, docs/CACHE.md).

Tier 1 (job CAS): roundtrip, verify-on-hit quarantine of corrupt and
torn entries, the ``cache/store`` fault decoupling, LRU eviction under
the byte bound, and journal-aware restart recovery. Tier 2 (window
memo): content-digest memoization, spill-tier verification, and —
through a stub-engine :class:`CrossRequestBatcher` — the
partial-overlap contract: a second job sharing windows with a first
dispatches only the delta (``serve_batch_windows`` counts it) while
its output stays byte-identical to a cold run.
"""

import os

import pytest

from racon_tpu.cache import (ResultCache, WindowMemo, records_from_store,
                             replay_records, window_digest)
from racon_tpu.models.window import Window, WindowType
from racon_tpu.obs.metrics import registry
from racon_tpu.resilience.faults import configure as configure_faults
from racon_tpu.server.batch import CrossRequestBatcher

RECORDS = [(0, b"c0", b"ACGT" * 16), (1, None, b""), (2, b"c2", b"TG")]


def _delta(before, key):
    return registry().snapshot().get(key, 0) - before.get(key, 0)


@pytest.fixture
def no_faults():
    configure_faults(None)
    yield
    configure_faults(None)


# --------------------------------------------------------------- tier 1


def test_cas_roundtrip_and_metrics(tmp_path):
    before = registry().snapshot()
    cache = ResultCache(str(tmp_path), max_bytes=1 << 20)
    assert cache.load("k") is None
    assert cache.store("k", RECORDS)
    assert cache.load("k") == RECORDS
    assert _delta(before, "cache_misses_total") == 1
    assert _delta(before, "cache_hits_total") == 1
    assert _delta(before, "cache_stores_total") == 1
    assert _delta(before, "cache_bytes") > 0


def test_cas_verify_fail_quarantines(tmp_path):
    cache = ResultCache(str(tmp_path), max_bytes=1 << 20)
    cache.store("k", RECORDS)
    path = cache._object_path("k")
    raw = open(path, "rb").read()
    # lint: atomic-ok (test corrupts a cache object in place)
    with open(path, "wb") as fh:
        fh.write(raw[:-2] + b"zz")
    before = registry().snapshot()
    assert cache.load("k") is None  # corrupt entry demotes to miss
    assert _delta(before, "cache_verify_fail_total") == 1
    assert os.path.exists(path + ".quarantine")
    assert not os.path.exists(path)
    # Quarantined = gone from the index: a plain miss from now on.
    before = registry().snapshot()
    assert cache.load("k") is None
    assert _delta(before, "cache_verify_fail_total") == 0
    # A fresh store of the same key recovers the slot.
    assert cache.store("k", RECORDS)
    assert cache.load("k") == RECORDS


def test_cas_torn_load_is_a_miss(tmp_path, no_faults):
    """The poisoning drill: ``cache/load!torn`` truncates the read
    in-process; verify-on-hit must demote it to a miss, never serve
    partial bytes."""
    cache = ResultCache(str(tmp_path), max_bytes=1 << 20)
    cache.store("k", RECORDS)
    configure_faults("cache/load:0!torn")
    before = registry().snapshot()
    assert cache.load("k") is None
    assert _delta(before, "cache_verify_fail_total") == 1
    configure_faults(None)
    # The torn entry was quarantined; re-store then hit clean.
    assert cache.store("k", RECORDS)
    assert cache.load("k") == RECORDS


def test_cas_store_fault_skips_store(tmp_path, no_faults):
    cache = ResultCache(str(tmp_path), max_bytes=1 << 20)
    configure_faults("cache/store:0")
    assert cache.store("k", RECORDS) is False
    configure_faults(None)
    assert cache.load("k") is None  # nothing was written
    assert cache.stats()["entries"] == 0


def test_cas_lru_eviction_and_touch(tmp_path):
    cache = ResultCache(str(tmp_path), max_bytes=700)
    blob = b"x" * 200
    for key in ("a", "b", "c"):
        assert cache.store(key, [(0, key.encode(), blob)])
    assert cache.stats()["entries"] == 2  # "a" evicted (oldest)
    assert cache.load("a") is None
    # Touch "b" so "c" becomes the LRU victim of the next store.
    assert cache.load("b") is not None
    assert cache.store("d", [(0, b"d", blob)])
    assert cache.load("c") is None
    assert cache.load("b") is not None
    before = registry().snapshot()
    assert before.get("cache_evictions_total", 0) >= 2


def test_cas_restart_recovery(tmp_path):
    """Journal-aware recovery: a new instance over the same directory
    reloads the published index (no payload re-hash — verification is
    per hit) and keeps serving; entries whose object vanished drop."""
    cache = ResultCache(str(tmp_path), max_bytes=1 << 20)
    cache.store("k", RECORDS)
    cache.store("gone", RECORDS)
    os.remove(cache._object_path("gone"))
    again = ResultCache(str(tmp_path), max_bytes=1 << 20)
    assert again.load("k") == RECORDS
    assert again.stats()["entries"] == 1


def test_replay_matches_commit_blob_format(tmp_path):
    """records_from_store ∘ replay_records is the identity on a
    checkpoint store: the CAS record codec is the exact inverse of the
    commit blob format."""
    from racon_tpu.resilience.checkpoint import CheckpointStore
    d1 = tmp_path / "one"
    store = CheckpointStore.create(str(d1), "fp")
    emitted = []
    replay_records(RECORDS, emit=emitted.append, store=store)
    derived = records_from_store(store)
    store.close()
    assert derived == RECORDS
    assert emitted == [b">c0\n" + b"ACGT" * 16 + b"\n", b">c2\nTG\n"]
    # And replaying the derived records into a second store commits
    # the same bytes.
    d2 = tmp_path / "two"
    store2 = CheckpointStore.create(str(d2), "fp")
    replay_records(derived, store=store2)
    assert records_from_store(store2) == RECORDS
    store2.close()


# --------------------------------------------------------------- tier 2


def _window(i, seq, layers=()):
    w = Window(i, 0, WindowType.NGS, seq, None)
    for data, begin, end in layers:
        w.layer_data.append(data)
        w.layer_quality.append(None)
        w.layer_begin.append(begin)
        w.layer_end.append(end)
    return w


def test_window_digest_covers_content():
    base = _window(0, b"ACGT", layers=[(b"ACG", 0, 2)])
    key = window_digest(b"s", base)
    assert key == window_digest(b"s", _window(7, b"ACGT",
                                              layers=[(b"ACG", 0, 2)]))
    assert key != window_digest(b"S2", base)          # scoring differs
    assert key != window_digest(b"s", _window(0, b"ACGA",
                                              layers=[(b"ACG", 0, 2)]))
    assert key != window_digest(b"s", _window(0, b"ACGT"))  # layers
    assert key != window_digest(b"s", _window(0, b"ACGT",
                                              layers=[(b"ACG", 0, 1)]))


def test_memo_roundtrip_and_spill(tmp_path):
    memo = WindowMemo(("k",), max_entries=2, spill_dir=str(tmp_path))
    seqs = [b"AAAA", b"CCCC", b"GGGG"]
    for i, s in enumerate(seqs):
        w = _window(i, s)
        w.consensus, w.polished = s[:2], True
        assert memo.put(w) == 2
    assert len(memo) == 2  # first window spilled
    spilled = memo.get(_window(0, b"AAAA"))
    assert spilled == (b"AA", True)
    # A corrupt spill file is unlinked and reads as a miss.
    key = memo.digest(_window(1, b"CCCC"))
    memo.get(_window(2, b"GGGG"))  # keep "CCCC" the spill victim
    w = _window(9, b"TTTT")
    w.consensus, w.polished = b"TT", True
    memo.put(w)  # overflows -> spills another entry
    for name in os.listdir(str(tmp_path)):
        p = os.path.join(str(tmp_path), name)
        raw = open(p, "rb").read()
        # lint: atomic-ok (test corrupts a spill file in place)
        with open(p, "wb") as fh:
            fh.write(raw[:-1] + b"z")
    before = registry().snapshot()
    assert memo.get(_window(0, b"AAAA")) is None
    assert _delta(before, "cache_verify_fail_total") == 1
    assert key  # silence unused warnings


class _StubEngine:
    """consensus_windows stand-in: deterministic per-window transform,
    counts every window that reaches the 'device'."""

    def __init__(self):
        self.dispatched = 0

    def consensus_windows(self, windows):
        self.dispatched += len(windows)
        for w in windows:
            w.consensus = bytes(reversed(bytes(w.backbone)))
            w.polished = True
        return len(windows)


def _run_batcher(seqs, memo, engine):
    windows = [_window(i, s) for i, s in enumerate(seqs)]
    b = CrossRequestBatcher(engine, capacity=4, wait_s=0.05,
                            queue_cap=8, memo=memo).start()
    try:
        n = b.consensus("job", "tenant", windows)
    finally:
        b.close()
    return n, [w.consensus for w in windows]


def test_partial_overlap_dispatches_only_delta():
    """The acceptance contract: job B shares half its windows with job
    A — B's run moves ``serve_batch_windows`` by exactly the delta,
    and both jobs' consensus is byte-identical to cold (memo-less)
    runs."""
    A = [b"AAAA", b"CCCC", b"GGGG", b"TTTT"]
    B = [b"GGGG", b"TTTT", b"ACAC", b"GTGT"]  # 2 shared, 2 new
    cold_a = _run_batcher(A, None, _StubEngine())[1]
    cold_b = _run_batcher(B, None, _StubEngine())[1]

    memo = WindowMemo(("k",))
    eng = _StubEngine()
    before = registry().snapshot()
    n_a, warm_a = _run_batcher(A, memo, eng)
    assert n_a == 4 and eng.dispatched == 4
    mid = registry().snapshot()
    n_b, warm_b = _run_batcher(B, memo, eng)
    assert n_b == 4
    assert eng.dispatched == 6  # only ACAC/GTGT hit the device
    after = registry().snapshot()
    assert warm_a == cold_a and warm_b == cold_b
    # serve_batch_windows counts only the delta for job B ...
    assert after["serve_batch_windows"] - mid["serve_batch_windows"] == 2
    # ... and the memo accounting agrees: 2 hits, 2 misses.
    assert after.get("cache_hits_total", 0) - \
        mid.get("cache_hits_total", 0) == 2
    assert after.get("cache_misses_total", 0) - \
        mid.get("cache_misses_total", 0) == 2
    assert after.get("cache_stores_total", 0) - \
        before.get("cache_stores_total", 0) == 6


def test_identical_resubmit_zero_dispatches():
    seqs = [b"AAAA", b"CCCC", b"GGGG"]
    memo = WindowMemo(("k",))
    eng = _StubEngine()
    cold = _run_batcher(seqs, None, _StubEngine())[1]
    n1, first = _run_batcher(seqs, memo, eng)
    n2, second = _run_batcher(seqs, memo, eng)
    assert eng.dispatched == 3  # resubmit never reached the device
    assert n1 == n2 == 3
    assert first == second == cold


def test_memo_disabled_is_todays_path():
    """memo=None (RACON_TPU_CACHE=0) must be exactly the pre-cache
    batcher: every window dispatches, no cache_* accounting moves."""
    seqs = [b"AAAA", b"CCCC"]
    eng = _StubEngine()
    before = registry().snapshot()
    _run_batcher(seqs, None, eng)
    _run_batcher(seqs, None, eng)
    after = registry().snapshot()
    assert eng.dispatched == 4
    for key in ("cache_hits_total", "cache_misses_total",
                "cache_stores_total"):
        assert after.get(key, 0) == before.get(key, 0)
