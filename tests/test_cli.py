"""CLI behavior tests (reference contract: src/main.cpp:14-160)."""

import subprocess
import sys

import pytest


def _run(*args):
    return subprocess.run(
        [sys.executable, "-m", "racon_tpu.cli", *args],
        capture_output=True, cwd="/root/repo")


def test_version():
    r = _run("--version")
    assert r.returncode == 0
    assert r.stdout.decode().startswith("v0.")


def test_help():
    r = _run("-h")
    assert r.returncode == 0
    out = r.stdout.decode()
    for flag in ("--include-unpolished", "--fragment-correction",
                 "--window-length", "--quality-threshold",
                 "--error-threshold", "--match", "--mismatch", "--gap",
                 "--threads"):
        assert flag in out


def test_missing_inputs():
    r = _run()
    assert r.returncode == 1
    assert b"error: missing input file(s)!" in r.stderr


def test_bad_extension():
    r = _run("a.txt", "b.txt", "c.txt")
    assert r.returncode == 1
    assert b"unsupported format extension" in r.stderr


@pytest.mark.slow
def test_cli_polishes_to_stdout(ref_data):
    r = _run("--backend", "native",
             ref_data("sample_reads.fastq.gz"),
             ref_data("sample_overlaps.sam.gz"),
             ref_data("sample_layout.fasta.gz"))
    assert r.returncode == 0
    lines = r.stdout.split(b"\n")
    assert lines[0].startswith(b">utg000001l LN:i:")
    assert b" RC:i:181 " in lines[0]
    assert len(lines[1]) > 40_000
    assert b"total =" in r.stderr
