"""CLI behavior tests (reference contract: src/main.cpp:14-160)."""

import subprocess
import sys

import pytest


def _run(*args):
    return subprocess.run(
        [sys.executable, "-m", "racon_tpu.cli", *args],
        capture_output=True, cwd="/root/repo")


def test_version():
    r = _run("--version")
    assert r.returncode == 0
    assert r.stdout.decode().startswith("v0.")


def test_help():
    r = _run("-h")
    assert r.returncode == 0
    out = r.stdout.decode()
    for flag in ("--include-unpolished", "--fragment-correction",
                 "--window-length", "--quality-threshold",
                 "--error-threshold", "--match", "--mismatch", "--gap",
                 "--threads"):
        assert flag in out


def test_missing_inputs():
    r = _run()
    assert r.returncode == 1
    assert b"error: missing input file(s)!" in r.stderr


def test_bad_extension():
    r = _run("a.txt", "b.txt", "c.txt")
    assert r.returncode == 1
    assert b"unsupported format extension" in r.stderr


def test_cli_dp_mesh_polishes(tmp_path):
    """--dp N builds a data-parallel mesh and polishes through the
    dp-sharded device engine (8 virtual CPU devices; the same sharding
    the v5e-8 recipe in docs/DISTRIBUTED.md uses on real chips)."""
    import os
    import numpy as np
    rng = np.random.default_rng(3)
    bases = np.frombuffer(b"ACGT", np.uint8)
    truth = bases[rng.integers(0, 4, 400)]

    def noisy():
        out = []
        for b in truth:
            r = rng.random()
            if r < 0.03:
                continue
            out.append(int(rng.integers(0, 4)) if r < 0.06 else int(
                np.searchsorted(bases, b)))
        return bytes(bases[np.array(out)])

    (tmp_path / "draft.fasta").write_bytes(
        b">c1\n" + noisy() + b"\n")
    reads, paf = [], []
    dlen = len((tmp_path / "draft.fasta").read_bytes().split(b"\n")[1])
    for i in range(8):
        r = noisy()
        reads.append(b">r%d\n%s\n" % (i, r))
        paf.append(f"r{i}\t{len(r)}\t0\t{len(r)}\t+\tc1\t{dlen}\t0\t{dlen}"
                   f"\t{min(len(r), dlen)}\t{max(len(r), dlen)}\t60")
    (tmp_path / "reads.fasta").write_bytes(b"".join(reads))
    (tmp_path / "ovl.paf").write_text("\n".join(paf) + "\n")

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    # The axon site hook (PYTHONPATH) re-points JAX_PLATFORMS at the
    # TPU tunnel; drop it so the subprocess honors the CPU mesh.
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if "axon" not in p)
    r = subprocess.run(
        [sys.executable, "-m", "racon_tpu.cli", "--backend", "jax",
         "--dp", "8", str(tmp_path / "reads.fasta"),
         str(tmp_path / "ovl.paf"), str(tmp_path / "draft.fasta")],
        capture_output=True, cwd="/root/repo", env=env)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert r.stdout.startswith(b">c1 LN:i:")


@pytest.mark.slow
def test_cli_polishes_to_stdout(ref_data):
    r = _run("--backend", "native",
             ref_data("sample_reads.fastq.gz"),
             ref_data("sample_overlaps.sam.gz"),
             ref_data("sample_layout.fasta.gz"))
    assert r.returncode == 0
    lines = r.stdout.split(b"\n")
    assert lines[0].startswith(b">utg000001l LN:i:")
    assert b" RC:i:181 " in lines[0]
    assert len(lines[1]) > 40_000
    assert b"total =" in r.stderr
