"""Fleet observability tests: worker metric shards, aggregation,
OpenMetrics export (racon_tpu/obs/fleet.py, obs/export.py,
docs/OBSERVABILITY.md)."""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from racon_tpu.obs import export as obs_export
from racon_tpu.obs import fleet as obs_fleet
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.resilience import faults

BASES = np.frombuffer(b"ACGT", np.uint8)


@pytest.fixture(autouse=True)
def fleet_sandbox(monkeypatch):
    """Keep the process-global injector, registry, and metrics writer
    out of other tests (and other tests' env out of these)."""
    for env in (faults.ENV_FAULTS, obs_fleet.ENV_OBS_DIR,
                obs_fleet.ENV_FLUSH_S, obs_export.ENV_METRICS_PORT,
                "RACON_TPU_TRACE", "RACON_TPU_DIST_SHARDS",
                "RACON_TPU_PIPELINE"):
        monkeypatch.delenv(env, raising=False)
    faults.configure(None)
    obs_metrics.reset()
    obs_fleet._WRITER = None
    yield
    faults.configure(None)
    obs_metrics.reset()
    obs_fleet._WRITER = None


class _Died(BaseException):
    """Stand-in for os._exit in in-process crash drills."""


@pytest.fixture
def soft_crash(monkeypatch):
    monkeypatch.setattr(obs_fleet, "hard_exit",
                        lambda code: (_ for _ in ()).throw(_Died(code)))
    return _Died


def _writer(d, wid="w0", fp="fp1", interval=0.0):
    reg = obs_metrics.MetricsRegistry()
    w = obs_fleet.WorkerMetricsWriter(str(d), wid, fp, reg=reg,
                                      interval_s=interval)
    return w, reg


# --------------------------------------------------------- writer shards

def test_writer_publishes_snapshot_history(tmp_path):
    w, reg = _writer(tmp_path)
    reg.inc("dist_claims")
    w.flush()
    reg.inc("dist_claims")
    w.flush(final=True)
    recs = [json.loads(ln) for ln in
            open(w.path, "rb").read().splitlines()]
    assert [r["seq"] for r in recs] == [0, 1]
    assert [r["final"] for r in recs] == [False, True]
    assert recs[0]["metrics"]["dist_claims"] == 1
    assert recs[1]["metrics"]["dist_claims"] == 2
    assert all(r["worker_id"] == "w0" and r["run_fp"] == "fp1"
               for r in recs)
    # After the final snapshot the writer is inert: late teardown paths
    # can call it unconditionally without growing the history.
    w.flush()
    assert len(open(w.path, "rb").read().splitlines()) == 2


def test_maybe_flush_honors_interval(tmp_path):
    w, _ = _writer(tmp_path, interval=3600.0)
    assert w.maybe_flush()          # first call always publishes
    assert not w.maybe_flush()      # interval not yet elapsed
    w.interval_s = 0.0
    assert w.maybe_flush()          # interval 0 = every call


def test_shard_path_sanitizes_worker_id(tmp_path):
    p = obs_fleet.shard_path(str(tmp_path), "w/0:evil id")
    assert os.path.dirname(p) == str(tmp_path)
    assert os.path.basename(p) == "worker_w_0_evil_id.metrics.jsonl"


def test_install_writer_flushes_eagerly(tmp_path):
    obs_fleet.install_writer(str(tmp_path), "w0", "fp1",
                             reg=obs_metrics.MetricsRegistry(),
                             interval_s=0.0)
    # A worker evicted before its first contig still appears.
    assert len(obs_fleet.load_worker_shards(str(tmp_path))) == 1
    obs_fleet.flush_final()
    shards = obs_fleet.load_worker_shards(str(tmp_path))
    assert shards[0]["records"][-1]["final"]


def test_torn_snapshot_recovers_prefix(tmp_path, soft_crash):
    """The obs/snapshot drill: a torn flush leaves a truncated shard at
    the *final* path (bypassing atomic publish); the reader must recover
    every complete record before the tear."""
    faults.configure("obs/snapshot:2!torn")
    w, reg = _writer(tmp_path)
    reg.inc("dist_claims")
    w.flush()
    reg.inc("dist_claims")
    w.flush()
    reg.inc("dist_claims")
    with pytest.raises(soft_crash):
        w.flush()
    faults.configure(None)
    shards = obs_fleet.load_worker_shards(str(tmp_path))
    assert len(shards) == 1
    assert not shards[0]["clean"]            # the tear is visible
    recs = shards[0]["records"]
    assert [r["seq"] for r in recs] == [0, 1]
    assert recs[-1]["metrics"]["dist_claims"] == 2
    # The torn shard still aggregates (one worker, last good record).
    model = obs_fleet.aggregate(str(tmp_path))
    assert model["fleet"]["dist_claims"] == 2
    assert not model["workers"]["w0"]["clean"]


# ----------------------------------------------------------- aggregation

def _two_worker_dir(tmp_path):
    wa, ra = _writer(tmp_path, "A", "fp1")
    ra.inc("dist_claims", 2)
    ra.inc("poa_windows_total", 30)
    ra.max("pipe_q_depth_peak", 3)
    ra.set("sched_windows", 10)
    ra.inc("phase_seconds_polish", 1.5)
    ra.inc("phase_seconds_total", 1.5)
    wa.flush(final=True)
    wb, rb = _writer(tmp_path, "B", "fp1")
    rb.inc("dist_claims", 3)
    rb.inc("poa_windows_total", 50)
    rb.max("pipe_q_depth_peak", 7)
    rb.set("sched_windows", 25)
    rb.inc("phase_seconds_polish", 2.5)
    rb.inc("phase_seconds_total", 2.5)
    wb.flush(final=True)
    return tmp_path


def test_aggregate_merges_by_kind(tmp_path):
    model = obs_fleet.aggregate(str(_two_worker_dir(tmp_path)))
    assert model["run_fp"] == "fp1"
    assert model["n_workers"] == 2
    fleet = model["fleet"]
    assert fleet["dist_claims"] == 5             # sum
    assert fleet["poa_windows_total"] == 80      # sum
    assert fleet["pipe_q_depth_peak"] == 7       # max
    assert fleet["sched_windows"] == 25          # last (worker order)
    assert fleet["phase_seconds_total"] == 4.0   # sum
    for wid, windows in (("A", 30), ("B", 50)):
        wrk = model["workers"][wid]
        assert wrk["final"] and wrk["clean"]
        assert wrk["phase_seconds"] == {"polish": pytest.approx(
            1.5 if wid == "A" else 2.5)}
        if wrk["wall_s"] > 0:
            assert wrk["windows_per_sec"] == pytest.approx(
                windows / wrk["wall_s"], abs=1e-3)


def test_aggregate_prefers_obs_subdir(tmp_path):
    """A ledger root aggregates from its obs/ subdir; a bare
    RACON_TPU_OBS_DIR aggregates in place."""
    sub = tmp_path / obs_fleet.OBS_SUBDIR
    sub.mkdir()
    w, reg = _writer(sub, "A", "fp1")
    reg.inc("dist_claims")
    w.flush(final=True)
    assert obs_fleet.aggregate(str(tmp_path))["n_workers"] == 1
    assert obs_fleet.aggregate(str(sub))["n_workers"] == 1


def test_aggregate_refuses_mixed_run_fp(tmp_path):
    wa, _ = _writer(tmp_path, "A", "fp1")
    wa.flush()
    wb, _ = _writer(tmp_path, "B", "fp2")
    wb.flush()
    with pytest.raises(obs_fleet.FleetObsError, match="different runs"):
        obs_fleet.aggregate(str(tmp_path))


def test_aggregate_empty_dir_raises(tmp_path):
    with pytest.raises(obs_fleet.FleetObsError, match="no worker"):
        obs_fleet.aggregate(str(tmp_path))


def test_timeline_compresses_renew_runs(tmp_path):
    w, _ = _writer(tmp_path, "A", "fp1")
    w.flush(final=True)
    events = [
        {"ev": "claim", "name": "shard_000", "worker": "A", "t": 1.0},
        {"ev": "renew", "name": "shard_000", "worker": "A", "t": 2.0},
        {"ev": "renew", "name": "shard_000", "worker": "A", "t": 3.0},
        {"ev": "renew", "name": "shard_000", "worker": "A", "t": 4.0},
        {"ev": "steal", "name": "shard_000", "worker": "B",
         "victim": "A", "t": 9.0, "expired_for_s": 4.0},
        {"ev": "renew", "name": "shard_000", "worker": "B", "t": 10.0},
        {"ev": "complete", "name": "shard_000", "worker": "B",
         "t": 11.0},
    ]
    with open(tmp_path / "events.jsonl", "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")
    model = obs_fleet.aggregate(str(tmp_path))
    lane = model["timeline"]["shard_000"]
    assert [e["ev"] for e in lane] == ["claim", "renew", "steal",
                                       "renew", "complete"]
    # A's 3 consecutive renews collapsed into one entry; B's renew run
    # after the steal stays separate (different worker).
    assert lane[1]["n"] == 3 and lane[1]["t_last"] == 4.0
    assert lane[3]["n"] == 1
    assert lane[2]["victim"] == "A"
    assert model["steals"] == 1


# ---------------------------------------------------------- OpenMetrics

def test_render_registry_valid_and_byte_stable():
    snap = {"dist_claims": 3, "pipe_q_depth_peak": 2.0,
            "sched_windows": 7, "poa_windows_total": 12,
            "ovl_device_fraction": 0.75,
            "sched_rounds_hist": {"2": 5},      # non-numeric: skipped
            "h2d_bytes": 1024}
    text = obs_export.render_registry(snap)
    assert obs_export.validate_openmetrics(text) == []
    assert text == obs_export.render_registry(dict(snap))
    # sum keys are counters and get the mandatory _total sample suffix —
    # not doubled when the registry key already carries it.
    assert "racon_tpu_dist_claims_total 3" in text
    assert "racon_tpu_poa_windows_total 12" in text
    assert "racon_tpu_poa_windows_total_total" not in text
    assert "# TYPE racon_tpu_poa_windows counter" in text
    # max/last keys are gauges, ints format without a decimal point.
    assert "# TYPE racon_tpu_pipe_q_depth_peak gauge" in text
    assert "racon_tpu_pipe_q_depth_peak 2\n" in text
    assert "racon_tpu_ovl_device_fraction 0.75" in text
    assert "sched_rounds_hist" not in text
    assert text.endswith("# EOF\n")


def test_render_fleet_series(tmp_path):
    model = obs_fleet.aggregate(str(_two_worker_dir(tmp_path)))
    text = obs_export.render_fleet(model)
    assert obs_export.validate_openmetrics(text) == []
    assert "racon_tpu_fleet_workers 2" in text
    assert 'racon_tpu_worker_windows_per_sec{worker="A"}' in text
    assert 'racon_tpu_worker_final{worker="B"} 1' in text
    assert "racon_tpu_dist_claims_total 5" in text
    assert text == obs_export.render_fleet(
        obs_fleet.aggregate(str(tmp_path)))


def test_validator_catches_structural_breakage():
    assert obs_export.validate_openmetrics("racon_tpu_x 1\n")
    bad = ("# HELP racon_tpu_c help\n# TYPE racon_tpu_c counter\n"
           "racon_tpu_c 1\n# EOF\n")
    assert any("_total" in e for e in
               obs_export.validate_openmetrics(bad))
    bad = ("# HELP racon_tpu_g help\n# TYPE racon_tpu_g gauge\n"
           "racon_tpu_g nope\n# EOF\n")
    assert any("non-numeric" in e for e in
               obs_export.validate_openmetrics(bad))
    ok = ("# HELP racon_tpu_g help\n# TYPE racon_tpu_g gauge\n"
          "racon_tpu_g 1\n# EOF\n")
    assert obs_export.validate_openmetrics(ok) == []


def test_pull_endpoint_serves_render():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("dist_claims", 4)
    server = obs_export.serve_metrics(
        0, lambda: obs_export.render_registry(reg.snapshot()))
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            body = resp.read().decode()
            ctype = resp.headers["Content-Type"]
        assert ctype == obs_export.CONTENT_TYPE
        assert "racon_tpu_dist_claims_total 4" in body
        assert obs_export.validate_openmetrics(body) == []
    finally:
        server.shutdown()
        server.server_close()


# ----------------------------------------------- registry merge hazards

def test_record_ovl_single_lock_under_contention():
    """The merge-hazard fix: record_ovl's read-modify-write runs under
    one registry lock, so concurrent batches neither drop increments
    nor publish a fraction from mismatched numerator/denominator."""
    reg = obs_metrics.MetricsRegistry()
    n_threads, n_iters = 8, 200

    def hammer():
        for _ in range(n_iters):
            obs_metrics.record_ovl(3, 1, 2, reg=reg)

    threads = [threading.Thread(target=hammer)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    total = n_threads * n_iters
    assert snap["ovl_device_jobs"] == 3 * total
    assert snap["ovl_native_jobs"] == 1 * total
    assert snap["ovl_tiles_exec"] == 2 * total
    assert snap["ovl_device_fraction"] == 0.75


def test_merge_kind_table():
    mk = obs_metrics.merge_kind
    assert mk("dist_claims") == obs_metrics.MERGE_SUM
    assert mk("poa_windows_total") == obs_metrics.MERGE_SUM
    # sched_flag_pulls is an inc'd counter despite the sched_ prefix.
    assert mk("sched_flag_pulls") == obs_metrics.MERGE_SUM
    assert mk("pipe_q_depth_peak") == obs_metrics.MERGE_MAX
    assert mk("sched_windows") == obs_metrics.MERGE_LAST
    assert mk("dist_workers") == obs_metrics.MERGE_LAST
    assert mk("ovl_device_fraction") == obs_metrics.MERGE_LAST
    mv = obs_metrics.merge_values
    assert mv("dist_claims", [2, None, 3]) == 5
    assert mv("pipe_q_depth_peak", [2, 7, 3]) == 7
    assert mv("sched_windows", [10, 25]) == 25
    assert mv("sched_rounds_hist", [{"2": 1}, {"2": 5}]) == {"2": 5}
    assert mv("dist_claims", [None, None]) is None


# ------------------------------------------- span context + report gate

def test_report_validates_fleet_span_attrs(tmp_path):
    from scripts import obs_report
    path = tmp_path / "t.jsonl"
    lines = [
        {"ev": "begin", "schema": 1, "unix_time": 0.0},
        {"ev": "span", "id": 1, "parent": None, "kind": "phase",
         "name": "p", "t0": 0.0, "dur_s": 0.1, "worker_id": 7,
         "shard": "oops", "run_fp": 12},
        {"ev": "span", "id": 2, "parent": None, "kind": "phase",
         "name": "q", "t0": 0.2, "dur_s": 0.1, "worker_id": "A",
         "shard": 0, "run_fp": "fp1"},
        {"ev": "span", "id": 3, "parent": None, "kind": "phase",
         "name": "r", "t0": 0.4, "dur_s": 0.1, "worker_id": "B",
         "run_fp": "fp2"},
    ]
    with open(path, "w") as fh:
        for ln in lines:
            fh.write(json.dumps(ln) + "\n")
    errs = obs_report.validate(obs_report.load_trace(str(path)))
    assert any("worker_id must be a string" in e for e in errs)
    assert any("shard must be an integer" in e for e in errs)
    assert any("run_fp must be a string" in e for e in errs)
    assert any("mixed run_fp" in e for e in errs)


def test_tracer_set_context_tags_spans(tmp_path):
    from racon_tpu.obs.trace import Tracer
    path = str(tmp_path / "t.jsonl")
    tr = Tracer(path)
    tr.set_context(worker_id="A", run_fp="fp1")
    with tr.span("phase", "one"):
        pass
    tr.set_context(shard=2)
    with tr.span("phase", "two", shard=5):   # span attrs win
        pass
    tr.set_context(shard=None)               # None drops the key
    with tr.span("phase", "three"):
        pass
    tr.finish()
    spans = {r["name"]: r for r in
             (json.loads(ln) for ln in open(path))
             if r.get("ev") == "span"}
    assert spans["one"]["worker_id"] == "A"
    assert spans["one"]["run_fp"] == "fp1"
    assert "shard" not in spans["one"]
    assert spans["two"]["shard"] == 5
    assert "shard" not in spans["three"]
    assert spans["three"]["worker_id"] == "A"


# ------------------------------------------------- SIGTERM final flush

def _tiny_inputs(d):
    rng = np.random.default_rng(7)
    drafts, reads, paf = [], [], []
    for c in range(2):
        truth = BASES[rng.integers(0, 4, 220)]
        keep = rng.random(len(truth)) > 0.04
        draft = bytes(truth[keep])
        drafts.append(b">c%d\n%s\n" % (c, draft))
        for i in range(4):
            keep = rng.random(len(truth)) > 0.04
            r = bytes(truth[keep])
            rid = f"r{c}_{i}"
            reads.append(b">%s\n%s\n" % (rid.encode(), r))
            paf.append(f"{rid}\t{len(r)}\t0\t{len(r)}\t+\tc{c}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    (d / "draft.fasta").write_bytes(b"".join(drafts))
    (d / "reads.fasta").write_bytes(b"".join(reads))
    (d / "ovl.paf").write_text("\n".join(paf) + "\n")


def test_sigterm_leaves_final_snapshot(tmp_path, monkeypatch, capsys):
    """The eviction contract end to end, in process: a ledger worker
    SIGTERM'd mid-shard exits 143 through the CLI's orderly teardown,
    which must publish a *final* metric snapshot before the process
    goes away."""
    from racon_tpu import cli
    _tiny_inputs(tmp_path)
    ledger = str(tmp_path / "ledger")
    monkeypatch.setenv("RACON_TPU_DIST_SHARDS", "2")
    monkeypatch.setenv(obs_fleet.ENV_FLUSH_S, "0")
    faults.configure("dist/contig:0!term")
    rc = cli.main(["--backend", "jax", "--ledger-dir", ledger,
                   "--workers", "1", "--worker-id", "W",
                   str(tmp_path / "reads.fasta"),
                   str(tmp_path / "ovl.paf"),
                   str(tmp_path / "draft.fasta")])
    capsys.readouterr()
    assert rc == 143
    shards = obs_fleet.load_worker_shards(
        os.path.join(ledger, obs_fleet.OBS_SUBDIR))
    assert len(shards) == 1
    last = shards[0]["records"][-1]
    assert last["worker_id"] == "W"
    assert last["final"], "SIGTERM teardown did not flush a final " \
                          "snapshot"

# ---------------------------------------------------------- elastic fleet

def test_aggregate_split_lineage_and_supervisor_fold(tmp_path):
    """The elastic-fleet view: split/spawn/retire event counts, the
    child->parent lineage map, split markers in the timeline, and the
    supervisor heartbeat's metric facts folded into the fleet model."""
    obs = tmp_path / obs_fleet.OBS_SUBDIR
    obs.mkdir()
    w, _ = _writer(obs, "A", "fp1")
    w.flush(final=True)
    events = [
        {"ev": "spawn", "worker": "as0", "reason": "scale-up"},
        {"ev": "claim", "name": "shard_0", "worker": "A", "t": 1.0},
        {"ev": "split", "name": "shard_0", "child": "shard_0s1_1",
         "worker": "A", "epoch": 1, "start": 2, "end": 6, "t": 2.0},
        {"ev": "claim", "name": "shard_0s1_1", "worker": "B",
         "t": 2.5},
        {"ev": "split", "name": "shard_0s1_1",
         "child": "shard_0s1_1s1_1", "worker": "B", "epoch": 1,
         "start": 4, "end": 6, "t": 3.0},
        {"ev": "retire", "worker": "as0", "reason": "scale-down"},
    ]
    with open(tmp_path / "events.jsonl", "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")
    hb = {"schema": 1, "unix_time": 12.0, "interval_s": 0.5,
          "target_workers": 2, "live_workers": 2, "done": False,
          "metrics": {"dist_scale_up_total": 3,
                      "dist_scale_down_total": 1,
                      "fleet_target_workers": 2,
                      "bogus_non_numeric": "nope"}}
    (obs / obs_fleet.SUPERVISOR_NAME).write_text(json.dumps(hb))
    model = obs_fleet.aggregate(str(tmp_path))
    assert model["splits"] == 2
    assert model["spawns"] == 1 and model["retires"] == 1
    assert model["lineage"] == {
        "shard_0s1_1": "shard_0",
        "shard_0s1_1s1_1": "shard_0s1_1"}
    lane = model["timeline"]["shard_0"]
    assert [e["ev"] for e in lane] == ["claim", "split"]
    assert lane[1]["child"] == "shard_0s1_1"
    assert model["supervisor"]["target_workers"] == 2
    # Heartbeat metrics fold into the fleet numbers; non-numeric
    # entries are dropped, never exported.
    assert model["fleet"]["dist_scale_up_total"] == 3
    assert model["fleet"]["dist_scale_down_total"] == 1
    assert model["fleet"]["fleet_target_workers"] == 2
    assert "bogus_non_numeric" not in model["fleet"]
    # ...and render as valid, byte-stable OpenMetrics.
    text = obs_export.render_fleet(model)
    assert obs_export.validate_openmetrics(text) == []
    assert "racon_tpu_dist_scale_up_total 3" in text
    assert "racon_tpu_fleet_target_workers 2" in text
    assert text == obs_export.render_fleet(
        obs_fleet.aggregate(str(tmp_path)))


def test_autoscale_merge_kinds():
    """The supervisor's counters sum across restarts; the target size
    is a point-in-time gauge and must take the last value."""
    mk = obs_metrics.merge_kind
    assert mk("dist_scale_up_total") == obs_metrics.MERGE_SUM
    assert mk("dist_scale_down_total") == obs_metrics.MERGE_SUM
    assert mk("dist_splits_total") == obs_metrics.MERGE_SUM
    assert mk("fleet_target_workers") == obs_metrics.MERGE_LAST
    assert obs_metrics.merge_values("fleet_target_workers",
                                    [4, 2]) == 2
