"""Ingest data-plane tests (ISSUE 12): parallel inflate plan
equivalence, mmap index-first reader parity, the zero-copy invariant,
offset-bearing truncation errors, fault-site recovery, prefetch
overlap, and the RACON_TPU_INGEST gate differential.

The contract under test everywhere: whatever path the gate selects —
BGZF worker-pool inflate, multi-member inflate, streamed single-member
inflate, or the mmap index-first readers — records, offsets, errors,
and polished output are byte-identical to the serial PR-8 readers.
"""

import contextlib
import gzip
import io
import os
import struct
import zlib

import numpy as np
import pytest

from racon_tpu.io import ingest as ingest_mod
from racon_tpu.io.inflate import bgzf_block_size, open_gzip_source
from racon_tpu.io.ingest import (IndexedFastaParser, IndexedFastqParser,
                                 materialized_copies, prefetch_ok,
                                 reset_materialized, scan_index_mmap)
from racon_tpu.io.parsers import (CHUNK_SIZE, FastaParser, FastqParser,
                                  ParseError, create_sequence_parser,
                                  scan_sequence_index)
from racon_tpu.pipeline.streaming import IngestPrefetcher, serial_chunks
from racon_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("RACON_TPU_INGEST", raising=False)
    monkeypatch.delenv("RACON_TPU_INGEST_WORKERS", raising=False)
    faults.configure(None)
    reset_materialized()
    yield
    faults.configure(None)


def _bgzf_block(payload: bytes) -> bytes:
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    cdata = co.compress(payload) + co.flush()
    bsize = len(cdata) + 26            # 12 hdr + 6 extra + 8 footer
    return (b"\x1f\x8b\x08\x04" + b"\x00" * 6 + struct.pack("<H", 6)
            + b"BC" + struct.pack("<HH", 2, bsize - 1) + cdata
            + struct.pack("<II", zlib.crc32(payload) & 0xFFFFFFFF,
                          len(payload)))


def _write_bgzf(path, payload, block=4096):
    with open(path, "wb") as fh:
        for i in range(0, len(payload), block):
            fh.write(_bgzf_block(payload[i:i + block]))
        fh.write(_bgzf_block(b""))     # BGZF EOF marker


def _write_members(path, payload, n=6):
    step = max(len(payload) // n, 1)
    with open(path, "wb") as fh:
        for i in range(0, len(payload), step):
            fh.write(gzip.compress(payload[i:i + step]))


FA_PAYLOAD = b"".join(
    b">r%d desc %d\nACGTTGCA%d\nGGGGCC\n" % (i, i, i) for i in range(400))
FQ_PAYLOAD = b"".join(
    b"@q%d\nACGTACGTAC\n+\nIIIIJJJJKK\n" % i for i in range(400))


# --------------------------------------------------------- inflate plans

def test_bgzf_header_detection(tmp_path):
    p = str(tmp_path / "x.gz")
    _write_bgzf(p, b"hello world")
    blob = open(p, "rb").read()
    size = bgzf_block_size(blob, 0, len(blob))
    assert size is not None and 0 < size <= len(blob)
    # A plain gzip member has no BC subfield.
    assert bgzf_block_size(gzip.compress(b"x"), 0, 99) is None


def test_plan_selection_and_roundtrip(tmp_path):
    cases = {}
    p = str(tmp_path / "bg.fasta.gz")
    _write_bgzf(p, FA_PAYLOAD)
    cases[p] = "bgzf"
    p = str(tmp_path / "mm.fasta.gz")
    _write_members(p, FA_PAYLOAD)
    cases[p] = "members"
    p = str(tmp_path / "st.fasta.gz")
    open(p, "wb").write(gzip.compress(FA_PAYLOAD))
    cases[p] = "stream"
    p = str(tmp_path / "empty.fasta.gz")
    open(p, "wb").close()
    cases[p] = "empty"
    for path, want in cases.items():
        with open_gzip_source(path) as src:
            got = b"".join(src.blocks())
        assert src.mode == want, (path, src.mode)
        assert got == (FA_PAYLOAD if want != "empty" else b"")


def test_parser_equivalence_across_plans(tmp_path):
    """BGZF vs multi-member vs streamed gzip vs mmap plain file: same
    records (names, data, quality) from create_sequence_parser."""
    paths = {}
    for tag, payload, ext in (("fa", FA_PAYLOAD, "fasta"),
                              ("fq", FQ_PAYLOAD, "fastq")):
        plain = str(tmp_path / f"{tag}.{ext}")
        open(plain, "wb").write(payload)
        bg = str(tmp_path / f"{tag}_bg.{ext}.gz")
        _write_bgzf(bg, payload)
        mm = str(tmp_path / f"{tag}_mm.{ext}.gz")
        _write_members(mm, payload)
        st = str(tmp_path / f"{tag}_st.{ext}.gz")
        open(st, "wb").write(gzip.compress(payload))
        paths[tag] = (plain, bg, mm, st)

    for tag, group in paths.items():
        outs = []
        for path in group:
            for gate in ("0", "1"):
                os.environ["RACON_TPU_INGEST"] = gate
                recs = [(s.name, bytes(s.data),
                         None if s.quality is None else bytes(s.quality))
                        for s in create_sequence_parser(path).parse_all()]
                outs.append(recs)
        assert all(o == outs[0] for o in outs), tag
        assert len(outs[0]) == 400


def test_chunked_parse_boundary_parity(tmp_path):
    """parse(max_bytes) must cut chunks at the same records on the
    indexed reader as on the serial one (identical nbytes budget)."""
    plain = str(tmp_path / "x.fasta")
    open(plain, "wb").write(FA_PAYLOAD)
    for mb in (1, 64, 333):
        serial, indexed = FastaParser(plain), IndexedFastaParser(plain)
        while True:
            c1, m1 = serial.parse(mb)
            c2, m2 = indexed.parse(mb)
            assert [s.name for s in c1] == [s.name for s in c2]
            assert m1 == m2
            if not m1:
                break


def test_scan_offsets_equivalence(tmp_path):
    for payload, ext in ((FA_PAYLOAD, "fasta"), (FQ_PAYLOAD, "fastq")):
        plain = str(tmp_path / f"s.{ext}")
        open(plain, "wb").write(payload)
        os.environ["RACON_TPU_INGEST"] = "0"
        serial = scan_sequence_index(plain)
        os.environ["RACON_TPU_INGEST"] = "1"
        assert scan_index_mmap(plain) == serial
        assert scan_sequence_index(plain) == serial   # dispatches mmap
        assert serial[0] == 400


# ----------------------------------------------------------- zero-copy

def test_zero_copy_invariant_single_line(tmp_path):
    """Single-line-per-record files must produce memoryview payloads
    with ZERO bytes materializations (the counting shim is the gate)."""
    fa = str(tmp_path / "z.fasta")
    open(fa, "wb").write(b">a\nACGTACGTAC\n>b\nTTTTGGGG\n")
    fq = str(tmp_path / "z.fastq")
    open(fq, "wb").write(b"@a\nACGT\n+\nIIII\n@b\nGGCC\n+\nJJJJ\n")
    reset_materialized()
    fa_recs = IndexedFastaParser(fa).parse_all()
    fq_recs = IndexedFastqParser(fq).parse_all()
    assert materialized_copies() == 0
    for s in fa_recs + fq_recs:
        assert isinstance(s.data, memoryview), type(s.data)
    assert all(isinstance(s.quality, memoryview) for s in fq_recs)
    # And the views feed the packed device encode with no copy.
    from racon_tpu.ops.encode import encode_bases
    enc = encode_bases(fa_recs[0].data)
    assert enc.tolist() == encode_bases(b"ACGTACGTAC").tolist()


def test_zero_copy_counts_multiline_joins(tmp_path):
    fa = str(tmp_path / "w.fasta")
    open(fa, "wb").write(b">a\nACGT\nACGT\n>b\nGGGG\n")
    reset_materialized()
    recs = IndexedFastaParser(fa).parse_all()
    assert bytes(recs[0].data) == b"ACGTACGT"
    assert materialized_copies() == 1      # the wrapped record only


# ------------------------------------------------- offset-bearing errors

def test_multimember_truncation_ordinal_and_offset(tmp_path):
    p = str(tmp_path / "t.fasta.gz")
    _write_members(p, FA_PAYLOAD, n=6)
    blob = open(p, "rb").read()
    open(p, "wb").write(blob[:-25])        # tear the final member
    with pytest.raises(ParseError) as ei:
        FastaParser(p).parse_all()
    msg = str(ei.value)
    assert "member" in msg and "compressed offset" in msg, msg
    assert ei.value.offset is not None and 0 < ei.value.offset < len(blob)


def test_large_gzip_truncation_offset(tmp_path):
    """>=4 MB multi-member gzip torn mid-member: the error names the
    member ordinal and a compressed offset inside the file."""
    line = bytes(np.frombuffer(b"ACGT", np.uint8)[
        np.random.default_rng(5).integers(0, 4, 1 << 20)])
    payload = b"".join(b">c%d\n%s\n" % (i, line) for i in range(8))
    assert len(payload) > 4 << 20
    p = str(tmp_path / "big.fasta.gz")
    _write_members(p, payload, n=8)
    blob = open(p, "rb").read()
    assert len(blob) > 1 << 20
    open(p, "wb").write(blob[:len(blob) // 2])   # cut deep mid-file
    with pytest.raises(ParseError) as ei:
        create_sequence_parser(p).parse_all()
    msg = str(ei.value)
    assert "compressed offset" in msg and "member" in msg, msg
    assert 0 < ei.value.offset <= len(blob) // 2


def test_fastq_quality_mismatch_names_record_and_offset(tmp_path):
    bad = b"@ok\nACGT\n+\nIIII\n@broke\nACGT\n+\nIIIIII\n"
    p = str(tmp_path / "bad.fastq")
    open(p, "wb").write(bad)
    msgs = []
    for cls in (FastqParser, IndexedFastqParser):
        with pytest.raises(ParseError) as ei:
            cls(p).parse_all()
        assert "'broke'" in str(ei.value)
        assert ei.value.offset == bad.index(b"@broke")
        msgs.append(str(ei.value))
    assert msgs[0] == msgs[1]              # identical error contract
    # The structural scan rejects it too, on both paths.
    for gate in ("0", "1"):
        os.environ["RACON_TPU_INGEST"] = gate
        with pytest.raises(ParseError, match="quality length mismatch"):
            scan_sequence_index(p)


# -------------------------------------------------- fault-site recovery

def test_io_inflate_fault_surfaces_and_recovers(tmp_path):
    p = str(tmp_path / "f.fasta.gz")
    _write_members(p, FA_PAYLOAD, n=4)
    faults.configure("io/inflate:0")
    with pytest.raises(ParseError, match="read failure"):
        FastaParser(p).parse_all()
    faults.configure(None)
    recs = FastaParser(p).parse_all()      # clean retry: full parse
    assert len(recs) == 400


def test_io_inflate_torn_degrades_to_short_read(tmp_path):
    """torn at the read-only io/inflate site = the short-read drill
    (resilience/faults.py degrades torn to raise at non-write sites)."""
    p = str(tmp_path / "g.fasta.gz")
    _write_bgzf(p, FA_PAYLOAD)
    faults.configure("io/inflate:1!torn")
    with pytest.raises(ParseError):
        FastaParser(p).parse_all()
    faults.configure(None)
    assert len(FastaParser(p).parse_all()) == 400


def test_io_read_fault_on_indexed_reader(tmp_path):
    plain = str(tmp_path / "h.fasta")
    open(plain, "wb").write(FA_PAYLOAD)
    faults.configure("io/read:2")
    with pytest.raises(ParseError, match="read failure"):
        IndexedFastaParser(plain).parse_all()
    faults.configure(None)
    assert len(IndexedFastaParser(plain).parse_all()) == 400


def test_prefetch_disabled_under_io_faults():
    assert prefetch_ok()
    faults.configure("io/read:5")
    assert not prefetch_ok()               # determinism guard
    faults.configure("h2d/chunk:0")
    assert prefetch_ok()                   # non-io sites don't care
    os.environ["RACON_TPU_INGEST"] = "0"
    faults.configure(None)
    assert not prefetch_ok()               # gate off wins


# --------------------------------------------------- prefetch overlap

def test_prefetcher_matches_serial_chunks(tmp_path):
    p = str(tmp_path / "pf.fastq")
    open(p, "wb").write(FQ_PAYLOAD)
    serial = [[s.name for s in chunk]
              for chunk, _ in serial_chunks(FastqParser(p), 700)]
    pf = IngestPrefetcher(FastqParser(p), 700, "test")
    try:
        streamed = [[s.name for s in chunk] for chunk, _ in pf.chunks()]
    finally:
        pf.close()
    assert streamed == serial and sum(map(len, serial)) == 400


def test_prefetcher_propagates_parse_error(tmp_path):
    p = str(tmp_path / "bad.fastq")
    open(p, "wb").write(b"@a\nACGT\n+\nIIII\nnot a header\n")
    pf = IngestPrefetcher(FastqParser(p), CHUNK_SIZE, "err")
    try:
        with pytest.raises(ParseError, match="malformed FASTQ"):
            for _chunk in pf.chunks():
                pass
    finally:
        pf.close()


def test_prefetcher_close_is_safe_midstream(tmp_path):
    p = str(tmp_path / "mid.fasta")
    open(p, "wb").write(FA_PAYLOAD)
    pf = IngestPrefetcher(FastaParser(p), 100, "abandon")
    next(iter(pf.chunks()))
    pf.close()                             # abandons cleanly, no hang
    pf.close()                             # idempotent


# ------------------------------------------------------- merge semantics

def test_ingest_merge_kinds():
    from racon_tpu.obs import metrics as obs_metrics
    mk = obs_metrics.merge_kind
    assert mk("ingest_bytes_in") == obs_metrics.MERGE_SUM
    assert mk("ingest_inflate_s") == obs_metrics.MERGE_SUM
    assert mk("ingest_records") == obs_metrics.MERGE_SUM
    assert mk("ingest_fraction_of_wall") == obs_metrics.MERGE_LAST
    assert mk("ingest_enabled") == obs_metrics.MERGE_LAST


# ------------------------------------------------------ CLI differential

def _cli_inputs(tmp_path, gz=False):
    rng = np.random.default_rng(7)
    bases = np.frombuffer(b"ACGT", np.uint8)
    truth = bases[rng.integers(0, 4, 360)]

    def noisy():
        out = []
        for b in truth:
            r = rng.random()
            if r < 0.04:
                continue
            out.append(int(bases[rng.integers(0, 4)]) if r < 0.08
                       else int(b))
        return bytes(out)

    draft = noisy()
    reads, paf = [], []
    for i in range(7):
        r = noisy()
        reads.append(b">r%d\n%s\n" % (i, r))
        paf.append(f"r{i}\t{len(r)}\t0\t{len(r)}\t+\tc1\t{len(draft)}"
                   f"\t0\t{len(draft)}\t{min(len(r), len(draft))}"
                   f"\t{max(len(r), len(draft))}\t60".encode())
    files = {"draft.fasta": b">c1\n" + draft + b"\n",
             "reads.fasta": b"".join(reads),
             "ovl.paf": b"\n".join(paf) + b"\n"}
    out = []
    for name, data in files.items():
        path = tmp_path / (name + (".gz" if gz else ""))
        path.write_bytes(gzip.compress(data) if gz else data)
        out.append(str(path))
    return out[1], out[2], out[0]          # reads, ovl, draft


def _run_cli(reads, ovl, draft):
    from racon_tpu import cli
    stdout = io.StringIO()
    stdout.buffer = io.BytesIO()
    with contextlib.redirect_stdout(stdout), \
            contextlib.redirect_stderr(io.StringIO()):
        rc = cli.main(["--backend", "jax", reads, ovl, draft])
    assert rc == 0
    return stdout.buffer.getvalue()


def test_cli_gate_differential(tmp_path):
    """RACON_TPU_INGEST=0 vs =1, plain and gzipped inputs: all four
    polished FASTAs byte-identical."""
    plain = _cli_inputs(tmp_path, gz=False)
    gz = _cli_inputs(tmp_path, gz=True)
    outs = []
    for group in (plain, gz):
        for gate in ("0", "1"):
            os.environ["RACON_TPU_INGEST"] = gate
            outs.append(_run_cli(*group))
    assert outs[0].startswith(b">c1 LN:i:")
    assert all(o == outs[0] for o in outs)


def test_ledger_fleet_gate_differential(tmp_path, monkeypatch):
    """A 2-shard ledger fleet with the ingest plane on merges
    byte-identically to the serial gate-off run."""
    import contextlib as _ctx
    from racon_tpu import cli
    from racon_tpu.distributed import ledger as dledger
    monkeypatch.setenv(dledger.ENV_SHARDS, "2")

    rng = np.random.default_rng(9)
    bases = np.frombuffer(b"ACGT", np.uint8)
    drafts, reads, paf = [], [], []
    for ci in range(2):
        truth = bases[rng.integers(0, 4, 300)]
        draft = bytes(truth)
        drafts.append(b">c%d\n%s\n" % (ci, draft))
        for i in range(5):
            idx = rng.random(300) > 0.05
            r = bytes(truth[idx])
            name = f"c{ci}r{i}"
            reads.append(b">%s\n%s\n" % (name.encode(), r))
            paf.append(f"{name}\t{len(r)}\t0\t{len(r)}\t+\tc{ci}\t300"
                       f"\t0\t300\t{len(r)}\t300\t60")
    (tmp_path / "draft.fasta").write_bytes(b"".join(drafts))
    (tmp_path / "reads.fasta").write_bytes(b"".join(reads))
    (tmp_path / "ovl.paf").write_text("\n".join(paf) + "\n")
    args = [str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.paf"),
            str(tmp_path / "draft.fasta")]

    def run(*extra):
        stdout = io.StringIO()
        stdout.buffer = io.BytesIO()
        with _ctx.redirect_stdout(stdout), \
                _ctx.redirect_stderr(io.StringIO()):
            rc = cli.main(["--backend", "jax", *extra, *args])
        assert rc == 0
        return stdout.buffer.getvalue()

    os.environ["RACON_TPU_INGEST"] = "0"
    base = run()
    os.environ["RACON_TPU_INGEST"] = "1"
    merged = run("--ledger-dir", str(tmp_path / "ledger"),
                 "--workers", "2", "--worker-id", "w0")
    assert merged == base and base.count(b">") == 2


@pytest.mark.ava
def test_ava_config_gate_differential(ref_data):
    """The kF ava config (reference golden workload) polishes
    byte-identically with the ingest plane on and off — gzipped FASTQ
    reads + gzipped ava PAF through the full fragment-correction
    path."""
    from racon_tpu.models.polisher import PolisherType, create_polisher

    def run():
        p = create_polisher(ref_data("sample_reads.fastq.gz"),
                            ref_data("sample_ava_overlaps.paf.gz"),
                            ref_data("sample_reads.fastq.gz"),
                            PolisherType.kF, 500, 10.0, 0.3,
                            1, -1, -1, backend="native")
        p.initialize()
        return [(s.name, bytes(s.data)) for s in p.polish(False)]

    os.environ["RACON_TPU_INGEST"] = "0"
    serial = run()
    os.environ["RACON_TPU_INGEST"] = "1"
    assert run() == serial
    assert len(serial) == 236
