"""Pallas kernels in interpreter mode on CPU vs their XLA twins.

The production Pallas kernels only run on TPU, so before this gate the
CPU tier-1 suite exercised the XLA twins alone — a kernel-body bug
(e.g. in the dual-column metadata shifts) would ship silently and only
surface as a TPU-side differential failure. ``interpret=True`` runs the
EXACT kernel body through the Pallas interpreter on CPU; these tests
pin it bit-identical to the twins the rest of tier-1 certifies.

Shapes honor the kernels' tiling contracts: band TB=128 lanes with
Lq % 8 == 0, flat TB=128 / CH=32 / Lt % 128 == 0.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from racon_tpu.ops.flat import fw_dirs_xla
from racon_tpu.ops.pallas.band_kernel import (UC_BOUNDARY, band_geometry,
                                              fw_dirs_band, fw_dirs_band_xla,
                                              fw_dirs_band_tile,
                                              fw_dirs_band_xla_tile)
from racon_tpu.ops.pallas.flat_kernel import fw_dirs_pallas

M, X, G = 5, -4, -8


def _band_inputs(rng, B=128, Lq=32, W=128):
    lq = rng.integers(10, Lq + 1, B).astype(np.int32)
    lt = (lq + rng.integers(-5, 6, B)).clip(5).astype(np.int32)
    qT = rng.integers(0, 4, (Lq, B)).astype(np.uint8)
    klo, _ = band_geometry(jnp.asarray(lq), jnp.asarray(lt), W)
    klo_h = np.asarray(klo)
    ts = rng.integers(0, 4, (B, int(lt.max()))).astype(np.uint8)
    tband = np.full((B, W + Lq), 7, np.uint8)
    for b in range(B):
        for y in range(W + Lq):
            j = klo_h[b] + y
            if 0 <= j < lt[b]:
                tband[b, y] = ts[b, j]
    return tband, qT, klo, lq


@pytest.mark.parametrize("scoring", [(M, X, G), (0, -1, -1)])
def test_band_kernel_interpret_matches_xla_twin(scoring):
    """fw_dirs_band(interpret=True) == fw_dirs_band_xla on all THREE
    outputs — dirs (packed dir|consumer|up_run byte), nxt (dual-column
    predecessor metadata plane), hlast — modulo the layout transpose."""
    m, x, g = scoring
    rng = np.random.default_rng(7)
    tband, qT, klo, lq = _band_inputs(rng)
    W = 128
    di, ni, hi = fw_dirs_band(jnp.asarray(tband), jnp.asarray(qT), klo,
                              jnp.asarray(lq), match=m, mismatch=x,
                              gap=g, W=W, interpret=True)
    dx, nx, hx = fw_dirs_band_xla(jnp.asarray(tband), jnp.asarray(qT),
                                  klo, jnp.asarray(lq), match=m,
                                  mismatch=x, gap=g, W=W)
    # Pallas band layout is [Lq, W, B]; the twin's is [Lq, B, W].
    assert np.array_equal(np.transpose(np.asarray(di), (0, 2, 1)),
                          np.asarray(dx))
    assert np.array_equal(np.transpose(np.asarray(ni), (0, 2, 1)),
                          np.asarray(nx))
    assert np.array_equal(np.asarray(hi), np.asarray(hx))


@pytest.mark.parametrize("scoring", [(M, X, G), (0, -1, -1)])
def test_tiled_band_kernel_interpret_matches_xla_twin(scoring):
    """fw_dirs_band_tile(interpret=True) == fw_dirs_band_xla_tile on all
    FIVE outputs (dirs, nxt, hlast, carried score frontier, carried
    packed N/U/C frontier), for both the cold-start tile (i0=0, boundary
    frontier) and a warm continuation tile (i0=T, frontier produced by
    the twin) — modulo the [T, W, B] vs [T, B, W] layout transpose."""
    m, x, g = scoring
    rng = np.random.default_rng(13)
    B, Lq, W, T = 8, 64, 128, 32
    tband, qT, klo, lq = _band_inputs(rng, B=B, Lq=Lq, W=W)
    klo_h = np.asarray(klo)
    NEG = -(2 ** 30)
    j0 = klo_h[:, None] + np.arange(W)[None, :]
    prev = jnp.asarray(np.where(j0 >= 0, j0 * g, NEG).astype(np.int32))
    uc = jnp.asarray(np.full((B, W), UC_BOUNDARY, np.int32))
    hl = prev
    for tile in range(2):
        i0 = jnp.full((B,), tile * T, jnp.int32)
        # Per-tile target window: rows [klo + i0, klo + i0 + W + T) of
        # the per-lane diagonal band, same 7-fill as the dispatcher.
        tb_t = jnp.asarray(tband[:, tile * T:tile * T + W + T])
        q_t = jnp.asarray(qT[tile * T:(tile + 1) * T])
        outs_i = fw_dirs_band_tile(tb_t, q_t, klo, jnp.asarray(lq), i0,
                                   prev, uc, hl, match=m, mismatch=x,
                                   gap=g, W=W, tb=B, ch=4, interpret=True)
        outs_x = fw_dirs_band_xla_tile(tb_t, q_t, klo, jnp.asarray(lq), i0,
                                       prev, uc, hl, match=m, mismatch=x,
                                       gap=g, W=W)
        di, ni, hi, pi, ui = [np.asarray(a) for a in outs_i]
        dx, nx, hx, px, ux = [np.asarray(a) for a in outs_x]
        assert np.array_equal(np.transpose(di, (0, 2, 1)), dx), tile
        assert np.array_equal(np.transpose(ni, (0, 2, 1)), nx), tile
        assert np.array_equal(hi, hx), tile
        assert np.array_equal(pi, px), tile
        assert np.array_equal(ui, ux), tile
        # Carry the TWIN's frontier into the next tile so the warm tile
        # exercises a realistic mid-read frontier on both paths.
        hl, prev, uc = outs_x[2], outs_x[3], outs_x[4]


@pytest.mark.parametrize("scoring", [(M, X, G), (0, -1, -1)])
def test_band_kernel_interpret_matches_xla_twin_k4(scoring):
    """Round 8: fw_dirs_band(nxt_k=4, interpret=True) ==
    fw_dirs_band_xla(nxt_k=4) on all FOUR outputs — dirs, nxt (hop-1
    plane), nxt2 (u16 hop-2/3 plane), hlast — and the dirs/nxt pair is
    bitwise the k=2 kernel's (the deep plane is pure addition)."""
    m, x, g = scoring
    rng = np.random.default_rng(7)
    tband, qT, klo, lq = _band_inputs(rng)
    W = 128
    args = (jnp.asarray(tband), jnp.asarray(qT), klo, jnp.asarray(lq))
    kw = dict(match=m, mismatch=x, gap=g, W=W)
    di, ni, n2i, hi = fw_dirs_band(*args, nxt_k=4, interpret=True, **kw)
    dx, nx, n2x, hx = fw_dirs_band_xla(*args, nxt_k=4, **kw)
    d2, n2, _ = fw_dirs_band_xla(*args, **kw)
    assert np.asarray(n2i).dtype == np.uint16
    assert np.array_equal(np.transpose(np.asarray(di), (0, 2, 1)),
                          np.asarray(dx))
    assert np.array_equal(np.transpose(np.asarray(ni), (0, 2, 1)),
                          np.asarray(nx))
    assert np.array_equal(np.transpose(np.asarray(n2i), (0, 2, 1)),
                          np.asarray(n2x))
    assert np.array_equal(np.asarray(hi), np.asarray(hx))
    assert np.array_equal(np.asarray(dx), np.asarray(d2))
    assert np.array_equal(np.asarray(nx), np.asarray(n2))


def test_tiled_band_kernel_interpret_matches_xla_twin_k4():
    """Round 8: the tiled kernels agree at nxt_k=4 on all SIX outputs
    (dirs, nxt, nxt2, hlast, score frontier, 24-bit packed frontier)
    across a cold and a warm tile — the geometry the wide-band device
    redo re-dispatches flagged windows through."""
    rng = np.random.default_rng(13)
    B, Lq, W, T = 8, 64, 128, 32
    tband, qT, klo, lq = _band_inputs(rng, B=B, Lq=Lq, W=W)
    klo_h = np.asarray(klo)
    NEG = -(2 ** 30)
    j0 = klo_h[:, None] + np.arange(W)[None, :]
    prev = jnp.asarray(np.where(j0 >= 0, j0 * G, NEG).astype(np.int32))
    from racon_tpu.ops.pallas.band_kernel import uc_boundary
    uc = jnp.asarray(np.full((B, W), uc_boundary(4), np.int32))
    hl = prev
    for tile in range(2):
        i0 = jnp.full((B,), tile * T, jnp.int32)
        tb_t = jnp.asarray(tband[:, tile * T:tile * T + W + T])
        q_t = jnp.asarray(qT[tile * T:(tile + 1) * T])
        outs_i = fw_dirs_band_tile(tb_t, q_t, klo, jnp.asarray(lq), i0,
                                   prev, uc, hl, match=M, mismatch=X,
                                   gap=G, W=W, tb=B, ch=4, nxt_k=4,
                                   interpret=True)
        outs_x = fw_dirs_band_xla_tile(tb_t, q_t, klo, jnp.asarray(lq),
                                       i0, prev, uc, hl, match=M,
                                       mismatch=X, gap=G, W=W, nxt_k=4)
        di, ni, n2i, hi, pi, ui = [np.asarray(a) for a in outs_i]
        dx, nx, n2x, hx, px, ux = [np.asarray(a) for a in outs_x]
        assert n2i.dtype == np.uint16 and n2x.dtype == np.uint16
        assert np.array_equal(np.transpose(di, (0, 2, 1)), dx), tile
        assert np.array_equal(np.transpose(ni, (0, 2, 1)), nx), tile
        assert np.array_equal(np.transpose(n2i, (0, 2, 1)), n2x), tile
        assert np.array_equal(hi, hx), tile
        assert np.array_equal(pi, px), tile
        assert np.array_equal(ui, ux), tile
        hl, prev, uc = outs_x[3], outs_x[4], outs_x[5]


def test_flat_kernel_interpret_matches_xla():
    """fw_dirs_pallas(interpret=True) == flat.fw_dirs_xla bit-for-bit
    (same [Lq, B, Lt] layout, packed byte included)."""
    rng = np.random.default_rng(3)
    B, Lq, Lt = 128, 32, 128
    tbuf = rng.integers(0, 4, (B, Lt)).astype(np.uint8)
    qT = rng.integers(0, 4, (Lq, B)).astype(np.uint8)
    a = fw_dirs_pallas(jnp.asarray(tbuf), jnp.asarray(qT), match=M,
                       mismatch=X, gap=G, interpret=True)
    b = fw_dirs_xla(jnp.asarray(tbuf), jnp.asarray(qT), match=M,
                    mismatch=X, gap=G)
    assert np.array_equal(np.asarray(a), np.asarray(b))
