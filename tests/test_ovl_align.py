"""Device overlap alignment (ops/ovl_align.py) vs the native path.

The device path computes breaking points straight from the banded
forward + column walk; the native path aligns, emits a CIGAR, and walks
it (models/overlap.py::breaking_points_from_cigar). Both must agree on
every handled overlap (same NW scoring and tie-breaks), and the device
must hand uncertifiable lanes back for fallback rather than emit them.
"""

import gzip
import os

import numpy as np
import pytest

from racon_tpu.models.polisher import create_polisher, PolisherType


def _write_dataset(tmp_path, n_reads=24, read_len=2400, seed=5,
                   rate=0.12, draft_len=40_000):
    """Tiny synthetic draft + reads + PAF with ``rate`` read-vs-draft
    error (default ~12%, ONT-class; the tiled ultralong tests use lower
    rates with longer reads — see test_ovl_tiled.py)."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    draft = bases[rng.integers(0, 4, draft_len)]

    def mutate(seq, rate):
        r = rng.random(len(seq))
        dele = r < rate / 3
        sub = (r >= rate / 3) & (r < 2 * rate / 3)
        ins = (r >= 2 * rate / 3) & (r < rate)
        counts = np.where(dele, 0, np.where(ins, 2, 1))
        starts = np.cumsum(counts) - counts
        out = np.zeros(int(counts.sum()), np.uint8)
        keep = ~dele
        base = np.where(sub, bases[rng.integers(0, 4, len(seq))], seq)
        out[starts[keep]] = base[keep]
        out[starts[ins] + 1] = bases[rng.integers(0, 4, int(ins.sum()))]
        return out

    rc = np.zeros(256, np.uint8)
    rc[bases] = np.frombuffer(b"TGCA", np.uint8)

    reads, paf = [], []
    for i in range(n_reads):
        t0 = int(rng.integers(0, len(draft) - read_len))
        seg = mutate(draft[t0:t0 + read_len], rate)
        strand = i % 3 == 1
        out = rc[seg][::-1] if strand else seg
        reads.append((f"r{i}", out.tobytes()))
        paf.append(f"r{i}\t{len(out)}\t0\t{len(out)}\t"
                   f"{'-' if strand else '+'}\tdraft\t{len(draft)}\t"
                   f"{t0}\t{t0 + read_len}\t{read_len}\t{read_len}\t255")

    d = str(tmp_path)
    with gzip.open(f"{d}/reads.fasta.gz", "wb") as f:
        for name, data in reads:
            f.write(b">" + name.encode() + b"\n" + data + b"\n")
    with gzip.open(f"{d}/draft.fasta.gz", "wb") as f:
        f.write(b">draft\n" + draft.tobytes() + b"\n")
    with gzip.open(f"{d}/overlaps.paf.gz", "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    return d


def _layer_snapshot(p):
    snap = []
    for w in p.windows:
        snap.append([
            (bytes(w.layer_data[i]), int(w.layer_begin[i]),
             int(w.layer_end[i]))
            for i in range(w.n_layers)])
    return snap


@pytest.mark.parametrize("window", [500, 1000])
def test_device_breaking_points_match_native(tmp_path, window):
    d = _write_dataset(tmp_path)
    args = (f"{d}/reads.fasta.gz", f"{d}/overlaps.paf.gz",
            f"{d}/draft.fasta.gz", PolisherType.kC, window, 10.0, 0.3,
            5, -4, -8)
    pn = create_polisher(*args, backend="native")
    pn.initialize()
    pj = create_polisher(*args, backend="jax")
    pj.initialize()
    assert _layer_snapshot(pj) == _layer_snapshot(pn)


def test_overlength_jobs_fall_back(tmp_path):
    """Reads past the device budget must route to the native fallback
    and still produce layers (not silently drop)."""
    d = _write_dataset(tmp_path, n_reads=3, read_len=17_000, seed=7)
    args = (f"{d}/reads.fasta.gz", f"{d}/overlaps.paf.gz",
            f"{d}/draft.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
            5, -4, -8)
    pn = create_polisher(*args, backend="native")
    pn.initialize()
    pj = create_polisher(*args, backend="jax")
    pj.initialize()
    assert _layer_snapshot(pj) == _layer_snapshot(pn)
    assert sum(w.n_layers for w in pj.windows) > 0
