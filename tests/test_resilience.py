"""Resilience subsystem tests: retry/backoff, fault injection,
checkpoint/resume (racon_tpu/resilience/, docs/RESILIENCE.md)."""

import contextlib
import io
import json
import os

import numpy as np
import pytest

from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.resilience import checkpoint as ckpt
from racon_tpu.resilience import faults, retry

BASES = np.frombuffer(b"ACGT", np.uint8)


@pytest.fixture(autouse=True)
def resilience_sandbox(monkeypatch):
    """Keep the process-global injector/policy/registry out of other
    tests (and other tests' env out of these)."""
    monkeypatch.delenv(retry.ENV_RETRY, raising=False)
    monkeypatch.delenv(faults.ENV_FAULTS, raising=False)
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()
    yield
    retry.configure(None)
    faults.configure(None)
    obs_metrics.reset()


# ----------------------------------------------------------- retry policy


def test_backoff_schedule_deterministic():
    a = retry.RetryPolicy(attempts=5, base=0.05, seed=3)
    b = retry.RetryPolicy(attempts=5, base=0.05, seed=3)
    assert a.schedule("h2d/chunk") == b.schedule("h2d/chunk")
    # Jitter is per-site: same policy, different site, different phase.
    assert a.schedule("h2d/chunk") != a.schedule("d2h/chunk")
    # Exponential growth under the cap, within the jitter band.
    sched = a.schedule("h2d/chunk")
    assert len(sched) == 4
    for k, d in enumerate(sched, 1):
        ideal = min(0.05 * 2.0 ** (k - 1), a.max_delay)
        assert ideal * 0.9 <= d <= ideal * 1.1


def test_backoff_cap_and_no_jitter():
    p = retry.RetryPolicy(attempts=10, base=1.0, multiplier=4.0,
                          max_delay=2.5, jitter=0.0)
    assert p.schedule()[-1] == 2.5
    assert p.delay(1) == 1.0            # jitter=0: exact


def test_policy_rejects_zero_attempts():
    with pytest.raises(ValueError, match="invalid attempts"):
        retry.RetryPolicy(attempts=0)


def test_default_policy_env(monkeypatch):
    monkeypatch.setenv(retry.ENV_RETRY, "attempts=7,base=0.2,seed=9")
    retry.configure(None)
    pol = retry.default_policy()
    assert (pol.attempts, pol.base, pol.seed) == (7, 0.2, 9)
    monkeypatch.setenv(retry.ENV_RETRY, "attempts")
    retry.configure(None)
    with pytest.raises(ValueError, match="invalid RACON_TPU_RETRY"):
        retry.default_policy()


# ------------------------------------------------------------- retry.call


def test_call_recovers_from_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("tunnel hiccup")
        return 42

    pol = retry.RetryPolicy(attempts=4, base=0.0, jitter=0.0)
    assert retry.call("t/site", flaky, policy=pol) == 42
    assert len(calls) == 3
    snap = obs_metrics.registry().snapshot()
    assert snap["res_retry_total"] == 2
    assert snap["res_retry_site_t_site"] == 2


def test_call_propagates_nontransient_immediately():
    calls = []

    def buggy():
        calls.append(1)
        raise KeyError("logic error")

    with pytest.raises(KeyError):
        retry.call("t/site", buggy,
                   policy=retry.RetryPolicy(attempts=4, base=0.0))
    assert len(calls) == 1
    assert "res_retry_total" not in obs_metrics.registry().snapshot()


def test_call_exhaustion_degradation_signal():
    def always_down():
        raise TimeoutError("still down")

    pol = retry.RetryPolicy(attempts=3, base=0.0, jitter=0.0)
    with pytest.raises(retry.RetryExhausted) as ei:
        retry.call("d2h/chunk", always_down, policy=pol)
    assert ei.value.site == "d2h/chunk"
    assert ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, TimeoutError)
    snap = obs_metrics.registry().snapshot()
    assert snap["res_retry_exhausted"] == 1
    assert snap["res_retry_total"] == 2     # last try isn't "retried"


def test_call_runs_injector_inside_retry_loop():
    """The acceptance scenario: a fault plan hitting the first N call
    indices at a site is absorbed by N retries of one logical call."""
    faults.configure("h2d/chunk:0,1,2")
    pol = retry.RetryPolicy(attempts=4, base=0.0, jitter=0.0)
    assert retry.call("h2d/chunk", lambda: "ok", policy=pol) == "ok"
    snap = obs_metrics.registry().snapshot()
    assert snap["res_retry_total"] == 3
    assert snap["res_fault_injected_total"] == 3


# --------------------------------------------------------- fault injector


def test_injector_explicit_indices():
    inj = faults.FaultInjector("x/y:0,2")
    with pytest.raises(faults.InjectedFault) as ei:
        inj.check("x/y")
    assert (ei.value.site, ei.value.index) == ("x/y", 0)
    inj.check("x/y")                        # index 1: clean
    with pytest.raises(faults.InjectedFault):
        inj.check("x/y")                    # index 2
    inj.check("other/site")                 # unlisted site: never fires
    assert inj.counts() == {"x/y": 3, "other/site": 1}
    assert [f[1] for f in inj.fired] == [0, 2]


def test_injector_probability_is_seed_deterministic():
    def pattern(seed):
        inj = faults.FaultInjector(f"s:p=0.5;seed={seed}")
        out = []
        for _ in range(64):
            try:
                inj.check("s")
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    assert pattern(1) == pattern(1)
    assert pattern(1) != pattern(2)
    assert 10 < sum(pattern(1)) < 54        # roughly fair coin


def test_injector_spec_errors():
    for bad in ("h2d/chunk", "s:p=1.5", "s:x,y", "s:0!explode",
                "seed=abc", ":0"):
        with pytest.raises(faults.FaultSpecError):
            faults.FaultInjector(bad)


def test_maybe_fault_unarmed_is_noop(monkeypatch):
    faults.configure(None)
    faults.maybe_fault("h2d/chunk")         # no injector: must not raise


# ------------------------------------------------------------- checkpoint


def test_checkpoint_roundtrip(tmp_path):
    d = str(tmp_path / "ck")
    with ckpt.CheckpointStore.create(d, "fp1") as store:
        store.commit(0, b"c0 LN:i:5", b"ACGTA")
        store.commit_dropped(1)
        store.commit(2, b"c2", b"TTT")

    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(res.committed) == [0, 1, 2]
    assert res.read_emitted(0) == b">c0 LN:i:5\nACGTA\n"
    assert res.read_emitted(1) is None
    assert res.read_emitted(2) == b">c2\nTTT\n"
    res.close()
    snap = obs_metrics.registry().snapshot()
    assert snap["res_ckpt_commits"] == 3
    assert snap["res_ckpt_resumes"] == 1


def test_checkpoint_torn_tail_and_orphan_shard_recovery(tmp_path):
    d = str(tmp_path / "ck")
    store = ckpt.CheckpointStore.create(d, "fp1")
    store.commit(0, b"c0", b"AAAA")
    store.commit(1, b"c1", b"CCCC")
    store.close()
    # Crash between shard append and manifest append: orphaned shard
    # bytes plus a torn (newline-less, half-written) manifest record.
    with open(store.shard_path, "ab") as fh:
        fh.write(b">c2\nGG")
    with open(store.manifest_path, "ab") as fh:
        fh.write(b'{"ev": "contig", "tid": 2, "off')

    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(res.committed) == [0, 1]
    assert res.read_emitted(1) == b">c1\nCCCC\n"
    # Shard truncated back to the last referenced byte...
    assert os.path.getsize(res.shard_path) == len(b">c0\nAAAA\n"
                                                  b">c1\nCCCC\n")
    # ...and the manifest rewritten to the valid prefix.
    lines = open(res.manifest_path, "rb").read().splitlines()
    assert len(lines) == 3 and json.loads(lines[0])["ev"] == "begin"
    res.close()


class _Died(BaseException):
    """Stand-in for os._exit in in-process crash drills — BaseException
    so no library except-clause can swallow the 'death'."""


@pytest.fixture
def soft_crash(monkeypatch):
    """Intercept the injector's hard-exit seam so kill/torn faults are
    testable in-process; yields the exception type the 'death' raises."""
    monkeypatch.setattr(faults, "hard_exit",
                        lambda code: (_ for _ in ()).throw(_Died(code)))
    return _Died


def test_first_commit_fsyncs_directory(tmp_path, monkeypatch):
    """The crash-consistency fix: file fsync alone doesn't make a fresh
    file's directory entry durable, so the first append after creating
    the store must also fsync the directory — and later commits must
    not keep paying for it."""
    from racon_tpu.utils import atomicio
    synced = []
    monkeypatch.setattr(atomicio, "fsync_dir",
                        lambda p: synced.append(os.path.abspath(p)))
    d = str(tmp_path / "ck")
    store = ckpt.CheckpointStore.create(d, "fp1")    # begin header
    store.commit(0, b"c0", b"AAAA")                  # first commit
    assert synced.count(os.path.abspath(d)) >= 2     # meta + appends
    synced.clear()
    store.commit(1, b"c1", b"CCCC")
    store.commit_dropped(2)
    assert synced == []          # directory entry already durable
    store.close()


def test_kill_between_appends_leaves_resumable_store(tmp_path,
                                                     soft_crash):
    """Eviction in the mid-commit window (after the shard append,
    before the manifest record): the orphaned shard bytes are discarded
    on resume and only that contig recomputes."""
    faults.configure("ckpt/manifest:1!kill")
    d = str(tmp_path / "ck")
    store = ckpt.CheckpointStore.create(d, "fp1")
    store.commit(0, b"c0", b"AAAA")
    with pytest.raises(soft_crash):
        store.commit(1, b"c1", b"CCCC")
    store.close()
    # c1's shard bytes landed, its manifest record didn't.
    assert b">c1\n" in open(store.shard_path, "rb").read()
    faults.configure(None)
    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(res.committed) == [0]
    assert os.path.getsize(res.shard_path) == len(b">c0\nAAAA\n")
    res.commit(1, b"c1", b"CCCC")                    # recompute works
    assert res.read_emitted(1) == b">c1\nCCCC\n"
    res.close()


def test_torn_manifest_fault_roundtrip(tmp_path, soft_crash):
    """The torn action at ckpt/manifest writes *half* the record
    durably then dies — resume must truncate to the last valid record
    and rewrite the manifest clean."""
    faults.configure("ckpt/manifest:1!torn")
    d = str(tmp_path / "ck")
    store = ckpt.CheckpointStore.create(d, "fp1")
    store.commit(0, b"c0", b"AAAA")
    with pytest.raises(soft_crash):
        store.commit(1, b"c1", b"CCCC")
    store.close()
    raw = open(store.manifest_path, "rb").read()
    assert not raw.endswith(b"\n")       # genuinely torn tail
    faults.configure(None)
    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(res.committed) == [0]
    lines = open(res.manifest_path, "rb").read()
    assert lines.endswith(b"\n") and lines.count(b"\n") == 2
    res.close()


def test_checkpoint_fingerprint_mismatch_refuses(tmp_path):
    d = str(tmp_path / "ck")
    ckpt.CheckpointStore.create(d, "fp1").close()
    with pytest.raises(ckpt.CheckpointError, match="refusing to resume"):
        ckpt.CheckpointStore.resume(d, "fp2")
    with pytest.raises(ckpt.CheckpointError, match="unreadable"):
        ckpt.CheckpointStore.resume(str(tmp_path / "nope"), "fp1")


def test_run_fingerprint_sensitivity(tmp_path):
    p = tmp_path / "in.fasta"
    p.write_bytes(b">a\nACGT\n")
    base = ckpt.run_fingerprint({"match": 5}, [str(p)])
    assert base == ckpt.run_fingerprint({"match": 5}, [str(p)])
    assert base != ckpt.run_fingerprint({"match": 6}, [str(p)])
    p.write_bytes(b">a\nACGA\n")
    assert base != ckpt.run_fingerprint({"match": 5}, [str(p)])


# ----------------------------------- v2 segmented manifests (docs/AVA.md)


def test_v2_roundtrip_segments_and_dropped(tmp_path):
    """A segment_targets=4 store amortizes 5 commits (one dropped) into
    2 run-length manifest records, and resume expands them back into
    per-target records indistinguishable from v1's."""
    d = str(tmp_path / "ck")
    with ckpt.CheckpointStore.create(d, "fp1",
                                     segment_targets=4) as store:
        store.commit(0, b"r0", b"ACGTA")
        store.commit(1, b"r1", b"TTT")
        store.commit_dropped(2)
        store.commit(3, b"r3", b"GG")       # seals segment [0, 4)
        store.commit(4, b"r4", b"CCCC")     # tail: sealed at close()

    recs = [json.loads(x) for x in
            open(os.path.join(d, ckpt.MANIFEST_NAME),
                 "rb").read().splitlines()]
    assert recs[0]["manifest"] == ckpt.MANIFEST_V2
    assert recs[0]["seg_targets"] == 4
    assert [r["ev"] for r in recs[1:]] == ["seg", "seg"]
    assert recs[1] == {"ev": "seg", "start": 0, "end": 4, "offset": 0,
                       "lengths": [10, 8, 0, 7]}   # >name\ndata\n blobs

    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert res.segment_targets == 4         # mode from the header
    assert sorted(res.committed) == [0, 1, 2, 3, 4]
    assert res.read_emitted(0) == b">r0\nACGTA\n"
    assert res.read_emitted(2) is None
    assert res.read_emitted(4) == b">r4\nCCCC\n"
    # Unsealed commits still serve live bytes (flushed, not yet fsync'd).
    res.commit(5, b"r5", b"AA")
    assert res.read_emitted(5) == b">r5\nAA\n"
    res.close()
    snap = obs_metrics.registry().snapshot()
    assert snap["res_ckpt_commits"] == 6
    assert snap["res_ckpt_seals"] == 3      # full, close, post-resume close


def test_v2_discontinuity_seals_segment(tmp_path):
    """A target-id gap (shard bounds are contiguous, but a worker can
    skip ahead after a steal) must seal the open segment — run-length
    records cannot span a hole."""
    d = str(tmp_path / "ck")
    with ckpt.CheckpointStore.create(d, "fp1",
                                     segment_targets=8) as store:
        store.commit(0, b"r0", b"AA")
        store.commit(1, b"r1", b"CC")
        store.commit(5, b"r5", b"GG")       # gap: seals [0, 2) first
    recs = [json.loads(x) for x in
            open(os.path.join(d, ckpt.MANIFEST_NAME),
                 "rb").read().splitlines()]
    segs = [(r["start"], r["end"]) for r in recs if r["ev"] == "seg"]
    assert segs == [(0, 2), (5, 6)]
    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(res.committed) == [0, 1, 5]
    assert res.read_emitted(5) == b">r5\nGG\n"
    res.close()


def test_v2_crash_loses_at_most_unsealed_segment(tmp_path):
    """An abandoned store (no close, so no tail seal) forfeits exactly
    the unsealed segment: its flushed shard bytes are truncated on
    resume and those targets recompute."""
    d = str(tmp_path / "ck")
    store = ckpt.CheckpointStore.create(d, "fp1", segment_targets=2)
    store.commit(0, b"r0", b"AAAA")
    store.commit(1, b"r1", b"CCCC")         # seals [0, 2)
    store.commit(2, b"r2", b"GGGG")         # unsealed; flushed to shard
    sealed_end = store.committed[1]["offset"] + \
        store.committed[1]["length"]
    assert os.path.getsize(store.shard_path) > sealed_end
    # No close(): simulate eviction mid-segment.
    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(res.committed) == [0, 1]
    assert os.path.getsize(res.shard_path) == sealed_end
    res.commit(2, b"r2", b"GGGG")           # recompute works
    res.close()
    fin = ckpt.CheckpointStore.resume(d, "fp1")
    assert fin.read_emitted(2) == b">r2\nGGGG\n"
    fin.close()


def test_v2_torn_seal_fault_at_segment_boundary(tmp_path, soft_crash):
    """The ckpt/manifest torn drill on a v2 store lands exactly on a
    segment seal: half the segment record becomes durable, recovery
    drops it and truncates the shard back to the last sealed segment."""
    faults.configure("ckpt/manifest:1!torn")
    d = str(tmp_path / "ck")
    store = ckpt.CheckpointStore.create(d, "fp1", segment_targets=2)
    store.commit(0, b"r0", b"AAAA")
    store.commit(1, b"r1", b"CCCC")         # seal #1 (fault index 0)
    store.commit(2, b"r2", b"GGGG")
    with pytest.raises(soft_crash):
        store.commit(3, b"r3", b"TTTT")     # seal #2 tears and dies
    raw = open(store.manifest_path, "rb").read()
    assert not raw.endswith(b"\n")          # genuinely torn tail
    faults.configure(None)
    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(res.committed) == [0, 1]
    assert os.path.getsize(res.shard_path) == len(b">r0\nAAAA\n"
                                                  b">r1\nCCCC\n")
    clean = open(res.manifest_path, "rb").read()
    assert clean.endswith(b"\n") and clean.count(b"\n") == 2
    res.commit(2, b"r2", b"GGGG")
    res.commit(3, b"r3", b"TTTT")
    res.close()
    fin = ckpt.CheckpointStore.resume(d, "fp1")
    assert sorted(fin.committed) == [0, 1, 2, 3]
    assert fin.read_emitted(3) == b">r3\nTTTT\n"
    fin.close()


def test_v2_compaction_byte_identity(tmp_path, monkeypatch):
    """Compaction merges adjacent contiguous segments and atomically
    rewrites the manifest; recovery from the compacted store must be
    byte-identical to its uncompacted twin."""
    def fill(d, compact):
        monkeypatch.setenv(ckpt.ENV_AVA_COMPACT, compact)
        with ckpt.CheckpointStore.create(d, "fp1",
                                         segment_targets=2) as store:
            for tid in range(8):
                store.commit(tid, b"r%d" % tid, b"ACGT" * (tid + 1))

    a = str(tmp_path / "compacted")
    b = str(tmp_path / "plain")
    fill(a, "2")        # compact every 2 seals
    fill(b, "0")        # never compact
    monkeypatch.delenv(ckpt.ENV_AVA_COMPACT)

    n_lines = lambda d: open(os.path.join(d, ckpt.MANIFEST_NAME),
                             "rb").read().count(b"\n")
    assert n_lines(b) == 5                  # header + 4 seg records
    assert n_lines(a) < n_lines(b)

    ra = ckpt.CheckpointStore.resume(a, "fp1")
    rb = ckpt.CheckpointStore.resume(b, "fp1")
    assert sorted(ra.committed) == sorted(rb.committed) == list(range(8))
    for tid in range(8):
        assert ra.read_emitted(tid) == rb.read_emitted(tid)
    ra.close()
    rb.close()
    snap = obs_metrics.registry().snapshot()
    assert snap["res_ckpt_compactions"] >= 1


def test_v1_stores_unaffected_by_v2_code(tmp_path):
    """segment_targets=0 (the kC default) writes a byte-for-byte v1
    manifest: per-target records carrying names, no header mode flag."""
    d = str(tmp_path / "ck")
    with ckpt.CheckpointStore.create(d, "fp1",
                                     segment_targets=0) as store:
        store.commit(0, b"c0", b"ACGT")
    recs = [json.loads(x) for x in
            open(os.path.join(d, ckpt.MANIFEST_NAME),
                 "rb").read().splitlines()]
    assert "manifest" not in recs[0]
    assert recs[1]["name"] == "c0"
    res = ckpt.CheckpointStore.resume(d, "fp1")
    assert res.segment_targets == 0
    assert res.read_emitted(0) == b">c0\nACGT\n"
    res.close()


# ------------------------------------------- degradation + CLI integration


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.04:
            continue
        out.append(int(BASES[rng.integers(0, 4)]) if r < 0.08 else int(b))
    return bytes(out)


def _build_windows(n, seed=0, coverage=5, wlen=80):
    from racon_tpu.models.window import Window, WindowType
    rng = np.random.default_rng(seed)
    ws = []
    for i in range(n):
        truth = BASES[rng.integers(0, 4, wlen)]
        backbone = _mutate(rng, truth)
        qual = bytes(rng.integers(43, 63, len(backbone), dtype=np.uint8))
        w = Window(i, i % 3, WindowType.TGS, backbone, qual)
        for _ in range(coverage):
            lay = _mutate(rng, truth)
            lq = bytes(rng.integers(43, 63, len(lay), dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        ws.append(w)
    return ws


def test_degradation_to_host_is_bit_identical():
    """Retry exhaustion at a transfer site must not change output: the
    chunk's windows reroute to the host path, which is bit-identical to
    the device path by design."""
    from racon_tpu.ops.poa import PoaEngine

    clean = _build_windows(8, seed=5)
    PoaEngine(backend="jax", log=io.StringIO()).consensus_windows(clean)

    retry.configure(retry.RetryPolicy(attempts=2, base=0.0, jitter=0.0))
    faults.configure("h2d/chunk:p=1.0")     # every upload attempt fails
    degraded = _build_windows(8, seed=5)
    log = io.StringIO()
    PoaEngine(backend="jax", log=log).consensus_windows(degraded)

    assert [w.consensus for w in degraded] == \
        [w.consensus for w in clean]
    assert "host path" in log.getvalue()
    snap = obs_metrics.registry().snapshot()
    assert snap["res_retry_exhausted"] >= 1
    assert snap["res_degraded_windows"] >= 1


def test_verbose_timing_path_h2d_retry_envelope(monkeypatch):
    """Pin the choke-point fix: the RACON_TPU_TIMING=1 per-round path
    shipped its arrays through a bare jax.device_put with no
    fault/retry/deadline envelope, so a transfer fault there bypassed
    the whole resilience layer. Now the upload retries like the packed
    path: a one-shot h2d/chunk fault is absorbed, output unchanged."""
    from racon_tpu.ops.poa import PoaEngine

    clean = _build_windows(6, seed=7)
    PoaEngine(backend="jax", log=io.StringIO()).consensus_windows(clean)
    obs_metrics.reset()

    monkeypatch.setenv("RACON_TPU_TIMING", "1")
    retry.configure(retry.RetryPolicy(attempts=3, base=0.0, jitter=0.0))
    faults.configure("h2d/chunk:0")
    timed = _build_windows(6, seed=7)
    with contextlib.redirect_stderr(io.StringIO()):
        PoaEngine(backend="jax",
                  log=io.StringIO()).consensus_windows(timed)

    assert [w.consensus for w in timed] == \
        [w.consensus for w in clean]
    snap = obs_metrics.registry().snapshot()
    assert snap["res_fault_injected_total"] >= 1
    assert snap["res_retry_total"] >= 1
    assert "res_retry_exhausted" not in snap


def _write_inputs(d, n_contigs=2, n_reads=6, clen=300):
    rng = np.random.default_rng(11)
    drafts, reads, paf = [], [], []
    for ci in range(n_contigs):
        truth = BASES[rng.integers(0, 4, clen)]
        draft = _mutate(rng, truth)
        drafts.append(b">c%d\n%s\n" % (ci, draft))
        for i in range(n_reads):
            r = _mutate(rng, truth)
            name = f"c{ci}r{i}"
            reads.append(b">" + name.encode() + b"\n" + r + b"\n")
            paf.append(f"{name}\t{len(r)}\t0\t{len(r)}\t+\tc{ci}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    (d / "draft.fasta").write_bytes(b"".join(drafts))
    (d / "reads.fasta").write_bytes(b"".join(reads))
    (d / "ovl.paf").write_text("\n".join(paf) + "\n")


def _run_cli(d, *extra):
    from racon_tpu import cli

    class _Capture(io.StringIO):
        pass

    stdout = _Capture()
    stdout.buffer = io.BytesIO()
    err = io.StringIO()
    with contextlib.redirect_stdout(stdout), \
            contextlib.redirect_stderr(err):
        rc = cli.main(["--backend", "jax", *extra,
                       str(d / "reads.fasta"), str(d / "ovl.paf"),
                       str(d / "draft.fasta")])
    return rc, stdout.buffer.getvalue(), err.getvalue()


def test_cli_resume_byte_identity(tmp_path):
    """The resume contract on the CLI surface: a completed checkpointed
    run re-emits byte-identically from the shard; a truncated manifest
    (simulated kill) resumes and still matches; a changed config
    refuses to resume."""
    _write_inputs(tmp_path)
    ck = str(tmp_path / "ck")

    rc, base, _ = _run_cli(tmp_path)
    assert rc == 0 and base.count(b">") == 2

    rc, fresh, _ = _run_cli(tmp_path, "--checkpoint-dir", ck)
    assert rc == 0 and fresh == base

    rc, resumed, err = _run_cli(tmp_path, "--checkpoint-dir", ck,
                                "--resume")
    assert rc == 0 and resumed == base
    assert "resuming: 2 contig(s)" in err

    # Kill simulation: drop the last manifest record; its contig must
    # recompute on resume with identical bytes.
    man = os.path.join(ck, ckpt.MANIFEST_NAME)
    lines = open(man, "rb").read().splitlines(keepends=True)
    open(man, "wb").write(b"".join(lines[:-1]))
    rc, partial, _ = _run_cli(tmp_path, "--checkpoint-dir", ck,
                              "--resume")
    assert rc == 0 and partial == base

    rc, _, err = _run_cli(tmp_path, "--checkpoint-dir", ck, "--resume",
                          "--match", "6")
    assert rc == 1 and "refusing to resume" in err


def test_cli_sigterm_mid_commit_resumes_byte_identical(tmp_path):
    """SIGTERM delivered in the mid-commit window (between the shard
    append and the manifest append, via the ckpt/manifest term action):
    the run exits 143, the half-committed contig's shard bytes are
    orphaned, and --resume still reproduces the serial bytes exactly."""
    _write_inputs(tmp_path)
    ck = str(tmp_path / "ck")
    rc, base, _ = _run_cli(tmp_path)
    assert rc == 0

    faults.configure("ckpt/manifest:1!term")
    rc, _, err = _run_cli(tmp_path, "--checkpoint-dir", ck)
    assert rc == 143, err
    assert "interrupted (signal 15); 1 contig(s) committed" in err
    # The second contig's shard bytes landed without a manifest record.
    shard_size = os.path.getsize(os.path.join(ck, ckpt.SHARD_NAME))
    man = open(os.path.join(ck, ckpt.MANIFEST_NAME), "rb").read()
    recs = [json.loads(x) for x in man.splitlines()]
    committed = [r for r in recs if r.get("ev") == "contig"]
    assert len(committed) == 1
    end = committed[0]["offset"] + committed[0]["length"]
    assert shard_size > end, "expected orphaned mid-commit shard bytes"

    faults.configure(None)
    rc, out, _ = _run_cli(tmp_path, "--checkpoint-dir", ck, "--resume")
    assert rc == 0 and out == base


def test_cli_resume_requires_checkpoint_dir(tmp_path):
    _write_inputs(tmp_path)
    rc, _, err = _run_cli(tmp_path, "--resume")
    assert rc == 1 and "--resume requires --checkpoint-dir" in err


@pytest.mark.ava
def test_ava_golden_resume_byte_identity(tmp_path):
    """Resume byte-identity on the reference acceptance inputs (the ava
    golden config tests/test_polisher.py gates on): full run vs
    checkpointed run vs resumed run, all byte-identical."""
    d = "/root/reference/test/data"
    if not os.path.isdir(d):
        pytest.skip("reference dataset not available")
    from racon_tpu import cli

    def run(*extra):
        stdout = io.StringIO()
        stdout.buffer = io.BytesIO()
        with contextlib.redirect_stdout(stdout), \
                contextlib.redirect_stderr(io.StringIO()):
            rc = cli.main([
                "--backend", "jax", *extra,
                os.path.join(d, "sample_reads.fastq.gz"),
                os.path.join(d, "sample_overlaps.paf.gz"),
                os.path.join(d, "sample_layout.fasta.gz")])
        assert rc == 0
        return stdout.buffer.getvalue()

    ck = str(tmp_path / "ck")
    base = run()
    assert run("--checkpoint-dir", ck) == base
    assert run("--checkpoint-dir", ck, "--resume") == base
