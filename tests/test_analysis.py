"""Contract-linter tests (racon_tpu/analysis/, docs/ANALYSIS.md).

Two proofs per rule, both required by the meta-test at the bottom:

- ``test_<rule>_clean``: the rule finds nothing on the real repo — the
  contracts actually hold, so ci.sh can gate on an empty baseline;
- ``test_<rule>_fires``: the rule catches its seeded violation in
  tests/fixtures/analysis/ (per-file directions) or against a
  synthetic registry (registry-direction checks) — the rule is not
  vacuously green.

Plus engine-level behavior: pragma suppression, baseline partition,
byte-stable reports, and the scripts/lint.py --ci exit code.
"""

import json
import os
import subprocess
import sys

import pytest

from racon_tpu.analysis import (ALL_RULES, Context, Finding,
                                load_baseline, render_json, render_text,
                                run_rules, split_findings, summary_line)
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.utils.envspec import EnvSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")


def _rule(name):
    return next(r for r in ALL_RULES if r.name == name)


def _fixture_ctx(*names, **overrides):
    files = [os.path.join(FIXTURES, n) for n in names]
    for f in files:
        assert os.path.exists(f), f
    return Context(REPO, files=files, full=False, **overrides)


def _ids(findings):
    return {f.rule for f in findings}


@pytest.fixture(scope="module")
def repo_findings():
    """One full-repo run shared by every clean-pass test."""
    return run_rules(ALL_RULES, Context(REPO))


def _clean(repo_findings, rule_name):
    rule = _rule(rule_name)
    hits = [f for f in repo_findings if f.rule in rule.ids]
    assert hits == [], "\n" + render_text(hits)


# ------------------------------------------------------------ env-contract


def test_env_contract_clean(repo_findings):
    _clean(repo_findings, "env-contract")


def test_env_contract_fires():
    found = list(_rule("env-contract").check(
        _fixture_ctx("env_violation.py")))
    assert {"ENV001", "ENV002"} <= _ids(found)

    # Registry directions: dead declaration + missing docs row ...
    ghost = EnvSpec("RACON_TPU_ZZ_GHOST", "", "flag", "ZZ.md", "ghost")
    ctx = Context(REPO, files=[], full=True,
                  env_registry={ghost.name: ghost}, docs_override={})
    assert {"ENV003", "ENV004"} <= _ids(
        _rule("env-contract").check(ctx))

    # ... and a documented name nobody declared.
    ctx = Context(REPO, files=[], full=True, env_registry={},
                  docs_override={"ZZ.md": "set RACON_TPU_ZZ_GHOST=1"})
    assert "ENV005" in _ids(_rule("env-contract").check(ctx))


# -------------------------------------------------------------- fault-site


def test_fault_site_clean(repo_findings):
    _clean(repo_findings, "fault-site")


def test_fault_site_fires():
    found = list(_rule("fault-site").check(
        _fixture_ctx("fault_violation.py")))
    assert "FLT001" in _ids(found)

    # Coverage direction: a declared site no test exercises. The name
    # is concatenated so THIS file doesn't satisfy the textual search.
    never = "zz/" + "never"
    ctx = Context(REPO, files=[], full=True, fault_sites=(never,),
                  fault_prefixes=())
    assert "FLT002" in _ids(_rule("fault-site").check(ctx))


# -------------------------------------------------------- metrics-contract


def test_metrics_contract_clean(repo_findings):
    _clean(repo_findings, "metrics-contract")


def test_metrics_contract_fires():
    found = list(_rule("metrics-contract").check(
        _fixture_ctx("metrics_violation.py")))
    assert "MET001" in _ids(found)

    # Registry directions: dead spec, undocumented spec, and a declared
    # merge kind that merge_kind() contradicts — one synthetic row
    # trips all three.
    ctx = Context(REPO, files=[], full=True,
                  metric_specs=(("zz_ghost_total", obs_metrics.MERGE_MAX,
                                 "zz_ghost_doc"),),
                  docs_override={})
    ids = _ids(_rule("metrics-contract").check(ctx))
    assert {"MET002", "MET003", "MET004"} <= ids


# ------------------------------------------------------------- span-schema


def test_span_schema_clean(repo_findings):
    _clean(repo_findings, "span-schema")


def test_span_schema_fires():
    found = list(_rule("span-schema").check(
        _fixture_ctx("span_violation.py")))
    assert {"SPAN001", "SPAN002"} <= _ids(found)

    # Validator direction: a schema kind nobody emits.
    ctx = Context(REPO, files=[], full=True,
                  span_required={"zz_ghost": ("a",)}, span_attr_free=())
    assert "SPAN003" in _ids(_rule("span-schema").check(ctx))


# ------------------------------------------------------------ atomic-write


def test_atomic_write_clean(repo_findings):
    _clean(repo_findings, "atomic-write")


def test_atomic_write_fires():
    found = list(_rule("atomic-write").check(
        _fixture_ctx("atomic_violation.py")))
    assert _ids(found) == {"ATM001"}


def test_atomic_write_pragma_suppresses(tmp_path):
    p = tmp_path / "pragma_case.py"
    p.write_text("def save(path, data):\n"
                 "    # lint: atomic-ok (test scratch file)\n"
                 "    with open(path, 'w') as fh:\n"
                 "        fh.write(data)\n")
    ctx = Context(REPO, files=[str(p)], full=False)
    assert list(_rule("atomic-write").check(ctx)) == []


# --------------------------------------------------------- lock-discipline


def test_lock_discipline_clean(repo_findings):
    _clean(repo_findings, "lock-discipline")


def test_lock_discipline_fires():
    found = list(_rule("lock-discipline").check(
        _fixture_ctx("lock_violation.py")))
    assert _ids(found) == {"LCK001"}
    # Both unguarded mutations, neither locked one.
    assert len(found) == 2
    assert all("Counter" in f.message for f in found)


# ------------------------------------------------------------- choke-point


def test_choke_point_clean(repo_findings):
    _clean(repo_findings, "choke-point")


def test_choke_point_fires():
    found = list(_rule("choke-point").check(
        _fixture_ctx("chokepoint_violation.py")))
    assert _ids(found) == {"CHK001"}


# ------------------------------------------------------------- determinism


def test_determinism_clean(repo_findings):
    _clean(repo_findings, "determinism")


def test_determinism_fires():
    found = list(_rule("determinism").check(
        _fixture_ctx("determinism_violation.py")))
    assert _ids(found) == {"DET001"}
    assert len(found) == 2  # time.time AND random.random


# --------------------------------------------------------------- histogram


def test_histogram_clean(repo_findings):
    _clean(repo_findings, "histogram")


def test_histogram_fires():
    found = list(_rule("histogram").check(
        _fixture_ctx("histogram_violation.py")))
    assert _ids(found) == {"HIS001"}

    # Registry directions: one injected family that nothing records,
    # with a 'hist' spec row but an empty corpus — the dead-producer
    # and missing-exporter directions both trip.
    ctx = Context(REPO, files=[], full=True,
                  hist_buckets={"zz_ghost_latency_s": (0.1, 1.0)},
                  metric_specs=(("zz_ghost_latency_s", "hist",
                                 "zz_ghost_doc"),))
    found = list(_rule("histogram").check(ctx))
    assert _ids(found) == {"HIS001"}
    msgs = "\n".join(f.message for f in found)
    assert "recorded nowhere" in msgs
    assert "no OpenMetrics histogram rendering" in msgs

    # A 'hist' spec row with no bounds behind it.
    ctx = Context(REPO, files=[], full=True, hist_buckets={},
                  metric_specs=(("zz_ghost_latency_s", "hist",
                                 "zz_ghost_doc"),))
    found = list(_rule("histogram").check(ctx))
    assert any("no bounds" in f.message for f in found)


def test_histogram_pragma_suppresses(tmp_path):
    p = tmp_path / "pragma_case.py"
    p.write_text("from racon_tpu.obs.metrics import record_hist\n"
                 "def observe():\n"
                 "    # lint: hist-ok (scratch family)\n"
                 "    record_hist('zz_scratch_s', 0.1)\n")
    ctx = Context(REPO, files=[str(p)], full=False)
    assert list(_rule("histogram").check(ctx)) == []


# ------------------------------------------------------- cache surface


def test_cache_surface_rules_fire():
    """The result-cache contract extensions (PR 16) are not vacuous:
    one seeded fixture trips each registry the cache surface joined —
    fault sites, metric specs, the ``cache`` span kind's attr schema,
    and the ATM001 scope over racon_tpu/cache/."""
    ctx = _fixture_ctx("cache_violation.py")
    assert "FLT001" in _ids(_rule("fault-site").check(ctx))
    assert "MET001" in _ids(_rule("metrics-contract").check(ctx))
    assert "SPAN002" in _ids(_rule("span-schema").check(ctx))
    assert "ATM001" in _ids(_rule("atomic-write").check(ctx))


def test_cache_registries_registered():
    """The registries themselves carry the cache rows: sites, metric
    specs (with the MERGE_LAST hit-ratio gauge), and the span kind."""
    from racon_tpu.resilience.faults import SITES
    assert "cache/load" in SITES and "cache/store" in SITES
    by_pattern = {p: k for p, k, _ in obs_metrics.METRIC_SPECS}
    assert by_pattern["cache_hits_total"] == obs_metrics.MERGE_SUM
    assert by_pattern["cache_hit_ratio"] == obs_metrics.MERGE_LAST
    assert obs_metrics.merge_kind("cache_hit_ratio") == \
        obs_metrics.MERGE_LAST
    assert obs_metrics.merge_kind("cache_verify_fail_total") == \
        obs_metrics.MERGE_SUM
    sys.path.insert(0, REPO)
    from scripts.obs_report import KIND_REQUIRED_ATTRS
    assert KIND_REQUIRED_ATTRS["cache"] == ("tier", "outcome")


# ------------------------------------------------------- engine mechanics


def test_reports_byte_stable():
    ctx_a, ctx_b = Context(REPO), Context(REPO)
    a = run_rules(ALL_RULES, ctx_a)
    b = run_rules(ALL_RULES, ctx_b)
    assert render_text(a) == render_text(b)
    assert render_json(a) == render_json(b)


def test_baseline_partition_and_fingerprint(tmp_path):
    f1 = Finding("ATM001", "error", "a.py", 3, "bare open")
    f2 = Finding("ATM001", "error", "b.py", 9, "bare open")
    base = tmp_path / "base.json"
    base.write_text(json.dumps([f1.fingerprint]))
    active, suppressed = split_findings([f1, f2],
                                        load_baseline(str(base)))
    assert active == [f2] and suppressed == [f1]
    # Line drift must not evict a finding from its baseline.
    drifted = Finding("ATM001", "error", "a.py", 33, "bare open")
    assert drifted.fingerprint == f1.fingerprint
    # Missing baseline file = empty baseline, not an error.
    assert load_baseline(str(tmp_path / "missing.json")) == []


def test_summary_line_format():
    f = Finding("ENV001", "error", "x.py", 1, "m")
    line = summary_line([f], [f, f], n_rules=8, n_files=101)
    assert line == ("lint_findings_total=3 active=1 baselined=2 "
                    "rules=8 files=101")


def test_lint_cli_ci_gate_passes():
    """The shipped baseline is empty and the repo lints clean, so the
    exact command ci.sh runs must exit 0 and print the summary."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--ci"], capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "lint_findings_total=" in proc.stdout
    assert "active=0" in proc.stdout


def test_lint_cli_ci_gate_fails_on_findings(tmp_path):
    """--ci exits 1 when a non-baselined finding exists: point the
    linter at a scratch repo containing one seeded violation."""
    scratch = tmp_path / "repo"
    (scratch / "racon_tpu").mkdir(parents=True)
    (scratch / "scripts").mkdir()
    src = open(os.path.join(FIXTURES, "determinism_violation.py")).read()
    (scratch / "racon_tpu" / "fingerprint.py").write_text(src)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--ci", "--root", str(scratch),
         "--baseline", str(tmp_path / "empty.json")],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 1
    assert "DET001" in proc.stdout


# ---------------------------------------------------------------- meta


def test_every_rule_has_clean_and_fire_tests():
    """The acceptance bar: no rule ships without both a clean-on-repo
    proof and a firing-on-fixture proof in this module."""
    names = set(globals())
    missing = []
    for rule in ALL_RULES:
        slug = rule.name.replace("-", "_")
        for suffix in ("clean", "fires"):
            fn = f"test_{slug}_{suffix}"
            if fn not in names:
                missing.append(fn)
    assert missing == [], missing


def test_rule_ids_unique_and_catalogued():
    seen = {}
    for rule in ALL_RULES:
        assert rule.ids, rule.name
        for rid in rule.ids:
            assert rid not in seen, f"{rid} in {rule.name} and {seen[rid]}"
            seen[rid] = rule.name
    assert len(ALL_RULES) >= 8
    # Every rule id is documented in the catalog.
    catalog = open(os.path.join(REPO, "docs", "ANALYSIS.md")).read()
    for rid in seen:
        assert rid in catalog, f"{rid} missing from docs/ANALYSIS.md"
