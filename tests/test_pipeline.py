"""Streaming execution pipeline tests (racon_tpu/pipeline/).

Covers the three layers separately and end to end: bounded queues
(backpressure, close/abort semantics), the stage driver (ordering,
exception propagation without hangs, clean teardown on an abandoned
consumer), the slice tracker (in-order release under out-of-order
retirement), the gating truth table, and the differential contract —
``stream_consensus`` / ``polish_stream`` must be bit-identical to the
serial path (ISSUE: RACON_TPU_PIPELINE=0 and =1 produce identical
polished FASTA; the golden-config differential runs under the ``ava``
marker like the scheduler's).
"""

import threading
import time

import numpy as np
import pytest

from racon_tpu.pipeline import (BoundedQueue, Pipeline, PipelineAborted,
                                QueueClosed, StageError, configure,
                                pipeline_depth, pipeline_enabled)
from racon_tpu.pipeline.streaming import SliceTracker, stream_consensus

BASES = np.frombuffer(b"ACGT", np.uint8)


@pytest.fixture(autouse=True)
def _reset_cli_depth():
    """configure() installs process-global CLI state; undo per test."""
    yield
    configure(None)


# ------------------------------------------------------------- queues


def test_queue_fifo_and_close_drain():
    q = BoundedQueue("q", 4)
    for i in range(3):
        q.put(i)
    q.close()
    assert [q.get(), q.get(), q.get()] == [0, 1, 2]
    with pytest.raises(QueueClosed):
        q.get()
    with pytest.raises(RuntimeError, match="closed"):
        q.put(99)


def test_queue_backpressure_blocks_producer():
    """A full queue blocks the producer until the consumer drains —
    the mechanism that bounds in-flight HBM buffers."""
    q = BoundedQueue("q", 2)
    done = []

    def produce():
        for i in range(6):
            q.put(i)
        done.append(True)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    time.sleep(0.05)
    assert not done, "producer ran past the capacity bound"
    assert q.depth == 2
    got = [q.get() for _ in range(6)]
    t.join(timeout=5)
    assert done and got == list(range(6))
    m = q.metrics()
    assert m["peak"] == 2 and m["items"] == 6
    assert m["put_wait_s"] > 0


def test_queue_abort_unblocks_blocked_put_and_drops_items():
    q = BoundedQueue("q", 1)
    q.put(0)
    errs = []

    def blocked_put():
        try:
            q.put(1)
        except PipelineAborted:
            errs.append("put")

    t = threading.Thread(target=blocked_put, daemon=True)
    t.start()
    time.sleep(0.02)
    q.abort()
    t.join(timeout=5)
    assert errs == ["put"]
    with pytest.raises(PipelineAborted):
        q.get()            # abort drops queued items too


# ------------------------------------------------------------- stages


def test_pipeline_happy_path_preserves_order():
    pipe = Pipeline("t")
    qa = pipe.queue("a", 2)
    qb = pipe.queue("b", 2)
    pipe.source("src", lambda: iter(range(10)), qa)
    pipe.stage("sq", lambda x: x * x, qa, qb)
    with pipe:
        out = list(pipe.drain(qb))
    assert out == [i * i for i in range(10)]
    assert not pipe.alive


def test_stage_returning_none_consumes_item():
    side = []
    pipe = Pipeline("t")
    qa = pipe.queue("a", 2)
    qb = pipe.queue("b", 2)

    def route(x):
        if x % 2:
            side.append(x)
            return None
        return x

    pipe.source("src", lambda: iter(range(6)), qa)
    pipe.stage("route", route, qa, qb)
    with pipe:
        out = list(pipe.drain(qb))
    assert out == [0, 2, 4]
    assert side == [1, 3, 5]


def test_stage_exception_propagates_without_hang():
    """A mid-pipeline failure must abort every queue (unblocking the
    producer stuck on a full edge) and re-raise at the consumer with
    the original exception chained."""
    pipe = Pipeline("t")
    qa = pipe.queue("a", 1)
    qb = pipe.queue("b", 1)

    def boom(x):
        if x == 2:
            raise ValueError("stage blew up")
        return x

    pipe.source("src", lambda: iter(range(100)), qa)
    pipe.stage("boom", boom, qa, qb)
    t0 = time.perf_counter()
    with pipe:
        with pytest.raises(StageError, match="'boom' failed") as ei:
            list(pipe.drain(qb))
    assert isinstance(ei.value.__cause__, ValueError)
    assert not pipe.alive
    assert time.perf_counter() - t0 < 10, "teardown hung"


def test_abandoned_consumer_tears_down_cleanly():
    """Breaking out of drain() early (generator abandoned) must not
    leave the producer blocked forever on a full queue."""
    pipe = Pipeline("t")
    qa = pipe.queue("a", 1)
    pipe.source("src", lambda: iter(range(100)), qa)
    with pipe:
        for item in pipe.drain(qa):
            break                # consumer walks away mid-stream
    # __exit__ aborted the queues, unblocking the producer stuck on the
    # full edge, and joined it.
    assert not pipe.alive


# ------------------------------------------------------- slice tracker


def test_slice_tracker_releases_in_order():
    tr = SliceTracker()
    tr.register(0, 0, 8, 2)
    tr.register(1, 8, 16, 1)
    tr.register(2, 16, 20, 1)
    assert tr.retire(1) == []              # slice 0 still in flight
    assert tr.retire(0) == []              # 1 of 2 items
    assert tr.retire(0) == [(0, 0, 8), (1, 8, 16)]   # releases 0 AND 1
    assert tr.retire(2) == [(2, 16, 20)]
    assert tr.flush() == []


def test_slice_tracker_zero_item_slice_releases():
    tr = SliceTracker()
    tr.register(0, 0, 4, 0)                # all-trivial slice: no items
    tr.register(1, 4, 8, 1)
    assert tr.retire(1) == [(0, 0, 4), (1, 4, 8)]


def test_slice_tracker_lost_item_fails_loudly():
    tr = SliceTracker()
    tr.register(0, 0, 4, 2)
    tr.retire(0)
    with pytest.raises(RuntimeError, match="never completed"):
        tr.flush()
    tr2 = SliceTracker()
    tr2.register(0, 0, 4, 1)
    tr2.retire(0)
    with pytest.raises(RuntimeError, match="more items"):
        tr2.retire(0)


# -------------------------------------------------------------- gating


def test_gating_truth_table(monkeypatch):
    monkeypatch.delenv("RACON_TPU_PIPELINE", raising=False)
    configure(None)
    assert not pipeline_enabled()          # default: off
    monkeypatch.setenv("RACON_TPU_PIPELINE", "1")
    assert pipeline_enabled()              # env enables
    configure(0)
    assert not pipeline_enabled()          # CLI 0 disables
    configure(3)
    assert pipeline_enabled()
    monkeypatch.setenv("RACON_TPU_PIPELINE", "0")
    assert not pipeline_enabled()          # env 0 beats the CLI knob
    monkeypatch.setenv("RACON_TPU_PIPELINE", "false")
    assert not pipeline_enabled()


def test_gating_depth(monkeypatch):
    monkeypatch.delenv("RACON_TPU_PIPELINE_DEPTH", raising=False)
    configure(None)
    assert pipeline_depth() == 2           # DEFAULT_DEPTH
    configure(5)
    assert pipeline_depth() == 5
    configure(None)
    monkeypatch.setenv("RACON_TPU_PIPELINE_DEPTH", "7")
    assert pipeline_depth() == 7
    monkeypatch.setenv("RACON_TPU_PIPELINE_DEPTH", "bogus")
    with pytest.raises(ValueError, match="invalid"):
        pipeline_depth()
    with pytest.raises(ValueError, match="invalid pipeline depth"):
        configure(-1)


# ----------------------------------------------- streaming differential


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.04:
            continue                       # deletion
        out.append(int(BASES[rng.integers(0, 4)]) if r < 0.08 else int(b))
        if r > 0.96:
            out.append(int(BASES[rng.integers(0, 4)]))  # insertion
    return bytes(out)


def _build_windows(n, seed=0, coverage=5, wlen=80):
    """Synthetic polishing windows with trivial (no-layer) windows
    sprinkled in, so the stream exercises both the inline backbone path
    and device chunks. Same seed => bit-identical window set."""
    from racon_tpu.models.window import Window, WindowType
    rng = np.random.default_rng(seed)
    ws = []
    for i in range(n):
        truth = BASES[rng.integers(0, 4, wlen)]
        backbone = _mutate(rng, truth)
        qual = bytes(rng.integers(43, 63, len(backbone), dtype=np.uint8))
        w = Window(i, i % 7, WindowType.TGS, backbone, qual)
        cov = 0 if i % 9 == 8 else coverage
        for _ in range(cov):
            lay = _mutate(rng, truth)
            lq = bytes(rng.integers(43, 63, len(lay), dtype=np.uint8))
            w.add_layer(lay, lq, 0, len(backbone) - 1)
        ws.append(w)
    return ws


def test_stream_consensus_bit_identical_to_serial():
    """The tentpole contract: the streaming executor shares the serial
    engine's slice planning, so its consensi are bit-identical, and its
    yielded ranges are ascending, contiguous, and cover every window."""
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.ops.poa import PoaEngine

    serial = _build_windows(24, seed=42)
    PoaEngine(backend="jax").consensus_windows(serial)

    streamed = _build_windows(24, seed=42)
    obs_metrics.reset()
    ranges = list(stream_consensus(PoaEngine(backend="jax"), streamed,
                                   chunk=8, depth=2))
    assert [w.consensus for w in streamed] == \
        [w.consensus for w in serial]
    # Ordered streaming: contiguous ascending cover of range(n).
    flat = [i for s, e in ranges for i in range(s, e)]
    assert flat == list(range(24))
    # The run recorded stage/queue gauges and a wall clock.
    snap = obs_metrics.registry().snapshot()
    assert snap.get("pipe_runs") == 1
    for key in ("pipe_stage_build_items", "pipe_stage_pack_items",
                "pipe_stage_compute_busy_s", "pipe_queue_run_peak",
                "pipe_wall_s"):
        assert key in snap, key
    extras = obs_metrics.pipeline_extras()
    assert "pipe_overlap_efficiency" in extras


def test_stream_consensus_abandoned_generator_closes_cleanly():
    from racon_tpu.ops.poa import PoaEngine
    ws = _build_windows(24, seed=7)
    gen = stream_consensus(PoaEngine(backend="jax"), ws, chunk=4, depth=1)
    next(gen)
    t0 = time.perf_counter()
    gen.close()                  # must abort queues + join stage threads
    assert time.perf_counter() - t0 < 10, "generator close hung"


def test_stream_consensus_empty_input():
    from racon_tpu.ops.poa import PoaEngine
    assert list(stream_consensus(PoaEngine(backend="jax"), [])) == []


def _write_two_contig_inputs(d, n_reads=8, clen=400):
    """Tiny two-contig polishing workload (obs_smoke.py's generator,
    doubled) — enough windows per contig to exercise the streaming
    assembler's multi-window joins and ordered emission."""
    rng = np.random.default_rng(11)
    drafts, reads, paf = [], [], []
    for ci in (1, 2):
        truth = BASES[rng.integers(0, 4, clen)]
        draft = _mutate(rng, truth)
        drafts.append(b">c%d\n%s\n" % (ci, draft))
        for i in range(n_reads):
            r = _mutate(rng, truth)
            name = f"c{ci}r{i}"
            reads.append(b">" + name.encode() + b"\n" + r + b"\n")
            paf.append(f"{name}\t{len(r)}\t0\t{len(r)}\t+\tc{ci}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    (d / "draft.fasta").write_bytes(b"".join(drafts))
    (d / "reads.fasta").write_bytes(b"".join(reads))
    (d / "ovl.paf").write_text("\n".join(paf) + "\n")
    return d


def test_polish_stream_matches_polish(tmp_path, monkeypatch):
    """polish_stream (the pipeline path polish() delegates to under
    RACON_TPU_PIPELINE=1) emits the same records, in the same order,
    with the same names/tags, as the serial polish()."""
    from racon_tpu.models.polisher import PolisherType, create_polisher
    monkeypatch.delenv("RACON_TPU_PIPELINE", raising=False)
    _write_two_contig_inputs(tmp_path)

    def make():
        p = create_polisher(
            str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.paf"),
            str(tmp_path / "draft.fasta"), PolisherType.kC,
            200, 10.0, 0.3, 5, -4, -8, backend="jax")
        p.initialize()
        return p

    serial = make().polish(True)
    streamed = list(make().polish_stream(True))
    assert [s.name for s in streamed] == [s.name for s in serial]
    assert [s.data for s in streamed] == [s.data for s in serial]
    assert len(serial) == 2


def test_polish_delegates_to_stream_when_enabled(tmp_path, monkeypatch):
    from racon_tpu.models.polisher import PolisherType, create_polisher
    _write_two_contig_inputs(tmp_path)

    def run():
        p = create_polisher(
            str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.paf"),
            str(tmp_path / "draft.fasta"), PolisherType.kC,
            200, 10.0, 0.3, 5, -4, -8, backend="jax")
        p.initialize()
        return p.polish(True)

    monkeypatch.setenv("RACON_TPU_PIPELINE", "0")
    off = run()
    monkeypatch.setenv("RACON_TPU_PIPELINE", "1")
    on = run()
    assert [s.name for s in on] == [s.name for s in off]
    assert [s.data for s in on] == [s.data for s in off]


# Reference acceptance configs (tests/test_polisher.py::_GOLDEN_CONFIGS).
_GOLDEN_CONFIGS = [
    ("sample_reads.fastq.gz", "sample_overlaps.sam.gz", 500, (5, -4, -8)),
    ("sample_reads.fastq.gz", "sample_overlaps.paf.gz", 500, (5, -4, -8)),
    ("sample_reads.fasta.gz", "sample_overlaps.paf.gz", 500, (5, -4, -8)),
    ("sample_reads.fasta.gz", "sample_overlaps.sam.gz", 500, (5, -4, -8)),
    ("sample_reads.fastq.gz", "sample_overlaps.paf.gz", 1000, (5, -4, -8)),
    ("sample_reads.fastq.gz", "sample_overlaps.paf.gz", 500, (1, -1, -1)),
]
_GOLDEN_IDS = ["sam_fastq", "paf_fastq", "paf_fasta", "sam_fasta",
               "window1000", "edit_scores"]


@pytest.mark.ava
@pytest.mark.parametrize("reads,overlaps,window,scores",
                         _GOLDEN_CONFIGS, ids=_GOLDEN_IDS)
def test_pipeline_differential_golden(ref_data, monkeypatch, reads,
                                      overlaps, window, scores):
    """RACON_TPU_PIPELINE=0 and =1 must produce bit-identical polished
    FASTA on every reference acceptance config — the pipeline reuses
    the serial engine's slice planning, so any divergence is an
    executor bug, not noise. ci.sh runs the sam_fastq case in the
    default tier; --full runs all six."""
    from racon_tpu.models.polisher import PolisherType, create_polisher

    def run():
        p = create_polisher(
            ref_data(reads), ref_data(overlaps),
            ref_data("sample_layout.fasta.gz"), PolisherType.kC,
            window, 10.0, 0.3, *scores, backend="jax")
        p.initialize()
        return p.polish(True)

    monkeypatch.setenv("RACON_TPU_PIPELINE", "0")
    serial = run()
    monkeypatch.setenv("RACON_TPU_PIPELINE", "1")
    piped = run()
    assert [s.data for s in piped] == [s.data for s in serial]
    assert [s.name for s in piped] == [s.name for s in serial]


@pytest.mark.ava
@pytest.mark.parametrize("reads,overlaps,window,scores",
                         _GOLDEN_CONFIGS, ids=_GOLDEN_IDS)
def test_walk_async_differential_golden(ref_data, monkeypatch, reads,
                                        overlaps, window, scores):
    """RACON_TPU_WALK_ASYNC=0 and =1 must produce bit-identical
    polished FASTA on every reference acceptance config, on the path
    where the decoupled walk actually runs (pipeline on, fixed rounds —
    the scheduler keeps fused dispatches, see sched/scheduler.py). The
    walk dispatch composes the same traced bodies the fused program
    compiles, so any divergence is a handoff bug, not noise."""
    from racon_tpu.models.polisher import PolisherType, create_polisher

    def run():
        p = create_polisher(
            ref_data(reads), ref_data(overlaps),
            ref_data("sample_layout.fasta.gz"), PolisherType.kC,
            window, 10.0, 0.3, *scores, backend="jax")
        p.initialize()
        return p.polish(True)

    monkeypatch.setenv("RACON_TPU_PIPELINE", "1")
    monkeypatch.setenv("RACON_TPU_SCHED", "0")
    monkeypatch.setenv("RACON_TPU_WALK_ASYNC", "0")
    fused = run()
    monkeypatch.setenv("RACON_TPU_WALK_ASYNC", "1")
    decoupled = run()
    assert [s.data for s in decoupled] == [s.data for s in fused]
    assert [s.name for s in decoupled] == [s.name for s in fused]
