"""Overlap transmute + breaking-points tests.

The vectorized CIGAR walk is differential-tested against a literal
base-by-base walk implementing the reference semantics
(src/overlap.cpp:216-281) on random CIGARs.
"""

import random

import numpy as np
import pytest

from racon_tpu.models.overlap import (
    Overlap, PolisherError, breaking_points_from_cigar, decompose_cigar,
)
from racon_tpu.models.sequence import Sequence


def slow_breaking_points(cigar, t_begin, t_end, q_start, window_length):
    """Base-by-base walk, straight from the reference's loop."""
    window_ends = []
    i = 0
    while i < t_end:
        if i > t_begin:
            window_ends.append(i - 1)
        i += window_length
    window_ends.append(t_end - 1)

    lens, ops = decompose_cigar(cigar)
    w = 0
    found_first = False
    first = last = (0, 0)
    q_ptr = q_start - 1
    t_ptr = t_begin - 1
    out = []
    for n, op in zip(lens, ops):
        op = chr(op)
        if op in "M=X":
            for _ in range(n):
                q_ptr += 1
                t_ptr += 1
                if not found_first:
                    found_first = True
                    first = (t_ptr, q_ptr)
                last = (t_ptr + 1, q_ptr + 1)
                if w < len(window_ends) and t_ptr == window_ends[w]:
                    if found_first:
                        out.append(first)
                        out.append(last)
                    found_first = False
                    w += 1
        elif op == "I":
            q_ptr += n
        elif op in "DN":
            for _ in range(n):
                t_ptr += 1
                if w < len(window_ends) and t_ptr == window_ends[w]:
                    if found_first:
                        out.append(first)
                        out.append(last)
                    found_first = False
                    w += 1
    return np.asarray(out, dtype=np.int64).reshape(-1, 4)


def random_cigar(rng, t_span):
    """Random CIGAR whose target advance equals t_span."""
    parts = []
    t_left = t_span
    while t_left > 0:
        op = rng.choice("MMMMMIDD")
        n = rng.randint(1, min(37, t_left if op != "I" else 37))
        if op == "I":
            parts.append(f"{n}I")
        else:
            n = min(n, t_left)
            parts.append(f"{n}{op}")
            t_left -= n
    return "".join(parts).encode()


@pytest.mark.parametrize("seed", range(8))
def test_breaking_points_match_slow_walk(seed):
    rng = random.Random(seed)
    t_begin = rng.randint(0, 900)
    t_span = rng.randint(1, 2500)
    t_end = t_begin + t_span
    q_start = rng.randint(0, 100)
    W = rng.choice([100, 500, 333])
    cigar = random_cigar(rng, t_span)
    fast = breaking_points_from_cigar(cigar, t_begin, t_end, q_start, W)
    slow = slow_breaking_points(cigar, t_begin, t_end, q_start, W)
    np.testing.assert_array_equal(fast, slow)


def test_breaking_points_simple():
    # 10M over a window boundary at W=5, t_begin=2: windows [2..4], [5..9]
    bp = breaking_points_from_cigar(b"10M", 2, 12, 0, 5)
    # windows touched: t in [2,4] (k=0), [5,9] (k=1), [10,11] (k=2)
    assert bp.shape == (3, 4)
    np.testing.assert_array_equal(bp[0], [2, 0, 5, 3])
    np.testing.assert_array_equal(bp[1], [5, 3, 10, 8])
    np.testing.assert_array_equal(bp[2], [10, 8, 12, 10])


def test_breaking_points_deletion_only_window():
    # first window covered only by deletions -> no pair for it
    bp = breaking_points_from_cigar(b"5D5M", 0, 10, 0, 5)
    assert bp.shape == (1, 4)
    np.testing.assert_array_equal(bp[0], [5, 0, 10, 5])


def _seqs():
    target = Sequence("ctg", b"ACGT" * 25)
    read = Sequence("r1", b"ACGT" * 10)
    return [target, read]


def test_transmute_by_name():
    seqs = _seqs()
    name_to_id = {"ctgt": 0, "r1q": 1}
    o = Overlap.from_paf("r1", 40, 0, 40, "+", "ctg", 100, 10, 50)
    o.transmute(seqs, name_to_id, {})
    assert o.is_transmuted and o.q_id == 1 and o.t_id == 0


def test_transmute_unknown_name_invalidates():
    o = Overlap.from_paf("zz", 40, 0, 40, "+", "ctg", 100, 10, 50)
    o.transmute(_seqs(), {"ctgt": 0}, {})
    assert not o.is_valid


def test_transmute_length_mismatch_fatal():
    o = Overlap.from_paf("r1", 39, 0, 39, "+", "ctg", 100, 10, 50)
    with pytest.raises(PolisherError, match="unequal lengths"):
        o.transmute(_seqs(), {"ctgt": 0, "r1q": 1}, {})


def test_transmute_by_id_mhap():
    seqs = _seqs()
    o = Overlap.from_mhap(2, 1, 0.1, 5, 0, 0, 40, 40, 0, 10, 50, 100)
    # q id 1 (0-based), t id 0
    o.transmute(seqs, {}, {1 << 1 | 0: 1, 0 << 1 | 1: 0})
    assert o.is_transmuted and o.q_id == 1 and o.t_id == 0


def test_sam_t_length_backfilled():
    seqs = _seqs()
    o = Overlap.from_sam("r1", 0, "ctg", 11, "40M")
    o.transmute(seqs, {"ctgt": 0, "r1q": 1}, {})
    assert o.t_length == len(seqs[0].data)


def test_alignment_operands_reverse_strand():
    target = Sequence("ctg", b"A" * 100)
    read = Sequence("r1", b"ACGTACGTAA")
    read.create_reverse_complement()
    o = Overlap.from_paf("r1", 10, 2, 8, "-", "ctg", 100, 10, 16)
    o.transmute([target, read], {"ctgt": 0, "r1q": 1}, {})
    q, t = o.alignment_operands([target, read])
    # reverse complement of ACGTACGTAA is TTACGTACGT; slice [10-8 : 10-2]
    assert q == b"TTACGTACGT"[2:8]
    assert t == b"A" * 6
