"""IO parser tests: synthetic round-trips plus reference-dataset counts.

Golden counts are derived from the reference's bundled lambda-phage dataset
(see SURVEY.md section 4: 236 reads / 1,674,628 bp, 181 read-to-contig PAF
records, 8016 all-vs-all PAF records, one 47,564 bp layout contig).
"""

import gzip

import pytest

from racon_tpu.io.parsers import (
    FastaParser, FastqParser, MhapParser, PafParser, SamParser,
    create_overlap_parser, create_sequence_parser, ParseError,
)


def test_fasta_roundtrip(tmp_path):
    p = tmp_path / "x.fasta"
    p.write_text(">s1 description here\nACGT\nacgt\n>s2\nTTTT\n")
    seqs = FastaParser(str(p)).parse_all()
    assert [s.name for s in seqs] == ["s1", "s2"]
    assert seqs[0].data == b"ACGTACGT"  # multi-line + uppercased
    assert seqs[1].data == b"TTTT"
    assert seqs[0].quality is None


def test_fasta_gzip(tmp_path):
    p = tmp_path / "x.fasta.gz"
    with gzip.open(p, "wt") as f:
        f.write(">a\nACGT\n")
    seqs = FastaParser(str(p)).parse_all()
    assert seqs[0].data == b"ACGT"


def test_fastq_quality_and_all_bang(tmp_path):
    p = tmp_path / "x.fastq"
    p.write_text("@r1\nACGT\n+\nII!I\n@r2\nACGT\n+\n!!!!\n")
    seqs = FastqParser(str(p)).parse_all()
    assert seqs[0].quality == b"II!I"
    # all-'!' quality is dropped (reference src/sequence.cpp:34-42)
    assert seqs[1].quality is None


def test_fastq_malformed_quality_rejected(tmp_path):
    # Quality bytes below '!' would decode to negative Phred weights;
    # the parser rejects them so host and device consensus paths can
    # assume non-negative weights by construction.
    p = tmp_path / "bad.fastq"
    p.write_bytes(b"@r1\nACGT\n+\nII I\n")  # 0x20 < '!'
    with pytest.raises(ParseError, match="malformed quality"):
        FastqParser(str(p)).parse_all()


def test_chunked_parse(tmp_path):
    p = tmp_path / "x.fasta"
    p.write_text("".join(f">s{i}\n{'ACGT' * 100}\n" for i in range(10)))
    parser = FastaParser(str(p))
    total = []
    more = True
    rounds = 0
    while more:
        recs, more = parser.parse(max_bytes=1000)
        total.extend(recs)
        rounds += 1
    assert len(total) == 10
    assert rounds > 1  # actually streamed


def test_paf_parse(tmp_path):
    p = tmp_path / "x.paf"
    p.write_text("q1\t100\t5\t95\t-\tt1\t200\t10\t110\t80\t90\t60\n")
    o = PafParser(str(p)).parse_all()[0]
    assert o.q_name == "q1" and o.t_name == "t1"
    assert o.strand is True
    assert (o.q_begin, o.q_end, o.q_length) == (5, 95, 100)
    assert (o.t_begin, o.t_end, o.t_length) == (10, 110, 200)
    assert o.length == 100  # max span
    assert abs(o.error - (1 - 90 / 100)) < 1e-9


def test_mhap_parse(tmp_path):
    p = tmp_path / "x.mhap"
    p.write_text("1 2 0.05 42 0 5 95 100 1 10 110 200\n")
    o = MhapParser(str(p)).parse_all()[0]
    assert o.q_id == 0 and o.t_id == 1  # 1-based -> 0-based
    assert o.strand is True  # 0 XOR 1


def test_sam_parse(tmp_path):
    p = tmp_path / "x.sam"
    p.write_text(
        "@HD\tVN:1.6\n"
        "r1\t0\tctg\t11\t60\t5S10M2I3D5M\t*\t0\t0\tAAAAAAAAAAAAAAAAAAAAAA\t*\n"
        "r2\t4\t*\t0\t0\t*\t*\t0\t0\tAAAA\t*\n")
    ovls = SamParser(str(p)).parse_all()
    o = ovls[0]
    assert o.t_begin == 10  # 1-based POS -> 0-based
    assert o.q_begin == 5  # leading clip
    assert o.q_end == 5 + 10 + 2 + 5
    assert o.q_length == 5 + 17
    assert o.t_end == 10 + 10 + 3 + 5
    assert ovls[1].is_valid is False  # unmapped flag 0x4


def test_sam_reverse_strand_flips_query_coords(tmp_path):
    p = tmp_path / "x.sam"
    p.write_text("r1\t16\tctg\t1\t60\t5S10M\t*\t0\t0\t*\t*\n")
    o = SamParser(str(p)).parse_all()[0]
    assert o.strand is True
    # forward coords were (5, 15) in a 15-long query
    assert (o.q_begin, o.q_end) == (0, 10)


def test_fastq_truncated_record_reports_offset(tmp_path):
    """EOF inside a FASTQ record must name the file AND the byte offset
    of the record that was cut, so a truncated download is diagnosable
    without bisecting the file by hand."""
    p = tmp_path / "trunc.fastq"
    good = b"@r1\nACGT\n+\nIIII\n"
    p.write_bytes(good + b"@r2\nACGT\n")  # record cut before '+'
    with pytest.raises(ParseError,
                       match=r"EOF inside the record starting.*"
                             r"at byte offset 16") as ei:
        FastqParser(str(p)).parse_all()
    assert ei.value.offset == len(good)


def test_fastq_malformed_quality_reports_offset(tmp_path):
    p = tmp_path / "bad.fastq"
    good = b"@r1\nACGT\n+\nIIII\n"
    p.write_bytes(good + b"@r2\nACGT\n+\nII I\n")
    with pytest.raises(ParseError, match="malformed quality") as ei:
        FastqParser(str(p)).parse_all()
    assert ei.value.offset == len(good)


def test_fasta_malformed_reports_offset(tmp_path):
    p = tmp_path / "bad.fasta"
    p.write_bytes(b"ACGT\n")  # data before any header
    with pytest.raises(ParseError, match="at byte offset 0") as ei:
        FastaParser(str(p)).parse_all()
    assert ei.value.offset == 0


def test_overlap_parsers_report_offset(tmp_path):
    good = "q1\t100\t5\t95\t-\tt1\t200\t10\t110\t80\t90\t60\n"
    p = tmp_path / "bad.paf"
    p.write_text(good + "short\tline\n")
    with pytest.raises(ParseError, match="malformed PAF") as ei:
        PafParser(str(p)).parse_all()
    assert ei.value.offset == len(good)
    m = tmp_path / "bad.mhap"
    m.write_text("1 2 0.05\n")
    with pytest.raises(ParseError, match="malformed MHAP") as ei:
        MhapParser(str(m)).parse_all()
    assert ei.value.offset == 0
    s = tmp_path / "bad.sam"
    s.write_text("@HD\tVN:1.6\nr1\tonly\tthree\n")
    with pytest.raises(ParseError, match="malformed SAM") as ei:
        SamParser(str(s)).parse_all()
    assert ei.value.offset == len("@HD\tVN:1.6\n")


def test_interleaved_chunked_parsers_stay_independent(tmp_path):
    """Two parsers chunk-reading concurrently (the streaming pipeline's
    parse stage interleaves sequences and overlaps) must not share or
    corrupt state: each record owns fresh immutable bytes."""
    a = tmp_path / "a.fasta"
    b = tmp_path / "b.fasta"
    a.write_text("".join(f">a{i}\n{'ACGT' * 50}\n" for i in range(8)))
    b.write_text("".join(f">b{i}\n{'TTAA' * 50}\n" for i in range(8)))
    pa, pb = FastaParser(str(a)), FastaParser(str(b))
    out_a, out_b = [], []
    more_a = more_b = True
    while more_a or more_b:
        if more_a:
            recs, more_a = pa.parse(max_bytes=300)
            out_a.extend(recs)
        if more_b:
            recs, more_b = pb.parse(max_bytes=300)
            out_b.extend(recs)
    assert [s.name for s in out_a] == [f"a{i}" for i in range(8)]
    assert [s.name for s in out_b] == [f"b{i}" for i in range(8)]
    assert all(s.data == b"ACGT" * 50 for s in out_a)
    assert all(s.data == b"TTAA" * 50 for s in out_b)


def test_extension_dispatch_errors(tmp_path):
    bad = tmp_path / "x.txt"
    bad.write_text("")
    with pytest.raises(ParseError, match="unsupported format"):
        create_sequence_parser(str(bad))
    with pytest.raises(ParseError, match="unsupported format"):
        create_overlap_parser(str(bad))


# -------------------- truncated streams / injected read failures -------------


def _truncated_gz(path, payload):
    blob = gzip.compress(payload)
    path.write_bytes(blob[:len(blob) // 2])  # cut the member mid-stream


def _big_payload(n_records=6000):
    # Larger than one _block_lines read (4 MB decompressed) so the
    # parser makes real progress before the stream breaks and the
    # reported offset proves the high-water tracking, not just 0.
    return b"".join(b">s%d\n%s\n" % (i, b"ACGT" * 400)
                    for i in range(n_records))


def test_truncated_gzip_reports_offset(tmp_path):
    """A gzip member cut mid-stream (interrupted download) must raise
    the parser's own typed error with the decompressed byte offset it
    reached — never silently yield the short record set."""
    p = tmp_path / "trunc.fasta.gz"
    payload = _big_payload()
    _truncated_gz(p, payload)
    parser = FastaParser(str(p))
    with pytest.raises(ParseError, match="corrupt or mislabelled") as ei:
        parser.parse_all()
    assert isinstance(ei.value.offset, int)
    assert 0 < ei.value.offset <= len(payload)
    # The parser is poisoned: a retried parse cannot masquerade as a
    # clean EOF on a prefix of the records.
    with pytest.raises(ParseError, match="previously failed"):
        parser.parse()


def test_injected_read_fault_is_typed_parse_error(tmp_path):
    """The io/read drill site: an injected stream failure converts the
    same way a real truncation does — typed, offset-bearing."""
    from racon_tpu.resilience import faults
    p = tmp_path / "x.fasta"
    good = b">s0\nACGT\n"
    p.write_bytes(good + b">s1\nTTTT\n")
    faults.configure("io/read:2")      # fail reading the 3rd line
    try:
        with pytest.raises(ParseError, match="read failure") as ei:
            FastaParser(str(p)).parse_all()
        assert ei.value.offset == len(good) + len(b">s1\n")
    finally:
        faults.configure(None)


def test_scan_index_truncated_gzip_reports_offset(tmp_path):
    from racon_tpu.io.parsers import scan_sequence_index
    payload = _big_payload()
    whole = tmp_path / "ok.fasta.gz"
    with gzip.open(whole, "wb") as f:
        f.write(payload)
    count, offsets = scan_sequence_index(str(whole))
    assert count == 6000 and len(offsets) == 6000

    p = tmp_path / "trunc.fasta.gz"
    _truncated_gz(p, payload)
    with pytest.raises(ParseError,
                       match="corrupt or truncated sequence") as ei:
        scan_sequence_index(str(p))
    assert isinstance(ei.value.offset, int)
    assert 0 < ei.value.offset <= len(payload)


# ------------------------- reference dataset golden counts -------------------


def test_reference_reads_counts(ref_data):
    seqs = FastaParser(ref_data("sample_reads.fasta.gz")).parse_all()
    assert len(seqs) == 236
    assert sum(len(s) for s in seqs) == 1674628


def test_reference_fastq_matches_fasta(ref_data):
    fa = FastaParser(ref_data("sample_reads.fasta.gz")).parse_all()
    fq = FastqParser(ref_data("sample_reads.fastq.gz")).parse_all()
    assert len(fq) == len(fa)
    assert all(a.data == b.data for a, b in zip(fa, fq))
    assert all(b.quality is not None and len(b.quality) == len(b.data)
               for b in fq)


def test_reference_layout_contig(ref_data):
    seqs = FastaParser(ref_data("sample_layout.fasta.gz")).parse_all()
    assert len(seqs) == 1
    assert len(seqs[0]) == 47564


def test_reference_overlap_counts(ref_data):
    paf = PafParser(ref_data("sample_overlaps.paf.gz")).parse_all()
    assert len(paf) == 181
    ava = PafParser(ref_data("sample_ava_overlaps.paf.gz")).parse_all()
    assert len(ava) == 8016
    sam = SamParser(ref_data("sample_overlaps.sam.gz")).parse_all()
    assert len(sam) > 0


def test_reference_mhap_equals_paf(ref_data):
    """PAF and MHAP encode the same all-vs-all overlaps (the reference's
    FragmentCorrection tests produce identical output from both,
    test/racon_test.cpp:237-289)."""
    paf = PafParser(ref_data("sample_ava_overlaps.paf.gz")).parse_all()
    mhap = MhapParser(ref_data("sample_ava_overlaps.mhap.gz")).parse_all()
    # the PAF variant carries one self-overlap per read which the MHAP file
    # omits; both are dropped downstream by the q_id == t_id filter
    # (src/polisher.cpp:259-262)
    paf = [o for o in paf if o.q_name != o.t_name]
    assert len(paf) == len(mhap) == 7780
    for a, b in zip(paf, mhap):
        assert (a.q_begin, a.q_end, a.q_length) == (b.q_begin, b.q_end, b.q_length)
        assert (a.t_begin, a.t_end, a.t_length) == (b.t_begin, b.t_end, b.t_length)
        assert a.strand == b.strand
