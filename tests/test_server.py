"""Server subsystem tests: the embeddable engine API, the
cross-request batcher, the journaled daemon lifecycle, and restart
recovery (racon_tpu/server/, docs/SERVER.md)."""

import contextlib
import io
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.resilience import faults
from racon_tpu.server.batch import (BatchedEngineProxy,
                                    CrossRequestBatcher, ServeError)
from racon_tpu.server.engine import JobSpec
from racon_tpu.server.jobs import Job, allocate_id, scan

BASES = np.frombuffer(b"ACGT", np.uint8)


@pytest.fixture(autouse=True)
def server_sandbox(monkeypatch):
    """Keep the process-global injector/registry out of other tests."""
    monkeypatch.delenv(faults.ENV_FAULTS, raising=False)
    faults.configure(None)
    obs_metrics.reset()
    yield
    faults.configure(None)
    obs_metrics.reset()


# ---------------------------------------------------------------- JobSpec


def test_jobspec_identity_and_roundtrip():
    spec = JobSpec("r.fa", "o.paf", "d.fa", window_length=250,
                   match=3, backend="jax")
    ident = spec.identity()
    # The identity dict is the checkpoint-fingerprint config: exactly
    # the output-affecting keys, never execution knobs.
    assert set(ident) == {"version", "include_unpolished",
                          "fragment_correction", "window_length",
                          "quality_threshold", "error_threshold",
                          "match", "mismatch", "gap"}
    assert "backend" not in ident and "threads" not in ident
    clone = JobSpec.from_dict(spec.as_dict())
    assert clone.identity() == ident
    assert clone.paths == ["r.fa", "o.paf", "d.fa"]
    assert clone.backend == "jax"


# ---------------------------------------------------------------- batcher


class _Window:
    """Stand-in with the Window surface the batcher touches."""

    def __init__(self, n=300, layers=3):
        self._n = n
        self.n_layers = layers
        self.polished = False

    def __len__(self):
        return self._n


class _FakeEngine:
    backend = "fake"

    def __init__(self, fail=False, delay_s=0.0):
        self.batches = []
        self.fail = fail
        self.delay_s = delay_s

    def consensus_windows(self, windows):
        self.batches.append(len(windows))
        if self.fail:
            raise RuntimeError("device wedged")
        if self.delay_s:
            time.sleep(self.delay_s)
        for w in windows:
            w.polished = True
        return len(windows)


def _concurrent_consensus(batcher, jobs):
    """Run [(job_id, tenant, windows), ...] concurrently; returns
    {job_id: result-or-exception}."""
    results = {}

    def run(jid, tenant, windows):
        proxy = BatchedEngineProxy(batcher, jid, tenant)
        try:
            results[jid] = proxy.consensus_windows(windows)
        except Exception as exc:  # collected for assertions
            results[jid] = exc

    threads = [threading.Thread(target=run, args=spec) for spec in jobs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def test_batcher_packs_across_jobs():
    """Three jobs' windows merge into one full-occupancy dispatch
    instead of three partial ones."""
    eng = _FakeEngine()
    b = CrossRequestBatcher(eng, capacity=32, wait_s=1.0,
                            queue_cap=8).start()
    try:
        results = _concurrent_consensus(b, [
            ("j1", "acme", [_Window() for _ in range(5)]),
            ("j2", "acme", [_Window() for _ in range(5)]),
            ("j3", "umbrella", [_Window() for _ in range(5)]),
        ])
    finally:
        b.close()
    assert results == {"j1": 5, "j2": 5, "j3": 5}
    assert sum(eng.batches) == 15
    assert len(eng.batches) < 3, "cross-job packing never happened"
    snap = obs_metrics.registry().snapshot()
    assert snap["serve_batch_windows"] == 15
    assert snap["serve_batch_occupancy"] > 0
    assert snap["serve_batches"] == len(eng.batches)


def test_batcher_splits_oversized_request():
    """A request larger than capacity slices into capacity-sized items
    — the chip never sees a super-sized batch."""
    eng = _FakeEngine()
    b = CrossRequestBatcher(eng, capacity=4, wait_s=0.01,
                            queue_cap=8).start()
    try:
        results = _concurrent_consensus(
            b, [("j1", "acme", [_Window() for _ in range(10)])])
    finally:
        b.close()
    assert results == {"j1": 10}
    assert max(eng.batches) <= 4


def test_batcher_tenant_fairness():
    """Round-robin compose: when one tenant floods staging, the other
    tenant still lands in the very next batch."""
    eng = _FakeEngine()
    b = CrossRequestBatcher(eng, capacity=4, wait_s=60.0, queue_cap=64)
    # Drive the dispatcher loop by hand: flood with acme, then one
    # umbrella item; the first composed batch must carry both tenants.
    from racon_tpu.server.batch import _WorkItem
    for i in range(6):
        b._stage(_WorkItem(f"a{i}", "acme", [_Window(), _Window()]))
    b._stage(_WorkItem("u0", "umbrella", [_Window(), _Window()]))
    batch = b._compose()
    assert {it.tenant for it in batch} == {"acme", "umbrella"}


def test_batcher_flush_deadline_dispatches_partial():
    """A lone small request does not wait forever for peers: the
    staging deadline flushes a partial batch."""
    eng = _FakeEngine()
    b = CrossRequestBatcher(eng, capacity=1024, wait_s=0.05,
                            queue_cap=8).start()
    try:
        t0 = time.perf_counter()
        results = _concurrent_consensus(
            b, [("j1", "acme", [_Window() for _ in range(3)])])
        elapsed = time.perf_counter() - t0
    finally:
        b.close()
    assert results == {"j1": 3}
    assert elapsed < 5.0


def test_batcher_dispatch_failure_fans_out_to_jobs():
    """A failed dispatch surfaces as ServeError on every job whose
    windows rode the batch — no hangs, no silent loss."""
    eng = _FakeEngine(fail=True)
    b = CrossRequestBatcher(eng, capacity=32, wait_s=0.5,
                            queue_cap=8).start()
    try:
        results = _concurrent_consensus(b, [
            ("j1", "acme", [_Window() for _ in range(2)]),
            ("j2", "umbrella", [_Window() for _ in range(2)]),
        ])
    finally:
        b.close()
    assert all(isinstance(v, ServeError) for v in results.values())


def test_batcher_injected_dispatch_fault():
    """The serve/dispatch fault site fires inside the dispatcher and
    fans out as a typed error (the chaos-drill hook for the daemon)."""
    faults.configure("serve/dispatch:0")
    eng = _FakeEngine()
    b = CrossRequestBatcher(eng, capacity=32, wait_s=0.5,
                            queue_cap=8).start()
    try:
        results = _concurrent_consensus(
            b, [("j1", "acme", [_Window() for _ in range(2)])])
    finally:
        b.close()
    assert isinstance(results["j1"], ServeError)
    snap = obs_metrics.registry().snapshot()
    assert snap["res_fault_site_serve_dispatch"] == 1


# ------------------------------------------------------------ job journal


def test_job_journal_roundtrip_and_id_allocation(tmp_path):
    root = str(tmp_path)
    assert allocate_id(root) == "j0001"
    spec = JobSpec("r.fa", "o.paf", "d.fa", window_length=123)
    d = os.path.join(root, "j0001")
    os.makedirs(d)
    job = Job("j0001", "acme", spec, d)
    job.persist()
    # Ids never reuse: allocation is max-existing + 1.
    assert allocate_id(root) == "j0002"
    loaded = scan(root)
    assert len(loaded) == 1
    assert loaded[0].id == "j0001"
    assert loaded[0].tenant == "acme"
    assert loaded[0].state == "queued"
    assert loaded[0].spec.identity() == spec.identity()
    # State transitions rewrite the journal atomically.
    job.state = "done"
    job.persist()
    assert scan(root)[0].state == "done"


# ---------------------------------------------------- daemon (in-process)


def _mutate(rng, truth):
    out = []
    for b in truth:
        r = rng.random()
        if r < 0.03:
            continue
        if r < 0.06:
            out.append(BASES[rng.integers(0, 4)])
        else:
            out.append(b)
    return bytes(bytearray(out))


def _write_inputs(d, n_contigs=2, n_reads=6, clen=300, seed=11):
    rng = np.random.default_rng(seed)
    drafts, reads, paf = [], [], []
    for ci in range(n_contigs):
        truth = BASES[rng.integers(0, 4, clen)]
        draft = _mutate(rng, truth)
        drafts.append(b">c%d\n%s\n" % (ci, draft))
        for i in range(n_reads):
            r = _mutate(rng, truth)
            name = f"c{ci}r{i}"
            reads.append(b">" + name.encode() + b"\n" + r + b"\n")
            paf.append(f"{name}\t{len(r)}\t0\t{len(r)}\t+\tc{ci}"
                       f"\t{len(draft)}\t0\t{len(draft)}"
                       f"\t{min(len(r), len(draft))}"
                       f"\t{max(len(r), len(draft))}\t60")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "draft.fasta"), "wb") as fh:
        fh.write(b"".join(drafts))
    with open(os.path.join(d, "reads.fasta"), "wb") as fh:
        fh.write(b"".join(reads))
    with open(os.path.join(d, "ovl.paf"), "w") as fh:
        fh.write("\n".join(paf) + "\n")


def _spec_for(d):
    return JobSpec(os.path.join(d, "reads.fasta"),
                   os.path.join(d, "ovl.paf"),
                   os.path.join(d, "draft.fasta"), backend="jax")


def _solo_cli_bytes(d):
    from racon_tpu import cli
    stdout = io.StringIO()
    stdout.buffer = io.BytesIO()
    with contextlib.redirect_stdout(stdout), \
            contextlib.redirect_stderr(io.StringIO()):
        rc = cli.main(["--backend", "jax",
                       os.path.join(d, "reads.fasta"),
                       os.path.join(d, "ovl.paf"),
                       os.path.join(d, "draft.fasta")])
    assert rc == 0
    return stdout.buffer.getvalue()


def _wait_finished(job, timeout_s=120.0):
    assert job.finished.wait(timeout_s), \
        f"job {job.id} still {job.state} after {timeout_s}s"


def test_daemon_jobs_match_solo_cli(tmp_path):
    """Tentpole acceptance (in-process half): concurrent jobs from two
    tenants through the shared batcher produce byte-identical output to
    solo serial CLI runs, and their windows co-ride dispatches."""
    from racon_tpu.server.daemon import PolishServer

    d1 = str(tmp_path / "in1")
    d2 = str(tmp_path / "in2")
    _write_inputs(d1, seed=11)
    _write_inputs(d2, seed=22)
    base1 = _solo_cli_bytes(d1)
    base2 = _solo_cli_bytes(d2)
    obs_metrics.reset()

    server = PolishServer(str(tmp_path / "state"))
    j1 = server.submit("acme", _spec_for(d1))
    j2 = server.submit("umbrella", _spec_for(d2))
    _wait_finished(j1)
    _wait_finished(j2)
    for b in server._batchers.values():
        b.close()
    assert (j1.state, j2.state) == ("done", "done"), (j1.error, j2.error)
    assert j1.result_bytes() == base1
    assert j2.result_bytes() == base2
    snap = obs_metrics.registry().snapshot()
    assert snap["serve_jobs_submitted"] == 2
    assert snap["serve_jobs_completed"] == 2
    assert snap["serve_batches"] >= 1


def test_daemon_http_surface(tmp_path):
    """submit/status/stream/cancel over the wire, plus /healthz and the
    OpenMetrics render."""
    from racon_tpu.obs.export import validate_openmetrics
    from racon_tpu.server.daemon import PolishServer, serve_http

    d = str(tmp_path / "in")
    _write_inputs(d)
    base = _solo_cli_bytes(d)

    server = PolishServer(str(tmp_path / "state"))
    httpd = serve_http(server, "127.0.0.1", 0)
    port = httpd.server_address[1]
    url = f"http://127.0.0.1:{port}"
    try:
        body = json.dumps({
            "tenant": "acme",
            "sequences": os.path.join(d, "reads.fasta"),
            "overlaps": os.path.join(d, "ovl.paf"),
            "targets": os.path.join(d, "draft.fasta"),
            "options": {"backend": "jax"}}).encode()
        with urllib.request.urlopen(urllib.request.Request(
                f"{url}/v1/jobs", data=body, method="POST")) as resp:
            sub = json.loads(resp.read())
        assert sub["id"] == "j0001"
        _wait_finished(server.get(sub["id"]))
        with urllib.request.urlopen(f"{url}/v1/jobs/{sub['id']}") as r:
            status = json.loads(r.read())
        assert status["state"] == "done", status
        with urllib.request.urlopen(
                f"{url}/v1/jobs/{sub['id']}/stream") as r:
            assert r.headers["X-Racon-State"] == "done"
            assert r.read() == base
        with urllib.request.urlopen(f"{url}/healthz") as r:
            health = json.loads(r.read())
        assert health["status"] == "ok"
        assert health["serve"]["jobs"][0]["id"] == "j0001"
        with urllib.request.urlopen(f"{url}/metrics") as r:
            assert validate_openmetrics(r.read().decode()) == []
        # Cancel on a terminal job is a no-op acknowledgment.
        with urllib.request.urlopen(urllib.request.Request(
                f"{url}/v1/jobs/{sub['id']}/cancel",
                method="POST")) as r:
            assert json.loads(r.read())["state"] == "done"
        # Unknown job -> 404.
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{url}/v1/jobs/j9999")
        assert ei.value.code == 404
    finally:
        httpd.shutdown()
        for b in server._batchers.values():
            b.close()


def test_daemon_restart_resumes_byte_identical(tmp_path):
    """Restart-recovery contract: a job interrupted mid-run (fault at
    the serve/commit site after the first contig committed, journal
    still saying "running" — the exact on-disk state a SIGKILL leaves)
    is re-queued by a fresh daemon, re-emits the committed prefix from
    the shard, and finishes byte-identical to a solo serial CLI run."""
    from racon_tpu.server.daemon import PolishServer

    d = str(tmp_path / "in")
    _write_inputs(d, n_contigs=3)
    base = _solo_cli_bytes(d)
    state = str(tmp_path / "state")

    faults.configure("serve/commit:1!raise")
    server1 = PolishServer(state)
    job = server1.submit("acme", _spec_for(d))
    _wait_finished(job)
    for b in server1._batchers.values():
        b.close()
    assert job.state == "failed"
    assert job.n_committed == 1, "expected exactly one committed contig"
    # A killed daemon never reaches the terminal journal write: restore
    # the journal to the state SIGKILL would have left it in.
    job.state = "running"
    job.persist()

    faults.configure(None)
    obs_metrics.reset()
    server2 = PolishServer(state)
    resumed = server2.recover()
    assert resumed == 1
    job2 = server2.get(job.id)
    _wait_finished(job2)
    for b in server2._batchers.values():
        b.close()
    assert job2.state == "done", job2.error
    assert job2.result_bytes() == base
    snap = obs_metrics.registry().snapshot()
    assert snap["serve_jobs_resumed"] == 1
    assert snap["res_ckpt_skips"] >= 1, "committed prefix not re-emitted"

    # Third instance: the terminal job survives restart read-only with
    # the exact same stream rebuilt from its store.
    server3 = PolishServer(state)
    assert server3.recover() == 0
    assert server3.get(job.id).state == "done"
    assert server3.get(job.id).result_bytes() == base


def test_daemon_submit_fault_and_cancel(tmp_path):
    """serve/submit faults surface to the submitter before any journal
    write; cancelling a queued job never runs it."""
    from racon_tpu.server.daemon import PolishServer

    d = str(tmp_path / "in")
    _write_inputs(d)
    server = PolishServer(str(tmp_path / "state"))

    faults.configure("serve/submit:0")
    with pytest.raises(Exception):
        server.submit("acme", _spec_for(d))
    assert scan(server.jobs_root) == []

    faults.configure(None)
    # Cancel racing the runner start: whichever side wins, the job ends
    # terminal and the journal agrees.
    job = server.submit("acme", _spec_for(d))
    server.cancel(job.id)
    _wait_finished(job)
    for b in server._batchers.values():
        b.close()
    assert job.state in ("cancelled", "done")
    assert scan(server.jobs_root)[0].state == job.state
