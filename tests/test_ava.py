"""Ava planner subsystem tests: length-weighted partitioning (and its
adoption by the work ledger), the shape-bucket planner, the record
spool, byte-aware gateway routing, and the kF single-parse path
(racon_tpu/ava/, docs/AVA.md)."""

import contextlib
import io
import os

import numpy as np
import pytest

from racon_tpu import ava
from racon_tpu.ava import emit as ava_emit
from racon_tpu.ava import partition as ava_part
from racon_tpu.ava import planner as ava_plan
from racon_tpu.gateway import dispatch as gw_dispatch
from racon_tpu.gateway.dispatch import RouteDecision, decide_route
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.ops import budget as ops_budget
from racon_tpu.server.engine import JobSpec

BASES = np.frombuffer(b"ACGT", np.uint8)

AVA_ENVS = (ava.ENV_AVA_SEG, ava_part.ENV_AVA_WEIGHTED,
            ops_budget.ENV_AVA_COMPILE_BUDGET,
            ava_emit.ENV_SERVE_SPOOL,
            gw_dispatch.ENV_GATE_FLEET, gw_dispatch.ENV_MIN_TARGETS,
            gw_dispatch.ENV_MIN_BYTES, gw_dispatch.ENV_QUEUE_PRESSURE)


@pytest.fixture(autouse=True)
def ava_sandbox(monkeypatch):
    for env in AVA_ENVS:
        monkeypatch.delenv(env, raising=False)
    obs_metrics.reset()
    yield
    obs_metrics.reset()


# ----------------------------------------------- weighted partitioning


def test_uniform_weights_match_count_partition():
    """Equal weights reproduce the count partition when it divides
    evenly, and stay within one target of it otherwise (the two round
    the remainder differently, never more)."""
    from racon_tpu.distributed.ledger import _partition
    for n, k in ((6, 3), (100, 4), (5, 5), (8, 2)):
        assert ava_part.weighted_partition(n, k, [10] * n) == \
            _partition(n, k)
    for n, k in ((7, 3), (100, 8)):
        w = ava_part.weighted_partition(n, k, [10] * n)
        sizes = [w[i + 1] - w[i] for i in range(k)]
        assert max(sizes) - min(sizes) <= 1


def test_weighted_partition_balances_bytes_not_counts():
    """Length-skewed reads (heavy prefix, light tail): the weighted cut
    lands where the BYTES halve, not where the record count does."""
    weights = [100] * 10 + [1] * 90
    bounds = ava_part.weighted_partition(100, 2, weights)
    assert bounds == [0, 6, 100]
    half = sum(weights) / 2
    assert abs(sum(weights[:bounds[1]]) - half) < 100
    # The count partition would load shard 0 with ~95% of the bytes.
    assert sum(weights[:50]) > 0.95 * sum(weights)
    # Degenerate skew — one dominant read: it sits alone in shard 0 and
    # every shard still owns at least one target.
    b = ava_part.weighted_partition(100, 4, [10_000] + [10] * 99)
    assert b[0] == 0 and b[-1] == 100 and b == sorted(set(b))
    assert b[1] == 1


def test_weighted_partition_invariants_random():
    """Property sweep: contiguous ascending bounds, full cover, >=1
    target per shard — the invariants every downstream consumer
    (manifest prefix, split carving, merge tiling) rests on."""
    rng = np.random.default_rng(7)
    for _ in range(50):
        n = int(rng.integers(1, 200))
        k = int(rng.integers(1, min(n, 12) + 1))
        w = rng.integers(1, 5000, n).tolist()
        b = ava_part.weighted_partition(n, k, w)
        assert b[0] == 0 and b[-1] == n and len(b) == k + 1
        assert all(b[i] < b[i + 1] for i in range(k))


def test_weights_from_offsets_shapes():
    assert ava_part.weights_from_offsets([]) == []
    assert ava_part.weights_from_offsets([0]) == [1]
    # Deltas, with the last target weighing the mean gap.
    assert ava_part.weights_from_offsets([0, 100, 150]) == [100, 50, 75]


def test_weighted_bounds_gate_and_consistency(monkeypatch):
    offs = [0, 1000, 1010, 1020]
    assert ava_part.weighted_bounds(4, 2, offs) == [0, 1, 4]
    # Single shard / inconsistent offsets: keep the count partition.
    assert ava_part.weighted_bounds(4, 1, offs) is None
    assert ava_part.weighted_bounds(5, 2, offs) is None
    monkeypatch.setenv(ava_part.ENV_AVA_WEIGHTED, "0")
    assert ava_part.weighted_bounds(4, 2, offs) is None


def test_ledger_publishes_weighted_bounds(tmp_path):
    """WorkLedger.open with a scan that returns skewed offsets must
    publish weighted bounds; a joiner adopts them verbatim."""
    from racon_tpu.distributed.ledger import WorkLedger
    offsets = [0, 9000, 9010, 9020, 9030, 9040]
    led = WorkLedger.open(str(tmp_path / "led"), "fp1", workers=1,
                          n_shards=2, weighted=True,
                          scan_targets=lambda: (6, offsets))
    assert led.bounds == [0, 1, 6]          # not the count split [0,3,6]
    joiner = WorkLedger.open(str(tmp_path / "led"), "fp1", workers=1)
    assert joiner.bounds == led.bounds


def test_ledger_count_bounds_when_gate_off(tmp_path, monkeypatch):
    monkeypatch.setenv(ava_part.ENV_AVA_WEIGHTED, "0")
    from racon_tpu.distributed.ledger import WorkLedger
    offsets = [0, 9000, 9010, 9020, 9030, 9040]
    led = WorkLedger.open(str(tmp_path / "led"), "fp1", workers=1,
                          n_shards=2, weighted=True,
                          scan_targets=lambda: (6, offsets))
    assert led.bounds == [0, 3, 6]


def test_ledger_kc_open_stays_count_partitioned(tmp_path):
    """A contig-polish open (weighted unset) keeps the count partition
    even when the scan supplies skewed offsets — the weighted cut is
    the kF worker's opt-in, not a side effect of scanning."""
    from racon_tpu.distributed.ledger import WorkLedger
    offsets = [0, 9000, 9010, 9020, 9030, 9040]
    led = WorkLedger.open(str(tmp_path / "led"), "fp1", workers=1,
                          n_shards=2,
                          scan_targets=lambda: (6, offsets))
    assert led.bounds == [0, 3, 6]


# --------------------------------------------------- shape-bucket plan


def test_plan_buckets_quantizes_and_coalesces():
    plan = ava_plan.plan_buckets([100, 120, 700, 100], window_length=500)
    q = ops_budget.ava_bucket_quantum(500)
    assert plan.quantum == q
    assert plan.n_targets == 4
    # 100 and 120 share the 2-quantum bucket; 700 gets its own.
    assert plan.buckets == ((2 * q, 3), (704, 1))
    # Input order 100,120,700,100 -> runs: [q, q], [700cap], [q].
    assert plan.n_runs == 3
    assert plan.n_buckets == len(plan.compile_keys) == 2
    assert 0.0 <= plan.pad_frac < 1.0


def test_plan_buckets_budget_doubles_quantum():
    """Millions of distinct lengths must collapse under the compile
    budget by coarsening, never by dropping targets."""
    rng = np.random.default_rng(3)
    lengths = rng.integers(200, 60_000, 5000).tolist()
    plan = ava_plan.plan_buckets(lengths, window_length=500, budget=8)
    assert plan.n_buckets <= 8
    assert plan.quantum > ops_budget.ava_bucket_quantum(500)
    assert plan.n_targets == 5000
    assert sum(c for _, c in plan.buckets) == 5000
    # Tighter budget -> coarser quantum, never a budget violation.
    tight = ava_plan.plan_buckets(lengths, window_length=500, budget=2)
    assert tight.n_buckets <= 2
    assert tight.quantum >= plan.quantum


def test_plan_buckets_empty_raises_and_env_budget(monkeypatch):
    with pytest.raises(ValueError, match="at least one target"):
        ava_plan.plan_buckets([])
    monkeypatch.setenv(ops_budget.ENV_AVA_COMPILE_BUDGET, "3")
    plan = ava_plan.plan_buckets(list(range(100, 50_000, 137)))
    assert plan.budget == 3 and plan.n_buckets <= 3


def test_record_ava_plan_publishes_gauges():
    plan = ava_plan.plan_buckets([100, 700, 100], window_length=500)
    obs_metrics.record_ava_plan(plan)
    snap = obs_metrics.registry().snapshot()
    assert snap["ava_targets"] == 3
    assert snap["ava_buckets"] == plan.n_buckets
    assert snap["ava_quantum"] == plan.quantum
    assert snap["ava_compile_budget"] == plan.budget
    assert snap["ava_pad_frac"] == plan.pad_frac


# --------------------------------------------------------- record spool


def test_record_spool_memory_and_spill_identity(tmp_path):
    records = [b"rec%03d:" % i + b"x" * i for i in range(64)]
    # Never-spill (limit 0) vs tiny-limit spill: identical streams.
    mem = ava_emit.RecordSpool(str(tmp_path), limit_bytes=0)
    disk = ava_emit.RecordSpool(str(tmp_path), limit_bytes=100)
    for r in records:
        mem.append(r)
        disk.append(r)
    assert not mem.spilled and disk.spilled
    assert os.path.exists(os.path.join(str(tmp_path),
                                       ava_emit.SPOOL_FILE))
    want = b"".join(records)
    assert mem.read_all() == disk.read_all() == want
    assert mem.total_bytes == disk.total_bytes == len(want)
    # Reads interleave with appends past the spill point.
    disk.append(b"tail")
    assert disk.read_all() == want + b"tail"
    disk.reset()
    assert disk.total_bytes == 0 and disk.read_all() == b""
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           ava_emit.SPOOL_FILE))
    mem.close()
    disk.close()


def test_record_spool_no_directory_never_spills():
    sp = ava_emit.RecordSpool(None, limit_bytes=4)
    for _ in range(32):
        sp.append(b"abcdefgh")
    assert not sp.spilled and len(sp.read_all()) == 256


def test_iter_fasta_records_matches_split(tmp_path):
    blob = b">a desc\nACGT\nTTTT\n>b\nCC\n>c\nGGGG\n"
    p = tmp_path / "out.fasta"
    p.write_bytes(blob)
    recs = list(ava_emit.iter_fasta_records(str(p)))
    assert recs == gw_dispatch._split_fasta(blob)
    assert b"".join(recs) == blob
    p.write_bytes(b"")
    assert list(ava_emit.iter_fasta_records(str(p))) == []


# --------------------------------------------------- byte-aware routing


def test_route_ava_jobs_size_by_bytes(monkeypatch):
    monkeypatch.setenv(gw_dispatch.ENV_GATE_FLEET, "1")
    monkeypatch.setenv(gw_dispatch.ENV_MIN_TARGETS, "4")
    monkeypatch.setenv(gw_dispatch.ENV_MIN_BYTES, "1000")
    monkeypatch.setenv(gw_dispatch.ENV_QUEUE_PRESSURE, "2")
    spec = JobSpec("r.fa", "o.paf", "r.fa", fragment_correction=True)

    # Few records but a megabyte of reads: bytes say fleet even though
    # the count threshold never fires.
    d = decide_route(spec, 3, queue_depth=0, target_bytes=5000)
    assert d.route == "fleet" and "target_bytes 5000 >= 1000" in d.reason
    assert d.target_bytes == 5000
    # Many tiny records, few bytes: count would misroute to the fleet;
    # bytes keep it local.
    d = decide_route(spec, 400, queue_depth=0, target_bytes=800)
    assert d.route == "local" and "target_bytes 800 < 1000" in d.reason
    # Queue pressure overrides in the ava regime too.
    d = decide_route(spec, 1, queue_depth=2, target_bytes=10)
    assert d.route == "fleet" and "queue_depth" in d.reason
    # Unarmed gateway: ava jobs stay local like everything else.
    monkeypatch.delenv(gw_dispatch.ENV_GATE_FLEET)
    d = decide_route(spec, 3, queue_depth=9, target_bytes=10**9)
    assert d == RouteDecision("local", "fleet-disabled", 3, 9, 10**9)


def test_route_non_ava_jobs_unchanged_by_bytes(monkeypatch):
    """A kC spec (and the policy tests' spec=None) still routes purely
    by count — target_bytes rides along for the gate span only."""
    monkeypatch.setenv(gw_dispatch.ENV_GATE_FLEET, "1")
    monkeypatch.setenv(gw_dispatch.ENV_MIN_TARGETS, "4")
    monkeypatch.setenv(gw_dispatch.ENV_MIN_BYTES, "1")
    d = decide_route(None, 3, queue_depth=0, target_bytes=10**9)
    assert d.route == "local"
    spec = JobSpec("r.fa", "o.paf", "d.fa")
    d = decide_route(spec, 4, queue_depth=0, target_bytes=0)
    assert d.route == "fleet" and "n_targets" in d.reason


def test_target_stats_returns_count_and_bytes(tmp_path):
    p = tmp_path / "t.fasta"
    p.write_bytes(b">c0\nACGT\n>c1\nAC\n")
    assert gw_dispatch.target_stats(str(p)) == (2, 16)


# ------------------------------------------------- segment-size policy


def test_seg_targets_for_regimes(monkeypatch):
    assert ava.seg_targets_for(True) == ava.DEFAULT_SEG_TARGETS
    assert ava.seg_targets_for(False) == 0
    monkeypatch.setenv(ava.ENV_AVA_SEG, "64")
    assert ava.seg_targets_for(True) == 64
    assert ava.seg_targets_for(False) == 64   # explicit env wins
    monkeypatch.setenv(ava.ENV_AVA_SEG, "0")
    assert ava.seg_targets_for(True) == 0
    monkeypatch.setenv(ava.ENV_AVA_SEG, "junk")
    assert ava.seg_targets_for(True) == 0     # malformed: fail safe, v1


# ----------------------------------------------------- kF single-parse


def _write_ava_inputs(d, n_reads=8, rlen=220):
    rng = np.random.default_rng(13)
    truth = BASES[rng.integers(0, 4, rlen)]
    reads, paf = [], []
    names = []
    for i in range(n_reads):
        out = []
        for b in truth:
            r = rng.random()
            if r < 0.03:
                continue
            out.append(int(BASES[rng.integers(0, 4)]) if r < 0.06
                       else int(b))
        data = bytes(out)
        name = f"read{i}"
        names.append((name, len(data)))
        reads.append(b">" + name.encode() + b"\n" + data + b"\n")
    for i in range(n_reads):
        qn, ql = names[i]
        tn, tl = names[(i + 1) % n_reads]
        paf.append(f"{qn}\t{ql}\t0\t{ql}\t+\t{tn}\t{tl}\t0\t{tl}"
                   f"\t{min(ql, tl)}\t{max(ql, tl)}\t60")
        paf.append(f"{tn}\t{tl}\t0\t{tl}\t+\t{qn}\t{ql}\t0\t{ql}"
                   f"\t{min(ql, tl)}\t{max(ql, tl)}\t60")
    (d / "reads.fasta").write_bytes(b"".join(reads))
    (d / "ava.paf").write_text("\n".join(paf) + "\n")


class _PoisonParser:
    """Stands in for the reads parser on the shared-path run: the
    polisher may look at .path (the single-parse detection) but any
    parse attempt means the reads file was read twice."""

    def __init__(self, path):
        self.path = path

    def __getattr__(self, name):
        raise AssertionError(
            f"kF single-parse violated: reads parser used ({name})")


def _kf_polish(reads_path, paf_path, targets_path, poison=False):
    from racon_tpu.models.polisher import PolisherType, create_polisher
    p = create_polisher(reads_path, paf_path, targets_path,
                        PolisherType.kF, 500, 10.0, 0.3, 1, -1, -1,
                        backend="native")
    p.engine.refine_rounds = 1
    if poison:
        p.sparser = _PoisonParser(reads_path)
    with contextlib.redirect_stderr(io.StringIO()):
        p.initialize()
        return p.polish(False)


def test_kf_single_parse_byte_identity(tmp_path):
    """The double-parse fix: reads==targets file parses once, and the
    output is identical to feeding the same content through two
    distinct files (which forces the two-parse path)."""
    _write_ava_inputs(tmp_path)
    reads = str(tmp_path / "reads.fasta")
    paf = str(tmp_path / "ava.paf")
    copy = str(tmp_path / "reads_copy.fasta")
    with open(reads, "rb") as src, open(copy, "wb") as dst:
        dst.write(src.read())

    shared = _kf_polish(reads, paf, reads, poison=True)
    twofile = _kf_polish(copy, paf, reads)
    assert len(shared) == len(twofile) == 8
    assert [s.name for s in shared] == [s.name for s in twofile]
    assert [s.data for s in shared] == [s.data for s in twofile]
