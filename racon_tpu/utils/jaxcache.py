"""Persistent JAX compilation cache for racon_tpu entry points.

Every distinct executable shape costs a fresh XLA compile; through this
environment's remote AOT helper that is 1-2 minutes per shape, and even
locally-attached TPUs pay tens of seconds. The persistent cache stores
serialized executables on disk so warm process starts skip compilation
entirely (measured round 5: a small consensus run dropped 44.5 s ->
12.1 s on its second fresh-process invocation).

Opt out with RACON_TPU_JAX_CACHE=0; point elsewhere with
RACON_TPU_JAX_CACHE=/path.

Cache population is also the observability layer's compile accounting
(racon_tpu/obs/metrics.py): enabling records the entry count at start,
and :func:`cache_extras` reports entries added since — every added
entry is a compile this process paid for (a warm run adds none).
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec

from racon_tpu.obs.metrics import registry as _obs_registry


def cache_entry_count(path: str) -> int:
    """Number of serialized executables currently in the cache dir."""
    try:
        return sum(1 for e in os.scandir(path) if e.is_file())
    except OSError:
        return 0


def enable_compile_cache(path: str | None = None) -> None:
    """Enable the cache (idempotent, safe before or after jax import)."""
    env = envspec.read("RACON_TPU_JAX_CACHE")
    reg = _obs_registry()
    if env in ("0", "false", "off"):
        reg.set("jax_cache_enabled", 0)
        return
    path = path or env or os.path.expanduser("~/.cache/racon_tpu/jax")
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        reg.set("jax_cache_enabled", 1)
        reg.set("_jax_cache_dir", path)
        reg.set("jax_cache_entries_start", cache_entry_count(path))
    except Exception:
        # Cache is an optimization; never fail a run over it.
        reg.set("jax_cache_enabled", 0)


def cache_extras(reg=None) -> dict:
    """Compile-cache counters for bench extras: entries at enable time
    and entries added since (~= fresh compiles this process)."""
    reg = reg if reg is not None else _obs_registry()
    out = {"jax_cache_enabled": int(reg.get("jax_cache_enabled", 0))}
    path = reg.get("_jax_cache_dir", "")
    if out["jax_cache_enabled"] and path:
        start = int(reg.get("jax_cache_entries_start", 0))
        out["jax_cache_entries_start"] = start
        out["jax_cache_entries_added"] = max(
            cache_entry_count(str(path)) - start, 0)
    return out
