"""Persistent JAX compilation cache for racon_tpu entry points.

Every distinct executable shape costs a fresh XLA compile; through this
environment's remote AOT helper that is 1-2 minutes per shape, and even
locally-attached TPUs pay tens of seconds. The persistent cache stores
serialized executables on disk so warm process starts skip compilation
entirely (measured round 5: a small consensus run dropped 44.5 s ->
12.1 s on its second fresh-process invocation).

Opt out with RACON_TPU_JAX_CACHE=0; point elsewhere with
RACON_TPU_JAX_CACHE=/path.
"""

from __future__ import annotations

import os


def enable_compile_cache(path: str | None = None) -> None:
    """Enable the cache (idempotent, safe before or after jax import)."""
    env = os.environ.get("RACON_TPU_JAX_CACHE", "")
    if env in ("0", "false", "off"):
        return
    path = path or env or os.path.expanduser("~/.cache/racon_tpu/jax")
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        # Cache is an optimization; never fail a run over it.
        pass
