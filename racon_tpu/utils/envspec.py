"""Declared registry of every RACON_TPU_* environment gate.

Every environment read of a ``RACON_TPU_*`` name in racon_tpu/,
scripts/, and bench.py resolves through :func:`read` below, and every
entry here carries the doc file that holds its row.  The env-contract
rule in racon_tpu/analysis enforces the triangle in both directions:

  code read  ->  declared spec   (ENV001/ENV002: undeclared reads flag)
  spec       ->  code read       (ENV003: dead declarations flag)
  spec       ->  docs row        (ENV004: undocumented gates flag)
  docs row   ->  spec            (ENV005: documented-but-unread flags)

:func:`read` returns the *raw string* (declared default when unset) —
call sites keep their own parsing so the migration onto the registry is
byte-identical to the pre-registry behaviour.  The ``kind`` tag is
descriptive metadata for the linter and docs, not a parser.
"""

from __future__ import annotations

import os
from typing import Dict, NamedTuple


class EnvSpec(NamedTuple):
    name: str     # full RACON_TPU_* variable name
    default: str  # raw default returned by read() when unset
    kind: str     # "flag" | "int" | "float" | "str" | "path" | "spec"
    doc: str      # docs/*.md file carrying this gate's row
    help: str     # one-line summary (docs row seed)


REGISTRY: Dict[str, EnvSpec] = {}


def declare(name: str, default: str, kind: str, doc: str,
            help: str) -> str:
    """Register one gate; returns the name so modules can bind ENV_*
    constants directly to a declaration."""
    if not name.startswith("RACON_TPU_"):
        raise ValueError(f"[racon_tpu::envspec] not a RACON_TPU_* "
                         f"gate: {name!r}")
    if name in REGISTRY:
        raise ValueError(f"[racon_tpu::envspec] duplicate declaration "
                         f"for {name!r}")
    if kind not in ("flag", "int", "float", "str", "path", "spec"):
        raise ValueError(f"[racon_tpu::envspec] unknown kind {kind!r} "
                         f"for {name!r}")
    REGISTRY[name] = EnvSpec(name, default, kind, doc, help)
    return name


def read(name: str) -> str:
    """Raw environment read through the registry.  Raises KeyError on
    names that were never declared — the runtime counterpart of the
    env-contract lint rule."""
    spec = REGISTRY.get(name)
    if spec is None:
        raise KeyError(f"[racon_tpu::envspec] undeclared env gate "
                       f"{name!r}; declare it in "
                       f"racon_tpu/utils/envspec.py")
    return os.environ.get(name, spec.default)


# --------------------------------------------------------------------
# The registry.  Grouped by doc file; keep alphabetical within groups.
# --------------------------------------------------------------------

# docs/AVA.md — assembly-scale all-vs-all planning
declare("RACON_TPU_AVA_COMPACT", "", "int", "AVA.md",
        "sealed v2 manifest segments between compaction rewrites "
        "(default 64; 0 disables compaction)")
declare("RACON_TPU_AVA_COMPILE_BUDGET", "", "int", "AVA.md",
        "max distinct shape buckets the ava planner may emit; the "
        "bucket quantum doubles until the plan fits (default 8)")
declare("RACON_TPU_AVA_SEG", "", "int", "AVA.md",
        "checkpoint-manifest targets per v2 segment record; unset = "
        "256 for ava runs and 0 (v1 per-target records) for kC")
declare("RACON_TPU_AVA_WEIGHTED", "1", "flag", "AVA.md",
        "length-weighted shard partitioning when target offsets are "
        "published (default on; 0 = count-balanced bounds)")

# docs/CACHE.md — content-addressed result cache
declare("RACON_TPU_CACHE", "", "flag", "CACHE.md",
        "result-cache master gate: on by default for the daemon (the "
        "serial CLI needs --cache-dir); 0/false disables both tiers")
declare("RACON_TPU_CACHE_DIR", "", "path", "CACHE.md",
        "cache root override (daemon default: <state-dir>/cache)")
declare("RACON_TPU_CACHE_MAX_MB", "256", "int", "CACHE.md",
        "job-level CAS byte bound; LRU eviction keeps it under this")
declare("RACON_TPU_CACHE_WINDOWS", "", "flag", "CACHE.md",
        "window memoization gate: on whenever the cache is on; "
        "0/false keeps Tier 1 but disables the in-batcher memo")

# docs/DISTRIBUTED.md — fleet, ledger, autoscaler
declare("RACON_TPU_AUTOSCALE_DEADLINE_S", "", "float", "DISTRIBUTED.md",
        "autoscaler run deadline: give up replacing workers after this")
declare("RACON_TPU_AUTOSCALE_FAULT_PLAN", "", "path", "DISTRIBUTED.md",
        "JSON chaos plan (kill/straggle events) for the autoscaler")
declare("RACON_TPU_AUTOSCALE_INTERVAL_S", "", "float", "DISTRIBUTED.md",
        "supervisor poll interval between scaling decisions")
declare("RACON_TPU_AUTOSCALE_MAX", "", "int", "DISTRIBUTED.md",
        "upper bound on concurrently live autoscaled workers")
declare("RACON_TPU_AUTOSCALE_MAX_SPAWNS", "", "int", "DISTRIBUTED.md",
        "total spawn budget: cap on workers ever launched per run")
declare("RACON_TPU_AUTOSCALE_MIN", "", "int", "DISTRIBUTED.md",
        "lower bound on live workers while open work remains")
declare("RACON_TPU_DIST_AVOID", "", "str", "DISTRIBUTED.md",
        "comma list of shard ids this worker must not claim")
declare("RACON_TPU_DIST_POLL", "", "float", "DISTRIBUTED.md",
        "worker poll interval while waiting for claimable shards")
declare("RACON_TPU_DIST_SHARDS", "", "int", "DISTRIBUTED.md",
        "shard count override for ledger initialisation")
declare("RACON_TPU_SPLIT", "1", "flag", "DISTRIBUTED.md",
        "dynamic shard splitting gate (default on)")
declare("RACON_TPU_SPLIT_AFTER_S", "", "float", "DISTRIBUTED.md",
        "min seconds on one shard before a worker offers a split")
declare("RACON_TPU_SPLIT_DEPTH", "", "int", "DISTRIBUTED.md",
        "max split lineage depth (guards handoff cascades)")

# docs/GATEWAY.md — fleet-serve gateway
declare("RACON_TPU_GATE_FLEET", "0", "flag", "GATEWAY.md",
        "fleet-serve gate: route eligible daemon jobs to an "
        "autoscaled ledger fleet (default off = all jobs in-process)")
declare("RACON_TPU_GATE_FLEET_MIN_BYTES", "8388608", "int", "GATEWAY.md",
        "ava routing size threshold: fragment-correction jobs whose "
        "targets file is at least this many bytes go to the fleet "
        "(target COUNT misprices read-sized targets)")
declare("RACON_TPU_GATE_FLEET_MIN_TARGETS", "32", "int", "GATEWAY.md",
        "routing size threshold: jobs with at least this many target "
        "contigs go to the fleet")
declare("RACON_TPU_GATE_LEASE_S", "10", "float", "GATEWAY.md",
        "gateway lease term; a standby adopts after a primary misses "
        "renewals for this long")
declare("RACON_TPU_GATE_QUEUE_PRESSURE", "8", "int", "GATEWAY.md",
        "queue-pressure override: at this admission-queue depth even "
        "small jobs route to the fleet")
declare("RACON_TPU_GATE_STANDBY_POLL_S", "0.2", "float", "GATEWAY.md",
        "standby gateway lease poll interval")
declare("RACON_TPU_GATE_WORKERS", "2", "int", "GATEWAY.md",
        "fleet size cap per gateway-dispatched job (the autoscale "
        "max the supervisor is started with)")

# docs/INGEST.md — parallel data plane
declare("RACON_TPU_INGEST", "", "flag", "INGEST.md",
        "parallel ingest gate: chunked inflate + mmap readers "
        "(default on; 0/false = serial readers)")
declare("RACON_TPU_INGEST_WORKERS", "", "int", "INGEST.md",
        "inflate worker-pool size override")

# docs/KERNELS.md — device kernels and walk geometry
declare("RACON_TPU_NO_BAND", "", "flag", "KERNELS.md",
        "disable banded DP scoring (full-matrix fallback)")
declare("RACON_TPU_NO_PALLAS", "", "flag", "KERNELS.md",
        "force the XLA twin kernels instead of Pallas")
declare("RACON_TPU_OVL_TILED", "1", "flag", "KERNELS.md",
        "tiled ultralong overlap alignment gate (default on)")
declare("RACON_TPU_REDO", "", "flag", "KERNELS.md",
        "on-device wide-band redo of flagged windows (default on)")
declare("RACON_TPU_WALK_K", "", "int", "KERNELS.md",
        "column-walk chain length k (1, 2, or 4; default 4)")

# docs/OBSERVABILITY.md — tracing, metrics, bench
declare("RACON_TPU_BENCH_DP", "", "path", "OBSERVABILITY.md",
        "dp-scaling bench output path (enables the dp sweep)")
declare("RACON_TPU_BENCH_E2E_REPS", "3", "int", "OBSERVABILITY.md",
        "bench.py end-to-end repetitions per measurement")
declare("RACON_TPU_BENCH_INGEST_MB", "16", "int", "OBSERVABILITY.md",
        "synthetic corpus size for the ingest micro-bench")
declare("RACON_TPU_BENCH_OUT", "", "path", "OBSERVABILITY.md",
        "bench.py JSON results output path")
declare("RACON_TPU_DP_TIMEOUT", "600", "float", "OBSERVABILITY.md",
        "per-point timeout for scripts/dp_scaling_bench.py workers")
declare("RACON_TPU_FLIGHT_EVENTS", "", "int", "OBSERVABILITY.md",
        "flight recorder ring capacity (default 256; 0 disables)")
declare("RACON_TPU_JAX_CACHE", "", "path", "OBSERVABILITY.md",
        "persistent jax compilation cache dir (warm-start reuse)")
declare("RACON_TPU_METRICS_PORT", "", "int", "OBSERVABILITY.md",
        "OpenMetrics pull endpoint port (unset = no endpoint)")
declare("RACON_TPU_OBS_DIR", "", "path", "OBSERVABILITY.md",
        "per-worker metrics snapshot directory (fleet obs plane)")
declare("RACON_TPU_OBS_FLUSH_S", "", "float", "OBSERVABILITY.md",
        "metrics snapshot flush interval override")
declare("RACON_TPU_TIMING", "", "flag", "OBSERVABILITY.md",
        "verbose per-round timing (separate dispatch per round)")
declare("RACON_TPU_TRACE", "", "path", "OBSERVABILITY.md",
        "span trace output directory (JSONL tracer gate)")
declare("RACON_TPU_TRACE_CTX", "", "str", "OBSERVABILITY.md",
        "inherited trace context handoff (trace_id:parent_span_id)")
declare("RACON_TPU_TRACE_XPROF", "", "flag", "OBSERVABILITY.md",
        "also capture an xprof/jax profiler trace alongside spans")

# docs/PIPELINE.md — streaming executor
declare("RACON_TPU_PIPELINE", "", "flag", "PIPELINE.md",
        "streaming pipeline gate (see pipeline/__init__ truth table)")
declare("RACON_TPU_PIPELINE_DEPTH", "", "int", "PIPELINE.md",
        "bounded-queue capacity per stage edge")
declare("RACON_TPU_WALK_ASYNC", "", "flag", "PIPELINE.md",
        "decoupled walk dispatches (0 forces fused forward+walk)")
declare("RACON_TPU_WALK_QUEUE", "", "int", "PIPELINE.md",
        "in-flight walk-input queue depth (budget-clamped)")

# docs/RESILIENCE.md — faults, retry, watchdog, deadlines
declare("RACON_TPU_DEADLINE_CELLS_PER_S", "", "float", "RESILIENCE.md",
        "dispatch deadline model: DP cells per second floor")
declare("RACON_TPU_DEADLINE_D2H", "", "float", "RESILIENCE.md",
        "fixed device-to-host transfer deadline override")
declare("RACON_TPU_DEADLINE_DISPATCH", "", "float", "RESILIENCE.md",
        "fixed dispatch deadline override")
declare("RACON_TPU_DEADLINE_H2D", "", "float", "RESILIENCE.md",
        "fixed host-to-device transfer deadline override")
declare("RACON_TPU_DEADLINE_MBPS", "", "float", "RESILIENCE.md",
        "transfer deadline model: MB/s floor")
declare("RACON_TPU_DEADLINE_SCALE", "", "float", "RESILIENCE.md",
        "global multiplier on every derived deadline")
declare("RACON_TPU_FAULTS", "", "spec", "RESILIENCE.md",
        "fault-injection spec (site[:action][@n][,...])")
declare("RACON_TPU_FAULT_HANG_S", "", "float", "RESILIENCE.md",
        "injected hang duration for the hang fault action")
declare("RACON_TPU_FAULT_STALL_S", "", "float", "RESILIENCE.md",
        "injected stall duration for the stall fault action")
declare("RACON_TPU_RETRY", "", "spec", "RESILIENCE.md",
        "retry policy overrides (attempts=..,base_s=..,...)")
declare("RACON_TPU_STALL_S", "", "float", "RESILIENCE.md",
        "pipeline stall-detector window override")
declare("RACON_TPU_STRAGGLER_FRAC", "", "float", "RESILIENCE.md",
        "straggler threshold as a fraction of fleet median rate")
declare("RACON_TPU_WATCHDOG_TERMINAL", "", "spec", "RESILIENCE.md",
        "terminal-breach limit (count or count/window_s)")

# docs/SERVER.md — resident daemon and cross-request batcher
declare("RACON_TPU_SERVE_BATCH", "256", "int", "SERVER.md",
        "cross-request batch capacity in windows per dispatch")
declare("RACON_TPU_SERVE_BATCH_WAIT_S", "0.05", "float", "SERVER.md",
        "max staging wait before a partial batch dispatches")
declare("RACON_TPU_SERVE_GRACE_S", "30", "float", "SERVER.md",
        "SIGTERM drain grace: seconds to finish in-flight jobs")
declare("RACON_TPU_SERVE_MAX_JOBS", "4", "int", "SERVER.md",
        "max concurrently running jobs (admission semaphore)")
declare("RACON_TPU_SERVE_QUEUE", "64", "int", "SERVER.md",
        "bounded admission queue depth in work items")
declare("RACON_TPU_SERVE_SPOOL_MB", "", "int", "SERVER.md",
        "in-memory result bytes per job before the stream spills to "
        "the job-directory spool file (default 8 MiB; 0 = never "
        "spill)")

# docs/SCHEDULER.md — shape-bucket scheduler
declare("RACON_TPU_ADAPTIVE", "", "flag", "SCHEDULER.md",
        "adaptive early-exit rounds (converged chunks stop early)")
declare("RACON_TPU_SCHED", "", "flag", "SCHEDULER.md",
        "shape-bucket scheduler gate (default on)")
