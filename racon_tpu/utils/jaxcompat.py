"""Portability shims for jax APIs that moved across releases.

The sharded paths target the current jax surface (``jax.shard_map`` with
``check_vma``, ``jax.lax.pvary``); older toolchains (0.4.x) expose
shard_map only under ``jax.experimental.shard_map`` (with ``check_rep``
instead of ``check_vma``) and have no varying-axes typing at all, where
``pvary`` is the identity by construction. Every call site routes
through this module so the supported API is picked once, at import time,
instead of tripping AttributeErrors / DeprecationWarnings per trace.
"""

from __future__ import annotations

import jax

# On 0.4.x `jax.shard_map` is a registered deprecation stub that raises
# AttributeError on access, so hasattr is the correct probe.
if hasattr(jax, "shard_map"):
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)


if hasattr(jax.lax, "pvary"):
    pvary = jax.lax.pvary
else:
    def pvary(x, axis_names):
        # Pre-varying-axes jax: every shard_map intermediate is already
        # implicitly device-varying; nothing to annotate.
        del axis_names
        return x
