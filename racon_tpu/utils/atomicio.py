"""Durable file writes: the one place the tmp+rename+fsync dance lives.

Three call sites used to hand-roll (or skip) crash-safe output: bench.py's
JSON artifact, the tracer's JSONL finalization (obs/trace.py), and the
resilience checkpoint store (resilience/checkpoint.py). They now share
these helpers, so every file the toolchain promises to be "complete or
absent" goes through the same sequence:

1. write to ``<path>.tmp.<pid>`` in the destination directory (same
   filesystem, so the rename is atomic),
2. flush + ``os.fsync`` the tmp file (data durable before it becomes
   visible),
3. ``os.replace`` onto the final name (readers see old-or-new, never a
   torn file),
4. best-effort fsync of the directory (the rename itself durable).

Appending stores (the checkpoint shard/manifest) instead use
:func:`append_fsync` per record and rely on record ordering for
atomicity — the caller documents which write commits.
"""

from __future__ import annotations

import os
from typing import Union


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync so a rename/append survives power
    loss; silently skipped where directories cannot be opened (e.g.
    some network filesystems)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (tmp + fsync + rename)."""
    d = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(d)


def atomic_write_text(path: str, text: str,
                      encoding: str = "utf-8") -> None:
    atomic_write_bytes(path, text.encode(encoding))


def atomic_finalize(tmp_path: str, final_path: str) -> None:
    """Promote an already-written (and closed) tmp file to its final
    name atomically. The caller is responsible for having fsync'd the
    tmp file's contents if it needs durability, not just atomicity."""
    os.replace(tmp_path, final_path)
    fsync_dir(os.path.dirname(os.path.abspath(final_path)))


def append_fsync(fh, data: Union[bytes, str]) -> int:
    """Append one record to an open file and make it durable; returns
    the record's start offset (the caller's manifest pointer)."""
    off = fh.tell()
    fh.write(data)
    fh.flush()
    os.fsync(fh.fileno())
    return off
