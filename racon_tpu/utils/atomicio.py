"""Durable file writes: the one place the tmp+rename+fsync dance lives.

Three call sites used to hand-roll (or skip) crash-safe output: bench.py's
JSON artifact, the tracer's JSONL finalization (obs/trace.py), and the
resilience checkpoint store (resilience/checkpoint.py). They now share
these helpers, so every file the toolchain promises to be "complete or
absent" goes through the same sequence:

1. write to ``<path>.tmp.<pid>`` in the destination directory (same
   filesystem, so the rename is atomic),
2. flush + ``os.fsync`` the tmp file (data durable before it becomes
   visible),
3. ``os.replace`` onto the final name (readers see old-or-new, never a
   torn file),
4. best-effort fsync of the directory (the rename itself durable).

Appending stores (the checkpoint shard/manifest) instead use
:func:`append_fsync` per record and rely on record ordering for
atomicity — the caller documents which write commits.
"""

from __future__ import annotations

import os
from typing import Optional, Union


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync so a rename/append survives power
    loss; silently skipped where directories cannot be opened (e.g.
    some network filesystems)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (tmp + fsync + rename)."""
    d = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(d)


def atomic_write_text(path: str, text: str,
                      encoding: str = "utf-8") -> None:
    atomic_write_bytes(path, text.encode(encoding))


class atomic_writer:
    """Context manager for STREAMING an atomic write: yields a binary
    file handle on ``<path>.tmp.<pid>``; a clean exit fsyncs and renames
    onto ``path``, any exception unlinks the tmp file and re-raises —
    readers of ``path`` see old-or-new, never a partial, even when the
    writer dies mid-stream (a crash leaves only the orphaned tmp, which
    a rerun under the same pid namespace simply overwrites).

    :func:`atomic_write_bytes` remains the one-shot form; this is for
    producers whose payload is too large or too incremental to buffer
    (the distributed ledger's merged FASTA).
    """

    def __init__(self, path: str):
        self.path = path
        self.tmp = f"{path}.tmp.{os.getpid()}"
        self._fh = None

    def __enter__(self):
        self._fh = open(self.tmp, "wb")
        return self._fh

    def __exit__(self, exc_type, exc, tb) -> bool:
        fh = self._fh
        self._fh = None
        if exc_type is not None:
            try:
                fh.close()
            finally:
                try:
                    os.remove(self.tmp)
                except OSError:
                    pass
            return False
        fh.flush()
        os.fsync(fh.fileno())
        fh.close()
        os.replace(self.tmp, self.path)
        fsync_dir(os.path.dirname(os.path.abspath(self.path)))
        return False


def atomic_finalize(tmp_path: str, final_path: str) -> None:
    """Promote an already-written (and closed) tmp file to its final
    name atomically. The caller is responsible for having fsync'd the
    tmp file's contents if it needs durability, not just atomicity."""
    os.replace(tmp_path, final_path)
    fsync_dir(os.path.dirname(os.path.abspath(final_path)))


def append_fsync(fh, data: Union[bytes, str],
                 sync_dir: Optional[str] = None) -> int:
    """Append one record to an open file and make it durable; returns
    the record's start offset (the caller's manifest pointer).

    The offset is taken by seeking to the end first, so a handle that
    raced another appender (the distributed steal window) still records
    where *its* bytes landed, not a stale position.

    ``sync_dir``: also fsync the containing directory. File fsync alone
    does not make the file's *directory entry* durable — a freshly
    created store could lose whole files (committed contigs included)
    on power loss. Callers pass the directory on the first append after
    creating a file; later appends don't need it.
    """
    off = fh.seek(0, os.SEEK_END)
    fh.write(data)
    fh.flush()
    os.fsync(fh.fileno())
    if sync_dir is not None:
        fsync_dir(sync_dir)
    return off


def publish_exclusive(path: str, data: bytes) -> bool:
    """Atomically publish ``data`` at ``path`` iff nothing is there yet.

    The first-claim primitive of the distributed work ledger: the bytes
    are fully written and fsync'd in a tmp file, then ``os.link``ed to
    the final name — link fails with EEXIST if any other process
    published first, so readers only ever see complete files and
    exactly one publisher wins. Returns True for the winner.
    """
    d = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.pub.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    try:
        os.link(tmp, path)
        won = True
    except FileExistsError:
        won = False
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
    if won:
        fsync_dir(d)
    return won


def load_jsonl_prefix(path: str, validate=None):
    """Read a JSONL file's longest valid record prefix.

    Crash-tolerant by construction: a final partially-written line (no
    trailing newline — a torn append), a JSON-invalid line, a non-object
    record, or a record ``validate(rec)`` rejects all end the prefix
    there instead of raising — everything before it is still trusted.
    Returns ``(records, clean)``; ``clean`` is False when anything was
    dropped, so callers know to rewrite the file.
    """
    import json
    with open(path, "rb") as fh:
        raw = fh.read()
    records = []
    lines = raw.split(b"\n")
    clean = not lines or lines[-1] == b""
    for line in lines[:-1] if lines else []:
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("non-object JSONL record")
            if validate is not None:
                validate(rec)
        except (ValueError, KeyError, TypeError, AttributeError):
            clean = False
            break
        records.append(rec)
    return records, clean
