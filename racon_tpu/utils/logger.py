"""Phase timing + progress logging (logger equivalent).

Mirrors the reference's vendored logger API as used by the Polisher
(reference: src/polisher.cpp:144,159,170-509): ``()`` starts/resets a
phase timer, ``("msg")`` prints elapsed time + message, ``["msg"]`` ticks
a 20-step progress bar, ``total("msg")`` prints total runtime. All output
goes to stderr so stdout stays clean FASTA.

Two extensions over the reference:

- When stderr is not a TTY (log files, CI pipes), ``tick`` falls back to
  one plain newline-terminated line per tick instead of ``\\r``-redrawing
  the bar — a redrawn bar in a log file is one garbled mega-line.
- Every completed phase is also emitted as a ``phase`` span through the
  structured tracer (racon_tpu/obs/trace.py) — a no-op unless
  RACON_TPU_TRACE / --trace is set.
- Output is serialized by a per-logger lock so pipeline stage threads
  (racon_tpu/pipeline/) can share one logger without interleaving
  mid-line; :meth:`with_prefix` hands a stage a tagged view that shares
  the parent's lock, timers, and bar state.
"""

from __future__ import annotations

import sys
import threading
import time


class Logger:
    def __init__(self, stream=None):
        self.stream = stream if stream is not None else sys.stderr
        isatty = getattr(self.stream, "isatty", None)
        try:
            self._tty = bool(isatty()) if isatty is not None else False
        except Exception:
            self._tty = False
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._phase_t0 = self._t0
        self._bar = 0          # progress position, 0..20
        self._bar_open = False  # TTY only: a partial '\r' line is on screen

    def begin(self) -> None:
        """Start/reset the phase timer — the reference's ``(*logger)()``."""
        with self._lock:
            self._phase_t0 = time.perf_counter()
            self._bar = 0

    def _close_bar(self) -> None:
        """End a partially drawn '\\r' bar line so the next print starts
        fresh (no-op when the stream gets complete lines)."""
        if self._bar_open:
            print(file=self.stream)
            self._bar_open = False

    def phase(self, msg: str) -> None:
        """Print elapsed phase time — the reference's ``(*logger)("msg")``."""
        with self._lock:
            self._close_bar()
            self._bar = 0
            elapsed = time.perf_counter() - self._phase_t0
            print(f"{msg} {elapsed:.6f} s", file=self.stream)
        from racon_tpu.obs.metrics import record_phase_seconds
        from racon_tpu.obs.trace import get_tracer
        get_tracer().emit("phase", msg, self._phase_t0, elapsed)
        # Always-on counterpart of the trace span: per-phase seconds in
        # the metrics registry feed the fleet aggregator even when
        # tracing is off (racon_tpu/obs/fleet.py).
        record_phase_seconds(msg, elapsed)

    def tick(self, msg: str) -> None:
        """Advance a 20-step progress bar — ``(*logger)["msg"]``."""
        with self._lock:
            self._bar = min(self._bar + 1, 20)
            bar = "=" * self._bar + " " * (20 - self._bar)
            elapsed = time.perf_counter() - self._phase_t0
            if self._tty:
                end = "\n" if self._bar == 20 else ""
                print(f"\r{msg} [{bar}] {elapsed:.6f} s", end=end,
                      file=self.stream, flush=True)
                self._bar_open = self._bar != 20
            else:
                # Non-TTY: '\r' never erases, so a redrawn bar would land
                # as one garbled mega-line; print a complete line per tick.
                print(f"{msg} [{bar}] {elapsed:.6f} s", file=self.stream,
                      flush=True)
            if self._bar == 20:
                self._bar = 0

    def line(self, msg: str) -> None:
        """Print a plain diagnostic line (closing any partial bar)."""
        with self._lock:
            self._close_bar()
            print(msg, file=self.stream)

    def total(self, msg: str) -> None:
        """Print total wall time — the reference's ``logger->total()``."""
        with self._lock:
            elapsed = time.perf_counter() - self._t0
            print(f"{msg} {elapsed:.6f} s", file=self.stream)

    def with_prefix(self, prefix: str) -> "Logger":
        """A view of this logger that prefixes every message — lets a
        pipeline stage tag its output (``log.with_prefix("[pack] ")``)
        while sharing the parent's lock, timers, and bar state, so
        concurrent stages never interleave mid-line."""
        return _PrefixLogger(self, prefix)


class _PrefixLogger:
    """with_prefix view: delegates to the parent with tagged messages."""

    __slots__ = ("_parent", "_prefix")

    def __init__(self, parent: Logger, prefix: str):
        self._parent = parent
        self._prefix = prefix

    @property
    def stream(self):
        return self._parent.stream

    def begin(self) -> None:
        self._parent.begin()

    def phase(self, msg: str) -> None:
        self._parent.phase(self._prefix + msg)

    def tick(self, msg: str) -> None:
        self._parent.tick(self._prefix + msg)

    def line(self, msg: str) -> None:
        self._parent.line(self._prefix + msg)

    def total(self, msg: str) -> None:
        self._parent.total(self._prefix + msg)

    def with_prefix(self, prefix: str) -> "_PrefixLogger":
        return _PrefixLogger(self._parent, self._prefix + prefix)


class NullLogger(Logger):
    """Silent logger for tests/library use."""

    def __init__(self):
        super().__init__(stream=_NullStream())

    def begin(self) -> None:
        pass

    def phase(self, msg: str) -> None:
        pass

    def tick(self, msg: str) -> None:
        pass

    def line(self, msg: str) -> None:
        pass

    def total(self, msg: str) -> None:
        pass

    def with_prefix(self, prefix: str) -> "NullLogger":
        return self


class _NullStream:
    """Inert stream so NullLogger never touches a real fd."""

    def isatty(self) -> bool:
        return False

    def write(self, s: str) -> int:
        return len(s)

    def flush(self) -> None:
        pass
