"""Phase timing + progress logging (logger equivalent).

Mirrors the reference's vendored logger API as used by the Polisher
(reference: src/polisher.cpp:144,159,170-509): ``()`` starts/resets a
phase timer, ``("msg")`` prints elapsed time + message, ``["msg"]`` ticks
a 20-step progress bar, ``total("msg")`` prints total runtime. All output
goes to stderr so stdout stays clean FASTA.
"""

from __future__ import annotations

import sys
import time


class Logger:
    def __init__(self, stream=None):
        self.stream = stream if stream is not None else sys.stderr
        self._t0 = time.perf_counter()
        self._phase_t0 = self._t0
        self._bar = 0

    def begin(self) -> None:
        """Start/reset the phase timer — the reference's ``(*logger)()``."""
        self._phase_t0 = time.perf_counter()
        self._bar = 0

    def phase(self, msg: str) -> None:
        """Print elapsed phase time — the reference's ``(*logger)("msg")``."""
        if self._bar:
            # Close a partially drawn progress bar so this line starts
            # fresh instead of appending to the '\r' bar.
            print(file=self.stream)
            self._bar = 0
        elapsed = time.perf_counter() - self._phase_t0
        print(f"{msg} {elapsed:.6f} s", file=self.stream)

    def tick(self, msg: str) -> None:
        """Advance a 20-step progress bar — ``(*logger)["msg"]``."""
        self._bar = min(self._bar + 1, 20)
        bar = "=" * self._bar + " " * (20 - self._bar)
        elapsed = time.perf_counter() - self._phase_t0
        end = "\n" if self._bar == 20 else ""
        print(f"\r{msg} [{bar}] {elapsed:.6f} s", end=end,
              file=self.stream, flush=True)
        if self._bar == 20:
            self._bar = 0

    def total(self, msg: str) -> None:
        """Print total wall time — the reference's ``logger->total()``."""
        elapsed = time.perf_counter() - self._t0
        print(f"{msg} {elapsed:.6f} s", file=self.stream)

    def sched_summary(self, telem) -> None:
        """One-line convergence-scheduler telemetry (a SchedTelemetry
        from racon_tpu/sched/ — keys documented in docs/SCHEDULER.md)."""
        if self._bar:
            print(file=self.stream)
            self._bar = 0
        print("[racon_tpu::Polisher::polish] scheduler " + telem.summary(),
              file=self.stream)


class NullLogger(Logger):
    """Silent logger for tests/library use."""

    def __init__(self):
        super().__init__(stream=None)

    def begin(self) -> None:
        pass

    def phase(self, msg: str) -> None:
        pass

    def tick(self, msg: str) -> None:
        pass

    def total(self, msg: str) -> None:
        pass

    def sched_summary(self, telem) -> None:
        pass
