"""Admission + cross-request batching for the resident daemon.

A one-shot CLI run hands the engine 8192-window chunks, so the chip's
batch dimension is always full. A service does not get that for free:
individual requests are small (a few contigs → a handful of windows),
and dispatching each job's windows alone would run the device at a few
percent occupancy. This module restores the full batch by packing
windows from EVERY in-flight job into one ``consensus_windows``
dispatch.

Correctness lean: window consensus is per-window deterministic and
independent of batch composition — the invariant the serial-vs-
streaming differential tests have pinned since PR 3 (the engine
buckets windows by shape internally, exactly as it does for one job's
mixed-size windows). So cross-job mixing can change throughput and
latency, never bytes; the server smoke byte-diffs every job against a
solo CLI run to hold the claim.

Mechanics:

- Job threads split their window chunks into capacity-sized work items
  and push them through one bounded MPMC admission queue
  (``pipeline/queues.py`` — a full queue blocks the submitter, which
  is the admission control), then block on their items' completion.
- A single dispatcher thread — the sole owner of device compute —
  stages arrivals into per-tenant FIFOs and composes batches
  round-robin across tenants (one item per tenant per pass), so a
  tenant flooding the queue cannot starve the others; a batch
  dispatches when full, or once its oldest item has waited
  ``RACON_TPU_SERVE_BATCH_WAIT_S`` (the latency floor a lone request
  pays for the chance to share the chip).
- Every dispatch runs under the ``serve/dispatch`` fault site and a
  dispatch-class watchdog deadline scaled by the batch's cell volume
  (ops/budget.py), so a wedged device turns into a typed error on the
  affected jobs instead of a silent hang.
- With a :class:`~racon_tpu.cache.memo.WindowMemo` attached (Tier 2
  of the result cache, docs/CACHE.md), each window is probed by
  content digest *before* it is packed into a work item: hits take
  their memoized consensus in place and never reach the device, so a
  job partially overlapping earlier work dispatches only the delta.
  Misses are memoized after their dispatch retires. ``memo=None``
  (the ``RACON_TPU_CACHE=0`` path) is byte-for-byte today's behavior.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from racon_tpu.pipeline.queues import (BoundedQueue, PipelineAborted,
                                       QueueClosed, QueueTimeout)
from racon_tpu.utils import envspec

ENV_BATCH = "RACON_TPU_SERVE_BATCH"
ENV_BATCH_WAIT = "RACON_TPU_SERVE_BATCH_WAIT_S"
ENV_QUEUE = "RACON_TPU_SERVE_QUEUE"


class ServeError(RuntimeError):
    """A job's dispatch failed inside the shared batcher."""


def batch_capacity() -> int:
    cap = int(envspec.read(ENV_BATCH))
    if cap < 1:
        raise ValueError(
            f"[racon_tpu::serve] {ENV_BATCH} must be >= 1, got {cap}")
    return cap


def batch_wait_s() -> float:
    w = float(envspec.read(ENV_BATCH_WAIT))
    if w < 0:
        raise ValueError(
            f"[racon_tpu::serve] {ENV_BATCH_WAIT} must be >= 0, "
            f"got {w}")
    return w


def queue_capacity() -> int:
    cap = int(envspec.read(ENV_QUEUE))
    if cap < 1:
        raise ValueError(
            f"[racon_tpu::serve] {ENV_QUEUE} must be >= 1, got {cap}")
    return cap


class _WorkItem:
    __slots__ = ("job_id", "tenant", "windows", "enq_t", "done",
                 "error", "polished", "trace")

    def __init__(self, job_id: str, tenant: str, windows: List,
                 trace=None):
        self.job_id = job_id
        self.tenant = tenant
        self.windows = windows
        self.enq_t = time.perf_counter()
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.polished = 0
        #: Trace-context rider (obs/trace.TraceContext or None): the
        #: dispatch span names every trace it served.
        self.trace = trace


class CrossRequestBatcher:
    """One dispatcher over one engine, fed by many jobs' threads.

    ``engine`` needs only ``consensus_windows(windows) -> int`` filling
    each window's consensus in place — the real PoaEngine in the
    daemon, a stub in the unit tests.
    """

    def __init__(self, engine, capacity: Optional[int] = None,
                 wait_s: Optional[float] = None,
                 queue_cap: Optional[int] = None, memo=None):
        self.engine = engine
        self.memo = memo
        self.capacity = capacity if capacity is not None \
            else batch_capacity()
        self.wait_s = wait_s if wait_s is not None else batch_wait_s()
        self._admit = BoundedQueue(
            "serve_admit",
            queue_cap if queue_cap is not None else queue_capacity())
        self._staged: Dict[str, deque] = {}   # dispatcher-thread only
        self._rr: List[str] = []              # dispatcher-thread only
        self._staged_windows = 0              # dispatcher-thread only
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "CrossRequestBatcher":
        self._thread = threading.Thread(target=self._run,
                                        name="serve-batcher",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop admitting; the dispatcher drains staged work and
        exits. Blocked submitters see the close as an error."""
        self._admit.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def abort(self) -> None:
        self._admit.abort()

    # ----------------------------------------------------------- job side

    def consensus(self, job_id: str, tenant: str, windows: List,
                  trace=None) -> int:
        """Blockingly run consensus for one job's window chunk through
        the shared batch stream; returns the number polished. Raises
        :class:`ServeError` if the dispatch carrying any slice failed.
        """
        if not windows:
            return 0
        pending = windows
        n_memo = 0
        if self.memo is not None:
            # Tier-2 probe: memoized windows take their consensus in
            # place and never enter the dispatch stream, so only the
            # delta reaches the device (serve_batch_windows counts it).
            from racon_tpu.obs.metrics import record_cache
            pending, hits = [], []
            for w in windows:
                val = self.memo.get(w)
                if val is None:
                    pending.append(w)
                else:
                    w.consensus, w.polished = val
                    hits.append(w)
            if hits:
                record_cache("window", "hit", n=len(hits))
            if pending:
                record_cache("window", "miss", n=len(pending))
            n_memo = sum(1 for w in hits if w.polished)
            if not pending:
                return n_memo
        items = [_WorkItem(job_id, tenant,
                           pending[s:s + self.capacity], trace=trace)
                 for s in range(0, len(pending), self.capacity)]
        for it in items:
            self._admit.put(it)  # blocks at capacity: admission control
        from racon_tpu.obs.metrics import registry
        registry().max("serve_queue_depth_peak", self._admit.depth)
        n = 0
        for it in items:
            it.done.wait()
            if it.error is not None:
                raise ServeError(
                    f"[racon_tpu::serve] job {it.job_id}: batch "
                    f"dispatch failed: {it.error}") from it.error
            n += it.polished
        if self.memo is not None:
            from racon_tpu.obs.metrics import record_cache
            stored = nbytes = 0
            for w in pending:
                sz = self.memo.put(w)
                if sz is not None:
                    stored += 1
                    nbytes += sz
            if stored:
                record_cache("window", "store", n=stored, nbytes=nbytes)
        return n + n_memo

    # ---------------------------------------------------- dispatcher side

    def _stage(self, item: _WorkItem) -> None:
        dq = self._staged.get(item.tenant)
        if dq is None:
            dq = self._staged[item.tenant] = deque()
            self._rr.append(item.tenant)
        dq.append(item)
        self._staged_windows += len(item.windows)

    def _oldest_enq(self) -> float:
        return min(dq[0].enq_t for dq in self._staged.values() if dq)

    def _compose(self) -> List[_WorkItem]:
        """Round-robin one item per tenant per pass until the batch is
        full — per-tenant fairness by construction: with T tenants
        staged, each is guaranteed ~1/T of every batch regardless of
        queue arrival order."""
        batch: List[_WorkItem] = []
        total = 0
        while total < self.capacity:
            progressed = False
            for tenant in list(self._rr):
                dq = self._staged.get(tenant)
                if not dq:
                    continue
                if batch and total + len(dq[0].windows) > self.capacity:
                    continue
                item = dq.popleft()
                self._staged_windows -= len(item.windows)
                batch.append(item)
                total += len(item.windows)
                progressed = True
                if total >= self.capacity:
                    break
            if not progressed:
                break
        # Rotate the starting tenant so ties don't always favor the
        # earliest joiner.
        if self._rr:
            self._rr.append(self._rr.pop(0))
        return batch

    def _dispatch(self, batch: List[_WorkItem]) -> None:
        from racon_tpu.obs.metrics import record_serve_batch
        from racon_tpu.ops.budget import dispatch_deadline_s
        from racon_tpu.resilience.faults import maybe_fault
        from racon_tpu.resilience.watchdog import guard

        windows = [w for it in batch for w in it.windows]
        wait_s = sum(time.perf_counter() - it.enq_t for it in batch)
        # Forward-plane cell volume drives the deadline, same model as
        # the engine's own dispatch class (ops/budget.py).
        cells = sum(len(w) * (w.n_layers + 1) for w in windows)
        t0 = time.perf_counter()
        try:
            maybe_fault("serve/dispatch")
            guard("serve/dispatch", dispatch_deadline_s(cells),
                  self.engine.consensus_windows, windows)
        except BaseException as exc:  # noqa: BLE001 — fanned back out per job
            for it in batch:
                it.error = exc
        else:
            for it in batch:
                it.polished = sum(1 for w in it.windows if w.polished)
        finally:
            for it in batch:
                it.done.set()
        record_serve_batch(
            n_windows=len(windows), capacity=self.capacity,
            jobs=sorted({it.job_id for it in batch}),
            tenants=sorted({it.tenant for it in batch}), wait_s=wait_s,
            round_s=time.perf_counter() - t0,
            trace_ids=[it.trace.trace_id for it in batch if it.trace],
            parent_ids=[it.trace.parent_id for it in batch if it.trace])

    def _run(self) -> None:
        closed = False
        while not (closed and self._staged_windows == 0):
            if self._staged_windows == 0:
                try:
                    self._stage(self._admit.get())
                except QueueClosed:
                    closed = True
                    continue
                except PipelineAborted:
                    return
            # Top up: wait for more work until the batch fills or the
            # oldest staged item's flush deadline lapses.
            while self._staged_windows < self.capacity and not closed:
                left = self._oldest_enq() + self.wait_s \
                    - time.perf_counter()
                if left <= 0:
                    break
                try:
                    self._stage(self._admit.get(timeout=left))
                except QueueTimeout:
                    break
                except QueueClosed:
                    closed = True
                except PipelineAborted:
                    return
            batch = self._compose()
            if batch:
                self._dispatch(batch)


class BatchedEngineProxy:
    """Engine facade handed to each job's Polisher: consensus routes
    through the shared cross-request batcher; everything else (backend
    probing, scheduler telemetry) forwards to the real engine, so the
    Polisher cannot tell it is sharing the chip."""

    def __init__(self, batcher: CrossRequestBatcher, job_id: str,
                 tenant: str, trace=None):
        self._batcher = batcher
        self._job_id = job_id
        self._tenant = tenant
        self._trace = trace

    def consensus_windows(self, windows: List) -> int:
        return self._batcher.consensus(self._job_id, self._tenant,
                                       windows, trace=self._trace)

    def __getattr__(self, name: str):
        return getattr(self._batcher.engine, name)
