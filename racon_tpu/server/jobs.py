"""Journaled job lifecycle for the resident daemon.

Every submitted job owns one directory under ``<state-dir>/jobs/``:

- ``job.json`` — the journal record (schema, id, tenant, the full
  :class:`~racon_tpu.server.engine.JobSpec`, current state, error),
  rewritten atomically at every state transition;
- ``ckpt/``    — a standard checkpoint-ledger store
  (resilience/checkpoint.py) holding every durably committed contig.

Together they make the daemon restartable by construction: after a
SIGKILL the journal says which jobs were in flight, and re-running each
through the engine's ``polish_job`` loop against its resumed store
re-emits the committed prefix byte-identically and polishes only the
remainder — the same resume contract the CLI and the distributed
worker already honor, reused rather than reinvented.

Job ids are sequential (``j0001``, ``j0002``, ...), allocated as
max-existing + 1 so a restarted daemon never reuses or reorders ids —
no clocks, no randomness, nothing to collide after recovery.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from racon_tpu.obs.trace import TraceContext, parse_trace_ctx
from racon_tpu.server.engine import JobSpec
from racon_tpu.utils.atomicio import atomic_write_text

SCHEMA = 1
JOB_FILE = "job.json"
CKPT_DIR = "ckpt"

#: Lifecycle: queued -> running -> done | failed | cancelled.
STATES = ("queued", "running", "done", "failed", "cancelled")
TERMINAL = ("done", "failed", "cancelled")


class JobCancelled(Exception):
    """Raised inside a job's polish loop when its cancel flag is set."""


class Job:
    """One submitted polishing job: journal record + result stream.
    The stream is a :class:`~racon_tpu.ava.emit.RecordSpool` — a plain
    in-memory chunk list for kC-sized results, spilling to a
    job-directory scratch file past ``RACON_TPU_SERVE_SPOOL_MB`` so an
    ava job's millions of records never pin millions of live objects.
    The spool is internally locked; runner appends and HTTP streamer
    reads interleave safely."""

    __slots__ = ("id", "tenant", "spec", "directory", "state", "error",
                 "spool", "cancel", "finished", "n_committed",
                 "trace", "t_submit")

    def __init__(self, job_id: str, tenant: str, spec: JobSpec,
                 directory: str, state: str = "queued",
                 error: Optional[str] = None,
                 trace: Optional[TraceContext] = None):
        from racon_tpu.ava.emit import RecordSpool
        self.id = job_id
        self.tenant = tenant
        self.spec = spec
        self.directory = directory
        self.state = state
        self.error = error
        self.spool = RecordSpool(directory)
        self.cancel = threading.Event()
        self.finished = threading.Event()
        self.n_committed = 0
        #: Job-scoped trace context (obs/trace.py), minted at submit and
        #: journaled so a restarted daemon keeps the job's trace_id.
        self.trace = trace
        self.t_submit = 0.0

    @property
    def ckpt_dir(self) -> str:
        return os.path.join(self.directory, CKPT_DIR)

    # ------------------------------------------------------- results

    def emit(self, blob: bytes) -> None:
        """The ``polish_job`` byte sink — committed-prefix re-emission
        and fresh records arrive here in target order."""
        self.spool.append(blob)

    def result_bytes(self) -> bytes:
        return self.spool.read_all()

    # ------------------------------------------------------- journal

    def persist(self) -> None:
        """Atomically rewrite the journal record (state transition)."""
        record = {"schema": SCHEMA, "id": self.id,
                  "tenant": self.tenant, "state": self.state,
                  "error": self.error, "spec": self.spec.as_dict(),
                  "trace": self.trace.encode() if self.trace else ""}
        atomic_write_text(os.path.join(self.directory, JOB_FILE),
                          json.dumps(record, sort_keys=True) + "\n")

    @classmethod
    def load(cls, directory: str) -> "Job":
        with open(os.path.join(directory, JOB_FILE), "r",
                  encoding="utf-8") as fh:
            record = json.load(fh)
        if record.get("schema") != SCHEMA:
            raise ValueError(
                f"[racon_tpu::serve] {directory}: unknown job journal "
                f"schema {record.get('schema')!r}")
        return cls(str(record["id"]), str(record["tenant"]),
                   JobSpec.from_dict(record["spec"]), directory,
                   state=str(record["state"]),
                   error=record.get("error"),
                   trace=parse_trace_ctx(str(record.get("trace", ""))))

    def status(self) -> Dict[str, object]:
        """JSON-ready view for the HTTP status endpoints."""
        return {"id": self.id, "tenant": self.tenant,
                "state": self.state, "error": self.error,
                "committed": self.n_committed,
                "bytes": self.spool.total_bytes,
                "trace": self.trace.encode() if self.trace else ""}


# ------------------------------------------------------------ directory

def allocate_id(jobs_root: str) -> str:
    """Next sequential job id under ``jobs_root`` (caller holds the
    server's submit lock)."""
    seq = 0
    if os.path.isdir(jobs_root):
        for name in os.listdir(jobs_root):
            if name.startswith("j") and name[1:].isdigit():
                seq = max(seq, int(name[1:]))
    return f"j{seq + 1:04d}"


def scan(jobs_root: str) -> List[Job]:
    """Load every journaled job, oldest first (restart recovery)."""
    out: List[Job] = []
    if not os.path.isdir(jobs_root):
        return out
    for name in sorted(os.listdir(jobs_root)):
        directory = os.path.join(jobs_root, name)
        if os.path.isfile(os.path.join(directory, JOB_FILE)):
            out.append(Job.load(directory))
    return out


def open_store(job: Job):
    """The job's checkpoint store: resumed when its meta exists (daemon
    restart), created fresh otherwise. Identity runs through
    JobSpec.fingerprint(), so a tampered input or edited spec refuses
    to resume instead of silently mixing outputs. Fresh stores for
    fragment-correction jobs get the v2 segmented manifest
    (ava.seg_targets_for); resumed stores keep whatever flavor their
    header records."""
    from racon_tpu.ava import seg_targets_for
    from racon_tpu.resilience.checkpoint import CheckpointStore
    fingerprint = job.spec.fingerprint()
    probe = CheckpointStore(job.ckpt_dir, fingerprint)
    if os.path.isfile(probe.meta_path):
        return CheckpointStore.resume(job.ckpt_dir, fingerprint)
    return CheckpointStore.create(
        job.ckpt_dir, fingerprint,
        segment_targets=seg_targets_for(job.spec.fragment_correction))


def rebuild_result(job: Job) -> None:
    """Reload a terminal job's emitted bytes from its store (restart
    made the in-memory stream empty). Committed shard slices are the
    exact originally emitted bytes, so the rebuilt stream is identical
    to what the pre-restart daemon served."""
    from racon_tpu.resilience.checkpoint import CheckpointStore
    store = CheckpointStore.resume(job.ckpt_dir,
                                   job.spec.fingerprint())
    try:
        job.spool.reset()
        for tid in sorted(store.committed):
            blob = store.read_emitted(tid)
            if blob is not None:
                job.spool.append(blob)
        job.n_committed = len(store.committed)
    finally:
        store.close()
