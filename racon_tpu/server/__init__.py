"""Polishing-as-a-service: the resident daemon and its engine library.

One process, many polishing jobs. The package splits into three layers
(docs/SERVER.md):

- :mod:`racon_tpu.server.engine` — the embeddable engine API every
  frontend shares. ``JobSpec`` is the single source of a run's
  output-affecting identity (the checkpoint fingerprint config),
  ``polish_job`` is the one resume-aware polish/commit/emit loop, and
  ``EngineSession`` owns warm compile-cache state so a resident process
  pays compilation exactly once per shape bucket. The serial CLI and
  the distributed ledger worker are thin frontends over this module.
- :mod:`racon_tpu.server.batch` — the admission + cross-request
  batcher: windows from multiple in-flight jobs pack into one device
  dispatch so the chip never runs a partial batch just because
  individual requests are small; per-tenant round-robin keeps one
  noisy tenant from starving the rest.
- :mod:`racon_tpu.server.daemon` — the long-lived HTTP daemon:
  journaled job lifecycle (submit/status/stream/cancel) persisted
  through the checkpoint store, so a daemon restart — SIGTERM or
  ``kill -9`` — resumes every in-flight job byte-identically.
"""

from racon_tpu.server.engine import (EngineSession, JobHooks, JobSpec,
                                     build_polisher, polish_job)

__all__ = ["EngineSession", "JobHooks", "JobSpec", "build_polisher",
           "polish_job"]
