"""The embeddable polishing engine: one library, thin frontends.

Before this module, three callers each hand-rolled the same sequence —
build a Polisher from option values, initialize, skip committed
targets, drive ``Polisher.polish_records`` (polisher.py:396), interleave
checkpoint re-emission with fresh records, commit each record durably:
the serial CLI (cli.py), the distributed ledger worker
(distributed/worker.py), and now the resident daemon (server/daemon.py).
The loop is subtle enough that the copies had already grown distinct
bug surfaces (stored-blob interleaving existed only in the CLI, the
zero-window fill-drop pass only in the worker). This module is the one
implementation; frontends differ only in the hooks they install.

Identity is the other deduplicated concern: :meth:`JobSpec.identity`
is the SINGLE source of the output-affecting config dict that feeds
``run_fingerprint`` — the CLI's checkpoint store, the ledger, and the
daemon's job journal all fingerprint through it, so a daemon job and a
solo CLI run of the same inputs agree byte-for-byte on what "the same
run" means.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from racon_tpu import __version__


class JobSpec:
    """Everything that defines one polishing job: the three input paths
    plus every output-affecting option, with the CLI's defaults.

    Execution knobs (backend, threads, mesh, pipeline) are deliberately
    NOT identity: the execution paths are bit-identical by design, so
    two runs differing only in how they execute share a fingerprint —
    exactly the contract cli.py's ``ckpt_config`` established.
    """

    __slots__ = ("sequences", "overlaps", "targets", "include_unpolished",
                 "fragment_correction", "window_length",
                 "quality_threshold", "error_threshold", "match",
                 "mismatch", "gap", "backend", "threads")

    def __init__(self, sequences: str, overlaps: str, targets: str, *,
                 include_unpolished: bool = False,
                 fragment_correction: bool = False,
                 window_length: int = 500,
                 quality_threshold: float = 10.0,
                 error_threshold: float = 0.3, match: int = 5,
                 mismatch: int = -4, gap: int = -8,
                 backend: str = "auto", threads: int = 1):
        self.sequences = sequences
        self.overlaps = overlaps
        self.targets = targets
        self.include_unpolished = bool(include_unpolished)
        self.fragment_correction = bool(fragment_correction)
        self.window_length = int(window_length)
        self.quality_threshold = float(quality_threshold)
        self.error_threshold = float(error_threshold)
        self.match = int(match)
        self.mismatch = int(mismatch)
        self.gap = int(gap)
        self.backend = backend
        self.threads = int(threads)

    @property
    def paths(self) -> List[str]:
        return [self.sequences, self.overlaps, self.targets]

    def identity(self) -> Dict[str, object]:
        """The output-affecting config dict — key-for-key the dict
        cli.py fed ``run_fingerprint`` since PR 4, so fingerprints are
        stable across the extraction."""
        return {
            "version": __version__,
            "include_unpolished": self.include_unpolished,
            "fragment_correction": self.fragment_correction,
            "window_length": self.window_length,
            "quality_threshold": self.quality_threshold,
            "error_threshold": self.error_threshold,
            "match": self.match,
            "mismatch": self.mismatch,
            "gap": self.gap,
        }

    def fingerprint(self) -> str:
        from racon_tpu.resilience.checkpoint import run_fingerprint
        return run_fingerprint(self.identity(), self.paths)

    # ------------------------------------------------------- serialization

    def as_dict(self) -> Dict[str, object]:
        """JSON-safe form for the daemon's job journal."""
        d = {"sequences": self.sequences, "overlaps": self.overlaps,
             "targets": self.targets}
        d.update({k: getattr(self, k) for k in self.__slots__[3:]})
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "JobSpec":
        kwargs = {k: d[k] for k in cls.__slots__[3:] if k in d}
        return cls(str(d["sequences"]), str(d["overlaps"]),
                   str(d["targets"]), **kwargs)


def build_polisher(spec: JobSpec, logger=None, mesh=None, engine=None):
    """Construct an (uninitialized) Polisher from a :class:`JobSpec`.

    ``engine``: optionally substitute a shared warm :class:`PoaEngine`
    (or the daemon's batching proxy) for the one the Polisher would
    build — the resident-process path, where compiled executables are
    owned by the session, not the job.
    """
    from racon_tpu.models.polisher import PolisherType, create_polisher
    polisher = create_polisher(
        spec.sequences, spec.overlaps, spec.targets,
        PolisherType.kF if spec.fragment_correction else PolisherType.kC,
        spec.window_length, spec.quality_threshold, spec.error_threshold,
        spec.match, spec.mismatch, spec.gap, backend=spec.backend,
        logger=logger, threads=spec.threads, mesh=mesh)
    if engine is not None:
        polisher.engine = engine
    return polisher


class JobHooks:
    """Per-record side-effect hooks threaded through :func:`polish_job`.

    The no-op defaults serve the serial CLI and the daemon; the
    distributed worker installs lease renewal, fault drills, and the
    dynamic shard-shrink (split) protocol through them:

    - ``range_end(default)`` — the loop's CURRENT exclusive end; the
      worker returns ``claim.info.end``, which shrinks when a split
      donates the tail mid-run.
    - ``before_build(first_tid)`` — fires with the first uncommitted
      tid just before the Polisher is constructed (the worker's
      claim-time split evaluation, BEFORE any windows are built).
    - ``on_resume(n_committed, n_windows_skipped)`` — after committed
      targets were pruned (the CLI's resume stderr line).
    - ``before_commit(tid, rec)`` — before the record is emitted and
      committed (worker: fault site, lease renewal, obs flush; daemon:
      cancellation check + ``serve/commit`` fault site).
    - ``after_commit(tid, rec)`` — after the durable commit (worker:
      dist accounting + post-commit split evaluation).
    - ``before_fill(tid)`` — before each zero-window fill-drop commit
      (worker: lease renewal).
    """

    def __init__(self, *, range_end: Optional[Callable] = None,
                 before_build: Optional[Callable] = None,
                 on_resume: Optional[Callable] = None,
                 before_commit: Optional[Callable] = None,
                 after_commit: Optional[Callable] = None,
                 before_fill: Optional[Callable] = None):
        self.range_end = range_end or (lambda default: default)
        self.before_build = before_build or (lambda first_tid: None)
        self.on_resume = on_resume or (lambda n_committed, n_skip: None)
        self.before_commit = before_commit or (lambda tid, rec: None)
        self.after_commit = after_commit or (lambda tid, rec: None)
        self.before_fill = before_fill or (lambda tid: None)


def polish_job(make_polisher: Callable, *, drop_unpolished: bool = True,
               store=None, tid_range: Optional[Tuple[int, int]] = None,
               n_targets: Optional[int] = None,
               emit: Optional[Callable[[bytes], None]] = None,
               fill_drops: bool = False,
               hooks: Optional[JobHooks] = None) -> int:
    """The one polish/commit/emit loop. Returns the number of targets
    in the job's final effective range.

    - ``store``: optional CheckpointStore; committed targets are
      pruned from compute and (when ``emit`` is set) re-emitted
      byte-identically from the shard, interleaved in input order with
      freshly polished records.
    - ``tid_range``: restrict to ``[start, end)`` target ids (the
      distributed shard path); None polishes everything.
    - ``n_targets``: total targets when the caller already knows it
      (skips nothing — it only avoids needing the Polisher when every
      tid in range is committed). With ``tid_range=None`` and
      ``n_targets=None`` the Polisher is always built and its parsed
      target count is used.
    - ``emit``: byte sink for the FASTA stream (stdout for the CLI, the
      job's result buffer for the daemon; the ledger worker passes
      None — its merge phase emits).
    - ``fill_drops``: commit targets that never reach the assembler
      (zero windows) as drops, so "every tid committed" is the
      completion invariant (the worker/daemon contract; the CLI keeps
      its historical manifests, which omit them).
    """
    from racon_tpu.obs.metrics import record_ckpt

    hooks = hooks if hooks is not None else JobHooks()
    committed = store.committed if store is not None else {}
    if tid_range is not None:
        start, end = int(tid_range[0]), int(tid_range[1])
    else:
        start, end = 0, n_targets

    next_tid = start

    def emit_stored(limit: int) -> None:
        # Re-emit committed contigs (exact shard bytes) for every
        # target slot before `limit` — interleaving stored and freshly
        # polished targets in input order keeps resumed output
        # byte-identical to an uninterrupted run's.
        nonlocal next_tid
        while next_tid < limit:
            if emit is not None and store is not None \
                    and next_tid in committed:
                blob = store.read_emitted(next_tid)
                if blob is not None:
                    emit(blob)
                record_ckpt("skip", next_tid,
                            len(blob) if blob else 0)
            next_tid += 1

    build = end is None or any(tid not in committed
                               for tid in range(start, end))
    if build:
        first = start
        while first in committed:
            first += 1
        hooks.before_build(first)
        polisher = make_polisher()
        polisher.initialize()
        if end is None:
            end = polisher._targets_size
        if tid_range is not None:
            polisher.restrict_targets(range(start, end))
        n_skip = polisher.skip_targets(committed) if committed else 0
        hooks.on_resume(len(committed), n_skip)
        # Each contig is handled the moment its last window retires,
        # then durably committed before the next one.
        for tid, rec in polisher.polish_records(drop_unpolished):
            if tid >= hooks.range_end(end):
                break  # range shrank under us (shard split donation)
            hooks.before_commit(tid, rec)
            emit_stored(tid)
            if emit is not None and rec is not None:
                emit(b">" + rec.name.encode() + b"\n" + rec.data +
                     b"\n")
            if store is not None:
                if rec is not None:
                    store.commit(tid, rec.name.encode(), rec.data)
                else:
                    store.commit_dropped(tid)
            hooks.after_commit(tid, rec)
            next_tid = tid + 1
    else:
        hooks.on_resume(len(committed), 0)

    end = hooks.range_end(end)
    if fill_drops and store is not None:
        # Targets with zero windows never reach the assembler, so they
        # yield nothing above — commit them as drops explicitly so the
        # done marker really means "every tid in range accounted for".
        for tid in range(start, end):
            if tid not in committed:
                hooks.before_fill(tid)
                store.commit_dropped(tid)
    emit_stored(end)
    return end - start


class EngineSession:
    """Explicit ownership of a resident process's warm state: the jax
    compile cache and a pool of :class:`PoaEngine` instances keyed by
    scoring parameters, shared across jobs so every job with the same
    scores reuses the same compiled executables (warm start is the
    whole point of the daemon — PROFILE.md's 44.5 s → 12.1 s jaxcache
    row becomes ~0 s for every job after the first per shape bucket).

    Window consensus is per-window deterministic and independent of
    batch composition (the serial-vs-streaming bit-identity invariant,
    differentially tested since PR 3), so sharing one engine — and
    mixing jobs' windows in its batches — cannot change any job's
    bytes.
    """

    def __init__(self):
        self._engines: Dict[tuple, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._activated = False  # guarded-by: _lock

    def activate(self) -> None:
        """Idempotently arm the persistent compile cache."""
        with self._lock:
            if self._activated:
                return
            self._activated = True
        from racon_tpu.utils.jaxcache import enable_compile_cache
        enable_compile_cache()

    def engine_for(self, spec: JobSpec, mesh=None):
        """The session's shared engine for this spec's scoring tuple."""
        from racon_tpu.ops.poa import PoaEngine
        key = (spec.match, spec.mismatch, spec.gap, spec.backend,
               spec.threads)
        with self._lock:
            eng = self._engines.get(key)
            if eng is None:
                eng = PoaEngine(spec.match, spec.mismatch, spec.gap,
                                backend=spec.backend,
                                threads=spec.threads, mesh=mesh)
                self._engines[key] = eng
            return eng
