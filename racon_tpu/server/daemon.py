"""Polishing-as-a-service: the resident multi-tenant daemon.

One long-lived process owns the warm state a one-shot CLI pays for on
every invocation — the persistent compile cache, the
:class:`~racon_tpu.server.engine.EngineSession` engine pool, and the
chunk-shape executables those engines hold — and serves polishing jobs
over a local HTTP API:

- ``POST /v1/jobs``              submit ``{tenant, sequences, overlaps,
  targets, options}`` → ``{id}``; the job is journaled before the
  response leaves (``serve/submit`` fault site).
- ``GET  /v1/jobs``              list jobs; ``GET /v1/jobs/<id>`` one
  job's status.
- ``GET  /v1/jobs/<id>/stream``  the job's FASTA bytes so far —
  byte-identical to a solo serial CLI run of the same inputs, the
  server smoke's acceptance gate.
- ``POST /v1/jobs/<id>/cancel``  cooperative cancel at the next contig
  boundary (committed work is kept).
- ``GET  /healthz``              watchdog liveness + a ``serve`` view
  (job table, active count); anything else serves the OpenMetrics
  registry render.

Every job runs the SAME engine loop as the CLI (``polish_job``)
against its own checkpoint store, with the job's device compute routed
through the shared :class:`~racon_tpu.server.batch.CrossRequestBatcher`
— many jobs, one dispatch stream, full batches. Restart recovery is
the checkpoint contract inherited whole: on startup every non-terminal
journaled job is re-queued (``serve_jobs_resumed``), its committed
prefix re-emitted from the shard byte-for-byte, and only the remainder
polished — so SIGKILL mid-job costs at most one uncommitted contig of
rework and zero output differences.

The daemon forces the in-process streaming pipeline off: concurrency
comes from jobs sharing the batcher, not from stages inside one job,
so the dispatcher thread stays the sole owner of device compute.

The content-addressed result cache (racon_tpu/cache/, docs/CACHE.md)
is armed by default (``RACON_TPU_CACHE=0`` disables): a fresh job
whose fingerprint hits the job-level CAS replays its verified contig
records straight into its store and stream — zero device dispatches —
and every batcher carries a window memo so partially-overlapping jobs
dispatch only the delta. Cache state lives under the state dir (or
``RACON_TPU_CACHE_DIR``) and survives restarts via the same
atomic-publication recovery contract as the job journal.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from racon_tpu.cache import (ResultCache, WindowMemo, cache_dir_for,
                             cache_enabled, records_from_store,
                             replay_records, window_memo_enabled)
from racon_tpu.server.batch import BatchedEngineProxy, CrossRequestBatcher
from racon_tpu.server.engine import (EngineSession, JobHooks, JobSpec,
                                     build_polisher, polish_job)
from racon_tpu.server.jobs import (TERMINAL, Job, JobCancelled,
                                   allocate_id, open_store,
                                   rebuild_result, scan)
from racon_tpu.utils import envspec
from racon_tpu.utils.atomicio import atomic_write_text

ENV_MAX_JOBS = "RACON_TPU_SERVE_MAX_JOBS"
ENV_GRACE = "RACON_TPU_SERVE_GRACE_S"

PORT_FILE = "port"


class PolishServer:
    """Job table + engine session + per-scoring-key batchers. All HTTP
    handlers and runner threads converge here; ``_lock`` guards the
    table and batcher pool, never held across polishing work."""

    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        self.jobs_root = os.path.join(state_dir, "jobs")
        os.makedirs(self.jobs_root, exist_ok=True)
        self.session = EngineSession()
        self._jobs: Dict[str, Job] = {}            # guarded-by: _lock
        self._batchers: Dict[Tuple, CrossRequestBatcher] = {}  # guarded-by: _lock
        self._threads: List[threading.Thread] = []  # guarded-by: _lock
        self._n_done = 0                            # guarded-by: _lock
        self._queued = 0                            # guarded-by: _lock
        self._draining = False                      # guarded-by: _lock
        self._lock = threading.Lock()
        self._sem = threading.BoundedSemaphore(
            max(1, int(envspec.read(ENV_MAX_JOBS))))
        self._t0 = time.perf_counter()
        # Tier-1 CAS, on by default for the daemon; the constructor
        # reloads the atomically-published index (journal-aware
        # recovery — no payload re-verification on restart).
        self.cache: Optional[ResultCache] = None
        if cache_enabled():
            self.cache = ResultCache(cache_dir_for(state_dir))

    # ------------------------------------------------------- lifecycle

    def recover(self) -> int:
        """Re-queue every journaled non-terminal job (daemon restart).
        Terminal jobs rejoin the table read-only, their result streams
        rebuilt from their stores so /stream keeps serving the exact
        pre-restart bytes. Returns the number of jobs resumed."""
        from racon_tpu.obs.metrics import record_serve_job
        from racon_tpu.obs.trace import mint_trace_context
        resumed = 0
        for job in scan(self.jobs_root):
            with self._lock:
                self._jobs[job.id] = job
            if job.state in TERMINAL:
                job.finished.set()
                if job.state == "done":
                    rebuild_result(job)
                continue
            job.state = "queued"
            job.t_submit = time.perf_counter()
            # Pre-trace journals (or a torn one) get a fresh root
            # context; jobs journaled with one keep their trace_id so
            # the post-restart spans join the same timeline.
            sid = record_serve_job(
                "resumed", job.id, job.tenant,
                trace_id=job.trace.trace_id if job.trace
                else mint_trace_context(job.spec.fingerprint()).trace_id,
                parent_id=job.trace.parent_id if job.trace else 0)
            if job.trace is None:
                job.trace = mint_trace_context(job.spec.fingerprint(),
                                               parent_id=sid)
            job.persist()
            resumed += 1
            self._launch(job)
        self._update_gauges()
        return resumed

    def drain(self, grace_s: Optional[float] = None) -> bool:
        """Stop admitting, let in-flight jobs finish within the grace
        window, then stop the batchers. Returns True when every runner
        exited in time (the clean-SIGTERM contract)."""
        grace = float(envspec.read(ENV_GRACE)) if grace_s is None \
            else float(grace_s)
        with self._lock:
            self._draining = True
            threads = list(self._threads)
            batchers = list(self._batchers.values())
        deadline = time.perf_counter() + grace
        clean = True
        for t in threads:
            t.join(max(0.0, deadline - time.perf_counter()))
            clean = clean and not t.is_alive()
        for b in batchers:
            b.close()
        return clean

    # ---------------------------------------------------------- job API

    def submit(self, tenant: str, spec: JobSpec) -> Job:
        from racon_tpu.obs.metrics import record_serve_job
        from racon_tpu.obs.trace import mint_trace_context
        from racon_tpu.resilience.faults import maybe_fault
        maybe_fault("serve/submit")
        with self._lock:
            if self._draining:
                raise RuntimeError(
                    "[racon_tpu::serve] daemon is draining; "
                    "not accepting jobs")
            job_id = allocate_id(self.jobs_root)
            directory = os.path.join(self.jobs_root, job_id)
            os.makedirs(directory, exist_ok=True)
            job = Job(job_id, str(tenant), spec, directory)
            self._jobs[job_id] = job
        # The "submitted" point is the job's root span: its trace_id is
        # the spec fingerprint prefix, and its span id becomes the
        # parent of every downstream span (this process or spawned).
        ctx = mint_trace_context(spec.fingerprint())
        sid = record_serve_job("submitted", job.id, job.tenant,
                               trace_id=ctx.trace_id)
        job.trace = mint_trace_context(spec.fingerprint(), parent_id=sid)
        job.t_submit = time.perf_counter()
        # Journaled BEFORE the submit response: a daemon killed right
        # after replying still knows about the job on restart.
        job.persist()
        self._update_gauges()
        self._launch(job)
        return job

    def cancel(self, job_id: str) -> Job:
        job = self.get(job_id)
        if job.state not in TERMINAL:
            job.cancel.set()
        return job

    def get(self, job_id: str) -> Job:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(job_id)
        return job

    def describe(self) -> Dict[str, object]:
        with self._lock:
            jobs = sorted(self._jobs.values(), key=lambda j: j.id)
            draining = self._draining
        active = sum(1 for j in jobs if j.state not in TERMINAL)
        return {"jobs": [j.status() for j in jobs], "active": active,
                "draining": draining}

    # ----------------------------------------------------------- runner

    def _launch(self, job: Job) -> None:
        t = threading.Thread(target=self._run_job, args=(job,),
                             name=f"serve-{job.id}", daemon=True)
        with self._lock:
            self._threads.append(t)
        t.start()

    def _batcher_for(self, spec: JobSpec) -> CrossRequestBatcher:
        # One batcher per scoring key: windows only ever share a
        # dispatch with windows the SAME compiled executables serve.
        key = (spec.match, spec.mismatch, spec.gap, spec.backend,
               spec.threads)
        with self._lock:
            b = self._batchers.get(key)
            if b is None:
                engine = self.session.engine_for(spec)
                memo = None
                if self.cache is not None and window_memo_enabled():
                    # Tier-2 memo, spilling under the cache root; one
                    # memo per scoring key, same sharing rule as the
                    # batcher itself.
                    memo = WindowMemo(
                        key,
                        spill_dir=self.cache.window_spill_dir(key))
                b = self._batchers[key] = \
                    CrossRequestBatcher(engine, memo=memo).start()
            return b

    def _route(self, job: Job, store):
        """The gateway routing decision for one admitted job
        (docs/GATEWAY.md): in-process batcher or autoscaled ledger
        fleet, from the job's target count and the current admission
        queue depth. Recorded as a ``gate`` span + counter so the
        per-job timeline shows the decision between submit and run."""
        from racon_tpu.gateway.dispatch import (decide_route,
                                                fleet_enabled,
                                                fleet_paths,
                                                target_stats)
        from racon_tpu.obs.metrics import record_gate
        n_targets = target_bytes = 0
        if fleet_enabled():
            try:
                n_targets, target_bytes = target_stats(job.spec.targets)
            except Exception:
                n_targets = target_bytes = 0  # unreadable inputs fail
                #                               later, locally
        with self._lock:
            depth = self._queued
        decision = decide_route(job.spec, n_targets, depth,
                                target_bytes=target_bytes)
        if decision.route == "fleet" and store.committed:
            # A job that started locally (committed prefix but no
            # fleet run dir) must finish locally: local stores number
            # every target tid (dropped ones included), fleet replay
            # numbers emitted contigs densely — mixing the two would
            # corrupt the resume.
            run_dir = fleet_paths(self.state_dir,
                                  job.spec.fingerprint()).run_dir
            if not os.path.isdir(run_dir):
                decision = decision._replace(
                    route="local", reason="resume-local-prefix")
        record_gate("route_fleet" if decision.route == "fleet"
                    else "route_local", job.id, job.tenant,
                    trace_id=job.trace.trace_id if job.trace else "-",
                    parent_id=job.trace.parent_id if job.trace else 0,
                    decision=decision.route, reason=decision.reason,
                    n_targets=decision.n_targets,
                    queue_depth=decision.queue_depth,
                    target_bytes=decision.target_bytes)
        return decision

    def _run_fleet(self, job: Job, store) -> None:
        """Execute one fleet-routed job through the gateway adapter
        and finish it exactly like a local run (same journal states,
        same CAS store, same gauges)."""
        from racon_tpu.gateway.dispatch import run_fleet_job
        state, error = "done", None
        try:
            run_fleet_job(
                job, self.state_dir, store,
                trace_ctx=job.trace.encode() if job.trace else "",
                log=sys.stderr)
        except JobCancelled:
            state = "cancelled"
        except Exception as exc:
            state, error = "failed", str(exc)
        else:
            if self.cache is not None:
                # Same Tier-1 store as the local path: a resubmission
                # of this fingerprint replays from the daemon CAS
                # without touching the fleet at all.
                try:
                    self.cache.store(job.spec.fingerprint(),
                                     records_from_store(store))
                except Exception as exc:
                    print(f"[racon_tpu::serve] cache store failed "
                          f"for job {job.id}: {exc}", file=sys.stderr)
        job.n_committed = len(store.committed)
        store.close()
        self._finish(job, state, error)

    def _run_job(self, job: Job) -> None:
        from racon_tpu.obs.metrics import record_hist
        from racon_tpu.resilience.faults import maybe_fault
        with self._lock:
            self._queued += 1
        with self._sem:
            with self._lock:
                self._queued -= 1
            if job.t_submit:
                record_hist("serve_queue_wait_s",
                            time.perf_counter() - job.t_submit)
            if job.cancel.is_set():
                self._finish(job, "cancelled", None)
                return
            job.state = "running"
            job.persist()
            try:
                store = open_store(job)
            except Exception as exc:
                self._finish(job, "failed", str(exc))
                return
            job.n_committed = len(store.committed)
            if self.cache is not None and not store.committed:
                # Tier-1 probe (fresh jobs only — a resumed job's
                # committed prefix already owns the store): a verified
                # CAS hit replays the whole result through the same
                # emit-then-commit order polish_job uses, so /stream,
                # the journal, and restart recovery are identical to a
                # fresh run — with zero device dispatches.
                records = self.cache.load(job.spec.fingerprint())
                if records is not None:
                    try:
                        replay_records(records, emit=job.emit,
                                       store=store)
                    except Exception as exc:
                        job.n_committed = len(store.committed)
                        store.close()
                        self._finish(job, "failed", str(exc))
                        return
                    job.n_committed = len(store.committed)
                    store.close()
                    self._finish(job, "done", None)
                    return
            decision = self._route(job, store)
            if decision.route == "fleet":
                self._run_fleet(job, store)
                return
            proxy = BatchedEngineProxy(self._batcher_for(job.spec),
                                       job.id, job.tenant,
                                       trace=job.trace)

            def before_commit(tid, rec):
                if job.cancel.is_set():
                    raise JobCancelled(job.id)
                maybe_fault("serve/commit")

            def after_commit(tid, rec):
                job.n_committed += 1

            def make_polisher():
                return build_polisher(job.spec, engine=proxy)

            state, error = "done", None
            try:
                polish_job(
                    make_polisher,
                    drop_unpolished=not job.spec.include_unpolished,
                    store=store, emit=job.emit, fill_drops=True,
                    hooks=JobHooks(before_commit=before_commit,
                                   after_commit=after_commit))
            except JobCancelled:
                state = "cancelled"
            except Exception as exc:
                state, error = "failed", str(exc)
            else:
                if self.cache is not None:
                    # Store the finished result under the job
                    # fingerprint. The job outcome is never coupled to
                    # cache health: injected cache/store faults are
                    # swallowed inside store(), and a genuinely failing
                    # store (disk full) costs the cache entry, not the
                    # job.
                    try:
                        self.cache.store(job.spec.fingerprint(),
                                         records_from_store(store))
                    except Exception as exc:
                        print(f"[racon_tpu::serve] cache store failed "
                              f"for job {job.id}: {exc}",
                              file=sys.stderr)
            finally:
                job.n_committed = len(store.committed)
                store.close()
            self._finish(job, state, error)

    def _finish(self, job: Job, state: str, error: Optional[str]) -> None:
        from racon_tpu.obs.metrics import record_hist, record_serve_job
        job.state = state
        job.error = error
        job.persist()
        if state == "done":
            with self._lock:
                self._n_done += 1
        if job.t_submit:
            record_hist("serve_job_latency_s",
                        time.perf_counter() - job.t_submit)
        record_serve_job("completed" if state == "done" else state,
                         job.id, job.tenant,
                         trace_id=job.trace.trace_id if job.trace else "-",
                         parent_id=job.trace.parent_id if job.trace else 0)
        self._update_gauges()
        # Last: anyone woken by the event sees the journal, metrics,
        # and gauges already final.
        job.finished.set()

    def _update_gauges(self) -> None:
        from racon_tpu.obs.metrics import set_serve_active, set_serve_rate
        with self._lock:
            active = sum(1 for j in self._jobs.values()
                         if j.state not in TERMINAL)
            n_done = self._n_done
        set_serve_active(active)
        minutes = max((time.perf_counter() - self._t0) / 60.0, 1e-9)
        set_serve_rate(n_done / minutes)


# --------------------------------------------------------------- HTTP

def serve_http(server: PolishServer, host: str, port: int):
    """Bind the daemon's HTTP front end (daemon thread). Returns the
    stdlib server; its ``server_address`` carries the bound port."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from racon_tpu.obs.export import CONTENT_TYPE, render_registry
    from racon_tpu.obs.metrics import registry
    from racon_tpu.resilience.watchdog import health_snapshot

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, body: bytes,
                   ctype: str = "application/json",
                   headers: Optional[List[Tuple[str, str]]] = None
                   ) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers or []:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _json(self, code: int, obj) -> None:
            self._reply(code, (json.dumps(obj, sort_keys=True) +
                               "\n").encode())

        def do_GET(self):  # noqa: N802 (stdlib naming)
            try:
                self._get()
            except KeyError:
                self._json(404, {"error": "no such job"})
            except Exception as exc:  # handler must not kill the daemon
                self._json(500, {"error": str(exc)})

        def _get(self) -> None:
            path = self.path.rstrip("/")
            if path == "/healthz":
                snap = dict(health_snapshot())
                snap["serve"] = server.describe()
                self._json(200 if snap.get("status") == "ok" else 503,
                           snap)
            elif path == "/v1/jobs":
                self._json(200, server.describe())
            elif path.startswith("/v1/jobs/") and \
                    path.endswith("/stream"):
                job = server.get(path.split("/")[3])
                self._reply(200, job.result_bytes(),
                            ctype="application/octet-stream",
                            headers=[("X-Racon-State", job.state)])
            elif path.startswith("/v1/jobs/"):
                self._json(200, server.get(path.split("/")[3]).status())
            else:
                self._reply(200, render_registry(
                    registry().snapshot()).encode(), ctype=CONTENT_TYPE)

        def do_POST(self):  # noqa: N802 (stdlib naming)
            try:
                self._post()
            except KeyError:
                self._json(404, {"error": "no such job"})
            except (ValueError, RuntimeError) as exc:
                self._json(400, {"error": str(exc)})
            except Exception as exc:  # handler must not kill the daemon
                self._json(500, {"error": str(exc)})

        def _post(self) -> None:
            path = self.path.rstrip("/")
            if path == "/v1/jobs":
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                spec = JobSpec(str(req["sequences"]),
                               str(req["overlaps"]),
                               str(req["targets"]),
                               **req.get("options", {}))
                job = server.submit(req.get("tenant", "default"), spec)
                self._json(202, {"id": job.id, "state": job.state})
            elif path.startswith("/v1/jobs/") and \
                    path.endswith("/cancel"):
                job = server.cancel(path.split("/")[3])
                self._json(200, job.status())
            else:
                self._json(404, {"error": "unknown endpoint"})

        def log_message(self, *args):  # silence per-request stderr
            pass

    httpd = ThreadingHTTPServer((host, int(port)), Handler)
    thread = threading.Thread(target=httpd.serve_forever,
                              name="serve-http", daemon=True)
    thread.start()
    return httpd


# --------------------------------------------------------------- entry

def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        prog="python -m racon_tpu.server",
        description="racon_tpu resident polishing daemon")
    parser.add_argument("--state-dir", required=True,
                        help="job journal + checkpoint root")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="HTTP port (0 = ephemeral; the bound port "
                             "is published to <state-dir>/port)")
    parser.add_argument("--standby", action="store_true",
                        help="block until the gateway lease over "
                             "state-dir can be acquired (adopting a "
                             "dead primary's in-flight jobs), instead "
                             "of failing when one is held")
    args = parser.parse_args(argv)

    from racon_tpu.obs.metrics import registry
    from racon_tpu.obs.trace import configure as configure_trace
    from racon_tpu.pipeline import configure as configure_pipeline
    tracer = configure_trace()
    # Jobs share the chip through the batcher, not through in-job
    # pipeline stages — the dispatcher must stay the only device owner.
    configure_pipeline(0)

    # Gateway lease (racon_tpu/gateway/ha.py): exactly one daemon owns
    # a state dir at a time. The primary first-claims (or steals an
    # expired lease); a --standby replica blocks here until the
    # primary dies or hands off, then ADOPTS: recover() below re-queues
    # the dead primary's journaled in-flight jobs.
    from racon_tpu.gateway.ha import GatewayLease, GatewayLeaseLost
    from racon_tpu.obs.metrics import record_gate
    os.makedirs(args.state_dir, exist_ok=True)
    lease = GatewayLease(args.state_dir, owner=f"gw{os.getpid()}")
    if args.standby:
        lease.acquire()
    elif not lease.try_acquire():
        print(f"[racon_tpu::serve] another gateway holds the lease on "
              f"{args.state_dir} (use --standby to wait and adopt)",
              file=sys.stderr)
        return 1
    if lease.adopted:
        print(f"[racon_tpu::serve] adopted state dir "
              f"{args.state_dir} from a dead primary (lease epoch "
              f"{lease.epoch})", file=sys.stderr)

    server = PolishServer(args.state_dir)
    server.session.activate()
    resumed = server.recover()
    if lease.adopted:
        # One adopt event per journaled in-flight job taken over — the
        # jobs' own trace contexts make the adoption visible in each
        # per-job timeline.
        adopted_jobs = [j for j in server.describe()["jobs"]
                        if j["state"] in ("queued", "running")]
        if adopted_jobs:
            for st in adopted_jobs:
                job = server.get(st["id"])
                record_gate("adopt", job.id, job.tenant,
                            trace_id=job.trace.trace_id if job.trace
                            else "-",
                            parent_id=job.trace.parent_id if job.trace
                            else 0, epoch=lease.epoch)
        else:
            record_gate("adopt", "-", "-", epoch=lease.epoch)
    if resumed:
        print(f"[racon_tpu::serve] resumed {resumed} in-flight "
              f"job(s)", file=sys.stderr)

    # Renewal loop: push the lease deadline out well inside the term;
    # the moment our nonce is gone (a standby fenced us) the only safe
    # reaction is a hard exit — keeping the journal would double-run
    # every job the adopter now owns.
    lease_stop = threading.Event()

    def _renew_loop():
        while not lease_stop.wait(max(0.05, lease.lease_s / 3.0)):
            try:
                lease.renew()
            except GatewayLeaseLost as exc:
                print(str(exc), file=sys.stderr)
                os._exit(75)

    threading.Thread(target=_renew_loop, name="gateway-lease",
                     daemon=True).start()

    try:
        httpd = serve_http(server, args.host, args.port)
    except OSError as exc:
        print(f"[racon_tpu::serve] cannot bind {args.host}:{args.port}"
              f": {exc}", file=sys.stderr)
        return 1
    port = httpd.server_address[1]
    atomic_write_text(os.path.join(args.state_dir, PORT_FILE),
                      f"{port}\n")
    print(f"[racon_tpu::serve] listening on {args.host}:{port} "
          f"(state: {args.state_dir})", file=sys.stderr)

    stop = threading.Event()
    signum_seen = {"n": signal.SIGTERM}

    def _on_signal(signum, frame):
        signum_seen["n"] = signum
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    stop.wait()

    print("[racon_tpu::serve] draining...", file=sys.stderr)
    httpd.shutdown()
    clean = server.drain()
    # Cooperative handoff: a released lease lets the next daemon claim
    # instantly and tells it the jobs were drained, not orphaned.
    lease_stop.set()
    try:
        lease.release()
    except OSError:
        pass
    # Flight recorder dump (obs/flightrec.py): lands beside the fleet
    # obs dir when RACON_TPU_OBS_DIR is set, else a silent no-op.
    from racon_tpu.obs import flightrec
    flightrec.dump(reason="daemon-drain")
    tracer.finish(metrics=registry().snapshot())
    if not clean:
        print("[racon_tpu::serve] drain grace expired with jobs "
              "still running", file=sys.stderr)
        return 1
    print("[racon_tpu::serve] drained clean", file=sys.stderr)
    return 0
