"""``python -m racon_tpu.server`` — launch the resident daemon."""

import sys

from racon_tpu.server.daemon import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
