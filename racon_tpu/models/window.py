"""Window: the unit of consensus — a backbone slice plus read layers.

Re-design of the reference's Window (src/window.{hpp,cpp}). The reference
holds raw (char*, len) pointers into Sequence storage and runs one SPOA
graph per window on a CPU thread (src/window.hpp:61-67, window.cpp:61-137).
Here a Window is a host-side descriptor holding zero-copy ``memoryview``
slices; consensus is computed for *batches* of windows at once by the JAX
engine (racon_tpu.ops.poa), with windows as the batch dimension.

Parity points:
- createWindow validates a non-empty backbone with equal-length quality
  (src/window.cpp:19-23).
- add_layer validates quality length and begin/end positions
  (src/window.cpp:42-59).
- Consensus of a window with fewer than 3 total sequences (backbone + 2
  layers) is the backbone itself, marked unpolished (src/window.cpp:63-66).
- Layers are processed sorted by window-relative begin (src/window.cpp:74-80).
- kTGS windows trim consensus ends with coverage < (n_seqs - 1) / 2
  (src/window.cpp:113-134); fully-trimmed windows warn about a chimeric
  contig and keep the untrimmed consensus.
"""

from __future__ import annotations

import enum
import sys
from typing import List, Optional

import numpy as np

from racon_tpu.models.overlap import PolisherError


class WindowType(enum.Enum):
    NGS = 0  # mean read length <= 1000 (src/polisher.cpp:246-247)
    TGS = 1


class Window:
    __slots__ = (
        "id", "rank", "type",
        "backbone", "backbone_quality",
        "layer_data", "layer_quality", "layer_begin", "layer_end",
        "consensus", "polished",
    )

    def __init__(self, id_: int, rank: int, type_: WindowType,
                 backbone, backbone_quality) -> None:
        if len(backbone) == 0 or (backbone_quality is not None and
                                  len(backbone) != len(backbone_quality)):
            raise PolisherError(
                "[racon_tpu::create_window] error: "
                "empty backbone sequence/unequal quality length!")
        self.id = id_
        self.rank = rank
        self.type = type_
        self.backbone = backbone
        self.backbone_quality = backbone_quality
        self.layer_data: List = []
        self.layer_quality: List[Optional[object]] = []
        self.layer_begin: List[int] = []
        self.layer_end: List[int] = []
        self.consensus: Optional[bytes] = None
        self.polished = False

    def __len__(self) -> int:
        return len(self.backbone)

    @property
    def n_layers(self) -> int:
        return len(self.layer_data)

    def add_layer(self, data, quality, begin: int, end: int) -> None:
        """Append a read segment layer (src/window.cpp:42-59).

        ``begin``/``end`` are window-relative target positions; ``end`` is
        the inclusive last matched backbone position (the reference passes
        last_match.t - window_start - 1, src/polisher.cpp:439-442).
        """
        if quality is not None and len(data) != len(quality):
            raise PolisherError(
                "[racon_tpu::Window::add_layer] error: unequal quality size!")
        # begin < 0 also rejected: the reference's uint32_t coercion makes
        # negative positions enormous and they fail its bounds check.
        if begin < 0 or begin >= end or begin > len(self.backbone) or \
                end > len(self.backbone):
            raise PolisherError(
                "[racon_tpu::Window::add_layer] error: "
                "layer begin and end positions are invalid!")
        self.layer_data.append(data)
        self.layer_quality.append(quality)
        self.layer_begin.append(begin)
        self.layer_end.append(end)

    def set_backbone_consensus(self) -> None:
        """Windows that cannot be polished keep their backbone
        (src/window.cpp:63-66)."""
        self.consensus = bytes(self.backbone)
        self.polished = False

    def apply_consensus(self, consensus: bytes, coverage: np.ndarray,
                        log=sys.stderr) -> None:
        """Install an engine-produced consensus, applying the kTGS coverage
        trim (src/window.cpp:113-134)."""
        if self.type == WindowType.TGS:
            average_coverage = (self.n_layers + 1 - 1) // 2  # (n_seqs-1)/2
            keep = np.flatnonzero(coverage[:len(consensus)] >= average_coverage)
            if len(keep) == 0 or keep[0] >= keep[-1]:
                print(
                    f"[racon_tpu::Window::generate_consensus] warning: contig "
                    f"{self.id} might be chimeric in window {self.rank}!",
                    file=log)
            else:
                consensus = consensus[keep[0]:keep[-1] + 1]
        self.consensus = consensus
        self.polished = True


def sorted_layer_order(window: Window) -> np.ndarray:
    """Layer processing order: ascending window-relative begin
    (src/window.cpp:74-80). Stable to keep input order among ties."""
    return np.argsort(np.asarray(window.layer_begin, dtype=np.int64),
                      kind="stable")


def window_arrays(window: Window):
    """Encode one window for a consensus engine (host or device).

    Returns (layers, bb_codes, bb_weights): layers is a list of
    (codes uint8, weights float32, begin, end) in processing order;
    weights are Phred (quality - 33) or 1.0 without quality, the backbone
    carries its quality or zeros (the reference's dummy '!' quality,
    src/polisher.cpp:141).
    """
    from racon_tpu.ops.encode import encode_bases
    layers = []
    for li in sorted_layer_order(window):
        data = bytes(window.layer_data[li])
        qual = window.layer_quality[li]
        codes = encode_bases(data)
        if qual is not None:
            wts = (np.frombuffer(bytes(qual), dtype=np.uint8)
                   .astype(np.float32) - 33.0)
        else:
            wts = np.ones(len(data), dtype=np.float32)
        layers.append((codes, wts, int(window.layer_begin[li]),
                       int(window.layer_end[li])))
    bb = encode_bases(bytes(window.backbone))
    if window.backbone_quality is not None:
        bw = (np.frombuffer(bytes(window.backbone_quality), dtype=np.uint8)
              .astype(np.float32) - 33.0)
    else:
        bw = np.zeros(len(bb), dtype=np.float32)
    return layers, bb, bw
