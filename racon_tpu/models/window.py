"""Window: the unit of consensus — a backbone slice plus read layers.

Re-design of the reference's Window (src/window.{hpp,cpp}). The reference
holds raw (char*, len) pointers into Sequence storage and runs one SPOA
graph per window on a CPU thread (src/window.hpp:61-67, window.cpp:61-137).
Here a Window is a host-side descriptor holding zero-copy ``memoryview``
slices; consensus is computed for *batches* of windows at once by the JAX
engine (racon_tpu.ops.poa_jax), with windows as the batch dimension.

Parity points:
- createWindow validates a non-empty backbone with equal-length quality
  (src/window.cpp:19-23).
- add_layer validates quality length and begin/end positions
  (src/window.cpp:42-59).
- Consensus of a window with fewer than 3 total sequences (backbone + 2
  layers) is the backbone itself, marked unpolished (src/window.cpp:63-66).
- Layers are processed sorted by window-relative begin (src/window.cpp:74-80).
- kTGS windows trim consensus ends with coverage < (n_seqs - 1) / 2
  (src/window.cpp:113-134); fully-trimmed windows warn about a chimeric
  contig and keep the untrimmed consensus.
"""

from __future__ import annotations

import enum
import sys
from typing import List, Optional

import numpy as np

from racon_tpu.models.overlap import PolisherError
from racon_tpu.ops.encode import encode_bases


class WindowType(enum.Enum):
    NGS = 0  # mean read length <= 1000 (src/polisher.cpp:246-247)
    TGS = 1


class Window:
    __slots__ = (
        "id", "rank", "type",
        "backbone", "backbone_quality",
        "layer_data", "layer_quality", "layer_begin", "layer_end",
        "consensus", "polished",
    )

    def __init__(self, id_: int, rank: int, type_: WindowType,
                 backbone, backbone_quality) -> None:
        if len(backbone) == 0 or (backbone_quality is not None and
                                  len(backbone) != len(backbone_quality)):
            raise PolisherError(
                "[racon_tpu::create_window] error: "
                "empty backbone sequence/unequal quality length!")
        self.id = id_
        self.rank = rank
        self.type = type_
        self.backbone = backbone
        self.backbone_quality = backbone_quality
        self.layer_data: List = []
        self.layer_quality: List[Optional[object]] = []
        self.layer_begin: List[int] = []
        self.layer_end: List[int] = []
        self.consensus: Optional[bytes] = None
        self.polished = False

    def __len__(self) -> int:
        return len(self.backbone)

    @property
    def n_layers(self) -> int:
        return len(self.layer_data)

    def add_layer(self, data, quality, begin: int, end: int) -> None:
        """Append a read segment layer (src/window.cpp:42-59).

        ``begin``/``end`` are window-relative target positions; ``end`` is
        the inclusive last matched backbone position (the reference passes
        last_match.t - window_start - 1, src/polisher.cpp:439-442).
        """
        if quality is not None and len(data) != len(quality):
            raise PolisherError(
                "[racon_tpu::Window::add_layer] error: unequal quality size!")
        # begin < 0 also rejected: the reference's uint32_t coercion makes
        # negative positions enormous and they fail its bounds check.
        if begin < 0 or begin >= end or begin > len(self.backbone) or \
                end > len(self.backbone):
            raise PolisherError(
                "[racon_tpu::Window::add_layer] error: "
                "layer begin and end positions are invalid!")
        self.layer_data.append(data)
        self.layer_quality.append(quality)
        self.layer_begin.append(begin)
        self.layer_end.append(end)

    def set_backbone_consensus(self) -> None:
        """Windows that cannot be polished keep their backbone
        (src/window.cpp:63-66)."""
        self.consensus = bytes(self.backbone)
        self.polished = False

    def apply_consensus(self, consensus: bytes, coverage: np.ndarray,
                        log=sys.stderr) -> None:
        """Install an engine-produced consensus, applying the kTGS coverage
        trim (src/window.cpp:113-134)."""
        if self.type == WindowType.TGS:
            average_coverage = (self.n_layers + 1 - 1) // 2  # (n_seqs-1)/2
            keep = np.flatnonzero(coverage[:len(consensus)] >= average_coverage)
            if len(keep) == 0 or keep[0] >= keep[-1]:
                print(
                    f"[racon_tpu::Window::generate_consensus] warning: contig "
                    f"{self.id} might be chimeric in window {self.rank}!",
                    file=log)
            else:
                consensus = consensus[keep[0]:keep[-1] + 1]
        self.consensus = consensus
        self.polished = True


def sorted_layer_order(window: Window) -> np.ndarray:
    """Layer processing order: ascending window-relative begin
    (src/window.cpp:74-80). Stable to keep input order among ties."""
    return np.argsort(np.asarray(window.layer_begin, dtype=np.int64),
                      kind="stable")


class WindowBatch:
    """Padded device-ready arrays for a batch of same-bucket windows.

    Layout (B = windows, C = max layers, L = max sequence length):
      backbone   uint8[B, L]   base codes (0..4), zero-padded
      backbone_w uint8[B, L]   per-base weights (phred-33, or 0 dummy —
                               the reference feeds '!' dummy quality for
                               targets without quality, src/polisher.cpp:141,383)
      backbone_len int32[B]
      layers     uint8[B, C, L]
      layer_w    uint8[B, C, L] (phred-33 with quality, 1 without —
                               SPOA default weight)
      layer_len  int32[B, C]
      layer_begin/end int32[B, C]  window-relative positions
      n_layers   int32[B]
    """

    __slots__ = ("windows", "backbone", "backbone_w", "backbone_len",
                 "layers", "layer_w", "layer_len", "layer_begin", "layer_end",
                 "n_layers", "dropped_layers", "truncated_bases")

    def __init__(self, windows: List[Window], max_layers: int, max_len: int,
                 allow_truncate: bool = False):
        B, C, L = len(windows), max_layers, max_len
        # No silent caps: the reference consumes every layer in full
        # (src/window.cpp:74-107), so caps below the batch maxima are an
        # error unless the caller explicitly opts into truncation, in which
        # case the damage is counted and queryable.
        need_c = max((w.n_layers for w in windows), default=0)
        need_l = max((max([len(w.backbone)] +
                          [len(d) for d in w.layer_data])
                      for w in windows), default=0)
        if not allow_truncate and (need_c > C or need_l > L):
            raise PolisherError(
                f"[racon_tpu::WindowBatch] error: caps (layers={C}, len={L}) "
                f"below batch maxima (layers={need_c}, len={need_l}); pass "
                f"allow_truncate=True to accept degraded consensus")
        self.dropped_layers = 0
        self.truncated_bases = 0
        self.windows = windows
        self.backbone = np.zeros((B, L), dtype=np.uint8)
        self.backbone_w = np.zeros((B, L), dtype=np.uint8)
        self.backbone_len = np.zeros(B, dtype=np.int32)
        self.layers = np.zeros((B, C, L), dtype=np.uint8)
        self.layer_w = np.zeros((B, C, L), dtype=np.uint8)
        self.layer_len = np.zeros((B, C), dtype=np.int32)
        self.layer_begin = np.zeros((B, C), dtype=np.int32)
        self.layer_end = np.zeros((B, C), dtype=np.int32)
        self.n_layers = np.zeros(B, dtype=np.int32)

        for b, w in enumerate(windows):
            lb = min(len(w.backbone), L)
            self.truncated_bases += len(w.backbone) - lb
            self.backbone_len[b] = lb
            self.backbone[b, :lb] = encode_bases(bytes(w.backbone[:lb]))
            if w.backbone_quality is not None:
                q = np.frombuffer(bytes(w.backbone_quality[:lb]),
                                  dtype=np.uint8)
                self.backbone_w[b, :lb] = q - 33
            order = sorted_layer_order(w)
            n = min(len(order), C)
            self.n_layers[b] = n
            self.dropped_layers += len(order) - n
            for c, li in enumerate(order[:n]):
                data = bytes(w.layer_data[li])
                ll = min(len(data), L)
                self.truncated_bases += len(data) - ll
                self.layer_len[b, c] = ll
                self.layers[b, c, :ll] = encode_bases(data[:ll])
                qual = w.layer_quality[li]
                if qual is None:
                    self.layer_w[b, c, :ll] = 1
                else:
                    q = np.frombuffer(bytes(qual), dtype=np.uint8)[:ll]
                    self.layer_w[b, c, :ll] = q - 33
                self.layer_begin[b, c] = w.layer_begin[li]
                self.layer_end[b, c] = w.layer_end[li]
