"""Sequence container: read / contig with optional quality.

TPU-first re-design of the reference's Sequence class
(reference: src/sequence.{hpp,cpp}). Data is kept as immutable Python
``bytes`` on the host; device-side packing happens per window batch in
racon_tpu.models.window. Reverse complements are built lazily via a
translate table instead of a char loop.

Behavioral parity points (cited against the reference):
- FASTA/FASTQ data is uppercased on construction (src/sequence.cpp:19-28).
- A FASTQ quality string whose Phred values are all zero (all ``!``) is
  treated as "no quality" (src/sequence.cpp:34-42).
- ``transmute(has_name, has_data, has_reverse_data)`` frees unneeded
  strings / builds the reverse complement (src/sequence.cpp:86-100).
- Reverse complement maps A<->T, C<->G and copies any other character
  verbatim; quality is reversed (src/sequence.cpp:49-84).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from racon_tpu.ops.encode import reverse_complement


def _upper(data):
    """``bytes.upper`` that preserves zero-copy ``memoryview`` payloads
    (io/ingest.py mmap plane): a vectorized lowercase scan first — the
    overwhelmingly common all-uppercase FASTA/FASTQ keeps its view; any
    lowercase base falls back to one uppercased ``bytes`` copy."""
    if isinstance(data, (bytes, bytearray)):
        return data.upper()
    arr = np.frombuffer(data, dtype=np.uint8)
    if bool(np.any((arr >= 0x61) & (arr <= 0x7A))):
        return bytes(data).upper()
    return data


def _all_bang(quality) -> bool:
    """All-``!`` check without materializing a view payload."""
    if isinstance(quality, (bytes, bytearray)):
        return quality.count(b"!") == len(quality)
    arr = np.frombuffer(quality, dtype=np.uint8)
    return bool(np.all(arr == 0x21)) if arr.size else True


class Sequence:
    __slots__ = (
        "name",
        "data",
        "quality",
        "reverse_complement",
        "reverse_quality",
        "_quality_prefix",
        "_reverse_quality_prefix",
    )

    def __init__(self, name: str, data: bytes, quality: Optional[bytes] = None):
        self.name = name
        self.data = _upper(data)
        # All-'!' quality (Phred sum == 0) counts as no quality.
        if quality is not None and _all_bang(quality):
            quality = None
        self.quality = quality
        self.reverse_complement: Optional[bytes] = None
        self.reverse_quality: Optional[bytes] = None
        self._quality_prefix: Optional[np.ndarray] = None
        self._reverse_quality_prefix: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.data)

    def create_reverse_complement(self) -> None:
        if self.reverse_complement is not None:
            return
        self.reverse_complement = reverse_complement(self.data)
        if self.quality is not None:
            qual = self.quality
            if not isinstance(qual, (bytes, bytearray)):
                qual = bytes(qual)  # mmap view: [::-1] is non-contiguous
            self.reverse_quality = qual[::-1]

    def transmute(self, has_name: bool, has_data: bool, has_reverse_data: bool) -> None:
        """Free unneeded fields / build reverse complement.

        Mirrors src/sequence.cpp:86-100: drop the name when unused, build the
        reverse complement when some overlap needs the reverse strand, drop
        forward data (and quality) when nothing references it.
        """
        if not has_name:
            self.name = ""
        if has_reverse_data:
            self.create_reverse_complement()
        if not has_data:
            self.data = b""
            self.quality = None
            self._quality_prefix = None

    # -- quality prefix sums: O(1) mean window quality for the layer filter --

    def quality_prefix(self, reverse: bool) -> Optional[np.ndarray]:
        """Prefix sums of (phred byte - 33) for fast mean-quality queries.

        The reference computes per-layer average quality with a scalar loop
        (src/polisher.cpp:409-413); we precompute a cumulative sum per
        sequence once so each layer's mean is two lookups.
        """
        qual = self.reverse_quality if reverse else self.quality
        if qual is None:
            return None
        cache = "_reverse_quality_prefix" if reverse else "_quality_prefix"
        pref = getattr(self, cache)
        if pref is None:
            vals = np.frombuffer(qual, dtype=np.uint8).astype(np.int64) - 33
            pref = np.concatenate([[0], np.cumsum(vals)])
            setattr(self, cache, pref)
        return pref

    def mean_quality(self, begin: int, end: int, reverse: bool) -> Optional[float]:
        """Mean Phred quality over [begin, end) on the chosen strand."""
        pref = self.quality_prefix(reverse)
        if pref is None or end <= begin:
            return None
        return float(pref[end] - pref[begin]) / (end - begin)
