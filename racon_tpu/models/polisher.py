"""Polisher: end-to-end orchestration from input files to polished contigs.

TPU-first re-design of the reference's Polisher (src/polisher.{hpp,cpp}).
The preprocessing pipeline keeps the reference's semantics step for step
(citations inline); the execution model changes where the reference uses a
thread pool:

- per-overlap edlib alignments (src/polisher.cpp:351-364) become one
  batched native/banded-NW call (racon_tpu/native) or a device batch;
- per-window spoa tasks (src/polisher.cpp:457-469) become PoaEngine
  batches with windows as the batch dimension (racon_tpu/ops/poa.py).
"""

from __future__ import annotations

import enum
import os
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from racon_tpu.io import parsers as iop
from racon_tpu.models.overlap import Overlap, PolisherError
from racon_tpu.models.sequence import Sequence
from racon_tpu.models.window import Window, WindowType
from racon_tpu.ops.poa import PoaEngine
from racon_tpu.utils.logger import Logger, NullLogger

# Streaming chunk size for reads/overlaps (src/polisher.cpp:22) — single
# source of truth lives with the parsers.
CHUNK_SIZE = iop.CHUNK_SIZE


class PolisherType(enum.Enum):
    kC = 0  # contig polishing (default)
    kF = 1  # fragment error-correction (-f)


class PolishedSequence:
    """Output record: polished contig with its FASTA header tags."""
    __slots__ = ("name", "data")

    def __init__(self, name: str, data: bytes):
        self.name = name
        self.data = data


def create_polisher(sequences_path: str, overlaps_path: str,
                    target_path: str, type_: PolisherType = PolisherType.kC,
                    window_length: int = 500, quality_threshold: float = 10.0,
                    error_threshold: float = 0.3, match: int = 5,
                    mismatch: int = -4, gap: int = -8,
                    backend: str = "auto", logger: Optional[Logger] = None,
                    threads: int = 1, mesh=None) -> "Polisher":
    """Validate options and dispatch parsers (src/polisher.cpp:51-130).

    ``mesh``: optional jax.sharding.Mesh with a "dp" axis — the consensus
    engine shards every chunk's job axis over it (see
    docs/DISTRIBUTED.md for single-host v5e-8 and multi-host recipes).
    """
    if not isinstance(type_, PolisherType):
        raise PolisherError(
            "[racon_tpu::create_polisher] error: invalid polisher type!")
    if window_length <= 0:
        raise PolisherError(
            "[racon_tpu::create_polisher] error: invalid window length!")
    sparser = iop.create_sequence_parser(sequences_path)
    oparser = iop.create_overlap_parser(overlaps_path)
    tparser = iop.create_sequence_parser(target_path)
    return Polisher(sparser, oparser, tparser, type_, window_length,
                    quality_threshold, error_threshold, match, mismatch,
                    gap, backend=backend, logger=logger, threads=threads,
                    mesh=mesh)


class Polisher:
    def __init__(self, sparser, oparser, tparser, type_: PolisherType,
                 window_length: int, quality_threshold: float,
                 error_threshold: float, match: int, mismatch: int,
                 gap: int, backend: str = "auto",
                 logger: Optional[Logger] = None,
                 window_chunk: int = 8192, threads: int = 1, mesh=None):
        self.sparser = sparser
        self.oparser = oparser
        self.tparser = tparser
        self.type = type_
        self.window_length = window_length
        self.quality_threshold = quality_threshold
        self.error_threshold = error_threshold
        # Host-side OS-thread fan-out for the native aligner (reference
        # -t, src/polisher.cpp:341-364); device batching is unaffected.
        self.threads = threads
        self.engine = PoaEngine(match, mismatch, gap, backend=backend,
                                threads=threads, mesh=mesh)
        self.logger = logger if logger is not None else NullLogger()
        self.window_chunk = window_chunk

        self.sequences: List[Sequence] = []
        self.windows: List[Window] = []
        self.targets_coverages: List[int] = []
        self._targets_size = 0
        self._window_type = WindowType.TGS

    # ------------------------------------------------------------ initialize

    def initialize(self) -> None:
        """Preprocess inputs into windows (src/polisher.cpp:162-449)."""
        if self.windows:
            print("[racon_tpu::Polisher::initialize] warning: "
                  "object already initialized!", file=sys.stderr)
            return
        log = self.logger
        log.begin()

        # Ingest stage (ISSUE 12): with the RACON_TPU_INGEST gate on
        # (and no io/* fault drill armed, which needs single-threaded
        # determinism), all three input files parse on background
        # threads concurrently — reads and overlaps inflate+parse while
        # targets are consumed, so phases 1-3 wait only on the slowest
        # file instead of the sum. Chunk protocol and error contract
        # are identical to the serial loops; bounded queues cap the
        # parsed-ahead memory at pipeline depth.
        from racon_tpu.io.ingest import prefetch_ok
        from racon_tpu.pipeline.streaming import (IngestPrefetcher,
                                                  serial_chunks)
        # kF single-parse (docs/AVA.md): a fragment-correction
        # invocation passes the SAME file as reads and targets
        # (``racon reads paf reads -f``), so parsing it twice doubles
        # the dominant I/O of an assembly-scale run for records the
        # dedup phase immediately discards. When the two paths are one
        # file, phase 2 feeds from the already-loaded targets instead
        # of a second parse — every lookup, comparison, and counter
        # runs as before, so the result is byte-identical.
        s_path = getattr(self.sparser, "path", None)
        t_path = getattr(self.tparser, "path", None)
        shared = (s_path is not None and t_path is not None
                  and os.path.realpath(s_path)
                  == os.path.realpath(t_path))
        prefetchers: List[IngestPrefetcher] = []
        src_s = None
        if prefetch_ok():
            pf_t = IngestPrefetcher(self.tparser, CHUNK_SIZE, "targets")
            pf_o = IngestPrefetcher(self.oparser, CHUNK_SIZE, "overlaps")
            prefetchers = [pf_t, pf_o]
            if not shared:
                pf_s = IngestPrefetcher(self.sparser, CHUNK_SIZE,
                                        "reads")
                prefetchers.append(pf_s)
                src_s = pf_s.chunks()
            src_t = pf_t.chunks()
            src_o = pf_o.chunks()
        else:
            src_t = serial_chunks(self.tparser, CHUNK_SIZE)
            if not shared:
                src_s = serial_chunks(self.sparser, CHUNK_SIZE)
            src_o = serial_chunks(self.oparser, CHUNK_SIZE)
        try:
            self._load_inputs(src_t, src_s, src_o, log)
        finally:
            for pf in prefetchers:
                pf.close()

    def _load_inputs(self, src_t, src_s, src_o, log) -> None:
        """Phases 1-7 of initialize(), consuming the three ingest chunk
        streams (prefetched or serial — same protocol). ``src_s`` may
        be None — the reads ARE the targets (kF single-parse above) —
        and phase 2 then replays the loaded target records through the
        identical dedup/bookkeeping path without touching the file."""
        # 1. Targets (src/polisher.cpp:172-187).
        self.sequences = []
        for chunk, _more in src_t:
            self.sequences.extend(chunk)
        targets_size = len(self.sequences)
        if targets_size == 0:
            raise PolisherError(
                "[racon_tpu::Polisher::initialize] error: "
                "empty target sequences set!")
        self._targets_size = targets_size

        name_to_id: Dict[str, int] = {}
        id_to_id: Dict[int, int] = {}
        for i, seq in enumerate(self.sequences):
            name_to_id[seq.name + "t"] = i
            id_to_id[i << 1 | 1] = i

        has_name = [True] * targets_size
        has_data = [True] * targets_size
        has_reverse = [False] * targets_size

        log.phase("[racon_tpu::Polisher::initialize] loaded target sequences")
        log.begin()

        # 2. Reads, streamed and deduplicated against targets
        # (src/polisher.cpp:196-234).
        if src_s is None:
            # The slice is a copy, so the loop below never iterates a
            # list it is appending to (it won't append here — every
            # "read" dedups against itself — but the invariant should
            # not depend on that).
            src_s = [(self.sequences[:targets_size], False)]
        sequences_size = 0
        total_len = 0
        for chunk, _more in src_s:
            for seq in chunk:
                total_len += len(seq.data)
                tid = name_to_id.get(seq.name + "t")
                if tid is not None:
                    tgt = self.sequences[tid]
                    if len(seq.data) != len(tgt.data) or \
                            len(seq.quality or b"") != len(tgt.quality or b""):
                        raise PolisherError(
                            "[racon_tpu::Polisher::initialize] error: "
                            f"duplicate sequence {seq.name} with unequal data")
                    name_to_id[seq.name + "q"] = tid
                    id_to_id[sequences_size << 1 | 0] = tid
                else:
                    idx = len(self.sequences)
                    self.sequences.append(seq)
                    name_to_id[seq.name + "q"] = idx
                    id_to_id[sequences_size << 1 | 0] = idx
                sequences_size += 1
        if sequences_size == 0:
            raise PolisherError(
                "[racon_tpu::Polisher::initialize] error: "
                "empty sequences set!")

        n_seqs = len(self.sequences)
        has_name += [False] * (n_seqs - targets_size)
        has_data += [False] * (n_seqs - targets_size)
        has_reverse += [False] * (n_seqs - targets_size)

        # NGS/TGS heuristic: mean read length (src/polisher.cpp:246-247).
        self._window_type = WindowType.NGS \
            if total_len / sequences_size <= 1000 else WindowType.TGS

        log.phase("[racon_tpu::Polisher::initialize] loaded sequences")
        log.begin()

        # 3. Overlaps, streamed; per-q_id-group filtering
        # (src/polisher.cpp:252-325).
        overlaps: List[Overlap] = []
        group: List[Overlap] = []

        def flush_group():
            kept = _filter_overlap_group(group, self.error_threshold,
                                         self.type)
            for o in kept:
                if o.strand:
                    has_reverse[o.q_id] = True
                else:
                    has_data[o.q_id] = True
            overlaps.extend(kept)
            group.clear()

        for chunk, _more in src_o:
            for o in chunk:
                o.transmute(self.sequences, name_to_id, id_to_id)
                if not o.is_valid:
                    continue
                if group and group[-1].q_id != o.q_id:
                    flush_group()
                group.append(o)
        flush_group()
        del name_to_id, id_to_id

        if not overlaps:
            raise PolisherError(
                "[racon_tpu::Polisher::initialize] error: "
                "empty overlap set!")

        log.phase("[racon_tpu::Polisher::initialize] loaded overlaps")
        log.begin()

        # 4. Sequence transmute: build reverse complements where some
        # overlap needs them, free what nothing references
        # (src/polisher.cpp:339-348).
        for i, seq in enumerate(self.sequences):
            seq.transmute(has_name[i], has_data[i], has_reverse[i])

        # 5. Breaking points; PAF/MHAP overlaps need a global alignment
        # first. With a device backend the whole phase runs as batched
        # banded NW on the TPU and the breaking points are reduced on
        # device (racon_tpu/ops/ovl_align.py — at genome scale this
        # phase dominated initialize on the host: 551 s of a 1325 s
        # 2 Mb/30x run on one core); over-budget or uncertified lanes
        # fall back to the batched native call, which also serves the
        # CPU backend outright (src/polisher.cpp:351-364,
        # overlap.cpp:194-213).
        import time as _time
        from racon_tpu.obs import metrics as obs_metrics
        t_align = _time.perf_counter()
        pending = [o for o in overlaps if len(o.cigar) == 0]
        if pending and self.engine.backend == "jax":
            from racon_tpu.ops.ovl_align import device_breaking_points
            # Edit-distance scoring (0, -1, -1): the reference derives
            # overlap CIGARs with edlib (src/overlap.cpp:198-200), and
            # the native fallback below uses the same NativeAligner
            # defaults — all three paths pick the same alignments.
            pending = device_breaking_points(
                pending, self.sequences, self.window_length,
                match=0, mismatch=-1, gap=-1, log=sys.stderr)
        if pending:
            from racon_tpu.native.aligner import NativeAligner
            from racon_tpu.ops.cigar import ops_to_cigar
            from racon_tpu.ops.encode import encode_bases
            # Edit-distance scoring, like edlib (src/overlap.cpp:198-200).
            aligner = NativeAligner(threads=self.threads)
            pairs = []
            for o in pending:
                q, t = o.alignment_operands(self.sequences)
                pairs.append((encode_bases(bytes(q)), encode_bases(bytes(t))))
            for o, ops in zip(pending, aligner.align_batch(pairs)):
                o.cigar = ops_to_cigar(ops)
        step = len(overlaps) // 20
        for i, o in enumerate(overlaps):
            o.find_breaking_points(self.sequences, self.window_length)
            # 20-tick cap as in the reference (src/polisher.cpp:359-364).
            if step and (i + 1) % step == 0 and (i + 1) // step <= 20:
                log.tick("[racon_tpu::Polisher::initialize] aligning overlaps")
        # The whole phase — device dispatch, native fallback, and the
        # breaking-point walk — is the 47 s align term of the 89 s 2 Mb
        # genome run (PROFILE.md); bench extras track it per round as
        # align_phase_seconds (metric_version 7).
        obs_metrics.record_align_phase(_time.perf_counter() - t_align)
        log.phase("[racon_tpu::Polisher::initialize] aligned overlaps")
        log.begin()

        # 6. Cut targets into windows (src/polisher.cpp:373-388).
        w_len = self.window_length
        id_to_first_window = [0] * (targets_size + 1)
        for i in range(targets_size):
            tgt = self.sequences[i]
            data = memoryview(tgt.data)
            qual = memoryview(tgt.quality) if tgt.quality is not None else None
            k = 0
            for j in range(0, len(tgt.data), w_len):
                e = min(j + w_len, len(tgt.data))
                self.windows.append(Window(
                    i, k, self._window_type, data[j:e],
                    qual[j:e] if qual is not None else None))
                k += 1
            id_to_first_window[i + 1] = id_to_first_window[i] + k

        # 7. Route overlap segments into windows with the 2%-span and
        # mean-quality filters (src/polisher.cpp:390-446). Filters and
        # window arithmetic run vectorized over each overlap's breaking-
        # point rows (at genome scale this loop sees tens of millions of
        # rows — the per-row Python of earlier rounds dominated
        # initialize); only surviving rows pay Python list appends.
        self.targets_coverages = [0] * targets_size
        min_span = 0.02 * w_len
        for o in overlaps:
            self.targets_coverages[o.t_id] += 1
            seq = self.sequences[o.q_id]
            bps = o.breaking_points
            if bps is None or len(bps) == 0:
                o.breaking_points = None
                continue
            data = seq.reverse_complement if o.strand else seq.data
            qual = seq.reverse_quality if o.strand else seq.quality
            dmv = memoryview(data) if data is not None else None
            qmv = memoryview(qual) if qual is not None else None
            first_t = bps[:, 0]
            first_q = bps[:, 1]
            last_q1 = bps[:, 3]
            ok = (last_q1 - first_q) >= min_span
            if qual is not None:
                pref = seq.quality_prefix(o.strand)
                if pref is not None:
                    n_b = last_q1 - first_q
                    avg = (pref[last_q1] - pref[first_q]) / \
                        np.maximum(n_b, 1)
                    ok &= ~((avg < self.quality_threshold) & (n_b > 0))
            wslot = first_t // w_len
            wid = id_to_first_window[o.t_id] + wslot
            wstart = wslot * w_len
            b = first_t - wstart
            e = bps[:, 2] - wstart - 1
            for r in np.flatnonzero(ok):
                self.windows[wid[r]].add_layer(
                    dmv[first_q[r]:last_q1[r]],
                    qmv[first_q[r]:last_q1[r]] if qmv is not None
                    else None,
                    int(b[r]), int(e[r]))
            o.breaking_points = None  # freed (src/polisher.cpp:445)

        log.phase("[racon_tpu::Polisher::initialize] "
                  "transformed data into windows")

    # ----------------------------------------------------------------- polish

    def skip_targets(self, committed) -> int:
        """Drop every window of the given target ids before polishing —
        the checkpoint-resume path (racon_tpu/resilience/checkpoint.py):
        committed contigs re-emit from the shard, so their windows must
        not recompute. Pruning whole targets is safe for the assembler:
        each contig's windows restart at rank 0, so the remaining
        boundary structure is unchanged. Returns #windows dropped.
        """
        committed = set(committed)
        if not committed:
            return 0
        keep = [w for w in self.windows if w.id not in committed]
        n = len(self.windows) - len(keep)
        self.windows = keep
        return n

    def restrict_targets(self, keep) -> int:
        """Drop every window NOT belonging to the given target ids —
        the distributed-shard path (racon_tpu/distributed/): a worker
        holding a work-ledger shard polishes only that shard's contigs
        while parsing the same input files as everyone else. Pruning
        whole targets is safe for the assembler by the same argument as
        :meth:`skip_targets` (each contig's windows restart at rank 0).
        Returns #windows dropped.
        """
        keep = set(keep)
        kept = [w for w in self.windows if w.id in keep]
        n = len(self.windows) - len(kept)
        self.windows = kept
        return n

    def polish_records(self, drop_unpolished_sequences: bool = True):
        """The one polishing loop: yield ``(target_id, record-or-None)``
        as each target's last window finalizes, in target input order.

        ``record`` is None for a target dropped as unpolished — the
        completion event still yields so a checkpointing caller can
        commit the drop (resume must skip its compute too). polish()
        and polish_stream() are thin views over this; the serial and
        streaming executors feed the same assembler, so the two paths
        stay bit-identical by construction.
        """
        from racon_tpu.pipeline import pipeline_enabled
        log = self.logger
        log.begin()
        asm = _ContigAssembler(self, drop_unpolished_sequences)

        if pipeline_enabled():
            from racon_tpu.pipeline import pipeline_depth
            from racon_tpu.pipeline.streaming import stream_consensus

            def _tick():
                log.tick(
                    "[racon_tpu::Polisher::polish] generating consensus")

            for s, e in stream_consensus(self.engine, self.windows,
                                         chunk=self.window_chunk,
                                         depth=pipeline_depth(),
                                         tick=_tick):
                for i in range(s, e):
                    done = asm.feed(i, self.windows[i])
                    if done is not None:
                        yield done
            self._log_sched_summary()
        else:
            n_windows = len(self.windows)
            for s in range(0, n_windows, self.window_chunk):
                self.engine.consensus_windows(
                    self.windows[s:s + self.window_chunk])
                log.tick(
                    "[racon_tpu::Polisher::polish] generating consensus")
            self._log_sched_summary()
            for i, w in enumerate(self.windows):
                done = asm.feed(i, w)
                if done is not None:
                    yield done

        log.phase("[racon_tpu::Polisher::polish] generated consensus")
        self.windows = []

    def polish(self, drop_unpolished_sequences: bool = True
               ) -> List[PolishedSequence]:
        """Batch windows through the engine, stitch contigs in order, tag
        and emit (src/polisher.cpp:451-513).

        With the streaming pipeline enabled (RACON_TPU_PIPELINE /
        --pipeline-depth; racon_tpu/pipeline/) the underlying
        :meth:`polish_records` loop runs the overlapped executor — same
        records, bit-identical.
        """
        return [rec for _tid, rec
                in self.polish_records(drop_unpolished_sequences)
                if rec is not None]

    def polish_stream(self, drop_unpolished_sequences: bool = True):
        """Streaming polish: yield each PolishedSequence as soon as all
        of its windows finalize, while later windows are still being
        packed/computed (racon_tpu/pipeline/streaming.py).

        The pipeline retires window slices out of order (host-path items
        overtake device chunks), but stream_consensus releases ranges in
        input order, so records come out exactly as polish() would list
        them — the two are differentially tested bit-identical.
        """
        for _tid, rec in self.polish_records(drop_unpolished_sequences):
            if rec is not None:
                yield rec

    def _log_sched_summary(self) -> None:
        telem = getattr(self.engine, "sched_telemetry", None)
        if telem is not None and telem.windows:
            # One source of truth: the counters go into the process
            # metrics registry, and the stderr line is formatted from
            # the same registry keys bench.py serializes.
            from racon_tpu.obs.metrics import (publish_sched, registry,
                                               sched_summary_line)
            publish_sched(telem, registry())
            self.logger.line("[racon_tpu::Polisher::polish] scheduler " +
                             sched_summary_line(registry()))


class _ContigAssembler:
    """Incremental contig stitching: feed finalized windows in input
    order; the last window of each target returns ``(target_id,
    PolishedSequence-or-None)`` — None when the target is dropped as
    unpolished, so completion is still observable (the checkpoint store
    commits drops too). One implementation serves every polish path so
    the record format cannot drift between the serial and streaming
    executors (src/polisher.cpp:478-508)."""

    __slots__ = ("p", "drop", "n_windows", "_data", "_num_polished")

    def __init__(self, polisher: Polisher, drop_unpolished: bool):
        self.p = polisher
        self.drop = drop_unpolished
        self.n_windows = len(polisher.windows)
        self._data: List[bytes] = []
        self._num_polished = 0

    def feed(self, i: int, w: Window
             ) -> Optional[Tuple[int, Optional[PolishedSequence]]]:
        p = self.p
        self._num_polished += 1 if w.polished else 0
        self._data.append(w.consensus or b"")
        last = (i == self.n_windows - 1) or (p.windows[i + 1].rank == 0)
        if not last:
            return None
        ratio = self._num_polished / (w.rank + 1)
        rec: Optional[PolishedSequence] = None
        if not self.drop or ratio > 0:
            data = b"".join(self._data)
            tags = "r" if p.type == PolisherType.kF else ""
            tags += f" LN:i:{len(data)}"
            tags += f" RC:i:{p.targets_coverages[w.id]}"
            tags += f" XC:f:{ratio:.6f}"
            rec = PolishedSequence(p.sequences[w.id].name + tags, data)
        self._num_polished = 0
        self._data = []
        return (w.id, rec)


def _filter_overlap_group(group: List[Overlap], error_threshold: float,
                          type_: PolisherType) -> List[Overlap]:
    """Drop high-error and self overlaps; in kC keep only the longest
    overlap per query (src/polisher.cpp:254-278 — the reference's pairwise
    elimination keeps the last occurrence of the maximum length)."""
    kept = [o for o in group
            if o.error <= error_threshold and o.q_id != o.t_id]
    if not kept or type_ != PolisherType.kC:
        return kept
    best = kept[0]
    for o in kept[1:]:
        if o.length >= best.length:
            best = o
    return [best]
