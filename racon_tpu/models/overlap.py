"""Overlap: normalized read<->target overlap in one of three input formats.

Re-design of the reference's Overlap class (src/overlap.{hpp,cpp}).
Semantics reproduced (with citations):

- MHAP constructor (src/overlap.cpp:15-27): 1-based numeric ids -> id-1,
  strand = a_rc XOR b_rc, length = max span, error = 1 - min/max span.
- PAF constructor (src/overlap.cpp:29-42): names kept, strand from the
  orientation column, same length/error estimate.
- SAM constructor (src/overlap.cpp:44-108): unmapped flag 0x4 -> invalid,
  strand from flag 0x10, 1-based POS -> 0-based t_begin, q_begin from the
  leading S/H clip, alignment lengths from the CIGAR walk, query coords
  flipped onto the reverse strand.
- transmute (src/overlap.cpp:129-177): resolve query via name+"q" or
  id<<1|0, target via name+"t" or id<<1|1; fatal on length disagreement;
  SAM t_length backfilled from the target sequence.
- find_breaking_points (src/overlap.cpp:179-282): missing CIGAR -> global
  alignment of the (strand-selected) query span vs the target span; then a
  CIGAR walk records the first/last matched base per window-length bucket
  of the target. The reference walks base-by-base; here the walk is
  vectorized over CIGAR runs (numpy), and the alignment itself is batched
  at the polisher level (C++ banded NW / TPU kernel) instead of one edlib
  call per overlap inside a thread pool.

Breaking points are stored as an (n_windows_touched, 4) int64 array of
rows (first_t, first_q, last_t_plus1, last_q_plus1) — the flat pair vector
of the reference, two pairs per touched window (src/overlap.cpp:247-254).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence as Seq

import numpy as np

_CIGAR_RE = re.compile(rb"(\d+)([MIDNSHP=X])")

# Per-op advances, indexed by op byte.
_Q_ADV = frozenset(b"MI=X")
_T_ADV = frozenset(b"MDN=X")
_MATCH_OPS = frozenset(b"M=X")


class PolisherError(RuntimeError):
    """Fatal input error (reference exits with fprintf+exit(1))."""


def decompose_cigar(cigar: bytes):
    """CIGAR string -> (lengths int64[R], ops uint8[R])."""
    lens: List[int] = []
    ops: List[int] = []
    for m in _CIGAR_RE.finditer(cigar):
        lens.append(int(m.group(1)))
        ops.append(m.group(2)[0])
    return np.asarray(lens, dtype=np.int64), np.asarray(ops, dtype=np.uint8)


class Overlap:
    __slots__ = (
        "q_name", "q_id", "q_begin", "q_end", "q_length",
        "t_name", "t_id", "t_begin", "t_end", "t_length",
        "strand", "length", "error", "cigar",
        "is_valid", "is_transmuted", "breaking_points",
    )

    def __init__(self):
        self.q_name: Optional[str] = None
        self.q_id: int = 0
        self.q_begin = self.q_end = self.q_length = 0
        self.t_name: Optional[str] = None
        self.t_id: int = 0
        self.t_begin = self.t_end = self.t_length = 0
        self.strand = False
        self.length = 0
        self.error = 0.0
        self.cigar: bytes = b""
        self.is_valid = True
        self.is_transmuted = False
        self.breaking_points: Optional[np.ndarray] = None

    # ---------------------------------------------------------------- ctors

    @classmethod
    def from_mhap(cls, a_id: int, b_id: int, accuracy: float, minmers: int,
                  a_rc: int, a_begin: int, a_end: int, a_length: int,
                  b_rc: int, b_begin: int, b_end: int, b_length: int) -> "Overlap":
        o = cls()
        o.q_id = a_id - 1
        o.q_begin, o.q_end, o.q_length = a_begin, a_end, a_length
        o.t_id = b_id - 1
        o.t_begin, o.t_end, o.t_length = b_begin, b_end, b_length
        o.strand = bool(a_rc ^ b_rc)
        o._span_stats()
        return o

    @classmethod
    def from_paf(cls, q_name: str, q_length: int, q_begin: int, q_end: int,
                 orientation: str, t_name: str, t_length: int, t_begin: int,
                 t_end: int) -> "Overlap":
        o = cls()
        o.q_name = q_name
        o.q_begin, o.q_end, o.q_length = q_begin, q_end, q_length
        o.t_name = t_name
        o.t_begin, o.t_end, o.t_length = t_begin, t_end, t_length
        o.strand = orientation == "-"
        o._span_stats()
        return o

    @classmethod
    def from_sam(cls, q_name: str, flag: int, t_name: str, pos: int,
                 cigar: str) -> "Overlap":
        o = cls()
        o.q_name = q_name
        o.t_name = t_name
        o.t_begin = pos - 1
        o.strand = bool(flag & 0x10)
        o.is_valid = not (flag & 0x4)
        o.cigar = cigar.encode()
        if len(o.cigar) < 2:
            if o.is_valid:
                raise PolisherError(
                    "[racon_tpu::Overlap] error: missing alignment from SAM object!")
            return o
        lens, ops = decompose_cigar(o.cigar)
        if len(lens) == 0:
            if o.is_valid:
                raise PolisherError(
                    "[racon_tpu::Overlap] error: missing alignment from SAM object!")
            return o
        # Leading S/H clip gives q_begin (src/overlap.cpp:60-69 parses the
        # first number in the CIGAR when the first op is a clip).
        q_begin = int(lens[0]) if ops[0] in (ord("S"), ord("H")) else 0
        q_aln = int(lens[np.isin(ops, [ord("M"), ord("="), ord("X"), ord("I")])].sum())
        t_aln = int(lens[np.isin(ops, [ord("M"), ord("="), ord("X"), ord("D"),
                                       ord("N")])].sum())
        clip = int(lens[np.isin(ops, [ord("S"), ord("H")])].sum())
        o.q_begin = q_begin
        o.q_end = q_begin + q_aln
        o.q_length = clip + q_aln
        if o.strand:
            o.q_begin, o.q_end = o.q_length - o.q_end, o.q_length - o.q_begin
        o.t_end = o.t_begin + t_aln
        o.t_length = 0  # backfilled at transmute (src/overlap.cpp:173-174)
        o.length = max(q_aln, t_aln)
        o.error = 1 - min(q_aln, t_aln) / o.length if o.length else 1.0
        return o

    def _span_stats(self) -> None:
        self.length = max(self.q_end - self.q_begin, self.t_end - self.t_begin)
        self.error = (1 - min(self.q_end - self.q_begin,
                              self.t_end - self.t_begin) / self.length
                      if self.length else 1.0)

    # ----------------------------------------------------------- transmute

    def transmute(self, sequences: Seq, name_to_id: Dict[str, int],
                  id_to_id: Dict[int, int]) -> None:
        """Resolve query/target references to sequence indices
        (src/overlap.cpp:129-177)."""
        if not self.is_valid or self.is_transmuted:
            return

        if self.q_name is not None:
            qid = name_to_id.get(self.q_name + "q")
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid
            self.q_name = None
        else:
            qid = id_to_id.get(self.q_id << 1 | 0)
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid

        if self.q_length != len(sequences[self.q_id].data):
            raise PolisherError(
                "[racon_tpu::Overlap::transmute] error: unequal lengths in "
                f"sequence and overlap file for sequence {sequences[self.q_id].name}!")

        if self.t_name is not None:
            tid = name_to_id.get(self.t_name + "t")
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid
            self.t_name = None
        else:
            tid = id_to_id.get(self.t_id << 1 | 1)
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid

        if self.t_length != 0 and self.t_length != len(sequences[self.t_id].data):
            raise PolisherError(
                "[racon_tpu::Overlap::transmute] error: unequal lengths in "
                f"target and overlap file for target {sequences[self.t_id].name}!")

        self.t_length = len(sequences[self.t_id].data)
        self.is_transmuted = True

    # ------------------------------------------------- breaking points

    @property
    def needs_alignment(self) -> bool:
        """True when a global alignment is still required (PAF/MHAP inputs)."""
        return self.is_transmuted and len(self.cigar) == 0 and \
            self.breaking_points is None

    def alignment_operands(self, sequences: Seq):
        """(query_bytes, target_bytes) for the global alignment, strand
        selected exactly as the reference does (src/overlap.cpp:194-197)."""
        seq = sequences[self.q_id]
        if self.strand:
            if seq.reverse_complement is None:
                seq.create_reverse_complement()
            q = seq.reverse_complement[self.q_length - self.q_end:
                                      self.q_length - self.q_begin]
        else:
            q = seq.data[self.q_begin:self.q_end]
        t = sequences[self.t_id].data[self.t_begin:self.t_end]
        return q, t

    def find_breaking_points(self, sequences: Seq, window_length: int,
                             aligner=None) -> None:
        """Populate breaking_points; aligns first when no CIGAR is present.

        ``aligner(q_bytes, t_bytes) -> cigar bytes`` is injected (native
        banded-NW or TPU batch kernel); the polisher normally pre-fills
        ``self.cigar`` for whole batches instead.
        """
        if not self.is_transmuted:
            raise PolisherError(
                "[racon_tpu::Overlap::find_breaking_points] error: "
                "overlap is not transmuted!")
        if self.breaking_points is not None:
            return
        if len(self.cigar) == 0:
            if aligner is None:
                raise PolisherError(
                    "[racon_tpu::Overlap::find_breaking_points] error: "
                    "no CIGAR and no aligner provided!")
            q, t = self.alignment_operands(sequences)
            self.cigar = aligner(q, t)
        self.breaking_points = breaking_points_from_cigar(
            self.cigar, self.t_begin, self.t_end,
            self.q_begin if not self.strand else self.q_length - self.q_end,
            window_length)
        self.cigar = b""  # freed after use (src/overlap.cpp:281)


def breaking_points_from_cigar(cigar: bytes, t_begin: int, t_end: int,
                               q_start: int, window_length: int) -> np.ndarray:
    """Vectorized equivalent of the reference's base-by-base CIGAR walk
    (src/overlap.cpp:216-281).

    Returns int64[(n_touched_windows, 4)] rows
    (first_match_t, first_match_q, last_match_t+1, last_match_q+1),
    windows keyed by t // window_length, ascending.
    """
    lens, ops = decompose_cigar(cigar)
    if len(lens) == 0:
        return np.zeros((0, 4), dtype=np.int64)

    q_adv = np.where(np.isin(ops, [ord("M"), ord("="), ord("X"), ord("I")]), lens, 0)
    t_adv = np.where(np.isin(ops, [ord("M"), ord("="), ord("X"), ord("D"),
                                   ord("N")]), lens, 0)
    q_pos = q_start + np.concatenate([[0], np.cumsum(q_adv)[:-1]])
    t_pos = t_begin + np.concatenate([[0], np.cumsum(t_adv)[:-1]])

    is_match = np.isin(ops, [ord("M"), ord("="), ord("X")])
    t0 = t_pos[is_match]
    q0 = q_pos[is_match]
    n = lens[is_match]
    # Clamp the walk at t_end: the reference's base-by-base loop never steps a
    # target pointer past t_end, so a truncated/inconsistent CIGAR stays
    # bounded instead of silently diverging (src/overlap.cpp:232-279).
    n = np.minimum(n, np.maximum(t_end - t0, 0))
    keep = n > 0
    t0, q0, n = t0[keep], q0[keep], n[keep]
    if len(t0) == 0:
        return np.zeros((0, 4), dtype=np.int64)

    W = window_length
    w0 = t0 // W
    w1 = (t0 + n - 1) // W
    counts = w1 - w0 + 1
    total = int(counts.sum())
    run_idx = np.repeat(np.arange(len(t0)), counts)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    win = w0[run_idx] + (np.arange(total) - starts[run_idx])

    ts = np.maximum(t0[run_idx], win * W)
    te = np.minimum(t0[run_idx] + n[run_idx] - 1, win * W + W - 1)
    fq = q0[run_idx] + (ts - t0[run_idx])
    lq = q0[run_idx] + (te - t0[run_idx]) + 1

    # win is non-decreasing; take first/last entry per distinct window.
    firsts = np.flatnonzero(np.diff(win, prepend=win[0] - 1))
    lasts = np.concatenate([firsts[1:] - 1, [total - 1]])
    return np.stack([ts[firsts], fq[firsts], te[lasts] + 1, lq[lasts]],
                    axis=1).astype(np.int64)
