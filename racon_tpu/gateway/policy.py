"""Cross-host autoscaling from service signals.

The PR 11 supervisor sizes the fleet from one number: open ledger
work (pending shards + an unfinished merge). That is the right signal
for a batch run and the wrong one for a service — by the time pending
shards pile up, jobs have already waited in the daemon's admission
queue. :func:`service_target` wraps the stock ``decide()`` clamp with
the service plane's own signals:

- ``serve_queue_depth_peak`` — jobs stacked on the admission
  semaphore (queue pressure means the gateway should pre-provision);
- the ``serve_queue_wait_s`` histogram (PR 17) — when p95 queue wait
  crosses :data:`SLOW_WAIT_S`, tenants are feeling the backlog;
- fleet windows/s (the worker heartbeat rate under the run's ledger)
  — a fleet already draining faster than the open work needs no boost,
  which keeps the pressure signals from oscillating the fleet size.

The chosen target lands in the ``gate_fleet_target`` gauge so the
OpenMetrics surface and the flight recorder show every sizing
decision.
"""

from __future__ import annotations

from typing import Optional

from racon_tpu.gateway.dispatch import ENV_QUEUE_PRESSURE
from racon_tpu.obs.metrics import (HIST_BUCKETS, hist_quantile, registry,
                                   set_gate_fleet_target)
from racon_tpu.utils import envspec

#: p95 admission-queue wait (seconds) past which the service is
#: considered backlogged and the fleet target gets a pressure boost.
SLOW_WAIT_S = 0.25


def fleet_windows_per_sec(ledger_dir: str) -> float:
    """Summed windows/s across the run's worker heartbeat shards —
    the fleet's current drain rate. 0.0 when no shard is readable yet
    (fleet still spawning), so the damper never blocks the first
    scale-up."""
    from racon_tpu.obs import fleet as _fleet
    try:
        shards = _fleet.load_worker_shards(
            _fleet.obs_dir_for(ledger_dir))
    except Exception:
        return 0.0
    total = 0.0
    for sh in shards:
        last = sh["records"][-1]
        wall = float(last.get("wall_s", 0.0))
        windows = last.get("metrics", {}).get("poa_windows_total", 0)
        if wall > 0 and windows:
            total += windows / wall
    return round(total, 3)


def service_target(open_work: Optional[int], policy,
                   reg=None, ledger_dir: Optional[str] = None) -> int:
    """Target worker count for one supervisor tick, from service
    signals layered over the stock open-work clamp. Plugged into the
    supervisor as its ``target_fn`` by the gateway adapter."""
    from racon_tpu.distributed.autoscaler import decide
    base = decide(open_work, policy)
    reg = reg if reg is not None else registry()
    boost = 0
    pressure = max(1, int(envspec.read(ENV_QUEUE_PRESSURE)))
    depth = int(reg.get("serve_queue_depth_peak", 0) or 0)
    if depth >= pressure:
        boost += 1
    hist = reg.get("serve_queue_wait_s", None)
    if isinstance(hist, dict) and hist.get("count"):
        p95 = hist_quantile(hist, 0.95,
                            HIST_BUCKETS["serve_queue_wait_s"])
        if p95 >= SLOW_WAIT_S:
            boost += 1
    if boost and ledger_dir is not None and open_work is not None:
        rate = fleet_windows_per_sec(ledger_dir)
        if rate >= float(max(1, open_work)):
            boost = 0  # already draining faster than work is arriving
    target = max(policy.min_workers,
                 min(policy.max_workers, base + boost))
    set_gate_fleet_target(target)
    return target
