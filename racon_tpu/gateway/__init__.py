"""Fleet-serve gateway: daemon jobs executed on the elastic ledger fleet.

The daemon (racon_tpu/server/) and the elastic fleet
(racon_tpu/distributed/) are the two halves of the polishing service;
this package is the seam that joins them (docs/GATEWAY.md):

- ``dispatch.py`` — the job→ledger adapter: routes each accepted
  :class:`~racon_tpu.server.engine.JobSpec` to the in-process batcher
  (small jobs) or to an autoscaled ledger fleet (large jobs / queue
  pressure), materializing one ``WorkLedger`` per fleet job keyed by
  the job fingerprint so a crashed or resubmitted run resumes
  byte-identically.
- ``ha.py`` — gateway fail-over: a nonce-fenced gateway lease (the
  ``distributed/ledger.py`` discipline applied to the daemon itself)
  lets a standby replica adopt the journal's in-flight jobs after a
  primary crash.
- ``policy.py`` — cross-host autoscaling from service signals: the
  fleet target is driven by queue depth and queue-wait latency, not
  only pending-shard counts.
"""

from racon_tpu.gateway.dispatch import (FleetDispatchError, RouteDecision,
                                        decide_route, fleet_paths,
                                        run_fleet_job)
from racon_tpu.gateway.ha import GatewayLease, GatewayLeaseLost
from racon_tpu.gateway.policy import service_target

__all__ = [
    "FleetDispatchError", "GatewayLease", "GatewayLeaseLost",
    "RouteDecision", "decide_route", "fleet_paths", "run_fleet_job",
    "service_target",
]
