"""Gateway fail-over: a nonce-fenced lease over the daemon state dir.

The job journal (server/jobs.py) plus the checkpoint ledger already
make any daemon replica able to ``recover()`` a dead primary's work —
what was missing is mutual exclusion: two daemons recovering the same
state dir would double-run every in-flight job. The gateway lease is
the same fencing discipline ``distributed/ledger.py`` uses for shards,
applied to the daemon itself:

- first claim publishes ``<state-dir>/gateway.lease`` exclusively
  (tmp + ``os.link``; losing the race is detected, never overwritten);
- a standby polls the lease and **steals** it only once the deadline
  passes: rewrite with a fresh nonce, re-read, and only proceed when
  its own nonce survived — concurrent standbys race on the rename and
  every loser sees a foreign nonce;
- the holder renews ahead of the deadline and verifies its nonce on
  every renewal; a fenced (stolen-from) gateway must stop journaling
  immediately (:class:`GatewayLeaseLost`), mirroring the worker-side
  ``LeaseLost`` contract;
- release rewrites a ``released`` marker (never unlink — deleting
  would re-arm the first-claim race for a slot that was cooperatively
  handed off).

Clock skew injection (``RACON_TPU_FAULTS='skew=...'``) shifts
:meth:`GatewayLease._now` exactly as it shifts the shard ledger's, so
the kill drill's standby adopts instantly instead of waiting out a
real lease term. The adoption point itself is the ``gate/adopt`` fault
site.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

from racon_tpu.resilience.faults import clock_skew, maybe_fault
from racon_tpu.utils import envspec
from racon_tpu.utils.atomicio import atomic_write_bytes, publish_exclusive

ENV_LEASE_S = "RACON_TPU_GATE_LEASE_S"
ENV_STANDBY_POLL_S = "RACON_TPU_GATE_STANDBY_POLL_S"

LEASE_NAME = "gateway.lease"


class GatewayLeaseLost(RuntimeError):
    """This gateway's nonce is no longer the one on disk: a standby
    fenced us off. The only correct reaction is to stop touching the
    journal and exit — the adopter owns every in-flight job now."""

    def __init__(self, owner: str):
        super().__init__(
            f"[racon_tpu::gate] gateway lease lost by {owner!r} — a "
            "standby adopted this state dir; refusing to keep running")
        self.owner = owner


class GatewayLease:
    """One daemon replica's claim over a state dir. Not thread-safe by
    design: exactly one thread (the renewal loop, between HTTP turns)
    owns the lease object."""

    def __init__(self, state_dir: str, owner: str,
                 lease_s: Optional[float] = None):
        self.state_dir = state_dir
        self.owner = str(owner)
        self.lease_s = float(envspec.read(ENV_LEASE_S)) \
            if lease_s is None else float(lease_s)
        self.path = os.path.join(state_dir, LEASE_NAME)
        self.epoch = 0
        self.nonce = ""
        self.deadline = 0.0
        self.adopted = False

    def _now(self) -> float:
        return time.time() + clock_skew()

    def _read(self) -> Optional[Dict]:
        """None when absent, unreadable, or torn — an unreadable lease
        cannot be renewed by anyone, so it counts as expired."""
        try:
            with open(self.path, "rb") as fh:
                rec = json.loads(fh.read())
            if not isinstance(rec, dict):
                return None
            return rec
        except (OSError, ValueError):
            return None

    def try_acquire(self) -> bool:
        """One claim attempt: first-claim if no lease file exists,
        steal if the current lease is expired, released, or torn.
        Returns False while another replica holds a live lease (or won
        the race) — the standby's poll loop just tries again."""
        nonce = os.urandom(8).hex()
        now = self._now()
        lease = {"name": "gateway", "worker": self.owner, "epoch": 1,
                 "nonce": nonce, "deadline": now + self.lease_s}
        if not os.path.exists(self.path):
            blob = (json.dumps(lease, sort_keys=True) + "\n").encode()
            if publish_exclusive(self.path, blob):
                self.epoch, self.nonce = 1, nonce
                self.deadline = lease["deadline"]
                self.adopted = False
                return True
            # Lost the first-claim race; look at what the winner wrote.
        cur = self._read()
        if cur is not None and float(cur.get("deadline", 0.0)) > now:
            return False  # live lease — not ours to touch
        # Expired, released, or torn: take it by rewriting, then verify
        # our write survived — concurrent standbys race on the rename
        # and every loser sees a foreign nonce on re-read.
        released = bool(cur.get("released")) if cur else False
        lease["epoch"] = int(cur.get("epoch", 0)) + 1 if cur else 1
        lease["deadline"] = self._now() + self.lease_s
        atomic_write_bytes(self.path, (json.dumps(
            lease, sort_keys=True) + "\n").encode())
        back = self._read()
        if back is None or back.get("nonce") != nonce:
            return False  # another standby's rename landed after ours
        self.epoch, self.nonce = int(lease["epoch"]), nonce
        self.deadline = lease["deadline"]
        # A steal of a non-released lease is an adoption: the previous
        # holder died without handing off, and its in-flight jobs are
        # now ours to recover. The ``gate/adopt`` fault site sits on
        # exactly this edge so the drill can break an adopting standby.
        self.adopted = not released and cur is not None
        if self.adopted:
            maybe_fault("gate/adopt")
        return True

    def acquire(self, poll_s: Optional[float] = None,
                deadline_s: float = 0.0) -> bool:
        """Block until the lease is ours (the standby loop). Polls at
        ``RACON_TPU_GATE_STANDBY_POLL_S``; with ``deadline_s`` > 0 the
        wait gives up (False) after that many seconds."""
        poll = float(envspec.read(ENV_STANDBY_POLL_S)) \
            if poll_s is None else float(poll_s)
        t0 = time.monotonic()
        while not self.try_acquire():
            if deadline_s and time.monotonic() - t0 > deadline_s:
                return False
            time.sleep(max(0.01, poll))
        return True

    def verify(self) -> None:
        """Fencing check: raise :class:`GatewayLeaseLost` unless our
        nonce is still the one on disk."""
        cur = self._read()
        if cur is None or cur.get("nonce") != self.nonce:
            raise GatewayLeaseLost(self.owner)

    def renew(self) -> None:
        """Push the deadline out; raises if we were fenced. Verify
        FIRST: renewing over a thief's lease would resurrect a fenced
        gateway."""
        self.verify()
        self.deadline = self._now() + self.lease_s
        lease = {"name": "gateway", "worker": self.owner,
                 "epoch": self.epoch, "nonce": self.nonce,
                 "deadline": self.deadline}
        atomic_write_bytes(self.path, (json.dumps(
            lease, sort_keys=True) + "\n").encode())

    def release(self) -> None:
        """Cooperative handoff marker (clean drain): the next standby
        may claim instantly, and ``adopted`` stays False for it — a
        released gateway's jobs were drained, not orphaned. Never
        unlinks; rewriting keeps the first-claim race armed exactly
        once per state dir lifetime."""
        marker = {"name": "gateway", "worker": self.owner,
                  "epoch": self.epoch, "released": True,
                  "nonce": os.urandom(8).hex(), "deadline": 0.0}
        atomic_write_bytes(self.path, (json.dumps(
            marker, sort_keys=True) + "\n").encode())
        self.nonce = ""
