"""The job→ledger adapter: one accepted JobSpec becomes one fleet run.

Routing happens at the seam the daemon already owns
(``PolishServer._run_job``, after the Tier-1 CAS probe): small jobs
stay on the resident in-process batcher — the cross-request packing
path is strictly better for them — and large jobs (or any job arriving
under queue pressure) are dispatched to an autoscaled ledger fleet.
The decision is pure policy over two numbers:

- ``n_targets`` — the job's target count (a one-pass index scan of the
  targets file, the same scan the ledger partitioner runs);
- ``queue_depth`` — jobs currently waiting on the daemon's admission
  semaphore.

``RACON_TPU_GATE_FLEET`` arms the fleet path;
``RACON_TPU_GATE_FLEET_MIN_TARGETS`` is the size threshold and
``RACON_TPU_GATE_QUEUE_PRESSURE`` the overflow override (a deep queue
routes even small jobs out — the lone daemon is the bottleneck, not
the job). ``gate/route`` is the decision's fault site.

Ava jobs (``fragment_correction``, docs/AVA.md) size by **total
target bytes** (``RACON_TPU_GATE_FLEET_MIN_BYTES``) instead of target
count: every read is a target there, so a count threshold tuned for
contigs would ship trivially small correction jobs to the fleet while
a megabase read set with few records stayed local. The queue-pressure
override applies to both regimes.

A fleet run reuses the distributed plane wholesale: the run directory
is keyed by the job **fingerprint** (the run identity the ledger and
the CAS already share), so a resubmitted or crash-adopted job attaches
to the same ledger and resumes byte-identically — a finished ledger
short-circuits the fleet entirely and just replays ``out.fasta``.
Spawned workers inherit three pieces of shared state through the
environment: the job's trace context (``RACON_TPU_TRACE_CTX``), the
fleet-shared result CAS (``RACON_TPU_CACHE_DIR`` under the gateway
root), and the shared jaxcache warm pool (``RACON_TPU_JAX_CACHE``) so
every worker after the first skips the cold compile (PROFILE.md:
44.5 s cold vs 12.1 s warm).

The merged FASTA is re-committed contig-by-contig into the job's own
checkpoint store through the same emit-then-commit order
``polish_job`` uses — so ``/stream``, the journal, restart recovery,
and the daemon CAS treat a fleet-executed job exactly like a local
one.
"""

from __future__ import annotations

import io
import os
import time
from typing import Callable, List, NamedTuple, Optional

from racon_tpu.resilience.faults import maybe_fault
from racon_tpu.utils import envspec

ENV_GATE_FLEET = "RACON_TPU_GATE_FLEET"
ENV_MIN_TARGETS = "RACON_TPU_GATE_FLEET_MIN_TARGETS"
ENV_MIN_BYTES = "RACON_TPU_GATE_FLEET_MIN_BYTES"
ENV_QUEUE_PRESSURE = "RACON_TPU_GATE_QUEUE_PRESSURE"
ENV_GATE_WORKERS = "RACON_TPU_GATE_WORKERS"

FLEET_SUBDIR = "fleet"
POOL_SUBDIR = "jaxcache"
CAS_SUBDIR = "cas"


class FleetDispatchError(RuntimeError):
    """A fleet run that cannot produce the job's bytes (supervisor
    failed, merged output missing). The job fails; the ledger keeps
    whatever was committed for the next attempt to resume."""


class RouteDecision(NamedTuple):
    route: str          # "fleet" | "local"
    reason: str         # human-readable policy clause that fired
    n_targets: int
    queue_depth: int
    target_bytes: int = 0  # ava size signal (0 for count-routed jobs)


class FleetPaths(NamedTuple):
    root: str        # <state>/fleet — shared across every fleet job
    run_dir: str     # <root>/<fp16> — one job fingerprint, one run
    ledger_dir: str  # <run>/ledger — the WorkLedger workers attach to
    pool_dir: str    # <root>/jaxcache — shared compile-cache warm pool
    cas_dir: str     # <root>/cas — fleet-shared result CAS


def fleet_enabled() -> bool:
    return envspec.read(ENV_GATE_FLEET).strip().lower() \
        not in ("", "0", "false", "off")


def count_targets(targets_path: str) -> int:
    """The job's target count — the routing policy's size signal, via
    the same streaming index scan the ledger partitioner uses."""
    from racon_tpu.io.parsers import scan_sequence_index
    n_records, _offsets = scan_sequence_index(targets_path)
    return n_records


def target_stats(targets_path: str) -> "tuple":
    """(target count, targets-file byte size) — the two routing size
    signals. The byte size is a stat, not a scan; it overstates
    sequence bytes by header/quality overhead, which is fine for a
    routing threshold."""
    return count_targets(targets_path), os.path.getsize(targets_path)


def decide_route(spec, n_targets: int, queue_depth: int = 0,
                 target_bytes: int = 0) -> RouteDecision:
    """Pure routing policy (the test matrix drives this directly).
    Fleet when armed AND (the job is large enough, or the daemon's
    queue is deep enough that shipping even a small job out beats
    waiting). Fragment-correction jobs measure "large enough" in
    target BYTES, everything else in target count. ``gate/route``
    fires before the decision is read."""
    maybe_fault("gate/route")
    ava = bool(getattr(spec, "fragment_correction", False))
    if not fleet_enabled():
        return RouteDecision("local", "fleet-disabled", n_targets,
                             queue_depth, target_bytes)
    pressure = max(1, int(envspec.read(ENV_QUEUE_PRESSURE)))
    if ava:
        min_bytes = max(1, int(envspec.read(ENV_MIN_BYTES)))
        if target_bytes >= min_bytes:
            return RouteDecision(
                "fleet", f"target_bytes {target_bytes} >= {min_bytes}",
                n_targets, queue_depth, target_bytes)
        if queue_depth >= pressure:
            return RouteDecision(
                "fleet", f"queue_depth {queue_depth} >= {pressure}",
                n_targets, queue_depth, target_bytes)
        return RouteDecision(
            "local", f"target_bytes {target_bytes} < {min_bytes}",
            n_targets, queue_depth, target_bytes)
    min_targets = max(1, int(envspec.read(ENV_MIN_TARGETS)))
    if n_targets >= min_targets:
        return RouteDecision(
            "fleet", f"n_targets {n_targets} >= {min_targets}",
            n_targets, queue_depth, target_bytes)
    if queue_depth >= pressure:
        return RouteDecision(
            "fleet", f"queue_depth {queue_depth} >= {pressure}",
            n_targets, queue_depth, target_bytes)
    return RouteDecision(
        "local", f"n_targets {n_targets} < {min_targets}", n_targets,
        queue_depth, target_bytes)


def fleet_paths(state_dir: str, fingerprint: str) -> FleetPaths:
    """Stable on-disk layout for one fleet job. The run dir is keyed
    by the job fingerprint — resubmission and standby adoption land on
    the same ledger; the warm pool and the result CAS are shared
    across every run under this gateway."""
    root = os.path.join(state_dir, FLEET_SUBDIR)
    run_dir = os.path.join(root, fingerprint[:16])
    return FleetPaths(
        root=root,
        run_dir=run_dir,
        ledger_dir=os.path.join(run_dir, "ledger"),
        pool_dir=os.path.join(root, POOL_SUBDIR),
        cas_dir=os.path.join(root, CAS_SUBDIR),
    )


def worker_cli_argv(spec, ledger_dir: str, workers: int) -> List[str]:
    """The CLI argv an autoscaled fleet worker runs for ``spec`` —
    identity flags only (JobSpec.identity() is the fingerprint
    contract), so the workers' run_fingerprint matches the daemon's
    and the ledger refuses nothing."""
    argv = list(spec.paths)
    if spec.include_unpolished:
        argv.append("--include-unpolished")
    if spec.fragment_correction:
        argv.append("--fragment-correction")
    argv += ["--window-length", str(spec.window_length),
             "--quality-threshold", str(spec.quality_threshold),
             "--error-threshold", str(spec.error_threshold),
             "--match", str(spec.match),
             "--mismatch", str(spec.mismatch),
             "--gap", str(spec.gap),
             "--threads", str(spec.threads),
             "--backend", spec.backend,
             "--ledger-dir", ledger_dir,
             "--workers", str(max(1, int(workers)))]
    return argv


def _split_fasta(blob: bytes) -> List[bytes]:
    """Split a merged FASTA back into per-contig byte runs. The merge
    output is the exact concatenation of per-contig emissions, so
    splitting at ``>`` record starts reconstructs each emission
    byte-for-byte."""
    records: List[bytes] = []
    start = None
    for line in blob.splitlines(keepends=True):
        if line.startswith(b">"):
            if start is not None:
                records.append(start)
            start = line
        elif start is not None:
            start += line
    if start is not None:
        records.append(start)
    return records


def run_fleet_job(job, state_dir: str, store, *,
                  trace_ctx: str = "",
                  target_fn: Optional[Callable] = None,
                  log=None) -> int:
    """Execute ``job`` on an autoscaled ledger fleet and stream the
    merged result through the job's own emit/commit path. Returns the
    number of contigs committed. Raises :class:`FleetDispatchError`
    when no merged output can be produced.

    The supervisor runs in the caller's (job runner) thread — the
    gateway holds no extra threads; concurrency across fleet jobs is
    the daemon's existing per-job runner model."""
    from racon_tpu.distributed.autoscaler import Autoscaler
    from racon_tpu.obs.metrics import record_gate
    from racon_tpu.server.jobs import JobCancelled

    spec = job.spec
    paths = fleet_paths(state_dir, spec.fingerprint())
    out_path = os.path.join(paths.ledger_dir, "out.fasta")
    workers = max(1, int(envspec.read(ENV_GATE_WORKERS)))
    t0 = time.perf_counter()
    trace_id = job.trace.trace_id if job.trace else "-"
    parent_id = job.trace.parent_id if job.trace else 0

    if not os.path.isfile(out_path):
        for d in (paths.ledger_dir, paths.pool_dir, paths.cas_dir):
            os.makedirs(d, exist_ok=True)
        extra_env = {
            # One on-disk compile cache for every spawned worker: the
            # first worker pays the cold compile into the pool, every
            # later (and every replacement) worker starts warm.
            "RACON_TPU_JAX_CACHE": paths.pool_dir,
            # Fleet-shared result CAS: workers probe/store per-shard
            # contig records keyed by shard fingerprint, so a re-run
            # of this fingerprint polishes nothing.
            "RACON_TPU_CACHE_DIR": paths.cas_dir,
        }
        if trace_ctx:
            extra_env["RACON_TPU_TRACE_CTX"] = trace_ctx
        if target_fn is None:
            # Drive the supervisor from service signals (queue depth,
            # queue-wait p95, fleet drain rate), not only open shards.
            from racon_tpu.gateway.policy import service_target
            ldir = paths.ledger_dir

            def target_fn(open_work, pol):
                return service_target(open_work, pol, ledger_dir=ldir)
        scaler = Autoscaler(
            paths.ledger_dir,
            worker_cli_argv(spec, paths.ledger_dir, workers),
            default_max=workers, out=io.BytesIO(), log=log,
            extra_env=extra_env,
            target_fn=target_fn,
            trace_dir=os.path.join(paths.ledger_dir, "obs"))
        rc = scaler.run()
        if rc != 0:
            raise FleetDispatchError(
                f"[racon_tpu::gate] fleet supervisor for job "
                f"{job.id} exited {rc} (ledger: {paths.ledger_dir})")
    if not os.path.isfile(out_path):
        raise FleetDispatchError(
            f"[racon_tpu::gate] fleet run for job {job.id} finished "
            f"without a merged output at {out_path}")
    # Re-commit the merged result through the job's own store in the
    # same emit-then-commit order polish_job uses: /stream, restart
    # recovery, and the daemon CAS see a fleet job exactly like a
    # local one. serve/commit keeps its meaning — "one contig became
    # durable in this job's store" — whichever path computed it. The
    # records stream straight off the merged file (ava runs emit one
    # per read — the whole-blob split this replaces held two copies
    # of a potentially enormous output in memory at once).
    from racon_tpu.ava.emit import iter_fasta_records
    n = 0
    committed = len(store.committed)
    for tid, rec in enumerate(iter_fasta_records(out_path)):
        if tid < committed:
            # Adoption/restart: the committed prefix re-emits from the
            # store byte-for-byte (polish_job's emit_stored contract),
            # zero recompute.
            stored = store.read_emitted(tid)
            if stored is not None:
                job.emit(stored)
            n += 1
            continue
        if job.cancel.is_set():
            raise JobCancelled(job.id)
        maybe_fault("serve/commit")
        nl = rec.index(b"\n")
        job.emit(rec)
        store.commit(tid, bytes(rec[1:nl]), bytes(rec[nl + 1:-1]))
        n += 1
    record_gate("fleet_run", job.id, job.tenant, trace_id=trace_id,
                parent_id=parent_id, decision="fleet",
                wall_s=round(time.perf_counter() - t0, 6),
                contigs=n, workers=workers)
    return n
