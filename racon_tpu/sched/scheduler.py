"""Chunk driver for convergence-aware refinement.

ConvergenceScheduler.run_chunk replaces the fixed engine's single
all-rounds dispatch (device_poa.device_chunk_packed) with a short
dispatch chain:

    sched_unpack ─ sched_rounds(rounds 0..1, detect) ─┐
      ┌───────────────────────────────────────────────┘
      │ per surviving round r = 2..R-1:
      │   d2h: conv + ovf flags (the only per-round tunnel pull)
      │   host: RepackPlan  ─ h2d: index vectors (a few KB)
      │   sched_repack ─ sched_rounds(round r, detect, traced `last`)
      └─ early exit when every window froze
    sched_pack ─ collect_chunk (unchanged d2h layout)

Rounds 0 and 1 fuse into one dispatch because detection cannot fire
before round 1 (see device_merge.converged_windows) — no window could
exit earlier, so splitting them would only add dispatch latency. From
round 2 on, each round runs on a repacked survivor batch whose shrinking
shapes land on ChunkPlan's coarse buckets; the tail dispatches share
one executable because ``last`` is traced, not static.

The consensus a frozen window records is the final-scale dual assembly
of its detection round's votes — bit-identical to the fixed engine's
last round (the replay argument lives in sched/rounds.py). Overflowed
windows freeze immediately too: their sticky flag already routes them
to the unbounded host redo, so further device rounds are wasted work.

This path keeps FUSED forward+walk dispatches: every round's walk
feeds the per-round convergence flag pull, so no walk here is free of
dependent anchor state — the decoupled-walk stage
(pipeline/streaming.py, ops/colwalk.py::dispatch_walk) applies only to
the fixed-round engine, whose FINAL walk nothing consumes until
retirement. stream_consensus falls back to fused dispatches whenever
this scheduler is active.
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from racon_tpu.obs.metrics import (record_flag_pull, record_h2d,
                                   registry as obs_registry)
from racon_tpu.obs.trace import get_tracer
from racon_tpu.sched.repack import RepackPlan
from racon_tpu.sched.telemetry import SchedTelemetry


def sched_enabled() -> bool:
    """Convergence scheduling is on unless RACON_TPU_SCHED=0 (the
    fixed-round single-dispatch engine is the fallback)."""
    return envspec.read("RACON_TPU_SCHED") not in ("0", "false")


class ConvergenceScheduler:
    """Runs ChunkPlans to consensus with per-window early exit.

    ``scales`` is PoaEngine's per-round insertion-scale schedule
    (_round_scales): all non-final entries must be equal — the dual
    assembly's bit-identity argument needs every replayable round to
    share one scale. The engine's [base]*(R-1) + [final] schedule
    satisfies this by construction; a hand-built schedule that doesn't
    is rejected here rather than silently producing divergent output.
    """

    def __init__(self, *, match: int, mismatch: int, gap: int,
                 scales: Sequence[float], mesh=None,
                 telemetry: Optional[SchedTelemetry] = None):
        self.match, self.mismatch, self.gap = match, mismatch, gap
        scales = tuple(float(s) for s in scales)
        if not scales:
            raise ValueError("[racon_tpu::ConvergenceScheduler] empty "
                             "scale schedule")
        if len(set(scales[:-1])) > 1:
            raise ValueError(
                "[racon_tpu::ConvergenceScheduler] non-final insertion "
                f"scales must be uniform, got {scales} — convergence "
                "freezing replays rounds and cannot honor a per-round "
                "varying scale (use RACON_TPU_SCHED=0)")
        self.rounds = len(scales)
        self.scale = scales[0] if len(scales) > 1 else scales[-1]
        self.scale_final = scales[-1]
        self.mesh = mesh
        self.telemetry = telemetry if telemetry is not None \
            else SchedTelemetry(self.rounds)

    # ------------------------------------------------------------------ h2d

    def put_chunk(self, plan) -> Tuple[object, object]:
        """Start the (async) h2d of a chunk's two packed byte buffers.

        Call for chunk i+1 before running chunk i's rounds: device_put
        returns immediately, so the transfer overlaps compute — the
        scheduler's replacement for the fixed path's depth-2 dispatch
        pipeline (its per-round host syncs preclude dispatch-level
        overlap, but h2d is the tunnel-bound phase worth hiding).
        """
        from racon_tpu.ops.device_poa import put_chunk_bufs
        return put_chunk_bufs(plan, mesh=self.mesh)

    # ------------------------------------------------------------------ run

    def run_chunk(self, plan, bufs: Optional[Tuple[object, object]] = None,
                  stats: Optional[dict] = None
                  ) -> Tuple[List[Optional[bytes]],
                             List[Optional[np.ndarray]]]:
        """Polish one ChunkPlan; returns collect_chunk's (codes, covs).

        ``bufs`` takes a pre-transferred put_chunk result; None ships
        the buffers here. ``stats`` matches dispatch_chunk's dict
        ("chunks", then collect_chunk's "d2h").
        """
        from racon_tpu.ops.device_poa import (_use_pallas, collect_chunk,
                                              round_band_width)
        from racon_tpu.sched.rounds import (sched_pack, sched_repack,
                                            sched_rounds, sched_unpack)
        import jax

        R = self.rounds
        telem = self.telemetry
        ndp = self.mesh.shape["dp"] if self.mesh is not None else 1
        band_w = (0 if envspec.read("RACON_TPU_NO_BAND")
                  not in ("", "0", "false") else plan.band_w)
        # Same per-chunk walk-depth selection as dispatch_chunk: pick k
        # at the round-0 (widest) band so every dispatch shares one k.
        from racon_tpu.ops.budget import walk_k_for
        nxt_k = walk_k_for(plan.B // ndp * plan.Lq * band_w) \
            if band_w else 1
        from racon_tpu.ops.colwalk import chain_len
        obs_registry().set("walk_chain_len",
                           chain_len(plan.LA, nxt_k if band_w else 1))
        statics = dict(match=self.match, mismatch=self.mismatch,
                       gap=self.gap, scale=self.scale,
                       scale_final=self.scale_final, Lq=plan.Lq,
                       LA=plan.LA, mesh=self.mesh, nxt_k=nxt_k)

        if bufs is None:
            bufs = self.put_chunk(plan)
        job_buf, win_buf = bufs
        tracer = get_tracer()
        reg = obs_registry()
        (bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf,
         out_codes, out_cov, out_total, out_ovf) = sched_unpack(
            job_buf, win_buf, Lq=plan.Lq, LA=plan.LA, n_win=plan.n_win)
        reg.inc("device_dispatches")

        n_real = plan.n_real_win
        telem.record_chunk(n_real)
        trash = plan.n_win
        real = np.zeros(plan.n_win, bool)
        real[:n_real] = True
        cur_win_h = plan.win          # host copy of the lane->window map
        cur_orig = np.arange(plan.n_win, dtype=np.int32)
        orig_ids = cur_orig

        # Rounds 0..pre-1 fused (detection fires on the last of them).
        pre = min(2, R)
        pallas = _use_pallas(plan.B // ndp, plan.Lq, plan.LA)
        for r in range(pre):
            telem.record_round(r, n_real)
        with tracer.span("round", f"rounds0-{pre - 1}", lanes=plan.B,
                         windows=n_real):
            (bb, bbw, alen, begin, end, ovf, conv, out_codes, out_cov,
             out_total, out_ovf, rounds_run) = sched_rounds(
                bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf,
                out_codes, out_cov, out_total, out_ovf, orig_ids, pre == R,
                n_win=plan.n_win, pallas=pallas,
                band_ws=tuple(round_band_width(band_w, r)
                              for r in range(pre)),
                detect=R >= 2, **statics)
        reg.inc("device_dispatches")
        exec_dev = rounds_run        # device scalar; pulled via sched_pack
        executed = pre

        n_alive = n_real
        cur_B, cur_nwin = plan.B, plan.n_win
        while executed < R and n_alive > 0:
            # The only per-round d2h: two bool vectors for control flow
            # (they feed telemetry for free). This pull is the sync
            # point, so its time (compute wait + tunnel round-trip) is
            # accounted separately from the transfer bandwidth keys.
            from racon_tpu.resilience.retry import call as retry_call

            def _pull_flags():
                t_pull = time.perf_counter()
                conv_h = np.asarray(conv)
                ovf_h = np.asarray(ovf)
                record_flag_pull(conv_h.nbytes + ovf_h.nbytes,
                                 time.perf_counter() - t_pull)
                return conv_h, ovf_h

            conv_h, ovf_h = retry_call("sched/flags", _pull_flags)
            frozen = real & (conv_h | ovf_h)
            telem.record_freeze(executed, int(frozen.sum()))
            surv = real & ~conv_h & ~ovf_h
            n_alive = int(surv.sum())
            if n_alive == 0:
                telem.record_skip(R - executed)
                break

            # Repack pays only when the survivor set lands in a SMALLER
            # shape bucket (lane axis or a >=2x window-axis drop) —
            # otherwise the repacked dispatch runs the same padded
            # shapes and the gather/flag-pull overhead is pure loss. In
            # that case fuse every remaining round into one dispatch on
            # the current layout (the fixed engine's program, detection
            # off): low-convergence chunks cost one flag pull over the
            # fixed path instead of a sync per round.
            from racon_tpu.ops.device_poa import _bucket_b, _round_up
            n_wc = surv.shape[0]
            n_lanes = int(np.count_nonzero(
                (cur_win_h < n_wc) & surv[np.minimum(cur_win_h, n_wc - 1)]))
            B2 = _round_up(_bucket_b(max(n_lanes, 1)), 128 * ndp)
            nw2 = _round_up(n_alive, 32)
            if B2 >= cur_B and 2 * nw2 > cur_nwin:
                for r in range(executed, R):
                    telem.record_round(r, n_alive)
                tail_ws = tuple(round_band_width(band_w, r)
                                for r in range(executed, R))
                # The fused tail runs the remaining rounds blind (no
                # per-round flag pull); the adaptive while_loop form
                # stops its device loop at the chunk's fixed point
                # instead of always running all R - executed rounds.
                adapt = (envspec.read("RACON_TPU_ADAPTIVE")
                         not in ("0", "false")
                         and len(tail_ws) >= 2
                         and len(set(tail_ws)) == 1)
                with tracer.span("round", f"rounds{executed}-{R - 1}",
                                 lanes=cur_B, windows=n_alive,
                                 fused_tail=1):
                    (bb, bbw, alen, begin, end, ovf, conv, out_codes,
                     out_cov, out_total, out_ovf, rounds_run) = \
                        sched_rounds(
                        bb, bbw, alen, begin, end, q, qw8, lq, w_read,
                        win, ovf, out_codes, out_cov, out_total, out_ovf,
                        orig_ids, True, n_win=cur_nwin, pallas=pallas,
                        band_ws=tail_ws, detect=False, adaptive=adapt,
                        **statics)
                reg.inc("device_dispatches")
                exec_dev = exec_dev + rounds_run
                executed = R
                break

            t0 = time.perf_counter()
            rp = RepackPlan(surv, cur_win_h, cur_orig, trash=trash,
                            n_shards=ndp)
            def _put_repack():
                t_put = time.perf_counter()
                if self.mesh is None:
                    lane_idx_d, new_win_d, win_map_d, win_real_d = \
                        jax.device_put((rp.lane_idx, rp.new_win,
                                        rp.win_map, rp.win_real))
                else:
                    from jax.sharding import (NamedSharding,
                                              PartitionSpec as P)
                    rep = NamedSharding(self.mesh, P())
                    lane_idx_d = jax.device_put(rp.lane_idx, rep)
                    win_map_d = jax.device_put(rp.win_map, rep)
                    win_real_d = jax.device_put(rp.win_real, rep)
                    new_win_d = jax.device_put(
                        rp.new_win, NamedSharding(self.mesh, P("dp")))
                record_h2d(rp.lane_idx.nbytes + rp.new_win.nbytes +
                           rp.win_map.nbytes + rp.win_real.nbytes,
                           time.perf_counter() - t_put, name="h2d/repack")
                return lane_idx_d, new_win_d, win_map_d, win_real_d

            from racon_tpu.ops.budget import transfer_deadline_s
            lane_idx_d, new_win_d, win_map_d, win_real_d = \
                retry_call("h2d/repack", _put_repack,
                           deadline_s=transfer_deadline_s(
                               rp.lane_idx.nbytes + rp.new_win.nbytes +
                               rp.win_map.nbytes + rp.win_real.nbytes,
                               "h2d"))
            with tracer.span("dispatch", "repack", lanes=rp.B,
                             windows=n_alive):
                (bb, bbw, alen, begin, end, q, qw8, lq, w_read, ovf) = \
                    sched_repack(bb, bbw, alen, begin, end, q, qw8, lq,
                                 w_read, ovf, lane_idx_d, new_win_d,
                                 win_map_d, win_real_d, mesh=self.mesh)
            reg.inc("device_dispatches")
            win = new_win_d
            cur_win_h = rp.new_win
            cur_orig = rp.orig_ids
            real = rp.win_real
            orig_ids = rp.orig_ids
            cur_B, cur_nwin = rp.B, rp.n_win
            telem.record_repack(time.perf_counter() - t0)

            telem.record_round(executed, n_alive)
            pallas = _use_pallas(rp.B // ndp, plan.Lq, plan.LA)
            with tracer.span("round", f"round{executed}", lanes=rp.B,
                             windows=n_alive):
                (bb, bbw, alen, begin, end, ovf, conv, out_codes, out_cov,
                 out_total, out_ovf, rounds_run) = sched_rounds(
                    bb, bbw, alen, begin, end, q, qw8, lq, w_read, win,
                    ovf, out_codes, out_cov, out_total, out_ovf, orig_ids,
                    executed == R - 1, n_win=rp.n_win, pallas=pallas,
                    band_ws=(round_band_width(band_w, executed),),
                    detect=True, **statics)
            reg.inc("device_dispatches")
            exec_dev = exec_dev + rounds_run
            executed += 1

        if n_alive > 0:
            # Whoever was still live froze on the schedule's last round.
            telem.record_freeze(R, n_alive)

        packed = sched_pack(out_codes, out_cov, out_total, out_ovf,
                            exec_dev, R)
        reg.inc("device_dispatches")
        if stats is not None:
            stats["chunks"] = stats.get("chunks", 0) + 1
            stats["_t_pack"] = time.perf_counter()
        return collect_chunk(plan, packed, stats=stats)
