"""Device programs for the convergence scheduler.

Three jitted entry points, all shape-stable per chunk:

- :func:`sched_unpack` — ChunkPlan byte buffers -> round-state arrays
  plus fresh device-resident output accumulators (indexed by ORIGINAL
  window id for the chunk's whole lifetime).
- :func:`sched_rounds` — one dispatch running 1..k refinement rounds,
  detecting fixed points on the last of them and scattering frozen
  windows' outputs into the accumulators. The freeze-everything flag
  (``last``) is a TRACED scalar, so every single-round dispatch of the
  tail (global rounds 2..R-1) shares ONE compiled executable.
- :func:`sched_repack` — gather-compaction of survivor state onto the
  dense lane/window axes a host RepackPlan laid out.

Why a frozen window's output is bit-identical to the fixed engine's:

1. All non-final rounds share one insertion-vote scale (PoaEngine's
   schedule is [base]*(R-1) + [final]), and from round 1 on anchors
   carry zero weights. So for rounds 1 <= r < R-1 the round function is
   literally replayed: if round r reproduced its own input state
   (anchor bytes + length + every lane span — the converged_windows
   predicate), rounds r+1..R-2 reproduce it again, vote-for-vote.
2. The final round differs ONLY in the assembly scale — alignment and
   vote extraction never see ins_scale. Its votes therefore equal the
   detection round's votes, and assembling THOSE votes at the final
   scale (the dual assembly below, computed every round from the same
   accumulators) IS the fixed engine's final output for that window.
3. Replay rounds also share the narrowed band width
   (device_poa.round_band_width, r >= 1 in both engines), so the
   escape-bound redo flags replay identically too.

Per-window convergence (not per-lane): one window's lanes vote into one
accumulator, so a single moved span can shift the whole window's merge —
the freeze unit must be the window. Detection starts at round 1 (the
round-0 anchor carries backbone quality weights; see
device_merge.converged_windows).

Caveat (shared with the dp-sharded engine, see ops/device_poa.py's
module docstring): repacking changes the batch size the vote matmul
accumulates over, which may reassociate the few FRACTIONAL f32 channels
(w_read-derived) for windows still live after round 2 — sub-epsilon
ties could in principle break differently there. Integer-weight
channels are exact at any batch size, and windows frozen at round 1
(the common case) never see a repacked batch.
"""

from __future__ import annotations

import functools


def _sched_core(bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf, *,
                match, mismatch, gap, scale, scale_final, Lq, n_win, LA,
                pallas, band_w, nxt_k=2, detect=False, axis_name=None):
    """One detecting round (traced body, single shard's view).

    _round_core's alignment+merge (shared via device_poa._lane_votes /
    _remap_state) plus (a) the per-window fixed-point predicate and
    (b) a final-scale assembly of the same vote accumulators.

    Returns (new_bb, new_bbw, new_alen, new_begin, new_end, conv, ovf,
    ovf_f, codes_f, cov_f, total_f): ``conv`` bool[n_win] fixed-point
    flags (all-False when ``detect`` is off), ``ovf`` the sticky
    host-redo flag (band escape / saturation / base-assembly overflow),
    ``ovf_f``/``codes_f``/``cov_f``/``total_f`` the final-scale output
    candidate a freezing window records.
    """
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops import device_merge as dm
    from racon_tpu.ops.device_poa import _lane_votes, _remap_state

    votes, esc_w = _lane_votes(
        bb, alen, begin, end, q, qw8, lq, w_read, win, match=match,
        mismatch=mismatch, gap=gap, Lq=Lq, LA=LA, pallas=pallas,
        band_w=band_w, nxt_k=nxt_k)
    acc = dm.aggregate_votes(votes, win, n_win + 1, extras={"_esc": esc_w})
    if axis_name is not None:
        acc = {k: jax.lax.psum(v, axis_name) for k, v in acc.items()}
    wesc = acc.pop("_esc")
    acc = {k: v[:-1] for k, v in acc.items()}       # drop padded-lane row
    acc = dm.add_backbone(acc, bb[:-1], bbw[:-1], alen[:-1])
    asm = dm.assemble(acc, alen[:-1], scale)
    codes, cov, total = dm.compact(asm, LA)
    map_b, map_e = dm.coord_maps(asm, alen[:-1], LA)
    new_bb, new_alen, nb, ne = _remap_state(
        codes, total, map_b, map_e, bb, alen, begin, end, win, LA)
    new_bbw = jnp.zeros_like(bbw)
    # Sticky-flag split: ``ovf`` (carried state) folds in this round's
    # BASE-scale assembly overflow, exactly like the fixed engine's
    # intermediate rounds; ``ovf_pre`` leaves it out, because the fixed
    # engine's FINAL round assembles at the final scale only — a window
    # frozen by the schedule's end must not inherit an overflow verdict
    # from an assembly the fixed engine never ran. (For converged
    # windows the two coincide: a fixed point has total == alen_old
    # <= LA.) sched_rounds picks per freeze reason.
    ovf_pre = ovf | (wesc[:-1] > 0)
    ovf = ovf_pre | (total > LA)

    if detect:
        # Span-change flags ride a second tiny membership matmul (and
        # one extra psum under dp — nb/ne only exist after the maps, so
        # they cannot ride the vote aggregation's psum).
        chg = ((nb != begin) | (ne != end)).astype(jnp.float32)
        wchg = dm.aggregate_flags(chg, win, n_win + 1)
        if axis_name is not None:
            wchg = jax.lax.psum(wchg, axis_name)
        conv = dm.converged_windows(codes, total, bb[:-1], alen[:-1],
                                    wchg[:-1])
    else:
        conv = jnp.zeros(n_win, dtype=bool)

    # Dual assembly: the final-scale output candidate, from the SAME
    # accumulators (free of alignment cost — assemble+compact only).
    if scale_final != scale:
        asm_f = dm.assemble(acc, alen[:-1], scale_final)
        codes_f, cov_f, total_f = dm.compact(asm_f, LA)
    else:
        codes_f, cov_f, total_f = codes, cov, total
    ovf_f = ovf_pre | (total_f > LA)
    return (new_bb, new_bbw, new_alen, nb, ne, conv, ovf, ovf_f,
            codes_f, cov_f, total_f)


def _make_sched_fn(*, match, mismatch, gap, scale, scale_final, Lq, n_win,
                   LA, pallas, band_w, detect, mesh, nxt_k=2):
    """_sched_core, or its dp-sharded shard_map under a mesh (same
    sharding contract as device_poa._make_round_fn: job axis over "dp",
    window arrays replicated, psums inside the core)."""
    core = functools.partial(
        _sched_core, match=match, mismatch=mismatch, gap=gap, scale=scale,
        scale_final=scale_final, Lq=Lq, n_win=n_win, LA=LA, pallas=pallas,
        band_w=band_w, nxt_k=nxt_k, detect=detect,
        axis_name=None if mesh is None else "dp")
    if mesh is None:
        return core
    from jax.sharding import PartitionSpec as P
    from racon_tpu.utils.jaxcompat import shard_map
    rep = P()
    job = P("dp")
    return shard_map(
        core, mesh=mesh,
        in_specs=(rep, rep, rep, job, job, job, job, job, job, job, rep),
        out_specs=(rep, rep, rep, job, job, rep, rep, rep, rep, rep, rep),
        check_vma=False)


@functools.partial(
    __import__("jax").jit, static_argnames=("Lq", "LA", "n_win"))
def sched_unpack(job_buf, win_buf, *, Lq, LA, n_win):
    """Unpack a chunk's packed byte buffers into round state plus fresh
    output accumulators (one dispatch; the zeros materialize on device).

    The accumulators are indexed by ORIGINAL window row for the chunk's
    whole lifetime — row ``n_win`` is the trash row non-frozen (and
    padded) writes land in. Returns (bb, bbw, alen, begin, end, q, qw8,
    lq, w_read, win, ovf, out_codes, out_cov, out_total, out_ovf).
    """
    import jax.numpy as jnp
    from racon_tpu.ops.device_poa import _unpack_bufs

    (q, qw8, begin, end, lq, win, w_read, bb, bbw, alen) = \
        _unpack_bufs(job_buf, win_buf, Lq, LA)
    ovf = jnp.zeros(n_win, dtype=bool)
    out_codes = jnp.zeros((n_win + 1, LA), jnp.uint8)
    out_cov = jnp.zeros((n_win + 1, LA), jnp.int32)
    out_total = jnp.ones(n_win + 1, jnp.int32)
    out_ovf = jnp.zeros(n_win + 1, dtype=bool)
    return (bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf,
            out_codes, out_cov, out_total, out_ovf)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "scale", "scale_final",
                     "Lq", "n_win", "LA", "pallas", "band_ws", "detect",
                     "adaptive", "mesh", "nxt_k"))
def sched_rounds(bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf,
                 out_codes, out_cov, out_total, out_ovf, orig_ids, last, *,
                 match, mismatch, gap, scale, scale_final, Lq, n_win, LA,
                 pallas, band_ws, detect, adaptive=False, mesh=None,
                 nxt_k=2):
    """Run ``len(band_ws)`` refinement rounds in one dispatch, detect on
    the last of them, and scatter frozen windows' final-scale outputs.

    ``orig_ids`` int32[n_win] maps current window rows to accumulator
    rows (padding rows -> trash). ``last`` is a TRACED bool scalar:
    True freezes every remaining window (the final global round) —
    traced so tail dispatches of different global rounds share one
    executable. A window freezes when it converged, went overflow (its
    redo verdict cannot change — the flag is sticky in the fixed engine
    too), or the schedule ended.

    ``adaptive`` (static; used by the scheduler's FUSED TAIL, where
    every band width is the shared narrowed one and ``last`` is True):
    runs the non-final rounds as a while_loop that exits once every
    window is converged or overflowed, then the final round once.
    Skipped rounds are exact replays for converged windows and discarded
    work for overflowed ones — the frozen outputs are bit-identical to
    the unrolled chain (the module docstring's replay argument applies
    round by round). Returns the extra ``rounds_run`` int32 scalar
    either way (== len(band_ws) when not adaptive).
    """
    import jax
    import jax.numpy as jnp

    conv = jnp.zeros(n_win, dtype=bool)
    if adaptive and len(band_ws) >= 2:
        assert len(set(band_ws)) == 1 and not detect, \
            "[racon_tpu::sched_rounds] adaptive tail requires uniform " \
            "band widths and detection off (fused-tail call shape)"
        fn_mid = _make_sched_fn(
            match=match, mismatch=mismatch, gap=gap, scale=scale,
            scale_final=scale_final, Lq=Lq, n_win=n_win, LA=LA,
            pallas=pallas, band_w=band_ws[0], detect=True, mesh=mesh,
            nxt_k=nxt_k)

        def cond(c):
            return (c[0] < len(band_ws) - 1) & ~jnp.all(c[6] | c[7])

        def body(c):
            k, bb, bbw, alen, begin, end, conv, ovf = c
            (bb, bbw, alen, begin, end, conv, ovf, _, _, _, _) = fn_mid(
                bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf)
            return (k + 1, bb, bbw, alen, begin, end, conv, ovf)

        (k, bb, bbw, alen, begin, end, conv, ovf) = jax.lax.while_loop(
            cond, body, (jnp.int32(0), bb, bbw, alen, begin, end, conv,
                         ovf))
        fn_last = _make_sched_fn(
            match=match, mismatch=mismatch, gap=gap, scale=scale,
            scale_final=scale_final, Lq=Lq, n_win=n_win, LA=LA,
            pallas=pallas, band_w=band_ws[-1], detect=False, mesh=mesh,
            nxt_k=nxt_k)
        (bb, bbw, alen, begin, end, conv, ovf, ovf_f, codes_f, cov_f,
         total_f) = fn_last(bb, bbw, alen, begin, end, q, qw8, lq,
                            w_read, win, ovf)
        rounds_run = k + 1
    else:
        for i, bw in enumerate(band_ws):
            fn = _make_sched_fn(
                match=match, mismatch=mismatch, gap=gap, scale=scale,
                scale_final=scale_final, Lq=Lq, n_win=n_win, LA=LA,
                pallas=pallas, band_w=bw,
                detect=detect and i == len(band_ws) - 1, mesh=mesh,
                nxt_k=nxt_k)
            (bb, bbw, alen, begin, end, conv, ovf, ovf_f, codes_f, cov_f,
             total_f) = fn(bb, bbw, alen, begin, end, q, qw8, lq, w_read,
                           win, ovf)
        rounds_run = jnp.int32(len(band_ws))
    freeze = conv | ovf | last
    trash = out_codes.shape[0] - 1
    sel = jnp.where(freeze, orig_ids, trash)
    out_codes = out_codes.at[sel].set(codes_f)
    out_cov = out_cov.at[sel].set(cov_f)
    # clip like _round_core's new_alen: the fixed engine's output length
    # is the NEXT state's alen (ovf covers total_f > LA).
    out_total = out_total.at[sel].set(jnp.clip(total_f, 1, LA))
    # Freeze-reason-matched flag: a schedule-end freeze records ovf_f
    # (no base-scale assembly runs in the fixed engine's final round);
    # a conv/ovf freeze keeps the carried sticky flag plus the frozen
    # output's own final-scale overflow (see _sched_core).
    out_ovf = out_ovf.at[sel].set(
        jnp.where(last, ovf_f, ovf | (total_f > LA)))
    return (bb, bbw, alen, begin, end, ovf, conv,
            out_codes, out_cov, out_total, out_ovf, rounds_run)


@functools.partial(__import__("jax").jit, static_argnames=("mesh",))
def sched_repack(bb, bbw, alen, begin, end, q, qw8, lq, w_read, ovf,
                 lane_idx, new_win, win_map, win_real, *, mesh=None):
    """Gather-compact survivor state onto new dense lane/window axes.

    Index vectors come from a host RepackPlan: ``lane_idx`` int32[B']
    old lane positions (padded -> 0), ``new_win`` int32[B'] new window
    per lane (padded -> dummy n_win'), ``win_map`` int32[n_win'+1] old
    window row per new row (padded + dummy -> old dummy row),
    ``win_real`` bool[n_win']. Padded lanes are re-dummied (lq=1,
    begin=0, end=1, w_read=0) exactly like ChunkPlan padding. Returns
    (bb, bbw, alen, begin, end, q, qw8, lq, w_read, ovf) on the new
    axes; the caller carries ``new_win`` forward as the win array.
    """
    import jax
    import jax.numpy as jnp

    pad = new_win == (win_map.shape[0] - 1)

    def glane(a, fill=None):
        out = jnp.take(a, lane_idx, axis=0)
        if fill is not None:
            out = jnp.where(pad, jnp.asarray(fill, out.dtype), out)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            spec = P("dp") if out.ndim == 1 else P("dp", None)
            out = jax.lax.with_sharding_constraint(
                out, NamedSharding(mesh, spec))
        return out

    nbb = jnp.take(bb, win_map, axis=0)
    nalen = jnp.take(alen, win_map, axis=0)
    # Anchor weights are identically zero from round 1 on (anchors
    # re-vote with neutral weights) and repack only runs after >= 2
    # rounds — materialize the zeros instead of gathering them.
    nbbw = jnp.zeros(nbb.shape, jnp.float32)
    novf = jnp.where(
        win_real,
        jnp.take(ovf, jnp.clip(win_map[:-1], 0, ovf.shape[0] - 1)),
        False)
    return (nbb, nbbw, nalen,
            glane(begin, 0), glane(end, 1), glane(q), glane(qw8),
            glane(lq, 1), glane(w_read, 0.0), novf)


@__import__("jax").jit
def sched_pack(out_codes, out_cov, out_total, out_ovf, rounds_exec,
               rounds_sched):
    """Pack the output accumulators (trash row dropped) into the SAME
    d2h byte layout as the fixed engine (device_poa._pack_body), so
    collect_chunk unpacks scheduler output unchanged. ``rounds_exec`` /
    ``rounds_sched`` are the chunk's executed vs scheduled round counts
    (the scheduler sums its dispatches' ``rounds_run``)."""
    from racon_tpu.ops.device_poa import _pack_body
    return _pack_body(out_codes[:-1], out_cov[:-1], out_total[:-1],
                      out_ovf[:-1], rounds_exec, rounds_sched)
