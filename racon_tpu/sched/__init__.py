"""Convergence-aware refinement scheduling (docs/SCHEDULER.md).

The fixed-round device engine (ops/device_poa.py) runs every window
through all ``refine_rounds + 1`` alignment+merge rounds; on real
polishing data most windows reach a fixed point by round 2 and the
remaining rounds replay them unchanged. This subsystem sits between the
polisher's chunk planner and the device engine and

  (a) detects per-window fixed points ON DEVICE — a cheap reduction
      appended to the merge step (ops/device_merge.aggregate_flags /
      converged_windows);
  (b) freezes converged windows immediately: every round also assembles
      the SAME votes at the final-round insertion scale, so a frozen
      window's output is bit-identical to what the fixed engine's last
      round would produce (see sched/rounds.py for the argument);
  (c) repacks surviving lanes into dense bucketed batches between
      rounds (sched/repack.py) and early-exits whole dispatches when a
      chunk fully converges;
  (d) emits round telemetry (sched/telemetry.py) through
      utils/logger.py and into bench.py extras.

``RACON_TPU_SCHED=0`` falls back to the fixed-round single-dispatch
engine.
"""

from racon_tpu.sched.repack import RepackPlan
from racon_tpu.sched.scheduler import ConvergenceScheduler, sched_enabled
from racon_tpu.sched.telemetry import SchedTelemetry

__all__ = ["ConvergenceScheduler", "RepackPlan", "SchedTelemetry",
           "sched_enabled"]
