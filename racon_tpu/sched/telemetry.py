"""Round telemetry for the convergence scheduler.

Counters only — every value is fed from flags the scheduler already
pulls to the host for control flow, so recording costs no extra device
syncs. Reporting routes through the metrics registry
(racon_tpu/obs/metrics.py): ``publish_sched`` writes the canonical
``sched_*`` keys the polisher's stderr summary and bench.py's extras
both read, so the serialized and printed views cannot drift (keys
documented in docs/SCHEDULER.md and docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import Dict, List


class SchedTelemetry:
    """Per-run convergence counters.

    ``rounds`` is the engine's total round count R (refine_rounds + 1).
    A window's ``rounds_used`` is the number of refinement rounds it
    actually executed before freezing: R means it never converged early
    (or the schedule is too short to detect), smaller values are the
    scheduler's win. Overflow (host-redo) windows freeze early too and
    count at their freeze round — their device rounds stop mattering
    the moment the sticky flag rises.
    """

    def __init__(self, rounds: int):
        self.rounds = int(rounds)
        self.windows = 0                  # real windows entering the sched
        self.chunks = 0
        # rounds_used -> windows frozen after exactly that many rounds
        self.hist: Dict[int, int] = {}
        # windows that EXECUTED round r (r -> count); survivor fractions
        # derive from this against self.windows
        self._alive: Dict[int, int] = {}
        self.repack_s = 0.0               # host planning + index h2d
        self.dispatches_saved = 0         # round-dispatches early-exited

    # ------------------------------------------------------------ recording

    def record_chunk(self, n_windows: int) -> None:
        self.chunks += 1
        self.windows += int(n_windows)

    def record_round(self, r: int, n_alive: int) -> None:
        """``n_alive`` windows executed refinement round ``r``."""
        self._alive[int(r)] = self._alive.get(int(r), 0) + int(n_alive)

    def record_freeze(self, rounds_used: int, n_windows: int) -> None:
        if n_windows:
            k = int(rounds_used)
            self.hist[k] = self.hist.get(k, 0) + int(n_windows)

    def record_repack(self, seconds: float) -> None:
        self.repack_s += float(seconds)

    def record_skip(self, n_dispatches: int) -> None:
        """A chunk fully converged with ``n_dispatches`` rounds unrun."""
        self.dispatches_saved += int(n_dispatches)

    # ------------------------------------------------------------- reporting

    def survivor_frac(self) -> List[float]:
        """Fraction of windows that executed round r, for r in 0..R-1."""
        if not self.windows:
            return [0.0] * self.rounds
        return [self._alive.get(r, 0) / self.windows
                for r in range(self.rounds)]

    def rounds_saved_frac(self) -> float:
        """Fraction of total window-rounds the scheduler skipped."""
        if not self.windows:
            return 0.0
        executed = sum(self._alive.get(r, 0) for r in range(self.rounds))
        return 1.0 - executed / (self.windows * self.rounds)

    def as_extras(self) -> Dict[str, object]:
        """JSON-serializable counters (the registry's sched_* keys)."""
        from racon_tpu.obs.metrics import (MetricsRegistry, publish_sched,
                                           sched_extras)
        reg = MetricsRegistry()
        publish_sched(self, reg)
        return sched_extras(reg)

    def summary(self) -> str:
        """One line for the polisher's stderr log (registry-formatted)."""
        from racon_tpu.obs.metrics import (MetricsRegistry, publish_sched,
                                           sched_summary_line)
        reg = MetricsRegistry()
        publish_sched(self, reg)
        return sched_summary_line(reg)
