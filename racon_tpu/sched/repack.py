"""Host-side survivor repacking between scheduler rounds.

After a detecting round, the scheduler knows (from the conv/ovf flags it
pulls for control flow anyway) which windows froze. A RepackPlan lays
the survivors out on fresh dense axes — windows renumbered 0..n_surv-1
(padded to the 32 grid, like ChunkPlan), lanes compacted onto the same
coarse batch buckets ChunkPlan uses — and emits the index vectors
sched_repack (racon_tpu/sched/rounds.py) gathers with ON DEVICE. Only
the tiny index vectors cross the tunnel; anchor tables, spans, and
query buffers never come back to the host.

Reusing ChunkPlan's bucketing (_bucket_b x 128*n_shards lane grid,
32-grid window rows) is what keeps the repacked dispatches cheap: a
run's shrinking survivor sets collapse onto a handful of (B, n_win)
buckets, so the single-round executable compiles once per bucket, and
every bucket stays dp-shardable (the lane axis is a multiple of
128 * n_shards, exactly like a fresh chunk's).
"""

from __future__ import annotations

import numpy as np

from racon_tpu.ops.device_poa import _bucket_b, _round_up


class RepackPlan:
    """Index plan mapping current chunk axes onto dense survivor axes.

    Parameters
    ----------
    surv : bool[n_win_cur] — survivor mask on the CURRENT window rows
        (False for frozen, overflowed, and padded rows).
    win : int32[B_cur] — current per-lane window ids (padded lanes hold
        the current dummy id ``n_win_cur``).
    orig_ids : int32[n_win_cur] — current rows' ORIGINAL output rows.
    trash : int — the output accumulators' trash row (original n_win).
    n_shards : int — dp shard count; the new lane axis pads to a
        multiple of ``128 * n_shards`` so it stays evenly shardable.

    Attributes (all numpy, ready for device_put)
    ----------
    n_surv, n_win, B : new real-window / padded-window / lane counts.
    win_map : int32[n_win + 1] — old window row per new row; padded
        rows and the new dummy row point at the OLD dummy row.
    win_real : bool[n_win] — which new rows carry a survivor.
    orig_ids : int32[n_win] — new rows' original output rows (padded
        rows -> ``trash``).
    lane_idx : int32[B] — old lane per new lane (padded -> 0; the
        gather's fill masks re-dummy those lanes).
    new_win : int32[B] — new window id per new lane (padded -> the new
        dummy ``n_win``); becomes the next dispatch's ``win`` array.
    """

    def __init__(self, surv: np.ndarray, win: np.ndarray,
                 orig_ids: np.ndarray, trash: int, n_shards: int = 1):
        surv = np.asarray(surv, bool)
        win = np.asarray(win, np.int64)
        n_win_cur = surv.shape[0]

        rows = np.flatnonzero(surv)             # ascending: order stable
        self.n_surv = int(rows.size)
        self.n_win = _round_up(self.n_surv, 32)

        self.win_map = np.full(self.n_win + 1, n_win_cur, np.int32)
        self.win_map[:self.n_surv] = rows
        self.win_real = np.zeros(self.n_win, bool)
        self.win_real[:self.n_surv] = True
        self.orig_ids = np.full(self.n_win, trash, np.int32)
        self.orig_ids[:self.n_surv] = np.asarray(orig_ids, np.int32)[rows]

        old2new = np.full(n_win_cur + 1, self.n_win, np.int64)
        old2new[rows] = np.arange(self.n_surv)

        keep = (win < n_win_cur) & surv[np.minimum(win, n_win_cur - 1)]
        lanes = np.flatnonzero(keep)
        self.n_lanes = int(lanes.size)
        self.B = _round_up(_bucket_b(max(self.n_lanes, 1)),
                           128 * n_shards)
        self.lane_idx = np.zeros(self.B, np.int32)
        self.lane_idx[:self.n_lanes] = lanes
        self.new_win = np.full(self.B, self.n_win, np.int32)
        self.new_win[:self.n_lanes] = old2new[win[lanes]]
