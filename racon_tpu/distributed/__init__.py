"""Preemption-tolerant sharded execution (docs/DISTRIBUTED.md).

The polishing loop is embarrassingly parallel over contigs, so fleet
scale-out is a *work distribution* problem, not a communication one:

- ``ledger.py`` — the contig work ledger: partitions the target set
  into shards, hands them to workers under time-bounded leases, and
  lets survivors steal shards whose lease expired;
- ``worker.py`` — the worker loop: claim → polish through the existing
  engine (``Polisher.polish_records``) with a per-shard checkpoint
  store → complete; plus the merge phase that assembles shard FASTAs
  in target order, byte-identical to the serial path.

Everything lives on a shared filesystem (or one host's disk for
multi-process runs); there is no coordinator process and no network
protocol — an evicted worker is simply a lease that stops being
renewed.
"""

from racon_tpu.distributed.ledger import (Claim, LeaseLost, LedgerError,
                                          WorkLedger)

__all__ = ["Claim", "LeaseLost", "LedgerError", "WorkLedger"]
